"""Rank-sharded out-of-core training: every rank streams its own shard.

The two scale axes built so far — disk (OocTrainer streams the bin
matrix, PR 8) and fleet (the host-driven data-parallel learner
allreduces histograms, PR 5/13) — compose here: each rank streams its
OWN contiguous row shard from its own chunk source through the bounded
prefetch ring, folds per-chunk histogram partials locally via the
shared ChunkFolder seam (data/chunksource.py), and exchanges only the
per-NODE histograms over the hardened KV transport.  Peak device
residency per rank stays O(2 chunks); wire volume stays O(F·B) per node
— the same observation "Out-of-Core GPU Gradient Boosting" and
XGBoost's external-memory mode make: chunked external-memory folds and
data-parallel allreduce are independent axes.

Exchange pattern per tree (HostParallelLearner's data mode, verbatim):

  - quantized: allgather (max|g|, max|h|) -> one global scale; exact
    int64 root totals; every node histogram ships as the 2-plane int16
    ``hist_q`` wire (F*B*4 bytes vs f32x3's F*B*12) and merges in exact
    integer arithmetic;
  - f32: root totals and histograms merge with rank-order sequential
    IEEE adds (the determinism anchor);
  - smaller-child selection uses GLOBAL row counts (a 8-byte ``_CNT``
    allgather), so every rank subtracts the same sibling.

Determinism contract (pinned by tests/test_oocdist.py): with
``quantized_training`` on, per-chunk int32 partials are associative, so
the merged node histogram — and therefore the model — is BYTE-IDENTICAL
for any per-rank chunk grid AND any rank count.  The f32 path keeps
per-rank folds bit-identical to that rank's in-memory scan (ROW_BLOCK
alignment) and is deterministic for a fixed world size, but its
rank-order merge makes the result world-dependent, exactly like the
in-memory data-parallel learner.

The host replays identical decisions on every rank from identical
gathered bytes, so collectives stay in lockstep program order (the KV
GC invariant).  Checkpoints ride the canonical topology-portable layout
(ckpt/state.py): the per-rank chunk grid is recorded as a ``dist/``
schedule fingerprint, which ``restore()`` exempts from the serial
grid-equality refusal — per-rank grids legitimately differ across world
sizes, while the GLOBAL dataset fingerprint stays enforced by the
canonical container handshake.
"""

from __future__ import annotations

import struct
from typing import List

import jax.numpy as jnp
import numpy as np

from ..data.chunksource import (
    ChunkFolder,
    ChunkPlan,
    ChunkStream,
    PrefetchStats,
    make_chunk_source,
)
from ..obs import tracer
from ..ops import qhist
from ..ops.grow import GrowResult
from ..ops.ooc import child_leaf_values, find_best_split, root_totals
from ..ops.split import NEG_INF
from ..parallel.comm import Comm
from ..utils.log import Log
from .ooc import OocTrainer

# wire formats shared with parallel/hostlearner.py: 8-byte local
# (n_left, n_right) row counts, 12-byte f32 root sums, 8-byte quantized
# scale maxima, 24-byte exact int64 quantized root totals
_CNT = struct.Struct("<ii")
_SUMS = struct.Struct("<fff")
_QMAX = struct.Struct("<ff")
_QSUMS = struct.Struct("<qqq")


class DistributedOocTrainer:
    """Drop-in ``learner`` for GBDT over a :class:`Comm`: ``grow()``
    matches OocTrainer's surface; inputs are this rank's row shard
    (vectors device-resident, matrix streamed from this rank's chunk
    source)."""

    # gbdt.py hands us f32 gradients even under quantized_training: the
    # quantization scale must be a max over ALL ranks' rows, so the
    # allgather of local maxima happens inside grow, over the KV
    # transport (XLA:CPU has no multi-process computations)
    quantizes_internally = True

    def __init__(self, train_set, config, grow_params, chunk_rows: int,
                 comm: Comm):
        self.params = grow_params._replace(compact=False)
        self.comm = comm
        self.num_rows = int(train_set.num_data)  # LOCAL shard rows
        self.num_features = int(train_set.num_features)
        self.plan = ChunkPlan(self.num_rows, chunk_rows)
        self.stats = PrefetchStats()
        self.depth = max(int(getattr(config, "ooc_prefetch_depth", 2) or 2), 1)
        self.source = make_chunk_source(train_set)
        self.chunks = ChunkStream(self.source, self.plan, self.depth,
                                  self.stats)
        self.folder = ChunkFolder(self.chunks, self.num_features,
                                  self.params.num_bins,
                                  self.params.row_block)
        self.quant = bool(self.params.quantized)
        self._qiter = -1  # stochastic-rounding counter (ckpt-synced)
        self._qscales = None  # (2,) np.float32 scales of the current tree
        self._trees_grown = 0
        tracer.event(
            "ooc.plan",
            rows=self.num_rows, features=self.num_features,
            chunk_rows=self.plan.chunk_rows, chunks=self.plan.num_chunks,
            depth=self.depth, source=self.source.describe(),
            rank=self.comm.rank, world=self.comm.nproc,
        )
        Log.info(
            "Distributed out-of-core training: rank %d/%d streams %d rows "
            "in %d chunks of %d (%s, prefetch depth %d, %s histogram wire)",
            self.comm.rank, self.comm.nproc, self.num_rows,
            self.plan.num_chunks, self.plan.chunk_rows,
            self.source.describe(), self.depth,
            "hist_q int16/int32" if self.quant else "f32",
        )

    def schedule_fingerprint(self) -> str:
        """Per-rank chunk-schedule identity.  The ``dist/`` prefix tells
        ``ckpt/state.py`` this grid is rank-local: integer folds are
        associative (and f32 folds ROW_BLOCK-aligned), so an elastic
        resume at a different world size — hence a different per-rank
        grid — is sound, and only the global dataset fingerprint gates
        the resume."""
        return (f"dist/{self.comm.nproc}w/r{self.comm.rank}/"
                f"{self.plan.fingerprint()}")

    def set_plan(self, plan) -> None:
        """Shard-plan seam parity with the other parallel learners: row
        moves are declined for out-of-core shards (rows are
        disk-resident; gbdt.py's rebalance arming already excludes us),
        so this is never reached with a changed plan."""
        del plan

    # -- merge helpers (hostlearner.py wire semantics) -----------------

    @staticmethod
    def _merge_f32(blobs: List[bytes], shape) -> np.ndarray:
        """Rank-order sequential IEEE f32 adds — deterministic for a
        fixed world size."""
        parts = [np.frombuffer(b, np.float32).reshape(shape) for b in blobs]
        tot = parts[0].copy()
        for p in parts[1:]:
            tot = tot + p
        return tot

    @staticmethod
    def _merge_q(blobs: List[bytes], f: int, b: int):
        """Exact integer merge of ``hist_q`` payloads; returns
        ``(planes, counts)`` with ``counts`` the summed exact count
        plane of any 3-plane (degenerate-node) payloads."""
        tot = np.zeros((f, b, 2), np.int64)
        counts = None
        for blob in blobs:
            arr = qhist.unpack_hist_q(blob, f, b)
            tot = tot + arr[..., :2]
            if arr.shape[-1] == 3:
                c = arr[..., 2].astype(np.int64)
                counts = c if counts is None else counts + c
        return tot, counts

    @staticmethod
    def _q_counts_if_degenerate(hist3: np.ndarray):
        """Ship the exact count plane iff this rank's quantized hessian
        mass for the node is zero while it still holds rows (hessians
        are non-negative, so the GLOBAL mass is zero iff every rank's
        is)."""
        if (int(hist3[0, :, 1].sum()) == 0
                and int(hist3[0, :, 2].sum()) > 0):
            return hist3[..., 2]
        return None

    def _global_hist(self, local_hist, node_cnt: float) -> np.ndarray:
        """Allgather + merge one node's local histogram partial into the
        global (F, B, 3) f32 histogram every rank scans identically."""
        f, b = self.num_features, self.params.num_bins
        if self.quant:
            h3 = np.asarray(local_hist)
            blob = qhist.pack_hist_q(
                h3[..., :2], self._q_counts_if_degenerate(h3))
            blobs = self.comm.allgather(blob, "hist_q")
            merged, exact_cnt = self._merge_q(blobs, f, b)
            return qhist.assemble_hist(merged, self._qscales,
                                       float(node_cnt), counts=exact_cnt)
        blobs = self.comm.allgather(
            np.asarray(local_hist, np.float32).tobytes(), "hist")
        return self._merge_f32(blobs, (f, b, 3))

    def _find_best(self, local_hist, sums: np.ndarray, depth_ok: bool,
                   feature_mask, meta, hyper, monotone=None,
                   leaf_lo=None, leaf_hi=None):
        """(gain, feat, thr, dbz, left(3,)) from the MERGED histogram —
        identical on every rank, so the replayed loops stay lockstep.
        Monotone bounds are per-leaf host scalars every rank derives from
        the same replay, so the constrained scan stays lockstep too."""
        ghist = self._global_hist(local_hist, float(sums[2]))
        if monotone is not None:
            res = find_best_split(
                jnp.asarray(ghist),
                jnp.asarray(np.asarray(sums, np.float32)),
                feature_mask, bool(depth_ok), meta, hyper,
                self.params.use_missing, monotone=monotone,
                leaf_lo=leaf_lo, leaf_hi=leaf_hi)
        else:
            res = find_best_split(
                jnp.asarray(ghist),
                jnp.asarray(np.asarray(sums, np.float32)),
                feature_mask, bool(depth_ok), meta, hyper,
                self.params.use_missing)
        left = np.asarray(
            [res.left_sum_g, res.left_sum_h, res.left_cnt], np.float32)
        return (np.float32(res.gain), int(res.feature),
                int(res.threshold_bin), int(res.default_bin_for_zero),
                left)

    # -- root totals ---------------------------------------------------

    def _root_sums_global(self, sums_local) -> np.ndarray:
        """Merge per-rank root totals: exact Python-int sums of the
        int32 quantized totals (then one host-side dequantization), or
        rank-order f32 adds."""
        if self.quant:
            s = np.asarray(sums_local)
            blobs = self.comm.allgather(
                _QSUMS.pack(int(s[0]), int(s[1]), int(s[2])), "hist_q")
            sums_i = [_QSUMS.unpack(b) for b in blobs]
            tot_g = sum(v[0] for v in sums_i)
            tot_h = sum(v[1] for v in sums_i)
            tot_c = sum(v[2] for v in sums_i)
            return np.asarray(
                [np.float32(np.float32(tot_g) * self._qscales[0]),
                 np.float32(np.float32(tot_h) * self._qscales[1]),
                 np.float32(tot_c)], np.float32)
        s = np.asarray(sums_local, np.float32)
        blobs = self.comm.allgather(
            _SUMS.pack(float(s[0]), float(s[1]), float(s[2])), "best_split")
        vals = [np.array(_SUMS.unpack(b), np.float32) for b in blobs]
        tot = vals[0].copy()
        for v in vals[1:]:
            tot = tot + v
        return tot

    # ------------------------------------------------------------------
    def grow(self, bins_ignored, grad, hess, select, feature_mask,
             meta, hyper, qscale=None) -> GrowResult:
        """Grow one leaf-wise tree: every rank streams its shard, folds
        local per-node partials, and merges them per node.

        The host-side replay mirrors OocTrainer.grow; the only
        distributed additions are the four exchange points (scale
        maxima, root totals, per-node histograms, child row counts)."""
        del qscale  # quantizes internally; driver never passes one
        L = self.params.num_leaves
        stats0 = dict(self.stats.as_dict())
        # monotone-constraint strategy seam (tree/strategy.py): bounds
        # replay host-side exactly as in OocTrainer.grow — every rank
        # derives identical np.float32 bounds from the lockstep replay,
        # so no extra exchange is needed; unconstrained keeps the exact
        # pre-strategy call graph (None kwargs)
        mono_t = self.params.strategy.split_gain.monotone
        use_mono = any(c != 0 for c in mono_t)
        if use_mono and len(mono_t) != self.num_features:
            raise ValueError(
                f"monotone constraint vector has {len(mono_t)} entries "
                f"but the dataset has {self.num_features} inner features")
        mono = jnp.asarray(mono_t, jnp.int32) if use_mono else None
        leaf_lo = np.full((L,), NEG_INF, np.float32)
        leaf_hi = np.full((L,), np.inf, np.float32)

        if self.quant:
            # per-tree quantization: global scales from allgathered local
            # maxima, then value-keyed stochastic rounding — a row
            # quantizes the same way whichever rank holds it, so the
            # merged integer histogram is invariant under rank count and
            # chunk grid.  _qiter is ckpt-synced (import_train_state), so
            # a resumed run draws the same rounding as one that never
            # died.
            self._qiter += 1
            seed = (int(self.params.quant_seed) * 2654435761
                    + self._qiter * 97 + 1) & 0xFFFFFFFF
            mx = np.asarray(qhist.local_absmax(grad, hess, select),
                            np.float32)
            blobs = self.comm.allgather(
                _QMAX.pack(float(mx[0]), float(mx[1])), "hist_q")
            maxima = [_QMAX.unpack(b) for b in blobs]
            self._qscales = qhist.scales_from_max(
                max(m[0] for m in maxima), max(m[1] for m in maxima),
                self.params.quant_bits)
            grad, hess = qhist.quantize_rows(
                grad, hess, jnp.asarray(self._qscales), np.uint32(seed),
                self.params.quant_bits)

        with tracer.span("ooc.grow", tree=self._trees_grown,
                         chunks=self.plan.num_chunks, rank=self.comm.rank):
            # ---- root: local streamed fold + global merges
            root_sums = self._root_sums_global(root_totals(grad, hess,
                                                           select))
            hist = self.folder.fold_root(grad, hess, select)

            bs_gain = np.full((L,), NEG_INF, np.float32)
            bs_feat = np.zeros((L,), np.int32)
            bs_thr = np.zeros((L,), np.int32)
            bs_dbz = np.zeros((L,), np.int32)
            bs_left = np.zeros((L, 3), np.float32)
            leaf_sum = np.zeros((L, 3), np.float32)
            leaf_value = np.zeros((L,), np.float32)
            leaf_cnt = np.zeros((L,), np.float32)
            leaf_depth = np.zeros((L,), np.int32)
            leaf_rows = np.zeros((L,), np.int64)  # LOCAL rows
            rec_i = {k: np.zeros((L - 1,), np.int32)
                     for k in ("leaf", "feat", "thr", "dbz")}
            rec_f = {k: np.zeros((L - 1,), np.float32)
                     for k in ("gain", "lval", "rval", "lcnt", "rcnt",
                               "internal_value")}
            leaf_sum[0] = root_sums
            leaf_cnt[0] = root_sums[2]
            leaf_rows[0] = self.num_rows

            def store(leaf: int, res) -> None:
                bs_gain[leaf] = res[0]
                bs_feat[leaf] = np.int32(res[1])
                bs_thr[leaf] = np.int32(res[2])
                bs_dbz[leaf] = np.int32(res[3])
                bs_left[leaf] = res[4]

            if use_mono:
                store(0, self._find_best(hist, root_sums, True,
                                         feature_mask, meta, hyper,
                                         monotone=mono,
                                         leaf_lo=leaf_lo[0],
                                         leaf_hi=leaf_hi[0]))
            else:
                store(0, self._find_best(hist, root_sums, True,
                                         feature_mask, meta, hyper))
            pool = {0: hist}
            leaf_id = jnp.zeros((self.num_rows,), jnp.int32)
            default_bin = np.asarray(meta.default_bin)
            is_categorical = np.asarray(meta.is_categorical)

            num_splits = 0
            while num_splits < L - 1:
                bl = int(np.argmax(bs_gain))
                gain = bs_gain[bl]
                if not (gain > 0.0):
                    break  # no further splits with positive gain
                s = num_splits
                rl = s + 1
                feat = int(bs_feat[bl])
                thr = int(bs_thr[bl])
                dbz = int(bs_dbz[bl])
                left = bs_left[bl].copy()
                right = leaf_sum[bl] - left
                if use_mono:
                    plo, phi = leaf_lo[bl], leaf_hi[bl]
                    lval_d, rval_d = child_leaf_values(
                        left, right, hyper.lambda_l1, hyper.lambda_l2,
                        plo, phi)
                    lval = np.float32(lval_d)
                    rval = np.float32(rval_d)
                    # BasicLeafConstraints mid-point tightening
                    cdir = int(mono_t[feat])
                    mid = np.float32((lval + rval) * np.float32(0.5))
                    child_lhi = mid if cdir > 0 else phi
                    child_llo = mid if cdir < 0 else plo
                    child_rlo = mid if cdir > 0 else plo
                    child_rhi = mid if cdir < 0 else phi
                    leaf_lo[bl], leaf_hi[bl] = child_llo, child_lhi
                    leaf_lo[rl], leaf_hi[rl] = child_rlo, child_rhi
                else:
                    lval_d, rval_d = child_leaf_values(
                        left, right, hyper.lambda_l1, hyper.lambda_l2)
                    lval = np.float32(lval_d)
                    rval = np.float32(rval_d)

                # ---- one streamed pass: partition + both children hists
                leaf_id, hist_l, hist_r, n_left = self.folder.fold_split(
                    leaf_id, pool[bl], grad, hess, select, feat,
                    int(default_bin[feat]), dbz, thr,
                    bool(is_categorical[feat]), bl, rl,
                )
                n_left = int(n_left)
                n_right = int(leaf_rows[bl]) - n_left
                # smaller child by GLOBAL row count: every rank must keep
                # the direct accumulation for the same child or the
                # subtraction trick would mix siblings across the merge
                blobs = self.comm.allgather(_CNT.pack(n_left, n_right),
                                            "best_split")
                cnts = [_CNT.unpack(b) for b in blobs]
                g_left = sum(c[0] for c in cnts)
                g_right = sum(c[1] for c in cnts)
                left_hist, right_hist = ChunkFolder.pick_children(
                    pool[bl], hist_l, hist_r, g_left, g_right)
                pool[bl] = left_hist
                pool[rl] = right_hist

                child_depth = int(leaf_depth[bl]) + 1
                depth_ok = (self.params.max_depth <= 0
                            or child_depth < self.params.max_depth)
                if use_mono:
                    lres = self._find_best(
                        left_hist, left, depth_ok, feature_mask, meta,
                        hyper, monotone=mono, leaf_lo=leaf_lo[bl],
                        leaf_hi=leaf_hi[bl])
                    rres = self._find_best(
                        right_hist, right, depth_ok, feature_mask, meta,
                        hyper, monotone=mono, leaf_lo=leaf_lo[rl],
                        leaf_hi=leaf_hi[rl])
                else:
                    lres = self._find_best(left_hist, left, depth_ok,
                                           feature_mask, meta, hyper)
                    rres = self._find_best(right_hist, right, depth_ok,
                                           feature_mask, meta, hyper)

                rec_i["leaf"][s] = bl
                rec_i["feat"][s] = feat
                rec_i["thr"][s] = thr
                rec_i["dbz"][s] = dbz
                rec_f["gain"][s] = gain
                rec_f["lval"][s] = lval
                rec_f["rval"][s] = rval
                rec_f["lcnt"][s] = left[2]
                rec_f["rcnt"][s] = right[2]
                rec_f["internal_value"][s] = leaf_value[bl]
                leaf_sum[bl] = left
                leaf_sum[rl] = right
                leaf_value[bl] = lval
                leaf_value[rl] = rval
                leaf_cnt[bl] = left[2]
                leaf_cnt[rl] = right[2]
                leaf_depth[bl] = child_depth
                leaf_depth[rl] = child_depth
                leaf_rows[bl] = n_left
                leaf_rows[rl] = n_right
                store(bl, lres)
                store(rl, rres)
                num_splits += 1

        self._trees_grown += 1
        self._emit_stream_obs(stats0)
        return GrowResult(
            num_splits=np.int32(num_splits),
            leaf_id=leaf_id,
            leaf_value=leaf_value,
            leaf_cnt=leaf_cnt,
            rec_leaf=rec_i["leaf"], rec_feat=rec_i["feat"],
            rec_thr=rec_i["thr"], rec_dbz=rec_i["dbz"],
            rec_gain=rec_f["gain"], rec_lval=rec_f["lval"],
            rec_rval=rec_f["rval"], rec_lcnt=rec_f["lcnt"],
            rec_rcnt=rec_f["rcnt"],
            rec_internal_value=rec_f["internal_value"],
        )

    # ------------------------------------------------------------------
    def add_tree_scores(self, score_k, arrays):
        """Streamed ``predict_binned`` over this rank's chunk grid."""
        return self.folder.streamed_scores(score_k, arrays)

    def _emit_stream_obs(self, before: dict) -> None:
        # rank stamps ride on every record (tracer.set_identity), but
        # the explicit attr keeps per-rank OOC gauges attributable even
        # in single-process simulations (LocalComm) where no identity is
        # set — `report merge` keys its OOC stall-share column on them
        OocTrainer._emit_stream_obs(self, before, rank=self.comm.rank)
