"""GBDT training driver — counterpart of src/boosting/gbdt.{cpp,h}
(TrainOneIter gbdt.cpp:381-495, Bagging :252-334, UpdateScore :539-562,
OutputMetric :564-622, model save/load :854-1008).

TPU-first layout: scores/gradients/hessians are device-resident
``(num_tree_per_iteration, N)`` f32 arrays; one boosting iteration runs
  objective.get_gradients  (jnp, fused elementwise)
  grow_tree                (jitted leaf-wise learner, ops/grow.py)
  add_leaf_outputs         (gather on the grower's leaf_id partition)
with only the O(num_leaves) split records returning to host per tree.
Bagging is a 0/1 row mask multiplied into the histogram kernel's select
vector — the out-of-bag rows still receive score updates because the
partition predicate covers every row (the reference needs a separate
UpdateScoreOutOfBag pass; here it is free).
"""

from __future__ import annotations

import functools
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ..model.tree import Tree


class _memo:
    """Call-once wrapper: several host-path metrics on one dataset share
    a single full score transfer."""

    def __init__(self, fn):
        self.fn = fn
        self.value = None

    def __call__(self):
        if self.value is None:
            self.value = self.fn()
        return self.value

from ..obs import fence, tracer
from ..obs.audit import audit
from ..ops.grow import GrowParams, grow_tree
from ..ops.predict import add_leaf_outputs, predict_binned, predict_raw
from ..ops.split import FeatureMeta, SplitHyper
from ..model.ensemble import stack_trees
from ..utils.log import Log
from ..utils.random import Random

K_MIN_SCORE = -np.inf


class GBDT:
    """The gradient-boosting driver (class GBDT, gbdt.h:24-258)."""

    # DART overrides: its per-iteration hooks (drop/normalize) are
    # host-side and incompatible with the fused partitioned trainer.
    supports_partitioned = True
    # data-parallel fused path (GOSS needs a global top_k, not sharded yet)
    supports_partitioned_data = True
    # out-of-core streaming (boosting/ooc.py): needs the serial mask
    # grower's replayable split loop.  DART opts out — its drop state
    # re-scores dropped trees over the full matrix every iteration,
    # which would multiply streaming passes.
    supports_ooc = True

    def __init__(self):
        self.models: List[Tree] = []
        self.iter = 0
        self.num_init_iteration = 0
        self.boost_from_average_ = False
        self.train_set = None
        self.objective = None
        self.config = None
        self.max_feature_idx = 0
        self.label_idx = 0
        self._rebalance = None
        self._membership = None
        self._iter_complete = False

    # ------------------------------------------------------------------
    def init(self, config, train_set, objective, training_metrics=()):
        """GBDT::Init + ResetTrainingData (gbdt.cpp:65-218)."""
        tracer.refresh_from_env()  # LIGHTGBM_TPU_TRACE may be set per-run
        audit.refresh_from_env()  # LIGHTGBM_TPU_AUDIT split-decision trail
        self.config = config
        self.train_set = train_set
        self.objective = objective
        self.num_data = train_set.num_data
        # with a custom objective (objective=None) the class count comes
        # from config.num_class (gbdt.cpp ResetTrainingData: num_class_)
        self.num_tree_per_iteration = (
            objective.num_tree_per_iteration
            if objective is not None
            else max(config.num_class, 1)
        )
        self.num_class = config.num_class
        self.max_feature_idx = train_set.num_total_features - 1
        self.label_idx = getattr(train_set, "label_idx", 0)
        self.feature_names = train_set.feature_names
        self.training_metrics = list(training_metrics)
        self.shrinkage_rate = config.learning_rate

        # multi-host bootstrap must precede ANY device use (a backend
        # query locks in a single-process runtime) — including the
        # objective's label transfer below
        if config.tree_learner.lower() in ("data", "feature", "voting"):
            from ..parallel.distributed import ensure_initialized

            ensure_initialized(config)

        # live elastic membership (parallel/membership.py): armed only
        # when the knob is on AND a MembershipRuntime has adopted an
        # epoch (bootstrap()/join() ran before Booster construction).
        # OFF is the exact static-fleet path — zero extra collectives.
        self._membership = None
        self._membership_pauses = []  # resize stalls (spot bench p50/p99)
        if getattr(config, "elastic_membership", False):
            from ..parallel import membership as _mship

            rt = _mship.runtime()
            if rt is None:
                rt = _mship.runtime_from_env()
            if rt is None or rt.epoch < 0:
                Log.warning(
                    "elastic_membership=true ignored: no adopted "
                    "MembershipRuntime (call bootstrap()/join(), or set "
                    "LIGHTGBM_TPU_MEMBER_DIR, before training)")
            else:
                self._membership = rt

        if objective is not None:
            md = train_set.metadata
            if (md.query_boundaries is not None
                    and config.tree_learner.lower() in
                    ("data", "feature", "voting")):
                # world-invariant ranking program: pad every shard's
                # queries to the GLOBAL max group size — a dataset
                # constant under whole-group moves.  Padding to the
                # local max would tie the (Q, S, S) lambda-matrix shape
                # (and so the f32 reduction order) to the world size
                # and to every reshard; quantized stochastic rounding
                # then amplifies the ulp drift into different trees.
                import jax as _jax

                _gs = np.diff(np.asarray(md.query_boundaries, np.int64))
                local_s = int(_gs.max()) if len(_gs) else 1
                if _jax.process_count() > 1:
                    from ..parallel import collect as _collect

                    blobs = _collect.allgather_bytes(
                        local_s.to_bytes(8, "little"), "misc")
                    local_s = max(int.from_bytes(b, "little")
                                  for b in blobs)
                md.pad_group_size = local_s
            objective.init(train_set.metadata, self.num_data)

        # persistent compile cache, keyed on the now-known backend
        from .. import enable_compile_cache

        enable_compile_cache()

        # out-of-core routing decides BEFORE the matrix upload: when the
        # streamed path is on, the (N, F) bin matrix never becomes
        # device-resident (self.bins stays None) and only the per-row
        # vectors live on device.
        from .ooc import resolve_out_of_core

        self.ooc = None
        ooc_on, ooc_chunk_rows, ooc_why = resolve_out_of_core(config, train_set)
        if ooc_on and self._membership is not None:
            Log.fatal(
                "elastic_membership is not supported with out-of-core "
                "streaming: membership transitions reshard rows in RAM, "
                "but streamed rows are disk-resident")
        if ooc_on:
            forced = "forced" in ooc_why
            unsupported = None
            if config.tree_learner.lower() not in ("serial", "data"):
                unsupported = (
                    f"tree_learner={config.tree_learner} (streaming "
                    "supports serial, or data with per-rank shards)")
            elif not self.supports_ooc:
                unsupported = f"boosting type {type(self).__name__}"
            if unsupported is not None:
                if forced:
                    Log.fatal(
                        "out_of_core=true is not supported with %s",
                        unsupported)
                Log.warning(
                    "out-of-core auto-routing (%s) skipped: not supported "
                    "with %s; training in-memory", ooc_why, unsupported)
                ooc_on = False

        # quantized training accumulates n*QMAX in int32 (root totals and
        # psum'd histogram bins, ops/grow.py) — past the headroom it would
        # wrap silently and grow wrong trees, so decline up front
        if config.quantized_training:
            from ..ops import qhist as _qhist

            n_rows = self.num_data
            if self._membership is not None:
                # the membership runtime already carries the fleet's
                # global row count; joiners must NOT issue init-time
                # collectives (the survivors are mid-iteration)
                n_rows = int(self._membership.num_data)
            elif config.tree_learner.lower() in ("data", "feature", "voting"):
                import jax as _jax

                if _jax.process_count() > 1:
                    # the data-parallel merge sums GLOBAL rows into a
                    # bin; gather the per-rank counts over the byte
                    # collectives (works on the KV transport too, where
                    # XLA:CPU has no multi-process computations)
                    from ..parallel import collect as _collect

                    blobs = _collect.allgather_bytes(
                        int(self.num_data).to_bytes(8, "little"), "misc")
                    n_rows = sum(int.from_bytes(b, "little")
                                 for b in blobs)
            limit = _qhist.max_rows_for(config.quantized_grad_bits)
            if n_rows > limit:
                Log.warning(
                    "quantized_training disabled: %d rows exceed the "
                    "int32 histogram-accumulator headroom (%d rows at "
                    "quantized_grad_bits=%d); training on f32 gradients",
                    n_rows, limit, config.quantized_grad_bits)
                config.quantized_training = False

        # device-resident training state
        self.bins = None if ooc_on else jnp.asarray(train_set.binned)
        self.num_bins = int(train_set.max_num_bin)
        self.meta = FeatureMeta.from_dataset(train_set)
        self.hyper = SplitHyper.from_config(config)
        # composable trainer core (tree/strategy.py): built AFTER the
        # quantized-headroom check above so the strategy reflects any
        # capability decline; rides GrowParams as a static (hashable)
        # field, so every learner picks plug-ins up through one seam
        from ..tree.strategy import TreeStrategy

        self.strategy = TreeStrategy.from_config(config, train_set)
        self.grow_params = GrowParams(
            num_leaves=config.num_leaves,
            num_bins=self.num_bins,
            max_depth=config.max_depth,
            use_missing=config.use_missing,
            top_k=config.top_k,
            quantized=config.quantized_training,
            quant_bits=config.quantized_grad_bits,
            quant_seed=config.seed,
            strategy=self.strategy,
        )
        # linear-tree state (tree/linear.py plug-in): the bin-value LUT
        # is built lazily on first fit; _linear_k pins the coefficient
        # width so every per-tree fit compiles one program shape
        self._value_lut = None
        self._linear_cat = None
        self._linear_k = None
        # tree-learner dispatch (TreeLearner::CreateTreeLearner,
        # tree_learner.cpp:9-33): serial on one chip, or a sharded learner
        # over the device mesh
        learner_type = config.tree_learner.lower()
        self.learner = None
        self.ptrainer = None
        if self._membership is not None:
            # elastic fleet: every member runs single-process JAX (the
            # jax.distributed service pins the world at init and turns
            # any peer death into an uncatchable C++ fatal), so the
            # leaf-wise loop is host-driven over the shared KV store.
            # The comm's rank/world are live properties of the epoch —
            # a transition resizes the learner with no learner change.
            from ..parallel.hostlearner import HostParallelLearner
            from ..parallel.membership import MembershipComm

            if train_set.metadata.query_boundaries is not None:
                Log.fatal(
                    "elastic_membership does not support query-grouped "
                    "(ranking) datasets yet: transitions cannot "
                    "re-derive group boundaries across the new world")
            self.learner = HostParallelLearner(
                "data", MembershipComm(self._membership), self.grow_params)
            Log.info(
                "Using host-driven elastic data-parallel learner: "
                "member=%d rank=%d/%d epoch=%d", self._membership.id,
                self._membership.rank, self._membership.nproc,
                self._membership.epoch)
        elif ooc_on:
            import jax as _jax

            if learner_type == "data" and _jax.process_count() > 1:
                # rank-sharded streaming: every rank streams its own
                # shard and node histograms merge over the hardened
                # byte collectives (boosting/oocdist.py)
                from ..parallel.comm import NetComm
                from .oocdist import DistributedOocTrainer

                self.ooc = DistributedOocTrainer(
                    train_set, config, self.grow_params, ooc_chunk_rows,
                    NetComm())
                Log.info(
                    "Using distributed out-of-core data-parallel "
                    "learner over %d processes", _jax.process_count())
            else:
                if learner_type == "data":
                    Log.warning(
                        "tree_learner=data requested with out-of-core "
                        "streaming but only one process is attached; "
                        "streaming serially")
                from .ooc import OocTrainer

                self.ooc = OocTrainer(
                    train_set, config, self.grow_params, ooc_chunk_rows)
            self.learner = self.ooc
        elif learner_type in ("data", "feature", "voting"):
            import jax as _jax

            from ..parallel import ShardedLearner, make_mesh

            nproc = _jax.process_count()
            if nproc > 1 and learner_type in ("feature", "voting"):
                # column-sharded / PV-Tree learners have no fused
                # multi-process formulation: the host drives the
                # leaf-wise loop over the hardened byte collectives
                from ..parallel.comm import NetComm
                from ..parallel.hostlearner import HostParallelLearner

                self.learner = HostParallelLearner(
                    learner_type, NetComm(), self.grow_params)
                Log.info(
                    "Using host-driven %s-parallel learner over %d "
                    "processes", learner_type, nproc)
            elif len(_jax.devices()) < 2:
                Log.warning(
                    "tree_learner=%s requested but only one device is "
                    "visible; falling back to serial", learner_type,
                )
            else:
                # data-parallel rides the partitioned fast path when
                # eligible (histogram psum per split); feature/voting
                # keep the mask grower's collective formulations
                if (learner_type == "data" and self.supports_partitioned
                        and self.supports_partitioned_data):
                    from .ptrainer import (
                        ShardedPartitionedTrainer,
                        eligible as _pt_eligible,
                    )

                    if _pt_eligible(config, train_set, objective,
                                    self.num_tree_per_iteration):
                        self.ptrainer = ShardedPartitionedTrainer(
                            train_set, config, objective, self.meta,
                            self.hyper, make_mesh(),
                        )
                        Log.info(
                            "Using data-parallel partitioned (fused) TPU "
                            "tree learner over %d devices",
                            self.ptrainer.d,
                        )
                if self.ptrainer is None:
                    if nproc > 1 and _jax.default_backend() == "cpu":
                        # XLA:CPU rejects multi-process computations;
                        # data-parallel runs host-driven over the KV
                        # collectives (same transport rule as collect.py)
                        from ..parallel.comm import NetComm
                        from ..parallel.hostlearner import (
                            HostParallelLearner,
                        )

                        self.learner = HostParallelLearner(
                            "data", NetComm(), self.grow_params)
                        Log.info(
                            "Using host-driven data-parallel learner "
                            "over %d processes", nproc)
                    else:
                        self.learner = ShardedLearner(
                            learner_type, make_mesh(), self.grow_params
                        )
        elif learner_type != "serial":
            Log.fatal("Unknown tree learner type %s", config.tree_learner)

        # Partitioned fused trainer (ops/pgrow.py): the TPU fast path for
        # serial single-class training with a row-local objective.  (The
        # earlier host-driven FastGrower is gone: per-split host round
        # trips cost ~80 ms over a tunneled device; pgrow supersedes it.)
        if self.learner is None and self.ptrainer is None and self.supports_partitioned:
            from .ptrainer import PartitionedTrainer, eligible as _pt_eligible

            if _pt_eligible(config, train_set, objective, self.num_tree_per_iteration):
                self.ptrainer = PartitionedTrainer(
                    train_set, config, objective, self.meta, self.hyper,
                    bins_dev=self.bins,
                )
                Log.info("Using partitioned (fused) TPU tree learner")
        k = self.num_tree_per_iteration
        self.scores = jnp.zeros((k, self.num_data), jnp.float32)
        init_score = train_set.metadata.init_score
        self.has_init_score = init_score is not None
        if self.has_init_score:
            self.scores = self.scores + jnp.asarray(
                np.asarray(init_score, np.float32).reshape(k, -1)
            )

        # validation sets
        self.valid_sets = []
        self.valid_bins = []
        self.valid_scores = []
        self.valid_metrics = []
        self.valid_names = []
        self.best_iter = []
        self.best_score = []
        self.best_msg = []

        # bagging state
        self.bag_rng = np.random.RandomState(config.bagging_seed)
        self.need_re_bagging = False
        self.is_bagging = (
            config.bagging_fraction < 1.0 and config.bagging_freq > 0
        )
        self.select = jnp.ones(self.num_data, jnp.float32)
        self.feature_rng = Random(config.feature_fraction_seed)
        self.full_feature_mask = jnp.ones(train_set.num_features, jnp.float32)

        # per-class "does this class have data" (SkipEmptyClass handling)
        self.class_need_train = [True] * k
        self.class_default_output = [0.0] * k

        # straggler-aware shard rebalancing (parallel/shardplan.py):
        # armed only when rebalance=true AND the learner actually owns a
        # row shard; OFF is the exact pre-existing static-shard behavior
        # (zero extra collectives)
        self._rebalance = None
        self._initial_local_rows = int(self.num_data)
        if getattr(config, "rebalance", False):
            self._init_rebalance()

        # elastic joiner: adopt the fleet's canonical state (the handoff
        # the coordinator published at admission).  No collectives here —
        # the survivors are mid-iteration when a joiner initializes.
        if self._membership is not None and self._membership.joined_mid_run:
            self._membership_join_restore()

    def add_valid(self, valid_set, valid_metrics, name: str):
        """GBDT::AddValidDataset (gbdt.cpp:220-250)."""
        self.valid_sets.append(valid_set)
        vb = jnp.asarray(valid_set.binned)
        self.valid_bins.append(vb)
        k = self.num_tree_per_iteration
        vs = jnp.zeros((k, valid_set.num_data), jnp.float32)
        init_score = valid_set.metadata.init_score
        if init_score is not None:
            vs = vs + jnp.asarray(np.asarray(init_score, np.float32).reshape(k, -1))
        # replay existing models onto the new valid set
        if self.models:
            arrays = stack_trees(self.models)
            for kk in range(k):
                idx = np.asarray(
                    [i * k + kk for i in range(len(self.models) // k)]
                )
                vs = vs.at[kk].add(
                    self._predict_binned_arrays(vb, arrays, idx)
                )
        self.valid_scores.append(vs)
        self.valid_metrics.append(list(valid_metrics))
        self.valid_names.append(name)
        self.best_iter.append([0] * len(valid_metrics))
        self.best_score.append([K_MIN_SCORE] * len(valid_metrics))
        self.best_msg.append([""] * len(valid_metrics))

    # ------------------------------------------------------------------
    def _boost_from_average(self):
        """gbdt.cpp:381-399 + LabelAverage (:349-379)."""
        if (
            not self.models
            and self.config.boost_from_average
            and not self.has_init_score
            and self.num_class <= 1
            and self.objective is not None
            and self.objective.boost_from_average
        ):
            label = np.asarray(self.train_set.metadata.label)
            import jax as _jax

            if self._membership is not None:
                # global label average over the live fleet (same
                # Allreduce shape as below, on the membership transport)
                sums = np.stack([
                    np.frombuffer(b, np.float64)
                    for b in self._membership.comm_allgather(
                        np.asarray([label.sum(), float(len(label))],
                                   np.float64).tobytes(),
                        what="label_average")
                ])
                init_score = float(sums[:, 0].sum() / max(sums[:, 1].sum(), 1.0))
            elif _jax.process_count() > 1:
                # distributed label average (GBDT::LabelAverage Allreduce,
                # gbdt.cpp:349-379): every process must boost from the
                # GLOBAL mean, not its local shard's
                from jax.experimental import multihost_utils

                sums = np.asarray(
                    multihost_utils.process_allgather(
                        np.asarray([label.sum(), float(len(label))])
                    )
                )
                init_score = float(sums[:, 0].sum() / max(sums[:, 1].sum(), 1.0))
            else:
                init_score = float(np.mean(label))
            tree = Tree.constant(init_score)
            self.scores = self.scores + jnp.float32(init_score)
            self.valid_scores = [vs + jnp.float32(init_score) for vs in self.valid_scores]
            if self.ptrainer is not None:
                self.ptrainer.add_score_constant(init_score)
            self.models.append(tree)
            self.boost_from_average_ = True
            Log.info("Start training from score %f", init_score)

    def _bagging(self, iter_: int) -> None:
        """Re-sample the 0/1 row mask (GBDT::Bagging, gbdt.cpp:275-334)."""
        if not self.is_bagging or iter_ % self.config.bagging_freq != 0:
            return
        bag_cnt = int(self.config.bagging_fraction * self.num_data)
        perm = self.bag_rng.permutation(self.num_data)
        mask = np.zeros(self.num_data, np.float32)
        mask[perm[:bag_cnt]] = 1.0
        self.select = jnp.asarray(mask)

    def _feature_mask(self):
        """feature_fraction sampling per tree
        (SerialTreeLearner::BeforeTrain, serial_tree_learner.cpp:236-262)."""
        frac = self.config.feature_fraction
        f = self.train_set.num_features
        if frac >= 1.0:
            return self.full_feature_mask
        used_cnt = max(1, int(f * frac))
        idx = self.feature_rng.sample(f, used_cnt)
        mask = np.zeros(f, np.float32)
        mask[idx] = 1.0
        return jnp.asarray(mask)

    def _get_gradients(self):
        """objective_->GetGradients (Boosting(), gbdt.cpp:692-700); returns
        (K, N) device arrays."""
        score = self.get_training_score()
        if self.num_tree_per_iteration == 1:
            g, h = self.objective.get_gradients(score[0])
            return g[None, :], h[None, :]
        return self.objective.get_gradients(score)

    def get_training_score(self):
        """Hook for DART's drop-then-score (GetTrainingScore)."""
        return self.scores

    # ------------------------------------------------------------------
    def train_one_iter(self, gradients=None, hessians=None, is_eval: bool = True) -> bool:
        """One boosting iteration (GBDT::TrainOneIter, gbdt.cpp:381-495).
        Returns True when training should stop.

        Under elastic membership this is a bounded retry loop: a peer
        death surfaces as ``net.PeerFailureError`` from some collective,
        the survivors negotiate a fleet resize at this boundary, and the
        iteration is replayed (or, when it already completed and only
        the boundary bookkeeping was cut short, skipped)."""
        if self._membership is None:
            return self._train_one_iter_impl(gradients, hessians, is_eval)

        from ..parallel import net as _net

        for _attempt in range(3):
            self._iter_complete = False
            try:
                return self._train_one_iter_impl(gradients, hessians, is_eval)
            except _net.PeerFailureError as e:
                self._membership_recover(e)
                if self._iter_complete:
                    # the trees of this iteration landed before the
                    # failure; only sync/eval was cut short — do not
                    # train it twice
                    return False
        raise _net.PeerFailureError(
            "membership recovery did not converge after 3 attempts")

    def _train_one_iter_impl(self, gradients=None, hessians=None,
                             is_eval: bool = True) -> bool:
        """The actual iteration body (see :meth:`train_one_iter`)."""
        from ..utils.profiling import timetag

        if self.ptrainer is not None and gradients is None:
            return self.train_iters_partitioned(1, is_eval=is_eval)

        import time as _time

        t_iter0 = _time.perf_counter()
        if self._membership is not None:
            # boundary snapshot for exact replay: a mid-iteration peer
            # failure rolls the RNG streams, the bagging mask AND the f32
            # score caches back so the retried iteration replays from a
            # bit-identical state (device arrays are immutable, so the
            # score snapshots are reference-captures, not copies)
            self._member_iter_snapshot = {
                "bag_rng": self.bag_rng.get_state(),
                "feature_rng": self.feature_rng.get_state(),
                "select": self.select,
                "num_models": len(self.models),
                "boost_from_average": self.boost_from_average_,
                "scores": self.scores,
                "valid_scores": tuple(self.valid_scores),
            }
        self._boost_from_average()

        # comms-volume accounting: the host-driven parallel learners keep
        # an always-on purpose->bytes ledger; snapshot it around the
        # iteration so irec carries this iteration's bytes sent
        comm = getattr(self.learner, "comm", None)
        bytes_before = comm.ledger_total() if comm is not None else 0

        with tracer.iteration(self.iter) as irec:
            with timetag.phase("boosting"):
                if gradients is None or hessians is None:
                    grad, hess = self._get_gradients()
                else:
                    grad = jnp.asarray(np.asarray(gradients, np.float32).reshape(
                        self.num_tree_per_iteration, -1))
                    hess = jnp.asarray(np.asarray(hessians, np.float32).reshape(
                        self.num_tree_per_iteration, -1))
                fence((grad, hess))

            with timetag.phase("bagging"):
                grad, hess = self._adjust_gradients(grad, hess)
                self._bagging(self.iter)
                fence(self.select)

            should_continue = False
            leaves_grown = 0
            # quantized training (use_quantized_grad): grad/hess go to the
            # learner as stochastically-rounded int16 with a per-class
            # global scale.  The host-driven parallel learners quantize
            # internally (they must allgather the scale maxima first).
            quantize = (self.config.quantized_training
                        and not getattr(self.learner,
                                        "quantizes_internally", False))
            for k in range(self.num_tree_per_iteration):
                feature_mask = self._feature_mask()
                with timetag.phase("tree"):
                    gk, hk, qscale = grad[k], hess[k], None
                    if quantize:
                        gk, hk, qscale = self._quantize_class(gk, hk, k)
                    if self.learner is not None:
                        gr = self.learner.grow(
                            self.bins, gk, hk, self.select, feature_mask,
                            self.meta, self.hyper,
                            **({"qscale": qscale} if qscale is not None
                               else {}),
                        )
                    else:
                        gr = grow_tree(
                            self.bins,
                            gk,
                            hk,
                            self.select,
                            feature_mask,
                            self.meta,
                            self.hyper,
                            self.grow_params,
                            qscale=qscale,
                        )
                    fence(gr)
                num_splits = int(gr.num_splits)
                if num_splits > 0:
                    should_continue = True
                    leaves_grown += num_splits + 1
                    tree = Tree.from_grow_result(gr, self.train_set)
                    lin_fi = lin_fv = None
                    if self.strategy.leaf_fit.linear:
                        # fit BEFORE shrinkage: the ridge solve targets
                        # the unshrunk gradients; shrinkage then scales
                        # coefficients and constant together
                        lin_fi, lin_fv = self._fit_linear_tree(
                            tree, gr, gk, hk)
                    tree.shrinkage(self.shrinkage_rate)
                    audit.record_tree(self.iter, k, gr, tree)
                    if self.strategy.split_gain.constrained:
                        # splits on constrained features ran the
                        # clipped-output gain path (ops/split.py)
                        mono_t = self.strategy.split_gain.monotone
                        rf = np.asarray(gr.rec_feat[:num_splits])
                        tracer.counter(
                            "tree.monotone_clip",
                            float(sum(1 for f in rf
                                      if mono_t[int(f)] != 0)))
                    with timetag.phase("train_score"):
                        # score update via the grower's partition (one gather)
                        lv = np.zeros(self.grow_params.num_leaves, np.float32)
                        lv[: tree.num_leaves] = tree.leaf_value[: tree.num_leaves]
                        leaf_vals = jnp.asarray(lv)
                        if tree.is_linear and tree.leaf_is_linear[
                                : tree.num_leaves].any():
                            self._add_linear_train_scores(
                                tree, gr, k, lin_fi, lin_fv, leaf_vals)
                        else:
                            self.scores = self.scores.at[k].set(
                                add_leaf_outputs(self.scores[k], gr.leaf_id, leaf_vals)
                            )
                        fence(self.scores)
                    with timetag.phase("valid_score"):
                        self._add_tree_to_valid_scores(tree, k)
                        fence(self.valid_scores)
                else:
                    tree = Tree(2)  # empty tree, kept for alignment
                self.models.append(tree)
            if irec is not None:
                irec["leaves"] = leaves_grown
                irec["trees"] = self.num_tree_per_iteration
                if self.is_bagging:
                    irec["bagged_rows"] = int(jnp.sum(self.select))
                if comm is not None:
                    irec["net_bytes"] = comm.ledger_total() - bytes_before

        if not should_continue:
            Log.warning(
                "Stopped training because there are no more leaves that meet "
                "the split requirements."
            )
            for _ in range(self.num_tree_per_iteration):
                self.models.pop()
            return True

        self.iter += 1
        self._iter_complete = True
        if self.ptrainer is not None:
            # scores advanced outside the partitioned channel
            self.ptrainer.score_dirty = True
        if self._rebalance is not None:
            # lockstep on every rank: the tree growing above is
            # collective, so all ranks reach this boundary together
            self._maybe_rebalance(_time.perf_counter() - t_iter0)
        if self._membership is not None:
            # membership churn drains to this same lockstep boundary
            self._maybe_membership()
        if is_eval:
            return self.eval_and_check_early_stopping()
        return False

    def train_iters_partitioned(self, num_iters: int, is_eval: bool = True) -> bool:
        """Run ``num_iters`` boosting iterations through the fused
        partitioned trainer (one device program, no per-iteration host
        round-trips).  Returns True when training should stop."""
        from ..utils.profiling import timetag

        if num_iters <= 0:
            return False
        self._boost_from_average()
        pt = self.ptrainer
        K = self.num_tree_per_iteration
        if pt.score_dirty:
            pt.sync_scores_from(self.scores if K > 1 else self.scores[0])
        # traced mode: one iteration per dispatch group with REAL per-phase
        # (histogram/split/partition/score_update) device-synced timings;
        # opt-in via LIGHTGBM_TPU_TRACE_PHASES (defaults on only in
        # interpret mode, where defusing doesn't distort the measurement)
        use_traced = (
            tracer.enabled
            and getattr(pt, "supports_traced", False)
            and K == 1
            and getattr(self.config, "boosting", "gbdt") != "goss"
            and tracer.phases_enabled(default=pt.interpret)
        )
        import time as _time

        t_chunk0 = _time.perf_counter()
        with timetag.phase("tree"):
            if use_traced:
                recs, scores_orig, n_done = pt.train_chunk_traced(
                    num_iters, self.shrinkage_rate, self.iter
                )
            else:
                recs, scores_orig, n_done = pt.train_chunk(
                    num_iters, self.shrinkage_rate, self.iter
                )
        chunk_wall = _time.perf_counter() - t_chunk0
        if tracer.enabled and not use_traced and n_done > 0:
            # fused chunks execute as ONE device program: emit amortized
            # per-iteration records (flagged) so the trace still has an
            # iteration axis to join compile/memory signals against
            per = chunk_wall / n_done
            for t in range(n_done):
                ns = recs["num_splits"][t]
                tracer.emit_iter(
                    self.iter + t, per, {"fused_chunk": per},
                    leaves=int(np.sum(ns + (ns > 0))), trees=K,
                    amortized=True,
                )
        with timetag.phase("train_score"):
            self.scores = scores_orig[None, :] if K == 1 else scores_orig
            fence(self.scores)
        chunk_trees = [[] for _ in range(K)]
        for t in range(n_done):
            for k in range(K):
                view = pt.grow_result_view(recs, t, k)
                if int(view.num_splits) > 0:
                    tree = Tree.from_grow_result(view, self.train_set)
                    tree.shrinkage(self.shrinkage_rate)
                    audit.record_tree(self.iter + t, k, view, tree)
                    chunk_trees[k].append(tree)
                else:
                    tree = Tree(2)  # empty tree, kept for class alignment
                self.models.append(tree)
        # valid scores advance ONCE per chunk per class: a single stacked
        # predict_binned over all of the chunk's trees (vs one dispatch
        # per tree — ~5 ms tunnel dispatch each)
        with timetag.phase("valid_score"):
            for k in range(K):
                if chunk_trees[k]:
                    self._add_trees_to_valid_scores(chunk_trees[k], k)
        self.iter += n_done
        if n_done < num_iters:
            Log.warning(
                "Stopped training because there are no more leaves that meet "
                "the split requirements."
            )
            return True
        if is_eval:
            return self.eval_and_check_early_stopping()
        return False

    def _adjust_gradients(self, grad, hess):
        """Hook for GOSS's gradient re-weighting; identity for GBDT."""
        return grad, hess

    def _quantize_class(self, gk, hk, k: int):
        """Quantize one class's (N,) grad/hess to int16 for the exact
        integer histogram path (ops/qhist.py).

        The scale is global over the selected rows: under a multi-process
        learner (ShardedLearner spanning hosts) the per-process abs-maxima
        are allgathered and max-reduced first, so every process derives
        the bit-identical scale — grow_tree psums the int32 histograms
        across the whole mesh, which is only meaningful when all levels
        share one scale.  The stochastic-rounding seed is value-keyed
        plus an (iteration, class) salt, so replays and row shuffles
        reproduce the same quantized vectors bit for bit."""
        import jax as _jax

        from ..ops import qhist

        bits = self.config.quantized_grad_bits
        mx = np.asarray(qhist.local_absmax(gk, hk, self.select), np.float32)
        if _jax.process_count() > 1:
            # same exchange HostParallelLearner does via its _QMAX blobs;
            # max is order-invariant, so every process agrees exactly
            from jax.experimental import multihost_utils

            mx = np.asarray(
                multihost_utils.process_allgather(mx), np.float32
            ).max(axis=0)
        qscale_np = qhist.scales_from_max(mx[0], mx[1], bits)
        seed = (int(self.config.seed) * 2654435761
                + self.iter * 97 + k * 131071 + 1) & 0xFFFFFFFF
        qscale = jnp.asarray(qscale_np)
        gq, hq = qhist.quantize_rows(gk, hk, qscale, np.uint32(seed), bits)
        return gq, hq, qscale

    def _add_tree_to_valid_scores(self, tree: Tree, k: int) -> None:
        self._add_trees_to_valid_scores([tree], k)

    def _add_trees_to_valid_scores(self, trees: List[Tree], k: int) -> None:
        if not self.valid_bins:
            return
        arrays = stack_trees(trees)
        for i, vb in enumerate(self.valid_bins):
            self.valid_scores[i] = self.valid_scores[i].at[k].add(
                self._predict_binned_arrays(vb, arrays)
            )

    def _add_tree_to_train_scores(self, tree: Tree, k: int) -> None:
        """Full binned traversal on the training set (used by rollback/DART
        where the grower's partition is no longer available)."""
        arrays = stack_trees([tree])
        if self.bins is None:
            # out-of-core: traversal is per-row, so streaming it over the
            # chunk grid is exact
            if "leaf_feat_inner" in arrays:
                arrays = dict(arrays)
                arrays["value_lut"] = self._linear_lut()[0]
            self.scores = self.scores.at[k].set(
                self.ooc.add_tree_scores(self.scores[k], arrays)
            )
            return
        self.scores = self.scores.at[k].add(
            self._predict_binned_arrays(self.bins, arrays)
        )

    # -- linear-leaf plug-in (tree/linear.py LeafFit strategy) ---------
    def _linear_lut(self):
        """Cached ``(value_lut, is_categorical)`` pair: the (F, B) f32
        bin-representative table every linear fit/score path shares, and
        the per-inner-feature categorical mask that keeps categorical
        splits out of leaf models."""
        if self._value_lut is None:
            from ..io.binning import CATEGORICAL
            from ..tree.linear import build_value_lut

            self._value_lut = jnp.asarray(
                build_value_lut(self.train_set, self.num_bins))
            self._linear_cat = np.asarray(
                [m.bin_type == CATEGORICAL
                 for m in self.train_set.bin_mappers], bool)
        return self._value_lut, self._linear_cat

    def _linear_kmax(self) -> int:
        """Pinned coefficient width: every per-tree fit pads its path
        planes to this k, so the batched Cholesky (and the OOC stats
        fold) compiles exactly one program shape per training run."""
        if self._linear_k is None:
            num_numerical = int((~self._linear_lut()[1]).sum())
            k = min(self.grow_params.num_leaves - 1, num_numerical)
            if self.config.max_depth > 0:
                k = min(k, self.config.max_depth)
            self._linear_k = max(k, 1)
        return self._linear_k

    def _fit_linear_tree(self, tree: Tree, gr, gk, hk):
        """Fit per-leaf ridge models for a freshly-grown tree (BEFORE
        shrinkage): accumulate the (L, k+1, k+1) normal equations over
        the selected rows, solve as one batched Cholesky, and attach the
        models to ``tree``.  Returns the packed (L, k) device path
        planes so the train-score update reuses them."""
        from ..tree.linear import (leaf_path_features, linear_fit_stats,
                                   pack_path_features, solve_linear_leaves)

        lut, is_cat = self._linear_lut()
        L = self.grow_params.num_leaves
        with tracer.span("tree.leaf_fit", leaves=tree.num_leaves):
            paths = leaf_path_features(gr, is_cat)
            fi, fv = pack_path_features(paths, L,
                                        k_max=self._linear_kmax())
            fi_d = jnp.asarray(fi)
            fv_d = jnp.asarray(fv)
            if self.bins is None:
                a, b = self.ooc.folder.fold_linear_stats(
                    gk, hk, self.select, gr.leaf_id, fi_d, fv_d, lut, L)
            else:
                a, b = linear_fit_stats(
                    self.bins, gk, hk, self.select, gr.leaf_id, fi_d,
                    fv_d, lut, L)
            w, ok = solve_linear_leaves(
                a, b, fv_d, gr.leaf_cnt,
                jnp.float32(self.strategy.leaf_fit.linear_lambda),
                jnp.float32(self.hyper.lambda_l2))
            w = np.asarray(w)
            tree.set_linear_models(paths, w[:, 1:], w[:, 0],
                                   np.asarray(ok), self.train_set)
        return fi_d, fv_d

    def _add_linear_train_scores(self, tree: Tree, gr, k: int, fi, fv,
                                 leaf_vals) -> None:
        """Train-score update for a linear tree via the grower's
        partition: linear leaves evaluate their (shrunk) model at the
        bin-representative values, constant-fallback leaves add the
        classic leaf output (``leaf_vals`` is the padded fallback
        plane)."""
        from ..tree.linear import linear_leaf_scores

        lut = self._linear_lut()[0]
        L, kw = fi.shape
        coeff = np.zeros((L, kw), np.float32)
        const = np.zeros(L, np.float32)
        isl = np.zeros(L, bool)
        for i in range(tree.num_leaves):
            if tree.leaf_is_linear[i]:
                cs = tree.leaf_coeff[i]
                coeff[i, : len(cs)] = cs
                const[i] = tree.leaf_const[i]
                isl[i] = True
        coeff_d = jnp.asarray(coeff)
        const_d = jnp.asarray(const)
        isl_d = jnp.asarray(isl)
        if self.bins is None:
            self.scores = self.scores.at[k].set(
                self.ooc.folder.fold_linear_scores(
                    self.scores[k], gr.leaf_id, fi, fv, coeff_d,
                    const_d, leaf_vals, isl_d, lut)
            )
            return
        self.scores = self.scores.at[k].add(
            linear_leaf_scores(self.bins, gr.leaf_id, fi, fv, coeff_d,
                               const_d, leaf_vals, isl_d, lut)
        )

    def _predict_binned_arrays(self, bins, arrays, idx=None):
        """Stacked-tree binned scoring, routed through the linear
        traversal when the stack carries linear-leaf planes
        (model/ensemble.py emits them only then) — constant ensembles
        keep the exact pre-strategy ``predict_binned`` dispatch."""
        def sel(name):
            a = arrays[name]
            return a if idx is None else a[idx]

        planes = (
            sel("split_feature_inner"), sel("threshold_bin"),
            sel("zero_bin"), sel("default_bin_for_zero"),
            sel("is_categorical"), sel("left_child"),
            sel("right_child"), sel("leaf_value"),
        )
        if "leaf_feat_inner" not in arrays:
            return predict_binned(bins, *planes)
        from ..tree.linear import predict_linear_binned

        return predict_linear_binned(
            bins, *planes, sel("leaf_feat_inner"), sel("leaf_feat_valid"),
            sel("leaf_coeff"), sel("leaf_const"), sel("leaf_is_linear"),
            self._linear_lut()[0])

    def rollback_one_iter(self) -> None:
        """GBDT::RollbackOneIter (gbdt.cpp:497-514)."""
        if self.iter <= 0:
            return
        k = self.num_tree_per_iteration
        last = self.models[-k:]
        for tree_id, tree in enumerate(last):
            tree.shrinkage(-1.0)
            self._add_tree_to_train_scores(tree, tree_id)
            for i in range(len(self.valid_bins)):
                arrays = stack_trees([tree])
                self.valid_scores[i] = self.valid_scores[i].at[tree_id].add(
                    self._predict_binned_arrays(self.valid_bins[i], arrays)
                )
        del self.models[-k:]
        self.iter -= 1
        if self.ptrainer is not None:
            # keep the partitioned score channel consistent (the segment
            # layout still matches the popped tree, so this is one cheap
            # in-place subtract; otherwise resync lazily)
            if not self.ptrainer.rollback_last():
                self.ptrainer.score_dirty = True

    # ------------------------------------------------------------------
    def eval_and_check_early_stopping(self) -> bool:
        """EvalAndCheckEarlyStopping + OutputMetric (gbdt.cpp:516-622)."""
        best_msg = self._output_metric(self.iter)
        if best_msg:
            Log.info(
                "Early stopping at iteration %d, the best iteration round is %d",
                self.iter,
                self.iter - self.config.early_stopping_round,
            )
            Log.info("Output of best iteration round:\n%s", best_msg)
            n_pop = self.config.early_stopping_round * self.num_tree_per_iteration
            del self.models[len(self.models) - n_pop:]
            return True
        return False

    def _train_score_host(self):
        return np.asarray(self.scores, np.float64)

    def _valid_score_host(self, i):
        return np.asarray(self.valid_scores[i], np.float64)

    def _metric_score(self, score):
        """(K, N) -> what metrics expect: (N,) when single-class."""
        return score[0] if score.shape[0] == 1 else score

    def _eval_metric(self, m, score_dev, host_fn):
        """Evaluate one metric, preferring its device twin (metric/
        device.py): keeps the (K, N) scores device-resident and transfers
        one scalar instead of pulling + sorting the full vector on host.
        ``host_fn`` should be a ``_memo``-wrapped puller so several
        host-path metrics on one dataset share a single transfer."""
        if getattr(type(m), "_dev_fn", None) is not None:
            try:
                return m.eval_device(self._metric_score(score_dev), self.objective)
            except Exception:  # pragma: no cover - fall back to host path
                pass
        return m.eval(self._metric_score(host_fn()), self.objective)

    def _output_metric(self, iter_: int) -> str:
        es_round = self.config.early_stopping_round
        need_output = (iter_ % self.config.output_freq) == 0
        msg_parts = []
        ret = ""
        if need_output and self.training_metrics:
            host_fn = _memo(self._train_score_host)
            for m in self.training_metrics:
                for name, val in self._eval_metric(m, self.scores, host_fn):
                    line = f"Iteration:{iter_}, training {name} : {val:g}"
                    Log.info("%s", line)
                    if es_round > 0:
                        msg_parts.append(line)
        meet = []
        if need_output or es_round > 0:
            for i in range(len(self.valid_metrics)):
                host_fn = _memo(functools.partial(self._valid_score_host, i))
                for j, m in enumerate(self.valid_metrics[i]):
                    results = self._eval_metric(m, self.valid_scores[i], host_fn)
                    for name, val in results:
                        line = f"Iteration:{iter_}, valid_{i+1} {name} : {val:g}"
                        if need_output:
                            Log.info("%s", line)
                        if es_round > 0:
                            msg_parts.append(line)
                    if not ret and es_round > 0:
                        factor = 1.0 if m.bigger_is_better else -1.0
                        cur = factor * results[-1][1]
                        if cur > self.best_score[i][j]:
                            self.best_score[i][j] = cur
                            self.best_iter[i][j] = iter_
                            meet.append((i, j))
                        elif iter_ - self.best_iter[i][j] >= es_round:
                            ret = self.best_msg[i][j]
        msg = "\n".join(msg_parts)
        for i, j in meet:
            self.best_msg[i][j] = msg
        return ret

    def get_eval_at(self, data_idx: int):
        """GBDT::GetEvalAt — [(name, value, bigger_is_better), ...] for
        callbacks/early stopping."""
        out = []
        if data_idx == 0:
            score_dev, host_fn = self.scores, _memo(self._train_score_host)
            metrics = self.training_metrics
        else:
            score_dev = self.valid_scores[data_idx - 1]
            host_fn = _memo(functools.partial(self._valid_score_host, data_idx - 1))
            metrics = self.valid_metrics[data_idx - 1]
        for m in metrics:
            for name, val in self._eval_metric(m, score_dev, host_fn):
                out.append((name, val, m.bigger_is_better))
        return out

    # ------------------------------------------------------------------
    # ------------------------------------------------------------------
    # straggler-aware shard rebalancing (parallel/shardplan.py)
    # ------------------------------------------------------------------
    def _rebalance_gather(self, blob: bytes):
        """The rebalance control-plane allgather: membership fleets ride
        the epoch-aware learner comm (jax.process_count() is 1 there);
        static fleets keep the exact pre-existing byte collectives."""
        if self._membership is not None:
            return self.learner.comm.allgather(blob, purpose="rebalance")
        from ..parallel.collect import allgather_bytes

        return allgather_bytes(blob, purpose="rebalance")

    def _init_rebalance(self) -> None:
        """Arm the rebalance controller when this run actually owns a
        row shard; otherwise log why the knob is ignored."""
        import jax as _jax

        from ..parallel.hostlearner import HostParallelLearner

        rt = self._membership
        nproc = rt.nproc if rt is not None else _jax.process_count()
        md = self.train_set.metadata
        why = None
        if nproc <= 1 and rt is None:
            why = "single process (nothing to rebalance)"
        elif self.ptrainer is not None:
            why = "fused partitioned trainer (static device layout)"
        elif self.ooc is not None:
            why = "out-of-core streaming (rows are disk-resident)"
        elif self.learner is None:
            why = "serial learner"
        elif (isinstance(self.learner, HostParallelLearner)
              and self.learner.mode == "feature"):
            why = "feature-parallel learner (columns are sharded, not rows)"
        elif md.init_score is not None:
            why = "per-row init_score is not relocatable yet"
        if why is not None:
            Log.warning("rebalance=true ignored: %s", why)
            return
        from ..parallel.shardplan import RebalanceController, ShardPlan

        if rt is not None:
            counts = list(rt.counts)
            rank = rt.rank
        else:
            counts = [
                int.from_bytes(g, "little")
                for g in self._rebalance_gather(
                    int(self.num_data).to_bytes(8, "little"))
            ]
            rank = _jax.process_index()
        group_bounds = None
        if md.query_boundaries is not None:
            # query-grouped data (lambdarank): moves snap to whole query
            # groups, so exchange the per-rank group sizes once and keep
            # the cumulative GLOBAL group boundaries in the controller
            sizes = np.diff(np.asarray(md.query_boundaries, np.int64))
            blobs = self._rebalance_gather(
                np.ascontiguousarray(sizes, np.int64).tobytes())
            all_sizes = np.concatenate(
                [np.frombuffer(b, np.int64) for b in blobs])
            group_bounds = np.concatenate(([0], np.cumsum(all_sizes)))
        self._rebalance = {
            "plan": ShardPlan.from_counts(counts),
            "ctl": RebalanceController(
                threshold=self.config.rebalance_threshold,
                patience=self.config.rebalance_patience,
                max_move_frac=self.config.rebalance_max_move_frac,
                group_bounds=group_bounds,
            ),
            "rank": rank,
            "group_bounds": group_bounds,
        }
        Log.info(
            "Shard rebalancing armed: shards=%s threshold=%.2f "
            "patience=%d max_move_frac=%.2f groups=%s", counts,
            self.config.rebalance_threshold,
            self.config.rebalance_patience,
            self.config.rebalance_max_move_frac,
            "whole-query" if group_bounds is not None else "row",
        )

    def _maybe_rebalance(self, wall_s: float) -> None:
        """Once per iteration, in lockstep on every rank: exchange the
        tiny per-rank compute/wait/heartbeat table, run the identical
        deterministic controller on it, and apply the plan it proposes
        at this iteration boundary."""
        import json as _json

        from ..parallel import net as _net

        rb = self._rebalance
        wait_s = _net.wait_clock_drain()
        compute_s = max(wall_s - wait_s, 0.0)
        hb_age = 0.0
        watch = (self._membership.watch if self._membership is not None
                 else _net.peer_watch())
        if watch is not None:
            ages = watch.ages()
            if ages:
                hb_age = max(float(v) for v in ages.values())
        entry = {"compute_s": compute_s, "wait_s": wait_s,
                 "hb_age": hb_age}
        table = [
            _json.loads(g)
            for g in self._rebalance_gather(_json.dumps(entry).encode())
        ]
        plan = rb["plan"]
        new_plan = rb["ctl"].observe(
            plan,
            [t["compute_s"] for t in table],
            [t["hb_age"] for t in table],
        )
        if new_plan is None:
            return
        tracer.event(
            "rebalance.trigger", iter=self.iter,
            compute_s=[round(float(t["compute_s"]), 4) for t in table],
            wait_s=[round(float(t["wait_s"]), 4) for t in table],
        )
        self._apply_rebalance(plan, new_plan)

    def _apply_rebalance(self, old_plan, new_plan) -> None:
        """Move row blocks to the new plan — 'checkpoint reshape in
        RAM': the same contiguous-slice semantics as the elastic restore
        path (ckpt/state.py reshard_to_local), applied to the live
        dataset/score/bagging state, then every row-derived binding is
        refreshed."""
        from ..parallel import net as _net
        from ..parallel.shardplan import exchange_rows

        rank = self._rebalance["rank"]
        md = self.train_set.metadata
        blocks = {
            "binned": (np.asarray(self.train_set.binned), 0),
            "label": (np.asarray(md.label), 0),
            "scores": (np.asarray(self.scores, np.float32), 1),
            "select": (np.asarray(self.select, np.float32), 0),
        }
        if md.weights is not None:
            blocks["weights"] = (np.asarray(md.weights), 0)
        if getattr(self.train_set, "bundled", None) is not None:
            blocks["bundled"] = (np.asarray(self.train_set.bundled), 0)
        comm = (self.learner.comm if self._membership is not None
                else None)
        moved = exchange_rows(old_plan, new_plan, rank, blocks, comm=comm)
        n_new = int(new_plan.counts[rank])

        self.train_set.binned = moved["binned"]
        if "bundled" in moved:
            self.train_set.bundled = moved["bundled"]
        md.num_data = n_new
        md.label = moved["label"]
        if "weights" in moved:
            md.weights = moved["weights"]
        # the shard's rows changed: cached checkpoint fingerprints are
        # stale (the GLOBAL fingerprint is invariant — contiguous
        # rank-ordered partition is preserved)
        for attr in ("_ckpt_fingerprint", "_ckpt_fp_parts"):
            if getattr(self.train_set, attr, None) is not None:
                setattr(self.train_set, attr, None)

        self.num_data = n_new
        if self.bins is not None:
            self.bins = jnp.asarray(self.train_set.binned)
        self.scores = jnp.asarray(moved["scores"])
        self.select = jnp.asarray(moved["select"])
        gb = self._rebalance.get("group_bounds")
        if gb is not None:
            # whole-group cuts (snap_to_groups) guarantee the new range
            # starts and ends on global group boundaries: re-derive the
            # local query layout before the objective re-binds it
            s, e = new_plan.rank_range(rank)
            local_b = gb[(gb >= s) & (gb <= e)]
            md.set_query(np.diff(local_b))
        # objective/metrics bind per-row device arrays at init
        if self.objective is not None:
            self.objective.init(md, n_new)
        for metric in self.training_metrics:
            metric.init(md, n_new)
        if self.learner is not None and hasattr(self.learner, "set_plan"):
            self.learner.set_plan(new_plan)
        self._rebalance["plan"] = new_plan
        if self._membership is not None:
            # rt.counts mirrors the epoch record, which only refreshes at
            # epoch commits — but eviction synthesis reads it as the LIVE
            # row layout.  Every member applies the identical plan in
            # lockstep (the controller is deterministic), so updating it
            # here keeps the whole fleet's view consistent mid-epoch.
            self._membership.counts = tuple(int(c) for c in new_plan.counts)
        # injected per-collective delays model per-row-slow hosts: their
        # stall shrinks with the rank's row share (bench.py elastic)
        _net.set_delay_scale(n_new / max(self._initial_local_rows, 1))
        moved_rows = sum(
            max(0, a - b) for a, b in zip(old_plan.counts, new_plan.counts)
        )
        tracer.counter("rebalance.move_rows", float(moved_rows))
        tracer.event("rebalance.plan", iter=self.iter,
                     before=list(old_plan.counts),
                     after=list(new_plan.counts))
        Log.info("Rebalanced shards at iteration %d: %s -> %s "
                 "(%d rows moved)", self.iter, list(old_plan.counts),
                 list(new_plan.counts), moved_rows)

    # ------------------------------------------------------------------
    # live elastic membership (parallel/membership.py)
    # ------------------------------------------------------------------
    def _maybe_membership(self) -> None:
        """Iteration-boundary membership sync, in lockstep on every
        member: a tiny intent allgather; on churn, drain into an epoch
        transition at this boundary."""
        decision = self._membership.sync()
        if decision is not None:
            self._apply_membership_change(decision)

    def _membership_recover(self, err) -> None:
        """A collective raised PeerFailureError: roll the partially-grown
        iteration back, converge on who is still alive, and resize."""
        rt = self._membership
        dead = tuple(r for r in getattr(err, "ranks", ()) if r != rt.id)
        Log.warning(
            "Peer failure under elastic membership: %s — negotiating a "
            "fleet resize (evidence: %s)", err, list(dead))
        if not self._iter_complete:
            self._membership_rollback_partial()
        decision = rt.sync(known_dead=dead)
        if decision is not None:
            self._apply_membership_change(decision)

    def _membership_rollback_partial(self) -> None:
        """Undo partially-grown iteration state left by a mid-grow peer
        failure so the retry replays from the boundary.  The boundary
        snapshot restores the score caches by reference, so the retry is
        bit-identical to a fleet that never saw the failure — including
        multi-class iterations, where arithmetically un-adding a tree
        would not round-trip (fl(fl(a+v)-v) != a in general).  The
        subtraction fallback only covers paths that never took a
        snapshot (e.g. the fused partitioned trainer's)."""
        snap = getattr(self, "_member_iter_snapshot", None)
        if snap is not None:
            # a first-iteration failure may land after _boost_from_average
            # ran: the snapshot predates it, so the constant tree and its
            # score shift roll back too and the retry re-derives the
            # global average on the resized fleet (same bytes — the
            # average is over the invariant global dataset)
            del self.models[snap["num_models"]:]
            self.boost_from_average_ = snap["boost_from_average"]
            self.scores = snap["scores"]
            self.valid_scores = list(snap["valid_scores"])
            self.bag_rng.set_state(snap["bag_rng"])
            self.feature_rng.set_state(snap["feature_rng"])
            self.select = snap["select"]
            return
        k = self.num_tree_per_iteration
        complete = self.iter * k + (1 if self.boost_from_average_ else 0)
        extra = self.models[complete:]
        for kk, tree in enumerate(extra):
            if tree.num_leaves > 1:
                tree.shrinkage(-1.0)
                self._add_tree_to_train_scores(tree, kk)
                self._add_tree_to_valid_scores(tree, kk)
        del self.models[complete:]

    def _membership_capture(self):
        """Snapshot this member's TrainState (ckpt.capture without the
        Booster wrapper — same meta contract, so the canonical merge /
        reshard machinery applies unchanged)."""
        from ..ckpt.state import (FORMAT_VERSION, TrainState,
                                  config_fingerprint, data_fingerprint,
                                  data_fingerprint_parts, pack_trees)

        arrays, py = self.export_train_state()
        arrays.update(pack_trees(self.models))
        meta = {
            "format_version": FORMAT_VERSION,
            "iteration": int(self.iter),
            "boosting_type": type(self).__name__.lower(),
            "num_models": len(self.models),
            "num_tree_per_iteration": int(self.num_tree_per_iteration),
            "num_data": int(self.num_data),
            "config_fingerprint": config_fingerprint(self.config),
            "data_fingerprint": data_fingerprint(self.train_set),
            "data_fingerprint_parts": data_fingerprint_parts(self.train_set),
            "num_valid": len(self.valid_scores),
            "best_iteration": -1,
        }
        return TrainState(meta, py, arrays)

    def _membership_replay_scores(self, binned) -> np.ndarray:
        """Re-derive a (K, n) f32 score cache for re-binned rows by
        replaying every tree in training accumulation order — one f32
        add per tree, the exact sequence the rows' original owner ran,
        so the replay is bit-identical to the scores it lost."""
        k = self.num_tree_per_iteration
        bins = jnp.asarray(binned)
        scores = jnp.zeros((k, binned.shape[0]), jnp.float32)
        offset = 1 if self.boost_from_average_ else 0
        for i, tree in enumerate(self.models):
            if tree.num_leaves <= 1:
                continue  # empty alignment tree: nothing was added
            kk = 0 if i < offset else (i - offset) % k
            arrays = stack_trees([tree])
            scores = scores.at[kk].add(
                self._predict_binned_arrays(bins, arrays))
        return np.asarray(scores, np.float32)

    def _membership_synthesize(self, member: int, own_state):
        """Reconstruct an evicted (SIGKILLed) member's TrainState without
        its participation: regenerate its rows through the row_provider
        seam, re-bin them with this member's mappers (identical on every
        member — the pre-partition contract), and replay the score cache.
        Deterministic, so every survivor synthesizes identical bytes."""
        from ..ckpt.state import TrainState, combine_fingerprint_parts
        from ..io.dataset import _bin_matrix
        from ..parallel import net as _net
        from ..parallel.shardplan import ShardPlan

        rt = self._membership
        if rt.row_provider is None:
            raise _net.PeerFailureError(
                f"cannot synthesize evicted member {member}'s shard: no "
                "row_provider seam armed (MembershipRuntime.row_provider)")
        if self.valid_scores:
            raise _net.PeerFailureError(
                "eviction with registered valid sets is not supported: "
                "the dead member's valid-score shard is unrecoverable")
        if type(self).__name__.lower() != "gbdt" and not getattr(
                self, "supports_membership_synthesis", False):
            raise _net.PeerFailureError(
                f"eviction under boosting type {type(self).__name__} is "
                "not supported: score replay assumes immutable past trees")
        # the LIVE layout, not the epoch record: a runtime rebalance moves
        # rows mid-epoch, so when the rebalancer is armed its applied plan
        # is authoritative (rt.counts is also kept in sync by
        # _apply_rebalance — this guards against any reader that isn't)
        old_plan = (self._rebalance["plan"] if self._rebalance is not None
                    else ShardPlan.from_counts(rt.counts))
        lo, hi = old_plan.rank_range(rt.members.index(member))
        X, y = rt.row_provider(lo, hi)
        ts = self.train_set
        binned = _bin_matrix(np.asarray(X, np.float64), ts.bin_mappers,
                             ts.used_feature_map)
        label = np.asarray(y, np.asarray(ts.metadata.label).dtype)
        n = int(binned.shape[0])
        import zlib as _zlib

        lab_bytes = np.ascontiguousarray(label).tobytes()
        parts = {
            "rows": n, "cols": int(binned.shape[1]),
            "crc_binned": _zlib.crc32(
                np.ascontiguousarray(binned).tobytes()) & 0xFFFFFFFF,
            "len_binned": int(binned.nbytes),
            "crc_label": _zlib.crc32(lab_bytes) & 0xFFFFFFFF,
            "len_label": len(lab_bytes),
        }
        rs = np.random.RandomState(self.config.bagging_seed)
        st = rs.get_state()
        arrays = dict(own_state.arrays)
        arrays["scores"] = self._membership_replay_scores(binned)
        # bagging-off fleets never mutate the mask; under bagging the
        # dead member's live mask is unrecoverable, so the reshard path's
        # need_re_bagging forces a fresh draw before the mask is used
        arrays["select"] = np.ones(n, np.float32)
        arrays["bag_rng_keys"] = np.asarray(st[1], np.uint32)
        py = dict(own_state.py)
        py["bag_rng"] = [str(st[0]), int(st[2]), int(st[3]), float(st[4])]
        py["need_re_bagging"] = True
        meta = dict(own_state.meta)
        meta["num_data"] = n
        meta["data_fingerprint"] = combine_fingerprint_parts([parts])
        meta["data_fingerprint_parts"] = parts
        meta["best_iteration"] = -1
        return TrainState(meta, py, arrays)

    def _apply_membership_change(self, decision) -> None:
        """One epoch transition, at an iteration boundary: gather every
        living participant's TrainState, synthesize the evicted ones,
        merge to the canonical global layout, commit the new epoch, and
        reshard to this member's new slice — all in RAM, the PR-15
        restart-time path made a runtime event."""
        import time as _time

        from ..ckpt import state as _ckpt
        from ..parallel import membership as _mship
        from ..parallel.shardplan import ShardPlan, _largest_remainder

        rt = self._membership
        t0 = _time.perf_counter()
        own = self._membership_capture()
        blobs = rt.gather_states(own.to_bytes(), decision.participants)
        states = dict(zip(decision.participants,
                          (_ckpt.TrainState.from_bytes(b) for b in blobs)))
        for d in decision.dead:
            states[d] = self._membership_synthesize(d, own)
        ordered = [states[m] for m in rt.members]
        canonical = _ckpt.merge_to_canonical(ordered)
        if rt.id in decision.leavers:
            # shard handed off; unwind out of the training loop
            raise _mship.CleanLeave(rt.epoch + 1)
        world = len(decision.new_members)
        total = int(canonical.meta["num_data"])
        counts = _largest_remainder([total / world] * world, total)
        handoff = canonical.to_bytes() if decision.joiners else None
        rt.commit_epoch(decision, counts, self.iter, total, handoff)
        self._membership_adopt(canonical, counts)
        pause = _time.perf_counter() - t0
        tracer.gauge("member.resize_pause_s", pause)
        self._membership_pauses.append(pause)
        Log.info(
            "Membership epoch %d at iteration %d: members=%s counts=%s "
            "(rank %d/%d)", rt.epoch, self.iter, list(rt.members),
            list(counts), rt.rank, rt.nproc)

    def _membership_adopt(self, canonical, counts) -> None:
        """Regenerate this member's new slice and restore its training
        state from the canonical container (reshard in RAM)."""
        from ..ckpt import state as _ckpt
        from ..io.dataset import _bin_matrix
        from ..parallel import collect as _collect
        from ..parallel import net as _net
        from ..parallel.shardplan import ShardPlan

        rt = self._membership
        # scope any collect.py gathers this process issues from here on
        # to the adopted epoch (fresh uid subtree — net.epoch_uid)
        _collect.set_epoch(rt.epoch)
        plan = ShardPlan.from_counts(counts)
        lo, hi = plan.rank_range(rt.rank)
        ts = self.train_set
        md = ts.metadata
        X, y = rt.row_provider(lo, hi)
        ts.binned = _bin_matrix(np.asarray(X, np.float64), ts.bin_mappers,
                                ts.used_feature_map)
        md.num_data = hi - lo
        md.set_label(np.asarray(y))
        for attr in ("_ckpt_fingerprint", "_ckpt_fp_parts"):
            if getattr(ts, attr, None) is not None:
                setattr(ts, attr, None)
        self.num_data = hi - lo
        if self.bins is not None:
            self.bins = jnp.asarray(ts.binned)
        # membership remaps member ids to ranks at every epoch: never
        # resume a sibling's per-rank stream — force the resized path
        canonical.meta.pop("shard_rows", None)
        local_fp = _ckpt.combine_fingerprint_parts(
            [_ckpt.data_fingerprint_parts(ts)])
        state = _ckpt.reshard_to_local(
            canonical, rt.rank, list(counts), [], local_fp,
            bag_seed=self.config.bagging_seed)
        self.models = _ckpt.unpack_trees(state.arrays)
        self.import_train_state(state.arrays, state.py)
        if self.objective is not None:
            self.objective.init(md, self.num_data)
        for metric in self.training_metrics:
            metric.init(md, self.num_data)
        if self.learner is not None and hasattr(self.learner, "set_plan"):
            self.learner.set_plan(plan)
        _net.set_delay_scale(self.num_data / max(self._initial_local_rows, 1))
        if self._rebalance is not None:
            self._rebalance["plan"] = plan
            self._rebalance["rank"] = rt.rank
            self._rebalance["ctl"].reset()

    def _membership_join_restore(self) -> None:
        """Mid-run joiner: adopt the canonical handoff the coordinator
        published at admission.  The worker already built its Dataset for
        the admitted slice, so this only restores trees + train state."""
        from ..ckpt import state as _ckpt

        rt = self._membership
        if int(rt.counts[rt.rank]) != int(self.num_data):
            Log.fatal(
                "elastic join: this worker holds %d rows but epoch %d "
                "assigns rank %d %d rows", self.num_data, rt.epoch,
                rt.rank, int(rt.counts[rt.rank]))
        canonical = _ckpt.TrainState.from_bytes(rt.read_handoff())
        own_fp = _ckpt.config_fingerprint(self.config)
        theirs = canonical.meta.get("config_fingerprint")
        if theirs is not None and theirs != own_fp:
            Log.fatal(
                "elastic join: this worker's training config (fingerprint "
                "%s) differs from the fleet's (%s) — a joiner must run the "
                "identical parameters", own_fp, theirs)
        canonical.meta.pop("shard_rows", None)
        local_fp = _ckpt.combine_fingerprint_parts(
            [_ckpt.data_fingerprint_parts(self.train_set)])
        state = _ckpt.reshard_to_local(
            canonical, rt.rank, list(rt.counts), [], local_fp,
            bag_seed=self.config.bagging_seed)
        self.models = _ckpt.unpack_trees(state.arrays)
        self.import_train_state(state.arrays, state.py)
        Log.info(
            "Joined fleet at epoch %d, iteration %d: rank %d/%d, %d "
            "rows, %d trees", rt.epoch, self.iter, rt.rank, rt.nproc,
            self.num_data, len(self.models))

    def export_train_state(self):
        """Checkpoint hook (ckpt/state.py): everything beyond the
        config/dataset/trees that the next iteration reads — score
        caches, the bagging/feature RNG streams, the live bagging mask,
        early-stopping bests.  Subclasses extend via super().

        Returns ``(arrays, py)``: numpy arrays for the npz payload and a
        JSON-serializable dict."""
        arrays = {
            "scores": np.asarray(self.scores, np.float32),
            "select": np.asarray(self.select, np.float32),
        }
        for i, vs in enumerate(self.valid_scores):
            arrays[f"valid_scores_{i}"] = np.asarray(vs, np.float32)
        st = self.bag_rng.get_state()
        arrays["bag_rng_keys"] = np.asarray(st[1], np.uint32)
        py = {
            "iter": int(self.iter),
            "num_init_iteration": int(self.num_init_iteration),
            "boost_from_average": bool(self.boost_from_average_),
            "shrinkage_rate": float(self.shrinkage_rate),
            "bag_rng": [str(st[0]), int(st[2]), int(st[3]), float(st[4])],
            "feature_rng": self.feature_rng.get_state(),
            "need_re_bagging": bool(self.need_re_bagging),
            "best_iter": [list(b) for b in self.best_iter],
            "best_score": [list(b) for b in self.best_score],
            "best_msg": [list(b) for b in self.best_msg],
            "class_need_train": list(self.class_need_train),
            "class_default_output": list(self.class_default_output),
        }
        if self.ptrainer is not None:
            arrays["pt_rowid"] = self.ptrainer.export_perm()
        return arrays, py

    def import_train_state(self, arrays, py) -> None:
        """Inverse of :meth:`export_train_state`; ``self.models`` is
        restored by the caller (ckpt/state.py unpacks the tree arrays)
        before this runs."""
        self.iter = int(py["iter"])
        self.num_init_iteration = int(py["num_init_iteration"])
        self.boost_from_average_ = bool(py["boost_from_average"])
        self.shrinkage_rate = float(py["shrinkage_rate"])
        self.scores = jnp.asarray(np.asarray(arrays["scores"], np.float32))
        self.select = jnp.asarray(np.asarray(arrays["select"], np.float32))
        for i in range(len(self.valid_scores)):
            self.valid_scores[i] = jnp.asarray(
                np.asarray(arrays[f"valid_scores_{i}"], np.float32)
            )
        name, pos, has_gauss, cached = py["bag_rng"]
        self.bag_rng.set_state(
            (str(name), np.asarray(arrays["bag_rng_keys"], np.uint32),
             int(pos), int(has_gauss), float(cached))
        )
        self.feature_rng.set_state(py["feature_rng"])
        self.need_re_bagging = bool(py["need_re_bagging"])
        self.best_iter = [list(map(int, b)) for b in py["best_iter"]]
        self.best_score = [list(map(float, b)) for b in py["best_score"]]
        self.best_msg = [list(map(str, b)) for b in py["best_msg"]]
        self.class_need_train = list(py["class_need_train"])
        self.class_default_output = list(py["class_default_output"])
        if self.learner is not None and hasattr(self.learner, "_qiter"):
            # internally-quantizing learners draw per-tree stochastic-
            # rounding seeds from a tree counter; re-anchor it to the
            # restored model list so a resumed run rounds exactly like
            # one that never died (counter increments before use, one
            # grow per appended model including empty alignment trees)
            self.learner._qiter = len(self.models) - 1
        if self.ptrainer is not None:
            if "pt_rowid" in arrays:
                self.ptrainer.import_perm(np.asarray(arrays["pt_rowid"]))
            # score channels re-sync from the restored original-order
            # scores at the next chunk (exact: channels are zero here)
            self.ptrainer.score_dirty = True

    def refresh_config(self) -> None:
        """Re-derive the config-dependent training state after a parameter
        reset (ResetConfig path used by callback.reset_parameter)."""
        self.hyper = SplitHyper.from_config(self.config)
        if self.ptrainer is not None:
            # the compiled chunk programs bake hyper/config in as closure
            # constants — swap state and drop the program cache
            self.ptrainer.hyper = self.hyper
            self.ptrainer.config = self.config
            self.ptrainer._progs.clear()
            self.ptrainer._traced_progs = None  # hyper is baked in there too
        self.shrinkage_rate = self.config.learning_rate
        self.is_bagging = (
            self.config.bagging_fraction < 1.0 and self.config.bagging_freq > 0
        )
        if not self.is_bagging:
            self.select = jnp.ones(self.num_data, jnp.float32)

    # ------------------------------------------------------------------
    @property
    def num_trees(self) -> int:
        return len(self.models)

    def current_iteration(self) -> int:
        return self.iter + self.num_init_iteration

    def _used_models(self, num_iteration: int = -1):
        num_used = len(self.models)
        if num_iteration > 0:
            ni = num_iteration + (1 if self.boost_from_average_ else 0)
            num_used = min(ni * self.num_tree_per_iteration, num_used)
        return self.models[:num_used]

    def predict_raw_scores(self, data: np.ndarray, num_iteration: int = -1) -> np.ndarray:
        """(num_pred, N) raw scores over raw (unbinned) features, batched
        on device (GBDT::PredictRaw).

        Batches go through the serving layer's shape-bucketed compile
        cache (serve/compilecache.py): N is padded up a power-of-two
        bucket ladder so repeated ad-hoc predicts at varying N reuse a
        small fixed set of compiled programs instead of recompiling per
        shape; padding rows are stripped before returning and never
        change real rows' outputs (row-independent traversal).  Set
        LIGHTGBM_TPU_PREDICT_BUCKETS=0 for the exact-shape legacy path."""
        models = self._used_models(num_iteration)
        k = self.num_tree_per_iteration
        n = data.shape[0]
        if not models:
            return np.zeros((k, n))
        import os

        if os.environ.get("LIGHTGBM_TPU_PREDICT_BUCKETS", "1") == "0":
            return self._predict_raw_scores_unbucketed(data, models, k)
        from ..ops.qpredict import quant_predict_enabled

        linear = any(getattr(t, "is_linear", False) for t in models)
        key = (len(models), k, linear)
        if linear:
            # v3 linear-leaf serving path (serve/compilecache.py): the
            # same bucket ladder, one extra coefficient gather per tree
            if quant_predict_enabled():
                Log.warning(
                    "LIGHTGBM_TPU_QUANT_PREDICT=1 ignored: quantized "
                    "serving does not support linear-leaf models; "
                    "serving exact")
            cached = getattr(self, "_bucketed_predictor", None)
            if cached is None or cached[0] != key:
                from ..serve.compilecache import BucketedLinearRawPredictor

                cached = (key,
                          BucketedLinearRawPredictor.from_models(models, k))
                self._bucketed_predictor = cached
            return cached[1].predict_raw_scores(np.asarray(data, np.float64))
        if quant_predict_enabled():
            # LIGHTGBM_TPU_QUANT_PREDICT=1: int16 rank-quantized
            # traversal (ops/qpredict.py) — route decisions are exact,
            # leaf values narrow to f16 (drift_bound documents the
            # output bound); unset/0 keeps the bit-exact default
            cached = getattr(self, "_quantized_predictor", None)
            if cached is None or cached[0] != key:
                from ..ops.qpredict import quantize_tree_arrays
                from ..serve.artifact import stacked_tree_arrays
                from ..serve.compilecache import BucketedQuantizedPredictor

                q = quantize_tree_arrays(
                    stacked_tree_arrays(models),
                    num_features=int(self.max_feature_idx) + 1)
                cached = (key, BucketedQuantizedPredictor.from_qtree_arrays(q, k))
                self._quantized_predictor = cached
            return cached[1].predict_raw_scores(np.asarray(data, np.float64))
        cached = getattr(self, "_bucketed_predictor", None)
        if cached is None or cached[0] != key:
            from ..serve.compilecache import BucketedRawPredictor

            cached = (key, BucketedRawPredictor.from_models(models, k))
            self._bucketed_predictor = cached
        return cached[1].predict_raw_scores(np.asarray(data, np.float64))

    def _predict_raw_scores_unbucketed(self, data: np.ndarray, models, k) -> np.ndarray:
        n = data.shape[0]
        from ..model.ensemble import split_hi_lo

        hi, lo, lo2 = split_hi_lo(np.asarray(data, np.float64))
        data_hi = jnp.asarray(hi)
        data_lo = jnp.asarray(lo)
        data_lo2 = jnp.asarray(lo2)
        arrays = stack_trees(models)
        linear = "leaf_feat_real" in arrays
        if linear:
            from ..ops.predict import predict_raw_linear
        out = np.zeros((k, n))
        for kk in range(k):
            idx = np.asarray([i for i in range(len(models)) if i % k == kk])
            raw_args = (
                data_hi,
                data_lo,
                data_lo2,
                arrays["split_feature"][idx],
                arrays["threshold_real"][idx],
                arrays["threshold_real_lo"][idx],
                arrays["threshold_real_lo2"][idx],
                arrays["default_value"][idx],
                arrays["default_value_lo"][idx],
                arrays["default_value_lo2"][idx],
                arrays["is_categorical"][idx],
                arrays["left_child"][idx],
                arrays["right_child"][idx],
                arrays["leaf_value"][idx],
            )
            if linear:
                scores = predict_raw_linear(
                    *raw_args,
                    arrays["leaf_feat_real"][idx],
                    arrays["leaf_feat_valid"][idx],
                    arrays["leaf_coeff"][idx],
                    arrays["leaf_const"][idx],
                    arrays["leaf_is_linear"][idx],
                )
            else:
                scores = predict_raw(*raw_args)
            out[kk] = np.asarray(scores, np.float64)
        return out

    def predict(self, data: np.ndarray, num_iteration: int = -1,
                raw_score: bool = False, pred_leaf: bool = False) -> np.ndarray:
        """Booster-level predict: (N,) or (N, K) converted outputs."""
        if pred_leaf:
            models = self._used_models(num_iteration)
            out = np.stack([t.predict_leaf_index(np.asarray(data, np.float64))
                            for t in models], axis=1)
            return out
        if self.config is not None and getattr(self.config, "pred_early_stop", False):
            # margin-based per-row early exit over trees
            # (CreatePredictionEarlyStopInstance, prediction_early_stop.cpp:74-89;
            # Predictor ctor wiring, application/predictor.hpp:24-120)
            from .pred_early_stop import (
                create_prediction_early_stop_instance,
                predict_with_early_stop,
            )

            # binary margin only applies to sigmoid-type objectives; the
            # reference keeps "none" (never stop) otherwise (predictor.hpp)
            if self.num_tree_per_iteration > 1:
                es_type = "multiclass"
            elif self.objective is not None and self.objective.name == "binary":
                es_type = "binary"
            else:
                es_type = "none"
            inst = create_prediction_early_stop_instance(
                es_type,
                int(self.config.pred_early_stop_freq),
                float(self.config.pred_early_stop_margin),
            )
            raw = predict_with_early_stop(
                self, np.asarray(data, np.float64), inst, num_iteration
            ).T  # (K, N)
            if raw_score:
                return raw[0] if raw.shape[0] == 1 else raw.T
            conv = self._convert_output(raw)
            return conv[0] if conv.shape[0] == 1 else conv.T
        raw = self.predict_raw_scores(data, num_iteration)
        if raw_score:
            return raw[0] if raw.shape[0] == 1 else raw.T
        conv = self._convert_output(raw)
        return conv[0] if conv.shape[0] == 1 else conv.T

    def _convert_output(self, raw: np.ndarray) -> np.ndarray:
        """Objective output conversion on (K, N) raw scores.  Like the
        traversal, the conversion's jnp programs are shape-keyed, so it
        runs bucket-padded (serve/compilecache.convert_bucketed) unless
        LIGHTGBM_TPU_PREDICT_BUCKETS=0 pins the exact-shape path."""
        if self.objective is None:
            return raw
        import os

        if os.environ.get("LIGHTGBM_TPU_PREDICT_BUCKETS", "1") == "0":
            return np.asarray(
                self.objective.convert_output(jnp.asarray(raw)), np.float64
            )
        from ..serve.compilecache import convert_bucketed

        return convert_bucketed(raw, self.objective.convert_output)

    # ------------------------------------------------------------------
    def sub_model_name(self) -> str:
        return "tree"

    def save_model_to_string(self, num_iteration: int = -1) -> str:
        """GBDT::SaveModelToString (gbdt.cpp:854-898) — reference format."""
        parts = [self.sub_model_name()]
        parts.append(f"num_class={self.num_class}")
        parts.append(f"num_tree_per_iteration={self.num_tree_per_iteration}")
        parts.append(f"label_index={self.label_idx}")
        parts.append(f"max_feature_idx={self.max_feature_idx}")
        if self.objective is not None:
            parts.append(f"objective={self.objective.to_string()}")
        if self.boost_from_average_:
            parts.append("boost_from_average")
        parts.append("feature_names=" + " ".join(self.feature_names))
        if self.train_set is not None:
            parts.append("feature_infos=" + " ".join(self.train_set.feature_infos()))
        parts.append("")
        for i, tree in enumerate(self._used_models(num_iteration)):
            parts.append(f"Tree={i}")
            parts.append(tree.to_string())
        parts.append("")
        parts.append("feature importances:")
        for name, cnt in self.feature_importance_pairs():
            parts.append(f"{name}={cnt}")
        return "\n".join(parts) + "\n"

    def save_model_to_file(self, filename: str, num_iteration: int = -1) -> None:
        with open(filename, "w") as f:
            f.write(self.save_model_to_string(num_iteration))

    def load_model_from_string(self, model_str: str) -> None:
        """GBDT::LoadModelFromString (gbdt.cpp:912-1008)."""
        self.models = []
        header, _, rest = model_str.partition("Tree=")
        kv = {}
        for line in header.splitlines():
            if "=" in line:
                k, _, v = line.partition("=")
                kv[k.strip()] = v.strip()
        if "num_class" not in kv:
            Log.fatal("Model file doesn't specify the number of classes")
        self.num_class = int(kv["num_class"])
        self.num_tree_per_iteration = int(
            kv.get("num_tree_per_iteration", self.num_class)
        )
        if "label_index" not in kv:
            Log.fatal("Model file doesn't specify the label index")
        self.label_idx = int(kv["label_index"])
        if "max_feature_idx" not in kv:
            Log.fatal("Model file doesn't specify max_feature_idx")
        self.max_feature_idx = int(kv["max_feature_idx"])
        self.boost_from_average_ = "boost_from_average" in header.splitlines()
        self.objective_name_loaded = kv.get("objective", "")
        self.feature_names = kv.get("feature_names", "").split()
        # tree blocks
        if rest:
            blocks = ("Tree=" + rest).split("Tree=")
            for blk in blocks:
                blk = blk.strip()
                if not blk or blk.startswith("feature importances"):
                    continue
                body = blk.partition("\n")[2]
                body = body.split("\nfeature importances:")[0]
                self.models.append(Tree.from_string(body))
        self.num_init_iteration = len(self.models) // max(self.num_tree_per_iteration, 1)
        self.iter = 0

    def feature_importance_pairs(self):
        """Split-count importance (GBDT::FeatureImportance,
        gbdt.cpp:1010-1034), sorted descending, nonzero only."""
        imp = np.zeros(self.max_feature_idx + 1, np.int64)
        for tree in self.models:
            m = tree.num_leaves - 1
            for s in range(m):
                if tree.split_gain[s] > 0:
                    imp[tree.split_feature[s]] += 1
        names = self.feature_names or [
            f"Column_{i}" for i in range(self.max_feature_idx + 1)
        ]
        pairs = [(names[i], int(imp[i])) for i in range(len(imp)) if imp[i] > 0]
        pairs.sort(key=lambda p: -p[1])
        return pairs

    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        imp = np.zeros(self.max_feature_idx + 1, np.float64)
        for tree in self.models:
            m = tree.num_leaves - 1
            for s in range(m):
                if tree.split_gain[s] > 0:
                    if importance_type == "gain":
                        imp[tree.split_feature[s]] += tree.split_gain[s]
                    else:
                        imp[tree.split_feature[s]] += 1
        return imp
