"""Boosting drivers — counterpart of src/boosting/ (factory
boosting.cpp:29-73).
"""

from .gbdt import GBDT
from .dart import DART
from .goss import GOSS


def create_boosting(boosting_type: str, input_model: str = ""):
    """Boosting::CreateBoosting (src/boosting/boosting.cpp:29-73)."""
    from ..utils.log import Log

    bt = boosting_type.lower()
    if bt == "gbdt":
        cls = GBDT
    elif bt == "dart":
        cls = DART
    elif bt == "goss":
        cls = GOSS
    else:
        Log.fatal("Unknown boosting type %s", boosting_type)
    return cls()


__all__ = ["GBDT", "DART", "GOSS", "create_boosting"]
