"""Fused partitioned trainers — boosting iterations as ONE device program.

Drives ops/pgrow.py.  The motivation is dispatch latency: a host round
trip to the (possibly tunneled) TPU costs up to ~80 ms, so the
reference's per-iteration host loop (GBDT::TrainOneIter,
gbdt.cpp:381-495) becomes a ``lax.fori_loop`` over iterations INSIDE one
jitted program.  Per iteration:

  K == 1 (binary/regression, incl. GOSS):
    update_and_root_hist kernel (score += PREVIOUS tree's pending delta;
      fresh gradients from the score/label channels; bagging select; the
      root histogram of the fresh values)           [in-place Pallas]
    -> feature sampling -> grow_tree_partitioned    [split_stream kernels]
    -> the tree's score delta is carried PENDING to the next iteration's
       update (the row layout doesn't change in between) and settled by
       one extra pass at chunk end.
    GOSS prepends a gradient-only pass + device top_k/Bernoulli sampling
    with the (n-top_k)/other_k up-weighting folded into g/h (goss.hpp).

  K > 1 (multiclass): ALL K gradient planes + K root histograms come
    from ONE streaming pass over the same score snapshot
    (update_multi_and_hists — GBDT::Boosting computes every class's
    gradients once per iteration, gbdt.cpp:692-700); each class's tree
    then reads its own g/h channel pair, and its leaf deltas land on its
    score row IMMEDIATELY after the tree via the score_add streamer,
    while the delta's partition layout is still current.  (Deltas must
    never stay pending across another class's tree: each tree physically
    re-permutes the rows.)

Scores, labels and weights travel as bitcast channels of the packed
matrix, so nothing is ever gathered back to original row order during
training; the original-order score vectors are rebuilt ONCE per chunk
(one scatter per class through the rowid channel) for metrics/eval.

Why every channel write goes through a Pallas kernel: ANY XLA-level
write to the 64 MB matrix — even a one-element ``.at[].set`` on a
donated loop carry — triggers a pathological whole-array copy
(~50-180 ms measured) on this backend; only ``input_output_aliases``
mutate truly in place.

Row-order-free semantics this relies on: histograms, leaf statistics and
elementwise objectives are permutation-invariant.  Ranking objectives
(query-grouped) are not — they keep the mask-based grower (ops/grow.py).

``ShardedPartitionedTrainer`` runs the same fused loop per shard under
``shard_map`` with per-split histogram psums — the data-parallel learner
(data_parallel_tree_learner.cpp) on the fast kernels.

Deliberate parity divergences from the reference (documented):
- bagging draws a per-row Bernoulli(bagging_fraction) mask with JAX
  threefry instead of the host RNG's exact-count subset
  (gbdt.cpp:275-334); same distribution, different stream.
- feature_fraction samples exactly ceil(frac*F) features via device
  top_k on uniform keys instead of utils/random.py's host sampler.
- GOSS's rest-sample is Bernoulli(other_k/rest) rather than an exact
  other_k-subset; the top set is exact top_k like the reference.
"""

from __future__ import annotations

import functools
import os
import types

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import JitWatch, fence, tracer
from ..ops.pgrow import (
    BundleMeta,
    PGrowParams,
    _expand_bundle_hist,
    _meta_table,
    grow_tree_partitioned,
    levelgrow_env_params,
    segment_values,
)
from ..ops.pkernels import (
    PLayout,
    pack_matrix_device,
    score_add,
    split_stream,
    update_and_root_hist,
    update_multi_and_hists,
)
from ..ops.split import (
    NEG_INF,
    FeatureMeta,
    SplitHyper,
    best_split_per_feature,
    finalize_split,
)
from ..utils.log import Log


def _f2i(x):
    return jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.int32)


def _i2f(x):
    return jax.lax.bitcast_convert_type(x, jnp.float32)


class PartitionedTrainer:
    """Owns the packed matrix + fused train-chunk programs for one GBDT."""

    # phase-separated traced mode (train_chunk_traced) — serial K == 1
    # only; the sharded trainer keeps the fused program (a defused
    # per-split host loop over a mesh would serialize the collectives)
    supports_traced = True

    def __init__(self, train_set, config, objective, meta: FeatureMeta, hyper: SplitHyper,
                 bins_dev=None):
        binned = train_set.binned
        n, f = binned.shape
        assert binned.dtype == np.uint8
        md = train_set.metadata
        self.has_weights = md.weights is not None
        # K > 1: multiclass — K score channels, K trees per iteration
        # (per-class tree loop, gbdt.cpp:445-480)
        self.K = int(getattr(objective, "num_tree_per_iteration", 1))
        # EFB: stream the bundled (N, G) matrix instead of (N, F) when the
        # dataset found exclusive bundles (io/bundle.py); split search and
        # the model stay in real-feature space via BundleMeta
        bundle = getattr(train_set, "bundle", None)
        self.bmeta = None
        num_cols, num_bins_hist = 0, 0
        if bundle is not None and train_set.bundled is not None:
            matrix = train_set.bundled
            num_cols = bundle.num_cols
            num_bins_hist = int(bundle.max_col_bin)
            self.bmeta = _build_bundle_meta(bundle, train_set, int(train_set.max_num_bin))
            bins_dev = None  # the unbundled device matrix is not what we pack
            max_col_bin = num_bins_hist
        else:
            matrix = binned
            max_col_bin = int(train_set.max_num_bin)
        # 4-bit packed words when every column fits 16 bins
        # (dense_nbits_bin.hpp:37): half the resident bin bytes/traffic
        # (LIGHTGBM_TPU_FORCE_BITS=8 disables, e.g. for A/B measurement)
        force_bits = os.environ.get("LIGHTGBM_TPU_FORCE_BITS", "")
        bits = 4 if max_col_bin <= 16 else 8
        if force_bits in ("4", "8"):
            bits = int(force_bits)
            if bits == 4 and max_col_bin > 16:
                bits = 8  # cannot pack >16 bins in 4 bits
        self.layout = PLayout(matrix.shape[1], num_score=self.K, with_weight=True, bits=bits)
        if bins_dev is None:
            bins_dev = jnp.asarray(np.asarray(matrix))
        self.p = pack_matrix_device(bins_dev, self.layout, label=md.label,
                                    weight=md.weights if self.has_weights else None)
        self.num_rows = n
        self.meta = meta
        self.hyper = hyper
        self.objective = objective
        self.config = config
        self.params = PGrowParams(
            num_leaves=max(2, int(config.num_leaves)),
            num_bins=int(train_set.max_num_bin),
            num_features=f,
            num_rows=n,
            max_depth=int(config.max_depth),
            use_missing=bool(config.use_missing),
            has_categorical=bool(np.any(np.asarray(meta.is_categorical))),
            num_cols=num_cols,
            num_bins_hist=num_bins_hist,
            bits=bits,
            **levelgrow_env_params(),
        )
        self.interpret = jax.default_backend() != "tpu"
        # start dirty: init_score / init_model may mutate GBDT.scores after
        # construction; the first chunk syncs the channel (identity-order
        # gather, cheap)
        self.score_dirty = True
        self._progs = {}
        self._apply_prog = None
        self._last_tree = None  # (N,) scaled leaf-delta vector, for rollback
        self._base_key = jax.random.PRNGKey(
            (int(config.bagging_seed) << 1) ^ int(config.feature_fraction_seed)
        )

    # -- score channel maintenance ------------------------------------
    def _grad_fn(self, score, label, weight):
        obj = self.objective
        return obj.gradients_rowwise(score, label, weight if self.has_weights else None)

    def _grad_all_fn(self, scores, label, weight):
        """All K gradient planes at once from the score snapshot."""
        obj = self.objective
        return obj.gradients_rowwise_all(
            scores, label, weight if self.has_weights else None
        )

    def _apply_delta(self, delta, k: int = 0) -> None:
        """score channel k += delta (N,) — one in-place Pallas pass.
        Gradient channels refresh at the next iteration's update pass, so
        the cheap score-only streamer suffices here."""
        if self._apply_prog is None:
            self._apply_prog = {}
        if k not in self._apply_prog:
            lay = self.layout
            interp = self.interpret

            @functools.partial(jax.jit, donate_argnums=(0,))
            def prog(p, delta):
                return score_add(p, lay, delta, k, num_rows=self.num_rows,
                                 interpret=interp)

            self._apply_prog[k] = prog
        self.p = self._apply_prog[k](self.p, jnp.asarray(delta, jnp.float32))

    def add_score_constant(self, c: float) -> None:
        self._apply_delta(jnp.full((self.num_rows,), np.float32(c)))

    def sync_scores_from(self, scores_orig) -> None:
        """Bring the score channels to an original-order (N,) / (K, N)
        target (rare — init_model / external updates)."""
        lay = self.layout
        rowid = self.p[lay.ROWID, : self.num_rows]
        target = np.atleast_2d(np.asarray(scores_orig, np.float32))
        for k in range(self.K):
            cur = _i2f(self.p[lay.SCORE + k, : self.num_rows])
            tk = jnp.asarray(target[k])[rowid]
            self._apply_delta(tk - cur, k=k)
        self.score_dirty = False

    def scores_original_order(self):
        """(N,) for K == 1, else (K, N)."""
        lay = self.layout
        rowid = self.p[lay.ROWID, : self.num_rows]
        outs = []
        for k in range(self.K):
            sc = _i2f(self.p[lay.SCORE + k, : self.num_rows])
            outs.append(jnp.zeros((self.num_rows,), jnp.float32).at[rowid].set(sc))
        return outs[0] if self.K == 1 else jnp.stack(outs)

    def rollback_last(self) -> bool:
        """Undo the most recent tree's score contribution (the segment
        layout still matches it — GBDT::RollbackOneIter).  Multiclass
        chunks track only the last class's delta, so they resync via
        score_dirty instead."""
        if self._last_tree is None or self.K != 1:
            return False
        self._apply_delta(-self._last_tree)
        self._last_tree = None
        return True

    # -- checkpoint support -------------------------------------------
    def export_perm(self):
        """The physical row permutation (ROWID channel).  Histogram
        accumulation order follows the partition layout each tree left
        behind, so bit-identical resume must restore it — rebuilding an
        identity layout would change float summation order."""
        lay = self.layout
        return np.asarray(self.p[lay.ROWID, : self.num_rows], np.int32)

    def import_perm(self, rowid) -> None:
        """Re-derive the packed matrix in the checkpointed physical row
        order: column ``j`` must hold original row ``rowid[j]``.  The
        matrix here is still identity-packed (fresh ``__init__``), so a
        single column gather permutes bins/label/weight/rowid together;
        score channels stay zero and re-sync exactly from the restored
        original-order scores at the next chunk."""
        rowid = np.asarray(rowid, np.int32)
        if rowid.shape != (self.num_rows,):
            from ..utils.log import Log

            # topology changed since the save (elastic resume): the
            # saved layout is meaningless for this partition — keep the
            # identity packing (a valid continuation; score channels
            # re-sync from the restored scores) instead of refusing
            Log.warning(
                "checkpoint row permutation has shape %s, expected (%d,); "
                "keeping identity layout (topology changed since save)",
                rowid.shape, self.num_rows,
            )
            self._last_tree = None
            self.score_dirty = True
            return
        head = jnp.take(self.p[:, : self.num_rows], jnp.asarray(rowid), axis=1)
        self.p = jnp.concatenate([head, self.p[:, self.num_rows:]], axis=1)
        self._last_tree = None
        self.score_dirty = True

    # -- the fused chunk program --------------------------------------
    def _build_program(self, T: int, bag_on: bool, bag_freq: int, used_features: int):
        lay = self.layout
        n = self.num_rows
        L = self.params.num_leaves
        F = self.params.num_features
        K = self.K
        grad_fn = self._grad_fn
        grad_all_fn = self._grad_all_fn
        params = self.params
        meta = self.meta
        hyper = self.hyper
        bmeta = self.bmeta
        interpret = self.interpret
        bag_frac = float(self.config.bagging_fraction)
        G = params.num_cols or F
        BH = params.num_bins_hist or params.num_bins
        cfg = self.config
        goss_on = (getattr(cfg, "boosting", "gbdt") == "goss") and K == 1
        if goss_on:
            top_cnt = max(1, int(n * float(cfg.top_rate)))
            other_cnt = max(1, int(n * float(cfg.other_rate)))
            goss_mult = float((n - top_cnt) / other_cnt)
            goss_prob = float(other_cnt / max(n - top_cnt, 1))
            goss_warm = int(1.0 / float(cfg.learning_rate))

        @functools.partial(jax.jit, donate_argnums=(0,))
        def prog(p, lr, key, iter0, t_run):
            def one_iter(t, carry):
                # once an iteration produced an empty tree, training has
                # logically stopped: later in-program iterations must be
                # FULL no-ops — growing a throwaway tree would repartition
                # rows and invalidate last_kept's physical layout (which
                # rollback_last applies positionally)
                return jax.lax.cond(carry[2], lambda c: c,
                                    functools.partial(_live_iter, t), carry)

            def _live_iter(t, carry):
                (p, recs, stopped, delta, last_kept) = carry
                it = iter0 + t
                # ---- canonical row order at every tree start.  The
                # partition layout a tree leaves behind depends on HOW it
                # was grown: the level grower speculatively partitions
                # whole candidate levels (including splits best-first
                # acceptance never takes), so LEVELGROW=1 and =0 leave
                # different physical row orders even when they build the
                # identical tree — and the NEXT tree's histogram float
                # summation order then differs (the 1-ULP model
                # divergence pinned by tests/test_audit.py).  One gather
                # back to original row order per tree makes every tree's
                # numerics independent of the previous tree's partition
                # history (it also pins the positional bagging/GOSS draws
                # below to original rows).  The positional carries
                # (pending delta, rollback snapshot) are re-mapped
                # through the SAME rowid so they stay aligned.
                rowid = p[lay.ROWID, :n]
                delta = jnp.zeros((n,), jnp.float32).at[rowid].set(delta)
                last_kept = jnp.zeros((n,), jnp.float32).at[rowid].set(last_kept)
                inv = jnp.zeros((n,), jnp.int32).at[rowid].set(
                    jnp.arange(n, dtype=jnp.int32))
                p = jax.lax.dynamic_update_slice(
                    p, jnp.take(p[:, :n], inv, axis=1), (0, 0))
                # disjoint purpose-tagged key streams: fold a purpose
                # constant (0=bagging, 1=feature, 2=GOSS) before the
                # iteration number so no two draws share a subkey
                if bag_on:
                    bkey = jax.random.fold_in(
                        jax.random.fold_in(key, 0), it // bag_freq
                    )
                    sel = jax.random.bernoulli(bkey, bag_frac, (n,)).astype(jnp.float32)
                else:
                    sel = None
                if used_features < F:
                    fkey = jax.random.fold_in(jax.random.fold_in(key, 1), it)
                    u = jax.random.uniform(fkey, (F,))
                    _, idx = jax.lax.top_k(u, used_features)
                    fmask = jnp.zeros((F,), jnp.float32).at[idx].set(1.0)
                else:
                    fmask = jnp.ones((F,), jnp.float32)

                ns_t = recs["num_splits"][t]
                raw_t = recs["raw"][t]
                if K == 1:
                    if goss_on:
                        # GOSS (goss.hpp:126-198): settle the pending
                        # delta + fresh gradients first (histogram-FREE
                        # pass — the F*B one-hot/matmul accumulation used
                        # to run here only to be discarded), score |g*h|
                        # on the fresh values, keep exactly top_cnt rows
                        # + a Bernoulli sample of the rest up-weighted
                        # into g/h, then the real pass computes the root
                        # histogram of the selected/scaled gradients.
                        p, _ = update_and_root_hist(
                            p, lay, grad_fn, delta=delta,
                            num_rows=n, num_features=G, num_bins=BH,
                            bits=params.bits, with_hist=False,
                            interpret=interpret,
                        )
                        gv = _i2f(p[lay.G, :n])
                        hv = _i2f(p[lay.H, :n])
                        gscore = jnp.abs(gv * hv)
                        _, top_idx = jax.lax.top_k(gscore, top_cnt)
                        is_top = jnp.zeros((n,), bool).at[top_idx].set(True)
                        gkey = jax.random.fold_in(jax.random.fold_in(key, 2), it)
                        sampled = (~is_top) & (
                            jax.random.uniform(gkey, (n,)) < goss_prob
                        )
                        warm = it < goss_warm
                        selv = jnp.where(
                            warm, 1.0, (is_top | sampled).astype(jnp.float32)
                        )
                        mulv = jnp.where(warm | (~sampled), 1.0, goss_mult)
                        p, root_hist = update_and_root_hist(
                            p, lay, grad_fn, sel=selv, mul=mulv,
                            num_rows=n, num_features=G, num_bins=BH,
                            bits=params.bits, interpret=interpret,
                        )
                        delta = jnp.zeros((n,), jnp.float32)
                    else:
                        # in-place channel refresh (score += previous
                        # tree's delta, new gradients, bagging select)
                        # FUSED with the root histogram of the fresh
                        # values — one pass.  The delta is PENDING from
                        # the previous iteration: the row layout did not
                        # change in between, so it applies against the
                        # current partition order.
                        p, root_hist = update_and_root_hist(
                            p, lay, grad_fn, delta=delta, sel=sel,
                            num_rows=n, num_features=G, num_bins=BH,
                            bits=params.bits, interpret=interpret,
                        )
                    tree, p = grow_tree_partitioned(
                        p, fmask, meta, hyper, params, bmeta=bmeta,
                        interpret=interpret, root_hist=root_hist,
                    )
                    # score delta: +lr * leaf_value over each segment,
                    # clamped like Tree.shrinkage (tree.h:13
                    # kMaxTreeOutput) so training-time scores match the
                    # stored model.  Once an iteration produces an empty
                    # tree, training has logically stopped and later
                    # in-program iterations must not touch the scores.
                    keep = ((tree.num_splits > 0) & (~stopped)).astype(jnp.float32)
                    lval = jnp.clip(lr * tree.leaf_value, -100.0, 100.0)
                    delta = segment_values(tree, n, keep * lval)
                    # rollback needs the last KEPT tree's delta: an empty
                    # tree zeroes the pending carry but must not clobber
                    # what rollback_last would subtract
                    last_kept = jnp.where(keep > 0, delta, last_kept)
                    any_split = tree.num_splits > 0
                    ns_t = ns_t.at[0].set(tree.num_splits)
                    raw_t = raw_t.at[0].set(tree.recs_raw)
                else:
                    # K trees per iteration (per-class loop,
                    # gbdt.cpp:445-480): ALL K gradient planes + K root
                    # histograms from the same score snapshot in ONE
                    # pass; each tree's delta lands on its score row
                    # IMMEDIATELY after the tree (while its partition
                    # layout is still current), which the precomputed
                    # gradient planes make snapshot-safe.
                    p, hists = update_multi_and_hists(
                        p, lay, grad_all_fn, sel=sel, num_rows=n,
                        num_features=G, num_bins=BH, bits=params.bits,
                        interpret=interpret,
                    )
                    any_split = jnp.array(False)
                    for k in range(K):
                        tree, p = grow_tree_partitioned(
                            p, fmask, meta, hyper, params, bmeta=bmeta,
                            interpret=interpret, root_hist=hists[k],
                            rows=lay.class_rows(k),
                        )
                        keep = ((tree.num_splits > 0) & (~stopped)).astype(jnp.float32)
                        lval = jnp.clip(lr * tree.leaf_value, -100.0, 100.0)
                        dk = segment_values(tree, n, keep * lval)
                        p = score_add(p, lay, dk, k, num_rows=n,
                                      interpret=interpret)
                        any_split = any_split | (tree.num_splits > 0)
                        ns_t = ns_t.at[k].set(tree.num_splits)
                        raw_t = raw_t.at[k].set(tree.recs_raw)
                    delta = delta  # unused for K > 1 (scores always settled)

                # ONE packed record buffer: per-op dispatch inside the
                # loop costs ~1-2 us, so ten separate stores would be a
                # measured ~10 ms/iter tax at 64 iters
                recs = {
                    "num_splits": recs["num_splits"].at[t].set(ns_t),
                    "raw": recs["raw"].at[t].set(raw_t),
                }
                new_stopped = stopped | (~any_split)
                return (p, recs, new_stopped, delta, last_kept)

            m = L - 1
            recs0 = {
                "num_splits": jnp.zeros((T, K), jnp.int32),
                "raw": jnp.zeros((T, K, m, 12)),
            }
            carry0 = (p, recs0, jnp.array(False), jnp.zeros((n,), jnp.float32),
                      jnp.zeros((n,), jnp.float32))
            p, recs, _, last_delta, last_kept = jax.lax.fori_loop(
                0, jnp.minimum(t_run, T), one_iter, carry0
            )
            if K == 1:
                # settle the last tree's delta into the channel so the
                # score channel is consistent at chunk boundaries (the
                # in-loop update applies tree t-1's delta at iteration
                # t).  Score-only band stream: the old settle ran a full
                # update_and_root_hist — a whole-matrix pass plus an
                # F*B histogram that was discarded — purely to add the
                # delta.  The g/h channels stay stale until the next
                # chunk's first update pass recomputes them from the
                # settled scores (nothing reads them in between; the
                # checkpoint exports scores + perm, never g/h).
                p = score_add(p, lay, last_delta, 0, num_rows=n,
                              interpret=interpret)
            # original-order scores for eval (K scatters per chunk)
            rowid = p[lay.ROWID, :n]
            outs = []
            for k in range(K):
                sc = _i2f(p[lay.SCORE + k, :n])
                outs.append(jnp.zeros((n,), jnp.float32).at[rowid].set(sc))
            scores_orig = outs[0] if K == 1 else jnp.stack(outs)
            return p, recs, scores_orig, last_kept

        return prog

    # record buffers are allocated at CHUNK_ALLOC granularity so a short
    # run (warmup) and a long run reuse one compiled program (the loop
    # bound is traced)
    CHUNK_ALLOC = 64

    def train_chunk(self, T: int, lr: float, iter0: int):
        """Run T fused boosting iterations (T <= CHUNK_ALLOC per call is
        one program invocation; longer runs loop).  Returns (records dict
        of numpy arrays, scores_orig (N,) device array, n_done)."""
        cfg = self.config
        bag_on = cfg.bagging_fraction < 1.0 and cfg.bagging_freq > 0
        bag_freq = max(1, int(cfg.bagging_freq))
        used_features = self.params.num_features
        if cfg.feature_fraction < 1.0:
            used_features = max(1, int(self.params.num_features * cfg.feature_fraction))
        # fixed allocation: every chunk size shares ONE compiled program
        # (the loop bound is traced; record buffers are CHUNK_ALLOC-sized)
        alloc = self.CHUNK_ALLOC
        pkey = (alloc, bag_on, bag_freq, used_features)
        if pkey not in self._progs:
            # JitWatch: compile accounting + unexpected-retrace flagging
            # on the hot entry point (obs/compilewatch.py)
            self._progs[pkey] = JitWatch(
                self._build_program(alloc, bag_on, bag_freq, used_features),
                name=f"ptrainer.chunk(bag={int(bag_on)},ff={used_features})",
                phase="chunk_program",
            )
        prog = self._progs[pkey]
        recs_np = None
        n_done = 0
        remaining = T
        scores_orig = None
        if T <= 0:
            return {}, self.scores_original_order(), 0
        while remaining > 0:
            step = min(remaining, alloc)
            with tracer.span("chunk_program", iters=step):
                self.p, recs, scores_orig, last_kept = prog(
                    self.p, jnp.float32(lr), self._base_key,
                    jnp.int32(iter0 + n_done), jnp.int32(step),
                )
            with tracer.span("records_fetch"):
                part = jax.device_get(recs)
            ns = part["num_splits"][:step]  # (step, K)
            stop = np.nonzero(np.all(ns == 0, axis=1))[0]
            done_here = int(stop[0]) if stop.size else step
            if done_here > 0:
                # last KEPT tree's settled delta (empty trees keep the
                # previous chunk's value so rollback stays consistent)
                self._last_tree = last_kept
            part = {k: v[:done_here] for k, v in part.items()}
            recs_np = part if recs_np is None else {
                k: np.concatenate([recs_np[k], part[k]]) for k in part
            }
            n_done += done_here
            remaining -= step
            if done_here < step:
                break
        return recs_np, scores_orig, n_done

    # -- phase-separated traced mode -----------------------------------
    def _traced_progs_build(self):
        """Small single-phase programs for the traced (defused) mode:
        update+root-hist, partition (split_stream), split search, score
        apply.  All dynamic inputs are traced scalars so each program
        compiles exactly once."""
        lay = self.layout
        n = self.num_rows
        params = self.params
        F = params.num_features
        B = params.num_bins
        G = params.num_cols or F
        BH = params.num_bins_hist or B
        L = params.num_leaves
        meta = self.meta
        hyper = self.hyper
        bmeta = self.bmeta
        interp = self.interpret
        grad_fn = self._grad_fn

        @functools.partial(jax.jit, donate_argnums=(0,))
        def upd(p, delta, sel):
            return update_and_root_hist(
                p, lay, grad_fn, delta=delta, sel=sel, num_rows=n,
                num_features=G, num_bins=BH, bits=params.bits,
                interpret=interp,
            )

        @functools.partial(jax.jit, donate_argnums=(0,))
        def part(p, start, cnt, word, shift, zb, dbz, thr, cat,
                 off_lo, off_hi, bias):
            return split_stream(
                p, start, cnt, word, shift, zb, dbz, thr, cat,
                off_lo=off_lo, off_hi=off_hi, bias=bias, num_features=G,
                num_bins=BH, bits=params.bits, rows=lay.rows,
                interpret=interp,
            )

        @jax.jit
        def find(hist2, sums2, fmask, depth_ok):
            # the fused program's find2, lifted out as its own dispatch
            if bmeta is not None:
                hist2 = jax.vmap(
                    lambda hh, ss: _expand_bundle_hist(hh, ss, bmeta, F, B)
                )(hist2, sums2)

            def one(hist, s):
                gain_f, thr_f, dbz_f, left_f = best_split_per_feature(
                    hist, s[0], s[1], s[2], meta, hyper, fmask,
                    params.use_missing,
                    has_categorical=params.has_categorical,
                )
                return finalize_split(
                    gain_f, thr_f, dbz_f, left_f, s[0], s[1], s[2], hyper
                )

            res = jax.vmap(one)(hist2, sums2)
            return res._replace(gain=jnp.where(depth_ok, res.gain, NEG_INF))

        @functools.partial(jax.jit, donate_argnums=(0,))
        def score(p, starts, cnts, num_splits, values):
            # segment_values inlined over the explicit (starts, cnts) —
            # the same EXACT integer-rank gather as ops.pgrow
            # .segment_values (a float range-add cumsum leaves
            # position-dependent 1-ULP residue inside segments; see that
            # docstring), so traced scores match the fused path's bit
            # for bit
            active = jnp.arange(L) <= num_splits
            v = jnp.where(active, values, 0.0)
            s = jnp.where(active & (cnts > 0), starts, n)
            marks = jnp.zeros((n + 1,), jnp.int32).at[s].add(1)
            rank = jnp.cumsum(marks)[:n] - 1
            order = jnp.argsort(s)
            delta = jnp.take(v, jnp.take(order, jnp.clip(rank, 0, L - 1)))
            return score_add(p, lay, delta, 0, num_rows=n,
                             interpret=interp), delta

        @functools.partial(jax.jit, donate_argnums=(0,))
        def canon(p, lt):
            # canonical row order at tree start — the traced twin of the
            # fused _live_iter's gather: makes every tree's numerics (and
            # the positional bagging draw) independent of the previous
            # tree's partition layout, and keeps the positional rollback
            # snapshot aligned through the reorder
            rowid = p[lay.ROWID, :n]
            lt = jnp.zeros((n,), jnp.float32).at[rowid].set(lt)
            inv = jnp.zeros((n,), jnp.int32).at[rowid].set(
                jnp.arange(n, dtype=jnp.int32))
            p = jax.lax.dynamic_update_slice(
                p, jnp.take(p[:, :n], inv, axis=1), (0, 0))
            return p, lt

        # phase= maps each program onto the measured span it runs under
        # (obs/costmodel.py joins HLO rooflines against those spans);
        # canon has no span of its own
        return {
            "update": JitWatch(upd, name="ptrainer.traced.update",
                               phase="histogram"),
            "partition": JitWatch(part, name="ptrainer.traced.partition",
                                  phase="partition"),
            "find": JitWatch(find, name="ptrainer.traced.find",
                             phase="split"),
            "score": JitWatch(score, name="ptrainer.traced.score",
                              phase="score_update"),
            "canon": JitWatch(canon, name="ptrainer.traced.canon"),
        }

    def train_chunk_traced(self, T: int, lr: float, iter0: int):
        """Phase-separated twin of ``train_chunk`` for run tracing: each
        boosting iteration executes as separate fenced device programs so
        the trace carries REAL per-phase timings —

          histogram    the streaming update+root-histogram pass
          partition    split_stream passes (in-place partition; note the
                       children histograms are accumulated IN this pass —
                       this port's core fusion — so the reference's
                       per-leaf "hist" time appears here)
          split        the vmapped split-search math over candidate
                       histograms
          score_update the leaf-delta application

        Same tree semantics as the fused classic path — bit-identical to
        a LIGHTGBM_TPU_LEVELGROW=0 fused chunk (the per-split selection
        below is the same bookkeeping ``grow_tree_partitioned`` replays).
        The canonical-row-order gather at each tree start (the fused
        path's tree-start canonicalization, mirrored here) pins the
        positional Bernoulli bag mask to original rows, so bagged runs
        match BOTH fused modes bit for bit as well.
        Per-split dispatch overhead is the documented price of
        attribution, which is why this mode is opt-in
        (LIGHTGBM_TPU_TRACE_PHASES).  K == 1, non-GOSS only — callers
        gate on ``supports_traced``/K."""
        assert self.K == 1, "traced mode is single-class only"
        cfg = self.config
        lay = self.layout
        n = self.num_rows
        params = self.params
        L = params.num_leaves
        F = params.num_features
        per = 32 // params.bits
        bag_on = cfg.bagging_fraction < 1.0 and cfg.bagging_freq > 0
        bag_freq = max(1, int(cfg.bagging_freq))
        bag_frac = float(cfg.bagging_fraction)
        used_features = F
        if cfg.feature_fraction < 1.0:
            used_features = max(1, int(F * cfg.feature_fraction))
        if not hasattr(self, "_traced_progs") or self._traced_progs is None:
            self._traced_progs = self._traced_progs_build()
        progs = self._traced_progs
        mtab = np.asarray(_meta_table(self.meta, self.bmeta, F, params.bits))
        l1 = float(self.hyper.lambda_l1)
        l2 = float(self.hyper.lambda_l2)
        max_depth = int(params.max_depth)
        key = self._base_key
        m = L - 1
        all_ns = np.zeros((T, 1), np.int32)
        all_raw = np.zeros((T, 1, m, 12), np.float32)
        n_done = 0
        zeros_n = jnp.zeros((n,), jnp.float32)
        ones_n = jnp.ones((n,), jnp.float32)

        def _leaf_out(g, h):
            reg = max(abs(g) - l1, 0.0)
            return -np.sign(g) * reg / (h + l2) if (h + l2) != 0 else 0.0

        for t in range(T):
            it = iter0 + t
            with tracer.iteration(it, mode="traced") as irec:
                # canonical row order at tree start (see the fused
                # _live_iter): partition-history-independent numerics +
                # original-row-pinned bagging draws; the rollback
                # snapshot rides through the same reorder
                self.p, lt = progs["canon"](
                    self.p,
                    self._last_tree if self._last_tree is not None
                    else zeros_n,
                )
                if self._last_tree is not None:
                    self._last_tree = lt
                if bag_on:
                    bkey = jax.random.fold_in(
                        jax.random.fold_in(key, 0), it // bag_freq
                    )
                    sel = jax.random.bernoulli(
                        bkey, bag_frac, (n,)
                    ).astype(jnp.float32)
                else:
                    sel = ones_n
                if used_features < F:
                    fkey = jax.random.fold_in(jax.random.fold_in(key, 1), it)
                    u = jax.random.uniform(fkey, (F,))
                    _, fidx = jax.lax.top_k(u, used_features)
                    fmask = jnp.zeros((F,), jnp.float32).at[fidx].set(1.0)
                else:
                    fmask = jnp.ones((F,), jnp.float32)

                with tracer.span("histogram"):
                    self.p, root_hist = progs["update"](self.p, zeros_n, sel)
                    fence(root_hist)
                root_sums = np.asarray(jnp.sum(root_hist[0], axis=0))

                # host-side split bookkeeping (the fused _PState tables)
                seg = np.zeros((L, 2), np.int64)
                seg[0] = (0, n)
                bs = np.full((L, 8), -np.inf, np.float32)
                leaf = np.zeros((L, 8), np.float32)
                leaf[0, 0:3] = root_sums
                leaf[0, 3] = _leaf_out(root_sums[0], root_sums[1])
                leaf[0, 4] = root_sums[2]
                recs = np.zeros((m, 12), np.float32)

                with tracer.span("split"):
                    rr = jax.device_get(progs["find"](
                        jnp.stack([root_hist, root_hist]),
                        jnp.stack([jnp.asarray(root_sums)] * 2),
                        fmask, jnp.array(True),
                    ))
                bs[0] = (rr.gain[0], rr.feature[0], rr.threshold_bin[0],
                         rr.default_bin_for_zero[0], rr.left_sum_g[0],
                         rr.left_sum_h[0], rr.left_cnt[0], 0.0)

                ns = 0
                while ns < L - 1:
                    bl = int(np.argmax(bs[:, 0]))
                    gain = float(bs[bl, 0])
                    if not gain > 0.0:
                        break
                    feat = int(bs[bl, 1])
                    thr = int(bs[bl, 2])
                    dbz = int(bs[bl, 3])
                    left = bs[bl, 4:7].astype(np.float64)
                    totals = leaf[bl, 0:3].astype(np.float64)
                    pval = float(leaf[bl, 3])
                    child_depth = leaf[bl, 5] + 1.0
                    start, cnt = int(seg[bl, 0]), int(seg[bl, 1])
                    mrow = mtab[feat]
                    col = int(mrow[2])
                    with tracer.span("partition"):
                        self.p, nl, lhist, rhist = progs["partition"](
                            self.p, jnp.int32(start), jnp.int32(cnt),
                            jnp.int32(col // per),
                            jnp.int32((col % per) * params.bits),
                            jnp.int32(mrow[0]), jnp.int32(dbz),
                            jnp.int32(thr), jnp.int32(mrow[1]),
                            jnp.int32(mrow[3]), jnp.int32(mrow[4]),
                            jnp.int32(mrow[5]),
                        )
                        nl = int(nl)  # host pull == the fence
                    right = totals - left
                    sums2 = np.stack([left, right]).astype(np.float32)
                    depth_ok = (max_depth <= 0) or (child_depth < max_depth)
                    with tracer.span("split"):
                        res2 = jax.device_get(progs["find"](
                            jnp.stack([lhist, rhist]), jnp.asarray(sums2),
                            fmask, jnp.array(bool(depth_ok)),
                        ))
                    rl = ns + 1
                    vals2 = [_leaf_out(sums2[0, 0], sums2[0, 1]),
                             _leaf_out(sums2[1, 0], sums2[1, 1])]
                    recs[ns] = (bl, feat, thr, dbz, gain, vals2[0], vals2[1],
                                sums2[0, 2], sums2[1, 2], pval, 0.0, 0.0)
                    seg[bl] = (start, nl)
                    seg[rl] = (start + nl, cnt - nl)
                    for j, li in enumerate((bl, rl)):
                        bs[li] = (res2.gain[j], res2.feature[j],
                                  res2.threshold_bin[j],
                                  res2.default_bin_for_zero[j],
                                  res2.left_sum_g[j], res2.left_sum_h[j],
                                  res2.left_cnt[j], 0.0)
                        leaf[li] = (sums2[j, 0], sums2[j, 1], sums2[j, 2],
                                    vals2[j], sums2[j, 2], child_depth,
                                    0.0, 0.0)
                    ns += 1

                if irec is not None:
                    irec["leaves"] = ns + 1
                    if bag_on:
                        irec["bagged_rows"] = int(jnp.sum(sel))
                if ns == 0:
                    break
                with tracer.span("score_update"):
                    lvals = np.clip(lr * leaf[:, 3], -100.0, 100.0)
                    self.p, delta = progs["score"](
                        self.p, jnp.asarray(seg[:, 0], jnp.int32),
                        jnp.asarray(seg[:, 1], jnp.int32), jnp.int32(ns),
                        jnp.asarray(lvals, jnp.float32),
                    )
                    fence(delta)
                self._last_tree = delta
                all_ns[t, 0] = ns
                all_raw[t, 0] = recs
                n_done += 1

        recs_np = {"num_splits": all_ns[:n_done], "raw": all_raw[:n_done]}
        return recs_np, self.scores_original_order(), n_done

    def grow_result_view(self, recs_np, t, k: int = 0):
        """GrowResult-like view of tree (t, class k)'s records
        (Tree.from_grow_result consumes exactly these fields).  Unpacks
        the (m, 12) raw record columns: [leaf, feat, thr, dbz, gain,
        lval, rval, lcnt, rcnt, ival, 0, 0]."""
        raw = recs_np["raw"][t][k]
        return types.SimpleNamespace(
            num_splits=recs_np["num_splits"][t][k],
            rec_leaf=raw[:, 0].astype(np.int32),
            rec_feat=raw[:, 1].astype(np.int32),
            rec_thr=raw[:, 2].astype(np.int32),
            rec_dbz=raw[:, 3].astype(np.int32),
            rec_gain=raw[:, 4],
            rec_lval=raw[:, 5],
            rec_rval=raw[:, 6],
            rec_lcnt=raw[:, 7],
            rec_rcnt=raw[:, 8],
            rec_internal_value=raw[:, 9],
        )


class ShardedPartitionedTrainer(PartitionedTrainer):
    """Data-parallel fused trainer: the partitioned fast path under
    ``shard_map`` over a device mesh — DataParallelTreeLearner
    (data_parallel_tree_learner.cpp:118-161) with split_stream kernels.

    Rows are split into equal contiguous per-device shards, each with its
    own packed matrix + BLK tail; child/root histograms are psum'd so
    every device takes the bit-identical split on its local segment.
    Grad/hess/scores stay device-resident across trees and chunks — no
    per-tree host round-trips (the reference's per-iteration
    ReduceScatter is the ONLY cross-device traffic, here one psum of the
    (G, BH, 3) tensor per split)."""

    supports_traced = False  # defusing would serialize the collectives

    def __init__(self, train_set, config, objective, meta, hyper, mesh):
        import jax as _jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        binned = train_set.binned
        n, f = binned.shape
        md = train_set.metadata
        self.has_weights = md.weights is not None
        self.mesh = mesh
        d = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
        self.d = d
        nproc = _jax.process_count()
        d_local = d // max(nproc, 1)
        # uniform shard length across ALL processes
        if nproc > 1:
            from jax.experimental import multihost_utils

            counts = np.asarray(multihost_utils.process_allgather(np.asarray(n)))
            per_proc = int(counts.max())
        else:
            per_proc = n
        nl = -(-per_proc // d_local)
        self.num_rows = nl  # per-shard rows (the grower's n)
        self.local_rows = n  # this process's real rows
        self.d_local = d_local

        bundle = getattr(train_set, "bundle", None)
        self.bmeta = None
        num_cols, num_bins_hist = 0, 0
        if bundle is not None and train_set.bundled is not None:
            matrix = np.asarray(train_set.bundled)
            num_cols = bundle.num_cols
            num_bins_hist = int(bundle.max_col_bin)
            self.bmeta = _build_bundle_meta(bundle, train_set, int(train_set.max_num_bin))
            max_col_bin = num_bins_hist
        else:
            matrix = np.asarray(binned)
            max_col_bin = int(train_set.max_num_bin)
        force_bits = os.environ.get("LIGHTGBM_TPU_FORCE_BITS", "")
        bits = 4 if max_col_bin <= 16 else 8
        if force_bits in ("4", "8"):
            bits = int(force_bits)
            if bits == 4 and max_col_bin > 16:
                bits = 8
        # K > 1: multiclass data-parallel — K score channels, K trees per
        # iteration from one gradient pass (same as the serial trainer)
        self.K = int(getattr(objective, "num_tree_per_iteration", 1))
        self.layout = PLayout(matrix.shape[1], num_score=self.K,
                              with_weight=True, bits=bits)

        from ..ops.pkernels import BLK, pack_matrix

        label = np.asarray(md.label, np.float32)
        weight = (np.asarray(md.weights, np.float32)
                  if self.has_weights else np.ones(n, np.float32))
        shards = []
        for k in range(d_local):
            lo, hi = k * nl, min((k + 1) * nl, n)
            nreal = max(0, hi - lo)
            mb = np.zeros((nl, matrix.shape[1]), np.uint8)
            lb = np.zeros((nl,), np.float32)
            wb = np.zeros((nl,), np.float32)
            if nreal:
                mb[:nreal] = matrix[lo:hi]
                lb[:nreal] = label[lo:hi]
                wb[:nreal] = weight[lo:hi]
            shards.append(np.asarray(
                pack_matrix(mb, self.layout, label=lb, weight=wb, num_real=nreal)
            ))
        local = np.stack(shards)  # (d_local, C, nl + BLK)
        sharding = NamedSharding(mesh, P("data"))
        if nproc > 1:
            gshape = (d, local.shape[1], local.shape[2])
            # each per-device buffer keeps the leading shard axis: the
            # (d, C, n) global array sharded on axis 0 has (1, C, n) shards
            bufs = [
                _jax.device_put(local[i][None], dev)
                for i, dev in enumerate(mesh.local_devices)
            ]
            self.p = _jax.make_array_from_single_device_arrays(gshape, sharding, bufs)
        else:
            self.p = _jax.device_put(jnp.asarray(local), sharding)

        self.meta = meta
        self.hyper = hyper
        self.objective = objective
        self.config = config
        self.params = PGrowParams(
            num_leaves=max(2, int(config.num_leaves)),
            num_bins=int(train_set.max_num_bin),
            num_features=f,
            num_rows=nl,
            max_depth=int(config.max_depth),
            use_missing=bool(config.use_missing),
            has_categorical=bool(np.any(np.asarray(meta.is_categorical))),
            num_cols=num_cols,
            num_bins_hist=num_bins_hist,
            bits=bits,
            axis_name="data",
            **levelgrow_env_params(),
        )
        self.interpret = _jax.default_backend() != "tpu"
        self.score_dirty = True
        self._progs = {}
        self._apply_prog = None
        self._scores_prog = None
        self._last_tree = None
        self._base_key = jax.random.PRNGKey(
            (int(config.bagging_seed) << 1) ^ int(config.feature_fraction_seed)
        )

    # ------------------------------------------------------------------
    def _shard_map(self, fn, in_specs, out_specs):
        from ..parallel.learner import _shard_map_compat

        return _shard_map_compat(fn, self.mesh, in_specs, out_specs)

    def _pad_local(self, vec):
        """Process-local (n,) row vector -> (d_local * nl,) shard-padded."""
        v = np.zeros((self.d_local * self.num_rows,), np.float32)
        vv = np.asarray(vec, np.float32)
        nl = self.num_rows
        for k in range(self.d_local):
            lo, hi = k * nl, min((k + 1) * nl, self.local_rows)
            if hi > lo:
                v[k * nl : k * nl + (hi - lo)] = vv[lo:hi]
        return v

    def _make_row_global(self, vec):
        """Shard-padded local vector -> global (d * nl,) row-sharded array."""
        import jax as _jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        nl = self.num_rows
        local = self._pad_local(vec).reshape(self.d_local, nl)
        sharding = NamedSharding(self.mesh, P("data"))
        if _jax.process_count() > 1:
            gshape = (self.d * nl,)
            bufs = [_jax.device_put(local[i], dev)
                    for i, dev in enumerate(self.mesh.local_devices)]
            return _jax.make_array_from_single_device_arrays(gshape, sharding, bufs)
        return _jax.device_put(jnp.asarray(local.reshape(-1)), sharding)

    def _gather_rows(self, garr):
        """Global (d * nl,) — or (K, d * nl), rows on the LAST axis —
        row-sharded array -> process-local (n,) / (K, n) numpy."""
        import jax as _jax

        axis = garr.ndim - 1
        if _jax.process_count() > 1:
            shards = sorted(garr.addressable_shards,
                            key=lambda s: (s.index[axis].start or 0))
            local = np.concatenate([np.asarray(s.data) for s in shards],
                                   axis=axis)
        else:
            local = np.asarray(garr)
        nl = self.num_rows
        parts = []
        for k in range(self.d_local):
            lo, hi = k * nl, min((k + 1) * nl, self.local_rows)
            parts.append(local[..., k * nl : k * nl + max(0, hi - lo)])
        return (np.concatenate(parts, axis=axis) if parts
                else local[..., :0])

    def _apply_delta(self, delta, k: int = 0) -> None:
        """delta in process-row order (n,); applied per shard in place to
        score channel ``k`` (score-only streamer — gradient channels
        refresh at the next chunk's update pass, like the serial path)."""
        from jax.sharding import PartitionSpec as P

        if self._apply_prog is None:
            self._apply_prog = {}
        if k not in self._apply_prog:
            lay = self.layout
            interp = self.interpret
            nl = self.num_rows

            def shard_body(pg, dg, k=k):
                return score_add(pg[0], lay, dg, k, num_rows=nl,
                                 interpret=interp)[None]

            self._apply_prog[k] = jax.jit(
                self._shard_map(shard_body, (P("data"), P("data")), P("data")),
                donate_argnums=(0,),
            )
        dg = delta if hasattr(delta, "sharding") else self._make_row_global(delta)
        self.p = self._apply_prog[k](self.p, dg)

    def add_score_constant(self, c: float) -> None:
        # constant only on REAL rows (padding rows' scores are unused)
        self._apply_delta(np.full((self.local_rows,), np.float32(c)))

    def sync_scores_from(self, scores_orig) -> None:
        """Bring score channels to an original-order target.  The delta
        must be computed in PHYSICAL row order: split_stream permutes
        shard columns, so the in-shard body gathers the row-order target
        through the ROWID channel and subtracts the positional current
        scores (mirrors the serial trainer's rowid gather)."""
        from jax.sharding import PartitionSpec as P

        if getattr(self, "_sync_prog", None) is None:
            self._sync_prog = {}
        lay = self.layout
        interp = self.interpret
        nl = self.num_rows
        target = np.atleast_2d(np.asarray(scores_orig, np.float32))
        for k in range(self.K):
            if k not in self._sync_prog:

                def shard_body(pg, tg, k=k):
                    p = pg[0]
                    rowid = p[lay.ROWID, :nl]
                    cur = _i2f(p[lay.SCORE + k, :nl])
                    dphys = tg[rowid] - cur
                    return score_add(p, lay, dphys, k, num_rows=nl,
                                     interpret=interp)[None]

                self._sync_prog[k] = jax.jit(
                    self._shard_map(shard_body, (P("data"), P("data")), P("data")),
                    donate_argnums=(0,),
                )
            tg = self._make_row_global(target[k])
            self.p = self._sync_prog[k](self.p, tg)
        self.score_dirty = False

    def _scores_global(self):
        from jax.sharding import PartitionSpec as P

        if self._scores_prog is None:
            lay = self.layout
            nl = self.num_rows
            K = self.K

            def shard_body(pg):
                p = pg[0]
                rowid = p[lay.ROWID, :nl]
                outs = [
                    jnp.zeros((nl,), jnp.float32).at[rowid].set(
                        _i2f(p[lay.SCORE + k, :nl])
                    )
                    for k in range(K)
                ]
                return jnp.stack(outs)  # (K, nl)

            self._scores_prog = jax.jit(
                self._shard_map(shard_body, (P("data"),), P(None, "data"))
            )
        return self._scores_prog(self.p)  # (K, d * nl)

    def scores_original_order(self):
        """(N,) for K == 1, else (K, N)."""
        got = jnp.asarray(self._gather_rows(self._scores_global()))
        return got[0] if self.K == 1 else got

    def rollback_last(self) -> bool:
        """K > 1 chunks track only the last class's delta; they resync
        via score_dirty instead (same contract as the serial trainer)."""
        if self._last_tree is None or self.K != 1:
            return False
        import jax as _jax

        neg = _jax.jit(lambda x: -x)(self._last_tree)
        self._apply_delta(neg)
        self._last_tree = None
        return True

    # -- checkpoint support -------------------------------------------
    def _local_shards_sorted(self):
        return sorted(self.p.addressable_shards,
                      key=lambda s: (s.index[0].start or 0))

    def export_perm(self):
        """(d, nl) int32 — every shard's ROWID channel (shard-LOCAL row
        ids: split_stream permutes columns within a shard only).
        COLLECTIVE in multi-process runs: local shards are allgathered
        over parallel/collect.py so every host returns the full global
        matrix and host 0 can write it."""
        import pickle

        import jax as _jax

        lay = self.layout
        local = np.stack([
            np.asarray(s.data)[0, lay.ROWID, : self.num_rows]
            for s in self._local_shards_sorted()
        ]).astype(np.int32)
        if _jax.process_count() > 1:
            from ..parallel.collect import allgather_bytes

            parts = [pickle.loads(b)
                     for b in allgather_bytes(pickle.dumps(local))]
            return np.concatenate(parts, axis=0)
        return local

    def import_perm(self, rowid) -> None:
        """Permute each addressable shard's columns to the checkpointed
        layout (host-side: the shards were just packed identity-order in
        ``__init__``) and rebuild the global array on the same devices."""
        import jax as _jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        rowid = np.asarray(rowid, np.int64)
        if rowid.shape != (self.d, self.num_rows):
            from ..utils.log import Log

            # elastic resume onto a different device/host grid: the
            # saved shard layout no longer applies — keep identity
            # packing (valid continuation, scores re-sync exactly)
            Log.warning(
                "checkpoint shard permutation has shape %s, expected "
                "(%d, %d); keeping identity layout (topology changed "
                "since save)", rowid.shape, self.d, self.num_rows,
            )
            self._last_tree = None
            self.score_dirty = True
            return
        nl = self.num_rows
        bufs, devs = [], []
        for s in self._local_shards_sorted():
            g = s.index[0].start or 0
            arr = np.array(s.data)  # (1, C, nl + BLK) host copy
            arr[0, :, :nl] = arr[0, :, :nl][:, rowid[g]]
            bufs.append(arr)
            devs.append(s.device)
        sharding = NamedSharding(self.mesh, P("data"))
        if _jax.process_count() > 1:
            self.p = _jax.make_array_from_single_device_arrays(
                self.p.shape, sharding,
                [_jax.device_put(b, d) for b, d in zip(bufs, devs)],
            )
        else:
            self.p = _jax.device_put(
                jnp.asarray(np.concatenate(bufs, axis=0)), sharding
            )
        self._last_tree = None
        self.score_dirty = True

    # ------------------------------------------------------------------
    def _build_program(self, T: int, bag_on: bool, bag_freq: int, used_features: int):
        from jax.sharding import PartitionSpec as P

        lay = self.layout
        nl = self.num_rows
        L = self.params.num_leaves
        F = self.params.num_features
        K = self.K
        grad_fn = self._grad_fn
        grad_all_fn = self._grad_all_fn
        params = self.params
        meta = self.meta
        hyper = self.hyper
        bmeta = self.bmeta
        interpret = self.interpret
        bag_frac = float(self.config.bagging_fraction)
        G = params.num_cols or F
        BH = params.num_bins_hist or params.num_bins
        cfg = self.config
        # GOSS in data-parallel mode is LOCAL per shard — the reference's
        # distributed GOSS also samples per machine over local indices
        # (goss.hpp Bagging over the local data partition); counts scale
        # with each shard's real rows
        goss_on = (getattr(cfg, "boosting", "gbdt") == "goss") and K == 1
        if goss_on:
            top_rate = float(cfg.top_rate)
            other_rate = float(cfg.other_rate)
            top_cnt_max = max(1, int(np.ceil(top_rate * nl)))
            goss_warm = int(1.0 / float(cfg.learning_rate))

        def shard_body(pg, nreal_g, lr, key, iter0, t_run):
            p = pg[0]
            ax = jax.lax.axis_index("data")
            nreal = nreal_g[0]  # this shard's real-row count

            def one_iter(t, carry):
                # post-stop iterations are full no-ops (see the serial
                # trainer: a throwaway tree would repartition rows under
                # the positionally-applied last_kept)
                return jax.lax.cond(carry[2], lambda c: c,
                                    functools.partial(_live_iter, t), carry)

            def _live_iter(t, carry):
                (p, recs, stopped, delta, last_kept) = carry
                it = iter0 + t
                # validity must travel WITH the row: split_stream permutes
                # shard columns, so padding is identified by the preserved
                # ROWID channel (local rowid >= nreal), never by position
                valid = (p[lay.ROWID, :nl] < nreal).astype(jnp.float32)
                if bag_on:
                    bkey = jax.random.fold_in(
                        jax.random.fold_in(
                            jax.random.fold_in(key, 0), it // bag_freq
                        ), ax
                    )
                    sel = jax.random.bernoulli(bkey, bag_frac, (nl,)).astype(jnp.float32)
                    sel = sel * valid
                else:
                    sel = None
                if used_features < F:
                    fkey = jax.random.fold_in(jax.random.fold_in(key, 1), it)
                    u = jax.random.uniform(fkey, (F,))
                    _, idx = jax.lax.top_k(u, used_features)
                    fmask = jnp.zeros((F,), jnp.float32).at[idx].set(1.0)
                else:
                    fmask = jnp.ones((F,), jnp.float32)

                ns_t = recs["num_splits"][t]
                raw_t = recs["raw"][t]
                if K == 1:
                    if goss_on:
                        # settle pending delta + fresh gradients first
                        # (histogram-free pass), then local top-k +
                        # Bernoulli rest-sample (goss.hpp:126-198 over
                        # the shard's rows)
                        p, _ = update_and_root_hist(
                            p, lay, grad_fn, delta=delta, num_rows=nl,
                            num_features=G, num_bins=BH, bits=params.bits,
                            with_hist=False, interpret=interpret,
                        )
                        gv = _i2f(p[lay.G, :nl])
                        hv = _i2f(p[lay.H, :nl])
                        gscore = jnp.abs(gv * hv) * valid
                        top_c = jnp.maximum(jnp.floor(top_rate * nreal), 1.0)
                        other_c = jnp.maximum(jnp.floor(other_rate * nreal), 1.0)
                        goss_mult = (nreal - top_c) / other_c
                        goss_prob = other_c / jnp.maximum(nreal - top_c, 1.0)
                        # exactly top_c rows marked top via the top_k
                        # INDICES (ADVICE r5: a >= threshold test admits
                        # every tie — common with integer features — and
                        # can never admit zero-gradient rows, so the
                        # nominal-count goss_mult was biased).  Padding
                        # rows are pushed below every valid row so ties
                        # at zero resolve to real rows first.
                        topc_i = jnp.clip(top_c.astype(jnp.int32), 1, top_cnt_max)
                        _, top_idx = jax.lax.top_k(
                            jnp.where(valid > 0, gscore, -1.0), top_cnt_max
                        )
                        rank_ok = jnp.arange(top_cnt_max) < topc_i
                        is_top = (jnp.zeros((nl,), bool).at[top_idx].set(rank_ok)
                                  & (valid > 0))
                        gkey = jax.random.fold_in(
                            jax.random.fold_in(jax.random.fold_in(key, 2), it), ax
                        )
                        sampled = ((~is_top)
                                   & (jax.random.uniform(gkey, (nl,)) < goss_prob)
                                   & (valid > 0))
                        warm = it < goss_warm
                        selv = jnp.where(
                            warm, valid, (is_top | sampled).astype(jnp.float32)
                        )
                        mulv = jnp.where(warm | (~sampled), 1.0, goss_mult)
                        p, root_hist = update_and_root_hist(
                            p, lay, grad_fn, sel=selv, mul=mulv,
                            num_rows=nl, num_features=G, num_bins=BH,
                            bits=params.bits, interpret=interpret,
                        )
                        delta = jnp.zeros((nl,), jnp.float32)
                    else:
                        p, root_hist = update_and_root_hist(
                            p, lay, grad_fn, delta=delta, sel=sel, num_rows=nl,
                            num_features=G, num_bins=BH, bits=params.bits,
                            interpret=interpret,
                        )
                    root_hist = jax.lax.psum(root_hist, "data")
                    tree, p = grow_tree_partitioned(
                        p, fmask, meta, hyper, params, bmeta=bmeta,
                        interpret=interpret, root_hist=root_hist,
                    )
                    keep = ((tree.num_splits > 0) & (~stopped)).astype(jnp.float32)
                    lval = jnp.clip(lr * tree.leaf_value, -100.0, 100.0)
                    delta = segment_values(tree, nl, keep * lval)
                    last_kept = jnp.where(keep > 0, delta, last_kept)
                    any_split = tree.num_splits > 0
                    ns_t = ns_t.at[0].set(tree.num_splits)
                    raw_t = raw_t.at[0].set(tree.recs_raw)
                else:
                    # K trees per iteration from one gradient pass; each
                    # class's delta lands on its score row immediately
                    # after its tree (mirrors the serial K > 1 branch,
                    # with per-level hist psums inside the grower)
                    p, hists = update_multi_and_hists(
                        p, lay, grad_all_fn, sel=sel, num_rows=nl,
                        num_features=G, num_bins=BH, bits=params.bits,
                        interpret=interpret,
                    )
                    hists = jax.lax.psum(hists, "data")
                    any_split = jnp.array(False)
                    for k in range(K):
                        tree, p = grow_tree_partitioned(
                            p, fmask, meta, hyper, params, bmeta=bmeta,
                            interpret=interpret, root_hist=hists[k],
                            rows=lay.class_rows(k),
                        )
                        keep = ((tree.num_splits > 0) & (~stopped)).astype(jnp.float32)
                        lval = jnp.clip(lr * tree.leaf_value, -100.0, 100.0)
                        dk = segment_values(tree, nl, keep * lval)
                        p = score_add(p, lay, dk, k, num_rows=nl,
                                      interpret=interpret)
                        any_split = any_split | (tree.num_splits > 0)
                        ns_t = ns_t.at[k].set(tree.num_splits)
                        raw_t = raw_t.at[k].set(tree.recs_raw)

                recs = {
                    "num_splits": recs["num_splits"].at[t].set(ns_t),
                    "raw": recs["raw"].at[t].set(raw_t),
                }
                new_stopped = stopped | (~any_split)
                return (p, recs, new_stopped, delta, last_kept)

            m = L - 1
            recs0 = {
                "num_splits": jnp.zeros((T, K), jnp.int32),
                "raw": jnp.zeros((T, K, m, 12)),
            }
            carry0 = (p, recs0, jnp.array(False), jnp.zeros((nl,), jnp.float32),
                      jnp.zeros((nl,), jnp.float32))
            p, recs, _, last_delta, last_kept = jax.lax.fori_loop(
                0, jnp.minimum(t_run, T), one_iter, carry0
            )
            if K == 1:
                # score-only chunk-end settle (see the serial trainer)
                p = score_add(p, lay, last_delta, 0, num_rows=nl,
                              interpret=interpret)
            rowid = p[lay.ROWID, :nl]
            scores_local = jnp.stack([
                jnp.zeros((nl,), jnp.float32).at[rowid].set(
                    _i2f(p[lay.SCORE + k, :nl])
                )
                for k in range(K)
            ])  # (K, nl)
            return p[None], recs, scores_local, last_kept

        mapped = self._shard_map(
            shard_body,
            (P("data"), P("data"), P(), P(), P(), P()),
            (P("data"), {"num_splits": P(), "raw": P()}, P(None, "data"),
             P("data")),
        )
        return jax.jit(mapped, donate_argnums=(0,))

    def train_chunk(self, T: int, lr: float, iter0: int):
        cfg = self.config
        bag_on = cfg.bagging_fraction < 1.0 and cfg.bagging_freq > 0
        bag_freq = max(1, int(cfg.bagging_freq))
        used_features = self.params.num_features
        if cfg.feature_fraction < 1.0:
            used_features = max(1, int(self.params.num_features * cfg.feature_fraction))
        alloc = self.CHUNK_ALLOC
        pkey = (alloc, bag_on, bag_freq, used_features)
        if pkey not in self._progs:
            self._progs[pkey] = JitWatch(
                self._build_program(alloc, bag_on, bag_freq, used_features),
                name=f"ptrainer.sharded_chunk(bag={int(bag_on)},ff={used_features})",
                phase="chunk_program",
            )
        prog = self._progs[pkey]
        recs_np = None
        n_done = 0
        remaining = T
        scores = None
        if T <= 0:
            return {}, self.scores_original_order(), 0
        if not hasattr(self, "_nreal_global"):
            # per-shard real-row counts, one scalar per device
            import jax as _jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            nl = self.num_rows
            vals = np.asarray(
                [max(0, min(self.local_rows - k * nl, nl))
                 for k in range(self.d_local)], np.int32,
            ).reshape(self.d_local, 1)
            sharding = NamedSharding(self.mesh, P("data"))
            if _jax.process_count() > 1:
                bufs = [_jax.device_put(vals[i], dev)
                        for i, dev in enumerate(self.mesh.local_devices)]
                self._nreal_global = _jax.make_array_from_single_device_arrays(
                    (self.d,), sharding, bufs
                )
            else:
                self._nreal_global = _jax.device_put(
                    jnp.asarray(vals.reshape(-1)), sharding
                )
        while remaining > 0:
            step = min(remaining, alloc)
            with tracer.span("chunk_program", iters=step):
                self.p, recs, scores, last_kept = prog(
                    self.p, self._nreal_global, jnp.float32(lr), self._base_key,
                    jnp.int32(iter0 + n_done), jnp.int32(step),
                )
            with tracer.span("records_fetch"):
                part = jax.device_get(recs)
            ns = part["num_splits"][:step]  # (step, K)
            stop = np.nonzero(np.all(ns == 0, axis=1))[0]
            done_here = int(stop[0]) if stop.size else step
            if done_here > 0:
                # K > 1 resyncs via score_dirty on rollback instead
                self._last_tree = last_kept if self.K == 1 else None
            part = {k: v[:done_here] for k, v in part.items()}
            recs_np = part if recs_np is None else {
                k: np.concatenate([recs_np[k], part[k]]) for k in part
            }
            n_done += done_here
            remaining -= step
            if done_here < step:
                break
        got = jnp.asarray(self._gather_rows(scores))
        scores_orig = got[0] if self.K == 1 else got
        return recs_np, scores_orig, n_done


def eligible(config, train_set, objective, num_tree_per_iteration: int) -> bool:
    """Can the partitioned trainer drive this configuration?  (The rest
    falls back to the mask-based grower, which handles everything.)"""
    flag = os.environ.get("LIGHTGBM_TPU_PGROW", "")
    if flag == "0":
        return False
    if flag != "force" and jax.default_backend() != "tpu":
        return False
    if objective is None:
        return False
    # quantized training runs through the mask grower's int32 histogram
    # path (ops/qhist.py); the fused kernels' bf16 3-term value split is
    # an f32 pipeline and would break the exact-integer contract
    if getattr(config, "quantized_training", False):
        return False
    # strategy plug-ins (tree/strategy.py): the fused kernels inline the
    # unconstrained split scan and constant leaf outputs; linear leaves
    # and monotone constraints run through the mask grower's strategy
    # seam instead (same decline shape as quantization above)
    if getattr(config, "linear_tree", False):
        return False
    if hasattr(config, "_monotone_active") and config._monotone_active():
        return False
    if num_tree_per_iteration == 1:
        if not getattr(objective, "rowwise", False):
            return False
    else:
        # multiclass: needs the all-classes row-local gradient plane
        # (gradients_rowwise_all); 6K+1 bf16 value rows must fit the
        # MXU's 128 sublanes in the fused update kernel
        if not getattr(objective, "rowwise_multi", False):
            return False
        if num_tree_per_iteration > 16:
            return False
        # multiclass GOSS: the fused trainers' GOSS sampling is K == 1
        # only — fall back to the mask grower, whose _adjust_gradients
        # hooks apply real GOSS to every class (silently training plain
        # GBDT here would be an algorithm regression)
        if getattr(config, "boosting", "gbdt") == "goss":
            return False
    # serial -> PartitionedTrainer; data -> ShardedPartitionedTrainer.
    # feature/voting keep the mask grower's collective formulations on a
    # device mesh, or the host-driven learners (parallel/hostlearner.py)
    # across processes — their per-node exchanges don't fuse.
    if config.tree_learner not in ("serial", "data"):
        return False
    if np.asarray(train_set.binned).dtype != np.uint8:
        return False
    if train_set.max_num_bin > 256:
        return False
    # bundling is built lazily, only once a partitioned run is plausible
    if hasattr(train_set, "ensure_bundles"):
        train_set.ensure_bundles(config)
    # Wide-matrix ceiling (Bosch-968/Epsilon-2000 shapes): two hard
    # budgets bound the fused kernels, not just the per-column unroll.
    # (a) Mosaic program size grows linearly with the per-block one-hot
    #     unroll (fixable with a rolled word-group loop), and
    # (b) VMEM: the split/level kernels hold 11 (C, BLK) stream buffers
    #     + the (BLK, BLK) tri + the (16, G*B) hist accumulators; at
    #     G=968, B=64 that is ~17 MB at BLK=1024 and the level kernel's
    #     double-buffered hist alone is ~8 MB — G=2000 cannot fit any
    #     BLK without spilling accumulators to HBM.
    # Beyond the cap the mask-based grower (which tiles columns freely
    # at the XLA level) handles these shapes; gpu_tree_learner.cpp's
    # multi-tuple packing is the reference analogue of that fallback.
    bundle = getattr(train_set, "bundle", None)
    cols = bundle.num_cols if bundle is not None else train_set.num_features
    if cols > 512:
        return False
    return True


def _build_bundle_meta(bundle, train_set, num_bins: int) -> BundleMeta:
    """Host-built device maps for the bundled histogram expansion."""
    f = train_set.num_features
    b = num_bins
    bh = int(bundle.max_col_bin)
    default_bin = np.asarray([m.default_bin for m in train_set.bin_mappers], np.int64)
    nb = np.asarray([m.num_bin for m in train_set.bin_mappers], np.int64)
    zero_slot = bundle.num_cols * bh  # appended all-zero row
    idx = np.full((f, b), zero_slot, np.int32)
    defmask = np.zeros((f, b), bool)
    for fe in range(f):
        if int(bundle.off_lo[fe]) == 0:
            # singleton raw column: every bin (incl. default) maps direct
            for bi in range(int(nb[fe])):
                idx[fe, bi] = int(bundle.col[fe]) * bh + bi
            continue
        for bi in range(int(nb[fe])):
            if bi == int(default_bin[fe]):
                defmask[fe, bi] = True
                continue
            v = int(bundle.off_lo[fe]) + bi - int(bundle.bias[fe])
            idx[fe, bi] = int(bundle.col[fe]) * bh + v
    return BundleMeta(
        col=jnp.asarray(bundle.col),
        off_lo=jnp.asarray(bundle.off_lo),
        off_hi=jnp.asarray(bundle.off_hi),
        bias=jnp.asarray(bundle.bias),
        idx=jnp.asarray(idx),
        defmask=jnp.asarray(defmask),
    )
