"""Fused partitioned trainer — boosting iterations as ONE device program.

Drives ops/pgrow.py for the serial single-class path.  The motivation is
dispatch latency: a host round-trip to the (possibly tunneled) TPU costs
up to ~80 ms, so the reference's per-iteration host loop
(GBDT::TrainOneIter, gbdt.cpp:381-495) becomes a ``lax.fori_loop`` over
iterations INSIDE one jitted program:

    gradients (from the score/label channels, in permuted row space)
    -> bagging mask -> feature sampling -> grow_tree_partitioned
    -> in-place per-segment score update -> split records[t]

Scores, labels and weights travel as bitcast channels of the packed
matrix, so nothing is ever gathered back to original row order during
training; the (N,) original-order score vector is rebuilt ONCE per chunk
(a single scatter through the rowid channel) for metrics/eval.

Row-order-free semantics this relies on: histograms, leaf statistics and
elementwise objectives are permutation-invariant.  Ranking objectives
(query-grouped) are not — they keep the mask-based grower (ops/grow.py).

Deliberate parity divergences from the reference (documented):
- bagging draws a per-row Bernoulli(bagging_fraction) mask with JAX
  threefry instead of the host RNG's exact-count subset
  (gbdt.cpp:275-334); same distribution, different stream.
- feature_fraction samples exactly ceil(frac*F) features via device
  top_k on uniform keys instead of utils/random.py's host sampler.
"""

from __future__ import annotations

import functools
import os
import types

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.pgrow import (
    BundleMeta,
    PGrowParams,
    grow_tree_partitioned,
    segment_values,
)
from ..ops.pkernels import PLayout, pack_matrix_device
from ..ops.split import FeatureMeta, SplitHyper
from ..utils.log import Log


def _f2i(x):
    return jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.int32)


def _i2f(x):
    return jax.lax.bitcast_convert_type(x, jnp.float32)


class PartitionedTrainer:
    """Owns the packed matrix + fused train-chunk programs for one GBDT."""

    def __init__(self, train_set, config, objective, meta: FeatureMeta, hyper: SplitHyper,
                 bins_dev=None):
        binned = train_set.binned
        n, f = binned.shape
        assert binned.dtype == np.uint8
        md = train_set.metadata
        self.has_weights = md.weights is not None
        # EFB: stream the bundled (N, G) matrix instead of (N, F) when the
        # dataset found exclusive bundles (io/bundle.py); split search and
        # the model stay in real-feature space via BundleMeta
        bundle = getattr(train_set, "bundle", None)
        self.bmeta = None
        num_cols, num_bins_hist = 0, 0
        if bundle is not None and train_set.bundled is not None:
            matrix = train_set.bundled
            num_cols = bundle.num_cols
            num_bins_hist = int(bundle.max_col_bin)
            self.bmeta = _build_bundle_meta(bundle, train_set, int(train_set.max_num_bin))
            bins_dev = None  # the unbundled device matrix is not what we pack
            max_col_bin = num_bins_hist
        else:
            matrix = binned
            max_col_bin = int(train_set.max_num_bin)
        # 4-bit packed words when every column fits 16 bins
        # (dense_nbits_bin.hpp:37): half the resident bin bytes/traffic
        # (LIGHTGBM_TPU_FORCE_BITS=8 disables, e.g. for A/B measurement)
        force_bits = os.environ.get("LIGHTGBM_TPU_FORCE_BITS", "")
        bits = 4 if max_col_bin <= 16 else 8
        if force_bits in ("4", "8"):
            bits = int(force_bits)
            if bits == 4 and max_col_bin > 16:
                bits = 8  # cannot pack >16 bins in 4 bits
        self.layout = PLayout(matrix.shape[1], num_score=1, with_weight=True, bits=bits)
        if bins_dev is None:
            bins_dev = jnp.asarray(np.asarray(matrix))
        self.p = pack_matrix_device(bins_dev, self.layout, label=md.label,
                                    weight=md.weights if self.has_weights else None)
        self.scratch = jnp.zeros_like(self.p)
        self.num_rows = n
        self.meta = meta
        self.hyper = hyper
        self.objective = objective
        self.config = config
        self.params = PGrowParams(
            num_leaves=max(2, int(config.num_leaves)),
            num_bins=int(train_set.max_num_bin),
            num_features=f,
            num_rows=n,
            max_depth=int(config.max_depth),
            use_missing=bool(config.use_missing),
            has_categorical=bool(np.any(np.asarray(meta.is_categorical))),
            num_cols=num_cols,
            num_bins_hist=num_bins_hist,
            bits=bits,
        )
        self.interpret = jax.default_backend() != "tpu"
        # start dirty: init_score / init_model may mutate GBDT.scores after
        # construction; the first chunk syncs the channel (identity-order
        # gather, cheap)
        self.score_dirty = True
        self._progs = {}
        self._last_tree = None  # (starts, cnts, scaled leaf deltas) for rollback
        self._base_key = jax.random.PRNGKey(
            (int(config.bagging_seed) << 1) ^ int(config.feature_fraction_seed)
        )

    # -- score channel maintenance ------------------------------------
    def add_score_constant(self, c: float) -> None:
        lay = self.layout
        sc = _i2f(self.p[lay.SCORE]) + jnp.float32(c)
        self.p = self.p.at[lay.SCORE].set(_f2i(sc))

    def sync_scores_from(self, scores_orig) -> None:
        """Permute an original-order (N,) score vector into the channel
        (one gather through rowid; rare — init_model / external updates)."""
        lay = self.layout
        rowid = self.p[lay.ROWID, : self.num_rows]
        perm = jnp.asarray(scores_orig, jnp.float32)[rowid]
        padded = jnp.zeros((self.p.shape[1],), jnp.float32).at[: self.num_rows].set(perm)
        self.p = self.p.at[lay.SCORE].set(_f2i(padded))
        self.score_dirty = False

    def scores_original_order(self):
        lay = self.layout
        rowid = self.p[lay.ROWID, : self.num_rows]
        sc = _i2f(self.p[lay.SCORE, : self.num_rows])
        return jnp.zeros((self.num_rows,), jnp.float32).at[rowid].set(sc)

    def rollback_last(self) -> bool:
        """Undo the most recent tree's score contribution (the segment
        layout still matches it — GBDT::RollbackOneIter)."""
        if self._last_tree is None:
            return False
        delta = self._last_tree
        lay = self.layout
        sc = _i2f(self.p[lay.SCORE, : self.num_rows]) - delta
        full = jnp.zeros((self.p.shape[1],), jnp.float32).at[: self.num_rows].set(sc)
        self.p = self.p.at[lay.SCORE].set(_f2i(full))
        self._last_tree = None
        return True

    # -- the fused chunk program --------------------------------------
    def _grad_fn(self, score, label, weight):
        obj = self.objective
        return obj.gradients_rowwise(score, label, weight if self.has_weights else None)

    def _build_program(self, T: int, bag_on: bool, bag_freq: int, used_features: int):
        lay = self.layout
        n = self.num_rows
        L = self.params.num_leaves
        F = self.params.num_features
        grad_fn = self._grad_fn
        params = self.params
        meta = self.meta
        hyper = self.hyper
        bmeta = self.bmeta
        interpret = self.interpret
        bag_frac = float(self.config.bagging_fraction)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def prog(p, scratch, lr, key, iter0, t_run):
            ones_sel = jnp.full((n,), np.float32(1.0).view(np.int32), jnp.int32)
            pad = p.shape[1] - n

            def row(x_i32):
                return jnp.concatenate([x_i32, jnp.zeros((pad,), jnp.int32)])[None, :]

            def one_iter(t, carry):
                (p, scratch, recs, stopped, last_starts, last_cnts, last_vals, last_ns) = carry
                it = iter0 + t
                # gradients from channels
                score = _i2f(p[lay.SCORE, :n])
                label = _i2f(p[lay.LABEL, :n])
                weight = _i2f(p[lay.WEIGHT, :n])
                g, h = grad_fn(score, label, weight)
                if bag_on:
                    bkey = jax.random.fold_in(key, 2 * (it // bag_freq))
                    sel = jax.random.bernoulli(bkey, bag_frac, (n,)).astype(jnp.float32)
                    sel_i = _f2i(sel)
                else:
                    sel_i = ones_sel
                # rebuild P functionally (concat, not .at[row].set): row
                # surgery on the 64 MB loop carry trips XLA's in-place
                # elision and costs whole-array copies per write; a clean
                # rebuild is one materialization (~0.2 ms)
                p = jnp.concatenate(
                    [p[: lay.G], row(_f2i(g)), row(_f2i(h)), row(sel_i), p[lay.SCORE :]],
                    axis=0,
                )

                if used_features < F:
                    fkey = jax.random.fold_in(key, 2 * it + 1)
                    u = jax.random.uniform(fkey, (F,))
                    _, idx = jax.lax.top_k(u, used_features)
                    fmask = jnp.zeros((F,), jnp.float32).at[idx].set(1.0)
                else:
                    fmask = jnp.ones((F,), jnp.float32)

                tree, p, scratch = grow_tree_partitioned(
                    p, scratch, fmask, meta, hyper, params, bmeta=bmeta,
                    interpret=interpret,
                )

                # score update: +lr * leaf_value over each segment.  Once
                # any iteration produces an empty tree, training has
                # logically stopped (GBDT::TrainOneIter returns finished;
                # the host truncates the records there) — later in-program
                # iterations must not touch the scores either, or the
                # channel would contain trees that are not in the model.
                keep = ((tree.num_splits > 0) & (~stopped)).astype(jnp.float32)
                # clamp like Tree.shrinkage (tree.h:13 kMaxTreeOutput): the
                # persisted tree stores clip(lr*value, +-100), so the score
                # channel must apply the same clip or training-time scores
                # diverge from what the stored model predicts
                lval = jnp.clip(lr * tree.leaf_value, -100.0, 100.0)
                delta = segment_values(tree, n, keep * lval)
                score2 = _i2f(p[lay.SCORE, :n]) + delta
                p = jnp.concatenate(
                    [p[: lay.SCORE], row(_f2i(score2)), p[lay.SCORE + 1 :]], axis=0
                )

                recs = {
                    "num_splits": recs["num_splits"].at[t].set(tree.num_splits),
                    "leaf": recs["leaf"].at[t].set(tree.rec_leaf),
                    "feat": recs["feat"].at[t].set(tree.rec_feat),
                    "thr": recs["thr"].at[t].set(tree.rec_thr),
                    "dbz": recs["dbz"].at[t].set(tree.rec_dbz),
                    "gain": recs["gain"].at[t].set(tree.rec_gain),
                    "lval": recs["lval"].at[t].set(tree.rec_lval),
                    "rval": recs["rval"].at[t].set(tree.rec_rval),
                    "lcnt": recs["lcnt"].at[t].set(tree.rec_lcnt),
                    "rcnt": recs["rcnt"].at[t].set(tree.rec_rcnt),
                    "ival": recs["ival"].at[t].set(tree.rec_internal_value),
                }
                kept = keep > 0
                new_stopped = stopped | (tree.num_splits == 0)
                pick = lambda a, b: jnp.where(kept, a, b)
                return (p, scratch, recs, new_stopped,
                        pick(tree.starts, last_starts), pick(tree.cnts, last_cnts),
                        pick(keep * lval, last_vals),
                        pick(tree.num_splits, last_ns))

            m = L - 1
            recs0 = {
                "num_splits": jnp.zeros((T,), jnp.int32),
                "leaf": jnp.zeros((T, m), jnp.int32),
                "feat": jnp.zeros((T, m), jnp.int32),
                "thr": jnp.zeros((T, m), jnp.int32),
                "dbz": jnp.zeros((T, m), jnp.int32),
                "gain": jnp.zeros((T, m)),
                "lval": jnp.zeros((T, m)),
                "rval": jnp.zeros((T, m)),
                "lcnt": jnp.zeros((T, m)),
                "rcnt": jnp.zeros((T, m)),
                "ival": jnp.zeros((T, m)),
            }
            carry0 = (p, scratch, recs0, jnp.array(False),
                      jnp.zeros((L,), jnp.int32),
                      jnp.zeros((L,), jnp.int32), jnp.zeros((L,)), jnp.int32(0))
            p, scratch, recs, _, ls, lc, lv, lns = jax.lax.fori_loop(
                0, jnp.minimum(t_run, T), one_iter, carry0
            )
            # original-order scores for eval (one scatter per chunk)
            rowid = p[lay.ROWID, :n]
            sc = _i2f(p[lay.SCORE, :n])
            scores_orig = jnp.zeros((n,), jnp.float32).at[rowid].set(sc)
            # last tree's per-position contribution (for rollback)
            last_delta = segment_values(
                types.SimpleNamespace(starts=ls, cnts=lc, num_splits=lns), n, lv
            )
            return p, scratch, recs, scores_orig, last_delta

        return prog

    # record buffers are allocated at CHUNK_ALLOC granularity so a short
    # run (warmup) and a long run reuse one compiled program (the loop
    # bound is traced)
    CHUNK_ALLOC = 64

    def train_chunk(self, T: int, lr: float, iter0: int):
        """Run T fused boosting iterations (T <= CHUNK_ALLOC per call is
        one program invocation; longer runs loop).  Returns (records dict
        of numpy arrays, scores_orig (N,) device array, n_done)."""
        cfg = self.config
        bag_on = cfg.bagging_fraction < 1.0 and cfg.bagging_freq > 0
        bag_freq = max(1, int(cfg.bagging_freq))
        used_features = self.params.num_features
        if cfg.feature_fraction < 1.0:
            used_features = max(1, int(self.params.num_features * cfg.feature_fraction))
        # fixed allocation: every chunk size shares ONE compiled program
        # (the loop bound is traced; record buffers are CHUNK_ALLOC-sized)
        alloc = self.CHUNK_ALLOC
        pkey = (alloc, bag_on, bag_freq, used_features)
        if pkey not in self._progs:
            self._progs[pkey] = self._build_program(alloc, bag_on, bag_freq, used_features)
        prog = self._progs[pkey]
        recs_np = None
        n_done = 0
        remaining = T
        scores_orig = None
        if T <= 0:
            return {}, self.scores_original_order(), 0
        while remaining > 0:
            step = min(remaining, alloc)
            self.p, self.scratch, recs, scores_orig, last_delta = prog(
                self.p, self.scratch, jnp.float32(lr), self._base_key,
                jnp.int32(iter0 + n_done), jnp.int32(step),
            )
            self._last_tree = last_delta
            part = jax.device_get(recs)
            ns = part["num_splits"][:step]
            stop = np.nonzero(ns == 0)[0]
            done_here = int(stop[0]) if stop.size else step
            part = {k: v[:done_here] for k, v in part.items()}
            recs_np = part if recs_np is None else {
                k: np.concatenate([recs_np[k], part[k]]) for k in part
            }
            n_done += done_here
            remaining -= step
            if done_here < step:
                break
        return recs_np, scores_orig, n_done

    def grow_result_view(self, recs_np, t):
        """GrowResult-like view of tree t's records (Tree.from_grow_result
        consumes exactly these fields)."""
        return types.SimpleNamespace(
            num_splits=recs_np["num_splits"][t],
            rec_leaf=recs_np["leaf"][t],
            rec_feat=recs_np["feat"][t],
            rec_thr=recs_np["thr"][t],
            rec_dbz=recs_np["dbz"][t],
            rec_gain=recs_np["gain"][t],
            rec_lval=recs_np["lval"][t],
            rec_rval=recs_np["rval"][t],
            rec_lcnt=recs_np["lcnt"][t],
            rec_rcnt=recs_np["rcnt"][t],
            rec_internal_value=recs_np["ival"][t],
        )


def eligible(config, train_set, objective, num_tree_per_iteration: int) -> bool:
    """Can the partitioned trainer drive this configuration?  (The rest
    falls back to the mask-based grower, which handles everything.)"""
    flag = os.environ.get("LIGHTGBM_TPU_PGROW", "")
    if flag == "0":
        return False
    if flag != "force" and jax.default_backend() != "tpu":
        return False
    if objective is None or num_tree_per_iteration != 1:
        return False
    if not getattr(objective, "rowwise", False):
        return False
    if config.tree_learner != "serial":
        return False
    if np.asarray(train_set.binned).dtype != np.uint8:
        return False
    if train_set.max_num_bin > 256:
        return False
    # bundling is built lazily, only once a partitioned run is plausible
    if hasattr(train_set, "ensure_bundles"):
        train_set.ensure_bundles(config)
    # the histogram kernel unrolls per-column one-hot builds; very wide
    # unbundled matrices blow up the Mosaic program (EFB normally keeps
    # G small — beyond this, the mask-based grower handles it)
    bundle = getattr(train_set, "bundle", None)
    cols = bundle.num_cols if bundle is not None else train_set.num_features
    if cols > 512:
        return False
    return True


def _build_bundle_meta(bundle, train_set, num_bins: int) -> BundleMeta:
    """Host-built device maps for the bundled histogram expansion."""
    f = train_set.num_features
    b = num_bins
    bh = int(bundle.max_col_bin)
    default_bin = np.asarray([m.default_bin for m in train_set.bin_mappers], np.int64)
    nb = np.asarray([m.num_bin for m in train_set.bin_mappers], np.int64)
    zero_slot = bundle.num_cols * bh  # appended all-zero row
    idx = np.full((f, b), zero_slot, np.int32)
    defmask = np.zeros((f, b), bool)
    for fe in range(f):
        if int(bundle.off_lo[fe]) == 0:
            # singleton raw column: every bin (incl. default) maps direct
            for bi in range(int(nb[fe])):
                idx[fe, bi] = int(bundle.col[fe]) * bh + bi
            continue
        for bi in range(int(nb[fe])):
            if bi == int(default_bin[fe]):
                defmask[fe, bi] = True
                continue
            v = int(bundle.off_lo[fe]) + bi - int(bundle.bias[fe])
            idx[fe, bi] = int(bundle.col[fe]) * bh + v
    return BundleMeta(
        col=jnp.asarray(bundle.col),
        off_lo=jnp.asarray(bundle.off_lo),
        off_hi=jnp.asarray(bundle.off_hi),
        bias=jnp.asarray(bundle.bias),
        idx=jnp.asarray(idx),
        defmask=jnp.asarray(defmask),
    )
