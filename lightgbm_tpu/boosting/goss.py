"""GOSS (Gradient-based One-Side Sampling) — counterpart of
src/boosting/goss.hpp (Bagging:126-198, BaggingHelper:79-124).

TPU-first: the per-thread reservoir loops become one device program —
|g*h| scoring, ``jax.lax.top_k`` for the keep set, a Bernoulli sample of
the rest with the (1-a)/b up-weighting folded into the gradient arrays.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.log import Log
from .gbdt import GBDT


class GOSS(GBDT):
    # the fused partitioned trainer implements GOSS natively (device
    # top_k + Bernoulli rest inside the chunk program); the hooks below
    # remain for the mask-grower fallback
    supports_partitioned = True
    # data-parallel GOSS samples per shard, matching the reference's
    # per-machine local TopK (goss.hpp Bagging over the local partition)
    supports_partitioned_data = True
    # out-of-core composes with GOSS for free: the |g*h| scoring, device
    # top_k and Bernoulli rest all run on the resident (K, N) vectors —
    # the sampled select mask reaches the streamed histograms unchanged,
    # even when the keep set spans chunk boundaries
    supports_ooc = True

    def init(self, config, train_set, objective, training_metrics=()):
        super().init(config, train_set, objective, training_metrics)
        if config.bagging_freq > 0 and config.bagging_fraction != 1.0:
            Log.fatal("Cannot use bagging in GOSS")
        Log.info("Using GOSS")
        if config.top_rate + config.other_rate >= 1.0:
            # whole data is used; plain gbdt behavior
            Log.warning("top_rate + other_rate >= 1.0; GOSS degenerates to GBDT")
        self._goss_key = jax.random.PRNGKey(config.bagging_seed)

    def _adjust_gradients(self, grad, hess):
        """GOSS sampling (goss.hpp:126-198): no sampling for the first
        1/learning_rate iterations, then keep top_rate by |g*h|, sample
        other_rate of the rest up-weighted by (n - top_k)/other_k."""
        cfg = self.config
        if self.iter < int(1.0 / cfg.learning_rate):
            self.select = jnp.ones(self.num_data, jnp.float32)
            return grad, hess
        n = self.num_data
        top_k = max(1, int(n * cfg.top_rate))
        other_k = max(1, int(n * cfg.other_rate))
        multiply = (n - top_k) / other_k

        score = jnp.sum(jnp.abs(grad * hess), axis=0)  # (N,)
        # exactly top_k rows (goss.hpp:96-124 ArgMaxAtK) — a >=threshold test
        # would keep extra rows on ties and silently raise the sampling rate
        _, top_idx = jax.lax.top_k(score, top_k)
        is_top = jnp.zeros(n, bool).at[top_idx].set(True)
        self._goss_key, sub = jax.random.split(self._goss_key)
        rest_all = n - top_k
        prob = other_k / max(rest_all, 1)
        sampled_rest = (~is_top) & (jax.random.uniform(sub, (n,)) < prob)
        self.select = (is_top | sampled_rest).astype(jnp.float32)
        scale = jnp.where(sampled_rest, multiply, 1.0).astype(grad.dtype)
        return grad * scale[None, :], hess * scale[None, :]

    def _bagging(self, iter_):
        # GOSS replaces bagging entirely (handled in _adjust_gradients)
        return

    # ------------------------------------------------------------------
    def export_train_state(self):
        """Checkpoint hook: the rest-sampling PRNGKey is chained
        (split per iteration), so resume must restore the exact key —
        reseeding from config would replay early draws.  (The fused
        partitioned GOSS path is stateless: it folds a base key with the
        iteration number inside the chunk program.)"""
        arrays, py = super().export_train_state()
        arrays["goss_key"] = np.asarray(self._goss_key)
        return arrays, py

    def import_train_state(self, arrays, py) -> None:
        super().import_train_state(arrays, py)
        self._goss_key = jnp.asarray(np.asarray(arrays["goss_key"]))

    def sub_model_name(self) -> str:
        return "tree"
