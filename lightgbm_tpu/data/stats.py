"""Pass-1 streaming statistics: per-feature mergeable sketches plus the
deterministic bin-construction row sample.

The sample (io/dataset.bin_sample_indices) is what find-bin actually
consumes — it makes streaming construction bit-identical to the
in-memory path.  The sketches are the *mergeable* superset the sample
cannot give: exact distinct-value/cardinality accounting per feature
(spilling to GK quantile summaries above a cap), collected chunk by
chunk with O(cap) memory and merged associatively across chunks or
hosts (parallel/collect.py), mirroring the reference's distributed
find-bin allgather.  They feed diagnostics (ingest trace gauges),
``BinMapper.find_bin_from_distinct`` for sketch-driven binning, and the
distributed ingest merge.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .sketch import (
    DEFAULT_CARDINALITY_CAP,
    DEFAULT_GK_EPS,
    CategoricalSketch,
    NumericSketch,
    deserialize_sketches,
    merge_sketch_lists,
    serialize_sketches,
)


class SampleCollector:
    """Collects the rows whose global index is in the (sorted) sample
    index set, with one forward cursor — the streaming equivalent of
    ``data[sample_indices]``.  With ``ncols`` known up front (dense
    files) rows land in a preallocated matrix; ``ncols=None`` (LibSVM,
    where width grows with the max seen index) keeps per-row vectors and
    pads at ``finish(ncols=...)``."""

    def __init__(self, sample_indices: np.ndarray, ncols: Optional[int] = None):
        self.indices = np.asarray(sample_indices, dtype=np.int64)
        self.rows: Optional[np.ndarray] = (
            np.empty((len(self.indices), ncols), dtype=np.float64)
            if ncols is not None else None
        )
        self._row_list: List[np.ndarray] = []
        self._cursor = 0

    def offer(self, start_row: int, chunk: np.ndarray) -> None:
        stop_row = start_row + chunk.shape[0]
        c = self._cursor
        while c < len(self.indices) and self.indices[c] < stop_row:
            row = chunk[self.indices[c] - start_row]
            if self.rows is not None:
                self.rows[c] = row
            else:
                self._row_list.append(np.asarray(row, np.float64))
            c += 1
        self._cursor = c

    def finish(self, ncols: Optional[int] = None,
               partial: bool = False) -> np.ndarray:
        """``partial=True`` accepts an incomplete collection and returns
        only the collected prefix — the bad-row-skip path, where rows
        sampled past the surviving row count never stream by."""
        if self._cursor != len(self.indices) and not partial:
            raise RuntimeError(
                f"sample collection incomplete: {self._cursor}/{len(self.indices)}"
            )
        if self.rows is not None:
            return self.rows[: self._cursor] if partial else self.rows
        width = ncols if ncols is not None else max(
            (len(r) for r in self._row_list), default=0
        )
        out = np.zeros((len(self._row_list), width), dtype=np.float64)
        for i, r in enumerate(self._row_list):
            out[i, : len(r)] = r[:width]
        return out


class SketchCollector:
    """Per-feature sketch bank, updated chunk by chunk.

    ``categorical`` holds FEATURE indices (post label/weight-drop) that
    get a CategoricalSketch; everything else is numeric.  Features may
    appear late (LibSVM width growth): a new column's sketch is
    back-filled with the zero count of every row already seen, so its
    totals match a column that was materialized from row 0."""

    def __init__(self, categorical: Optional[set] = None,
                 cap: int = DEFAULT_CARDINALITY_CAP,
                 eps: float = DEFAULT_GK_EPS):
        self.categorical = set(categorical or ())
        self.cap = cap
        self.eps = eps
        self.sketches: List[object] = []
        self.rows_seen = 0

    def _new_sketch(self, fidx: int):
        if fidx in self.categorical:
            return CategoricalSketch(cap=self.cap)
        return NumericSketch(cap=self.cap, eps=self.eps)

    def _grow_to(self, ncols: int) -> None:
        while len(self.sketches) < ncols:
            s = self._new_sketch(len(self.sketches))
            if self.rows_seen:
                # rows seen before this column appeared are implicit zeros
                s.total_cnt += self.rows_seen
                if isinstance(s, NumericSketch):
                    s.zero_cnt += self.rows_seen
                else:
                    s.counts[0] = s.counts.get(0, 0) + self.rows_seen
            self.sketches.append(s)

    def update(self, features: np.ndarray) -> None:
        """Fold one chunk's FEATURE matrix in (chunk-local width is
        allowed; missing trailing columns count as zeros)."""
        rows, width = features.shape
        self._grow_to(width)
        for f, sk in enumerate(self.sketches):
            if f < width:
                sk.update(features[:, f])
            else:
                sk.total_cnt += rows
                if isinstance(sk, NumericSketch):
                    sk.zero_cnt += rows
                else:
                    sk.counts[0] = sk.counts.get(0, 0) + rows
        self.rows_seen += rows

    # ------------------------------------------------------------------
    def summary(self) -> dict:
        """Trace-friendly digest: per-feature cardinality and spill
        state (what the ingest span attaches as gauges)."""
        spilled = sum(
            1 for s in self.sketches
            if getattr(s, "spilled", False)
        )
        cards = [s.cardinality() if isinstance(s, NumericSketch)
                 else len(s.counts) for s in self.sketches]
        return {
            "features": len(self.sketches),
            "spilled": spilled,
            "max_cardinality": int(max(cards, default=0)),
        }

    def merge_across_hosts(self) -> None:
        """Allgather + feature-wise merge of every host's sketch bank —
        the ingest mirror of distributed find-bin.  No-op when
        single-process."""
        import jax

        if jax.process_count() == 1:
            return
        from ..parallel.collect import allgather_bytes

        blobs = allgather_bytes(serialize_sketches(self.sketches))
        lists = [deserialize_sketches(b) for b in blobs]
        width = max(len(lst) for lst in lists)
        for lst in lists:
            # narrower hosts saw fewer LibSVM columns: widen with
            # zero-backfilled sketches so the feature-wise zip lines up
            rows = lst[0].total_cnt if lst else 0
            while len(lst) < width:
                s = self._new_sketch(len(lst))
                s.total_cnt += rows
                if isinstance(s, NumericSketch):
                    s.zero_cnt += rows
                else:
                    s.counts[0] = s.counts.get(0, 0) + rows
                lst.append(s)
        merged = merge_sketch_lists(lists)
        self.sketches = merged
        self.rows_seen = merged[0].total_cnt if merged else 0


def mappers_from_sketches(
    collector: SketchCollector,
    total_rows: int,
    config,
    categorical: Optional[Sequence[int]] = None,
) -> List:
    """Sketch-driven find-bin: feed each feature's (distinct, count)
    summary through ``BinMapper.find_bin_from_distinct``.  Bit-identical
    to in-memory find-bin over the same rows while every sketch is
    exact; approximate (bounded by the GK eps) after a spill.  Used when
    the full-data statistics, not a row sample, should define the bins
    (``bin_construct_sample_cnt >= num_rows`` streaming runs and the
    distributed ingest merge)."""
    from ..io.binning import CATEGORICAL, NUMERICAL, BinMapper

    cats = set(categorical or ())
    filter_cnt = int(config.min_data_in_leaf)
    mappers = []
    for f, sk in enumerate(collector.sketches):
        vals, cnts = sk.to_distinct_counts()
        # find-bin's contract: zeros (and NaNs, which FindBin folds into
        # the zero block) ride ``total - counts.sum()``.  NumericSketch
        # excludes both from its distinct map, so passing total_cnt
        # implies them exactly; CategoricalSketch keeps category 0
        # in-band, which FindBin's zero-insert logic accepts unchanged.
        m = BinMapper()
        m.find_bin_from_distinct(
            vals, cnts, sk.total_cnt, config.max_bin,
            config.min_data_in_bin, filter_cnt,
            CATEGORICAL if f in cats else NUMERICAL,
        )
        mappers.append(m)
    return mappers
