"""Out-of-core streaming dataset construction.

Counterpart of the reference's TextReader/PipelineReader + the sampling
half of DatasetLoader, rebuilt for bounded-memory ingest:

  ``reader``  chunked CSV/TSV/LibSVM parsers (one backend for streaming
              AND single-shot loads)
  ``sketch``  mergeable per-feature summaries (distinct-count maps
              spilling to GK quantile sketches, Misra-Gries categorical
              counts)
  ``stats``   pass-1 collection: deterministic bin-construction sample +
              sketch bank, with the cross-host merge
  ``ingest``  two-pass orchestration: Dataset(path) -> packed bin matrix
              without ever materializing the raw float matrix
  ``cache``   binary-cache format v2: uncompressed npz with a version +
              source-identity header and per-block CRCs, giving the
              trainer checksummed random access into the bin matrix
  ``prefetch`` double-buffered host->device chunk streaming (the
              out-of-core training pipe) with overlap accounting

See docs/DATA.md for the pipeline contract and memory budget knobs.
"""

from .cache import (  # noqa: F401
    CACHE_FORMAT_VERSION,
    CacheReader,
    build_cache_meta,
    open_cache_reader,
    read_cache_meta,
    stale_reason,
)
from .ingest import should_stream, stream_dataset  # noqa: F401
from .prefetch import (  # noqa: F401
    ArrayChunkSource,
    CacheChunkSource,
    ChunkPlan,
    ChunkPrefetcher,
    PrefetchStats,
)
from .reader import DenseChunkReader, LibSVMChunkReader, make_reader  # noqa: F401
from .sketch import CategoricalSketch, GKSketch, NumericSketch  # noqa: F401
from .stats import SampleCollector, SketchCollector  # noqa: F401

__all__ = [
    "should_stream", "stream_dataset",
    "DenseChunkReader", "LibSVMChunkReader", "make_reader",
    "GKSketch", "NumericSketch", "CategoricalSketch",
    "SampleCollector", "SketchCollector",
    "CACHE_FORMAT_VERSION", "CacheReader", "build_cache_meta",
    "open_cache_reader", "read_cache_meta", "stale_reason",
    "ChunkPlan", "ChunkPrefetcher", "PrefetchStats",
    "ArrayChunkSource", "CacheChunkSource",
]
