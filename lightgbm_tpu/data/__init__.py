"""Out-of-core streaming dataset construction.

Counterpart of the reference's TextReader/PipelineReader + the sampling
half of DatasetLoader, rebuilt for bounded-memory ingest:

  ``reader``  chunked CSV/TSV/LibSVM parsers (one backend for streaming
              AND single-shot loads)
  ``sketch``  mergeable per-feature summaries (distinct-count maps
              spilling to GK quantile sketches, Misra-Gries categorical
              counts)
  ``stats``   pass-1 collection: deterministic bin-construction sample +
              sketch bank, with the cross-host merge
  ``ingest``  two-pass orchestration: Dataset(path) -> packed bin matrix
              without ever materializing the raw float matrix

See docs/DATA.md for the pipeline contract and memory budget knobs.
"""

from .ingest import should_stream, stream_dataset  # noqa: F401
from .reader import DenseChunkReader, LibSVMChunkReader, make_reader  # noqa: F401
from .sketch import CategoricalSketch, GKSketch, NumericSketch  # noqa: F401
from .stats import SampleCollector, SketchCollector  # noqa: F401

__all__ = [
    "should_stream", "stream_dataset",
    "DenseChunkReader", "LibSVMChunkReader", "make_reader",
    "GKSketch", "NumericSketch", "CategoricalSketch",
    "SampleCollector", "SketchCollector",
]
