"""Chunked text readers — counterpart of the reference's TextReader /
PipelineReader (include/LightGBM/utils/text_reader.h,
pipeline_reader.h): stream a CSV/TSV/LibSVM file as bounded-size row
chunks so no caller ever needs the whole raw float matrix in memory.

One parsing code path: the legacy single-shot ``io/parser.load_text_file``
and the two-pass streaming ingest (data/ingest.py) both parse through
these readers, so dense and streaming loads cannot drift in dtype or
missing-value semantics.  Per-chunk parsing backend: the native
multithreaded parser (native/parser.cpp, reference-exact Atof) when a
compiler is available, else pandas' C engine — the SAME backend choice
for every chunk of a file, whatever the chunk size.

Chunking is by NON-BLANK lines (the native scanner and the reference's
TextReader both index non-blank lines), so chunk boundaries never change
parsed values: a file read as one chunk and as two hundred chunks yields
bit-identical rows.
"""

from __future__ import annotations

import io
import os
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..utils.log import Log

# default per-chunk raw-matrix budget when chunk_rows is not forced
DEFAULT_CHUNK_BYTES = 32 << 20  # 32 MiB of float64 cells per chunk
MIN_CHUNK_ROWS = 1024
MAX_CHUNK_ROWS = 1 << 21


def auto_chunk_rows(ncols: int, chunk_bytes: int = DEFAULT_CHUNK_BYTES) -> int:
    rows = chunk_bytes // max(8 * max(ncols, 1), 1)
    return int(min(max(rows, MIN_CHUNK_ROWS), MAX_CHUNK_ROWS))


def iter_line_blocks(path: str, chunk_lines: int,
                     skip_lines: int = 0) -> Iterator[Tuple[int, bytes, int]]:
    """Yield ``(start_line, block_bytes, num_lines)`` where lines are
    counted over NON-BLANK lines only and ``start_line`` is the index of
    the block's first non-blank line after ``skip_lines`` were dropped.
    Memory is bounded by one block."""
    buf: List[bytes] = []
    start = 0
    n_in_buf = 0
    skipped = 0
    with open(path, "rb") as f:
        for raw in f:
            if not raw.strip():
                continue
            if skipped < skip_lines:
                skipped += 1
                continue
            buf.append(raw)
            n_in_buf += 1
            if n_in_buf >= chunk_lines:
                yield start, b"".join(buf), n_in_buf
                start += n_in_buf
                buf, n_in_buf = [], 0
    if buf:
        yield start, b"".join(buf), n_in_buf


def count_data_lines(path: str, skip_lines: int = 0) -> int:
    """Cheap pass-0 row count: non-blank lines minus the header."""
    n = 0
    with open(path, "rb") as f:
        for raw in f:
            if raw.strip():
                n += 1
    return max(0, n - skip_lines)


def read_header_names(path: str, sep: Optional[str]) -> List[str]:
    """First non-blank line parsed as column names (quote-aware via
    pandas when the line carries quotes)."""
    with open(path, "rb") as f:
        first = b""
        for raw in f:
            if raw.strip():
                first = raw
                break
    text = first.decode("utf-8", "replace").strip()
    if '"' in text or "'" in text:
        import pandas as pd

        df = pd.read_csv(io.StringIO(text), sep=sep or r"\s+", header=0,
                         engine="python", nrows=0)
        return [str(c) for c in df.columns]
    sp = None if sep in (None, r"\s+") else sep
    return [t.strip() for t in text.split(sp)]


# ----------------------------------------------------------------------
def _native_parse_block(block: bytes, sep: str) -> Optional[np.ndarray]:
    """Parse one dense block with the native parser (reference-exact
    Atof).  Returns None to signal the pandas fallback."""
    from ..native import get_lib

    lib = get_lib()
    if lib is None:
        return None
    import ctypes

    sep_b = b" " if sep == r"\s+" else sep.encode()
    handle = lib.ltpu_scan(block, len(block))
    try:
        nrows = ctypes.c_int64()
        ncols = ctypes.c_int()
        if lib.ltpu_dims_csv(handle, block, sep_b, 0,
                             ctypes.byref(nrows), ctypes.byref(ncols)) != 0:
            return None
        mat = np.empty((nrows.value, ncols.value), dtype=np.float64)
        rc = lib.ltpu_parse_csv(
            handle, block, sep_b, 0,
            mat.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            nrows.value, ncols.value, min(os.cpu_count() or 1, 16),
        )
        if rc != 0:
            return None
        return mat
    finally:
        lib.ltpu_scan_free(handle)


def _pandas_parse_block(block: bytes, sep: str) -> np.ndarray:
    import pandas as pd

    df = pd.read_csv(
        io.BytesIO(block), sep=sep, header=None,
        engine="c" if sep != r"\s+" else "python",
    )
    return df.to_numpy(dtype=np.float64)


class DenseChunkReader:
    """Chunked reader for CSV/TSV files.  Every chunk is the FULL column
    set (label/weight/group columns included) — column-role slicing is
    the caller's job, exactly like the reference's parser emitting all
    (idx, value) pairs."""

    def __init__(self, path: str, sep: str, has_header: bool,
                 chunk_rows: Optional[int] = None):
        self.path = path
        self.sep = sep
        self.has_header = has_header
        self.header_names: Optional[List[str]] = (
            read_header_names(path, sep) if has_header else None
        )
        self._chunk_rows = chunk_rows
        self._num_rows: Optional[int] = None
        self._ncols: Optional[int] = None

    # -- pass 0 --------------------------------------------------------
    def count_rows(self) -> int:
        if self._num_rows is None:
            self._num_rows = count_data_lines(
                self.path, skip_lines=1 if self.has_header else 0
            )
        return self._num_rows

    @property
    def ncols(self) -> int:
        if self._ncols is None:
            for _, chunk in self.iter_chunks(probe_rows=MIN_CHUNK_ROWS):
                self._ncols = chunk.shape[1]
                break
            if self._ncols is None:
                Log.fatal("Data file %s is empty", self.path)
        return self._ncols

    def chunk_rows(self) -> int:
        if self._chunk_rows:
            return int(self._chunk_rows)
        return auto_chunk_rows(self.ncols)

    # -- chunk iteration ----------------------------------------------
    def parse_block(self, block: bytes) -> np.ndarray:
        mat = _native_parse_block(block, self.sep)
        if mat is None:
            mat = _pandas_parse_block(block, self.sep)
        if self._ncols is None:
            self._ncols = mat.shape[1]
        elif mat.shape[1] != self._ncols:
            Log.fatal(
                "Inconsistent column count in %s: chunk has %d, expected %d",
                self.path, mat.shape[1], self._ncols,
            )
        return mat

    def iter_chunks(self, probe_rows: Optional[int] = None
                    ) -> Iterator[Tuple[int, np.ndarray]]:
        """Yield ``(start_row, (rows, ncols) float64 matrix)``."""
        rows = probe_rows or self.chunk_rows()
        skip = 1 if self.has_header else 0
        for start, block, _ in iter_line_blocks(self.path, rows, skip):
            yield start, self.parse_block(block)

    def read_all(self) -> Tuple[np.ndarray, Optional[List[str]]]:
        """Single-shot load (legacy io/parser path): one chunk spanning
        the file, so the memory profile matches the old whole-file
        parse."""
        chunks = [c for _, c in self.iter_chunks(probe_rows=MAX_CHUNK_ROWS)]
        if not chunks:
            Log.fatal("Data file %s is empty", self.path)
        mat = chunks[0] if len(chunks) == 1 else np.vstack(chunks)
        return mat, self.header_names


# ----------------------------------------------------------------------
class LibSVMChunkReader:
    """Chunked LibSVM reader.  Chunks are ``(features, labels)``; the
    global feature count is the max seen index + 1, discovered during
    pass 1 (``grow_ncols``) and then frozen for pass 2 via ``set_ncols``."""

    def __init__(self, path: str, chunk_rows: Optional[int] = None):
        self.path = path
        self.has_header = False
        self.header_names = None
        self._chunk_rows = chunk_rows
        self._num_rows: Optional[int] = None
        self.ncols_seen = 0  # grows as chunks are parsed

    def count_rows(self) -> int:
        if self._num_rows is None:
            self._num_rows = count_data_lines(self.path)
        return self._num_rows

    def chunk_rows(self) -> int:
        if self._chunk_rows:
            return int(self._chunk_rows)
        return auto_chunk_rows(32)

    def parse_block(self, block: bytes) -> Tuple[np.ndarray, np.ndarray]:
        mat_lab = self._native_parse(block)
        if mat_lab is None:
            mat_lab = self._python_parse(block)
        feats, labels = mat_lab
        self.ncols_seen = max(self.ncols_seen, feats.shape[1])
        return feats, labels

    def _native_parse(self, block: bytes):
        from ..native import get_lib

        lib = get_lib()
        if lib is None:
            return None
        import ctypes

        handle = lib.ltpu_scan(block, len(block))
        try:
            nrows = ctypes.c_int64()
            ncols = ctypes.c_int()
            if lib.ltpu_dims_libsvm(handle, block, ctypes.byref(nrows),
                                    ctypes.byref(ncols)) != 0:
                return None
            mat = np.zeros((nrows.value, ncols.value), dtype=np.float64)
            labels = np.empty(nrows.value, dtype=np.float64)
            pd_ = ctypes.POINTER(ctypes.c_double)
            rc = lib.ltpu_parse_libsvm(
                handle, block, mat.ctypes.data_as(pd_),
                labels.ctypes.data_as(pd_),
                nrows.value, ncols.value, min(os.cpu_count() or 1, 16),
            )
            if rc != 0:
                return None
            return mat, labels.astype(np.float32)
        finally:
            lib.ltpu_scan_free(handle)

    def _python_parse(self, block: bytes) -> Tuple[np.ndarray, np.ndarray]:
        labels: List[float] = []
        rows: List[List[Tuple[int, float]]] = []
        max_idx = -1
        for line in block.split(b"\n"):
            toks = line.split()
            if not toks:
                continue
            labels.append(float(toks[0]))
            row: List[Tuple[int, float]] = []
            for t in toks[1:]:
                i, v = t.split(b":")
                idx = int(i)
                row.append((idx, float(v)))
                max_idx = max(max_idx, idx)
            rows.append(row)
        mat = np.zeros((len(rows), max_idx + 1), dtype=np.float64)
        for r, row in enumerate(rows):
            for idx, v in row:
                mat[r, idx] = v
        return mat, np.asarray(labels, dtype=np.float32)

    def iter_chunks(self) -> Iterator[Tuple[int, np.ndarray, np.ndarray]]:
        """Yield ``(start_row, features, labels)``.  Feature matrices are
        chunk-local width; callers pad to a global width (``ncols_seen``
        after a full pass, or a frozen pass-1 count)."""
        for start, block, _ in iter_line_blocks(self.path, self.chunk_rows()):
            feats, labels = self.parse_block(block)
            yield start, feats, labels

    def read_all(self) -> Tuple[np.ndarray, np.ndarray]:
        feats_list, labels_list = [], []
        for _, feats, labels in self.iter_chunks():
            feats_list.append(feats)
            labels_list.append(labels)
        if not feats_list:
            Log.fatal("Data file %s is empty", self.path)
        width = self.ncols_seen
        padded = [
            np.pad(f, ((0, 0), (0, width - f.shape[1]))) if f.shape[1] < width else f
            for f in feats_list
        ]
        return np.vstack(padded), np.concatenate(labels_list)


def make_reader(path: str, chunk_rows: Optional[int] = None,
                has_header: bool = False):
    """Sniff the format (io/parser.sniff_format) and build the matching
    chunked reader."""
    from ..io.parser import sniff_format

    kind, sep = sniff_format(path)
    if kind == "libsvm":
        return LibSVMChunkReader(path, chunk_rows=chunk_rows)
    return DenseChunkReader(path, sep, has_header, chunk_rows=chunk_rows)
