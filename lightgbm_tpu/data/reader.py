"""Chunked text readers — counterpart of the reference's TextReader /
PipelineReader (include/LightGBM/utils/text_reader.h,
pipeline_reader.h): stream a CSV/TSV/LibSVM file as bounded-size row
chunks so no caller ever needs the whole raw float matrix in memory.

One parsing code path: the legacy single-shot ``io/parser.load_text_file``
and the two-pass streaming ingest (data/ingest.py) both parse through
these readers, so dense and streaming loads cannot drift in dtype or
missing-value semantics.  Per-chunk parsing backend: the native
multithreaded parser (native/parser.cpp, reference-exact Atof) when a
compiler is available, else pandas' C engine — the SAME backend choice
for every chunk of a file, whatever the chunk size.

Chunking is by NON-BLANK lines (the native scanner and the reference's
TextReader both index non-blank lines), so chunk boundaries never change
parsed values: a file read as one chunk and as two hundred chunks yields
bit-identical rows.
"""

from __future__ import annotations

import io
import os
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..utils.log import Log

# default per-chunk raw-matrix budget when chunk_rows is not forced
DEFAULT_CHUNK_BYTES = 32 << 20  # 32 MiB of float64 cells per chunk
MIN_CHUNK_ROWS = 1024
MAX_CHUNK_ROWS = 1 << 21

# tokens the salvage parser treats as NaN (pandas C-engine default NA
# set, lowercased; the fast paths keep their own identical semantics)
_NA_TOKENS = frozenset({
    "", "#n/a", "#n/a n/a", "#na", "-1.#ind", "-1.#qnan", "-nan",
    "1.#ind", "1.#qnan", "<na>", "n/a", "na", "null", "nan", "none",
})


def _parse_value_token(tok: str) -> Optional[float]:
    """One field -> float (NaN for the NA set), or None if malformed."""
    t = tok.strip()
    if t.lower() in _NA_TOKENS:
        return float("nan")
    try:
        return float(t)
    except ValueError:
        return None


def _report_bad_rows(reader, bad: List[Tuple[int, str]]) -> None:
    """Apply ``reader.bad_row_policy`` to the triaged rows: 'error'
    fails loudly naming the file and 1-based data-row number; 'skip'
    counts them (obs ``data.bad_rows``) and warns once per block."""
    if not bad:
        return
    from ..obs import tracer

    lineno, reason = bad[0]
    if reader.bad_row_policy != "skip":
        Log.fatal(
            "%s: malformed data row %d (%s)%s — set bad_row_policy=skip "
            "to drop such rows",
            reader.path, lineno, reason,
            f" and {len(bad) - 1} more" if len(bad) > 1 else "",
        )
    reader.bad_rows += len(bad)
    tracer.counter("data.bad_rows", len(bad),
                   file=os.path.basename(reader.path))
    Log.warning(
        "%s: skipped %d malformed data row(s); first: row %d (%s)",
        reader.path, len(bad), lineno, reason,
    )


def auto_chunk_rows(ncols: int, chunk_bytes: int = DEFAULT_CHUNK_BYTES) -> int:
    rows = chunk_bytes // max(8 * max(ncols, 1), 1)
    return int(min(max(rows, MIN_CHUNK_ROWS), MAX_CHUNK_ROWS))


def iter_line_blocks(path: str, chunk_lines: int,
                     skip_lines: int = 0) -> Iterator[Tuple[int, bytes, int]]:
    """Yield ``(start_line, block_bytes, num_lines)`` where lines are
    counted over NON-BLANK lines only and ``start_line`` is the index of
    the block's first non-blank line after ``skip_lines`` were dropped.
    Memory is bounded by one block."""
    buf: List[bytes] = []
    start = 0
    n_in_buf = 0
    skipped = 0
    with open(path, "rb") as f:
        for raw in f:
            if not raw.strip():
                continue
            if skipped < skip_lines:
                skipped += 1
                continue
            buf.append(raw)
            n_in_buf += 1
            if n_in_buf >= chunk_lines:
                yield start, b"".join(buf), n_in_buf
                start += n_in_buf
                buf, n_in_buf = [], 0
    if buf:
        yield start, b"".join(buf), n_in_buf


def count_data_lines(path: str, skip_lines: int = 0) -> int:
    """Cheap pass-0 row count: non-blank lines minus the header."""
    n = 0
    with open(path, "rb") as f:
        for raw in f:
            if raw.strip():
                n += 1
    return max(0, n - skip_lines)


def read_header_names(path: str, sep: Optional[str]) -> List[str]:
    """First non-blank line parsed as column names (quote-aware via
    pandas when the line carries quotes)."""
    with open(path, "rb") as f:
        first = b""
        for raw in f:
            if raw.strip():
                first = raw
                break
    text = first.decode("utf-8", "replace").strip()
    if '"' in text or "'" in text:
        import pandas as pd

        df = pd.read_csv(io.StringIO(text), sep=sep or r"\s+", header=0,
                         engine="python", nrows=0)
        return [str(c) for c in df.columns]
    sp = None if sep in (None, r"\s+") else sep
    return [t.strip() for t in text.split(sp)]


# ----------------------------------------------------------------------
def _native_parse_block(block: bytes, sep: str) -> Optional[np.ndarray]:
    """Parse one dense block with the native parser (reference-exact
    Atof).  Returns None to signal the pandas fallback."""
    from ..native import get_lib

    lib = get_lib()
    if lib is None:
        return None
    import ctypes

    sep_b = b" " if sep == r"\s+" else sep.encode()
    handle = lib.ltpu_scan(block, len(block))
    try:
        nrows = ctypes.c_int64()
        ncols = ctypes.c_int()
        if lib.ltpu_dims_csv(handle, block, sep_b, 0,
                             ctypes.byref(nrows), ctypes.byref(ncols)) != 0:
            return None
        mat = np.empty((nrows.value, ncols.value), dtype=np.float64)
        rc = lib.ltpu_parse_csv(
            handle, block, sep_b, 0,
            mat.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            nrows.value, ncols.value, min(os.cpu_count() or 1, 16),
        )
        if rc != 0:
            return None
        return mat
    finally:
        lib.ltpu_scan_free(handle)


def _pandas_parse_block(block: bytes, sep: str) -> np.ndarray:
    import pandas as pd

    df = pd.read_csv(
        io.BytesIO(block), sep=sep, header=None,
        engine="c" if sep != r"\s+" else "python",
    )
    return df.to_numpy(dtype=np.float64)


class DenseChunkReader:
    """Chunked reader for CSV/TSV files.  Every chunk is the FULL column
    set (label/weight/group columns included) — column-role slicing is
    the caller's job, exactly like the reference's parser emitting all
    (idx, value) pairs."""

    def __init__(self, path: str, sep: str, has_header: bool,
                 chunk_rows: Optional[int] = None,
                 bad_row_policy: str = "error"):
        self.path = path
        self.sep = sep
        self.has_header = has_header
        self.header_names: Optional[List[str]] = (
            read_header_names(path, sep) if has_header else None
        )
        self._chunk_rows = chunk_rows
        self._num_rows: Optional[int] = None
        self._ncols: Optional[int] = None
        self.bad_row_policy = bad_row_policy
        self.bad_rows = 0  # cumulative skipped rows (policy='skip')

    # -- pass 0 --------------------------------------------------------
    def count_rows(self) -> int:
        if self._num_rows is None:
            self._num_rows = count_data_lines(
                self.path, skip_lines=1 if self.has_header else 0
            )
        return self._num_rows

    @property
    def ncols(self) -> int:
        if self._ncols is None:
            for _, chunk in self.iter_chunks(probe_rows=MIN_CHUNK_ROWS):
                self._ncols = chunk.shape[1]
                break
            if self._ncols is None:
                Log.fatal("Data file %s is empty", self.path)
        return self._ncols

    def chunk_rows(self) -> int:
        if self._chunk_rows:
            return int(self._chunk_rows)
        return auto_chunk_rows(self.ncols)

    # -- chunk iteration ----------------------------------------------
    def parse_block(self, block: bytes, start_row: int = 0) -> np.ndarray:
        """Parse one block.  The fast paths (native parser, pandas C
        engine) are tried first and are byte-for-byte what a clean file
        always gets; only when a block fails to parse — or parses at a
        width inconsistent with the rest of the file — does the per-line
        salvage pass run, applying ``bad_row_policy``: 'error' fails
        loudly naming the file and 1-based data-row number, 'skip' drops
        the malformed rows and counts them (obs ``data.bad_rows``)."""
        mat: Optional[np.ndarray] = None
        try:
            mat = _native_parse_block(block, self.sep)
            if mat is None:
                mat = _pandas_parse_block(block, self.sep)
        except Exception:
            mat = None
        if mat is not None and self._ncols is not None \
                and mat.shape[1] != self._ncols:
            mat = None  # width flip mid-file: let salvage name the rows
        if mat is None:
            mat = self._salvage_block(block, start_row)
        if self._ncols is None and mat.shape[1] > 0:
            self._ncols = mat.shape[1]
        return mat

    def _salvage_block(self, block: bytes, start_row: int) -> np.ndarray:
        """Per-line triage of a block the fast path rejected.  The
        surviving lines are re-joined and parsed through the SAME fast
        path (native parser / pandas C engine), so their values are
        bit-identical to a file that never had the bad rows; the
        token-level parse is used for validation only (and as a last
        resort if the fast path rejects even the surviving lines)."""
        sep = None if self.sep in (None, r"\s+") else self.sep
        expected = self._ncols
        rows: List[List[float]] = []
        good_lines: List[bytes] = []
        bad: List[Tuple[int, str]] = []  # (1-based data-row number, reason)
        for raw in block.split(b"\n"):
            if not raw.strip():
                continue
            lineno = start_row + len(rows) + len(bad) + 1
            toks = raw.decode("utf-8", "replace").strip().split(sep)
            vals = [_parse_value_token(t) for t in toks]
            if any(v is None for v in vals):
                j = next(k for k, v in enumerate(vals) if v is None)
                bad.append((lineno, f"unparsable value {toks[j]!r} "
                                    f"in field {j + 1}"))
                continue
            if expected is None:
                expected = len(vals)
            if len(vals) != expected:
                bad.append((lineno, f"{len(vals)} fields, expected {expected}"))
                continue
            rows.append(vals)  # type: ignore[arg-type]
            good_lines.append(raw if raw.endswith(b"\n") else raw + b"\n")
        _report_bad_rows(self, bad)
        if not rows:
            return np.empty((0, expected or 0), dtype=np.float64)
        good_block = b"".join(good_lines)
        try:
            mat = _native_parse_block(good_block, self.sep)
            if mat is None:
                mat = _pandas_parse_block(good_block, self.sep)
            if mat.shape == (len(rows), expected):
                return mat
        except Exception:
            pass
        # the fast path rejects even the validated lines (e.g. quoting
        # the naive splitter misread): fall back to the token values
        return np.asarray(rows, dtype=np.float64)

    def iter_chunks(self, probe_rows: Optional[int] = None
                    ) -> Iterator[Tuple[int, np.ndarray]]:
        """Yield ``(start_row, (rows, ncols) float64 matrix)``.
        ``start_row`` counts EMITTED rows, so with ``bad_row_policy=
        'skip'`` downstream offsets stay dense; on a clean file it is
        identical to the raw non-blank line index."""
        rows = probe_rows or self.chunk_rows()
        skip = 1 if self.has_header else 0
        emitted = 0
        for start, block, _ in iter_line_blocks(self.path, rows, skip):
            mat = self.parse_block(block, start_row=start)
            if mat.shape[0] == 0:
                continue
            yield emitted, mat
            emitted += mat.shape[0]

    def read_all(self) -> Tuple[np.ndarray, Optional[List[str]]]:
        """Single-shot load (legacy io/parser path): one chunk spanning
        the file, so the memory profile matches the old whole-file
        parse."""
        chunks = [c for _, c in self.iter_chunks(probe_rows=MAX_CHUNK_ROWS)]
        if not chunks:
            Log.fatal("Data file %s is empty", self.path)
        mat = chunks[0] if len(chunks) == 1 else np.vstack(chunks)
        return mat, self.header_names


# ----------------------------------------------------------------------
class LibSVMChunkReader:
    """Chunked LibSVM reader.  Chunks are ``(features, labels)``; the
    global feature count is the max seen index + 1, discovered during
    pass 1 (``grow_ncols``) and then frozen for pass 2 via ``set_ncols``."""

    def __init__(self, path: str, chunk_rows: Optional[int] = None,
                 bad_row_policy: str = "error"):
        self.path = path
        self.has_header = False
        self.header_names = None
        self._chunk_rows = chunk_rows
        self._num_rows: Optional[int] = None
        self.ncols_seen = 0  # grows as chunks are parsed
        self.bad_row_policy = bad_row_policy
        self.bad_rows = 0

    def count_rows(self) -> int:
        if self._num_rows is None:
            self._num_rows = count_data_lines(self.path)
        return self._num_rows

    def chunk_rows(self) -> int:
        if self._chunk_rows:
            return int(self._chunk_rows)
        return auto_chunk_rows(32)

    def parse_block(self, block: bytes,
                    start_row: int = 0) -> Tuple[np.ndarray, np.ndarray]:
        mat_lab = self._native_parse(block)
        if mat_lab is None:
            good_block = self._scan_lines(block, start_row)
            if good_block is not block:
                # surviving lines go back through the SAME fast path so
                # their values match a file without the bad rows
                mat_lab = self._native_parse(good_block)
            if mat_lab is None:
                mat_lab = self._python_parse(good_block)
        feats, labels = mat_lab
        self.ncols_seen = max(self.ncols_seen, feats.shape[1])
        return feats, labels

    def _scan_lines(self, block: bytes, start_row: int) -> bytes:
        """Validate each line; apply ``bad_row_policy`` to the broken
        ones.  Returns the block itself when every line is fine, else
        the surviving lines re-joined."""
        good: List[bytes] = []
        bad: List[Tuple[int, str]] = []
        n_seen = 0
        for raw in block.split(b"\n"):
            toks = raw.split()
            if not toks:
                continue
            n_seen += 1
            lineno = start_row + n_seen
            try:
                float(toks[0])
                for t in toks[1:]:
                    i, v = t.split(b":")
                    int(i), float(v)
            except ValueError as e:
                bad.append((lineno, str(e)))
                continue
            good.append(raw if raw.endswith(b"\n") else raw + b"\n")
        if not bad:
            return block
        _report_bad_rows(self, bad)
        return b"".join(good)

    def _native_parse(self, block: bytes):
        from ..native import get_lib

        lib = get_lib()
        if lib is None:
            return None
        import ctypes

        handle = lib.ltpu_scan(block, len(block))
        try:
            nrows = ctypes.c_int64()
            ncols = ctypes.c_int()
            if lib.ltpu_dims_libsvm(handle, block, ctypes.byref(nrows),
                                    ctypes.byref(ncols)) != 0:
                return None
            mat = np.zeros((nrows.value, ncols.value), dtype=np.float64)
            labels = np.empty(nrows.value, dtype=np.float64)
            pd_ = ctypes.POINTER(ctypes.c_double)
            rc = lib.ltpu_parse_libsvm(
                handle, block, mat.ctypes.data_as(pd_),
                labels.ctypes.data_as(pd_),
                nrows.value, ncols.value, min(os.cpu_count() or 1, 16),
            )
            if rc != 0:
                return None
            return mat, labels.astype(np.float32)
        finally:
            lib.ltpu_scan_free(handle)

    def _python_parse(self, block: bytes) -> Tuple[np.ndarray, np.ndarray]:
        labels: List[float] = []
        rows: List[List[Tuple[int, float]]] = []
        max_idx = -1
        for line in block.split(b"\n"):
            toks = line.split()
            if not toks:
                continue
            labels.append(float(toks[0]))
            row: List[Tuple[int, float]] = []
            for t in toks[1:]:
                i, v = t.split(b":")
                idx = int(i)
                row.append((idx, float(v)))
                max_idx = max(max_idx, idx)
            rows.append(row)
        mat = np.zeros((len(rows), max_idx + 1), dtype=np.float64)
        for r, row in enumerate(rows):
            for idx, v in row:
                mat[r, idx] = v
        return mat, np.asarray(labels, dtype=np.float32)

    def iter_chunks(self) -> Iterator[Tuple[int, np.ndarray, np.ndarray]]:
        """Yield ``(start_row, features, labels)``.  Feature matrices are
        chunk-local width; callers pad to a global width (``ncols_seen``
        after a full pass, or a frozen pass-1 count).  ``start_row``
        counts emitted rows (dense under ``bad_row_policy='skip'``)."""
        emitted = 0
        for start, block, _ in iter_line_blocks(self.path, self.chunk_rows()):
            feats, labels = self.parse_block(block, start_row=start)
            if feats.shape[0] == 0:
                continue
            yield emitted, feats, labels
            emitted += feats.shape[0]

    def read_all(self) -> Tuple[np.ndarray, np.ndarray]:
        feats_list, labels_list = [], []
        for _, feats, labels in self.iter_chunks():
            feats_list.append(feats)
            labels_list.append(labels)
        if not feats_list:
            Log.fatal("Data file %s is empty", self.path)
        width = self.ncols_seen
        padded = [
            np.pad(f, ((0, 0), (0, width - f.shape[1]))) if f.shape[1] < width else f
            for f in feats_list
        ]
        return np.vstack(padded), np.concatenate(labels_list)


def make_reader(path: str, chunk_rows: Optional[int] = None,
                has_header: bool = False, bad_row_policy: str = "error"):
    """Sniff the format (io/parser.sniff_format) and build the matching
    chunked reader."""
    from ..io.parser import sniff_format

    kind, sep = sniff_format(path)
    if kind == "libsvm":
        return LibSVMChunkReader(path, chunk_rows=chunk_rows,
                                 bad_row_policy=bad_row_policy)
    return DenseChunkReader(path, sep, has_header, chunk_rows=chunk_rows,
                            bad_row_policy=bad_row_policy)
