"""ChunkSource: the streaming seam shared by every out-of-core learner.

PR 8's ``boosting/ooc.py`` buried three reusable pieces inside its
serial trainer: picking a chunk source for a dataset, running the
prefetch ring over a chunk plan, and the per-chunk histogram fold /
split-application loops.  This module hoists them into one seam so the
serial OocTrainer and the distributed rank-sharded trainer
(``boosting/oocdist.py``) consume the identical streaming machinery:

  ``make_chunk_source``  dataset -> chunk source (CRC-checked binary
                         cache via data/cache.py when the dataset was
                         loaded from one, else the host/memmap array)
  ``ChunkStream``        a (source, plan, depth, stats) bundle whose
                         ``stream()`` runs the bounded prefetch ring of
                         data/prefetch.py — one object owns a rank's
                         whole streaming configuration
  ``ChunkFolder``        the fold algebra over a ChunkStream: the root
                         histogram fold, the one-pass split fold that
                         partitions ``leaf_id`` and builds BOTH child
                         histograms, the smaller-child-direct /
                         larger-by-subtraction rule, and the streamed
                         ``predict_binned`` score pass

Bit-identity contract (inherited verbatim from boosting/ooc.py, whose
parity suite pins it): with chunk boundaries on ``ROW_BLOCK`` multiples
the f32 folds reproduce the in-memory scan's left-to-right block adds
bit for bit, and integer (quantized-training) folds are associative —
identical for ANY chunk grid and, summed across ranks, for ANY rank
count.  The folder contains no cross-rank logic; distributed callers
exchange its per-rank partials themselves (the fold algebra composes
with allreduce exactly because the integer partials are associative).
"""

from __future__ import annotations

import numpy as np

from ..ops.histogram import ROW_BLOCK
from ..ops.ooc import (
    root_hist_chunk,
    scatter_add_slice,
    split_chunk,
    subtract_sibling,
)
from ..ops.predict import predict_binned
from .prefetch import (
    ArrayChunkSource,
    CacheChunkSource,
    ChunkPlan,
    ChunkPrefetcher,
    PrefetchStats,
)

__all__ = [
    "ArrayChunkSource",
    "CacheChunkSource",
    "ChunkFolder",
    "ChunkPlan",
    "ChunkStream",
    "PrefetchStats",
    "make_chunk_source",
]


def make_chunk_source(train_set):
    """Chunk source for a constructed dataset: prefer checksummed reads
    straight from the v2 binary cache the dataset was loaded from; any
    other dataset streams from its host (or memmapped) ``binned``
    array."""
    path = getattr(train_set, "cache_path", None)
    if path:
        from .cache import open_cache_reader

        reader = open_cache_reader(path)
        if reader is not None:
            return CacheChunkSource(reader)
    return ArrayChunkSource(np.asarray(train_set.binned))


class ChunkStream:
    """One rank's streaming configuration: a chunk source, the grid over
    its rows, the prefetch depth, and the accumulated overlap stats.

    ``stream()`` yields ``(index, start, stop, device_chunk)`` in
    schedule order through the bounded prefetch ring; every pass shares
    ``stats`` so fetch/stall accounting accumulates across trees."""

    def __init__(self, source, plan: ChunkPlan, depth: int = 2,
                 stats: PrefetchStats | None = None):
        self.source = source
        self.plan = plan
        self.depth = max(int(depth), 1)
        self.stats = stats if stats is not None else PrefetchStats()

    def stream(self):
        return ChunkPrefetcher(self.source, self.plan, self.depth,
                               self.stats).stream()

    def describe(self) -> str:
        return self.source.describe()

    def fingerprint(self) -> str:
        return self.plan.fingerprint()


class ChunkFolder:
    """The per-chunk fold algebra over a :class:`ChunkStream`.

    Stateless beyond its (stream, shapes) configuration: every method
    takes the device-resident row vectors and returns fresh carries, so
    serial and distributed trainers replay their host-driven loops
    through the same folds.  ``quantized`` folds (integer grad/hess)
    produce exact int32 partials; f32 folds keep the ROW_BLOCK-aligned
    block-add order."""

    def __init__(self, stream: ChunkStream, num_features: int,
                 num_bins: int, row_block: int = ROW_BLOCK):
        self.stream = stream
        self.num_features = int(num_features)
        self.num_bins = int(num_bins)
        self.row_block = int(row_block)

    def fold_root(self, grad, hess, select):
        """One streamed pass folding every chunk into the root
        histogram; (F, B, 3) int32 under integer gradients, f32
        otherwise (matching ``build_histogram``'s in-memory dtypes)."""
        import jax.numpy as jnp

        quant = jnp.issubdtype(grad.dtype, jnp.integer)
        hist = jnp.zeros((self.num_features, self.num_bins, 3),
                         jnp.int32 if quant else jnp.float32)
        for _i, start, _stop, chunk in self.stream.stream():
            hist = root_hist_chunk(hist, chunk, grad, hess, select,
                                   np.int32(start), self.num_bins,
                                   self.row_block)
        return hist

    def fold_split(self, leaf_id, parent_hist, grad, hess, select, feat,
                   zero_bin, dbz, thr, is_cat, bl, rl):
        """One streamed pass applying one split: partition ``leaf_id``
        by the split predicate and fold BOTH children's histogram
        partials (2x flops for 1x transfer — transfers bound the
        out-of-core regime).  Returns ``(leaf_id, hist_l, hist_r,
        n_left)`` with ``n_left`` the (local) left-row count."""
        import jax.numpy as jnp

        hist_l = jnp.zeros_like(parent_hist)
        hist_r = jnp.zeros_like(parent_hist)
        n_left = jnp.zeros((), jnp.int32)
        for _i, start, _stop, chunk in self.stream.stream():
            leaf_id, hist_l, hist_r, n_left = split_chunk(
                leaf_id, hist_l, hist_r, n_left, chunk, grad, hess,
                select, np.int32(start), np.int32(feat),
                np.int32(zero_bin), np.int32(dbz), np.int32(thr),
                bool(is_cat), np.int32(bl), np.int32(rl), self.num_bins,
                self.row_block,
            )
        return leaf_id, hist_l, hist_r, n_left

    @staticmethod
    def pick_children(parent_hist, hist_l, hist_r, n_left: int,
                      n_right: int):
        """The smaller-child-direct / larger-by-subtraction rule
        (FeatureHistogram::Subtract): keep the DIRECT accumulation for
        the smaller child and derive the larger as parent - smaller,
        matching the in-memory grower's numerics.  ``n_left``/``n_right``
        are the row counts the rule keys on — LOCAL rows for a serial
        trainer, GLOBAL rows for a distributed one (every rank must pick
        the same child).  Returns ``(left_hist, right_hist)``."""
        if n_left < n_right:
            return hist_l, subtract_sibling(parent_hist, hist_l)
        return subtract_sibling(parent_hist, hist_r), hist_r

    def streamed_scores(self, score_k, arrays):
        """Streamed ``predict_binned`` over the chunk grid: the
        rollback / DART score path when the matrix is not
        device-resident.  The traversal is per-row, so chunking is
        exact.  Stacked arrays carrying linear-leaf planes
        (``leaf_feat_inner`` et al., model/ensemble.py) route through
        the linear traversal; the linear term needs the bin-value LUT
        under ``arrays["value_lut"]``."""
        linear = "leaf_feat_inner" in arrays
        if linear:
            from ..tree.linear import predict_linear_binned
        for _i, start, _stop, chunk in self.stream.stream():
            if linear:
                delta = predict_linear_binned(
                    chunk,
                    arrays["split_feature_inner"],
                    arrays["threshold_bin"],
                    arrays["zero_bin"],
                    arrays["default_bin_for_zero"],
                    arrays["is_categorical"],
                    arrays["left_child"],
                    arrays["right_child"],
                    arrays["leaf_value"],
                    arrays["leaf_feat_inner"],
                    arrays["leaf_feat_valid"],
                    arrays["leaf_coeff"],
                    arrays["leaf_const"],
                    arrays["leaf_is_linear"],
                    arrays["value_lut"],
                )
            else:
                delta = predict_binned(
                    chunk,
                    arrays["split_feature_inner"],
                    arrays["threshold_bin"],
                    arrays["zero_bin"],
                    arrays["default_bin_for_zero"],
                    arrays["is_categorical"],
                    arrays["left_child"],
                    arrays["right_child"],
                    arrays["leaf_value"],
                )
            score_k = scatter_add_slice(score_k, delta, np.int32(start))
        return score_k

    # -- linear-leaf folds (tree/linear.py LeafFit plug-in) -------------
    def fold_linear_stats(self, grad, hess, select, leaf_id, feat_idx,
                          feat_valid, value_lut, num_leaves: int):
        """One streamed pass accumulating the per-leaf linear-fit normal
        equations (A, b) — the out-of-core counterpart of
        ``tree.linear.linear_fit_stats``.  Chunk boundaries differ from
        the resident kernel's fixed row blocks, so the f32 add order may
        differ (documented drift, docs/TREES.md); the fold body is the
        SAME ``_fold_block`` both paths share."""
        import jax.numpy as jnp

        from ..tree.linear import linear_stats_chunk

        k1 = feat_idx.shape[1] + 1
        a = jnp.zeros((num_leaves, k1, k1), jnp.float32)
        b = jnp.zeros((num_leaves, k1), jnp.float32)
        for _i, start, _stop, chunk in self.stream.stream():
            a, b = linear_stats_chunk(a, b, chunk, grad, hess, select,
                                      leaf_id, np.int32(start), feat_idx,
                                      feat_valid, value_lut)
        return a, b

    def fold_linear_scores(self, score_k, leaf_id, feat_idx, feat_valid,
                           coeff, const, fallback, is_lin, value_lut):
        """Streamed train-score update for one freshly-grown linear tree
        via the grower's ``leaf_id`` partition (the out-of-core
        counterpart of ``tree.linear.linear_leaf_scores``)."""
        from ..tree.linear import linear_scores_chunk

        for _i, start, _stop, chunk in self.stream.stream():
            delta = linear_scores_chunk(chunk, leaf_id, np.int32(start),
                                        feat_idx, feat_valid, coeff,
                                        const, fallback, is_lin,
                                        value_lut)
            score_k = scatter_add_slice(score_k, delta, np.int32(start))
        return score_k
