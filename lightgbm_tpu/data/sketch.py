"""Mergeable per-feature statistics sketches for streaming find-bin.

The reference's DatasetLoader samples rows and feeds raw values to
BinMapper::FindBin.  When data streams through in chunks — or lives on
several hosts — per-feature statistics must instead be collected as
*mergeable summaries*:

- ``NumericSketch``: an exact distinct-value -> count map while the
  cardinality stays under ``cap``; above it, the map spills into a
  GK-style quantile sketch (Greenwald-Khanna, SIGMOD'01) with rank error
  eps·n.  Zero/NaN counts and min/max stay exact through the spill.
- ``CategoricalSketch``: exact count map spilling to Misra-Gries heavy
  hitters (capacity ``cap``), each count's undercount bounded by the
  tracked ``error`` term.

All sketches merge associatively: ``merge(merge(a, b), c)`` and
``merge(a, merge(b, c))`` summarize the same multiset, so chunk order —
and host order under the ``parallel/`` allgather — cannot change the
result of an exact (unspilled) sketch, and only widens error bounds, not
correctness, for spilled ones.

``to_distinct_counts()`` emits the (distinct_values, counts) pairs that
``BinMapper.find_bin_from_distinct`` consumes, so an exact sketch
reproduces the in-memory mapper bit-for-bit.
"""

from __future__ import annotations

import pickle
from typing import Dict, List, Optional, Tuple

import numpy as np

DEFAULT_CARDINALITY_CAP = 4096
DEFAULT_GK_EPS = 0.001


class GKSketch:
    """GK-style quantile summary over weighted values.

    Entries are ``(v, g, delta)`` sorted by v: ``g`` is the weight gap to
    the previous entry, ``delta`` the rank uncertainty.  COMPRESS merges
    adjacent entries while ``g_i + g_{i+1} + delta_{i+1} <= 2*eps*n``,
    which keeps any rank query within eps·n of truth (Greenwald-Khanna
    invariant).  Weighted inserts enter with delta=0 (their own rank is
    exact at insert time), so heavy distinct values never lose mass.
    Merging two summaries concatenates by value and adds the error
    budgets (standard mergeable-summary argument: eps_out <= eps_a +
    eps_b; we compress against the COMBINED n, so repeated merges stay
    bounded in size)."""

    __slots__ = ("eps", "vals", "g", "delta", "n")

    def __init__(self, eps: float = DEFAULT_GK_EPS):
        self.eps = float(eps)
        self.vals = np.empty(0, np.float64)
        self.g = np.empty(0, np.int64)
        self.delta = np.empty(0, np.int64)
        self.n = 0

    # ------------------------------------------------------------------
    def insert_batch(self, values: np.ndarray, counts: np.ndarray) -> None:
        """Insert distinct (value, count) pairs (values need not be
        sorted or disjoint from existing entries)."""
        if len(values) == 0:
            return
        order = np.argsort(values, kind="stable")
        v_new = np.asarray(values, np.float64)[order]
        g_new = np.asarray(counts, np.int64)[order]
        self._merge_arrays(v_new, g_new, np.zeros(len(v_new), np.int64),
                           int(g_new.sum()))

    def merge(self, other: "GKSketch") -> None:
        self._merge_arrays(other.vals, other.g, other.delta, other.n)

    def _merge_arrays(self, v2, g2, d2, n2) -> None:
        v = np.concatenate([self.vals, v2])
        g = np.concatenate([self.g, g2])
        d = np.concatenate([self.delta, d2])
        order = np.argsort(v, kind="stable")
        self.vals, self.g, self.delta = v[order], g[order], d[order]
        self.n += int(n2)
        self._compress()

    def _compress(self) -> None:
        if len(self.vals) <= 3:
            return
        budget = max(1, int(2 * self.eps * self.n))
        out_v: List[float] = []
        out_g: List[int] = []
        out_d: List[int] = []
        # walk right-to-left so each merge folds g into the RIGHT
        # neighbor (GK folds tuple i into i+1); endpoints stay exact
        acc_g = int(self.g[-1])
        acc_d = int(self.delta[-1])
        cur_v = float(self.vals[-1])
        for i in range(len(self.vals) - 2, 0, -1):
            gi = int(self.g[i])
            if gi + acc_g + acc_d <= budget:
                acc_g += gi
            else:
                out_v.append(cur_v)
                out_g.append(acc_g)
                out_d.append(acc_d)
                cur_v, acc_g, acc_d = float(self.vals[i]), gi, int(self.delta[i])
        out_v.append(cur_v)
        out_g.append(acc_g)
        out_d.append(acc_d)
        # first entry (minimum) always kept exact
        out_v.append(float(self.vals[0]))
        out_g.append(int(self.g[0]))
        out_d.append(int(self.delta[0]))
        self.vals = np.asarray(out_v[::-1], np.float64)
        self.g = np.asarray(out_g[::-1], np.int64)
        self.delta = np.asarray(out_d[::-1], np.int64)

    # ------------------------------------------------------------------
    def quantile(self, q: float) -> float:
        if len(self.vals) == 0:
            return float("nan")
        target = q * self.n
        ranks = np.cumsum(self.g)
        idx = int(np.searchsorted(ranks, target, side="left"))
        return float(self.vals[min(idx, len(self.vals) - 1)])

    def to_distinct_counts(self) -> Tuple[np.ndarray, np.ndarray]:
        """Representative (value, weight) pairs for find-bin: the sketch
        entries themselves, whose weights sum to n.  Equal values (from
        merges of summaries sharing a support point) are combined so the
        output is strictly increasing, as find-bin requires."""
        if len(self.vals) == 0:
            return self.vals.copy(), self.g.copy()
        keep = np.concatenate([[True], np.diff(self.vals) > 0])
        seg = np.cumsum(keep) - 1
        g = np.zeros(int(seg[-1]) + 1, np.int64)
        np.add.at(g, seg, self.g)
        return self.vals[keep], g


class NumericSketch:
    """Exact distinct-value map spilling to GK above ``cap`` distinct
    non-zero values.  Zero and NaN counts ride exact side counters (they
    get special treatment in FindBin and must never be approximated)."""

    __slots__ = ("cap", "eps", "counts", "gk", "zero_cnt", "nan_cnt",
                 "total_cnt", "min_val", "max_val")

    def __init__(self, cap: int = DEFAULT_CARDINALITY_CAP,
                 eps: float = DEFAULT_GK_EPS):
        self.cap = int(cap)
        self.eps = float(eps)
        self.counts: Optional[Dict[float, int]] = {}
        self.gk: Optional[GKSketch] = None
        self.zero_cnt = 0
        self.nan_cnt = 0
        self.total_cnt = 0
        self.min_val = np.inf
        self.max_val = -np.inf

    @property
    def spilled(self) -> bool:
        return self.gk is not None

    def cardinality(self) -> int:
        """Distinct non-zero values (exact until spilled, then a lower
        bound given by the summary size)."""
        return len(self.gk.vals) if self.spilled else len(self.counts)

    # ------------------------------------------------------------------
    def update(self, column: np.ndarray) -> None:
        """Fold one chunk's raw column in."""
        col = np.asarray(column, np.float64)
        self.total_cnt += len(col)
        nan_mask = np.isnan(col)
        self.nan_cnt += int(nan_mask.sum())
        col = col[~nan_mask]
        zero_mask = col == 0.0
        self.zero_cnt += int(zero_mask.sum())
        col = col[~zero_mask]
        if len(col) == 0:
            return
        self.min_val = min(self.min_val, float(col.min()))
        self.max_val = max(self.max_val, float(col.max()))
        vals, cnts = np.unique(col, return_counts=True)
        self._add_distinct(vals, cnts.astype(np.int64))

    def _add_distinct(self, vals: np.ndarray, cnts: np.ndarray) -> None:
        if self.gk is not None:
            self.gk.insert_batch(vals, cnts)
            return
        for v, c in zip(vals.tolist(), cnts.tolist()):
            self.counts[v] = self.counts.get(v, 0) + c
        if len(self.counts) > self.cap:
            self._spill()

    def _spill(self) -> None:
        gk = GKSketch(self.eps)
        vals = np.fromiter(self.counts.keys(), np.float64, len(self.counts))
        cnts = np.fromiter(self.counts.values(), np.int64, len(self.counts))
        gk.insert_batch(vals, cnts)
        self.gk = gk
        self.counts = None

    # ------------------------------------------------------------------
    def merge(self, other: "NumericSketch") -> None:
        self.zero_cnt += other.zero_cnt
        self.nan_cnt += other.nan_cnt
        self.total_cnt += other.total_cnt
        self.min_val = min(self.min_val, other.min_val)
        self.max_val = max(self.max_val, other.max_val)
        if other.spilled and not self.spilled:
            self._spill()
        if self.spilled:
            if other.spilled:
                self.gk.merge(other.gk)
            elif other.counts:
                vals = np.fromiter(other.counts.keys(), np.float64,
                                   len(other.counts))
                cnts = np.fromiter(other.counts.values(), np.int64,
                                   len(other.counts))
                self.gk.insert_batch(vals, cnts)
        elif other.counts:
            vals = np.fromiter(other.counts.keys(), np.float64,
                               len(other.counts))
            cnts = np.fromiter(other.counts.values(), np.int64,
                               len(other.counts))
            self._add_distinct(vals, cnts)

    # ------------------------------------------------------------------
    def to_distinct_counts(self) -> Tuple[np.ndarray, np.ndarray]:
        """Sorted (distinct non-zero values, counts) — what find-bin
        consumes.  Exact until spilled; sketch representatives after."""
        if self.spilled:
            return self.gk.to_distinct_counts()
        vals = np.fromiter(self.counts.keys(), np.float64, len(self.counts))
        cnts = np.fromiter(self.counts.values(), np.int64, len(self.counts))
        order = np.argsort(vals, kind="stable")
        return vals[order], cnts[order]


class CategoricalSketch:
    """Exact category-count map spilling to Misra-Gries heavy hitters.
    ``error`` bounds how much any surviving counter may undercount."""

    __slots__ = ("cap", "counts", "error", "total_cnt", "nan_cnt", "spilled")

    def __init__(self, cap: int = DEFAULT_CARDINALITY_CAP):
        self.cap = int(cap)
        self.counts: Dict[int, int] = {}
        self.error = 0
        self.total_cnt = 0
        self.nan_cnt = 0
        self.spilled = False

    def update(self, column: np.ndarray) -> None:
        col = np.asarray(column, np.float64)
        self.total_cnt += len(col)
        nan_mask = np.isnan(col)
        self.nan_cnt += int(nan_mask.sum())
        # NaN folds into category 0, like FindBin's zero-block insert
        # does for the in-memory path (NaN rows ride the implied zero
        # count, which lands on categorical value 0)
        iv = np.where(nan_mask, 0.0, col).astype(np.int64)
        vals, cnts = np.unique(iv, return_counts=True)
        for v, c in zip(vals.tolist(), cnts.tolist()):
            self.counts[v] = self.counts.get(v, 0) + c
        self._shrink()

    def _shrink(self) -> None:
        """Misra-Gries decrement: subtract the (cap+1)-th largest count
        from everyone and drop non-positives."""
        if len(self.counts) <= self.cap:
            return
        self.spilled = True
        cnts = sorted(self.counts.values(), reverse=True)
        dec = cnts[self.cap]
        self.error += dec
        self.counts = {v: c - dec for v, c in self.counts.items() if c > dec}

    def merge(self, other: "CategoricalSketch") -> None:
        self.total_cnt += other.total_cnt
        self.nan_cnt += other.nan_cnt
        self.error += other.error
        self.spilled = self.spilled or other.spilled
        for v, c in other.counts.items():
            self.counts[v] = self.counts.get(v, 0) + c
        self._shrink()

    def to_distinct_counts(self) -> Tuple[np.ndarray, np.ndarray]:
        vals = np.fromiter(self.counts.keys(), np.float64, len(self.counts))
        cnts = np.fromiter(self.counts.values(), np.int64, len(self.counts))
        order = np.argsort(vals, kind="stable")
        return vals[order], cnts[order]


# ----------------------------------------------------------------------
def serialize_sketches(sketches: List) -> bytes:
    """Length-stable wire form for the parallel/ allgather (the same
    pickled-state convention as the distributed find-bin path)."""
    return pickle.dumps(sketches, protocol=pickle.HIGHEST_PROTOCOL)


def deserialize_sketches(blob: bytes) -> List:
    return pickle.loads(blob)


def merge_sketch_lists(lists: List[List]) -> List:
    """Fold per-host sketch lists feature-wise: the associative merge
    makes the result independent of host order up to the documented
    error bounds (bit-identical while every sketch is exact)."""
    if not lists:
        return []
    base = lists[0]
    for other in lists[1:]:
        if len(other) != len(base):
            raise ValueError("sketch lists disagree on feature count")
        for mine, theirs in zip(base, other):
            mine.merge(theirs)
    return base
