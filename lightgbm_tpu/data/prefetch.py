"""Double-buffered host→device chunk prefetch (the out-of-core pipe).

A chunk's life: read from the cache (seek + CRC verify) into a host
staging array → ``jax.device_put`` (async dispatch) → consumed by the
grower's chunk program.  The producer thread runs one chunk AHEAD of the
consumer, so the read+transfer of chunk i+1 overlaps the device compute
on chunk i — with compute ≥ transfer per chunk, the stream runs at
compute speed and the transfer is free.

The ring is BOUNDED: ``depth`` (default 2 = double buffering) chunks may
be in flight at once, so peak device residency from streaming is two
chunk buffers no matter how large the dataset — the queue blocks the
producer, the consumer drops its reference as soon as the chunk program
has taken the buffer.

Overlap accounting (the bench/obs "is it actually hidden?" signal):
the producer clocks fetch time (read + CRC + device_put dispatch), the
consumer clocks stall time (blocked on an empty ring).  ``overlap_pct =
100 * (1 - stall / fetch)`` — 100 when every fetch was hidden behind
compute, 0 when the consumer waited out every byte.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterator, List, Optional, Tuple

import numpy as np


class ChunkPlan:
    """The chunk grid over [0, num_rows): ``bounds[i] = (start, stop)``.

    All chunks are ``chunk_rows`` long except a final partial chunk.
    The out-of-core trainer requires ``chunk_rows`` to be a histogram
    ``ROW_BLOCK`` multiple (callers round up) so the streamed block
    summation is bit-identical to the in-memory pass."""

    def __init__(self, num_rows: int, chunk_rows: int):
        if chunk_rows <= 0:
            raise ValueError(f"chunk_rows must be positive, got {chunk_rows}")
        self.num_rows = int(num_rows)
        self.chunk_rows = int(chunk_rows)
        self.bounds: List[Tuple[int, int]] = [
            (s, min(s + chunk_rows, num_rows))
            for s in range(0, max(num_rows, 1), chunk_rows)
        ]

    @property
    def num_chunks(self) -> int:
        return len(self.bounds)

    def fingerprint(self) -> str:
        """Schedule identity recorded into checkpoints: a resume must
        stream the same grid to replay the same block summation."""
        return f"{self.num_rows}r/{self.chunk_rows}c/{self.num_chunks}"


class ArrayChunkSource:
    """Chunk source over a host-resident (or memmapped) bin matrix."""

    def __init__(self, binned: np.ndarray):
        self.binned = binned
        self.num_rows, self.num_cols = binned.shape
        self.dtype = binned.dtype

    def read(self, start: int, stop: int) -> np.ndarray:
        return np.ascontiguousarray(self.binned[start:stop])

    def describe(self) -> str:
        kind = "memmap" if isinstance(self.binned, np.memmap) else "array"
        return f"{kind}({self.num_rows}x{self.num_cols})"


class CacheChunkSource:
    """Chunk source over a v2 binary cache (checksummed random access)."""

    def __init__(self, reader):
        self.reader = reader  # data/cache.py CacheReader
        self.num_rows = reader.num_rows
        self.num_cols = reader.num_cols
        self.dtype = reader.dtype

    def read(self, start: int, stop: int) -> np.ndarray:
        return self.reader.read_rows(start, stop, verify=True)

    def describe(self) -> str:
        return f"cache({self.reader.path})"


class PrefetchStats:
    """Accumulated overlap accounting across passes."""

    def __init__(self):
        self.chunks = 0
        self.bytes = 0
        self.fetch_s = 0.0
        self.stall_s = 0.0
        self.passes = 0
        self.peak_inflight = 0

    def overlap_pct(self) -> float:
        if self.fetch_s <= 0.0:
            return 100.0
        return max(0.0, min(100.0, 100.0 * (1.0 - self.stall_s / self.fetch_s)))

    def as_dict(self) -> dict:
        return {
            "chunks": self.chunks,
            "bytes": self.bytes,
            "passes": self.passes,
            "fetch_s": round(self.fetch_s, 6),
            "stall_s": round(self.stall_s, 6),
            "overlap_pct": round(self.overlap_pct(), 2),
            "peak_inflight": self.peak_inflight,
        }


class ChunkPrefetcher:
    """Bounded ring of in-flight host→device chunk transfers.

    One background producer per pass: reads chunk bytes (CRC-verified by
    the source) and dispatches ``jax.device_put`` — JAX transfers are
    async, so the device DMA of chunk i+1 proceeds while the consumer's
    chunk-i programs run.  ``stream()`` yields ``(index, start, stop,
    device_chunk)`` in schedule order.
    """

    def __init__(self, source, plan: ChunkPlan, depth: int = 2,
                 stats: Optional[PrefetchStats] = None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.source = source
        self.plan = plan
        self.depth = depth
        self.stats = stats if stats is not None else PrefetchStats()

    def stream(self) -> Iterator[Tuple[int, int, int, object]]:
        import jax

        # ring capacity depth-1 + the producer's in-hand chunk = depth
        ring: "queue.Queue" = queue.Queue(maxsize=max(self.depth - 1, 1))
        stats = self.stats
        stats.passes += 1
        inflight = [0]
        lock = threading.Lock()

        def produce():
            try:
                for i, (start, stop) in enumerate(self.plan.bounds):
                    t0 = time.perf_counter()
                    host = self.source.read(start, stop)
                    dev = jax.device_put(host)
                    stats.fetch_s += time.perf_counter() - t0
                    stats.bytes += host.nbytes
                    with lock:
                        inflight[0] += 1
                        stats.peak_inflight = max(stats.peak_inflight,
                                                  inflight[0])
                    ring.put((i, start, stop, dev))
                ring.put(None)
            except BaseException as e:  # surface in the consumer
                ring.put(e)

        t = threading.Thread(target=produce, name="ooc-prefetch", daemon=True)
        t.start()
        try:
            while True:
                t0 = time.perf_counter()
                item = ring.get()
                stats.stall_s += time.perf_counter() - t0
                if item is None:
                    break
                if isinstance(item, BaseException):
                    raise item
                with lock:
                    inflight[0] -= 1
                stats.chunks += 1
                yield item
        finally:
            t.join(timeout=30.0)
