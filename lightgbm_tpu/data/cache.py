"""Binary dataset cache, format v2: random access + integrity.

The PR-3 cache was a ``np.savez_compressed`` archive: great for
shipping, useless for out-of-core training — DEFLATE has no random
access, so the only read is "inflate everything".  Format v2 keeps the
same npz member layout (``io/dataset.py`` owns the payload schema) but

  - stores members UNCOMPRESSED (``np.savez``), so the ``binned``
    matrix's bytes sit contiguous in the file and a row-range is one
    ``seek`` + ``read`` (or an ``np.memmap`` view);
  - adds a ``__cache_meta__`` JSON header: format version, the SOURCE
    file's identity (path/size/mtime) so a regenerated source refuses a
    stale cache instead of silently training old data, and the dataset
    fingerprint (the same ``rows x cols : crc32`` digest checkpoint
    resume verifies);
  - adds ``chunk_crc``: one CRC32 per ``CRC_ROWS``-row block of the
    binned matrix, so the out-of-core chunk iterator verifies every
    block it streams (bit-rot on a multi-hour run surfaces as a clear
    error at the offending chunk, not as a silently-wrong model).

``CRC_ROWS`` matches the histogram kernel's ``ROW_BLOCK`` so any
bit-identity-preserving chunk size (a ``ROW_BLOCK`` multiple) covers
whole CRC blocks.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Dict, Optional

import numpy as np

from ..ops.histogram import ROW_BLOCK
from ..utils.log import Log

CACHE_FORMAT_VERSION = 2
CRC_ROWS = ROW_BLOCK  # 4096 — aligned with the histogram block size

_META_KEY = "__cache_meta__"


# ----------------------------------------------------------------------
# header build / verify (io/dataset.py save_binary / load_binary hooks)
# ----------------------------------------------------------------------
def source_identity(source_path: Optional[str]) -> Dict:
    """Identity of the text file a cache was built from.  Size + mtime
    (ns) is the staleness test: editing or regenerating the source
    changes at least one of them."""
    if not source_path:
        return {}
    try:
        st = os.stat(source_path)
    except OSError:
        return {}
    return {
        "source_path": os.path.abspath(source_path),
        "source_size": int(st.st_size),
        "source_mtime_ns": int(st.st_mtime_ns),
    }


def chunk_crcs(binned: np.ndarray, crc_rows: int = CRC_ROWS) -> np.ndarray:
    """Per-block CRC32s of the row-major binned matrix."""
    n = binned.shape[0]
    out = np.empty((max(-(-n // crc_rows), 1),), np.uint32)
    if n == 0:
        out[0] = 0
        return out
    for b in range(out.shape[0]):
        blk = np.ascontiguousarray(binned[b * crc_rows: (b + 1) * crc_rows])
        out[b] = zlib.crc32(blk.tobytes()) & 0xFFFFFFFF
    return out


def build_cache_meta(binned: np.ndarray, label: Optional[np.ndarray],
                     source_path: Optional[str] = None) -> Dict:
    """The ``__cache_meta__`` JSON dict for ``save_binary``."""
    crc = zlib.crc32(np.ascontiguousarray(binned).tobytes())
    if label is not None:
        crc = zlib.crc32(np.ascontiguousarray(
            np.asarray(label)).tobytes(), crc)
    meta = {
        "format_version": CACHE_FORMAT_VERSION,
        "crc_rows": CRC_ROWS,
        "num_data": int(binned.shape[0]),
        "num_features": int(binned.shape[1]),
        "bin_dtype": str(binned.dtype),
        "data_fingerprint":
            f"{binned.shape[0]}x{binned.shape[1]}:{crc & 0xFFFFFFFF:08x}",
    }
    meta.update(source_identity(source_path))
    return meta


def read_cache_meta(npz) -> Optional[Dict]:
    """The parsed ``__cache_meta__`` header, or None on a v1 cache."""
    if _META_KEY not in getattr(npz, "files", ()):
        return None
    try:
        return json.loads(str(npz[_META_KEY]))
    except (ValueError, TypeError):
        return None


def stale_reason(meta: Dict) -> Optional[str]:
    """Why this cache must be refused, or None when it is trustworthy.
    A cache whose recorded source still exists but has changed size or
    mtime was built from different bytes — training it would silently
    use old data."""
    src = meta.get("source_path")
    if not src or not os.path.exists(src):
        return None  # source gone/moved: nothing to compare against
    st = os.stat(src)
    if int(st.st_size) != int(meta.get("source_size", -1)):
        return (f"source {src} size changed "
                f"({meta.get('source_size')} -> {st.st_size} bytes)")
    if int(st.st_mtime_ns) != int(meta.get("source_mtime_ns", -1)):
        return f"source {src} was modified after the cache was written"
    return None


# ----------------------------------------------------------------------
# random access into the stored matrix
# ----------------------------------------------------------------------
class CacheReader:
    """Checksummed random access to the ``binned`` member of a v2 cache.

    Locates the member's raw bytes inside the (uncompressed) zip
    container once, then serves row ranges by seek+read — or the whole
    matrix as a read-only ``np.memmap`` — without inflating anything.
    ``read_rows`` verifies the per-block CRCs of every fully-covered
    block, which is every block when the caller's chunk grid is
    ``crc_rows``-aligned (the out-of-core trainer's grid is).
    """

    def __init__(self, path: str):
        import zipfile

        self.path = path
        with zipfile.ZipFile(path) as zf:
            names = zf.namelist()
            if "binned.npy" not in names or f"{_META_KEY}.npy" not in names:
                raise ValueError(
                    f"{path} is not a format-v{CACHE_FORMAT_VERSION} "
                    "binary dataset cache (missing header); regenerate "
                    "it with task=ingest")
            info = zf.getinfo("binned.npy")
            if info.compress_type != zipfile.ZIP_STORED:
                raise ValueError(
                    f"{path} stores the bin matrix compressed — no random "
                    "access; regenerate the cache with task=ingest")
            with zf.open(f"{_META_KEY}.npy") as f:
                self.meta = json.loads(str(np.lib.format.read_array(f)))
            with zf.open("chunk_crc.npy") as f:
                self.crcs = np.lib.format.read_array(f)
            # raw offset of the member's bytes: local header is
            # 30 bytes + name + extra (the extra field can differ from
            # the central directory's copy, so parse the local one)
            with open(path, "rb") as f:
                f.seek(info.header_offset)
                hdr = f.read(30)
                if hdr[:4] != b"PK\x03\x04":
                    raise ValueError(f"{path}: corrupt zip local header")
                name_len, extra_len = struct.unpack("<HH", hdr[26:30])
                member_start = info.header_offset + 30 + name_len + extra_len
                # then the npy header in front of the raw array bytes
                f.seek(member_start)
                version = np.lib.format.read_magic(f)
                if version == (1, 0):
                    shape, fortran, dtype = \
                        np.lib.format.read_array_header_1_0(f)
                elif version == (2, 0):
                    shape, fortran, dtype = \
                        np.lib.format.read_array_header_2_0(f)
                else:
                    raise ValueError(
                        f"{path}: unsupported npy header version {version}")
                if fortran:
                    raise ValueError(f"{path}: Fortran-order bin matrix")
                self.data_offset = f.tell()
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.num_rows, self.num_cols = self.shape
        self.row_bytes = self.num_cols * self.dtype.itemsize
        self.crc_rows = int(self.meta.get("crc_rows", CRC_ROWS))
        self._f = open(path, "rb")

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def memmap(self) -> np.ndarray:
        """Read-only memmap of the whole matrix (host pages stay
        demand-loaded; nothing is materialized)."""
        return np.memmap(self.path, dtype=self.dtype, mode="r",
                         offset=self.data_offset, shape=self.shape)

    def read_rows(self, start: int, stop: int,
                  verify: bool = True) -> np.ndarray:
        """Rows [start, stop) as a fresh C-order array, CRC-verified."""
        if not (0 <= start <= stop <= self.num_rows):
            raise IndexError(f"row range [{start}, {stop}) outside "
                             f"[0, {self.num_rows})")
        self._f.seek(self.data_offset + start * self.row_bytes)
        raw = self._f.read((stop - start) * self.row_bytes)
        if len(raw) != (stop - start) * self.row_bytes:
            raise IOError(f"{self.path}: short read at rows "
                          f"[{start}, {stop}) — truncated cache?")
        arr = np.frombuffer(raw, dtype=self.dtype).reshape(
            stop - start, self.num_cols)
        if verify:
            self._verify_blocks(arr, start, stop)
        return arr

    def _verify_blocks(self, arr: np.ndarray, start: int, stop: int) -> None:
        cr = self.crc_rows
        b0 = -(-start // cr)  # first block fully inside [start, stop)
        while b0 * cr < stop:
            lo = b0 * cr
            hi = min(lo + cr, self.num_rows)
            if hi > stop:  # partially covered: next read verifies it
                break
            blk = arr[lo - start: hi - start]
            crc = zlib.crc32(np.ascontiguousarray(blk).tobytes()) & 0xFFFFFFFF
            if b0 < len(self.crcs) and crc != int(self.crcs[b0]):
                raise IOError(
                    f"{self.path}: CRC mismatch on rows [{lo}, {hi}) "
                    f"(block {b0}): cache is corrupt — regenerate it "
                    "with task=ingest")
            b0 += 1

    def verify_all(self) -> None:
        """Stream every block through the CRC check (bounded memory)."""
        for start in range(0, max(self.num_rows, 1), self.crc_rows):
            stop = min(start + self.crc_rows, self.num_rows)
            if stop > start:
                self.read_rows(start, stop, verify=True)


def open_cache_reader(path: str) -> Optional[CacheReader]:
    """A :class:`CacheReader` for ``path``, or None (with a log line)
    when the cache predates random access."""
    try:
        return CacheReader(path)
    except (ValueError, OSError) as e:
        Log.warning("No random access into cache %s: %s", path, e)
        return None
