"""Two-pass out-of-core dataset construction.

The in-memory path (io/parser.load_text_file -> BinnedDataset.from_raw)
materializes the whole file as a float64 matrix before binning — at
Higgs scale (10.5M x 28) that is a 2.4 GB scratch allocation that dwarfs
the 300 MB packed bin matrix actually kept.  This pipeline streams
instead:

  pass 0  count non-blank data lines (cheap byte scan, no parse)
  pass 1  parse chunk-by-chunk: collect the deterministic
          bin-construction row sample (bit-identical to the in-memory
          sample: same LCG indices over the same row order) + mergeable
          per-feature sketches (data/stats.py); find bins from the
          sample
  pass 2  parse chunk-by-chunk again, writing each chunk's bin indices
          straight into the PREALLOCATED packed uint8/uint16 matrix

Peak host memory is the packed matrix plus O(one chunk) — the raw float
matrix never exists.  Because find-bin consumes exactly the sample the
in-memory path would draw, the resulting BinMappers, packed matrix and
any model trained from them are bit-identical to non-streaming
construction of the same file.

Routing: ``Dataset(path)`` streams when ``should_stream`` says so —
``LIGHTGBM_TPU_STREAM_INGEST`` = ``0`` (never) / ``1`` (always) /
``<MiB threshold>`` / ``auto`` (default: stream above
``DEFAULT_AUTO_THRESHOLD_MB`` or when ``use_two_round_loading``, the
reference's own low-memory loading flag, is set).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from ..io.parser import _resolve_column, _resolve_columns, _side_files
from ..obs import tracer
from ..obs.memory import host_rss_mb
from ..utils.log import Log
from .reader import DenseChunkReader, LibSVMChunkReader, make_reader
from .stats import SampleCollector, SketchCollector

DEFAULT_AUTO_THRESHOLD_MB = 256


# ----------------------------------------------------------------------
def stream_mode(config=None) -> str:
    """'never' | 'always' | 'auto' | '<MiB>' from env + config.  The env
    knob wins; config.stream_ingest is the param-file surface."""
    v = os.environ.get("LIGHTGBM_TPU_STREAM_INGEST", "").strip().lower()
    if not v or v == "auto":
        v = str(getattr(config, "stream_ingest", "auto") or "auto").lower()
    if v in ("0", "false", "off", "never"):
        return "never"
    if v in ("1", "true", "on", "always", "force"):
        return "always"
    return v  # 'auto' or a numeric MiB threshold


def should_stream(path: str, config) -> bool:
    mode = stream_mode(config)
    if mode == "never":
        return False
    if mode == "always":
        return True
    threshold_mb = DEFAULT_AUTO_THRESHOLD_MB
    if mode != "auto":
        try:
            threshold_mb = float(mode)
        except ValueError:
            Log.warning("Unparsable stream-ingest mode %r; using auto", mode)
    if getattr(config, "use_two_round_loading", False):
        # the reference's two-round loading IS the low-memory path
        return True
    try:
        return os.path.getsize(path) > threshold_mb * (1 << 20)
    except OSError:
        return False


# ----------------------------------------------------------------------
@dataclass
class ColumnRoles:
    """Label/weight/group/ignore column assignment over the FULL parsed
    column set — the exact slicing io/parser.load_text_file applies, so
    streaming and in-memory loads pick identical feature columns."""

    label_idx: int = 0
    weight_col: int = -1
    group_col: int = -1
    keep: List[int] = field(default_factory=list)
    feat_names: List[str] = field(default_factory=list)


def resolve_roles(config, names: Optional[List[str]], ncols: int) -> ColumnRoles:
    label_idx, _ = _resolve_column(config.label_column, names, default=0)
    weight_idx, weight_abs = _resolve_column(config.weight_column, names, default=-1)
    group_idx, group_abs = _resolve_column(config.group_column, names, default=-1)
    ignore = _resolve_columns(config.ignore_column, names)

    # numeric specs are label-relative and shift past the label column
    # (config.h:119-133); name:-resolved are header-absolute
    def absolute(idx: int, is_name: bool) -> int:
        if idx < 0 or is_name:
            return idx
        return idx if idx < label_idx else idx + 1

    roles = ColumnRoles(label_idx=label_idx)
    drop = {label_idx}
    if weight_idx >= 0:
        roles.weight_col = absolute(weight_idx, weight_abs)
        drop.add(roles.weight_col)
    if group_idx >= 0:
        roles.group_col = absolute(group_idx, group_abs)
        drop.add(roles.group_col)
    for ig, ig_abs in ignore:
        drop.add(absolute(ig, ig_abs))
    roles.keep = [i for i in range(ncols) if i not in drop]
    roles.feat_names = (
        [names[i] for i in roles.keep] if names
        else [f"Column_{i}" for i in range(len(roles.keep))]
    )
    return roles


def resolve_categorical(categorical_feature, feat_names: List[str]) -> set:
    """Python-API categorical spec -> FEATURE-matrix column indices,
    with the same name resolution basic.py applies."""
    if categorical_feature in ("auto", None) or not categorical_feature:
        return set()
    cats = set()
    for c in categorical_feature:
        if isinstance(c, str):
            if feat_names and c in feat_names:
                cats.add(feat_names.index(c))
            else:
                Log.fatal("Unknown categorical feature %s", c)
        else:
            cats.add(int(c))
    return cats


# ----------------------------------------------------------------------
def group_sizes_from_ids(gid: np.ndarray) -> np.ndarray:
    """Query-id column -> per-query sizes (run lengths), identical to
    the io/parser conversion."""
    change = np.nonzero(np.diff(gid))[0] + 1
    bounds = np.concatenate([[0], change, [len(gid)]])
    return np.diff(bounds).astype(np.int64)


class _RSSWatch:
    """Peak host-RSS watermark over explicit ticks (obs gauge source)."""

    def __init__(self):
        self.start_mb = host_rss_mb()
        self.peak_mb = self.start_mb

    def tick(self) -> float:
        rss = host_rss_mb()
        if rss > self.peak_mb:
            self.peak_mb = rss
        return rss


def stream_dataset(
    path: str,
    config,
    *,
    feature_name="auto",
    categorical_feature="auto",
    reference=None,
    chunk_rows: Optional[int] = None,
):
    """Stream ``path`` into a BinnedDataset without materializing the
    raw float matrix.  ``reference`` (a constructed BinnedDataset)
    reuses its bin mappers — the CreateValid alignment path — and skips
    pass 1 entirely."""
    import time as _time

    from ..io.dataset import (
        BinnedDataset,
        Metadata,
        bin_rows_into,
        bin_sample_indices,
        find_bin_mappers_from_sample,
        packed_bin_dtype,
    )

    t_start = _time.perf_counter()
    rss = _RSSWatch()
    if chunk_rows is None:
        env_rows = os.environ.get("LIGHTGBM_TPU_STREAM_CHUNK_ROWS", "")
        if env_rows:
            chunk_rows = int(env_rows)
        elif int(getattr(config, "stream_chunk_rows", 0) or 0) > 0:
            chunk_rows = int(config.stream_chunk_rows)
    reader = make_reader(path, chunk_rows=chunk_rows,
                         has_header=config.has_header,
                         bad_row_policy=getattr(config, "bad_row_policy",
                                                "error"))
    libsvm = isinstance(reader, LibSVMChunkReader)

    # -- pass 0: row count (needed up front: the LCG sample draws
    # indices over [0, n), exactly like DatasetLoader) ------------------
    with tracer.span("ingest.pass0_count", path=path):
        n = reader.count_rows()
    if n == 0:
        Log.fatal("Data file %s is empty", path)

    report = {
        "streamed": True,
        "path": path,
        "rows": int(n),
        "libsvm": bool(libsvm),
        "rss_start_mb": round(rss.start_mb, 1),
    }

    # -- pass 1: sample + sketches + (dense) column roles ---------------
    roles: Optional[ColumnRoles] = None
    sample_idx = bin_sample_indices(n, config)
    sketches: Optional[SketchCollector] = None
    sampled_feats = None
    cats: set = set()
    chunks_seen = 0

    if reference is None:
        collector = SampleCollector(
            sample_idx, ncols=None if libsvm else reader.ncols
        )
        with tracer.span("ingest.pass1_stats", rows=int(n)):
            if libsvm:
                sketches = SketchCollector()
                for start, feats, _labels in reader.iter_chunks():
                    collector.offer(start, feats)
                    sketches.update(feats)
                    chunks_seen += 1
                    tracer.counter("ingest.chunks", phase="pass1")
                    tracer.gauge("ingest.host_rss_mb", rss.tick(), phase="pass1")
                width = reader.ncols_seen
                sampled_feats = collector.finish(
                    ncols=width, partial=reader.bad_rows > 0
                )
                feat_names = [f"Column_{i}" for i in range(width)]
                roles = ColumnRoles(label_idx=0,
                                    keep=list(range(width)),
                                    feat_names=feat_names)
            else:
                roles = resolve_roles(config, reader.header_names, reader.ncols)
                if feature_name != "auto" and feature_name is not None:
                    roles.feat_names = list(feature_name)
                cats = resolve_categorical(categorical_feature, roles.feat_names)
                sketches = SketchCollector(categorical=cats)
                keep = np.asarray(roles.keep, dtype=np.int64)
                for start, chunk in reader.iter_chunks():
                    collector.offer(start, chunk)
                    sketches.update(chunk[:, keep])
                    chunks_seen += 1
                    tracer.counter("ingest.chunks", phase="pass1")
                    tracer.gauge("ingest.host_rss_mb", rss.tick(), phase="pass1")
                sampled_feats = collector.finish(
                    partial=reader.bad_rows > 0
                )[:, keep]
            if getattr(config, "is_parallel_find_bin", False):
                from ..parallel.distributed import ensure_initialized

                if ensure_initialized(config):
                    # ingest mirror of distributed find-bin: every host
                    # ends with the identical merged sketch bank
                    sketches.merge_across_hosts()
            tracer.event("ingest.sketches", **sketches.summary())

        with tracer.span("ingest.find_bin", sample=int(len(sample_idx))):
            mappers = find_bin_mappers_from_sample(sampled_feats, n, config, cats)
            used = [i for i, m in enumerate(mappers) if not m.is_trivial]
            if not used:
                Log.fatal("Cannot construct Dataset: all features are trivial (constant)")
            bin_mappers = [mappers[i] for i in used]
            used_map = np.asarray(used, dtype=np.int32)
        del sampled_feats, collector
        report["sketch"] = sketches.summary()
    else:
        bin_mappers = reference.bin_mappers
        used_map = reference.used_feature_map
        if libsvm:
            width = reference.num_total_features
            roles = ColumnRoles(label_idx=0, keep=list(range(width)),
                                feat_names=list(reference.feature_names))
        else:
            roles = resolve_roles(config, reader.header_names, reader.ncols)
            roles.feat_names = list(reference.feature_names)

    # -- pass 2: bin chunks into the preallocated packed matrix ---------
    ds = BinnedDataset()
    ds.num_total_features = (reference.num_total_features if reference is not None
                             else len(roles.keep) if not libsvm else width)
    ds.max_bin = reference.max_bin if reference is not None else config.max_bin
    ds.bin_mappers = bin_mappers
    ds.used_feature_map = used_map
    ds.feature_names = roles.feat_names
    ds.label_idx = roles.label_idx

    dtype = packed_bin_dtype(bin_mappers)
    binned = np.empty((n, len(bin_mappers)), dtype=dtype)
    label = np.zeros(n, dtype=np.float32)
    weights = np.empty(n, dtype=np.float32) if roles.weight_col >= 0 else None
    gid = np.empty(n, dtype=np.float64) if roles.group_col >= 0 else None
    keep = np.asarray(roles.keep, dtype=np.int64)

    pass2_chunks = 0
    filled = 0
    with tracer.span("ingest.pass2_bin", rows=int(n)):
        if libsvm:
            target_w = (reference.num_total_features
                        if reference is not None else width)
            for start, feats, labels_chunk in reader.iter_chunks():
                if feats.shape[1] < target_w:
                    feats = np.pad(feats, ((0, 0), (0, target_w - feats.shape[1])))
                elif feats.shape[1] > target_w:
                    # columns unseen by pass 1 cannot happen (same file);
                    # a reference narrower than the data truncates, like
                    # ValueToBin's unseen-feature clamp
                    feats = feats[:, :target_w]
                bin_rows_into(binned, start, feats, bin_mappers, used_map)
                label[start : start + len(labels_chunk)] = labels_chunk
                filled = start + len(labels_chunk)
                pass2_chunks += 1
                tracer.counter("ingest.chunks", phase="pass2")
                tracer.gauge("ingest.host_rss_mb", rss.tick(), phase="pass2")
        else:
            for start, chunk in reader.iter_chunks():
                stop = start + chunk.shape[0]
                bin_rows_into(binned, start, chunk[:, keep], bin_mappers, used_map)
                label[start:stop] = chunk[:, roles.label_idx].astype(np.float32)
                if weights is not None:
                    weights[start:stop] = chunk[:, roles.weight_col].astype(np.float32)
                if gid is not None:
                    gid[start:stop] = chunk[:, roles.group_col]
                filled = stop
                pass2_chunks += 1
                tracer.counter("ingest.chunks", phase="pass2")
                tracer.gauge("ingest.host_rss_mb", rss.tick(), phase="pass2")

    if filled < n:
        # bad_row_policy='skip' dropped rows: pass 0's raw line count
        # over-allocated; trim to the surviving rows (both passes skip
        # the SAME rows — the parse is deterministic)
        Log.warning("%s: %d of %d data rows were malformed and skipped",
                    path, n - filled, n)
        report["bad_rows"] = int(n - filled)
        report["rows"] = int(filled)
        binned = binned[:filled]
        label = label[:filled]
        weights = weights[:filled] if weights is not None else None
        gid = gid[:filled] if gid is not None else None
        n = filled

    ds.binned = binned
    ds.metadata = Metadata(n)
    ds.metadata.set_label(label)
    group = group_sizes_from_ids(gid) if gid is not None else None

    # side files fill whatever the columns didn't provide (metadata.cpp)
    fweights, fgroup = _side_files(path, n)
    if weights is None:
        weights = fweights
    if group is None:
        group = fgroup
    ds.metadata.set_weights(weights)
    ds.metadata.set_query(group)

    rss.tick()
    report.update({
        "chunks_pass1": int(chunks_seen),
        "chunks_pass2": int(pass2_chunks),
        "chunk_rows": int(reader.chunk_rows()),
        "num_features_used": int(len(bin_mappers)),
        "packed_mb": round(binned.nbytes / 1e6, 1),
        "rss_peak_mb": round(rss.peak_mb, 1),
        "wall_s": round(_time.perf_counter() - t_start, 3),
    })
    report["rows_per_s"] = round(n / max(report["wall_s"], 1e-9), 1)
    ds.ingest_report = report
    tracer.event("ingest.done", **{k: v for k, v in report.items()
                                   if not isinstance(v, dict)})
    tracer.gauge("ingest.rss_peak_mb", rss.peak_mb)
    return ds
