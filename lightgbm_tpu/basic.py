"""User-facing Dataset and Booster — counterpart of
python-package/lightgbm/basic.py (Dataset:551, Booster:1176).

The reference's classes are ctypes shims over the C API; here they wrap the
in-process host/device pipeline directly: Dataset lazily constructs a
BinnedDataset (io/dataset.py), Booster owns a boosting driver
(boosting/gbdt.py) with device-resident state.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .boosting import create_boosting
from .config import Config
from .io.dataset import BinnedDataset
from .metric import create_metric
from .objective import create_objective
from .utils.log import Log


def _to_2d_float(data, want_cats: bool = False):
    """-> (array, column_names) or, with ``want_cats``, (array, names,
    auto_categorical_indices).  Pandas ``category`` dtype columns are
    mapped to their integer codes (missing -> NaN) and reported as
    auto-detected categorical features, mirroring the reference's pandas
    handling under categorical_feature="auto"
    (python-package/lightgbm/basic.py _data_from_pandas)."""
    try:
        import pandas as pd

        if isinstance(data, pd.DataFrame):
            cat_idx = [i for i, c in enumerate(data.columns)
                       if isinstance(data.dtypes.iloc[i], pd.CategoricalDtype)]
            levels = []
            if cat_idx:
                data = data.copy(deep=False)
                for i in cat_idx:
                    col = data.columns[i]
                    levels.append(list(data[col].cat.categories))
                    codes = data[col].cat.codes.to_numpy(np.float64)
                    codes[codes < 0] = np.nan  # code -1 == missing
                    data[col] = codes
            arr = data.to_numpy(dtype=np.float64)
            names = [str(c) for c in data.columns]
            return (arr, names, cat_idx, levels) if want_cats else (arr, names)
    except ImportError:
        pass
    # scipy CSR/CSC input (basic.py __init_from_csr/__init_from_csc):
    # the TPU pipeline is dense by design (README sparse-bins decision) —
    # densify here; EFB re-compacts exclusive columns downstream
    if hasattr(data, "tocsr") and hasattr(data, "toarray"):
        Log.warning(
            "Sparse input is densified for the TPU pipeline "
            "(%d x %d); EFB bundling recovers the memory on device",
            *data.shape,
        )
        arr = np.asarray(data.toarray(), dtype=np.float64)
        return (arr, None, [], []) if want_cats else (arr, None)
    arr = np.asarray(data, dtype=np.float64)
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    return (arr, None, [], []) if want_cats else (arr, None)


def _map_pandas_categorical(data, pandas_categorical):
    """Predict-time DataFrame: map category columns through the TRAINING
    category order (reference basic.py _data_from_pandas +
    pandas_categorical round-trip) so codes line up with the model."""
    try:
        import pandas as pd
    except ImportError:  # pragma: no cover
        return data
    if not isinstance(data, pd.DataFrame) or not pandas_categorical:
        return data
    cat_cols = [c for i, c in enumerate(data.columns)
                if isinstance(data.dtypes.iloc[i], pd.CategoricalDtype)]
    if not cat_cols:
        return data
    if len(cat_cols) != len(pandas_categorical):
        # the reference raises on exactly this shape mismatch
        # ("train and valid dataset categorical_feature do not match")
        Log.fatal(
            "predict data has %d pandas categorical columns but the model "
            "was trained with %d", len(cat_cols), len(pandas_categorical),
        )
    data = data.copy(deep=False)
    for col, levels in zip(cat_cols, pandas_categorical):
        codes = pd.Categorical(data[col], categories=levels).codes.astype(np.float64)
        codes[codes < 0] = np.nan
        data[col] = codes
    return data


class Dataset:
    """Lazily-constructed binned dataset (basic.py:551 Dataset)."""

    def __init__(
        self,
        data,
        label=None,
        max_bin: Optional[int] = None,
        reference: Optional["Dataset"] = None,
        weight=None,
        group=None,
        init_score=None,
        silent: bool = False,
        feature_name="auto",
        categorical_feature="auto",
        params: Optional[Dict[str, Any]] = None,
        free_raw_data: bool = False,
    ):
        if isinstance(data, str):
            self.data_path = data
            self.data = None
            self.pandas_columns = None
            self._auto_categorical = []
            self.pandas_categorical = []
        else:
            self.data_path = None
            (self.data, self.pandas_columns, self._auto_categorical,
             self.pandas_categorical) = _to_2d_float(data, want_cats=True)
        self.label = label
        self.max_bin = max_bin
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.params = dict(params) if params else {}
        # only an EXPLICIT max_bin argument becomes a dataset param —
        # otherwise booster params may fill it at Booster construction
        if max_bin is not None:
            self.params.setdefault("max_bin", max_bin)
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.free_raw_data = free_raw_data
        self._constructed: Optional[BinnedDataset] = None
        self.label_idx = 0

    # ------------------------------------------------------------------
    def construct(self, extra_params: Optional[Dict[str, Any]] = None) -> BinnedDataset:
        """Build (or return) the binned dataset (basic.py _lazy_init).

        ``extra_params`` fill gaps for this construction only (booster
        params reaching the dataset) — the Dataset's own ``params`` win
        and are never mutated, so the same un-constructed Dataset can be
        reused by a second Booster with different params.
        """
        if self._constructed is not None:
            return self._constructed
        merged = dict(extra_params) if extra_params else {}
        merged.update(self.params)
        cfg = Config.from_params(
            {k: v for k, v in merged.items() if k != "categorical_feature"}
        )
        if self.data is None and self.data_path is not None:
            # binary dataset cache first (DatasetLoader::LoadFromBinFile)
            if BinnedDataset.is_binary_cache(self.data_path):
                ds = BinnedDataset.load_binary(self.data_path)
                if self.label is not None:
                    ds.metadata.set_label(self.label)
                if self.weight is not None:
                    ds.metadata.set_weights(self.weight)
                if self.group is not None:
                    ds.metadata.set_query(self.group)
                if self.init_score is not None:
                    ds.metadata.set_init_score(self.init_score)
                self._constructed = ds
                return ds
            from .data.ingest import should_stream, stream_dataset

            if should_stream(self.data_path, cfg):
                # out-of-core path (data/ingest.py): two-pass chunked
                # construction, bit-identical mappers/bins to the
                # in-memory load of the same file — the raw float matrix
                # is never materialized, so self.data stays None
                ref = self.reference.construct() if self.reference is not None else None
                ds = stream_dataset(
                    self.data_path, cfg,
                    feature_name=self.feature_name,
                    categorical_feature=self.categorical_feature,
                    reference=ref,
                )
                if self.label is not None:
                    ds.metadata.set_label(self.label)
                if self.weight is not None:
                    ds.metadata.set_weights(self.weight)
                if self.group is not None:
                    ds.metadata.set_query(self.group)
                if self.init_score is not None:
                    ds.metadata.set_init_score(self.init_score)
                self.label_idx = ds.label_idx
                self._constructed = ds
                return ds
            from .io.parser import load_text_file

            feats, label, weights, group, names, label_idx = load_text_file(
                self.data_path, cfg
            )
            self.data = feats
            self.label_idx = label_idx
            if self.label is None:
                self.label = label
            if self.weight is None:
                self.weight = weights
            if self.group is None:
                self.group = group
            if self.feature_name == "auto":
                self.feature_name = names

        names = None
        if self.feature_name != "auto" and self.feature_name is not None:
            names = list(self.feature_name)
        elif self.pandas_columns is not None:
            names = self.pandas_columns

        cats: Optional[Sequence[int]] = None
        if self.categorical_feature != "auto" and self.categorical_feature:
            cats = []
            for c in self.categorical_feature:
                if isinstance(c, str):
                    if names and c in names:
                        cats.append(names.index(c))
                    else:
                        Log.fatal("Unknown categorical feature %s", c)
                else:
                    cats.append(int(c))
        elif self.categorical_feature == "auto" and getattr(
            self, "_auto_categorical", None
        ):
            # pandas category dtype columns (mapped to codes in
            # _to_2d_float) become categorical features automatically
            cats = list(self._auto_categorical)

        ref = self.reference.construct() if self.reference is not None else None
        if self.reference is not None:
            self._remap_categorical_to_reference(self.reference)
        self._constructed = BinnedDataset.from_raw(
            self.data,
            cfg,
            label=self.label,
            weight=self.weight,
            group=self.group,
            init_score=self.init_score,
            feature_names=names,
            categorical_features=cats,
            reference=ref,
        )
        self._constructed.label_idx = self.label_idx
        if self.free_raw_data:
            self.data = None
        return self._constructed

    # ------------------------------------------------------------------
    def _remap_categorical_to_reference(self, ref: "Dataset") -> None:
        """Validation Dataset built from a pandas frame: its category
        columns were coded against the frame's OWN level order
        (_to_2d_float), but the tree thresholds are bin ids over the
        TRAINING set's levels — remap codes through the reference's
        ``pandas_categorical`` (the reference's _data_from_pandas
        round-trip) and, like the reference, raise when the categorical
        column sets don't line up."""
        train_levels = getattr(ref, "pandas_categorical", None) or []
        my_levels = getattr(self, "pandas_categorical", None) or []
        if not my_levels and not train_levels:
            return
        if len(my_levels) != len(train_levels):
            Log.fatal(
                "train and valid dataset categorical_feature do not match: "
                "valid has %d pandas categorical columns, train has %d",
                len(my_levels), len(train_levels),
            )
        if self.data is None:
            return
        for col_idx, vl, tl in zip(self._auto_categorical, my_levels,
                                   train_levels):
            if list(vl) == list(tl):
                continue
            # valid-code -> train-code lookup; levels unseen at train
            # time become missing (NaN), matching predict-time remap
            pos = {v: i for i, v in enumerate(tl)}
            lut = np.asarray([pos.get(v, np.nan) for v in vl], np.float64)
            col = np.asarray(self.data[:, col_idx], np.float64)
            ok = ~np.isnan(col)
            out = np.full(col.shape, np.nan)
            out[ok] = lut[col[ok].astype(np.int64)]
            self.data[:, col_idx] = out
        self.pandas_categorical = [list(t) for t in train_levels]

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, silent=False, params=None) -> "Dataset":
        return Dataset(
            data,
            label=label,
            reference=self,
            weight=weight,
            group=group,
            init_score=init_score,
            silent=silent,
            params=params or self.params,
        )

    def set_label(self, label) -> "Dataset":
        self.label = label
        if self._constructed is not None:
            self._constructed.metadata.set_label(label)
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = weight
        if self._constructed is not None:
            self._constructed.metadata.set_weights(weight)
        return self

    def set_group(self, group) -> "Dataset":
        self.group = group
        if self._constructed is not None:
            self._constructed.metadata.set_query(group)
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = init_score
        if self._constructed is not None:
            self._constructed.metadata.set_init_score(init_score)
        return self

    def get_label(self):
        if self._constructed is not None:
            return np.asarray(self._constructed.metadata.label)
        return None if self.label is None else np.asarray(self.label)

    def get_weight(self):
        if self._constructed is not None and self._constructed.metadata.weights is not None:
            return np.asarray(self._constructed.metadata.weights)
        return None if self.weight is None else np.asarray(self.weight)

    def get_group(self):
        return None if self.group is None else np.asarray(self.group)

    def get_init_score(self):
        return None if self.init_score is None else np.asarray(self.init_score)

    def num_data(self) -> int:
        if self._constructed is not None:
            return self._constructed.num_data
        return len(self.data) if self.data is not None else 0

    def num_feature(self) -> int:
        if self._constructed is not None:
            return self._constructed.num_total_features
        return self.data.shape[1] if self.data is not None else 0

    def save_binary(self, filename: str) -> "Dataset":
        # record which source file the cache came from, so a later load
        # can refuse the cache when that file changes underneath it
        self.construct().save_binary(filename, source_path=self.data_path)
        return self

    def subset(self, used_indices, params=None) -> "Dataset":
        """Row-subset Dataset sharing this dataset's bin mappers and
        BINNED rows (Dataset::CopySubset — no per-fold re-binning)."""
        used_indices = np.asarray(used_indices)
        sub = Dataset.__new__(Dataset)
        sub.data_path = None
        sub.data = self.data[used_indices] if self.data is not None else None
        sub.pandas_columns = self.pandas_columns
        sub._auto_categorical = list(getattr(self, "_auto_categorical", []))
        sub.pandas_categorical = list(getattr(self, "pandas_categorical", []))
        sub.label = None
        sub.max_bin = self.max_bin
        sub.reference = self
        sub.weight = None
        sub.init_score = None
        sub.params = dict(params) if params else dict(self.params)
        sub.feature_name = self.feature_name
        sub.categorical_feature = self.categorical_feature
        sub.free_raw_data = False
        sub.label_idx = self.label_idx
        sub._constructed = self.construct().subset(used_indices)
        qb = sub._constructed.metadata.query_boundaries
        sub.group = None if qb is None else np.diff(qb)
        return sub


class Booster:
    """Training/prediction handle (basic.py:1176 Booster)."""

    def __init__(
        self,
        params: Optional[Dict[str, Any]] = None,
        train_set: Optional[Dataset] = None,
        model_file: Optional[str] = None,
        model_str: Optional[str] = None,
        silent: bool = False,
    ):
        self.params = dict(params) if params else {}
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._name_to_index: Dict[str, int] = {}

        self.pandas_categorical = []
        if train_set is not None:
            self.config = Config.from_params(self.params)
            self.pandas_categorical = getattr(train_set, "pandas_categorical", [])
            # dataset-relevant train params reach construction unless the
            # Dataset set them explicitly (Dataset._update_params: the
            # dataset's own params win, booster params fill the gaps) —
            # passed per-construction, never written into train_set.params
            binned = train_set.construct(extra_params=self.params)
            self.train_dataset = train_set
            self.objective = create_objective(self.config)
            self.boosting = create_boosting(self.config.boosting_type)
            # training metrics only when asked (is_provide_training_metric
            # gate, gbdt.cpp ResetTrainingData); the python engine path
            # evaluates "training" as a valid set instead
            training_metrics = (
                self._make_metrics(binned) if self.config.is_training_metric else []
            )
            self.boosting.init(self.config, binned, self.objective, training_metrics)
            self._num_datasets = 1
        elif model_file is not None or model_str is not None:
            if model_file is not None:
                with open(model_file) as f:
                    model_str = f.read()
            model_str = self._strip_pandas_categorical(model_str)
            self.config = Config.from_params(self.params)
            self.boosting = create_boosting("gbdt")
            self.boosting.config = self.config
            self.boosting.load_model_from_string(model_str)
            self.objective = self._objective_from_model_string(
                self.boosting.objective_name_loaded
            )
            self.boosting.objective = self.objective
            self.train_dataset = None
            self._num_datasets = 0
        else:
            Log.fatal("Booster needs a train_set, model_file or model_str")

    # ------------------------------------------------------------------
    def _strip_pandas_categorical(self, model_str: str) -> str:
        """Parse + remove the trailing pandas_categorical json line
        (written by model_to_string; reference model-file convention).
        The removal span comes from the RAW line — computing it from the
        stripped text mis-sliced model files with CRLF endings or
        trailing whitespace on the line."""
        marker = "\npandas_categorical:"
        pos = model_str.rfind(marker)
        if pos >= 0:
            import json

            raw_line, _, rest = model_str[pos + len(marker):].partition("\n")
            try:
                self.pandas_categorical = json.loads(raw_line.strip()) or []
            except ValueError:
                self.pandas_categorical = []
            model_str = model_str[:pos] + rest
        return model_str

    def _objective_from_model_string(self, obj_str: str):
        from .objective import objective_from_string

        return objective_from_string(obj_str)

    def _metric_names(self) -> List[str]:
        names = self.config.metric
        if not names:
            names = [self.config.objective]
        return [n for n in names if n.lower() not in ("none", "null", "")]

    def _make_metrics(self, binned):
        metrics = []
        for name in self._metric_names():
            m = create_metric(name, self.config)
            if m is None:
                Log.warning("Unknown metric %s", name)
                continue
            m.init(binned.metadata, binned.num_data)
            metrics.append(m)
        return metrics

    # ------------------------------------------------------------------
    def add_valid(self, data: Dataset, name: str) -> "Booster":
        binned = data.construct()
        self.boosting.add_valid(binned, self._make_metrics(binned), name)
        self._name_to_index[name] = self._num_datasets
        self._num_datasets += 1
        return self

    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting iteration (Booster.update, basic.py:1377).  With a
        custom ``fobj(preds, train_set) -> (grad, hess)`` mirrors
        LGBM_BoosterUpdateOneIterCustom."""
        if fobj is None:
            return self.boosting.train_one_iter(is_eval=False)
        preds = self._raw_train_scores()
        grad, hess = fobj(preds, self.train_dataset)
        return self.boosting.train_one_iter(
            np.asarray(grad, np.float32),
            np.asarray(hess, np.float32),
            is_eval=False,
        )

    def _raw_train_scores(self) -> np.ndarray:
        sc = self.boosting._train_score_host()
        return sc[0] if sc.shape[0] == 1 else sc.reshape(-1)

    def rollback_one_iter(self) -> "Booster":
        self.boosting.rollback_one_iter()
        return self

    def current_iteration(self) -> int:
        return self.boosting.current_iteration()

    @property
    def num_trees(self) -> int:
        return self.boosting.num_trees

    # ------------------------------------------------------------------
    def eval_train(self, feval=None):
        return self.__inner_eval("training", 0, feval)

    def eval_valid(self, feval=None):
        out = []
        for name, idx in self._name_to_index.items():
            out.extend(self.__inner_eval(name, idx, feval))
        return out

    def eval(self, data: Dataset, name: str, feval=None):
        if name in self._name_to_index:
            return self.__inner_eval(name, self._name_to_index[name], feval)
        Log.fatal("Dataset %s was not added with add_valid", name)

    def __inner_eval(self, data_name: str, data_idx: int, feval=None):
        """[(data_name, metric_name, value, bigger_is_better), ...]"""
        results = []
        for name, val, bigger in self.boosting.get_eval_at(data_idx):
            results.append((data_name, name, val, bigger))
        if feval is not None:
            if data_idx == 0:
                preds = self._raw_train_scores()
                fdata = self.train_dataset
            else:
                sc = self.boosting._valid_score_host(data_idx - 1)
                preds = sc[0] if sc.shape[0] == 1 else sc.reshape(-1)
                binned = self.boosting.valid_sets[data_idx - 1]
                fdata = Dataset.__new__(Dataset)
                fdata._constructed = binned
                fdata.label = np.asarray(binned.metadata.label)
                qb = binned.metadata.query_boundaries
                fdata.group = None if qb is None else np.diff(qb)
                fdata.weight = binned.metadata.weights
                fdata.init_score = None
            ret = feval(preds, fdata)
            if isinstance(ret, tuple):
                ret = [ret]
            for name, val, bigger in ret:
                results.append((data_name, name, val, bigger))
        return results

    # ------------------------------------------------------------------
    def predict(
        self,
        data,
        num_iteration: int = -1,
        raw_score: bool = False,
        pred_leaf: bool = False,
        data_has_header: bool = False,
        is_reshape: bool = True,
    ) -> np.ndarray:
        if isinstance(data, str):
            from .io.parser import load_text_file

            feats, _, _, _, _, _ = load_text_file(data, self.config)
            data = feats
        else:
            data = _map_pandas_categorical(data, self.pandas_categorical)
            data, _ = _to_2d_float(data)
        return self.boosting.predict(
            data, num_iteration=num_iteration, raw_score=raw_score, pred_leaf=pred_leaf
        )

    # ------------------------------------------------------------------
    def save_model(self, filename: str, num_iteration: int = -1) -> "Booster":
        with open(filename, "w") as f:
            f.write(self.model_to_string(num_iteration))
        return self

    def model_to_string(self, num_iteration: int = -1) -> str:
        s = self.boosting.save_model_to_string(num_iteration)
        if self.pandas_categorical:
            import json

            s += "\npandas_categorical:" + json.dumps(
                self.pandas_categorical, default=str
            ) + "\n"
        return s

    def dump_model(self, num_iteration: int = -1) -> dict:
        """JSON dump (GBDT::DumpModel, gbdt.cpp:702-736)."""
        b = self.boosting
        return {
            "name": b.sub_model_name(),
            "version": "v2",
            "num_class": b.num_class,
            "num_tree_per_iteration": b.num_tree_per_iteration,
            "label_index": b.label_idx,
            "max_feature_idx": b.max_feature_idx,
            "objective": b.objective.to_string() if b.objective else "",
            "feature_names": list(b.feature_names),
            "tree_info": [t.to_json() for t in b._used_models(num_iteration)],
        }

    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        return self.boosting.feature_importance(importance_type)

    def feature_name(self) -> List[str]:
        return list(self.boosting.feature_names)

    # pickling support: serialize via model string
    def __getstate__(self):
        return {
            "params": self.params,
            "model_str": self.model_to_string(),
            "best_iteration": self.best_iteration,
            "best_score": self.best_score,
        }

    def __setstate__(self, state):
        new = Booster(params=state["params"], model_str=state["model_str"])
        self.__dict__.update(new.__dict__)
        self.best_iteration = state["best_iteration"]
        self.best_score = state["best_score"]

    def __copy__(self):
        return self.__deepcopy__(None)

    def __deepcopy__(self, memo):
        return Booster(params=self.params, model_str=self.model_to_string())
