"""Placeholder — implemented in a later milestone."""
class Dataset:
    pass


class Booster:
    pass
