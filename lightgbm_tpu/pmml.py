"""PMML export — counterpart of pmml/pmml.py (reference): convert a saved
model (text format or in-memory Booster) to PMML XML.  Like the reference,
supports regression and binary objectives (tree ensembles with numerical /
categorical simple predicates).
"""

from __future__ import annotations

import itertools
from typing import List, Optional

from .basic import Booster
from .utils.log import Log

_HEADER = """<?xml version="1.0" encoding="UTF-8"?>
<PMML version="4.3" xmlns="http://www.dmg.org/PMML-4_3">
\t<Header copyright="lightgbm_tpu">
\t\t<Application name="lightgbm_tpu"/>
\t</Header>
"""


def _tree_pmml(tree, feature_names: List[str], unique_id) -> List[str]:
    """One tree as a PMML TreeModel Node hierarchy (pmml.py
    print_nodes_pmml)."""
    lines: List[str] = []

    def predicate(tab, node_id, is_left, prev_idx, is_leaf):
        idx = tree.leaf_parent[node_id] if is_leaf else prev_idx
        field = feature_names[tree.split_feature[idx]]
        thr = tree.threshold[idx]
        if is_left:
            op = "equal" if tree.decision_type[prev_idx] == 1 else "lessOrEqual"
        else:
            op = "notEqual" if tree.decision_type[prev_idx] == 1 else "greaterThan"
        lines.append(
            "\t" * (tab + 1)
            + f'<SimplePredicate field="{field}" operator="{op}" value="{thr:.17g}" />'
        )

    def walk(node_id, tab, is_left, prev_idx):
        if node_id < 0:
            node_id = ~node_id
            score = tree.leaf_value[node_id]
            count = tree.leaf_count[node_id]
            is_leaf = True
        else:
            score = tree.internal_value[node_id]
            count = tree.internal_count[node_id]
            is_leaf = False
        lines.append(
            "\t" * tab
            + f'<Node id="{next(unique_id)}" score="{score:.17g}" recordCount="{count}">'
        )
        if prev_idx is not None:
            predicate(tab, node_id, is_left, prev_idx, is_leaf)
        else:
            lines.append("\t" * (tab + 1) + "<True />")
        if not is_leaf:
            walk(tree.left_child[node_id], tab + 1, True, node_id)
            walk(tree.right_child[node_id], tab + 1, False, node_id)
        lines.append("\t" * tab + "</Node>")

    if tree.num_leaves > 1:
        walk(0, 4, True, None)
    else:
        lines.append(
            "\t" * 4
            + f'<Node id="{next(unique_id)}" score="{tree.leaf_value[0]:.17g}" recordCount="0">'
        )
        lines.append("\t" * 5 + "<True />")
        lines.append("\t" * 4 + "</Node>")
    return lines


def model_to_pmml(booster: Booster, model_name: str = "LightGBM_tpu_model") -> str:
    """Booster -> PMML string (regression / binary, like the reference)."""
    b = booster.boosting
    obj = b.objective.name if b.objective is not None else "regression"
    if obj not in ("regression", "regression_l1", "huber", "fair", "poisson",
                   "binary"):
        Log.fatal("PMML export supports regression and binary objectives, got %s", obj)
    feature_names = b.feature_names or [
        f"Column_{i}" for i in range(b.max_feature_idx + 1)
    ]
    func = "classification" if obj == "binary" else "regression"

    out = [_HEADER]
    out.append("\t<DataDictionary>")
    for name in feature_names:
        out.append(
            f'\t\t<DataField name="{name}" optype="continuous" dataType="double"/>'
        )
    out.append('\t\t<DataField name="prediction" optype="continuous" dataType="double"/>')
    out.append("\t</DataDictionary>")
    out.append(
        f'\t<MiningModel modelName="{model_name}" functionName="regression">'
    )
    out.append("\t\t<MiningSchema>")
    for name in feature_names:
        out.append(f'\t\t\t<MiningField name="{name}"/>')
    out.append('\t\t\t<MiningField name="prediction" usageType="target"/>')
    out.append("\t\t</MiningSchema>")
    if obj == "binary":
        out.append("\t\t<Output>")
        out.append(
            '\t\t\t<OutputField name="probability" optype="continuous" '
            'dataType="double" feature="transformedValue">'
        )
        out.append(
            "\t\t\t\t<Apply function=\"/\"><NumericConstant>1</NumericConstant>"
            "<Apply function=\"+\"><NumericConstant>1</NumericConstant>"
            "<Apply function=\"exp\"><Apply function=\"*\">"
            "<NumericConstant>-1</NumericConstant>"
            "<FieldRef field=\"prediction\"/></Apply></Apply></Apply></Apply>"
        )
        out.append("\t\t\t</OutputField>")
        out.append("\t\t</Output>")
    out.append(
        '\t\t<Segmentation multipleModelMethod="sum">'
    )
    unique_id = itertools.count(1)
    for i, tree in enumerate(b.models):
        out.append(f'\t\t\t<Segment id="{i + 1}">')
        out.append("\t\t\t\t<True />")
        out.append(
            '\t\t\t\t<TreeModel functionName="regression" '
            'splitCharacteristic="binarySplit">'
        )
        out.append("\t\t\t\t\t<MiningSchema>")
        for name in feature_names:
            out.append(f'\t\t\t\t\t\t<MiningField name="{name}"/>')
        out.append("\t\t\t\t\t</MiningSchema>")
        out.extend(_tree_pmml(tree, feature_names, unique_id))
        out.append("\t\t\t\t</TreeModel>")
        out.append("\t\t\t</Segment>")
    out.append("\t\t</Segmentation>")
    out.append("\t</MiningModel>")
    out.append("</PMML>")
    return "\n".join(out) + "\n"


def pmml_from_model_file(model_path: str, out_path: Optional[str] = None) -> str:
    """CLI-style conversion of a saved model file (pmml.py __main__)."""
    booster = Booster(model_file=model_path)
    pmml = model_to_pmml(booster)
    if out_path:
        with open(out_path, "w") as f:
            f.write(pmml)
    return pmml


if __name__ == "__main__":
    import sys

    if len(sys.argv) < 2:
        print("usage: python -m lightgbm_tpu.pmml <model.txt> [out.pmml]")
        sys.exit(1)
    res = pmml_from_model_file(sys.argv[1], sys.argv[2] if len(sys.argv) > 2 else None)
    if len(sys.argv) <= 2:
        print(res)
