"""Training/cv entry points — counterpart of
python-package/lightgbm/engine.py (train:17, cv:~250).
"""

from __future__ import annotations

import collections
import copy
from typing import Any, Dict, List, Optional

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset
from .ckpt.manager import PreemptionExit
from .config import canonicalize_params
from .obs import tracer
from .obs.audit import audit
from .parallel.net import NetError
from .utils.log import Log


def train(
    params: Dict[str, Any],
    train_set: Dataset,
    num_boost_round: int = 100,
    valid_sets=None,
    valid_names=None,
    fobj=None,
    feval=None,
    init_model=None,
    feature_name="auto",
    categorical_feature="auto",
    early_stopping_rounds: Optional[int] = None,
    evals_result: Optional[dict] = None,
    verbose_eval=True,
    learning_rates=None,
    keep_training_booster: bool = True,
    callbacks=None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_freq: int = 0,
    checkpoint_keep: int = 3,
    checkpoint_resume="auto",
    checkpoint_manager=None,
) -> Booster:
    """lgb.train (engine.py:17-199).

    Fault tolerance (TPU extension, docs/CHECKPOINT.md): pass
    ``checkpoint_dir``/``checkpoint_freq`` (or a prebuilt
    ``CheckpointManager`` via ``checkpoint_manager``) to write full
    training-state checkpoints every ``checkpoint_freq`` iterations.
    ``checkpoint_resume`` is ``"auto"`` (resume only an interrupted
    run), ``False`` (never), or ``"force"`` (require a checkpoint).
    A resumed run is bit-identical to one that never died.  Multihost
    checkpoints are saved in a canonical topology-free layout, so a
    run may resume on a *different* world size (elastic resume — same
    world stays byte-identical; a resized fleet reshards and continues
    from the same iteration).  ``rebalance=True`` additionally lets a
    data-parallel fleet shift shard boundaries off a persistently slow
    host at iteration boundaries (docs/ROBUSTNESS.md)."""
    tracer.refresh_from_env()  # LIGHTGBM_TPU_TRACE=trace.jsonl
    audit.refresh_from_env()   # LIGHTGBM_TPU_AUDIT=audit.jsonl
    params = dict(params or {})
    canon = canonicalize_params(params)
    num_boost_round = int(canon.pop("num_iterations", num_boost_round))
    if "early_stopping_round" in canon:
        early_stopping_rounds = int(canon["early_stopping_round"])
    # strip the loop-controlling keys: the python loop owns iteration count
    # and early stopping (engine.py:100-118), not the inner driver
    for alias in ("num_iterations", "num_iteration", "num_tree", "num_trees",
                  "num_round", "num_rounds", "num_boost_round",
                  "early_stopping_round", "early_stopping_rounds",
                  "early_stopping"):
        params.pop(alias, None)

    if fobj is not None:
        params.setdefault("objective", "none")
    if feature_name != "auto":
        train_set.feature_name = feature_name
    if categorical_feature != "auto":
        train_set.categorical_feature = categorical_feature

    with tracer.span("booster_init"):
        booster = Booster(params=params, train_set=train_set)
    tracer.event(
        "train_begin", num_boost_round=num_boost_round,
        objective=str(params.get("objective", "")),
        num_leaves=str(params.get("num_leaves", "")),
        num_data=train_set.num_data(),
        mode="out_of_core" if getattr(booster.boosting, "ooc", None)
        is not None else "in_memory",
    )
    if init_model is not None:
        _apply_init_model(booster, init_model, train_set)

    # valid sets
    valid_list: List[Dataset] = []
    name_list: List[str] = []
    if valid_sets is not None:
        if isinstance(valid_sets, Dataset):
            valid_sets = [valid_sets]
        for i, vs in enumerate(valid_sets):
            if vs is train_set:
                name_list.append("training")
                valid_list.append(None)  # marker: evaluate on train scores
                continue
            if valid_names is not None and i < len(valid_names):
                name = valid_names[i]
            else:
                name = f"valid_{i}"
            booster.add_valid(vs, name)
            valid_list.append(vs)
            name_list.append(name)

    eval_train = "training" in name_list

    # callbacks (engine.py:120-152)
    cbs = set(callbacks or [])
    if verbose_eval is True:
        cbs.add(callback_mod.print_evaluation())
    elif isinstance(verbose_eval, int) and verbose_eval is not False:
        cbs.add(callback_mod.print_evaluation(verbose_eval))
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        cbs.add(callback_mod.early_stopping(early_stopping_rounds,
                                            verbose=bool(verbose_eval)))
    if learning_rates is not None:
        cbs.add(callback_mod.reset_parameter(learning_rate=learning_rates))
    if evals_result is not None:
        cbs.add(callback_mod.record_evaluation(evals_result))
    cbs_before = {c for c in cbs if getattr(c, "before_iteration", False)}
    cbs_after = cbs - cbs_before
    cbs_before = sorted(cbs_before, key=lambda c: getattr(c, "order", 0))
    cbs_after = sorted(cbs_after, key=lambda c: getattr(c, "order", 0))

    # checkpoint/resume wiring (ckpt/, docs/CHECKPOINT.md): params may
    # carry the config-level knobs; explicit arguments win
    ckpt_mgr = checkpoint_manager
    own_mgr = False
    if ckpt_mgr is None:
        cdir = checkpoint_dir or str(canon.get("checkpoint_dir", "") or "")
        if cdir:
            from .ckpt import CheckpointManager

            cfreq = int(checkpoint_freq or canon.get("checkpoint_freq", 0) or 0)
            ckpt_mgr = CheckpointManager(
                cdir, freq=cfreq,
                keep_last=int(canon.get("checkpoint_keep", checkpoint_keep)),
            )
            own_mgr = True
    start_iter = 0
    if ckpt_mgr is not None:
        ckpt_mgr.track_callbacks(list(cbs_before) + list(cbs_after))
        cbs_after = sorted(cbs_after + [ckpt_mgr],
                           key=lambda c: getattr(c, "order", 0))
        resume = checkpoint_resume
        if isinstance(resume, str):
            resume = resume.lower()
        if resume not in (False, None, "false", "0", "none"):
            state = ckpt_mgr.try_restore(
                booster, require=(resume == "force"),
                ignore_complete=(resume == "force"),
            )
            if state is not None:
                start_iter = state.iteration

    def _net_abort(e: NetError) -> None:
        """Cooperative abort (docs/ROBUSTNESS.md): a peer died or a
        collective timed out.  Flush the last completed checkpoint so it
        is durable, then let the typed error propagate — the CLI maps it
        to a retryable exit code and the next ``task=train`` auto-resumes
        bit-identically from that boundary."""
        if ckpt_mgr is not None:
            try:
                ckpt_mgr.flush()
            except Exception:  # pragma: no cover - disk-full etc.
                pass
        Log.warning(
            "Training aborted by transport failure (%s): %s — latest "
            "completed checkpoint preserved; rerun to auto-resume",
            type(e).__name__, e,
        )

    def _finalize(b: Booster) -> Booster:
        if ckpt_mgr is not None:
            if ckpt_mgr.preempted:
                ckpt_mgr.flush()  # preempted: leave resumable state
            else:
                ckpt_mgr.mark_complete(b)
            if own_mgr:
                ckpt_mgr.close()
        return b

    def _ckpt_bounded(step: int, i: int) -> int:
        """Clip a fused-chunk length so chunk ends land on checkpoint
        boundaries (the manager can only capture between dispatches)."""
        if ckpt_mgr is not None and ckpt_mgr.freq > 0:
            step = min(step, ckpt_mgr.freq - (i % ckpt_mgr.freq))
        return max(step, 1)

    # Fused fast path: with no per-iteration host decisions (no valid
    # sets, no custom objective, no before-iteration callbacks, no early
    # stopping) the whole run executes as chunked device programs —
    # per-iteration host round-trips cost ~80 ms on a tunneled TPU.
    ptrainer = getattr(booster.boosting, "ptrainer", None)
    if (
        ptrainer is not None
        and fobj is None
        and not name_list
        and not cbs_before
        and not (early_stopping_rounds and early_stopping_rounds > 0)
    ):
        i = start_iter
        stopped = False
        while i < num_boost_round and not stopped:
            step = _ckpt_bounded(num_boost_round - i, i)
            iter_before = booster.boosting.iter
            stopped = booster.boosting.train_iters_partitioned(step, is_eval=False)
            done = booster.boosting.iter - iter_before
            try:
                for t in range(done):
                    for cb in cbs_after:
                        cb(callback_mod.CallbackEnv(
                            booster, params, i + t, 0, num_boost_round, []))
            except PreemptionExit:
                booster.best_iteration = booster.current_iteration()
                return _finalize(booster)
            except NetError as ne:
                _net_abort(ne)
                raise
            i += done
            if done < step:
                Log.info("Finished training with %d iterations", i)
                break
        booster.best_iteration = booster.current_iteration()
        return _finalize(booster)

    # Fused path WITH eval: when an eval period > 1 is configured
    # (output_freq, or an integer verbose_eval), run fused chunks of
    # ``period`` iterations between eval points instead of dropping to
    # one-dispatch-per-iteration; early stopping and the periodic
    # callbacks consume chunk-boundary metrics.  (The reference's CLI
    # evaluates at output_freq granularity the same way,
    # application.cpp:225-250; the python API's per-iteration eval is
    # preserved whenever period == 1.)
    # opt-in is output_freq ONLY: an integer verbose_eval controls PRINT
    # frequency in the reference API, never evaluation frequency, so it
    # must not change which iterations get evaluated
    period = int(canon.get("output_freq", 1))
    if (
        ptrainer is not None
        and fobj is None
        and not cbs_before
        and period > 1
    ):
        i = start_iter
        while i < num_boost_round:
            step = _ckpt_bounded(min(period, num_boost_round - i), i)
            iter_before = booster.boosting.iter
            booster.boosting.train_iters_partitioned(step, is_eval=False)
            done = booster.boosting.iter - iter_before
            i += done
            evaluation_result_list = []
            if valid_sets is not None or eval_train:
                with tracer.span("eval", iter=i):
                    if eval_train:
                        evaluation_result_list.extend(booster.eval_train(feval))
                    evaluation_result_list.extend(booster.eval_valid(feval))
            try:
                for cb in cbs_after:
                    cb(callback_mod.CallbackEnv(
                        booster, params, i - 1, 0, num_boost_round,
                        evaluation_result_list))
            except callback_mod.EarlyStopException as es:
                booster.best_iteration = es.best_iteration + 1
                _record_best_score(booster, es.best_score)
                break
            except PreemptionExit:
                break
            except NetError as ne:
                _net_abort(ne)
                raise
            if done < step:
                Log.info("Finished training with %d iterations", i)
                break
        if booster.best_iteration <= 0:
            booster.best_iteration = booster.current_iteration()
        return _finalize(booster)

    # training loop
    for i in range(start_iter, num_boost_round):
        for cb in cbs_before:
            cb(callback_mod.CallbackEnv(booster, params, i, 0, num_boost_round, None))
        finished = booster.update(fobj=fobj)
        evaluation_result_list = []
        if valid_sets is not None or eval_train:
            with tracer.span("eval", iter=i):
                if eval_train:
                    evaluation_result_list.extend(booster.eval_train(feval))
                evaluation_result_list.extend(booster.eval_valid(feval))
        try:
            for cb in cbs_after:
                cb(callback_mod.CallbackEnv(
                    booster, params, i, 0, num_boost_round, evaluation_result_list))
        except callback_mod.EarlyStopException as es:
            booster.best_iteration = es.best_iteration + 1
            _record_best_score(booster, es.best_score)
            break
        except PreemptionExit:
            break
        except NetError as ne:
            _net_abort(ne)
            raise
        if finished:
            Log.info("Finished training with %d iterations", i + 1)
            break
    if booster.best_iteration <= 0:
        booster.best_iteration = booster.current_iteration()
    return _finalize(booster)


def _metric_rank(name: str, params: Dict[str, Any]) -> int:
    """Position of a result metric in the configured metric list (prefix
    match tolerates decorated names like ndcg@5); unknown -> end."""
    metric = params.get("metric", "")
    if isinstance(metric, str):
        # Config._parse_list accepts comma OR whitespace separators
        metric = [m for m in metric.replace(",", " ").split() if m]
    for i, m in enumerate(metric or []):
        if name == m or name.startswith(str(m)):
            return i
    return 1 << 30


def _record_best_score(booster: Booster, best_score_list) -> None:
    if not best_score_list:
        return
    out: Dict[str, Dict[str, float]] = collections.defaultdict(dict)
    for item in best_score_list:
        out[item[0]][item[1]] = item[2]
    booster.best_score = dict(out)


def _apply_init_model(booster: Booster, init_model, train_set: Dataset) -> None:
    """Continued training (engine.py init_model / gbdt.cpp input_model):
    load the model and seed the training scores with its predictions."""
    if isinstance(init_model, Booster):
        model_str = init_model.model_to_string()
    else:
        with open(init_model) as f:
            model_str = f.read()
    prev = Booster(params=booster.params, model_str=model_str)
    b = booster.boosting
    # schema-drift guard: a feature-count mismatch used to surface as a
    # shape error deep in the trainer (or silent garbage predictions
    # when the new data happens to be wider).  The continuous-training
    # factory hits this whenever the watched data directory drifts, so
    # name the mismatch and the fix here instead.
    prev_nf = int(getattr(prev.boosting, "max_feature_idx", -1)) + 1
    new_nf = int(train_set.num_feature())
    if prev_nf > 0 and prev_nf != new_nf:
        Log.fatal(
            "init_model was trained on %d features but the new training "
            "data has %d — continued training requires the same feature "
            "schema (same columns, same order). Retrain from scratch, or "
            "fix the data source that drifted.", prev_nf, new_nf)
    prev_tpi = int(max(prev.boosting.num_tree_per_iteration, 1))
    new_tpi = int(max(b.num_tree_per_iteration, 1))
    if prev_tpi != new_tpi:
        Log.fatal(
            "init_model boosts %d tree(s) per iteration but the new "
            "training config boosts %d (different objective/num_class?) "
            "— continued training requires the same objective shape.",
            prev_tpi, new_tpi)
    b.models = prev.boosting.models + b.models
    b.num_init_iteration = len(prev.boosting.models) // max(
        prev.boosting.num_tree_per_iteration, 1
    )
    b.boost_from_average_ = prev.boosting.boost_from_average_
    raw = train_set.data
    if raw is None:
        Log.fatal("Continued training requires the raw training data")
    import jax.numpy as jnp

    init_scores = prev.boosting.predict_raw_scores(np.asarray(raw, np.float64))
    b.scores = b.scores + jnp.asarray(init_scores.astype(np.float32))


def cv(
    params: Dict[str, Any],
    train_set: Dataset,
    num_boost_round: int = 10,
    folds=None,
    nfold: int = 5,
    stratified: bool = False,
    shuffle: bool = True,
    metrics=None,
    fobj=None,
    feval=None,
    init_model=None,
    feature_name="auto",
    categorical_feature="auto",
    early_stopping_rounds: Optional[int] = None,
    fpreproc=None,
    verbose_eval=None,
    show_stdv: bool = True,
    seed: int = 0,
    callbacks=None,
) -> Dict[str, List[float]]:
    """lgb.cv (engine.py:~250-400): k-fold cross-validation returning
    {metric-mean: [...], metric-stdv: [...]}."""
    params = dict(params or {})
    if metrics is not None:
        params["metric"] = metrics
    canon = canonicalize_params(params)
    num_boost_round = int(canon.pop("num_iterations", num_boost_round))
    for alias in ("num_iterations", "num_iteration", "num_tree", "num_trees",
                  "num_round", "num_rounds", "num_boost_round"):
        params.pop(alias, None)

    full = train_set.construct()
    n = full.num_data
    label = np.asarray(full.metadata.label)

    # build folds (engine.py _make_n_folds)
    if folds is None:
        rng = np.random.RandomState(seed)
        if stratified:
            try:
                from sklearn.model_selection import StratifiedKFold

                skf = StratifiedKFold(n_splits=nfold, shuffle=shuffle,
                                      random_state=seed if shuffle else None)
                folds = list(skf.split(np.zeros(n), label))
            except ImportError:
                stratified = False
        if not stratified:
            idx = rng.permutation(n) if shuffle else np.arange(n)
            parts = np.array_split(idx, nfold)
            folds = [
                (np.concatenate([parts[j] for j in range(nfold) if j != i]), parts[i])
                for i in range(nfold)
            ]

    boosters = []
    for train_idx, test_idx in folds:
        tr = train_set.subset(np.sort(train_idx))
        te = train_set.subset(np.sort(test_idx))
        fold_params = params.copy()
        if fpreproc is not None:
            # per-fold params stay local (reference engine's tparam)
            tr, te, fold_params = fpreproc(tr, te, fold_params)
        bst = Booster(params=fold_params, train_set=tr)
        bst.add_valid(te, "valid")
        boosters.append(bst)

    results = collections.defaultdict(list)
    best_iter = num_boost_round
    history: List[Dict[str, float]] = []
    for i in range(num_boost_round):
        merged = collections.defaultdict(list)
        for bst in boosters:
            bst.update(fobj=fobj)
            for _, name, val, bigger in bst.eval_valid(feval):
                merged[(name, bigger)].append(val)
        one = {}
        for (name, bigger), vals in merged.items():
            mean, std = float(np.mean(vals)), float(np.std(vals))
            results[name + "-mean"].append(mean)
            results[name + "-stdv"].append(std)
            one[name] = (mean, bigger)
        history.append(one)
        if verbose_eval:
            msg = "\t".join(
                f"cv_agg {k}: {results[k + '-mean'][-1]:g} + {results[k + '-stdv'][-1]:g}"
                for k in {name for (name, _) in merged}
            )
            Log.info("[%d]\t%s", i + 1, msg)
        if early_stopping_rounds and len(history) > early_stopping_rounds:
            # stop on the FIRST configured metric (the reference keys
            # early stopping off config order, not dict iteration order)
            first = min(merged.keys(), key=lambda kb: _metric_rank(kb[0], params))
            (name, bigger) = first
            series = results[name + "-mean"]
            best = int(np.argmax(series) if bigger else np.argmin(series))
            if len(series) - 1 - best >= early_stopping_rounds:
                for k in list(results.keys()):
                    results[k] = results[k][: best + 1]
                break
    return dict(results)
