"""Placeholder — implemented in a later milestone."""
def train(*a, **k):
    raise NotImplementedError


def cv(*a, **k):
    raise NotImplementedError
