"""Partitioned in-program leaf-wise grower — the performance tree learner.

Counterpart of SerialTreeLearner::Train + DataPartition
(src/treelearner/serial_tree_learner.cpp:152-207, data_partition.hpp) with
the reference's asymptotics restored on TPU: rows live physically
partitioned by leaf inside the packed (C, N) matrix of ops/pkernels.py,
so each split costs O(parent segment) streaming (partition) plus
O(smaller child) histogram work — not O(N) — and the whole tree grows
inside ONE XLA program (a lax.while_loop over best-first splits, ~3 us
kernel dispatch per split, zero host round-trips).

vs ops/grow.py (the mask-based single-program grower): that pays a full
O(N) masked pass per split (~10 ms at 1M rows -> 2.5 s per 255-leaf
tree).  This grower runs the same tree in ~40 ms.  grow.py remains the
shard_map-distributed path (collectives) and the small-data path.

The histogram subtraction trick (FeatureHistogram::Subtract,
feature_histogram.hpp:63) carries over unchanged: only the child with
fewer physical rows is streamed; the sibling is parent - smaller.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .pkernels import BLK, PLayout, hist_dyn, partition_segment
from .split import (
    NEG_INF,
    FeatureMeta,
    SplitHyper,
    best_split_per_feature,
    finalize_split,
    leaf_output,
)


class PGrowParams(NamedTuple):
    """Static (compile-time) parameters of the partitioned grower."""

    num_leaves: int
    num_bins: int  # padded per-feature B (<= 256)
    num_features: int
    num_rows: int  # real data rows (P has BLK tail padding)
    max_depth: int = -1
    use_missing: bool = True
    has_categorical: bool = True  # static: skips the categorical split scan
    # EFB: physical matrix columns / histogram bins per column.  0 means
    # unbundled (columns == features, bins == num_bins).
    num_cols: int = 0
    num_bins_hist: int = 0
    # bin word width: 4 (Dense4bitsBin form, 8 bins/word) when every
    # column fits 16 bins, else 8
    bits: int = 8


class BundleMeta(NamedTuple):
    """Device-side EFB maps (io/bundle.py BundleInfo, shipped once).

    idx maps (feature, feature-bin) -> flat bundle-histogram slot, with
    default/padding bins pointing at the appended zero slot; the default
    bin's mass is reconstructed as leaf_totals - non-default sums
    (exactly the reference's bias/zero-bin subtraction in
    FeatureHistogram::FindBestThreshold)."""

    col: jnp.ndarray  # (F,) int32 bundle column per feature
    off_lo: jnp.ndarray  # (F,) int32
    off_hi: jnp.ndarray  # (F,) int32
    bias: jnp.ndarray  # (F,) int32
    idx: jnp.ndarray  # (F, B) int32 into (G*BH [+1 zero slot], 3)
    defmask: jnp.ndarray  # (F, B) bool


def _expand_bundle_hist(hist_g, sums, bmeta: BundleMeta, f: int, b: int):
    """(G, BH, 3) bundle histogram -> (F, B, 3) per-feature histograms."""
    flat = jnp.concatenate([hist_g.reshape(-1, 3), jnp.zeros((1, 3))], axis=0)
    hf = flat[bmeta.idx.reshape(-1)].reshape(f, b, 3)
    nd_sums = jnp.sum(hf, axis=1)  # (F, 3): non-default mass
    dfl = sums[None, :] - nd_sums
    return jnp.where(bmeta.defmask[:, :, None], dfl[:, None, :], hf)


class PTreeResult(NamedTuple):
    """One grown tree: split records (same contract as ops/grow.GrowResult
    minus leaf_id — the partitioned layout replaces it with the segment
    table) plus the final leaf segments for the in-place score update."""

    num_splits: jnp.ndarray  # scalar int32
    starts: jnp.ndarray  # (L,) int32 physical segment start per leaf
    cnts: jnp.ndarray  # (L,) int32 physical rows per leaf
    leaf_value: jnp.ndarray  # (L,) raw (pre-shrinkage) outputs
    leaf_cnt: jnp.ndarray  # (L,) f32 selected counts
    rec_leaf: jnp.ndarray
    rec_feat: jnp.ndarray
    rec_thr: jnp.ndarray
    rec_dbz: jnp.ndarray
    rec_gain: jnp.ndarray
    rec_lval: jnp.ndarray
    rec_rval: jnp.ndarray
    rec_lcnt: jnp.ndarray
    rec_rcnt: jnp.ndarray
    rec_internal_value: jnp.ndarray


class _PState(NamedTuple):
    p: jnp.ndarray
    scratch: jnp.ndarray
    num_splits: jnp.ndarray
    done: jnp.ndarray
    starts: jnp.ndarray
    cnts: jnp.ndarray
    pool: jnp.ndarray  # (L, F, B, 3)
    bs_gain: jnp.ndarray
    bs_feat: jnp.ndarray
    bs_thr: jnp.ndarray
    bs_dbz: jnp.ndarray
    bs_left: jnp.ndarray  # (L, 3)
    leaf_sum: jnp.ndarray  # (L, 3)
    leaf_value: jnp.ndarray
    leaf_cnt: jnp.ndarray
    leaf_depth: jnp.ndarray
    rec_leaf: jnp.ndarray
    rec_feat: jnp.ndarray
    rec_thr: jnp.ndarray
    rec_dbz: jnp.ndarray
    rec_gain: jnp.ndarray
    rec_lval: jnp.ndarray
    rec_rval: jnp.ndarray
    rec_lcnt: jnp.ndarray
    rec_rcnt: jnp.ndarray
    rec_internal_value: jnp.ndarray


def _store_split(st: _PState, leaf, res) -> _PState:
    return st._replace(
        bs_gain=st.bs_gain.at[leaf].set(res.gain),
        bs_feat=st.bs_feat.at[leaf].set(res.feature),
        bs_thr=st.bs_thr.at[leaf].set(res.threshold_bin),
        bs_dbz=st.bs_dbz.at[leaf].set(res.default_bin_for_zero),
        bs_left=st.bs_left.at[leaf].set(
            jnp.stack([res.left_sum_g, res.left_sum_h, res.left_cnt])
        ),
    )


@functools.partial(jax.jit, static_argnames=("params", "interpret"))
def grow_tree_partitioned(
    p: jnp.ndarray,
    scratch: jnp.ndarray,
    feature_mask: jnp.ndarray,
    meta: FeatureMeta,
    hyper: SplitHyper,
    params: PGrowParams,
    bmeta: BundleMeta = None,
    interpret: bool = False,
):
    """Grow one leaf-wise tree over the partitioned matrix.

    Returns (PTreeResult, p', scratch').  ``p`` arrives with the g/h/sel
    channels freshly written for this tree; row ORDER is whatever the
    previous tree left (irrelevant — the root segment is always the full
    [0, num_rows) range and histograms are order-invariant)."""
    L = params.num_leaves
    F = params.num_features
    B = params.num_bins
    n = params.num_rows
    # physical columns the kernels stream (EFB bundles or plain features)
    G = params.num_cols or F
    BH = params.num_bins_hist or B
    bundled = bmeta is not None

    def find_best(hist, sums, depth_ok):
        sg, sh, sc = sums[0], sums[1], sums[2]
        if bundled:
            hist = _expand_bundle_hist(hist, sums, bmeta, F, B)
        gain_f, thr_f, dbz_f, left_f = best_split_per_feature(
            hist, sg, sh, sc, meta, hyper, feature_mask, params.use_missing,
            has_categorical=params.has_categorical,
        )
        res = finalize_split(gain_f, thr_f, dbz_f, left_f, sg, sh, sc, hyper)
        return res._replace(gain=jnp.where(depth_ok, res.gain, NEG_INF))

    root_hist = hist_dyn(p, 0, n, G, BH, bits=params.bits, interpret=interpret)
    root_sums = jnp.sum(root_hist[0], axis=0)  # (3,): totals via feature 0
    root_res = find_best(root_hist, root_sums, jnp.array(True))

    zi = jnp.zeros((L,), jnp.int32)
    zf = jnp.zeros((L,))
    zr = jnp.zeros((L - 1,))
    zri = jnp.zeros((L - 1,), jnp.int32)
    st = _PState(
        p=p,
        scratch=scratch,
        num_splits=jnp.int32(0),
        done=jnp.array(False),
        starts=zi,
        cnts=zi.at[0].set(n),
        pool=jnp.zeros((L, G, BH, 3)).at[0].set(root_hist),
        bs_gain=jnp.full((L,), NEG_INF),
        bs_feat=zi,
        bs_thr=zi,
        bs_dbz=zi,
        bs_left=jnp.zeros((L, 3)),
        leaf_sum=jnp.zeros((L, 3)).at[0].set(root_sums),
        leaf_value=zf.at[0].set(
            leaf_output(root_sums[0], root_sums[1], hyper.lambda_l1, hyper.lambda_l2)
        ),
        leaf_cnt=zf.at[0].set(root_sums[2]),
        leaf_depth=zi,
        rec_leaf=zri, rec_feat=zri, rec_thr=zri, rec_dbz=zri,
        rec_gain=zr, rec_lval=zr, rec_rval=zr, rec_lcnt=zr, rec_rcnt=zr,
        rec_internal_value=zr,
    )
    st = _store_split(st, 0, root_res)

    def cond(st: _PState):
        return (~st.done) & (st.num_splits < L - 1)

    def body(st: _PState):
        gain = jnp.max(st.bs_gain)
        return jax.lax.cond(gain > 0.0, _split, lambda s: s._replace(done=True), st)

    def _split(st: _PState):
        s = st.num_splits
        bl = jnp.argmax(st.bs_gain).astype(jnp.int32)
        right_leaf = (s + 1).astype(jnp.int32)

        feat = st.bs_feat[bl]
        thr = st.bs_thr[bl]
        dbz = st.bs_dbz[bl]
        gain = st.bs_gain[bl]
        start = st.starts[bl]
        cnt = st.cnts[bl]
        zb = meta.default_bin[feat]
        cat = meta.is_categorical[feat].astype(jnp.int32)
        if bundled:
            colidx = bmeta.col[feat]
            off_lo, off_hi, bias = bmeta.off_lo[feat], bmeta.off_hi[feat], bmeta.bias[feat]
        else:
            colidx = feat
            off_lo, off_hi, bias = jnp.int32(0), jnp.int32(256), jnp.int32(0)

        per = 32 // params.bits
        p, scratch, nl = partition_segment(
            st.p, st.scratch, start, cnt,
            colidx // per, (colidx % per) * params.bits, zb, dbz, thr, cat,
            off_lo=off_lo, off_hi=off_hi, bias=bias,
            bits=params.bits, interpret=interpret,
        )

        left = st.bs_left[bl]
        totals = st.leaf_sum[bl]
        right = totals - left
        lg, lh, lc = left[0], left[1], left[2]
        rg, rh, rc = right[0], right[1], right[2]
        lval = leaf_output(lg, lh, hyper.lambda_l1, hyper.lambda_l2)
        rval = leaf_output(rg, rh, hyper.lambda_l1, hyper.lambda_l2)

        # smaller child (by physical rows) streamed; sibling by subtraction
        nr = cnt - nl
        ils = nl < nr
        sm_start = jnp.where(ils, start, start + nl)
        sm_cnt = jnp.where(ils, nl, nr)
        sm_hist = hist_dyn(p, sm_start, sm_cnt, G, BH, bits=params.bits, interpret=interpret)
        lg_hist = st.pool[bl] - sm_hist
        left_hist = jnp.where(ils, sm_hist, lg_hist)
        right_hist = jnp.where(ils, lg_hist, sm_hist)
        pool = st.pool.at[bl].set(left_hist).at[right_leaf].set(right_hist)

        child_depth = st.leaf_depth[bl] + 1
        depth_ok = (
            jnp.array(True)
            if params.max_depth <= 0
            else child_depth < params.max_depth
        )
        lres = find_best(left_hist, left, depth_ok)
        rres = find_best(right_hist, right, depth_ok)

        st = st._replace(
            p=p,
            scratch=scratch,
            num_splits=s + 1,
            starts=st.starts.at[right_leaf].set(start + nl),
            cnts=st.cnts.at[bl].set(nl).at[right_leaf].set(nr),
            pool=pool,
            leaf_sum=st.leaf_sum.at[bl].set(left).at[right_leaf].set(right),
            leaf_value=st.leaf_value.at[bl].set(lval).at[right_leaf].set(rval),
            leaf_cnt=st.leaf_cnt.at[bl].set(lc).at[right_leaf].set(rc),
            leaf_depth=st.leaf_depth.at[bl].set(child_depth).at[right_leaf].set(child_depth),
            rec_leaf=st.rec_leaf.at[s].set(bl),
            rec_feat=st.rec_feat.at[s].set(feat),
            rec_thr=st.rec_thr.at[s].set(thr),
            rec_dbz=st.rec_dbz.at[s].set(dbz),
            rec_gain=st.rec_gain.at[s].set(gain),
            rec_lval=st.rec_lval.at[s].set(lval),
            rec_rval=st.rec_rval.at[s].set(rval),
            rec_lcnt=st.rec_lcnt.at[s].set(lc),
            rec_rcnt=st.rec_rcnt.at[s].set(rc),
            rec_internal_value=st.rec_internal_value.at[s].set(st.leaf_value[bl]),
        )
        st = _store_split(st, bl, lres)
        st = _store_split(st, right_leaf, rres)
        return st

    st = jax.lax.while_loop(cond, body, st)
    res = PTreeResult(
        num_splits=st.num_splits,
        starts=st.starts,
        cnts=st.cnts,
        leaf_value=st.leaf_value,
        leaf_cnt=st.leaf_cnt,
        rec_leaf=st.rec_leaf,
        rec_feat=st.rec_feat,
        rec_thr=st.rec_thr,
        rec_dbz=st.rec_dbz,
        rec_gain=st.rec_gain,
        rec_lval=st.rec_lval,
        rec_rval=st.rec_rval,
        rec_lcnt=st.rec_lcnt,
        rec_rcnt=st.rec_rcnt,
        rec_internal_value=st.rec_internal_value,
    )
    return res, st.p, st.scratch


def segment_values(tree: PTreeResult, num_rows: int, values: jnp.ndarray) -> jnp.ndarray:
    """(N,) vector assigning ``values[leaf]`` to each position of that
    leaf's segment — the partitioned-space replacement for
    leaf_id-indexed lookups.  Built scatter-free for TPU: the segments
    tile [0, N) contiguously, so the per-position value is a cumulative
    sum of per-boundary deltas (one tiny (L,) scatter + one (N,) cumsum
    instead of an (N,)-indexed gather)."""
    L = tree.starts.shape[0]
    active = jnp.arange(L) <= tree.num_splits
    starts = jnp.where(active, tree.starts, num_rows)
    order = jnp.argsort(starts)
    sorted_starts = starts[order]
    sorted_vals = jnp.where(active, values, 0.0)[order]
    prev = jnp.concatenate([jnp.zeros((1,)), sorted_vals[:-1]])
    deltas = sorted_vals - prev
    line = jnp.zeros((num_rows,), jnp.float32).at[
        jnp.clip(sorted_starts, 0, num_rows - 1)
    ].add(jnp.where(sorted_starts < num_rows, deltas, 0.0))
    return jnp.cumsum(line)


def leaf_id_from_segments(tree: PTreeResult, p: jnp.ndarray, layout: PLayout, num_rows: int) -> jnp.ndarray:
    """(N,) int32 leaf index in ORIGINAL row order (via the rowid
    channel) — the GrowResult.leaf_id contract for driver code that needs
    it (one O(N) scatter; avoided on the fast path)."""
    L = tree.starts.shape[0]
    leaf_at_pos = segment_values(
        tree, num_rows, jnp.arange(L, dtype=jnp.float32)
    ).astype(jnp.int32)
    rowid = p[layout.ROWID, :num_rows]
    return jnp.zeros((num_rows,), jnp.int32).at[rowid].set(leaf_at_pos)
