"""Partitioned in-program leaf-wise grower — the performance tree learner.

Counterpart of SerialTreeLearner::Train + DataPartition
(src/treelearner/serial_tree_learner.cpp:152-207, data_partition.hpp) with
the reference's asymptotics restored on TPU: rows live physically
partitioned by leaf inside the packed (C, N) matrix of ops/pkernels.py,
so each split costs ONE streaming pass over the parent segment
(``split_stream``: two-ended in-place partition + BOTH children's
histograms in the same pass) — not O(N) — and the whole tree grows
inside ONE XLA program (a lax.while_loop over best-first splits).

vs ops/grow.py (the mask-based single-program grower): that pays a full
O(N) masked pass per split (~10 ms at 1M rows -> 2.5 s per 255-leaf
tree).  This grower runs the same tree in tens of ms.  grow.py remains
the shard_map-distributed path (collectives) and the small-data path.

Design notes (v2, measured on v5e):
- The reference's histogram-subtraction trick
  (FeatureHistogram::Subtract, feature_histogram.hpp:63) is SUPERSEDED:
  both children's histograms fall out of the partition pass for free
  (the bin one-hots — the VPU-bound cost — are shared, and the value
  rows just widen 7->14 MXU sublanes), so the (L, F, B, 3) histogram
  pool and its per-split updates are gone entirely.
- Per-split bookkeeping is packed into FOUR wide arrays (seg/bs/leaf/
  recs) updated with one scatter each: per-op dispatch inside a TPU
  while_loop body costs ~1-2 us, so the old ~25 small updates were a
  measured ~150 us/split tax.
- Left/right split search runs as ONE vmapped call over the stacked
  (2, F, B, 3) children histograms.
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..obs.compilewatch import JitWatch
from .histogram_pallas import hist_segments
from .pkernels import (
    BLK,
    PLayout,
    _hist_from_rows,
    hist_dyn,
    level_stream,
    split_stream,
)
from .split import (
    NEG_INF,
    FeatureMeta,
    SplitHyper,
    best_split_per_feature,
    finalize_split,
    leaf_output,
)


class PGrowParams(NamedTuple):
    """Static (compile-time) parameters of the partitioned grower."""

    num_leaves: int
    num_bins: int  # padded per-feature B (<= 256)
    num_features: int
    num_rows: int  # real data rows (P has BLK tail padding)
    max_depth: int = -1
    use_missing: bool = True
    has_categorical: bool = True  # static: skips the categorical split scan
    # EFB: physical matrix columns / histogram bins per column.  0 means
    # unbundled (columns == features, bins == num_bins).
    num_cols: int = 0
    num_bins_hist: int = 0
    # bin word width: 4 (Dense4bitsBin form, 8 bins/word) when every
    # column fits 16 bins, else 8
    bits: int = 8
    # data-parallel mode: shard_map mesh axis to psum histograms over
    # (DataParallelTreeLearner, data_parallel_tree_learner.cpp:148-161 —
    # the ReduceScatter of local histograms becomes one psum; every
    # device then takes the identical best split on its local segment).
    # None/"" = serial.
    axis_name: str = None
    # level-batched expansion (phase 1) toggles.  These used to be env
    # reads (LIGHTGBM_TPU_LEVELGROW / LIGHTGBM_TPU_MAXLVL) at trace time
    # inside the jitted grower — invisible to the jit cache key, so a
    # mid-process env change silently did nothing.  They are now read
    # ONCE at trainer construction (boosting/ptrainer.py) and threaded
    # here, where the static params tuple IS the cache key.
    levelwise: bool = True
    max_levels: int = 24


def levelgrow_env_params() -> dict:
    """Read the level-grower env knobs once — construction-time helper
    for PGrowParams(**levelgrow_env_params())."""
    return {
        "levelwise": os.environ.get("LIGHTGBM_TPU_LEVELGROW", "1") != "0",
        "max_levels": int(os.environ.get("LIGHTGBM_TPU_MAXLVL", "24")),
    }


class BundleMeta(NamedTuple):
    """Device-side EFB maps (io/bundle.py BundleInfo, shipped once).

    idx maps (feature, feature-bin) -> flat bundle-histogram slot, with
    default/padding bins pointing at the appended zero slot; the default
    bin's mass is reconstructed as leaf_totals - non-default sums
    (exactly the reference's bias/zero-bin subtraction in
    FeatureHistogram::FindBestThreshold)."""

    col: jnp.ndarray  # (F,) int32 bundle column per feature
    off_lo: jnp.ndarray  # (F,) int32
    off_hi: jnp.ndarray  # (F,) int32
    bias: jnp.ndarray  # (F,) int32
    idx: jnp.ndarray  # (F, B) int32 into (G*BH [+1 zero slot], 3)
    defmask: jnp.ndarray  # (F, B) bool


def _expand_bundle_hist(hist_g, sums, bmeta: BundleMeta, f: int, b: int):
    """(G, BH, 3) bundle histogram -> (F, B, 3) per-feature histograms."""
    flat = jnp.concatenate([hist_g.reshape(-1, 3), jnp.zeros((1, 3))], axis=0)
    hf = flat[bmeta.idx.reshape(-1)].reshape(f, b, 3)
    nd_sums = jnp.sum(hf, axis=1)  # (F, 3): non-default mass
    dfl = sums[None, :] - nd_sums
    return jnp.where(bmeta.defmask[:, :, None], dfl[:, None, :], hf)


class PTreeResult(NamedTuple):
    """One grown tree: split records (same contract as ops/grow.GrowResult
    minus leaf_id — the partitioned layout replaces it with the segment
    table) plus the final leaf segments for the in-place score update."""

    num_splits: jnp.ndarray  # scalar int32
    starts: jnp.ndarray  # (L,) int32 physical segment start per leaf
    cnts: jnp.ndarray  # (L,) int32 physical rows per leaf
    leaf_value: jnp.ndarray  # (L,) raw (pre-shrinkage) outputs
    leaf_cnt: jnp.ndarray  # (L,) f32 selected counts
    recs_raw: jnp.ndarray  # (L-1, 12) f32 packed split records (the
    #   rec_* views below are slices of this; consumers inside fused
    #   loops should store recs_raw whole — one buffer update, not ten)
    rec_leaf: jnp.ndarray
    rec_feat: jnp.ndarray
    rec_thr: jnp.ndarray
    rec_dbz: jnp.ndarray
    rec_gain: jnp.ndarray
    rec_lval: jnp.ndarray
    rec_rval: jnp.ndarray
    rec_lcnt: jnp.ndarray
    rec_rcnt: jnp.ndarray
    rec_internal_value: jnp.ndarray


class _PState(NamedTuple):
    p: jnp.ndarray
    num_splits: jnp.ndarray
    done: jnp.ndarray
    seg: jnp.ndarray  # (L, 2) i32 [start, cnt]
    bs: jnp.ndarray  # (L, 8) f32 [gain, feat, thr, dbz, lg, lh, lc, 0]
    leaf: jnp.ndarray  # (L, 8) f32 [sum_g, sum_h, sum_c, value, cnt, depth, 0, 0]
    recs: jnp.ndarray  # (L-1, 12) f32 [leaf, feat, thr, dbz, gain, lval,
    #                                   rval, lcnt, rcnt, ival, 0, 0]
    pslot: jnp.ndarray  # (L,) i32 candidate-table slot of each pool leaf
    #   (>= 0: node came from the level-batched expansion; -1: classic)


def _meta_table(meta: FeatureMeta, bmeta, f: int, bits: int) -> jnp.ndarray:
    """(F, 8) f32 per-feature lookup (one gather per split instead of
    six): [default_bin, is_cat, col, off_lo, off_hi, bias, 0, 0].
    Integer values < 2^24 are exact in f32."""
    db = meta.default_bin.astype(jnp.float32)
    cat = meta.is_categorical.astype(jnp.float32)
    if bmeta is not None:
        col = bmeta.col.astype(jnp.float32)
        off_lo = bmeta.off_lo.astype(jnp.float32)
        off_hi = bmeta.off_hi.astype(jnp.float32)
        bias = bmeta.bias.astype(jnp.float32)
    else:
        col = jnp.arange(f, dtype=jnp.float32)
        off_lo = jnp.zeros((f,), jnp.float32)
        off_hi = jnp.full((f,), float(1 << bits), jnp.float32)
        bias = jnp.zeros((f,), jnp.float32)
    z = jnp.zeros((f,), jnp.float32)
    return jnp.stack([db, cat, col, off_lo, off_hi, bias, z, z], axis=1)


@functools.partial(jax.jit, static_argnames=("params", "interpret", "rows"),
                   donate_argnums=(0,))
def grow_tree_partitioned(
    p: jnp.ndarray,
    feature_mask: jnp.ndarray,
    meta: FeatureMeta,
    hyper: SplitHyper,
    params: PGrowParams,
    bmeta: BundleMeta = None,
    interpret: bool = False,
    root_hist: jnp.ndarray = None,
    rows: tuple = None,
):
    """Grow one leaf-wise tree over the partitioned matrix.

    Returns (PTreeResult, p').  ``p`` arrives with the g/h/sel channels
    freshly written for this tree; row ORDER is whatever the previous
    tree left (irrelevant — the root segment is always the full
    [0, num_rows) range and histograms are order-invariant).

    Two-phase growth (v3): per-split kernel launches cost ~0.3 ms of
    fixed overhead on the tunneled runtime — 2/3 of a 255-leaf iteration
    — so phase 1 expands the tree LEVEL-batched (one ``level_stream``
    launch partitions every active segment and emits all children
    histograms; one vmapped split-search per level), then phase 2 replays
    the reference's EXACT best-first selection (SerialTreeLearner::Train's
    argmax-over-leaves order, including the leaf-id tie order) as a cheap
    bookkeeping loop over the precomputed candidate tables.  Nodes the
    selection wants beyond the expanded depth fall back to the classic
    per-split ``split_stream`` path in the same loop.  The final tree is
    identical to the per-split grower's; only the kernel-launch count
    changes (~levels instead of ~num_leaves).  Set
    LIGHTGBM_TPU_LEVELGROW=0 (read once at trainer construction and
    threaded through ``params.levelwise``) to force the classic path."""
    L = params.num_leaves
    F = params.num_features
    B = params.num_bins
    n = params.num_rows
    # physical columns the kernels stream (EFB bundles or plain features)
    G = params.num_cols or F
    BH = params.num_bins_hist or B
    bundled = bmeta is not None
    if rows is None:
        # default single-class channel rows; multiclass callers pass
        # PLayout.class_rows(k) so tree k reads its own g/h pair
        rows = PLayout(G, bits=params.bits).rows
    per = 32 // params.bits
    mtab = _meta_table(meta, bmeta, F, params.bits)
    levelwise = params.levelwise and L > 4

    def find2(hist2, sums2, depth_ok):
        """Best split for sibling leaves at once: hist2 (2, G/F, B, 3),
        sums2 (2, 3) -> per-leaf scalars stacked on axis 0."""
        if bundled:
            hist2 = jax.vmap(
                lambda hh, ss: _expand_bundle_hist(hh, ss, bmeta, F, B)
            )(hist2, sums2)

        def one(hist, s):
            gain_f, thr_f, dbz_f, left_f = best_split_per_feature(
                hist, s[0], s[1], s[2], meta, hyper, feature_mask,
                params.use_missing, has_categorical=params.has_categorical,
            )
            return finalize_split(gain_f, thr_f, dbz_f, left_f, s[0], s[1], s[2], hyper)

        res = jax.vmap(one)(hist2, sums2)
        return res._replace(gain=jnp.where(depth_ok, res.gain, NEG_INF))

    if root_hist is None:
        if levelwise:
            # multi-leaf segmented histogram kernel (one launch covers a
            # whole level's segments; the root is level 0's single
            # segment) — bit-identical to hist_dyn: same per-block
            # accumulation order, same fchunk tuning, same 3-term re-sum
            seg0_tab = jnp.zeros((8, 2), jnp.int32).at[0, 1].set(n)
            root_hist = hist_segments(
                p, seg0_tab, 1, num_features=G, num_bins=BH,
                bits=params.bits, rows=rows, smax=8, interpret=interpret,
            )[0]
        else:
            root_hist = hist_dyn(p, 0, n, G, BH, bits=params.bits, rows=rows,
                                 interpret=interpret)
        if params.axis_name:
            root_hist = jax.lax.psum(root_hist, params.axis_name)
    # (callers passing root_hist in data-parallel mode psum it themselves)
    root_sums = jnp.sum(root_hist[0], axis=0)  # (3,): totals via feature 0
    rr = find2(jnp.stack([root_hist, root_hist]),
               jnp.stack([root_sums, root_sums]), jnp.array(True))

    root_val = leaf_output(root_sums[0], root_sums[1], hyper.lambda_l1, hyper.lambda_l2)
    root_bs = jnp.stack([rr.gain[0], rr.feature[0].astype(jnp.float32),
                         rr.threshold_bin[0].astype(jnp.float32),
                         rr.default_bin_for_zero[0].astype(jnp.float32),
                         rr.left_sum_g[0], rr.left_sum_h[0], rr.left_cnt[0],
                         jnp.float32(0.0)])
    root_leaf = jnp.stack([root_sums[0], root_sums[1], root_sums[2], root_val,
                           root_sums[2], jnp.float32(0.0), jnp.float32(0.0),
                           jnp.float32(0.0)])
    seg0 = jnp.zeros((L, 2), jnp.int32).at[0, 1].set(n)
    bs0 = jnp.full((L, 8), NEG_INF, jnp.float32).at[0].set(root_bs)
    leaf0 = jnp.zeros((L, 8), jnp.float32).at[0].set(root_leaf)

    # ---- phase 1: level-batched expansion into candidate tables ------
    if levelwise:
        SMAX = min(-(-(L + 1) // 8) * 8, 512)
        CANDMAX = 2 * SMAX
        MAXLVL = params.max_levels
        c_seg0 = jnp.zeros((CANDMAX, 2), jnp.int32).at[0, 1].set(n)
        c_bs0 = jnp.full((CANDMAX, 8), NEG_INF, jnp.float32).at[0].set(root_bs)
        c_leaf0 = jnp.zeros((CANDMAX, 8), jnp.float32).at[0].set(root_leaf)
        c_childlo0 = jnp.full((CANDMAX,), -1, jnp.int32)
        frontier0 = jnp.zeros((SMAX,), jnp.int32)  # slot 0 = root

        def lcond(s):
            return (s[7] > 0) & (s[8] < MAXLVL)

        def lbody(s):
            (p, c_seg, c_bs, c_leaf, c_childlo, cand_n, frontier,
             frontier_n, level) = s
            idx = jnp.arange(SMAX)
            fvalid = idx < frontier_n
            fslots = jnp.clip(frontier, 0, CANDMAX - 1)
            gains = jnp.where(fvalid, c_bs[fslots, 0], NEG_INF)
            active = gains > 0.0
            # cap: children must fit both the frontier array and the
            # candidate table; dropped nodes stay splittable via the
            # phase-2 classic tail
            n_act = jnp.minimum(jnp.sum(active.astype(jnp.int32)), SMAX // 2)
            n_act = jnp.minimum(n_act, jnp.maximum((CANDMAX - cand_n) // 2, 0))
            # compact active slots to the front (stable frontier order)
            order = jnp.argsort(jnp.where(active, 0, 1), stable=True)
            aslots = fslots[order]
            arow = idx < n_act
            segs = c_seg[aslots]  # (SMAX, 2)
            bsr = c_bs[aslots]
            feat = jnp.clip(bsr[:, 1].astype(jnp.int32), 0, F - 1)
            thr = bsr[:, 2].astype(jnp.int32)
            dbz = bsr[:, 3].astype(jnp.int32)
            mrows = mtab[feat]
            col = mrows[:, 2].astype(jnp.int32)
            seg_tab = jnp.stack([
                segs[:, 0], jnp.where(arow, segs[:, 1], 0),
                col // per, (col % per) * params.bits,
                mrows[:, 0].astype(jnp.int32), dbz, thr,
                mrows[:, 1].astype(jnp.int32),
                mrows[:, 3].astype(jnp.int32), mrows[:, 4].astype(jnp.int32),
                mrows[:, 5].astype(jnp.int32), jnp.zeros_like(col),
            ], axis=1)
            p, nl, hists = level_stream(
                p, seg_tab, n_act, num_features=G, num_bins=BH,
                bits=params.bits, rows=rows, smax=SMAX, interpret=interpret,
            )
            if params.axis_name:
                # ONE collective per level (vs per split): global children
                # histograms keep the tree bit-identical on every device
                hists = jax.lax.psum(
                    jnp.where(arow[:, None, None], hists, 0.0), params.axis_name
                )
            lsums = bsr[:, 4:7]
            tots = c_leaf[aslots][:, 0:3]
            rsums = tots - lsums
            cdepth = c_leaf[aslots][:, 5] + 1.0
            hist_l = jax.vmap(lambda h: _hist_from_rows(h, G, BH, row0=0))(hists)
            hist_r = jax.vmap(lambda h: _hist_from_rows(h, G, BH, row0=7))(hists)
            hist2 = jnp.stack([hist_l, hist_r], axis=1)  # (SMAX, 2, G, BH, 3)
            sums2 = jnp.stack([lsums, rsums], axis=1)  # (SMAX, 2, 3)
            dok2 = (jnp.ones((SMAX, 2), bool) if params.max_depth <= 0
                    else jnp.stack([cdepth < params.max_depth] * 2, axis=1))
            res = jax.vmap(find2)(hist2, sums2, dok2)  # fields (SMAX, 2)
            vals2 = leaf_output(sums2[..., 0], sums2[..., 1],
                                hyper.lambda_l1, hyper.lambda_l2)  # (SMAX, 2)
            il = jnp.where(arow, cand_n + 2 * idx, CANDMAX)
            ir = jnp.where(arow, cand_n + 2 * idx + 1, CANDMAX)
            seg_l = jnp.stack([segs[:, 0], nl], axis=1)
            seg_r = jnp.stack([segs[:, 0] + nl, segs[:, 1] - nl], axis=1)
            c_seg = (c_seg.at[il].set(seg_l, mode="drop")
                     .at[ir].set(seg_r, mode="drop"))

            def bs_rows(k):
                return jnp.stack([
                    res.gain[:, k], res.feature[:, k].astype(jnp.float32),
                    res.threshold_bin[:, k].astype(jnp.float32),
                    res.default_bin_for_zero[:, k].astype(jnp.float32),
                    res.left_sum_g[:, k], res.left_sum_h[:, k],
                    res.left_cnt[:, k], jnp.zeros((SMAX,), jnp.float32),
                ], axis=1)

            c_bs = (c_bs.at[il].set(bs_rows(0), mode="drop")
                    .at[ir].set(bs_rows(1), mode="drop"))

            def leaf_rows(k):
                z = jnp.zeros((SMAX,), jnp.float32)
                return jnp.stack([
                    sums2[:, k, 0], sums2[:, k, 1], sums2[:, k, 2],
                    vals2[:, k], sums2[:, k, 2], cdepth, z, z,
                ], axis=1)

            c_leaf = (c_leaf.at[il].set(leaf_rows(0), mode="drop")
                      .at[ir].set(leaf_rows(1), mode="drop"))
            par = jnp.where(arow, aslots, CANDMAX)
            c_childlo = c_childlo.at[par].set(
                jnp.where(arow, il, -1), mode="drop")
            children = jnp.clip(
                jnp.stack([il, ir], axis=1).reshape(-1)[:SMAX], 0, CANDMAX - 1
            )
            return (p, c_seg, c_bs, c_leaf, c_childlo, cand_n + 2 * n_act,
                    children, 2 * n_act, level + 1)

        (p, c_seg, c_bs, c_leaf, c_childlo, _, _, _, _) = jax.lax.while_loop(
            lcond, lbody,
            (p, c_seg0, c_bs0, c_leaf0, c_childlo0, jnp.int32(1), frontier0,
             jnp.int32(1), jnp.int32(0)),
        )
        pslot0 = jnp.full((L,), -1, jnp.int32).at[0].set(0)
    else:
        CANDMAX = 1
        c_seg = jnp.zeros((1, 2), jnp.int32)
        c_bs = jnp.zeros((1, 8), jnp.float32)
        c_leaf = jnp.zeros((1, 8), jnp.float32)
        c_childlo = jnp.full((1,), -1, jnp.int32)
        pslot0 = jnp.full((L,), -1, jnp.int32)

    # ---- phase 2: exact best-first selection ------------------------
    st = _PState(
        p=p,
        num_splits=jnp.int32(0),
        done=jnp.array(False),
        seg=seg0,
        bs=bs0,
        leaf=leaf0,
        recs=jnp.zeros((L - 1, 12), jnp.float32),
        pslot=pslot0,
    )

    def cond(st: _PState):
        return (~st.done) & (st.num_splits < L - 1)

    def body(st: _PState):
        gain = jnp.max(st.bs[:, 0])
        return jax.lax.cond(gain > 0.0, _split, lambda s: s._replace(done=True), st)

    def _split(st: _PState):
        s = st.num_splits
        bl = jnp.argmax(st.bs[:, 0]).astype(jnp.int32)
        rl = (s + 1).astype(jnp.int32)

        bsrow = st.bs[bl]
        gain = bsrow[0]
        feat = bsrow[1].astype(jnp.int32)
        thr = bsrow[2].astype(jnp.int32)
        dbz = bsrow[3].astype(jnp.int32)
        left = bsrow[4:7]
        leafrow = st.leaf[bl]
        totals = leafrow[0:3]
        pval = leafrow[3]
        child_depth = leafrow[5] + 1.0
        segrow = st.seg[bl]
        start = segrow[0]
        cnt = segrow[1]
        slot = st.pslot[bl]
        childlo = c_childlo[jnp.clip(slot, 0, CANDMAX - 1)]
        has_pre = (slot >= 0) & (childlo >= 0)

        def take_pre(p):
            clo = jnp.clip(childlo, 0, CANDMAX - 1)
            chi = jnp.clip(childlo + 1, 0, CANDMAX - 1)
            seg2 = jnp.stack([c_seg[clo], c_seg[chi]])
            bs2 = jnp.stack([c_bs[clo], c_bs[chi]])
            leaf2 = jnp.stack([c_leaf[clo], c_leaf[chi]])
            ps2 = jnp.stack([clo, chi])
            return p, seg2, bs2, leaf2, ps2

        def take_classic(p):
            mrow = mtab[feat]
            zb = mrow[0].astype(jnp.int32)
            cat = mrow[1].astype(jnp.int32)
            colidx = mrow[2].astype(jnp.int32)
            off_lo = mrow[3].astype(jnp.int32)
            off_hi = mrow[4].astype(jnp.int32)
            bias = mrow[5].astype(jnp.int32)
            p, nl, lhist, rhist = split_stream(
                p, start, cnt,
                colidx // per, (colidx % per) * params.bits, zb, dbz, thr, cat,
                off_lo=off_lo, off_hi=off_hi, bias=bias,
                num_features=G, num_bins=BH, bits=params.bits, rows=rows,
                interpret=interpret,
            )
            hist2 = jnp.stack([lhist, rhist])
            if params.axis_name:
                # global children histograms; the split decision below is
                # then bit-identical on every device (local segments
                # diverge, the tree does not)
                hist2 = jax.lax.psum(hist2, params.axis_name)

            right = totals - left
            sums2 = jnp.stack([left, right])  # (2, 3)
            vals2 = leaf_output(sums2[:, 0], sums2[:, 1], hyper.lambda_l1,
                                hyper.lambda_l2)  # (2,)
            depth_ok = (
                jnp.array(True)
                if params.max_depth <= 0
                else child_depth < params.max_depth
            )
            res2 = find2(hist2, sums2, depth_ok)

            seg2 = jnp.stack(
                [jnp.stack([start, nl]), jnp.stack([start + nl, cnt - nl])]
            )
            bs2 = jnp.stack(
                [res2.gain, res2.feature.astype(jnp.float32),
                 res2.threshold_bin.astype(jnp.float32),
                 res2.default_bin_for_zero.astype(jnp.float32),
                 res2.left_sum_g, res2.left_sum_h, res2.left_cnt,
                 jnp.zeros((2,), jnp.float32)], axis=1
            )  # (2, 8)
            leaf2 = jnp.stack(
                [sums2[:, 0], sums2[:, 1], sums2[:, 2], vals2, sums2[:, 2],
                 jnp.full((2,), child_depth),
                 jnp.zeros((2,)), jnp.zeros((2,))], axis=1
            )  # (2, 8)
            ps2 = jnp.full((2,), -1, jnp.int32)
            return p, seg2, bs2, leaf2, ps2

        p, seg2, bs2, leaf2, ps2 = jax.lax.cond(
            has_pre, take_pre, take_classic, st.p
        )
        # child outputs are recomputed HERE, at one shared (2,)-shaped
        # site outside the cond, from the children's g/h sums.  The
        # level-batched precompute evaluates leaf_output over (SMAX, 2)
        # candidate batches; routing both branches through the SAME
        # division op removes batch-shape / fusion-context rounding as a
        # variable between the LEVELGROW modes, so accepted leaf values
        # depend only on the (psum-exact) integer-scaled g/h sums.
        leaf2 = leaf2.at[:, 3].set(
            leaf_output(leaf2[:, 0], leaf2[:, 1],
                        hyper.lambda_l1, hyper.lambda_l2))
        idx2 = jnp.stack([bl, rl])
        rec = jnp.stack(
            [bl.astype(jnp.float32), feat.astype(jnp.float32),
             thr.astype(jnp.float32), dbz.astype(jnp.float32), gain,
             leaf2[0, 3], leaf2[1, 3], leaf2[0, 2], leaf2[1, 2], pval,
             jnp.float32(0.0), jnp.float32(0.0)]
        )

        return st._replace(
            p=p,
            num_splits=s + 1,
            seg=st.seg.at[idx2].set(seg2),
            bs=st.bs.at[idx2].set(bs2),
            leaf=st.leaf.at[idx2].set(leaf2),
            recs=st.recs.at[s].set(rec),
            pslot=st.pslot.at[idx2].set(ps2),
        )

    st = jax.lax.while_loop(cond, body, st)
    recs = st.recs
    res = PTreeResult(
        num_splits=st.num_splits,
        starts=st.seg[:, 0],
        cnts=st.seg[:, 1],
        leaf_value=st.leaf[:, 3],
        leaf_cnt=st.leaf[:, 4],
        recs_raw=recs,
        rec_leaf=recs[:, 0].astype(jnp.int32),
        rec_feat=recs[:, 1].astype(jnp.int32),
        rec_thr=recs[:, 2].astype(jnp.int32),
        rec_dbz=recs[:, 3].astype(jnp.int32),
        rec_gain=recs[:, 4],
        rec_lval=recs[:, 5],
        rec_rval=recs[:, 6],
        rec_lcnt=recs[:, 7],
        rec_rcnt=recs[:, 8],
        rec_internal_value=recs[:, 9],
    )
    return res, st.p


# compile/retrace + HLO cost accounting on the standalone grower entry
# (obs/compilewatch.py): when the fused chunk programs trace this
# inline, the call passes straight through the watch
grow_tree_partitioned = JitWatch(grow_tree_partitioned,
                                 "ops.grow_tree_partitioned", phase="tree")


def level_hists(p, seg_tab, n_active, params: PGrowParams, rows=None,
                interpret: bool = False):
    """(smax, G, BH, 3) histograms of every active leaf segment of a
    level in ONE kernel launch (ops/histogram_pallas.hist_segments) —
    the multi-leaf replacement for a per-leaf hist_dyn launch loop.

    The fused grower normally gets level histograms for free from
    ``level_stream``'s partition pass; this helper serves callers that
    need segment histograms OUTSIDE a partition (root histograms, the
    kernel A/B harness in bench.py, numerics tripwires), at one launch
    per level instead of one per leaf.  seg_tab: (smax, 2) int32 rows of
    [start, cnt]."""
    G = params.num_cols or params.num_features
    BH = params.num_bins_hist or params.num_bins
    if rows is None:
        rows = PLayout(G, bits=params.bits).rows
    smax = int(seg_tab.shape[0])
    return hist_segments(
        p, seg_tab, n_active, num_features=G, num_bins=BH,
        bits=params.bits, rows=rows, smax=smax, interpret=interpret,
    )


def segment_values(tree: PTreeResult, num_rows: int, values: jnp.ndarray) -> jnp.ndarray:
    """(N,) vector assigning ``values[leaf]`` to each position of that
    leaf's segment — the partitioned-space replacement for
    leaf_id-indexed lookups.

    The lookup must be EXACT, not merely close: a float range-add
    (+v at starts, -v at ends, cumsum) leaves position-dependent 1-ULP
    residue inside segments because XLA's cumsum is a parallel prefix
    sum whose reassociation differs per position — and the physical
    order of rows inside a segment is NOT layout-stable (the level
    grower's speculative partitions shuffle it), so that residue made
    training scores depend on partition history.  Instead: an integer
    cumsum over segment-start marks (exact) ranks each position's
    covering segment, and the value is gathered — every row of a leaf
    gets the bit-identical ``values[leaf]``."""
    L = tree.starts.shape[0]
    active = jnp.arange(L) <= tree.num_splits
    v = jnp.where(active, values, 0.0)
    # empty segments share their start with a neighbour: park them (and
    # inactive slots) past the end so they never win the rank lookup
    s = jnp.where(active & (tree.cnts > 0), tree.starts, num_rows)
    marks = jnp.zeros((num_rows + 1,), jnp.int32).at[s].add(1)
    rank = jnp.cumsum(marks)[:num_rows] - 1
    order = jnp.argsort(s)  # segment slots in physical start order
    return jnp.take(v, jnp.take(order, jnp.clip(rank, 0, L - 1)))


def split_audit_rows(gr):
    """Host-side iterator over a GrowResult-like view's accepted splits,
    in acceptance order — the audit-trail hook (obs/audit.py).

    Accepts anything carrying the raw split-record contract that
    ``Tree.from_grow_result`` consumes (``ops/grow.GrowResult``, this
    module's :class:`PTreeResult`, ``ptrainer.grow_result_view``), which
    is exactly why audit trails are comparable across the mask, fused
    classic (LEVELGROW=0), level-batched (LEVELGROW=1) and traced
    trainer paths: they all converge on these records.  Values are
    pulled once per tree (one host transfer for device-resident views)
    and floats keep their stored f32 identity so two bit-identical
    record buffers yield identical rows."""
    import numpy as np

    ns = int(gr.num_splits)
    if ns <= 0:
        return
    leaf = np.asarray(gr.rec_leaf)
    thr = np.asarray(gr.rec_thr)
    dbz = np.asarray(gr.rec_dbz)
    gain = np.asarray(gr.rec_gain)
    lcnt = np.asarray(gr.rec_lcnt)
    rcnt = np.asarray(gr.rec_rcnt)
    for s in range(ns):
        yield {
            "s": s,
            "leaf": int(leaf[s]),
            "bin": int(thr[s]),
            "dbz": int(dbz[s]),
            "gain": float(gain[s]),
            "lcnt": int(lcnt[s]),
            "rcnt": int(rcnt[s]),
        }


def leaf_id_from_segments(tree: PTreeResult, p: jnp.ndarray, layout: PLayout, num_rows: int) -> jnp.ndarray:
    """(N,) int32 leaf index in ORIGINAL row order (via the rowid
    channel) — the GrowResult.leaf_id contract for driver code that needs
    it (one O(N) scatter; avoided on the fast path)."""
    L = tree.starts.shape[0]
    leaf_at_pos = segment_values(
        tree, num_rows, jnp.arange(L, dtype=jnp.float32)
    ).astype(jnp.int32)
    rowid = p[layout.ROWID, :num_rows]
    return jnp.zeros((num_rows,), jnp.int32).at[rowid].set(leaf_at_pos)
