"""Device compute ops: histogram construction, split finding, tree growth,
prediction.  This package is the TPU counterpart of the reference's
src/treelearner/ + the hot half of src/io/ (dense_bin.hpp histogram kernel,
feature_histogram.hpp split scan) rebuilt as jitted XLA/Pallas programs.
"""

from .histogram import build_histogram
from .split import best_split_all_features
from .grow import GrowParams, grow_tree
from .predict import predict_binned, predict_raw

__all__ = [
    "build_histogram",
    "best_split_all_features",
    "GrowParams",
    "grow_tree",
    "predict_binned",
    "predict_raw",
]
