"""Quantized-gradient histogram support (``quantized_training=true``).

The histogram contraction is the hot kernel and the data-parallel
histogram allreduce its dominant comms cost (docs/PARALLEL.md: 5.57
MB/iter at 2000 features for the f32x3 wire).  Following the
low-precision-histogram lever of "GPU-acceleration for Large-scale Tree
Boosting" (1706.08359) and "XGBoost: Scalable GPU Accelerated Learning"
(1806.11248), this module quantizes the per-row gradient/hessian to a
few signed integer levels once per iteration and keeps EVERYTHING from
that point to the split scan in exact integer arithmetic:

  - per-iteration global scales  ``s_g = max|g| / QMAX`` (selected rows,
    allreduced across ranks), same for the hessian;
  - per-row stochastic rounding ``q = clip(floor(x/s + u), -QMAX, QMAX)``
    stored as int16, where the uniform ``u`` is a hash of the VALUE's
    own bit pattern mixed with an iteration key — so a row's rounding
    decision is independent of its position and the quantized histogram
    is invariant under row permutation (the f32 path never had that);
  - int32 histogram accumulation through the same blocked one-hot
    contraction (``preferred_element_type=int32``) — integer adds are
    associative, so chunk boundaries, device counts and reduction
    orders all produce the SAME histogram, bit for bit;
  - dequantization happens exactly once, at split-scan time.

Wire format (``hist_q``): a histogram payload ships only the two int16
quantized planes — ``F*B*4`` bytes against the f32x3 wire's ``F*B*12``,
exactly 3x smaller by protocol arithmetic.  The count plane is NOT
shipped: like the reference's two-plane histograms (feature_histogram.hpp
derives counts as ``RoundInt(sum_hess * cnt_factor)``), the receiver
reconstructs counts from the hessian plane and the node totals it
already has.  If a per-bin sum overflows int16 the payload falls back to
a length-discriminated int32 format (``F*B*8`` bytes) — still 1.5x
smaller, and the receiver infers the width from the blob length alone.
One degenerate case needs real counts: a node whose quantized hessians
all round to 0 (small-hessian rows under a scale set by the global max)
has ``sum_qh == 0``, and a derived count plane would be all zeros even
though the node holds rows — min_data_in_leaf would then prune every
split.  A sender detects this locally (hessians are non-negative, so the
global hessian mass is zero iff every rank's is) and ships a 3-plane
payload carrying its exact int count plane (``F*B*6`` / ``F*B*12``
bytes); the receiver blends exact counts with the cnt_factor derivation
for the remaining rows.  All four formats have distinct lengths, so the
blob length alone still discriminates.

``QUANT_BITS`` defaults to 5 (QMAX=15): small enough that a 2-rank
int16 wire sum holds ~2184 rows per bin per rank before the fallback
triggers, while int32 device accumulation holds to ~143M rows per bin.
That device bound is enforced at train time: boosting declines
``quantized_training`` (with a warning) when the global row count
exceeds :func:`max_rows_for`, instead of silently wrapping int32.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# Default quantization width. QMAX = 2^(bits-1) - 1 signed levels per
# side; 5 bits mirrors the reference's quantized-training default
# (LightGBM's use_quantized_grad path trains at 4-6 bit gradients).
QUANT_BITS = 5


def qmax_for(bits: int) -> int:
    """Largest quantized magnitude at a given signed bit width."""
    return (1 << (bits - 1)) - 1


def max_rows_for(bits: int = QUANT_BITS) -> int:
    """Largest global row count the int32 histogram accumulators can hold.

    A node (and in the worst case a single bin) sums up to ``n * QMAX``
    in int32 — both the root totals and the per-bin psum'd histogram
    (ops/grow.py) — so past ``(2**31 - 1) // QMAX`` rows the accumulation
    can wrap silently.  Training checks this bound up front and declines
    quantized mode rather than producing wrong trees."""
    return (2 ** 31 - 1) // qmax_for(bits)


# ----------------------------------------------------------------------
# scales
# ----------------------------------------------------------------------
@jax.jit
def local_absmax(grad: jnp.ndarray, hess: jnp.ndarray,
                 select: jnp.ndarray) -> jnp.ndarray:
    """(2,) f32 of ``(max|g|, max|h|)`` over the selected rows — the
    local contribution to the per-iteration global scale."""
    g = jnp.max(jnp.abs(grad) * select)
    h = jnp.max(jnp.abs(hess) * select)
    return jnp.stack([g, h])


def scales_from_max(gmax: float, hmax: float, bits: int = QUANT_BITS) -> np.ndarray:
    """(2,) np.float32 quantization scales from the GLOBAL abs-maxima.

    Host-side np.float32 arithmetic on purpose: every rank must derive
    the bit-identical scale from the same gathered maxima, and a single
    f32 divide is deterministic everywhere.  A degenerate (all-zero)
    channel gets scale 1.0 — its rows quantize to exact zeros."""
    q = np.float32(qmax_for(bits))
    g = np.float32(gmax)
    h = np.float32(hmax)
    sg = g / q if g > 0 else np.float32(1.0)
    sh = h / q if h > 0 else np.float32(1.0)
    return np.asarray([sg, sh], np.float32)


# ----------------------------------------------------------------------
# stochastic rounding
# ----------------------------------------------------------------------
def _hash_uniform(x: jnp.ndarray, key: jnp.ndarray) -> jnp.ndarray:
    """[0, 1) uniform keyed by the VALUE's own bits and the iteration key.

    A murmur3-style integer finalizer over ``bitcast(x) ^ key``: equal
    values always round the same way within an iteration (row-order
    invariance), different iterations re-draw (unbiasedness across the
    boosting run).  No PRNG state, no row indices.

    Only the top 24 hash bits are used: a 24-bit integer converts to
    float32 exactly, so ``u <= (2**24 - 1) * 2**-24 < 1`` strictly.
    Converting all 32 bits would round values within 128 of ``2**32``
    UP to ``2**32`` and return exactly 1.0, pushing ``floor(x/s + u)``
    a full unit high."""
    u = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    u = u ^ key.astype(jnp.uint32)
    u = (u ^ (u >> 16)) * jnp.uint32(0x7FEB352D)
    u = (u ^ (u >> 15)) * jnp.uint32(0x846CA68B)
    u = u ^ (u >> 16)
    return (u >> 8).astype(jnp.float32) * jnp.float32(2.0 ** -24)


@functools.partial(jax.jit, static_argnames=("bits",))
def quantize_rows(grad: jnp.ndarray, hess: jnp.ndarray, scales: jnp.ndarray,
                  seed, bits: int = QUANT_BITS):
    """Stochastically round ``(grad, hess)`` to int16 levels in
    ``[-QMAX, QMAX]`` under the (2,) ``scales``.

    ``floor(x/s + u)`` with ``u ~ U[0,1)`` is unbiased: the expectation
    over ``u`` is exactly ``x/s``.  ``u`` comes from :func:`_hash_uniform`
    so the draw depends only on (value, iteration seed)."""
    q = jnp.float32(qmax_for(bits))
    seed = jnp.asarray(seed, jnp.uint32)

    def one(x, s, salt):
        u = _hash_uniform(x, seed ^ jnp.uint32(salt))
        y = jnp.floor(x / s + u)
        return jnp.clip(y, -q, q).astype(jnp.int16)

    qg = one(grad, scales[0], 0x9E3779B9)
    qh = one(hess, scales[1], 0x85EBCA6B)
    return qg, qh


# ----------------------------------------------------------------------
# dequantization
# ----------------------------------------------------------------------
@jax.jit
def dequantize_hist(hist_q: jnp.ndarray, scales: jnp.ndarray) -> jnp.ndarray:
    """(..., 3) int32 quantized histogram -> (..., 3) f32 for the split
    scan.  The count channel is an exact integer count here (device
    paths keep all three planes); only the wire drops it."""
    return jnp.stack(
        [
            hist_q[..., 0].astype(jnp.float32) * scales[0],
            hist_q[..., 1].astype(jnp.float32) * scales[1],
            hist_q[..., 2].astype(jnp.float32),
        ],
        axis=-1,
    )


@jax.jit
def dequantize_sums(sums_q: jnp.ndarray, scales: jnp.ndarray) -> jnp.ndarray:
    """(3,) int quantized node totals -> (3,) f32 (g, h, count)."""
    return jnp.stack(
        [
            sums_q[0].astype(jnp.float32) * scales[0],
            sums_q[1].astype(jnp.float32) * scales[1],
            sums_q[2].astype(jnp.float32),
        ]
    )


def derive_count_plane(hist2: np.ndarray, node_cnt: float,
                       exact: np.ndarray = None) -> np.ndarray:
    """Reconstruct the count plane of a 2-plane quantized histogram.

    The reference's histograms are genuinely two-plane; counts come from
    ``RoundInt(sum_hess * cnt_factor)`` with ``cnt_factor = node_cnt /
    node_sum_hess`` (feature_histogram.hpp).  Here the quantized-hessian
    plane plays that role: every row lands in exactly one bin of feature
    0, so feature 0's bins sum to the node's quantized-hessian total —
    no extra wire traffic to learn it.

    ``exact`` is the summed (F, B) count plane of the ranks that shipped
    3-plane payloads (their hessian mass quantized to zero, so derivation
    could not see their rows).  Those rows are counted exactly; the
    cnt_factor derivation covers only the remainder, whose hessian mass
    is exactly the merged hessian plane (the exact-shippers contributed
    zero to it)."""
    hist2 = np.asarray(hist2)
    qh_tot = int(hist2[0, :, 1].sum())
    if exact is not None:
        exact = np.asarray(exact, np.float32)
        rest = max(float(node_cnt) - float(exact[0, :].sum()), 0.0)
        cf = np.float32(rest) / np.float32(max(qh_tot, 1))
        return exact + np.rint(
            hist2[..., 1].astype(np.float32) * cf).astype(np.float32)
    if qh_tot == 0 and float(node_cnt) > 0:
        # no sender shipped counts yet the node holds rows: every bin
        # derives to zero and min_data_in_leaf prunes all splits here.
        # Reachable only when a sender skipped the 3-plane fallback
        # (e.g. negative hessians break the local-zero test).
        from ..utils.log import Log

        Log.warning(
            "quantized histogram node with %d rows has zero hessian "
            "mass and no exact count plane; its splits will be pruned",
            int(node_cnt))
    cf = np.float32(node_cnt) / np.float32(max(qh_tot, 1))
    return np.rint(hist2[..., 1].astype(np.float32) * cf).astype(np.float32)


def assemble_hist(hist2: np.ndarray, scales: np.ndarray,
                  node_cnt: float, counts: np.ndarray = None) -> np.ndarray:
    """Merged 2-plane int wire histogram -> (F, B, 3) f32 for the scan.

    ``counts`` forwards the merged exact count plane (if any 3-plane
    payloads arrived) to :func:`derive_count_plane`."""
    hist2 = np.asarray(hist2)
    out = np.empty(hist2.shape[:2] + (3,), np.float32)
    out[..., 0] = hist2[..., 0].astype(np.float32) * np.float32(scales[0])
    out[..., 1] = hist2[..., 1].astype(np.float32) * np.float32(scales[1])
    out[..., 2] = derive_count_plane(hist2, node_cnt, exact=counts)
    return out


# ----------------------------------------------------------------------
# wire format (purpose tag "hist_q")
# ----------------------------------------------------------------------
def pack_hist_q(hist2, counts=None) -> bytes:
    """Pack the (F, B, 2) int32 (sum_qg, sum_qh) planes for the wire.

    Primary format: little-endian int16, ``F*B*4`` bytes — 3x smaller
    than the f32x3 wire's ``F*B*12``.  If any per-bin sum exceeds int16
    range the whole payload falls back to int32 (``F*B*8`` bytes); the
    receiver discriminates the formats by blob length, so there is no
    header byte to spoil the 3x arithmetic.

    ``counts`` (an exact (F, B) int count plane) appends a third plane
    (``F*B*6`` / ``F*B*12`` bytes).  A sender ships it only when its
    hessian mass for the node quantized to zero — without it the
    receiver's derived count plane would miss these rows entirely."""
    arr = np.ascontiguousarray(np.asarray(hist2, np.int32))
    if counts is not None:
        arr = np.ascontiguousarray(np.concatenate(
            [arr, np.asarray(counts, np.int32)[..., None]], axis=-1))
    if abs(int(arr.min(initial=0))) <= 32767 and int(arr.max(initial=0)) <= 32767:
        return arr.astype("<i2").tobytes()
    return arr.astype("<i4").tobytes()


def unpack_hist_q(blob: bytes, num_features: int, num_bins: int) -> np.ndarray:
    """Inverse of :func:`pack_hist_q` -> (F, B, 2) or (F, B, 3) int32.

    The last axis is 3 when the sender shipped its exact count plane
    (all four lengths — {2, 3} planes x {int16, int32} — are distinct,
    so the blob length alone picks the format)."""
    m = num_features * num_bins
    by_len = {m * 4: ("<i2", 2), m * 8: ("<i4", 2),
              m * 6: ("<i2", 3), m * 12: ("<i4", 3)}
    fmt = by_len.get(len(blob))
    if fmt is None:
        raise ValueError(
            f"hist_q payload of {len(blob)} B matches neither the int16 "
            f"({m * 4}/{m * 6} B) nor the int32 ({m * 8}/{m * 12} B) "
            f"2/3-plane formats for F={num_features}, B={num_bins}")
    arr = np.frombuffer(blob, fmt[0]).astype(np.int32)
    return arr.reshape(num_features, num_bins, fmt[1])


def wire_bytes_f32(num_features: int, num_bins: int) -> int:
    """Protocol arithmetic: bytes of one f32x3 histogram payload."""
    return num_features * num_bins * 3 * 4


def wire_bytes_q(num_features: int, num_bins: int) -> int:
    """Protocol arithmetic: bytes of one int16x2 ``hist_q`` payload."""
    return num_features * num_bins * 2 * 2


# ----------------------------------------------------------------------
# drift bound
# ----------------------------------------------------------------------
def quant_drift_bound(scale_g: float, scale_h: float, n_rows: int,
                      lambda_l2: float, min_hessian: float = 0.0,
                      bits: int = QUANT_BITS) -> float:
    """Analytic worst-case bound on the split-gain perturbation that
    quantized training can introduce, in the style of
    ``ops/qpredict.drift_bound``.

    Each quantized row carries error < one quantization unit, so a sum
    over ``n`` rows drifts by at most ``dG = n*s_g`` (``dH = n*s_h``),
    while the sum itself is bounded by ``A = n*s_g*QMAX``.  For one leaf
    term ``phi = G^2 / (H + lambda_l2)`` with ``H >= Hmin``, the enclosure
    of phi over the error ball has width at most

        (A + dG)^2 / max(Hmin - dH, eps)  -  (A - dG)^2 / (Hmin + dH)

    and a split gain is a sum of three phi terms (left + right - parent),
    so the exported bound is 3x the enclosure width plus an f32
    evaluation slack.  Caveat (shared with qpredict.drift_bound): the
    bound speaks to gain VALUES; a constraint (min_data_in_leaf etc.)
    sitting exactly on a quantization boundary can still flip a
    candidate's validity."""
    q = float(qmax_for(bits))
    n = float(n_rows)
    sg = float(scale_g)
    sh = float(scale_h)
    a = n * sg * q
    dg = n * sg
    dh = n * sh
    hmin = float(lambda_l2) + max(float(min_hessian), 0.0)
    eps = 1e-12
    hi = (a + dg) ** 2 / max(hmin - dh, eps)
    lo = max(a - dg, 0.0) ** 2 / (hmin + dh)
    width = hi - lo
    slack = 1e-6 * max(hi, 1.0)  # f32 evaluation noise on the scan itself
    return 3.0 * width + slack
