"""Histogram construction — the hot kernel of the framework.

Counterpart of the reference's per-bin scatter loops
(src/io/dense_bin.hpp:66 ConstructHistogram, the 4-way unrolled CPU kernel;
src/treelearner/ocl/histogram256.cl, the OpenCL workgroup kernel).

TPU-first design: TPUs have no fast scatter/atomics, but they have an MXU.
The histogram

    hist[f, b, c] = sum_n vals[n, c] * [bins[n, f] == b]

is a matmul between the (3, N) value matrix and the implicit one-hot
N x (F*B) matrix of bin indicators.  We block over rows so the one-hot
tile lives only in VMEM/registers and never round-trips HBM:
for each row block R we contract (3, R) @ (R, F*B) on the MXU and
accumulate in f32.  This mirrors the OpenCL kernel's per-workgroup
sub-histogram + final reduction, with the MXU playing the role of the
atomic local adds.

Leaf selection (the reference's ordered-bin / data-partition machinery) is
a mask multiplied into the values: rows outside the target leaf contribute
zeros.  That accepts O(N) work per split — the XLA-friendly trade
documented in SURVEY §7 — and makes bagging free (bagging masks compose).

A Pallas kernel (histogram_pallas.py) replaces this XLA formulation on
TPU where beneficial; this module is the always-correct reference path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..obs.compilewatch import JitWatch

# Rows per block in the blocked one-hot contraction. 4096 keeps the
# bf16 one-hot tile (ROW_BLOCK x F*B) comfortably inside VMEM after XLA
# tiling while amortizing loop overhead.
ROW_BLOCK = 4096


def _hist_one_block(bins_blk: jnp.ndarray, vals_blk: jnp.ndarray, num_bins: int) -> jnp.ndarray:
    """(R, F) uint bins + (R, 3) f32 vals -> (F, B, 3) partial histogram.

    Integer ``vals`` (the quantized-training path: int16 stochastic-
    rounded grad/hess) take the same contraction with an int16 one-hot
    and ``preferred_element_type=int32`` — exact integer accumulation,
    no precision knob needed."""
    r, f = bins_blk.shape
    if jnp.issubdtype(vals_blk.dtype, jnp.integer):
        onehot = (
            bins_blk[:, :, None] == jnp.arange(num_bins, dtype=bins_blk.dtype)
        ).astype(vals_blk.dtype)
        onehot = onehot.reshape(r, f * num_bins)
        part = jax.lax.dot_general(
            vals_blk.T,
            onehot,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        return part.reshape(3, f, num_bins).transpose(1, 2, 0)
    # one-hot (R, F, B) reshaped to (R, F*B). f32, not bf16: a mixed dot
    # would downcast the gradient operand and lose ~2^-8 relative accuracy,
    # visibly degrading split gains (the reference's own GPU kernel keeps
    # f32 accumulators for the same reason).
    onehot = (bins_blk[:, :, None] == jnp.arange(num_bins, dtype=bins_blk.dtype)).astype(
        jnp.float32
    )
    onehot = onehot.reshape(r, f * num_bins)
    # (3, R) @ (R, F*B) -> (3, F*B) on the MXU, f32 accumulation.
    # HIGHEST precision: the TPU MXU's default bf16 passes would round the
    # gradient operand (~2^-8 relative), visibly perturbing split gains.
    part = jax.lax.dot_general(
        vals_blk.T,
        onehot,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    return part.reshape(3, f, num_bins).transpose(1, 2, 0)


@functools.partial(jax.jit, static_argnames=("num_bins", "row_block"))
def build_histogram(
    bins: jnp.ndarray,
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    select: jnp.ndarray,
    num_bins: int,
    row_block: int = ROW_BLOCK,
    init: jnp.ndarray = None,
) -> jnp.ndarray:
    """Build the (F, B, 3) histogram tensor of (sum_g, sum_h, count).

    Parameters
    ----------
    bins : (N, F) uint8/uint16/int32 — bin index per (row, feature).
    grad, hess : (N,) f32 gradients/hessians — or int16 quantized levels
        (ops/qhist.py), in which case the result is an exact int32
        histogram whose adds are associative: any chunking, sharding or
        row order produces the identical tensor.
    select : (N,) f32 0/1 — leaf-membership (x bagging) mask.
    num_bins : static B — the padded max bin count.
    init : optional (F, B, 3) carry the block partials fold onto.  Passing
        the previous chunk's histogram here makes chunked accumulation
        reproduce the single-pass scan's left-to-right block summation
        bit-for-bit, as long as every chunk boundary lands on a
        ``row_block`` multiple (the out-of-core path's contract).

    Equivalent to DenseBin::ConstructHistogram (dense_bin.hpp:66) run over
    every feature with the leaf's data indices, without the index
    indirection: masked rows contribute zero to every bin.
    """
    n, f = bins.shape
    if jnp.issubdtype(grad.dtype, jnp.integer):
        # quantized training: int16 grad/hess, int32 accumulation. The
        # select mask arrives as whatever the caller has (f32 0/1 or
        # int16 0/1) — cast, it is exact either way.
        s = select.astype(grad.dtype)
        vals = jnp.stack([grad * s, hess * s, s], axis=1)  # (N, 3) int16
    else:
        vals = jnp.stack([grad * select, hess * select, select], axis=1)  # (N, 3)

    pad = (-n) % row_block
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
    nblocks = (n + pad) // row_block

    bins_b = bins.reshape(nblocks, row_block, f)
    vals_b = vals.reshape(nblocks, row_block, 3)

    def body(carry, xs):
        b_blk, v_blk = xs
        return carry + _hist_one_block(b_blk, v_blk, num_bins), None

    if init is None:
        acc_dtype = (jnp.int32 if jnp.issubdtype(vals.dtype, jnp.integer)
                     else jnp.float32)
        init = jnp.zeros((f, num_bins, 3), dtype=acc_dtype)
    hist, _ = jax.lax.scan(body, init, (bins_b, vals_b))
    return hist


# compile/retrace + HLO cost accounting on the standalone kernel entry
# (obs/compilewatch.py): calls made while an outer jit traces (the fused
# chunk programs inline this) pass straight through the watch
build_histogram = JitWatch(build_histogram, "ops.build_histogram",
                           phase="histogram")


def accumulate_histogram(
    hist: jnp.ndarray,
    bins: jnp.ndarray,
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    select: jnp.ndarray,
    num_bins: int,
    row_block: int = ROW_BLOCK,
) -> jnp.ndarray:
    """Chunk-accumulating histogram entry point: fold one row-chunk's
    block partials onto ``hist`` (the running (F, B, 3) carry).

    Streaming chunks [0, R), [R, 2R), ... through this in ascending order
    with ``R % row_block == 0`` performs exactly the adds — same values,
    same order — as one :func:`build_histogram` call over the
    concatenated rows, which is the out-of-core trainer's bit-identity
    invariant (only the last chunk may be partial; its padding rows
    contribute exact zeros, as in the single-pass tail)."""
    return build_histogram(bins, grad, hess, select, num_bins, row_block, hist)


def histogram_from_parent(parent_hist: jnp.ndarray, sibling_hist: jnp.ndarray) -> jnp.ndarray:
    """The histogram-subtraction trick (FeatureHistogram::Subtract,
    feature_histogram.hpp:63; serial_tree_learner.cpp:484-489): the larger
    child's histogram is parent - smaller sibling, avoiding a second data
    pass."""
    return parent_hist - sibling_hist
