"""Vectorized best-split search over a (F, B, 3) histogram tensor.

Counterpart of FeatureHistogram::FindBestThreshold*
(src/treelearner/feature_histogram.hpp:71-198, 253-387).  The reference
scans each feature's bins sequentially in two directions with three
zero/missing placements; here every (feature, placement, threshold) cell is
evaluated at once from prefix sums, and the sequential early-`break`s become
masks (they are monotone in the scan direction, so masking is equivalent).

Zero/missing placements (FindBestThresholdNumerical, hpp:85-96): rows whose
value is zero/missing live in the feature's `default_bin`; a split may
route them left (as-if bin 0), naturally (their own bin), or right (as-if
bin B-1).  The chosen placement is recorded as `default_bin_for_zero` and
replayed at partition/prediction time (tree.h DefaultValueForZero).

Tie-breaking parity: the reference keeps the first strictly-better
candidate in scan order, which prefers (a) lower feature index, (b)
placement order zero-left, natural, zero-right, (c) larger threshold for
the right-to-left scans (placements zero-left/natural) and smaller
threshold for the left-to-right scan (zero-right).

Numerical-precision note: the reference accumulates in float64 with
kEpsilon=1e-15 seeds; this implementation uses float32 (the same trade the
reference's own GPU path makes with gpu_use_dp=false) and drops the
epsilons, which are below f32 resolution.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

# host constant: a jnp scalar here would initialize the XLA backend at
# import time, which breaks jax.distributed.initialize (must run first)
NEG_INF = float("-inf")


class SplitHyper(NamedTuple):
    """Split-relevant hyperparameters (TreeConfig, config.h:189-234)."""

    lambda_l1: jnp.ndarray
    lambda_l2: jnp.ndarray
    min_data_in_leaf: jnp.ndarray
    min_sum_hessian_in_leaf: jnp.ndarray
    min_gain_to_split: jnp.ndarray

    @classmethod
    def from_config(cls, config) -> "SplitHyper":
        return cls(
            jnp.float32(config.lambda_l1),
            jnp.float32(config.lambda_l2),
            jnp.float32(config.min_data_in_leaf),
            jnp.float32(config.min_sum_hessian_in_leaf),
            jnp.float32(config.min_gain_to_split),
        )


class FeatureMeta(NamedTuple):
    """Static per-feature metadata arrays (FeatureMetainfo, hpp:14-21)."""

    num_bins: jnp.ndarray  # (F,) int32
    default_bin: jnp.ndarray  # (F,) int32
    is_categorical: jnp.ndarray  # (F,) bool

    @classmethod
    def from_dataset(cls, dataset) -> "FeatureMeta":
        import numpy as np
        from ..io.binning import CATEGORICAL

        return cls(
            jnp.asarray(np.array([m.num_bin for m in dataset.bin_mappers], np.int32)),
            jnp.asarray(np.array([m.default_bin for m in dataset.bin_mappers], np.int32)),
            jnp.asarray(
                np.array([m.bin_type == CATEGORICAL for m in dataset.bin_mappers], bool)
            ),
        )


class SplitResult(NamedTuple):
    """Scalar best split over all features (SplitInfo, split_info.hpp:17)."""

    gain: jnp.ndarray  # already min_gain_shift-subtracted
    feature: jnp.ndarray  # inner feature index, int32
    threshold_bin: jnp.ndarray  # int32
    default_bin_for_zero: jnp.ndarray  # int32
    left_sum_g: jnp.ndarray
    left_sum_h: jnp.ndarray
    left_cnt: jnp.ndarray
    right_sum_g: jnp.ndarray
    right_sum_h: jnp.ndarray
    right_cnt: jnp.ndarray
    left_output: jnp.ndarray
    right_output: jnp.ndarray


def leaf_split_gain(sum_g, sum_h, l1, l2):
    """GetLeafSplitGain (feature_histogram.hpp:230-236)."""
    reg = jnp.maximum(jnp.abs(sum_g) - l1, 0.0)
    return reg * reg / (sum_h + l2)


def leaf_output(sum_g, sum_h, l1, l2):
    """CalculateSplittedLeafOutput (feature_histogram.hpp:244-249)."""
    reg = jnp.maximum(jnp.abs(sum_g) - l1, 0.0)
    return -jnp.sign(sum_g) * reg / (sum_h + l2)


def _threshold_l1(sum_g, l1):
    """ThresholdL1 (feature_histogram.hpp:238-242), signed."""
    return jnp.sign(sum_g) * jnp.maximum(jnp.abs(sum_g) - l1, 0.0)


def leaf_split_gain_given_output(sum_g, sum_h, l1, l2, output):
    """GetLeafSplitGainGivenOutput (feature_histogram.hpp): the gain a
    leaf contributes when its output is FORCED to ``output`` (the
    monotone-clipped value) instead of the unconstrained optimum.  At
    the unconstrained optimum this equals ``leaf_split_gain`` exactly in
    real arithmetic but NOT in f32 — which is why the unconstrained path
    keeps the closed form and stays bit-identical."""
    sg_l1 = _threshold_l1(sum_g, l1)
    return -(2.0 * sg_l1 * output + (sum_h + l2) * output * output)


def _argmax_prefer_high(x):
    """argmax returning the HIGHEST index among ties (right-to-left scan)."""
    n = x.shape[-1]
    return n - 1 - jnp.argmax(x[..., ::-1], axis=-1)


def best_split_per_feature(
    hist: jnp.ndarray,
    sum_g: jnp.ndarray,
    sum_h: jnp.ndarray,
    num_data: jnp.ndarray,
    meta: FeatureMeta,
    hyper: SplitHyper,
    feature_mask: jnp.ndarray,
    use_missing: bool = True,
    has_categorical: bool = True,
    monotone: jnp.ndarray = None,
    leaf_lo: jnp.ndarray = None,
    leaf_hi: jnp.ndarray = None,
):
    """Per-feature best split: returns (gain_f, thr_f, dbz_f, left_f) with
    shapes (F,), (F,), (F,), (F, 3).  The per-feature half of
    FindBestThresholds — exposed separately so the parallel learners can
    vote / reduce over features before the global argmax.

    hist : (F, B, 3) f32 histogram of (sum_g, sum_h, cnt) per bin.
    sum_g/sum_h/num_data : leaf totals (LeafSplits snapshot) — used for the
        complement side exactly like the reference (right = total - left).
    feature_mask : (F,) f32 0/1 — feature_fraction sampling mask.
    monotone/leaf_lo/leaf_hi : monotone-constraint surface (strategy
        seam, docs/TREES.md).  ``monotone`` is the (F,) int32 direction
        vector (+1/0/-1) and ``leaf_lo``/``leaf_hi`` the leaf's
        inherited output bounds.  ``None`` (the default) compiles the
        EXACT pre-constraint graph — the bit-parity contract for
        unconstrained training.  When set: candidate child outputs are
        clipped to [leaf_lo, leaf_hi], gains are scored at the clipped
        outputs (GetLeafSplitGainGivenOutput), and candidates on a
        constrained feature whose clipped outputs violate the direction
        are invalidated.  Categorical candidates keep unconstrained
        gains (their strategy direction is forced to 0; outputs are
        still bound-clipped by the grower).
    """
    f, b, _ = hist.shape
    l1, l2 = hyper.lambda_l1, hyper.lambda_l2
    min_cnt = hyper.min_data_in_leaf
    min_hess = hyper.min_sum_hessian_in_leaf

    if monotone is None:
        gain_shift = leaf_split_gain(sum_g, sum_h, l1, l2)
    else:
        parent_out = jnp.clip(leaf_output(sum_g, sum_h, l1, l2),
                              leaf_lo, leaf_hi)
        gain_shift = leaf_split_gain_given_output(
            sum_g, sum_h, l1, l2, parent_out)
    min_gain_shift = gain_shift + hyper.min_gain_to_split

    cum = jnp.cumsum(hist, axis=1)  # (F, B, 3)
    db = meta.default_bin  # (F,)
    nb = meta.num_bins  # (F,)
    hist_db = jnp.take_along_axis(hist, db[:, None, None], axis=1)[:, 0, :]  # (F, 3)

    thr = jnp.arange(b - 1)  # candidate thresholds t: left = bins <= t
    db_gt_t = (db[:, None] > thr[None, :]).astype(hist.dtype)  # (F, B-1)
    db_le_t = 1.0 - db_gt_t

    base = cum[:, : b - 1, :]  # natural left sums, (F, B-1, 3)
    # zero-left: default bin's mass always on the left
    left_zl = base + db_gt_t[:, :, None] * hist_db[:, None, :]
    # zero-right: default bin's mass always on the right
    left_zr = base - db_le_t[:, :, None] * hist_db[:, None, :]

    def eval_placement(left, extra_valid):
        lg, lh, lc = left[..., 0], left[..., 1], left[..., 2]
        rg, rh, rc = sum_g - lg, sum_h - lh, num_data - lc
        valid = (
            extra_valid
            & (lc >= min_cnt)
            & (rc >= min_cnt)
            & (lh >= min_hess)
            & (rh >= min_hess)
            & (thr[None, :] <= nb[:, None] - 2)
        )
        if monotone is None:
            gain = leaf_split_gain(lg, lh, l1, l2) + leaf_split_gain(rg, rh, l1, l2)
        else:
            lout = jnp.clip(leaf_output(lg, lh, l1, l2), leaf_lo, leaf_hi)
            rout = jnp.clip(leaf_output(rg, rh, l1, l2), leaf_lo, leaf_hi)
            c = monotone[:, None]  # (F, 1) broadcast over thresholds
            bad = ((c > 0) & (lout > rout)) | ((c < 0) & (lout < rout))
            gain = (leaf_split_gain_given_output(lg, lh, l1, l2, lout)
                    + leaf_split_gain_given_output(rg, rh, l1, l2, rout))
            gain = jnp.where(bad, NEG_INF, gain)
        gain = jnp.where(valid & (gain > min_gain_shift), gain, NEG_INF)
        return gain  # (F, B-1)

    interior = (db > 0) & (db < nb - 1)
    always = jnp.ones_like(db_gt_t, dtype=bool)
    if use_missing:
        # placement order and tie preference mirror
        # FindBestThresholdNumerical (hpp:85-96)
        gain_zl = eval_placement(left_zl, always & (thr[None, :] != db[:, None] - 1))
        gain_nat = eval_placement(base, interior[:, None] & always)
        gain_zr = eval_placement(
            left_zr, (nb[:, None] > 2) & (thr[None, :] != db[:, None])
        )
        # One flattened first-max argmax with the reference's tie order
        # baked into the axis layout: zero-left before natural before
        # zero-right (strict > between placements), HIGH threshold
        # preferred within zl/nat (reversed), LOW within zr — collapses
        # the 3x (argmax + takes + wheres) cascade, which dominates the
        # per-split cost inside the grower's while_loop.
        flat_gain = jnp.concatenate(
            [gain_zl[:, ::-1], gain_nat[:, ::-1], gain_zr], axis=1
        )  # (F, 3*(B-1))
        idx = jnp.argmax(flat_gain, axis=1)
        best_gain_f = jnp.take_along_axis(flat_gain, idx[:, None], axis=1)[:, 0]
        pl = idx // (b - 1)
        off = idx % (b - 1)
        best_thr_f = jnp.where(pl == 2, off, b - 2 - off).astype(jnp.int32)
        best_dbz_f = jnp.where(
            pl == 0, 0, jnp.where(pl == 1, db, nb - 1)
        ).astype(jnp.int32)
        left_all = jnp.concatenate([left_zl, base, left_zr], axis=1)  # (F, 3(B-1), 3)
        lidx = pl * (b - 1) + best_thr_f
        best_left_f = jnp.take_along_axis(left_all, lidx[:, None, None], axis=1)[:, 0, :]
    else:
        gain_nat = eval_placement(base, always)
        t_idx = _argmax_prefer_high(gain_nat)
        best_gain_f = jnp.take_along_axis(gain_nat, t_idx[:, None], axis=1)[:, 0]
        best_thr_f = t_idx.astype(jnp.int32)
        best_dbz_f = db.astype(jnp.int32)
        best_left_f = jnp.take_along_axis(base, t_idx[:, None, None], axis=1)[:, 0, :]

    if not has_categorical:
        best_gain_f = jnp.where(feature_mask > 0, best_gain_f, NEG_INF)
        best_gain_f = jnp.where(
            jnp.isfinite(best_gain_f), best_gain_f - min_gain_shift, NEG_INF
        )
        return best_gain_f, best_thr_f, best_dbz_f, best_left_f

    # categorical one-vs-rest (FindBestThresholdCategorical, hpp:100-198):
    # left = exactly bin t, decision type "is"; zeros keep their natural bin
    cg, ch, cc = hist[..., 0], hist[..., 1], hist[..., 2]  # (F, B)
    og, oh, oc = sum_g - cg, sum_h - ch, num_data - cc
    cat_valid = (
        (cc >= min_cnt)
        & (oc >= min_cnt)
        & (ch >= min_hess)
        & (oh >= min_hess)
        & (jnp.arange(b)[None, :] <= nb[:, None] - 1)
    )
    cat_gain = leaf_split_gain(cg, ch, l1, l2) + leaf_split_gain(og, oh, l1, l2)
    cat_gain = jnp.where(cat_valid & (cat_gain > min_gain_shift), cat_gain, NEG_INF)
    cat_t = _argmax_prefer_high(cat_gain)  # right-to-left scan
    cat_best = jnp.take_along_axis(cat_gain, cat_t[:, None], axis=1)[:, 0]
    cat_left = jnp.take_along_axis(hist, cat_t[:, None, None], axis=1)[:, 0, :]

    is_cat = meta.is_categorical
    best_gain_f = jnp.where(is_cat, cat_best, best_gain_f)
    best_thr_f = jnp.where(is_cat, cat_t.astype(jnp.int32), best_thr_f)
    best_dbz_f = jnp.where(is_cat, db, best_dbz_f)
    best_left_f = jnp.where(is_cat[:, None], cat_left, best_left_f)

    best_gain_f = jnp.where(feature_mask > 0, best_gain_f, NEG_INF)
    # subtract the shift so gains are comparable across leaves/shards
    best_gain_f = jnp.where(
        jnp.isfinite(best_gain_f), best_gain_f - min_gain_shift, NEG_INF
    )
    return best_gain_f, best_thr_f, best_dbz_f, best_left_f


def finalize_split(gain_f, thr_f, dbz_f, left_f, sum_g, sum_h, num_data,
                   hyper: SplitHyper, leaf_lo=None, leaf_hi=None
                   ) -> SplitResult:
    """Global argmax over the per-feature arrays (ArrayArgs::ArgMax —
    first/lowest index wins ties) and SplitInfo assembly.
    ``leaf_lo``/``leaf_hi`` (monotone bounds) clip the child outputs;
    None keeps the exact unconstrained graph."""
    l1, l2 = hyper.lambda_l1, hyper.lambda_l2
    fbest = jnp.argmax(gain_f).astype(jnp.int32)
    gain = gain_f[fbest]
    left = left_f[fbest]
    lg, lh, lc = left[0], left[1], left[2]
    rg, rh, rc = sum_g - lg, sum_h - lh, num_data - lc
    lout = leaf_output(lg, lh, l1, l2)
    rout = leaf_output(rg, rh, l1, l2)
    if leaf_lo is not None:
        lout = jnp.clip(lout, leaf_lo, leaf_hi)
        rout = jnp.clip(rout, leaf_lo, leaf_hi)
    return SplitResult(
        gain=gain,
        feature=fbest,
        threshold_bin=thr_f[fbest],
        default_bin_for_zero=dbz_f[fbest],
        left_sum_g=lg,
        left_sum_h=lh,
        left_cnt=lc,
        right_sum_g=rg,
        right_sum_h=rh,
        right_cnt=rc,
        left_output=lout,
        right_output=rout,
    )


def slice_features(meta: FeatureMeta, lo: int, hi: int) -> FeatureMeta:
    """Metadata for the contiguous column block ``[lo, hi)`` — the unit
    the feature-parallel learner shards over."""
    return FeatureMeta(
        meta.num_bins[lo:hi], meta.default_bin[lo:hi],
        meta.is_categorical[lo:hi]
    )


def best_split_feature_block(
    hist: jnp.ndarray,
    lo: jnp.ndarray,
    sum_g: jnp.ndarray,
    sum_h: jnp.ndarray,
    num_data: jnp.ndarray,
    meta_block: FeatureMeta,
    hyper: SplitHyper,
    feature_mask_block: jnp.ndarray,
    use_missing: bool = True,
    monotone: jnp.ndarray = None,
    leaf_lo: jnp.ndarray = None,
    leaf_hi: jnp.ndarray = None,
) -> SplitResult:
    """Best split over a contiguous column block starting at global
    feature index ``lo``; ``hist``/``meta_block``/``feature_mask_block``
    cover only the block's columns and the returned ``feature`` is
    GLOBAL.  The per-feature scan is elementwise in F, so a block's
    result equals the corresponding slice of the full-matrix scan bit
    for bit — the property that lets feature-parallel ranks search only
    their own columns yet reproduce the serial model exactly.
    ``monotone`` covers only the block's columns."""
    gain_f, thr_f, dbz_f, left_f = best_split_per_feature(
        hist, sum_g, sum_h, num_data, meta_block, hyper,
        feature_mask_block, use_missing,
        monotone=monotone, leaf_lo=leaf_lo, leaf_hi=leaf_hi,
    )
    res = finalize_split(
        gain_f, thr_f, dbz_f, left_f, sum_g, sum_h, num_data, hyper,
        leaf_lo=leaf_lo, leaf_hi=leaf_hi,
    )
    return res._replace(feature=res.feature + jnp.int32(lo))


def best_split_all_features(
    hist: jnp.ndarray,
    sum_g: jnp.ndarray,
    sum_h: jnp.ndarray,
    num_data: jnp.ndarray,
    meta: FeatureMeta,
    hyper: SplitHyper,
    feature_mask: jnp.ndarray,
    use_missing: bool = True,
    monotone: jnp.ndarray = None,
    leaf_lo: jnp.ndarray = None,
    leaf_hi: jnp.ndarray = None,
) -> SplitResult:
    """Best split across every feature for one leaf (per-feature scan +
    global argmax)."""
    gain_f, thr_f, dbz_f, left_f = best_split_per_feature(
        hist, sum_g, sum_h, num_data, meta, hyper, feature_mask, use_missing,
        monotone=monotone, leaf_lo=leaf_lo, leaf_hi=leaf_hi,
    )
    return finalize_split(gain_f, thr_f, dbz_f, left_f, sum_g, sum_h,
                          num_data, hyper, leaf_lo=leaf_lo, leaf_hi=leaf_hi)

