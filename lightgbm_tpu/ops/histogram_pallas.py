"""Pallas TPU histogram kernel — the device counterpart of the
reference's GPU histogram kernels (src/treelearner/ocl/histogram256.cl:345
per-workgroup sub-histograms + in-kernel reduction; host driver
src/treelearner/gpu_tree_learner.cpp:123-191).

Why not the XLA one-hot matmul (ops/histogram.py)?  XLA materializes the
(rows, F*B) one-hot operand through HBM — ~7 KB of traffic per row — which
measures at ~0.21 us/row on v5e.  Here the one-hot tile is built in VMEM,
fed straight to the MXU, and never touches HBM: the kernel streams only
the packed bin words + values (~44 B/row) and accumulates the (F*B, 4)
histogram in a VMEM scratch across sequential grid steps.

Input layout: one (C, S) int32 matrix `P` whose rows are
    [0..W)   : packed bin words (`per` bins of `bits` bits each per word)
    W        : grad  (f32 bitcast)
    W+1      : hess  (f32 bitcast)
    W+2      : select(f32 bitcast; 0/1 bagging x leaf mask)
(extra rows beyond W+3, e.g. a row-id payload, are ignored).  This is the
partitioned-data layout of ops/pgrow.py: a leaf's rows are a contiguous
column range, so the kernel only needs a [lo, hi) column mask — no gather.

Output: (F, B, 3) f32 of (sum_grad, sum_hess, count) per (feature, bin).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Columns (rows of data) per grid step.  The one-hot chunk is
# (FCHUNK*B, BLK) f32; BLK=1024 with FCHUNK*B<=512 keeps it ~2 MB.
BLK = 1024


def _hist_kernel(lohi_ref, p_ref, out_ref, acc_ref, *, nf, nb, w_words, per, bits, fchunk):
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        acc_ref[:, :] = jnp.zeros_like(acc_ref)

    pos = jax.lax.broadcasted_iota(jnp.int32, (1, BLK), 1) + j * BLK
    valid = ((pos >= lohi_ref[0]) & (pos < lohi_ref[1])).astype(jnp.float32)
    g = pltpu.bitcast(p_ref[w_words : w_words + 1, :], jnp.float32)
    h = pltpu.bitcast(p_ref[w_words + 1 : w_words + 2, :], jnp.float32)
    sel = pltpu.bitcast(p_ref[w_words + 2 : w_words + 3, :], jnp.float32) * valid
    gs = g * sel
    hs = h * sel

    # The MXU's fast path is bf16xbf16->f32, but a bf16-rounded gradient
    # loses ~2^-8 relative accuracy per element (the reference's GPU kernel
    # keeps f32 accumulators for the same reason, histogram256.cl:345).
    # Because the dot's N dimension pads to 128 lanes regardless, extra
    # value rows are FREE: send each value as THREE bf16 terms
    # (x = hi + mid + lo, covering ~24 mantissa bits = f32 fidelity) and
    # re-sum the three output columns outside — f32 accuracy at bf16 speed.
    def split3(x):
        x_hi = x.astype(jnp.bfloat16)
        r1 = x - x_hi.astype(jnp.float32)
        x_mid = r1.astype(jnp.bfloat16)
        x_lo = (r1 - x_mid.astype(jnp.float32)).astype(jnp.bfloat16)
        return x_hi, x_mid, x_lo

    g3 = split3(gs)
    h3 = split3(hs)
    vals = jnp.concatenate(
        list(g3) + list(h3) + [sel.astype(jnp.bfloat16)], axis=0
    )  # (7, BLK) bf16

    mask_v = (1 << bits) - 1
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (nb, BLK), 0)
    for c0 in range(0, nf, fchunk):
        c1 = min(c0 + fchunk, nf)
        chunks = []
        for f in range(c0, c1):
            w, p = divmod(f, per)
            byte = (p_ref[w : w + 1, :] >> (p * bits)) & mask_v  # (1, BLK)
            chunks.append((byte == iota_b).astype(jnp.bfloat16))  # (nb, BLK)
        oh = jnp.concatenate(chunks, axis=0)  # ((c1-c0)*nb, BLK)
        acc_ref[c0 * nb : c1 * nb, :] += jax.lax.dot_general(
            oh,
            vals,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == pl.num_programs(0) - 1)
    def _flush():
        out_ref[:, :] = acc_ref[:, :]


@functools.partial(
    jax.jit, static_argnames=("num_features", "num_bins", "per", "bits")
)
def hist_segment(
    p: jnp.ndarray,
    lo: jnp.ndarray,
    hi: jnp.ndarray,
    num_features: int,
    num_bins: int,
    per: int = 4,
    bits: int = 8,
) -> jnp.ndarray:
    """(F, B, 3) histogram of columns [lo, hi) of the packed matrix ``p``.

    p : (C, S) int32, S a multiple of BLK — see module docstring.
    lo, hi : int32 scalars — the valid column range (the leaf's segment,
      relative to this slice).  Columns outside contribute zero.
    """
    c, s = p.shape
    assert s % BLK == 0, f"segment length {s} not a multiple of {BLK}"
    w_words = -(-num_features // per)
    fb = num_features * num_bins
    # chunk features so the one-hot tile stays ~<=2MB and row count is a
    # multiple of 128 where possible
    fchunk = max(1, min(num_features, 512 // num_bins))

    lohi = jnp.stack([lo.astype(jnp.int32), hi.astype(jnp.int32)])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(s // BLK,),
        in_specs=[
            pl.BlockSpec((c, BLK), lambda j, lohi: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (fb, 7), lambda j, lohi: (0, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[pltpu.VMEM((fb, 7), jnp.float32)],
    )
    out = pl.pallas_call(
        functools.partial(
            _hist_kernel,
            nf=num_features,
            nb=num_bins,
            w_words=w_words,
            per=per,
            bits=bits,
            fchunk=fchunk,
        ),
        out_shape=jax.ShapeDtypeStruct((fb, 7), jnp.float32),
        grid_spec=grid_spec,
    )(lohi, p)
    # re-sum the 3-term splits: (sum_g, sum_h, count)
    hist = jnp.stack(
        [
            out[:, 0] + (out[:, 1] + out[:, 2]),
            out[:, 3] + (out[:, 4] + out[:, 5]),
            out[:, 6],
        ],
        axis=1,
    )
    return hist.reshape(num_features, num_bins, 3)


def pack_columns(
    bins, grad, hess, select, row_id=None, per: int = 4, bits: int = 8
):
    """Build the (C, N) int32 packed matrix from (N, F) bins + value
    vectors.  Rows: W bin words, grad, hess, select[, row_id]."""
    n, f = bins.shape
    w = -(-f // per)
    pad_f = w * per - f
    bb = jnp.pad(bins.astype(jnp.int32), ((0, 0), (0, pad_f)))
    bb = bb.reshape(n, w, per)
    shifts = (jnp.arange(per) * bits).astype(jnp.int32)
    words = jnp.sum(bb << shifts[None, None, :], axis=2, dtype=jnp.int32)  # (N, W)
    rows = [
        words.T,
        jax.lax.bitcast_convert_type(grad.astype(jnp.float32), jnp.int32)[None, :],
        jax.lax.bitcast_convert_type(hess.astype(jnp.float32), jnp.int32)[None, :],
        jax.lax.bitcast_convert_type(select.astype(jnp.float32), jnp.int32)[None, :],
    ]
    if row_id is not None:
        rows.append(row_id.astype(jnp.int32)[None, :])
    return jnp.concatenate(rows, axis=0)
