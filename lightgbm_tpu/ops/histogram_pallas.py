"""Pallas TPU histogram kernel — the device counterpart of the
reference's GPU histogram kernels (src/treelearner/ocl/histogram256.cl:345
per-workgroup sub-histograms + in-kernel reduction; host driver
src/treelearner/gpu_tree_learner.cpp:123-191).

Why not the XLA one-hot matmul (ops/histogram.py)?  XLA materializes the
(rows, F*B) one-hot operand through HBM — ~7 KB of traffic per row — which
measures at ~0.21 us/row on v5e.  Here the one-hot tile is built in VMEM,
fed straight to the MXU, and never touches HBM: the kernel streams only
the packed bin words + values (~44 B/row) and accumulates the (F*B, 4)
histogram in a VMEM scratch across sequential grid steps.

Input layout: one (C, S) int32 matrix `P` whose rows are
    [0..W)   : packed bin words (`per` bins of `bits` bits each per word)
    W        : grad  (f32 bitcast)
    W+1      : hess  (f32 bitcast)
    W+2      : select(f32 bitcast; 0/1 bagging x leaf mask)
(extra rows beyond W+3, e.g. a row-id payload, are ignored).  This is the
partitioned-data layout of ops/pgrow.py: a leaf's rows are a contiguous
column range, so the kernel only needs a [lo, hi) column mask — no gather.

Output: (F, B, 3) f32 of (sum_grad, sum_hess, count) per (feature, bin).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Columns (rows of data) per grid step.  The one-hot chunk is
# (FCHUNK*B, BLK) bf16; BLK=1024 with FCHUNK*B<=1024 keeps it <=2 MB.
BLK = 1024
_LANE = 128  # MXU/DMA lane quantum


def tune_fchunk(num_features: int, num_bins: int,
                max_tile_bytes: int = 2 * 1024 * 1024) -> int:
    """Feature-chunk width for the one-hot histogram dots, tuned against
    the (bin-count, feature-count) shape instead of the old fixed
    ``512 // num_bins`` rule.

    The kernel builds the bin one-hots as an (fchunk*B, BLK) bf16 tile
    and contracts it on the MXU.  Per 1024-row block the estimated cost
    is sum over chunks of roundup(chunk*B, 128) MXU rows (the systolic
    array pads the non-contracting dim to the 128-lane quantum) plus a
    fixed per-dot issue overhead — so the tuner prefers chunk widths
    whose row count is 128-aligned AND divide the feature count evenly
    (no ragged tail tile), under a VMEM tile budget.  Bit-safety: fchunk
    only groups which (feature, bin) cells share one dot_general; each
    cell still contracts the same BLK lanes in the same order, so ANY
    fchunk produces bit-identical histograms.

    ``LIGHTGBM_TPU_HIST_FCHUNK`` overrides (clamped to [1, F]); the
    split/level kernels call with a smaller ``max_tile_bytes`` because
    their VMEM is already crowded by the partition stream buffers.
    """
    env = os.environ.get("LIGHTGBM_TPU_HIST_FCHUNK", "")
    if env:
        try:
            return max(1, min(num_features, int(env)))
        except ValueError:
            pass
    cap = max(1, min(num_features, max_tile_bytes // max(num_bins * BLK * 2, 1)))
    best = max(
        range(1, cap + 1),
        key=lambda f: (-fchunk_cost(num_features, num_bins, f), f),
    )
    return best


def fchunk_cost(num_features: int, num_bins: int, fchunk: int) -> int:
    """Estimated per-block MXU row cost of a feature-chunk width: sum of
    128-padded one-hot rows over chunks plus a fixed per-dot issue
    overhead.  Exposed for the bench kernel A/B report."""
    cost, rem, chunks = 0, num_features, 0
    while rem > 0:
        c = min(fchunk, rem)
        rem -= c
        chunks += 1
        cost += -(-c * num_bins // _LANE) * _LANE
    return cost + chunks * 256  # per-dot issue overhead (~2 lane rows)


def _hist_kernel(lohi_ref, p_ref, out_ref, acc_ref, *, nf, nb, rows, per, bits, fchunk):
    j = pl.program_id(0)
    g_row, h_row, sel_row = rows

    @pl.when(j == 0)
    def _init():
        acc_ref[:, :] = jnp.zeros_like(acc_ref)

    pos = jax.lax.broadcasted_iota(jnp.int32, (1, BLK), 1) + j * BLK
    valid = ((pos >= lohi_ref[0]) & (pos < lohi_ref[1])).astype(jnp.float32)
    g = pltpu.bitcast(p_ref[g_row : g_row + 1, :], jnp.float32)
    h = pltpu.bitcast(p_ref[h_row : h_row + 1, :], jnp.float32)
    sel = pltpu.bitcast(p_ref[sel_row : sel_row + 1, :], jnp.float32) * valid
    gs = g * sel
    hs = h * sel

    # The MXU's fast path is bf16xbf16->f32, but a bf16-rounded gradient
    # loses ~2^-8 relative accuracy per element (the reference's GPU kernel
    # keeps f32 accumulators for the same reason, histogram256.cl:345).
    # Because the dot's N dimension pads to 128 lanes regardless, extra
    # value rows are FREE: send each value as THREE bf16 terms
    # (x = hi + mid + lo, covering ~24 mantissa bits = f32 fidelity) and
    # re-sum the three output columns outside — f32 accuracy at bf16 speed.
    def split3(x):
        x_hi = x.astype(jnp.bfloat16)
        r1 = x - x_hi.astype(jnp.float32)
        x_mid = r1.astype(jnp.bfloat16)
        x_lo = (r1 - x_mid.astype(jnp.float32)).astype(jnp.bfloat16)
        return x_hi, x_mid, x_lo

    g3 = split3(gs)
    h3 = split3(hs)
    vals = jnp.concatenate(
        list(g3) + list(h3) + [sel.astype(jnp.bfloat16)], axis=0
    )  # (7, BLK) bf16

    mask_v = (1 << bits) - 1
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (nb, BLK), 0)
    for c0 in range(0, nf, fchunk):
        c1 = min(c0 + fchunk, nf)
        chunks = []
        for f in range(c0, c1):
            w, p = divmod(f, per)
            byte = (p_ref[w : w + 1, :] >> (p * bits)) & mask_v  # (1, BLK)
            chunks.append((byte == iota_b).astype(jnp.bfloat16))  # (nb, BLK)
        oh = jnp.concatenate(chunks, axis=0)  # ((c1-c0)*nb, BLK)
        acc_ref[c0 * nb : c1 * nb, :] += jax.lax.dot_general(
            oh,
            vals,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == pl.num_programs(0) - 1)
    def _flush():
        out_ref[:, :] = acc_ref[:, :]


@functools.partial(
    jax.jit,
    static_argnames=("num_features", "num_bins", "per", "bits", "rows", "interpret"),
)
def hist_segment(
    p: jnp.ndarray,
    lo: jnp.ndarray,
    hi: jnp.ndarray,
    num_features: int,
    num_bins: int,
    per: int = 4,
    bits: int = 8,
    rows: tuple = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """(F, B, 3) histogram of columns [lo, hi) of the packed matrix ``p``.

    p : (C, S) int32, S a multiple of BLK — see module docstring.
    lo, hi : int32 scalars — the valid column range (the leaf's segment,
      relative to this slice).  Columns outside contribute zero.
    rows : optional (g, h, sel) channel-row triple for matrices whose
      value rows are NOT at W..W+2 (the pgrow packed layout pads the bin
      words to 8 sublanes — pass ``PLayout.rows``).
    """
    c, s = p.shape
    assert s % BLK == 0, f"segment length {s} not a multiple of {BLK}"
    if rows is None:
        w_words = -(-num_features // per)
        rows = (w_words, w_words + 1, w_words + 2)
    fb = num_features * num_bins
    fchunk = tune_fchunk(num_features, num_bins)

    lohi = jnp.stack([lo.astype(jnp.int32), hi.astype(jnp.int32)])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(s // BLK,),
        in_specs=[
            pl.BlockSpec((c, BLK), lambda j, lohi: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (fb, 7), lambda j, lohi: (0, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[pltpu.VMEM((fb, 7), jnp.float32)],
    )
    out = pl.pallas_call(
        functools.partial(
            _hist_kernel,
            nf=num_features,
            nb=num_bins,
            rows=rows,
            per=per,
            bits=bits,
            fchunk=fchunk,
        ),
        out_shape=jax.ShapeDtypeStruct((fb, 7), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(lohi, p)
    # re-sum the 3-term splits: (sum_g, sum_h, count)
    hist = jnp.stack(
        [
            out[:, 0] + (out[:, 1] + out[:, 2]),
            out[:, 3] + (out[:, 4] + out[:, 5]),
            out[:, 6],
        ],
        axis=1,
    )
    return hist.reshape(num_features, num_bins, 3)


# ======================================================================
# hist_segments: multi-leaf segmented histograms, ONE kernel launch
# ======================================================================
def _hist_multi_kernel(sref, p_any, hist_out, acc2, buf_ref, rsem, hsem, *,
                       nf, nb, rows, c, fchunk, bits, fbp):
    """All ``n_active`` leaf segments' (F, B) histograms in one launch.

    Per-segment streaming copies _hist_kernel's double-buffered DMA
    pattern (ops/pkernels._hist_kernel); per-segment (8, F*B) results
    are DMA'd to the output double-buffered while the next segment
    streams — the per-leaf kernel-launch fixed cost (~0.3 ms measured on
    the tunneled runtime) collapses to one launch per LEVEL.

    sref: (1 + smax, 2) int32 — row 0 holds [n_active, 0]; row 1+s holds
    segment s's [start, cnt]."""
    n_active = sref[0, 0]
    g_row, h_row, sel_row = rows
    per = 32 // bits
    mask = (1 << bits) - 1
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (nb, BLK), 0)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, BLK), 1)

    def one_seg(s, _):
        slot = jax.lax.rem(s, 2)

        # wait for the DMA that used this accumulator slot two segments ago
        @pl.when(s >= 2)
        def _():
            pltpu.make_async_copy(acc2.at[slot], acc2.at[slot], hsem.at[slot]).wait()

        acc2[slot] = jnp.zeros_like(acc2[slot])
        acc = acc2.at[slot]
        start = sref[1 + s, 0]
        cnt = sref[1 + s, 1]
        base = pl.multiple_of((start // BLK) * BLK, _LANE)
        head = start - base
        nblk = (head + cnt + BLK - 1) // BLK

        def get_dma(bslot, j):
            return pltpu.make_async_copy(
                p_any.at[:, pl.ds(base + j * BLK, BLK)], buf_ref.at[bslot],
                rsem.at[bslot],
            )

        @pl.when(nblk > 0)
        def _():
            get_dma(0, 0).start()

        def body(j, _):
            bslot = jax.lax.rem(j, 2)

            @pl.when(j + 1 < nblk)
            def _():
                get_dma(1 - bslot, j + 1).start()

            get_dma(bslot, j).wait()
            blk = buf_ref[bslot]
            pos = lane + j * BLK
            valid = ((pos >= head) & (pos < head + cnt)).astype(jnp.float32)
            sel = pltpu.bitcast(blk[sel_row : sel_row + 1, :], jnp.float32) * valid
            g = pltpu.bitcast(blk[g_row : g_row + 1, :], jnp.float32) * sel
            h = pltpu.bitcast(blk[h_row : h_row + 1, :], jnp.float32) * sel

            def split3(x):
                x_hi = x.astype(jnp.bfloat16)
                r1 = x - x_hi.astype(jnp.float32)
                x_mid = r1.astype(jnp.bfloat16)
                x_lo = (r1 - x_mid.astype(jnp.float32)).astype(jnp.bfloat16)
                return [x_hi, x_mid, x_lo]

            vals = jnp.concatenate(
                split3(g) + split3(h) + [sel.astype(jnp.bfloat16)], axis=0
            )
            for c0 in range(0, nf, fchunk):
                c1 = min(c0 + fchunk, nf)
                chunks = []
                for f in range(c0, c1):
                    wd, p4 = divmod(f, per)
                    byte = (blk[wd : wd + 1, :] >> (p4 * bits)) & mask
                    chunks.append((byte == iota_b).astype(jnp.bfloat16))
                oh = jnp.concatenate(chunks, axis=0)
                acc[0:7, c0 * nb : c1 * nb] += jax.lax.dot_general(
                    vals, oh, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            return 0

        jax.lax.fori_loop(0, nblk, body, 0)
        pltpu.make_async_copy(acc2.at[slot], hist_out.at[s], hsem.at[slot]).start()
        return 0

    jax.lax.fori_loop(0, n_active, one_seg, 0)

    @pl.when(n_active >= 1)
    def _():
        slot = jax.lax.rem(n_active - 1, 2)
        pltpu.make_async_copy(acc2.at[slot], acc2.at[slot], hsem.at[slot]).wait()

    @pl.when(n_active >= 2)
    def _():
        slot = jax.lax.rem(n_active - 2, 2)
        pltpu.make_async_copy(acc2.at[slot], acc2.at[slot], hsem.at[slot]).wait()


@functools.partial(
    jax.jit,
    static_argnames=("num_features", "num_bins", "bits", "rows", "smax", "interpret"),
)
def hist_segments(
    p: jnp.ndarray,
    seg_tab: jnp.ndarray,
    n_active,
    *,
    num_features: int,
    num_bins: int,
    bits: int = 8,
    rows: tuple = None,
    smax: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """(smax, F, B, 3) histograms of ``n_active`` leaf segments of the
    packed matrix ``p`` in ONE kernel launch — the multi-leaf form of
    ``hist_segment`` for level-batched growers (one launch covers every
    active leaf of a tree level instead of one launch per leaf).

    seg_tab : (smax, 2) int32 rows of [start, cnt] (disjoint segments).
      Output rows for s >= n_active are undefined.  ``p`` must
      have enough tail columns that every segment's covering BLK-blocks
      exist (the pgrow packed matrix carries a BLK tail for exactly
      this; otherwise pad columns to the next BLK multiple).
    rows : (g, h, sel) channel-row triple; defaults to the plain
      pack_columns layout (W, W+1, W+2).
    """
    c = p.shape[0]
    per = 32 // bits
    if rows is None:
        w_words = -(-num_features // per)
        rows = (w_words, w_words + 1, w_words + 2)
    fb = num_features * num_bins
    fbp = -(-fb // _LANE) * _LANE  # sliced VMEM refs must be lane-aligned
    fchunk = tune_fchunk(num_features, num_bins)
    hdr = jnp.zeros((1, 2), jnp.int32).at[0, 0].set(jnp.int32(n_active))
    sv = jnp.concatenate([hdr, seg_tab.astype(jnp.int32)], axis=0)
    out = pl.pallas_call(
        functools.partial(
            _hist_multi_kernel, nf=num_features, nb=num_bins, rows=rows,
            c=c, fchunk=fchunk, bits=bits, fbp=fbp,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[
                pltpu.VMEM((2, 8, fbp), jnp.float32),  # double-buffered acc
                pltpu.VMEM((2, c, BLK), jnp.int32),  # stream buffers
                pltpu.SemaphoreType.DMA((2,)),  # read sem
                pltpu.SemaphoreType.DMA((2,)),  # hist-out sem
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((smax, 8, fbp), jnp.float32),
        interpret=interpret,
    )(sv, p)
    out = out[:, :, :fb]
    hist = jnp.stack(
        [
            out[:, 0] + (out[:, 1] + out[:, 2]),
            out[:, 3] + (out[:, 4] + out[:, 5]),
            out[:, 6],
        ],
        axis=2,
    )  # (smax, F*B, 3)
    return hist.reshape(smax, num_features, num_bins, 3)


# ======================================================================
# quantized-training variant: exact int32 accumulation
# ======================================================================
def _hist_kernel_q(lohi_ref, p_ref, out_ref, acc_ref, *, nf, nb, rows, per,
                   bits, fchunk):
    """Integer twin of ``_hist_kernel`` for quantized training: the value
    rows hold int16 levels stored as plain int32 words (no f32 bitcast),
    the one-hot tile is int32, and the dot accumulates with
    ``preferred_element_type=int32``.  No 3-term bf16 split — integer
    accumulation is EXACT, so one term suffices and the (F*B, 3) output
    needs no re-summation pass."""
    j = pl.program_id(0)
    g_row, h_row, sel_row = rows

    @pl.when(j == 0)
    def _init():
        acc_ref[:, :] = jnp.zeros_like(acc_ref)

    pos = jax.lax.broadcasted_iota(jnp.int32, (1, BLK), 1) + j * BLK
    valid = ((pos >= lohi_ref[0]) & (pos < lohi_ref[1])).astype(jnp.int32)
    sel = p_ref[sel_row : sel_row + 1, :] * valid  # int32 0/1
    g = p_ref[g_row : g_row + 1, :] * sel
    h = p_ref[h_row : h_row + 1, :] * sel
    vals = jnp.concatenate([g, h, sel], axis=0)  # (3, BLK) int32

    mask_v = (1 << bits) - 1
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (nb, BLK), 0)
    for c0 in range(0, nf, fchunk):
        c1 = min(c0 + fchunk, nf)
        chunks = []
        for f in range(c0, c1):
            w, p = divmod(f, per)
            byte = (p_ref[w : w + 1, :] >> (p * bits)) & mask_v
            chunks.append((byte == iota_b).astype(jnp.int32))
        oh = jnp.concatenate(chunks, axis=0)  # ((c1-c0)*nb, BLK) int32
        acc_ref[c0 * nb : c1 * nb, :] += jax.lax.dot_general(
            oh,
            vals,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        )

    @pl.when(j == pl.num_programs(0) - 1)
    def _flush():
        out_ref[:, :] = acc_ref[:, :]


@functools.partial(
    jax.jit,
    static_argnames=("num_features", "num_bins", "per", "bits", "rows", "interpret"),
)
def hist_segment_q(
    p: jnp.ndarray,
    lo: jnp.ndarray,
    hi: jnp.ndarray,
    num_features: int,
    num_bins: int,
    per: int = 4,
    bits: int = 8,
    rows: tuple = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """(F, B, 3) EXACT int32 histogram of columns [lo, hi) of a
    quantized packed matrix (``pack_columns_q``) — the quantized-training
    twin of :func:`hist_segment`.  The output is order-invariant by
    construction (integer adds), which the bench ``kernel_ab`` leg pins
    against the f32 kernel in interpret mode."""
    c, s = p.shape
    assert s % BLK == 0, f"segment length {s} not a multiple of {BLK}"
    if rows is None:
        w_words = -(-num_features // per)
        rows = (w_words, w_words + 1, w_words + 2)
    fb = num_features * num_bins
    fchunk = tune_fchunk(num_features, num_bins)

    lohi = jnp.stack([lo.astype(jnp.int32), hi.astype(jnp.int32)])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(s // BLK,),
        in_specs=[
            pl.BlockSpec((c, BLK), lambda j, lohi: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (fb, 3), lambda j, lohi: (0, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[pltpu.VMEM((fb, 3), jnp.int32)],
    )
    out = pl.pallas_call(
        functools.partial(
            _hist_kernel_q,
            nf=num_features,
            nb=num_bins,
            rows=rows,
            per=per,
            bits=bits,
            fchunk=fchunk,
        ),
        out_shape=jax.ShapeDtypeStruct((fb, 3), jnp.int32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(lohi, p)
    return out.reshape(num_features, num_bins, 3)


def pack_columns_q(bins, qgrad, qhess, select, per: int = 4, bits: int = 8):
    """Quantized twin of :func:`pack_columns`: the value rows carry the
    int16 levels (and the 0/1 select) widened to plain int32 words —
    integer identity, no bitcasting."""
    n, f = bins.shape
    w = -(-f // per)
    pad_f = w * per - f
    bb = jnp.pad(bins.astype(jnp.int32), ((0, 0), (0, pad_f)))
    bb = bb.reshape(n, w, per)
    shifts = (jnp.arange(per) * bits).astype(jnp.int32)
    words = jnp.sum(bb << shifts[None, None, :], axis=2, dtype=jnp.int32)
    rows = [
        words.T,
        qgrad.astype(jnp.int32)[None, :],
        qhess.astype(jnp.int32)[None, :],
        select.astype(jnp.int32)[None, :],
    ]
    return jnp.concatenate(rows, axis=0)


def pack_columns(
    bins, grad, hess, select, row_id=None, per: int = 4, bits: int = 8
):
    """Build the (C, N) int32 packed matrix from (N, F) bins + value
    vectors.  Rows: W bin words, grad, hess, select[, row_id]."""
    n, f = bins.shape
    w = -(-f // per)
    pad_f = w * per - f
    bb = jnp.pad(bins.astype(jnp.int32), ((0, 0), (0, pad_f)))
    bb = bb.reshape(n, w, per)
    shifts = (jnp.arange(per) * bits).astype(jnp.int32)
    words = jnp.sum(bb << shifts[None, None, :], axis=2, dtype=jnp.int32)  # (N, W)
    rows = [
        words.T,
        jax.lax.bitcast_convert_type(grad.astype(jnp.float32), jnp.int32)[None, :],
        jax.lax.bitcast_convert_type(hess.astype(jnp.float32), jnp.int32)[None, :],
        jax.lax.bitcast_convert_type(select.astype(jnp.float32), jnp.int32)[None, :],
    ]
    if row_id is not None:
        rows.append(row_id.astype(jnp.int32)[None, :])
    return jnp.concatenate(rows, axis=0)
