"""Leaf-wise tree growth under jit — counterpart of
SerialTreeLearner::Train (src/treelearner/serial_tree_learner.cpp:152-207)
plus DataPartition (data_partition.hpp) and the histogram pool.

TPU-first redesign:
- The per-leaf index lists of DataPartition become one flat ``leaf_id[N]``
  vector updated by a predicate on the split feature's bin column
  (partition-by-predicate: O(N) per split, no index shuffling, static
  shapes).
- The LRU HistogramPool becomes a dense ``(num_leaves, F, B, 3)`` pool —
  every active leaf keeps its histogram so the subtraction trick
  (larger child = parent - smaller) is one tensor subtract
  (serial_tree_learner.cpp:484-489).
- The best-first loop is a ``lax.while_loop`` whose state carries the
  per-leaf best-split table (best_split_per_leaf_); each iteration splits
  the argmax-gain leaf and recomputes best splits only for the two
  children, exactly like the reference.
- The reference's BeforeFindBestSplit data-count gate (both children
  < 2*min_data_in_leaf) is subsumed by the in-scan min_data masks — a leaf
  with cnt < 2*min_data can never satisfy min_data on both sides — so only
  the max_depth gate is applied explicitly.

Everything is static-shaped: one XLA compile per
(N, F, B, num_leaves) configuration, reused across all boosting
iterations.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .histogram import ROW_BLOCK, build_histogram
from .split import (
    NEG_INF,
    FeatureMeta,
    SplitHyper,
    best_split_all_features,
    leaf_output,
)


class GrowParams(NamedTuple):
    """Static (compile-time) growth parameters."""

    num_leaves: int
    num_bins: int  # padded B
    max_depth: int = -1
    use_missing: bool = True
    row_block: int = ROW_BLOCK


class GrowResult(NamedTuple):
    """Arrays describing the grown tree; host code turns this into a Tree
    model (model/tree.py).  Record index s = s-th split."""

    num_splits: jnp.ndarray  # scalar int32; num_leaves = num_splits + 1
    leaf_id: jnp.ndarray  # (N,) int32 final leaf of every row
    leaf_value: jnp.ndarray  # (L,) raw (pre-shrinkage) outputs
    leaf_cnt: jnp.ndarray  # (L,) f32
    rec_leaf: jnp.ndarray  # (L-1,) int32 leaf index that was split
    rec_feat: jnp.ndarray  # (L-1,) int32 inner feature
    rec_thr: jnp.ndarray  # (L-1,) int32 threshold bin
    rec_dbz: jnp.ndarray  # (L-1,) int32 default_bin_for_zero
    rec_gain: jnp.ndarray  # (L-1,) f32 split gain
    rec_lval: jnp.ndarray  # (L-1,) f32 left child output
    rec_rval: jnp.ndarray  # (L-1,) f32 right child output
    rec_lcnt: jnp.ndarray  # (L-1,) f32
    rec_rcnt: jnp.ndarray  # (L-1,) f32
    rec_internal_value: jnp.ndarray  # (L-1,) f32 parent leaf value


class _State(NamedTuple):
    num_splits: jnp.ndarray
    done: jnp.ndarray
    leaf_id: jnp.ndarray
    pool: jnp.ndarray  # (L, F, B, 3)
    # best_split_per_leaf_ table
    bs_gain: jnp.ndarray  # (L,)
    bs_feat: jnp.ndarray
    bs_thr: jnp.ndarray
    bs_dbz: jnp.ndarray
    bs_left: jnp.ndarray  # (L, 3) left (sum_g, sum_h, cnt)
    # per-leaf totals & bookkeeping
    leaf_sum: jnp.ndarray  # (L, 3)
    leaf_value: jnp.ndarray  # (L,)
    leaf_cnt: jnp.ndarray  # (L,)
    leaf_depth: jnp.ndarray  # (L,)
    # split records
    rec_leaf: jnp.ndarray
    rec_feat: jnp.ndarray
    rec_thr: jnp.ndarray
    rec_dbz: jnp.ndarray
    rec_gain: jnp.ndarray
    rec_lval: jnp.ndarray
    rec_rval: jnp.ndarray
    rec_lcnt: jnp.ndarray
    rec_rcnt: jnp.ndarray
    rec_internal_value: jnp.ndarray


def _store_split(st: _State, leaf, res) -> _State:
    """Write a SplitResult into the per-leaf best-split table."""
    return st._replace(
        bs_gain=st.bs_gain.at[leaf].set(res.gain),
        bs_feat=st.bs_feat.at[leaf].set(res.feature),
        bs_thr=st.bs_thr.at[leaf].set(res.threshold_bin),
        bs_dbz=st.bs_dbz.at[leaf].set(res.default_bin_for_zero),
        bs_left=st.bs_left.at[leaf].set(
            jnp.stack([res.left_sum_g, res.left_sum_h, res.left_cnt])
        ),
    )


@functools.partial(jax.jit, static_argnames=("params",))
def grow_tree(
    bins: jnp.ndarray,
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    select: jnp.ndarray,
    feature_mask: jnp.ndarray,
    meta: FeatureMeta,
    hyper: SplitHyper,
    params: GrowParams,
) -> GrowResult:
    """Grow one leaf-wise tree.  See module docstring."""
    n, f = bins.shape
    L = params.num_leaves
    B = params.num_bins

    def hist_of(sel):
        return build_histogram(bins, grad, hess, sel, B, params.row_block)

    def find_best(hist, sums, depth_ok):
        res = best_split_all_features(
            hist, sums[0], sums[1], sums[2], meta, hyper, feature_mask,
            use_missing=params.use_missing,
        )
        return res._replace(gain=jnp.where(depth_ok, res.gain, NEG_INF))

    # ---- root (BeforeTrain: LeafSplits::Init + root histogram)
    tg = jnp.sum(grad * select)
    th = jnp.sum(hess * select)
    tc = jnp.sum(select)
    root_hist = hist_of(select)
    root_sums = jnp.stack([tg, th, tc])
    root_depth_ok = (params.max_depth <= 0) or True  # root depth 0 < any max_depth >= 1
    root_res = best_split_all_features(
        root_hist, tg, th, tc, meta, hyper, feature_mask, use_missing=params.use_missing
    )

    zi = jnp.zeros((L,), jnp.int32)
    zf = jnp.zeros((L,))
    zr = jnp.zeros((L - 1,))
    zri = jnp.zeros((L - 1,), jnp.int32)
    st = _State(
        num_splits=jnp.int32(0),
        done=jnp.array(False),
        leaf_id=jnp.zeros((n,), jnp.int32),
        pool=jnp.zeros((L, f, B, 3)).at[0].set(root_hist),
        bs_gain=jnp.full((L,), NEG_INF),
        bs_feat=zi,
        bs_thr=zi,
        bs_dbz=zi,
        bs_left=jnp.zeros((L, 3)),
        leaf_sum=jnp.zeros((L, 3)).at[0].set(root_sums),
        leaf_value=zf,
        leaf_cnt=zf.at[0].set(tc),
        leaf_depth=zi,
        rec_leaf=zri, rec_feat=zri, rec_thr=zri, rec_dbz=zri,
        rec_gain=zr, rec_lval=zr, rec_rval=zr, rec_lcnt=zr, rec_rcnt=zr,
        rec_internal_value=zr,
    )
    st = _store_split(st, 0, root_res)
    del root_depth_ok

    def cond(st: _State):
        return (~st.done) & (st.num_splits < L - 1)

    def body(st: _State):
        best_leaf = jnp.argmax(st.bs_gain).astype(jnp.int32)
        gain = st.bs_gain[best_leaf]
        # "No further splits with positive gain" (serial_tree_learner.cpp:191)
        return jax.lax.cond(gain > 0.0, _split, lambda s: s._replace(done=True), st)

    def _split(st: _State):
        s = st.num_splits
        bl = jnp.argmax(st.bs_gain).astype(jnp.int32)
        right_leaf = (s + 1).astype(jnp.int32)

        feat = st.bs_feat[bl]
        thr = st.bs_thr[bl]
        dbz = st.bs_dbz[bl]
        gain = st.bs_gain[bl]
        left = st.bs_left[bl]  # (3,)
        totals = st.leaf_sum[bl]
        right = totals - left
        lg, lh, lc = left[0], left[1], left[2]
        rg, rh, rc = right[0], right[1], right[2]
        lval = leaf_output(lg, lh, hyper.lambda_l1, hyper.lambda_l2)
        rval = leaf_output(rg, rh, hyper.lambda_l1, hyper.lambda_l2)

        # ---- partition by predicate (DataPartition::Split + the
        # DefaultValueForZero bin remap, dense_bin.hpp:191-232)
        col = jnp.take(bins, feat, axis=1).astype(jnp.int32)
        zero_bin = meta.default_bin[feat]
        fval = jnp.where(col == zero_bin, dbz, col)
        is_cat = meta.is_categorical[feat]
        goes_left = jnp.where(is_cat, fval == thr, fval <= thr)
        in_leaf = st.leaf_id == bl
        leaf_id = jnp.where(in_leaf & ~goes_left, right_leaf, st.leaf_id)

        # ---- histograms: smaller child direct, larger by subtraction
        is_left_smaller = lc < rc
        smaller_id = jnp.where(is_left_smaller, bl, right_leaf)
        smaller_hist = hist_of(select * (leaf_id == smaller_id))
        larger_hist = st.pool[bl] - smaller_hist
        left_hist = jnp.where(is_left_smaller, smaller_hist, larger_hist)
        right_hist = jnp.where(is_left_smaller, larger_hist, smaller_hist)
        pool = st.pool.at[bl].set(left_hist).at[right_leaf].set(right_hist)

        # ---- children best splits (max_depth gate from BeforeFindBestSplit)
        child_depth = st.leaf_depth[bl] + 1
        depth_ok = (
            jnp.array(True)
            if params.max_depth <= 0
            else child_depth < params.max_depth
        )
        lres = find_best(left_hist, left, depth_ok)
        rres = find_best(right_hist, right, depth_ok)

        st = st._replace(
            num_splits=s + 1,
            leaf_id=leaf_id,
            pool=pool,
            leaf_sum=st.leaf_sum.at[bl].set(left).at[right_leaf].set(right),
            leaf_value=st.leaf_value.at[bl].set(lval).at[right_leaf].set(rval),
            leaf_cnt=st.leaf_cnt.at[bl].set(lc).at[right_leaf].set(rc),
            leaf_depth=st.leaf_depth.at[bl].set(child_depth).at[right_leaf].set(child_depth),
            rec_leaf=st.rec_leaf.at[s].set(bl),
            rec_feat=st.rec_feat.at[s].set(feat),
            rec_thr=st.rec_thr.at[s].set(thr),
            rec_dbz=st.rec_dbz.at[s].set(dbz),
            rec_gain=st.rec_gain.at[s].set(gain),
            rec_lval=st.rec_lval.at[s].set(lval),
            rec_rval=st.rec_rval.at[s].set(rval),
            rec_lcnt=st.rec_lcnt.at[s].set(lc),
            rec_rcnt=st.rec_rcnt.at[s].set(rc),
            rec_internal_value=st.rec_internal_value.at[s].set(st.leaf_value[bl]),
        )
        st = _store_split(st, bl, lres)
        st = _store_split(st, right_leaf, rres)
        return st

    st = jax.lax.while_loop(cond, body, st)
    return GrowResult(
        num_splits=st.num_splits,
        leaf_id=st.leaf_id,
        leaf_value=st.leaf_value,
        leaf_cnt=st.leaf_cnt,
        rec_leaf=st.rec_leaf,
        rec_feat=st.rec_feat,
        rec_thr=st.rec_thr,
        rec_dbz=st.rec_dbz,
        rec_gain=st.rec_gain,
        rec_lval=st.rec_lval,
        rec_rval=st.rec_rval,
        rec_lcnt=st.rec_lcnt,
        rec_rcnt=st.rec_rcnt,
        rec_internal_value=st.rec_internal_value,
    )
