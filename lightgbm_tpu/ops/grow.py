"""Leaf-wise tree growth under jit — counterpart of
SerialTreeLearner::Train (src/treelearner/serial_tree_learner.cpp:152-207)
plus DataPartition (data_partition.hpp) and the histogram pool, with the
reference's three parallel learners folded in as collective hooks:

- ``parallel="serial"``  — single-chip (SerialTreeLearner).
- ``parallel="data"``    — rows sharded over ``axis_name``; local
  histograms psum'd so every shard sees the global (F, B, 3) tensor and
  derives the identical split (DataParallelTreeLearner,
  data_parallel_tree_learner.cpp:148-248 — the ReduceScatter+Allreduce
  pair collapses to one XLA psum over ICI).
- ``parallel="feature"`` — rows replicated, feature *search* sharded by a
  per-shard feature mask; per-shard best splits argmax'd across the mesh
  (FeatureParallelTreeLearner, feature_parallel_tree_learner.cpp:31-79 —
  the SplitInfo::MaxReducer Allreduce becomes all_gather + argmax).
- ``parallel="voting"``  — rows sharded; each shard proposes its local
  top-2k features, a global vote picks top-k, and only those features'
  histograms are psum'd (VotingParallelTreeLearner,
  voting_parallel_tree_learner.cpp:54-56,164-350 — top-k histogram
  compression for bandwidth-bound meshes).

TPU-first redesign (vs the reference's index lists):
- The per-leaf index lists of DataPartition become one flat ``leaf_id[N]``
  vector updated by a predicate on the split feature's bin column
  (partition-by-predicate: O(N) per split, no index shuffling, static
  shapes).
- The LRU HistogramPool becomes a dense ``(num_leaves, F, B, 3)`` pool —
  every active leaf keeps its histogram so the subtraction trick
  (larger child = parent - smaller) is one tensor subtract
  (serial_tree_learner.cpp:484-489).
- The best-first loop is a ``lax.while_loop`` whose state carries the
  per-leaf best-split table (best_split_per_leaf_); each iteration splits
  the argmax-gain leaf and recomputes best splits only for the two
  children, exactly like the reference.

Everything is static-shaped: one XLA compile per
(N, F, B, num_leaves) configuration, reused across all boosting
iterations.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..tree.strategy import DEFAULT_STRATEGY, TreeStrategy
from .histogram import ROW_BLOCK, build_histogram
from .qhist import QUANT_BITS, dequantize_hist, dequantize_sums
from .split import (
    NEG_INF,
    FeatureMeta,
    SplitHyper,
    best_split_per_feature,
    finalize_split,
    leaf_output,
)


class GrowParams(NamedTuple):
    """Static (compile-time) growth parameters."""

    num_leaves: int
    num_bins: int  # padded B
    max_depth: int = -1
    use_missing: bool = True
    row_block: int = ROW_BLOCK
    parallel: str = "serial"  # serial | data | feature | voting
    axis_name: str = ""  # mesh axis name for the collectives
    top_k: int = 20  # voting: top-k voted features (config top_k)
    num_machines: int = 1  # voting: local-constraint scaling divisor
    compact: bool = True  # tiered leaf-row compaction (see _tiers)
    # quantized training (ops/qhist.py): int16 grad/hess levels in, int32
    # histogram pool, dequantization at split-scan time only
    quantized: bool = False
    quant_bits: int = QUANT_BITS
    quant_seed: int = 0  # stochastic-rounding key base (config seed)
    # composable trainer core (tree/strategy.py, docs/TREES.md): the
    # strategy rides the static params so plug-ins (monotone directions,
    # leaf-fit kind) are compile-time — the default strategy compiles
    # the exact pre-strategy graph
    strategy: TreeStrategy = DEFAULT_STRATEGY


# Smallest compaction tier.  Below ~4x this, the masked full-scan is
# cheaper than the gather choreography.
TIER_MIN = 8192


def _tiers(n: int, include_full: bool = False):
    """Static power-of-two buffer sizes N/2, N/4, ... >= TIER_MIN.

    The smaller child of any split has at most half its parent's rows, so
    a leaf with cnt rows fits the smallest tier >= cnt; `lax.switch` picks
    the branch at runtime.  This is the in-program counterpart of the
    reference's per-leaf index lists (DataPartition) — O(bucket(N_leaf))
    histogram work per split instead of O(N), with every branch statically
    shaped so the whole tree still grows inside one XLA program.

    ``include_full`` adds a full-size bucket for the row-sharded modes:
    there "smaller" is decided by GLOBAL counts, and the globally-smaller
    child may still own every row of one shard."""
    npow = 1
    while npow < n:
        npow *= 2
    out = [npow] if include_full else []
    s = npow // 2
    while s >= TIER_MIN:
        out.append(s)
        s //= 2
    return out


class GrowResult(NamedTuple):
    """Arrays describing the grown tree; host code turns this into a Tree
    model (model/tree.py).  Record index s = s-th split."""

    num_splits: jnp.ndarray  # scalar int32; num_leaves = num_splits + 1
    leaf_id: jnp.ndarray  # (N,) int32 final leaf of every row
    leaf_value: jnp.ndarray  # (L,) raw (pre-shrinkage) outputs
    leaf_cnt: jnp.ndarray  # (L,) f32
    rec_leaf: jnp.ndarray  # (L-1,) int32 leaf index that was split
    rec_feat: jnp.ndarray  # (L-1,) int32 inner feature
    rec_thr: jnp.ndarray  # (L-1,) int32 threshold bin
    rec_dbz: jnp.ndarray  # (L-1,) int32 default_bin_for_zero
    rec_gain: jnp.ndarray  # (L-1,) f32 split gain
    rec_lval: jnp.ndarray  # (L-1,) f32 left child output
    rec_rval: jnp.ndarray  # (L-1,) f32 right child output
    rec_lcnt: jnp.ndarray  # (L-1,) f32
    rec_rcnt: jnp.ndarray  # (L-1,) f32
    rec_internal_value: jnp.ndarray  # (L-1,) f32 parent leaf value


class _State(NamedTuple):
    num_splits: jnp.ndarray
    done: jnp.ndarray
    leaf_id: jnp.ndarray
    pool: jnp.ndarray  # (L, F, B, 3) — global hist (serial/data/feature),
    # LOCAL hist for voting (reduction deferred to the vote)
    # best_split_per_leaf_ table
    bs_gain: jnp.ndarray  # (L,)
    bs_feat: jnp.ndarray
    bs_thr: jnp.ndarray
    bs_dbz: jnp.ndarray
    bs_left: jnp.ndarray  # (L, 3) left (sum_g, sum_h, cnt)
    # per-leaf totals & bookkeeping (GLOBAL sums in all modes)
    leaf_sum: jnp.ndarray  # (L, 3)
    leaf_value: jnp.ndarray  # (L,)
    leaf_cnt: jnp.ndarray  # (L,)
    leaf_depth: jnp.ndarray  # (L,)
    leaf_rows: jnp.ndarray  # (L,) int32 LOCAL row count (tier choice)
    # split records
    rec_leaf: jnp.ndarray
    rec_feat: jnp.ndarray
    rec_thr: jnp.ndarray
    rec_dbz: jnp.ndarray
    rec_gain: jnp.ndarray
    rec_lval: jnp.ndarray
    rec_rval: jnp.ndarray
    rec_lcnt: jnp.ndarray
    rec_rcnt: jnp.ndarray
    rec_internal_value: jnp.ndarray
    # monotone-constraint output bounds per leaf (None when the
    # strategy is unconstrained: None is an empty pytree, so the
    # disabled while_loop state — and graph — is unchanged)
    leaf_lo: jnp.ndarray = None
    leaf_hi: jnp.ndarray = None


def _store_split(st: _State, leaf, res) -> _State:
    """Write a SplitResult into the per-leaf best-split table."""
    return st._replace(
        bs_gain=st.bs_gain.at[leaf].set(res.gain),
        bs_feat=st.bs_feat.at[leaf].set(res.feature),
        bs_thr=st.bs_thr.at[leaf].set(res.threshold_bin),
        bs_dbz=st.bs_dbz.at[leaf].set(res.default_bin_for_zero),
        bs_left=st.bs_left.at[leaf].set(
            jnp.stack([res.left_sum_g, res.left_sum_h, res.left_cnt])
        ),
    )


@functools.partial(jax.jit, static_argnames=("params",))
def grow_tree(
    bins: jnp.ndarray,
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    select: jnp.ndarray,
    feature_mask: jnp.ndarray,
    meta: FeatureMeta,
    hyper: SplitHyper,
    params: GrowParams,
    qscale: jnp.ndarray = None,
) -> GrowResult:
    """Grow one leaf-wise tree.  See module docstring.

    Under a parallel mode this must be called inside ``shard_map`` over a
    mesh axis named ``params.axis_name`` (parallel/learner.py does this);
    ``bins``/``grad``/``hess``/``select`` are then the per-shard blocks.

    Quantized training: when ``grad``/``hess`` arrive as int16 levels
    (ops/qhist.quantize_rows), the whole histogram pool switches to
    exact int32 accumulation — the subtraction trick becomes an integer
    identity and psum order stops mattering — and ``qscale`` (the (2,)
    global scales) dequantizes once, at split-scan time.
    """
    n, f = bins.shape
    L = params.num_leaves
    B = params.num_bins
    mode = params.parallel
    ax = params.axis_name
    # monotone plug-in (tree/strategy.py): the direction tuple is part
    # of the static params, so the unconstrained default bakes NOTHING
    # into the graph (mono stays None and every constraint branch below
    # is dead Python, not masked XLA)
    mono_t = params.strategy.split_gain.monotone
    use_mono = any(c != 0 for c in mono_t)
    if use_mono and len(mono_t) != f:
        raise ValueError(
            f"monotone direction vector has {len(mono_t)} entries for "
            f"{f} features")
    mono = jnp.asarray(mono_t, jnp.int32) if use_mono else None
    quantized = jnp.issubdtype(grad.dtype, jnp.integer)
    if quantized and qscale is None:
        raise ValueError("integer grad/hess require the qscale argument")
    tiers = (
        _tiers(n, include_full=params.parallel in ("data", "voting"))
        if params.compact and not quantized
        # the compaction gather bitcasts f32 value columns into int32
        # words — meaningless for int16 levels, and the masked full scan
        # keeps quantized accumulation exact; so quantized runs un-tiered
        else []
    )

    if tiers:
        # Random row access on TPU is latency-bound (~tens of M rows/s),
        # so the compaction gather must touch each row ONCE: bins are
        # byte-packed into int32 words and concatenated with the bitcast
        # grad/hess/select columns — one (S, W) gather per histogram
        # instead of four.  (The TPU analogue of the reference's 4-bit
        # packed Dense4bitsBin, dense_nbits_bin.hpp, generalized to the
        # gather path.)
        per = 4 if bins.dtype == jnp.uint8 else 2
        bits = 8 if per == 4 else 16
        lanes = -(-f // per)
        pad_f = lanes * per - f
        bb = jnp.pad(bins, ((0, 0), (0, pad_f))).astype(jnp.int32)
        bb = bb.reshape(n, lanes, per)
        shifts = (jnp.arange(per) * bits).astype(jnp.int32)
        packed = jnp.sum(bb << shifts[None, None, :], axis=2, dtype=jnp.int32)
        comb = jnp.concatenate(
            [
                packed,
                jax.lax.bitcast_convert_type(grad, jnp.int32)[:, None],
                jax.lax.bitcast_convert_type(hess, jnp.int32)[:, None],
                jax.lax.bitcast_convert_type(select, jnp.int32)[:, None],
            ],
            axis=1,
        )
        # dummy row n absorbs the compaction buffers' padding gathers
        comb_p = jnp.concatenate([comb, jnp.zeros((1, lanes + 3), jnp.int32)], 0)
        unpack_mask = jnp.int32((1 << bits) - 1)

    def _reduce_hist(h):
        if mode == "data":
            h = jax.lax.psum(h, ax)
        # voting keeps LOCAL histograms in the pool; serial/feature are
        # already global (feature mode replicates rows)
        return h

    def hist_full(sel):
        return _reduce_hist(build_histogram(bins, grad, hess, sel, B, params.row_block))

    def hist_leaf(leaf_mask, row_cnt):
        """Histogram of one leaf's rows.  With tiers: compact the leaf's
        rows into the smallest static power-of-two buffer that fits
        (lax.switch picks the branch), so work is O(bucket(N_leaf) * F * B)
        instead of O(N * F * B) — the in-program DataPartition."""
        if not tiers:
            return hist_full(select * leaf_mask.astype(select.dtype))

        def make_branch(S):
            def br(mask):
                rows = jnp.nonzero(mask, size=S, fill_value=n)[0]
                cm = comb_p[rows]  # (S, lanes+3): the single gather
                words = cm[:, :lanes, None] >> shifts[None, None, :]
                sbins = (words & unpack_mask).reshape(S, lanes * per)[:, :f]
                sgrad = jax.lax.bitcast_convert_type(cm[:, lanes], jnp.float32)
                shess = jax.lax.bitcast_convert_type(cm[:, lanes + 1], jnp.float32)
                ssel = jax.lax.bitcast_convert_type(cm[:, lanes + 2], jnp.float32)
                return build_histogram(
                    sbins, sgrad, shess, ssel, B, min(S, params.row_block)
                )
            return br

        tiers_arr = jnp.asarray(tiers)  # descending sizes
        fits = (tiers_arr >= row_cnt).astype(jnp.int32)
        idx = jnp.clip(jnp.sum(fits) - 1, 0, len(tiers) - 1)
        h = jax.lax.switch(idx, [make_branch(S) for S in tiers], leaf_mask)
        return _reduce_hist(h)

    def global_sums(tg, th, tc):
        if mode in ("data", "voting"):
            tg = jax.lax.psum(tg, ax)
            th = jax.lax.psum(th, ax)
            tc = jax.lax.psum(tc, ax)
        return tg, th, tc

    def find_best(hist, sums, depth_ok, lo=None, hi=None):
        """hist: pool entry (global for serial/data/feature, local for
        voting); sums: GLOBAL leaf totals; lo/hi: the leaf's monotone
        output bounds (None when unconstrained)."""
        sg, sh, sc = sums[0], sums[1], sums[2]
        if mode == "voting":
            # quantized: ballots are cast from the dequantized LOCAL
            # hist; the elected columns are psum'd in exact int32 FIRST
            # and dequantized once after the reduction
            lhist = dequantize_hist(hist, qscale) if quantized else hist
            # 1. local proposals from LOCAL hist with /num_machines
            #    constraints (voting_parallel_tree_learner.cpp:54-56)
            local_tot = jnp.sum(lhist[0], axis=0)  # (3,): identical per f
            local_hyper = hyper._replace(
                min_data_in_leaf=hyper.min_data_in_leaf / params.num_machines,
                min_sum_hessian_in_leaf=hyper.min_sum_hessian_in_leaf
                / params.num_machines,
            )
            lg_f, _, _, _ = best_split_per_feature(
                lhist, local_tot[0], local_tot[1], local_tot[2],
                meta, local_hyper, feature_mask, params.use_missing,
                monotone=mono, leaf_lo=lo, leaf_hi=hi,
            )
            k2 = min(2 * params.top_k, f)
            _, top2k = jax.lax.top_k(lg_f, k2)
            # 2. global vote (GlobalVoting, :164-195): count proposals
            votes = jnp.zeros((f,), jnp.float32).at[top2k].add(1.0)
            votes = jax.lax.psum(votes, ax)
            # stable tie-break toward lower feature index
            k1 = min(params.top_k, f)
            _, voted = jax.lax.top_k(votes - jnp.arange(f) * 1e-6, k1)
            voted_mask = jnp.zeros((f,), jnp.float32).at[voted].set(1.0)
            # 3. reduce only the voted features' histograms
            #    (CopyLocalHistogram + ReduceScatter, :196-350)
            if quantized:
                voted_i = voted_mask.astype(jnp.int32)
                hist_voted = dequantize_hist(
                    jax.lax.psum(hist * voted_i[:, None, None], ax), qscale
                )
            else:
                hist_voted = jax.lax.psum(hist * voted_mask[:, None, None], ax)
            gain_f, thr_f, dbz_f, left_f = best_split_per_feature(
                hist_voted, sg, sh, sc, meta, hyper,
                feature_mask * voted_mask, params.use_missing,
                monotone=mono, leaf_lo=lo, leaf_hi=hi,
            )
            res = finalize_split(gain_f, thr_f, dbz_f, left_f, sg, sh, sc,
                                 hyper, leaf_lo=lo, leaf_hi=hi)
        else:
            if quantized:
                # serial/feature: global int hist; data: already int-psum'd
                # in _reduce_hist — either way one dequantization here
                hist = dequantize_hist(hist, qscale)
            gain_f, thr_f, dbz_f, left_f = best_split_per_feature(
                hist, sg, sh, sc, meta, hyper, feature_mask,
                params.use_missing, monotone=mono, leaf_lo=lo, leaf_hi=hi,
            )
            res = finalize_split(gain_f, thr_f, dbz_f, left_f, sg, sh, sc,
                                 hyper, leaf_lo=lo, leaf_hi=hi)
            if mode == "feature":
                # global best across feature shards: all_gather the scalar
                # SplitInfo and take the max-gain shard (ties -> lowest
                # shard, matching lowest feature index under contiguous
                # feature sharding) — SplitInfo::MaxReducer Allreduce
                all_res = jax.lax.all_gather(res, ax)
                i = jnp.argmax(all_res.gain)
                res = jax.tree_util.tree_map(lambda x: x[i], all_res)
        return res._replace(gain=jnp.where(depth_ok, res.gain, NEG_INF))

    # ---- root (BeforeTrain: LeafSplits::Init + root histogram)
    if quantized:
        # exact integer node totals: the int32 psum is order-invariant,
        # so every shard count yields the identical root sums
        s16 = select.astype(jnp.int16)
        tgq = jnp.sum(grad * s16, dtype=jnp.int32)
        thq = jnp.sum(hess * s16, dtype=jnp.int32)
        tcq = jnp.sum(s16, dtype=jnp.int32)
        tgq, thq, tcq = global_sums(tgq, thq, tcq)
        root_sums = dequantize_sums(jnp.stack([tgq, thq, tcq]), qscale)
        tc = root_sums[2]
    else:
        tg = jnp.sum(grad * select)
        th = jnp.sum(hess * select)
        tc = jnp.sum(select)
        tg, th, tc = global_sums(tg, th, tc)
        root_sums = jnp.stack([tg, th, tc])
    root_hist = hist_full(select)
    if use_mono:
        root_lo = jnp.float32(NEG_INF)
        root_hi = jnp.float32(float("inf"))
        root_res = find_best(root_hist, root_sums, jnp.array(True),
                             root_lo, root_hi)
    else:
        root_res = find_best(root_hist, root_sums, jnp.array(True))

    zi = jnp.zeros((L,), jnp.int32)
    zf = jnp.zeros((L,))
    zr = jnp.zeros((L - 1,))
    zri = jnp.zeros((L - 1,), jnp.int32)
    st = _State(
        num_splits=jnp.int32(0),
        done=jnp.array(False),
        leaf_id=jnp.zeros((n,), jnp.int32),
        pool=jnp.zeros((L, f, B, 3), root_hist.dtype).at[0].set(root_hist),
        bs_gain=jnp.full((L,), NEG_INF),
        bs_feat=zi,
        bs_thr=zi,
        bs_dbz=zi,
        bs_left=jnp.zeros((L, 3)),
        leaf_sum=jnp.zeros((L, 3)).at[0].set(root_sums),
        leaf_value=zf,
        leaf_cnt=zf.at[0].set(tc),
        leaf_depth=zi,
        leaf_rows=zi.at[0].set(n),
        rec_leaf=zri, rec_feat=zri, rec_thr=zri, rec_dbz=zri,
        rec_gain=zr, rec_lval=zr, rec_rval=zr, rec_lcnt=zr, rec_rcnt=zr,
        rec_internal_value=zr,
        leaf_lo=jnp.full((L,), NEG_INF) if use_mono else None,
        leaf_hi=jnp.full((L,), float("inf")) if use_mono else None,
    )
    st = _store_split(st, 0, root_res)

    def cond(st: _State):
        return (~st.done) & (st.num_splits < L - 1)

    def body(st: _State):
        best_leaf = jnp.argmax(st.bs_gain).astype(jnp.int32)
        gain = st.bs_gain[best_leaf]
        # "No further splits with positive gain" (serial_tree_learner.cpp:191)
        return jax.lax.cond(gain > 0.0, _split, lambda s: s._replace(done=True), st)

    def _split(st: _State):
        s = st.num_splits
        bl = jnp.argmax(st.bs_gain).astype(jnp.int32)
        right_leaf = (s + 1).astype(jnp.int32)

        feat = st.bs_feat[bl]
        thr = st.bs_thr[bl]
        dbz = st.bs_dbz[bl]
        gain = st.bs_gain[bl]
        left = st.bs_left[bl]  # (3,) GLOBAL left sums
        totals = st.leaf_sum[bl]
        right = totals - left
        lg, lh, lc = left[0], left[1], left[2]
        rg, rh, rc = right[0], right[1], right[2]
        lval = leaf_output(lg, lh, hyper.lambda_l1, hyper.lambda_l2)
        rval = leaf_output(rg, rh, hyper.lambda_l1, hyper.lambda_l2)
        if use_mono:
            # clip the stored outputs to the parent's bounds, then
            # tighten the children's bounds at the mid-point when the
            # split feature is constrained (BasicLeafConstraints)
            plo, phi = st.leaf_lo[bl], st.leaf_hi[bl]
            lval = jnp.clip(lval, plo, phi)
            rval = jnp.clip(rval, plo, phi)
            cdir = mono[st.bs_feat[bl]]
            mid = (lval + rval) * 0.5
            child_lhi = jnp.where(cdir > 0, mid, phi)
            child_llo = jnp.where(cdir < 0, mid, plo)
            child_rlo = jnp.where(cdir > 0, mid, plo)
            child_rhi = jnp.where(cdir < 0, mid, phi)

        # ---- partition by predicate (DataPartition::Split + the
        # DefaultValueForZero bin remap, dense_bin.hpp:191-232)
        col = jnp.take(bins, feat, axis=1).astype(jnp.int32)
        zero_bin = meta.default_bin[feat]
        fval = jnp.where(col == zero_bin, dbz, col)
        is_cat = meta.is_categorical[feat]
        goes_left = jnp.where(is_cat, fval == thr, fval <= thr)
        in_leaf = st.leaf_id == bl
        leaf_id = jnp.where(in_leaf & ~goes_left, right_leaf, st.leaf_id)

        # ---- histograms: smaller child direct, larger by subtraction.
        # "smaller" is by row count (not selected count) so the compaction
        # tier always fits the computed child.  Row-sharded modes must
        # agree GLOBALLY on which child is computed — the psum'd histogram
        # would otherwise mix one shard's left rows with another's right.
        n_rows_left = jnp.sum((in_leaf & goes_left).astype(jnp.int32))
        n_rows_right = st.leaf_rows[bl] - n_rows_left
        if mode in ("data", "voting"):
            g_left = jax.lax.psum(n_rows_left, ax)
            g_right = jax.lax.psum(n_rows_right, ax)
        else:
            g_left, g_right = n_rows_left, n_rows_right
        is_left_smaller = g_left < g_right
        smaller_id = jnp.where(is_left_smaller, bl, right_leaf)
        # tier choice uses the LOCAL row count of the chosen child
        smaller_rows = jnp.where(is_left_smaller, n_rows_left, n_rows_right)
        smaller_hist = hist_leaf(leaf_id == smaller_id, smaller_rows)
        larger_hist = st.pool[bl] - smaller_hist
        left_hist = jnp.where(is_left_smaller, smaller_hist, larger_hist)
        right_hist = jnp.where(is_left_smaller, larger_hist, smaller_hist)
        pool = st.pool.at[bl].set(left_hist).at[right_leaf].set(right_hist)

        # ---- children best splits (max_depth gate from BeforeFindBestSplit)
        child_depth = st.leaf_depth[bl] + 1
        depth_ok = (
            jnp.array(True)
            if params.max_depth <= 0
            else child_depth < params.max_depth
        )
        if use_mono:
            lres = find_best(left_hist, left, depth_ok,
                             child_llo, child_lhi)
            rres = find_best(right_hist, right, depth_ok,
                             child_rlo, child_rhi)
            st = st._replace(
                leaf_lo=st.leaf_lo.at[bl].set(child_llo)
                .at[right_leaf].set(child_rlo),
                leaf_hi=st.leaf_hi.at[bl].set(child_lhi)
                .at[right_leaf].set(child_rhi),
            )
        else:
            lres = find_best(left_hist, left, depth_ok)
            rres = find_best(right_hist, right, depth_ok)

        st = st._replace(
            num_splits=s + 1,
            leaf_id=leaf_id,
            pool=pool,
            leaf_sum=st.leaf_sum.at[bl].set(left).at[right_leaf].set(right),
            leaf_value=st.leaf_value.at[bl].set(lval).at[right_leaf].set(rval),
            leaf_cnt=st.leaf_cnt.at[bl].set(lc).at[right_leaf].set(rc),
            leaf_depth=st.leaf_depth.at[bl].set(child_depth).at[right_leaf].set(child_depth),
            leaf_rows=st.leaf_rows.at[bl].set(n_rows_left).at[right_leaf].set(n_rows_right),
            rec_leaf=st.rec_leaf.at[s].set(bl),
            rec_feat=st.rec_feat.at[s].set(feat),
            rec_thr=st.rec_thr.at[s].set(thr),
            rec_dbz=st.rec_dbz.at[s].set(dbz),
            rec_gain=st.rec_gain.at[s].set(gain),
            rec_lval=st.rec_lval.at[s].set(lval),
            rec_rval=st.rec_rval.at[s].set(rval),
            rec_lcnt=st.rec_lcnt.at[s].set(lc),
            rec_rcnt=st.rec_rcnt.at[s].set(rc),
            rec_internal_value=st.rec_internal_value.at[s].set(st.leaf_value[bl]),
        )
        st = _store_split(st, bl, lres)
        st = _store_split(st, right_leaf, rres)
        return st

    st = jax.lax.while_loop(cond, body, st)
    return GrowResult(
        num_splits=st.num_splits,
        leaf_id=st.leaf_id,
        leaf_value=st.leaf_value,
        leaf_cnt=st.leaf_cnt,
        rec_leaf=st.rec_leaf,
        rec_feat=st.rec_feat,
        rec_thr=st.rec_thr,
        rec_dbz=st.rec_dbz,
        rec_gain=st.rec_gain,
        rec_lval=st.rec_lval,
        rec_rval=st.rec_rval,
        rec_lcnt=st.rec_lcnt,
        rec_rcnt=st.rec_rcnt,
        rec_internal_value=st.rec_internal_value,
    )
