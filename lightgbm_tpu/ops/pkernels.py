"""Dynamic-segment Pallas kernels for the partitioned tree grower.

TPU-native counterpart of the reference's histogram kernels and data
partition (src/treelearner/ocl/histogram256.cl:345 per-workgroup
sub-histograms + reduction, host driver gpu_tree_learner.cpp:123-191;
src/treelearner/data_partition.hpp:94-150 ``Split``).

The training matrix ``P`` is one (C, N) int32 array whose rows are

    0..W-1      : packed bin words, 4 uint8 bins per int32 (W = ceil(F/4))
    W..WPAD-1   : padding (WPAD = W rounded up to 8 sublanes)
    WPAD + 0    : grad   (f32 bitcast)
    WPAD + 1    : hess   (f32 bitcast)
    WPAD + 2    : select (f32 bitcast; 0/1 bagging mask)
    WPAD + 3..  : score channel(s), label, row id, weight — an 8-aligned
                  "mutable band" so the in-place channel-update kernel can
                  DMA it as one aligned row block.

Rows are kept PHYSICALLY PARTITIONED by leaf: each leaf owns a
contiguous column range [start, start+cnt).  That gives the reference's
DataPartition asymptotics (O(N_leaf) per split, not O(N)) without any
gather — TPU gathers measure ~20 Mrow/s while streaming DMA + MXU runs
at GB/s.

Two hard-won backend facts shape this file (measured on v5e via the
tunneled runtime):
  1. ANY XLA-level write to the 64 MB packed matrix — even a one-element
     `.at[0,0].add(1)` on a donated loop carry — triggers a pathological
     whole-array copy costing 50-180 ms.  Only Pallas kernels with
     ``input_output_aliases`` mutate it truly in place.  The resolution
     is a carry-layout contract, not donation avoidance: the matrix
     travels the fused loop carry untouched by XLA ops (every mutation
     is an aliased Pallas pass; all scalar/per-leaf bookkeeping lives in
     SEPARATE small carry arrays), and the jitted kernel entry points
     here (``split_stream``/``level_stream``/``score_add``) carry
     ``donate_argnums=(0,)`` so standalone calls alias straight through
     instead of paying a defensive input copy.  ``update_channels`` /
     ``score_add`` stream only the 8-aligned mutable band for
     score/gradient maintenance — the bin words are never re-read or
     re-written by a pass that doesn't need them.
  2. The kernels are VPU-compute-bound, not HBM-bound: the (B, BLK)
     bin-equality one-hots and the (BLK, BLK) permutation one-hots cost
     ~1 us per 64 compares/lane-block, while the DMA itself is tens of
     GB/s.  So histogram work is fused INTO the partition pass
     (``split_stream``): the partition must stream the parent segment
     anyway, and adding both children's histograms only widens the MXU
     operand from 7 to 14 sublanes — free on a 128-wide systolic array.

``split_stream`` replaces the old partition + copy-back + child-histogram
trio with ONE pass: a two-ended in-place partition (blocks are consumed
from both ends of the segment so vacated space always precedes the write
frontiers — the protocol is simulated exhaustively in
tests/test_pgrow.py) that accumulates (Σg, Σh, Σsel) per (feature, bin)
for the left AND right children while each block is resident in VMEM.
It needs NO scratch copy of the matrix (the old design kept a second
full-size buffer: 670 MB at Higgs scale) and halves per-split traffic.

Why matmuls everywhere: Mosaic has no vector scatter/gather and no
cumsum, but the MXU is nearly free next to the VPU.  So
- cumsum(goes_left) = one dot with a triangular ones matrix,
- the in-block compaction is a one-hot permutation matmul applied to the
  block's four byte planes (integers 0..255 are exact in bf16, so the
  permutation is bit-exact on int32/f32 data),
- per-bin accumulation = dot of bf16 value rows with bin-equality
  one-hots (3-term hi/mid/lo value split keeps f32 fidelity),
exactly the trade SURVEY §7 prescribes (scatter -> one-hot matmul).

Within-leaf row ORDER is not preserved (the two-ended scheme interleaves
front and back blocks).  Nothing downstream depends on it: histograms,
leaf sums and segment score updates are permutation-invariant, and the
original row index travels in the ROWID channel for prediction/eval
unscrambling.  (The reference's DataPartition::Split is stable, but no
consumer of that stability exists there either — it falls out of its
per-thread buffer merge.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .histogram_pallas import tune_fchunk

BLK = 1024  # columns (data rows) per streamed chunk
_LANE = 128  # DMA lane-alignment quantum
_RING = 3  # read-buffer ring depth per stream end (max occupancy 2 + 1 inflight)


def num_words(num_features: int, bits: int = 8) -> int:
    return -(-num_features // (32 // bits))


class PLayout:
    """Channel-row indices inside the packed matrix.

    ``bits`` selects the bin word width: 8 (4 bins/int32) for max_bin up
    to 256, or 4 (8 bins/int32) when every column fits 16 bins — the TPU
    form of the reference's Dense4bitsBin (dense_nbits_bin.hpp:37),
    halving resident bin bytes and per-row stream traffic.

    The mutable rows (grad/hess/select/scores + label/rowid/weight) live
    in an 8-sublane-aligned band starting at WPAD so ``update_channels``
    can DMA-slice them (Mosaic requires row-slice shapes and offsets
    aligned to the (8, 128) tile)."""

    def __init__(self, num_features: int, num_score: int = 1, with_weight: bool = True,
                 bits: int = 8):
        self.F = num_features
        self.bits = bits
        self.per = 32 // bits
        self.W = num_words(num_features, bits)
        self.WPAD = -(-self.W // 8) * 8
        # K grad/hess row PAIRS (multiclass trains K trees per iteration
        # from K gradient planes computed once per iteration —
        # GBDT::Boosting, gbdt.cpp:692-700); K == 1 reproduces the
        # classic G/H/SEL/SCORE ordering exactly.
        K = num_score
        self.G = self.WPAD  # class-0 pair (g_row(0)/h_row(0))
        self.H = self.WPAD + 1
        self.SEL = self.WPAD + 2 * K
        self.SCORE = self.SEL + 1  # .. SCORE + num_score - 1
        self.num_score = num_score
        self.LABEL = self.SCORE + num_score
        self.ROWID = self.LABEL + 1
        self.WEIGHT = self.ROWID + 1 if with_weight else -1
        self.with_weight = with_weight
        band = 2 * K + 1 + num_score + 2 + (1 if with_weight else 0)
        self.BAND = -(-band // 8) * 8
        self.C = self.WPAD + self.BAND

    def g_row(self, k: int) -> int:
        return self.WPAD + 2 * k

    def h_row(self, k: int) -> int:
        return self.WPAD + 2 * k + 1

    def class_rows(self, k: int):
        """(g, h, sel) row triple for class k — static kernel param."""
        return (self.g_row(k), self.h_row(k), self.SEL)

    @property
    def rows(self):
        """(g, h, sel) row indices for class 0."""
        return (self.G, self.H, self.SEL)


def num_channels(num_features: int, num_score: int = 1, with_weight: bool = True,
                 bits: int = 8) -> int:
    return PLayout(num_features, num_score, with_weight, bits).C


def pack_matrix(bins: np.ndarray, layout: PLayout, label=None, weight=None,
                num_real=None) -> jnp.ndarray:
    """Build the (C, N + BLK) packed matrix from (N, F) uint8 bins.

    The BLK tail columns absorb block-granular DMA overruns.  grad/hess
    start at 0, select at 1, scores at 0; rowid is the original row
    index (prediction / eval unscrambling).  Rows >= ``num_real`` are
    shard-padding dummies: select stays 0 so they never enter a
    histogram (Metadata::CheckOrPartition's equal-shard padding)."""
    n, f = bins.shape
    assert f == layout.F
    assert bins.dtype == np.uint8, "partitioned path requires max_bin <= 256"
    assert int(bins.max(initial=0)) < (1 << layout.bits), (
        f"bin values exceed the {layout.bits}-bit word field"
    )
    nr = n if num_real is None else int(num_real)
    w, per, bits = layout.W, layout.per, layout.bits
    pad_f = w * per - f
    bb = np.pad(np.asarray(bins), ((0, 0), (0, pad_f))).astype(np.uint32)
    bb = bb.reshape(n, w, per)
    words = np.zeros((n, w), np.uint32)
    for k in range(per):
        words |= bb[:, :, k] << (bits * k)
    words = words.view(np.int32)
    P = np.zeros((layout.C, n + BLK), np.int32)
    P[:w, :n] = words.T
    one = np.float32(1.0).view(np.int32)
    P[layout.SEL, :nr] = one
    if label is not None:
        P[layout.LABEL, :n] = np.asarray(label, np.float32).view(np.int32)
    P[layout.ROWID, :n] = np.arange(n, dtype=np.int32)
    if layout.with_weight:
        wv = np.ones(n, np.float32) if weight is None else np.asarray(weight, np.float32)
        P[layout.WEIGHT, :n] = wv.view(np.int32)
    return jnp.asarray(P)


def pack_matrix_device(bins_dev, layout: PLayout, label=None, weight=None) -> jnp.ndarray:
    """pack_matrix built ON DEVICE from an already-transferred (N, F)
    uint8 bins array.  Host->device bandwidth through the tunneled TPU is
    ~10 MB/s, so shipping the 28 B/row bins once and deriving the packed
    matrix with XLA shifts beats shipping the 64 B/row matrix."""
    n, f = bins_dev.shape
    w, per, bits = layout.W, layout.per, layout.bits
    pad_f = w * per - f
    bb = jnp.pad(bins_dev.astype(jnp.int32), ((0, 0), (0, pad_f)))
    # mask defensively: an oversized bin value would OR into the next
    # feature's field (callers guarantee the bound; this keeps corruption
    # local to the offending feature instead of silent cross-talk)
    bb = bb & ((1 << bits) - 1)
    bb = bb.reshape(n, w, per)
    shifts = (jnp.arange(per, dtype=jnp.int32) * bits)[None, None, :]
    words = jnp.sum(bb << shifts, axis=2, dtype=jnp.int32)  # (N, W)
    one = np.float32(1.0).view(np.int32)

    def frow(x):
        return jax.lax.bitcast_convert_type(jnp.asarray(x, jnp.float32), jnp.int32)

    rows = [words.T]
    if layout.WPAD > w:
        rows.append(jnp.zeros((layout.WPAD - w, n), jnp.int32))
    rows.append(jnp.zeros((2 * layout.num_score, n), jnp.int32))  # g/h pairs
    rows.append(jnp.full((1, n), one, jnp.int32))  # sel
    rows.append(jnp.zeros((layout.num_score, n), jnp.int32))  # scores
    rows.append(frow(label if label is not None else np.zeros(n, np.float32))[None, :])
    rows.append(jnp.arange(n, dtype=jnp.int32)[None, :])  # rowid
    if layout.with_weight:
        wv = jnp.ones((n,), jnp.float32) if weight is None else jnp.asarray(weight, jnp.float32)
        rows.append(jax.lax.bitcast_convert_type(wv, jnp.int32)[None, :])
    p = jnp.concatenate(rows, axis=0)
    cpad = layout.C - p.shape[0]
    return jnp.pad(p, ((0, cpad), (0, BLK)))


def _planes(blk_i32, c):
    """(C, BLK) int32 -> (4C, BLK) bf16 byte planes (exact in bf16)."""
    ps = [(blk_i32 >> (8 * k)) & 255 for k in range(4)]
    return jnp.concatenate(ps, axis=0).astype(jnp.bfloat16)


def _unplanes(dots_f32, c):
    """(4C, BLK) f32 byte planes -> (C, BLK) int32 (exact repack)."""
    p = dots_f32.astype(jnp.int32)
    return (
        p[0 * c : 1 * c]
        | (p[1 * c : 2 * c] << 8)
        | (p[2 * c : 3 * c] << 16)
        | (p[3 * c : 4 * c] << 24)
    )


def _split3(x):
    """f32 -> 3 bf16 planes (hi, mid, lo): f32 fidelity at bf16 matmul
    speed; the dot's sublane dim pads to 128 so extra rows are free."""
    hi = x.astype(jnp.bfloat16)
    r1 = x - hi.astype(jnp.float32)
    mid = r1.astype(jnp.bfloat16)
    lo = (r1 - mid.astype(jnp.float32)).astype(jnp.bfloat16)
    return [hi, mid, lo]


def _hist_from_rows(out, num_features, num_bins, row0=0):
    """(Σ 3-term g, Σ 3-term h, cnt) rows -> (F, B, 3) histogram."""
    hist = jnp.stack(
        [
            out[row0 + 0] + (out[row0 + 1] + out[row0 + 2]),
            out[row0 + 3] + (out[row0 + 4] + out[row0 + 5]),
            out[row0 + 6],
        ],
        axis=1,
    )
    return hist.reshape(num_features, num_bins, 3)


# ======================================================================
# histogram kernel (root histogram / standalone segments)
# ======================================================================
def _hist_kernel(sref, p_any, o_ref, acc_ref, buf_ref, sem, *, nf, nb, rows, c, fchunk, bits):
    start = sref[0]
    cnt = sref[1]
    g_row, h_row, sel_row = rows
    base = pl.multiple_of((start // BLK) * BLK, _LANE)
    head = start - base
    nblk = (head + cnt + BLK - 1) // BLK
    acc_ref[:, :] = jnp.zeros_like(acc_ref)

    def get_dma(slot, j):
        return pltpu.make_async_copy(
            p_any.at[:, pl.ds(base + j * BLK, BLK)], buf_ref.at[slot], sem.at[slot]
        )

    get_dma(0, 0).start()

    iota_b = jax.lax.broadcasted_iota(jnp.int32, (nb, BLK), 0)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, BLK), 1)

    def body(j, _):
        slot = jax.lax.rem(j, 2)

        @pl.when(j + 1 < nblk)
        def _():
            get_dma(1 - slot, j + 1).start()

        get_dma(slot, j).wait()
        blk = buf_ref[slot]
        pos = lane + j * BLK
        valid = ((pos >= head) & (pos < head + cnt)).astype(jnp.float32)
        sel = pltpu.bitcast(blk[sel_row : sel_row + 1, :], jnp.float32) * valid
        g = pltpu.bitcast(blk[g_row : g_row + 1, :], jnp.float32) * sel
        h = pltpu.bitcast(blk[h_row : h_row + 1, :], jnp.float32) * sel

        vals = jnp.concatenate(
            _split3(g) + _split3(h) + [sel.astype(jnp.bfloat16)], axis=0
        )

        per = 32 // bits
        mask = (1 << bits) - 1
        for c0 in range(0, nf, fchunk):
            c1 = min(c0 + fchunk, nf)
            chunks = []
            for f in range(c0, c1):
                wd, p4 = divmod(f, per)
                byte = (blk[wd : wd + 1, :] >> (p4 * bits)) & mask
                chunks.append((byte == iota_b).astype(jnp.bfloat16))
            oh = jnp.concatenate(chunks, axis=0)
            # (7, BLK) x (F_c*B, BLK) -> (7, F_c*B): value rows on sublanes
            # so the accumulator/output is (8, F*B) — lane-major, which
            # copies out clean (an (F*B, 7) output pays a strided
            # VMEM->HBM copy measured at ~2 ms).
            acc_ref[0:7, c0 * nb : c1 * nb] += jax.lax.dot_general(
                vals, oh, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
        return 0

    jax.lax.fori_loop(0, nblk, body, 0)
    o_ref[:, :] = acc_ref[:, :]


@functools.partial(jax.jit, static_argnames=("num_features", "num_bins", "bits", "rows", "interpret"))
def hist_dyn(p, start, cnt, num_features, num_bins, bits=8, rows=None, interpret=False):
    """(F, B, 3) histogram of the leaf segment [start, start+cnt) of the
    packed matrix ``p`` — DenseBin::ConstructHistogram (dense_bin.hpp:66)
    over the leaf's contiguous rows, streamed at HBM bandwidth.  bits=4
    streams the Dense4bitsBin-packed form (8 bins per word).  ``rows``
    is the (g, h, sel) channel-row triple (PLayout.rows); defaults to the
    standard layout for ``num_features``."""
    if rows is None:
        wpad = -(-num_words(num_features, bits) // 8) * 8
        rows = (wpad, wpad + 1, wpad + 2)
    c = p.shape[0]
    fb = num_features * num_bins
    fchunk = tune_fchunk(num_features, num_bins)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, nf=num_features, nb=num_bins, rows=rows, c=c,
                          fchunk=fchunk, bits=bits),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((8, fb), jnp.float32),
                pltpu.VMEM((2, c, BLK), jnp.int32),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((8, fb), jnp.float32),
        interpret=interpret,
    )(jnp.stack([jnp.int32(start), jnp.int32(cnt)]), p)
    return _hist_from_rows(out, num_features, num_bins)


# ======================================================================
# update_and_root_hist: fused channel refresh + root histogram
# ======================================================================
def _upd_hist_kernel(sref, aux_any, p_any_in, p_any, o_ref, acc_ref, buf_ref, abuf,
                     stage, rsem, asem, wsem, sem_unused, *, nf, nb, rows, c,
                     fchunk, bits, grad_fn, lay_rows, use_sel, use_mul,
                     use_weight, n_delta, n_score, k_grad, with_hist=True):
    """One streaming pass over ALL rows: score += delta, (g, h) =
    grad_fn(score, label, weight), select = sel, block written back in
    place, AND the root (F, B, 3) histogram accumulated from the fresh
    values.  Structurally a copy of _hist_kernel (its DMA pattern
    measures at full HBM bandwidth) plus a _stream_flush write-back.

    ``lay_rows`` = (G, H, SEL, SCORE, LABEL, ROWID, WEIGHT) absolute row
    indices."""
    n = sref[0]
    g_row, h_row, sel_row = rows
    G_, H_, SEL_, SCORE_, LABEL_, ROWID_, WEIGHT_ = lay_rows
    nblk = (n + BLK - 1) // BLK
    if with_hist:
        acc_ref[:, :] = jnp.zeros_like(acc_ref)

    def get_dma(slot, j):
        return pltpu.make_async_copy(
            p_any.at[:, pl.ds(j * BLK, BLK)], buf_ref.at[slot], rsem.at[slot]
        )

    def get_aux(slot, j):
        return pltpu.make_async_copy(
            aux_any.at[:, pl.ds(j * BLK, BLK)], abuf.at[slot], asem.at[slot]
        )

    get_dma(0, 0).start()
    get_aux(0, 0).start()

    iota_b = jax.lax.broadcasted_iota(jnp.int32, (nb, BLK), 0)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, BLK), 1)

    def body(j, _):
        slot = jax.lax.rem(j, 2)

        @pl.when(j + 1 < nblk)
        def _():
            get_dma(1 - slot, j + 1).start()
            get_aux(1 - slot, j + 1).start()

        get_dma(slot, j).wait()
        get_aux(slot, j).wait()
        blk = buf_ref[slot]
        aux = abuf[slot]

        # ---- channel update (single-class contract: multiclass runs
        # update_multi_and_hists instead)
        scores = pltpu.bitcast(blk[SCORE_ : SCORE_ + 1, :], jnp.float32)
        if n_delta:
            scores = scores + aux[0:1, :]
        label = pltpu.bitcast(blk[LABEL_ : LABEL_ + 1, :], jnp.float32)
        weight = (
            pltpu.bitcast(blk[WEIGHT_ : WEIGHT_ + 1, :], jnp.float32)
            if use_weight else None
        )
        gv, hv = grad_fn(scores, label, weight)
        gv = gv.astype(jnp.float32)
        hv = hv.astype(jnp.float32)
        if use_mul:
            # GOSS: sampled-rest rows carry the (n-top_k)/other_k
            # gradient up-weighting (goss.hpp:112-117) — scales g/h but
            # NOT the select row, so histogram counts stay row counts
            mulv = aux[6:7, :]
            gv = gv * mulv
            hv = hv * mulv
        if use_sel:
            selv = aux[7:8, :]
        else:
            selv = pltpu.bitcast(blk[SEL_ : SEL_ + 1, :], jnp.float32)
        out = blk
        out = _setrow(out, G_, pltpu.bitcast(gv, jnp.int32))
        out = _setrow(out, H_, pltpu.bitcast(hv, jnp.int32))
        if use_sel:
            out = _setrow(out, SEL_, pltpu.bitcast(selv, jnp.int32))
        if n_delta:
            out = _setrow(out, SCORE_, pltpu.bitcast(scores, jnp.int32))
        _stream_flush(stage, wsem, p_any, out, j, j * BLK)

        # ---- root histogram from the fresh values (skipped entirely for
        # histogram-free passes — GOSS's gradient-prep pass used to pay
        # the full F*B one-hot/matmul accumulation only to discard it)
        if with_hist:
            pos = lane + j * BLK
            valid = (pos < n).astype(jnp.float32)
            sel = selv * valid
            g = gv * sel
            h = hv * sel
            vals = jnp.concatenate(
                _split3(g) + _split3(h) + [sel.astype(jnp.bfloat16)], axis=0
            )
            per = 32 // bits
            mask = (1 << bits) - 1
            for c0 in range(0, nf, fchunk):
                c1 = min(c0 + fchunk, nf)
                chunks = []
                for f in range(c0, c1):
                    wd, p4 = divmod(f, per)
                    byte = (blk[wd : wd + 1, :] >> (p4 * bits)) & mask
                    chunks.append((byte == iota_b).astype(jnp.bfloat16))
                oh = jnp.concatenate(chunks, axis=0)
                acc_ref[0:7, c0 * nb : c1 * nb] += jax.lax.dot_general(
                    vals, oh, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
                )
        return 0

    jax.lax.fori_loop(0, nblk, body, 0)
    _stream_drain(stage, wsem, nblk)
    if with_hist:
        o_ref[:, :] = acc_ref[:, :]
    else:
        o_ref[:, :] = jnp.zeros_like(o_ref)


def update_and_root_hist(p, layout: PLayout, grad_fn, delta=None, sel=None,
                         mul=None, *, num_rows, num_features, num_bins,
                         bits=8, rows=None, with_hist: bool = True,
                         interpret: bool = False):
    """Fused per-iteration channel maintenance + root histogram: ONE
    streaming pass writes score += delta, fresh (g, h), bagging select —
    in place via input_output_aliases — and returns the root (F, B, 3)
    histogram of the fresh values (the fused trainer starts every tree
    with exactly this pair).  GBDT::Boosting + Bagging + the root
    ConstructHistogram in one pass (gbdt.cpp:692-700, 275-334).

    ``with_hist=False`` runs the identical channel update (bit-for-bit
    the same matrix writes) with the histogram accumulation compiled
    out and returns (p, None) — the GOSS gradient-prep pass, which used
    to pay the full F*B one-hot/matmul work only to discard it."""
    if rows is None:
        rows = layout.rows
    ntot = p.shape[1]
    c = p.shape[0]
    fb = num_features * num_bins
    fchunk = tune_fchunk(num_features, num_bins)

    def fit(v):
        v = jnp.asarray(v, jnp.float32)
        pad = ntot - v.shape[0]
        return jnp.concatenate([v, jnp.zeros((pad,), jnp.float32)]) if pad else v

    zero = jnp.zeros((ntot,), jnp.float32)
    use_sel = sel is not None
    # aux rows 0..K-1: pending per-class score deltas; row 7: bagging
    # select.  K <= 7 is enforced by the trainer's eligibility gate.
    if delta is None:
        n_delta = 0
        drows = []
    else:
        delta = jnp.asarray(delta, jnp.float32)
        if delta.ndim > 1:
            delta = delta[0]
        n_delta = 1
        drows = [fit(delta)]
    use_mul = mul is not None
    rows8 = (drows + [zero] * (6 - len(drows))
             + [fit(mul) if use_mul else zero]
             + [fit(sel) if use_sel else zero])
    aux = jnp.stack(rows8)
    lay_rows = (layout.G, layout.H, layout.SEL, layout.SCORE, layout.LABEL,
                layout.ROWID, layout.WEIGHT)
    kern = functools.partial(
        _upd_hist_kernel, nf=num_features, nb=num_bins, rows=rows, c=c,
        fchunk=fchunk, bits=bits, grad_fn=grad_fn, lay_rows=lay_rows,
        use_sel=use_sel, use_mul=use_mul, use_weight=layout.with_weight,
        n_delta=n_delta, n_score=layout.num_score, k_grad=0,
        with_hist=with_hist,
    )
    p, out = pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),  # aux
                pl.BlockSpec(memory_space=pl.ANY),  # P (alias)
            ],
            out_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ],
            scratch_shapes=[
                pltpu.VMEM((8, fb), jnp.float32),
                pltpu.VMEM((2, c, BLK), jnp.int32),
                pltpu.VMEM((2, 8, BLK), jnp.float32),
                pltpu.VMEM((2, c, BLK), jnp.int32),  # write stage
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA(()),
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct(p.shape, jnp.int32),
            jax.ShapeDtypeStruct((8, fb), jnp.float32),
        ),
        input_output_aliases={2: 0},
        interpret=interpret,
    )(jnp.stack([jnp.int32(num_rows)]), aux, p)
    if not with_hist:
        return p, None
    return p, _hist_from_rows(out, num_features, num_bins)


# ======================================================================
# update_multi_and_hists: K gradient planes + K root histograms, one pass
# ======================================================================
def _upd_multi_kernel(sref, aux_any, p_any_in, p_any, o_ref, acc_ref, buf_ref, abuf,
                      stage, rsem, asem, wsem, *, nf, nb, c, fchunk, bits,
                      grad_all_fn, lay, use_sel):
    """One streaming pass over ALL rows: (g_k, h_k) for EVERY class k from
    the score-channel snapshot (GBDT::Boosting computes all K gradient
    planes once per iteration, gbdt.cpp:692-700), bagging select, the
    block written back in place, and ALL K root histograms accumulated —
    the K value groups just widen the MXU operand (7K+... sublanes)."""
    n = sref[0]
    K = lay.num_score
    nblk = (n + BLK - 1) // BLK
    acc_ref[:, :] = jnp.zeros_like(acc_ref)

    def get_dma(slot, j):
        return pltpu.make_async_copy(
            p_any.at[:, pl.ds(j * BLK, BLK)], buf_ref.at[slot], rsem.at[slot]
        )

    def get_aux(slot, j):
        return pltpu.make_async_copy(
            aux_any.at[:, pl.ds(j * BLK, BLK)], abuf.at[slot], asem.at[slot]
        )

    get_dma(0, 0).start()
    get_aux(0, 0).start()

    iota_b = jax.lax.broadcasted_iota(jnp.int32, (nb, BLK), 0)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, BLK), 1)

    def body(j, _):
        slot = jax.lax.rem(j, 2)

        @pl.when(j + 1 < nblk)
        def _():
            get_dma(1 - slot, j + 1).start()
            get_aux(1 - slot, j + 1).start()

        get_dma(slot, j).wait()
        get_aux(slot, j).wait()
        blk = buf_ref[slot]
        aux = abuf[slot]

        scores = pltpu.bitcast(blk[lay.SCORE : lay.SCORE + K, :], jnp.float32)
        label = pltpu.bitcast(blk[lay.LABEL : lay.LABEL + 1, :], jnp.float32)
        weight = (
            pltpu.bitcast(blk[lay.WEIGHT : lay.WEIGHT + 1, :], jnp.float32)
            if lay.with_weight else None
        )
        gv, hv = grad_all_fn(scores, label, weight)  # (K, BLK) each
        gv = gv.astype(jnp.float32)
        hv = hv.astype(jnp.float32)
        if use_sel:
            selv = aux[7:8, :]
        else:
            selv = pltpu.bitcast(blk[lay.SEL : lay.SEL + 1, :], jnp.float32)
        out = blk
        for k in range(K):
            out = _setrow(out, lay.g_row(k), pltpu.bitcast(gv[k : k + 1], jnp.int32))
            out = _setrow(out, lay.h_row(k), pltpu.bitcast(hv[k : k + 1], jnp.int32))
        if use_sel:
            out = _setrow(out, lay.SEL, pltpu.bitcast(selv, jnp.int32))
        _stream_flush(stage, wsem, p_any, out, j, j * BLK)

        # ---- K root histograms from the fresh values
        pos = lane + j * BLK
        valid = (pos < n).astype(jnp.float32)
        sel = selv * valid
        groups = []
        for k in range(K):
            groups += _split3(gv[k : k + 1] * sel) + _split3(hv[k : k + 1] * sel)
        groups.append(sel.astype(jnp.bfloat16))
        vals = jnp.concatenate(groups, axis=0)  # (6K + 1, BLK)
        per = 32 // bits
        mask = (1 << bits) - 1
        nv = 6 * K + 1
        for c0 in range(0, nf, fchunk):
            c1 = min(c0 + fchunk, nf)
            chunks = []
            for f in range(c0, c1):
                wd, p4 = divmod(f, per)
                byte = (blk[wd : wd + 1, :] >> (p4 * bits)) & mask
                chunks.append((byte == iota_b).astype(jnp.bfloat16))
            oh = jnp.concatenate(chunks, axis=0)
            acc_ref[0:nv, c0 * nb : c1 * nb] += jax.lax.dot_general(
                vals, oh, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
        return 0

    jax.lax.fori_loop(0, nblk, body, 0)
    _stream_drain(stage, wsem, nblk)
    o_ref[:, :] = acc_ref[:, :]


def update_multi_and_hists(p, layout: PLayout, grad_all_fn, sel=None,
                           *, num_rows, num_features, num_bins, bits=8,
                           interpret: bool = False):
    """Multiclass per-iteration channel maintenance: ALL K (g, h) planes
    written from the same score snapshot + K root histograms, one
    streaming pass.  Returns (p', [hist_k (F, B, 3) for k in range(K)])."""
    K = layout.num_score
    ntot = p.shape[1]
    c = p.shape[0]
    fb = num_features * num_bins
    fchunk = tune_fchunk(num_features, num_bins)
    nv = 6 * K + 1
    nvpad = -(-nv // 8) * 8

    def fit(v):
        v = jnp.asarray(v, jnp.float32)
        pad = ntot - v.shape[0]
        return jnp.concatenate([v, jnp.zeros((pad,), jnp.float32)]) if pad else v

    zero = jnp.zeros((ntot,), jnp.float32)
    use_sel = sel is not None
    aux = jnp.stack([zero] * 7 + [fit(sel) if use_sel else zero])
    kern = functools.partial(
        _upd_multi_kernel, nf=num_features, nb=num_bins, c=c, fchunk=fchunk,
        bits=bits, grad_all_fn=grad_all_fn, lay=layout, use_sel=use_sel,
    )
    p, out = pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ],
            scratch_shapes=[
                pltpu.VMEM((nvpad, fb), jnp.float32),
                pltpu.VMEM((2, c, BLK), jnp.int32),
                pltpu.VMEM((2, 8, BLK), jnp.float32),
                pltpu.VMEM((2, c, BLK), jnp.int32),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct(p.shape, jnp.int32),
            jax.ShapeDtypeStruct((nvpad, fb), jnp.float32),
        ),
        input_output_aliases={2: 0},
        interpret=interpret,
    )(jnp.stack([jnp.int32(num_rows)]), aux, p)
    cnt = out[6 * K]
    hists = []
    for k in range(K):
        g = out[6 * k + 0] + (out[6 * k + 1] + out[6 * k + 2])
        h = out[6 * k + 3] + (out[6 * k + 4] + out[6 * k + 5])
        hists.append(
            jnp.stack([g, h, cnt], axis=1).reshape(num_features, num_bins, 3)
        )
    return p, hists


# ======================================================================
# score_add: in-place score-row segment update (multiclass per-tree,
# chunk-end settle, traced score_update)
# ======================================================================
def _score_band_kernel(aux_any, p_in, p_any, buf, abuf, rsem, asem, wsem, *,
                       band0, bandn, nblk, score_off):
    """Band-streaming score update: score += delta touching ONLY the
    8-aligned mutable band (``update_channels``' ring pattern).  The old
    kernel streamed every matrix row — including the packed bin words —
    just to rewrite them unchanged; reading the band alone halves (or
    better) the traffic of every score-only pass and leaves the bin/rowid
    rows genuinely untouched ("read once per round")."""
    R, K = _URING, _UAHEAD

    def rd(j):
        sl = jax.lax.rem(j, R)
        return pltpu.make_async_copy(
            p_any.at[band0 : band0 + bandn, pl.ds(j * BLK, BLK)], buf.at[sl], rsem.at[sl]
        )

    def rda(j):
        sl = jax.lax.rem(j, R)
        return pltpu.make_async_copy(
            aux_any.at[:, pl.ds(j * BLK, BLK)], abuf.at[sl], asem.at[sl]
        )

    def wr(j):
        sl = jax.lax.rem(j, R)
        return pltpu.make_async_copy(
            buf.at[sl], p_any.at[band0 : band0 + bandn, pl.ds(j * BLK, BLK)], wsem.at[sl]
        )

    for k in range(min(K, nblk)):
        rd(k).start()
        rda(k).start()

    def body(j, _):
        sl = jax.lax.rem(j, R)
        rd(j).wait()
        rda(j).wait()
        blk = buf[sl]
        sc = pltpu.bitcast(blk[score_off : score_off + 1, :], jnp.float32)
        sc = sc + abuf[sl][0:1, :]
        buf[sl] = _setrow(blk, score_off, pltpu.bitcast(sc, jnp.int32))
        wr(j).start()

        @pl.when(j + K < nblk)
        def _():
            @pl.when(j + K - R >= 0)
            def _():
                wr(j + K - R).wait()

            rd(j + K).start()
            rda(j + K).start()

        return 0

    jax.lax.fori_loop(0, nblk, body, 0)
    for k in range(min(_URING, nblk)):
        wr(nblk - 1 - k).wait()


@functools.partial(jax.jit, static_argnames=("layout", "k", "num_rows", "interpret"),
                   donate_argnums=(0,))
def score_add(p, layout: PLayout, delta, k: int = 0, *, num_rows,
              interpret: bool = False):
    """score channel k += delta (N,) in place — the per-tree score update
    of the multiclass fused loop (applied IMMEDIATELY after each tree,
    while the delta's row layout is still current) and the chunk-end
    pending-delta settle.  Streams only the mutable band, not the full
    matrix; donated at the jit level so standalone calls never pay a
    defensive whole-matrix copy."""
    ntot = p.shape[1]
    v = jnp.asarray(delta, jnp.float32)
    pad = ntot - v.shape[0]
    if pad:
        v = jnp.concatenate([v, jnp.zeros((pad,), jnp.float32)])
    aux = jnp.concatenate([v[None, :], jnp.zeros((7, ntot), jnp.float32)], axis=0)
    nblk = (int(num_rows) + BLK - 1) // BLK
    band0, bandn = layout.WPAD, layout.BAND
    kern = functools.partial(
        _score_band_kernel, band0=band0, bandn=bandn, nblk=nblk,
        score_off=layout.SCORE + k - band0,
    )
    return pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(1,),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),  # aux
                pl.BlockSpec(memory_space=pl.ANY),  # P (alias)
            ],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[
                pltpu.VMEM((_URING, bandn, BLK), jnp.int32),
                pltpu.VMEM((_URING, 8, BLK), jnp.float32),
                pltpu.SemaphoreType.DMA((_URING,)),
                pltpu.SemaphoreType.DMA((_URING,)),
                pltpu.SemaphoreType.DMA((_URING,)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(p.shape, jnp.int32),
        input_output_aliases={1: 0},
        interpret=interpret,
    )(aux, p)


# ======================================================================
# split_stream: two-ended in-place partition + both-children histograms
# ======================================================================
def _stream_flush(stage, wsem, dst_any, merged, nstart, dst_off):
    """Start one aligned BLK write via the double-buffered stage.  Caller
    guarantees wait-before-reuse via _stage_wait."""
    slot = jax.lax.rem(nstart, 2)

    @pl.when(nstart >= 2)
    def _():
        pltpu.make_async_copy(stage.at[slot], stage.at[slot], wsem.at[slot]).wait()

    stage[slot] = merged
    pltpu.make_async_copy(
        stage.at[slot], dst_any.at[:, pl.ds(dst_off, BLK)], wsem.at[slot]
    ).start()


def _stream_drain(stage, wsem, nstarts):
    @pl.when(nstarts >= 1)
    def _():
        pltpu.make_async_copy(stage.at[0], stage.at[0], wsem.at[0]).wait()

    @pl.when(nstarts >= 2)
    def _():
        pltpu.make_async_copy(stage.at[1], stage.at[1], wsem.at[1]).wait()


def _run_segment(
    p_any, hist_ref, scalars,
    bufF, bufB, carL, carR, stageL, stageR, tri_ref,
    rsemF, rsemB, csemL, csemR, wsemL, wsemR,
    *, c, bits, nf, nb, rows, fchunk,
):
    """One pass over one parent segment: stable-unordered in-place
    partition by the split predicate + (F, B, 3) histograms of BOTH
    children accumulated into ``hist_ref`` (caller zeroes it and builds
    ``tri_ref`` once).  Returns the left-child row count.

    Two-ended block protocol (verified by exhaustive simulation in
    tests/test_pgrow.py::test_twoend_protocol): blocks are read from the
    front and the back of the segment; lefts compact forward into
    front-vacated space, rights compact backward into back-vacated space.
    Before classifying, any side whose vacated space hit zero is topped
    up with a demand read; a flush whose target block is the other side's
    in-flight read waits that read first.  Invariants guarantee writes
    only ever land on blocks already read."""
    (start, cnt, word, shift, zero_bin, dbz, thr, is_cat,
     off_lo, off_hi, bias) = scalars
    # EFB bundle range remap (feature_group.h PushData layout): the
    # feature's bins occupy stored values [off_lo, off_hi) with ``bias``
    # correcting a dropped zero default bin; values outside the range
    # mean "this feature at its default".  Unbundled features pass
    # (0, 1<<bits, 0), making fb == raw value.
    g_row, h_row, sel_row = rows

    base = pl.multiple_of((start // BLK) * BLK, _LANE)
    head = start - base
    E = head + cnt
    nblk = (E + BLK - 1) // BLK

    ii = jax.lax.broadcasted_iota(jnp.int32, (BLK, BLK), 0)

    # preload carries: carL holds the head block (lanes < head preserved
    # as pre-filled carry), carR the tail block (lanes >= E-(nblk-1)*BLK
    # preserved, filled from the end)
    cpL = pltpu.make_async_copy(p_any.at[:, pl.ds(base, BLK)], carL, csemL)
    # clamp: an empty block-aligned segment (cnt=0, head=0 -> nblk=0)
    # would otherwise issue a DMA at base-BLK (negative when base=0);
    # the preloaded data is unused in that case
    cpR = pltpu.make_async_copy(
        p_any.at[:, pl.ds(base + jnp.maximum(nblk - 1, 0) * BLK, BLK)], carR, csemR
    )
    cpL.start()
    cpR.start()
    cpL.wait()
    cpR.wait()

    def dmaF(k):  # k-th front read = block k
        slot = jax.lax.rem(k, _RING)
        return pltpu.make_async_copy(
            p_any.at[:, pl.ds(base + k * BLK, BLK)], bufF.at[slot], rsemF.at[slot]
        )

    def dmaB(k):  # k-th back read = block nblk-1-k
        slot = jax.lax.rem(k, _RING)
        return pltpu.make_async_copy(
            p_any.at[:, pl.ds(base + (nblk - 1 - k) * BLK, BLK)],
            bufB.at[slot],
            rsemB.at[slot],
        )

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, BLK), 1)
    iota_c = jax.lax.broadcasted_iota(jnp.int32, (c, 1), 0)
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (nb, BLK), 0)
    per = 32 // bits
    vmask = (1 << bits) - 1

    def body(j, st):
        if_, ib, cf, cb, kf, kb, fl, fr, cl, cr = st

        # ---- demand reads: top up any side whose vacated space is 0
        budget = if_ + ib < nblk
        doF = ((cf - fl) == 0) & ((if_ > cf) | budget)
        issF = doF & (if_ == cf)

        @pl.when(issF)
        def _():
            dmaF(if_).start()

        if_ = if_ + issF

        @pl.when(doF)
        def _():
            dmaF(cf).wait()

        cf = cf + doF

        budget = if_ + ib < nblk
        doB = ((cb - fr) == 0) & ((ib > cb) | budget)
        issB = doB & (ib == cb)

        @pl.when(issB)
        def _():
            dmaB(ib).start()

        ib = ib + issB

        @pl.when(doB)
        def _():
            dmaB(cb).wait()

        cb = cb + doB

        # ---- force-consume so a hand block exists
        budget = if_ + ib < nblk
        noq = ((cf - kf) == 0) & ((cb - kb) == 0)
        availF = (if_ > cf) | budget
        doCF = noq & availF
        issCF = doCF & (if_ == cf)

        @pl.when(issCF)
        def _():
            dmaF(if_).start()

        if_ = if_ + issCF

        @pl.when(doCF)
        def _():
            dmaF(cf).wait()

        cf = cf + doCF
        doCB = noq & (~availF)
        issCB = doCB & (ib == cb)

        @pl.when(issCB)
        def _():
            dmaB(ib).start()

        ib = ib + issCB

        @pl.when(doCB)
        def _():
            dmaB(cb).wait()

        cb = cb + doCB

        # ---- hand block
        useF = (cf - kf) > 0
        slotF = jax.lax.rem(kf, _RING)
        slotB = jax.lax.rem(kb, _RING)
        hand = jnp.where(useF, bufF[slotF], bufB[slotB])
        jh = jnp.where(useF, kf, nblk - 1 - kb)
        kf = kf + useF
        kb = kb + (~useF)

        # ---- classify: split predicate (DataPartition::Split fused with
        # the DefaultValueForZero bin remap of dense_bin.hpp:191-232)
        pos = lane + jh * BLK
        valid = (pos >= head) & (pos < E)
        wordrow = jnp.sum(jnp.where(iota_c == word, hand, 0), axis=0, keepdims=True)
        binv = (wordrow >> shift) & vmask
        in_range = (binv >= off_lo) & (binv < off_hi)
        fb = jnp.where(in_range, binv - off_lo + bias, zero_bin)
        fv = jnp.where(fb == zero_bin, dbz, fb)
        eqv = (fv == thr).astype(jnp.int32)
        lev = (fv <= thr).astype(jnp.int32)
        # select on int32 (Mosaic cannot legalize arith.select on i1 vectors)
        gl = (jnp.where(is_cat == 1, eqv, lev) == 1) & valid
        gr = valid & (~gl)
        glm = gl.astype(jnp.float32)
        grm = gr.astype(jnp.float32)

        # ---- both-children histograms while the block is in VMEM: the
        # bin one-hots (the VPU-bound part) are shared; the value rows
        # just widen 7 -> 14 sublanes (free on the MXU)
        selv = pltpu.bitcast(hand[sel_row : sel_row + 1, :], jnp.float32)
        gv = pltpu.bitcast(hand[g_row : g_row + 1, :], jnp.float32) * selv
        hv = pltpu.bitcast(hand[h_row : h_row + 1, :], jnp.float32) * selv
        vals = jnp.concatenate(
            _split3(gv * glm) + _split3(hv * glm) + [(selv * glm).astype(jnp.bfloat16)]
            + _split3(gv * grm) + _split3(hv * grm) + [(selv * grm).astype(jnp.bfloat16)],
            axis=0,
        )  # (14, BLK)
        for c0 in range(0, nf, fchunk):
            c1 = min(c0 + fchunk, nf)
            chunks = []
            for f in range(c0, c1):
                wd, p4 = divmod(f, per)
                byte = (hand[wd : wd + 1, :] >> (p4 * bits)) & vmask
                chunks.append((byte == iota_b).astype(jnp.bfloat16))
            oh = jnp.concatenate(chunks, axis=0)
            hist_ref[0:14, c0 * nb : c1 * nb] += jax.lax.dot_general(
                vals, oh, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )

        # ---- in-block compaction via permutation matmuls
        lr = jnp.concatenate(
            [glm.astype(jnp.bfloat16), grm.astype(jnp.bfloat16)], axis=0
        )  # (2, BLK)
        cum2 = jax.lax.dot_general(
            lr, tri_ref[:, :], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(jnp.int32)
        cumL = cum2[0:1]
        cumR = cum2[1:2]
        cntl = jnp.max(cumL)
        cntr = jnp.max(cumR)
        planes = _planes(hand, c)
        tgtL = cl + cumL - 1
        tgtL = tgtL - jnp.where(tgtL >= BLK, BLK, 0)
        ohL = (gl & (ii == tgtL)).astype(jnp.bfloat16)
        tgtR = BLK - cr - cumR
        tgtR = tgtR + jnp.where(tgtR < 0, BLK, 0)
        ohR = (gr & (ii == tgtR)).astype(jnp.bfloat16)
        permL = _unplanes(
            jax.lax.dot_general(planes, ohL, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32), c
        )
        permR = _unplanes(
            jax.lax.dot_general(planes, ohR, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32), c
        )

        # ---- left flush (forward, into front-vacated space)
        tL = cl + cntl
        flushL = tL >= BLK
        # if the target block is an in-flight read, consume it first
        nwB = flushL & (ib > cb) & (fl == nblk - 1 - cb)

        @pl.when(nwB)
        def _():
            dmaB(cb).wait()

        cb = cb + nwB
        nwF = flushL & (if_ > cf) & (fl == cf)

        @pl.when(nwF)
        def _():
            dmaF(cf).wait()

        cf = cf + nwF
        mergedL = jnp.where(lane < cl, carL[:, :], permL)

        @pl.when(flushL)
        def _():
            _stream_flush(stageL, wsemL, p_any, mergedL, fl, base + fl * BLK)

        carL[:, :] = jnp.where(flushL, permL, mergedL)
        cl = jnp.where(flushL, tL - BLK, tL)
        fl = fl + flushL

        # ---- right flush (backward, into back-vacated space)
        tR = cr + cntr
        flushR = tR >= BLK
        rtgt = nblk - 1 - fr
        nwB2 = flushR & (ib > cb) & (rtgt == nblk - 1 - cb)

        @pl.when(nwB2)
        def _():
            dmaB(cb).wait()

        cb = cb + nwB2
        nwF2 = flushR & (if_ > cf) & (rtgt == cf)

        @pl.when(nwF2)
        def _():
            dmaF(cf).wait()

        cf = cf + nwF2
        mergedR = jnp.where(lane >= BLK - cr, carR[:, :], permR)

        @pl.when(flushR)
        def _():
            _stream_flush(stageR, wsemR, p_any, mergedR, fr, base + rtgt * BLK)

        carR[:, :] = jnp.where(flushR, permR, mergedR)
        cr = jnp.where(flushR, tR - BLK, tR)
        fr = fr + flushR

        # ---- prefetch the hand side
        budget = if_ + ib < nblk
        pfF = budget & useF & ((if_ - kf) < _RING)

        @pl.when(pfF)
        def _():
            dmaF(if_).start()

        if_ = if_ + pfF
        budget = if_ + ib < nblk
        pfB = budget & (~useF) & ((ib - kb) < _RING)

        @pl.when(pfB)
        def _():
            dmaB(ib).start()

        ib = ib + pfB
        return (if_, ib, cf, cb, kf, kb, fl, fr, cl, cr)

    z = jnp.int32(0)
    st = jax.lax.fori_loop(
        0, nblk, body,
        (z, z, z, z, z, z, z, z, jnp.int32(head), nblk * BLK - E),
    )
    if_, ib, cf, cb, kf, kb, fl, fr, cl, cr = st

    # the final carries exactly tile one block (cl + cr ∈ {0, BLK}):
    # lefts at [0, cl), rights at [cl, BLK) == [BLK-cr, BLK)
    has_mid = (cl + cr) > 0

    @pl.when(has_mid)
    def _():
        merged = jnp.where(lane < cl, carL[:, :], carR[:, :])
        _stream_flush(stageL, wsemL, p_any, merged, fl, base + fl * BLK)

    _stream_drain(stageL, wsemL, fl + has_mid)
    _stream_drain(stageR, wsemR, fr)

    # drain any still-in-flight reads (their data is unused)
    @pl.when(if_ > cf)
    def _():
        dmaF(cf).wait()

    @pl.when(ib > cb)
    def _():
        dmaB(cb).wait()

    return fl * BLK + cl - head


def _build_tri(tri_ref):
    """Triangular cumsum operand, built once per kernel (cheaper than an
    HBM-resident constant: reading a 2 MB tri per pass costs more than
    one (BLK, BLK) compare)."""
    ii = jax.lax.broadcasted_iota(jnp.int32, (BLK, BLK), 0)
    jj = jax.lax.broadcasted_iota(jnp.int32, (BLK, BLK), 1)
    tri_ref[:, :] = (ii <= jj).astype(jnp.bfloat16)


def _split_kernel(
    sref, p_in, p_any, hist_ref, nl_ref,
    bufF, bufB, carL, carR, stageL, stageR, tri_ref,
    rsemF, rsemB, csemL, csemR, wsemL, wsemR,
    *, c, bits, nf, nb, rows, fchunk,
):
    """Single-segment wrapper over _run_segment (the classic per-split
    launch; grow_tree_partitioned's deep tail and standalone callers)."""
    _build_tri(tri_ref)
    hist_ref[:, :] = jnp.zeros_like(hist_ref)
    scalars = tuple(sref[k] for k in range(11))
    nl = _run_segment(
        p_any, hist_ref, scalars, bufF, bufB, carL, carR, stageL, stageR,
        tri_ref, rsemF, rsemB, csemL, csemR, wsemL, wsemR,
        c=c, bits=bits, nf=nf, nb=nb, rows=rows, fchunk=fchunk,
    )
    nl_ref[0] = nl


def _level_kernel(
    sref, p_in, p_any, hist_out, nl_ref,
    bufF, bufB, carL, carR, stageL, stageR, tri_ref, hacc,
    rsemF, rsemB, csemL, csemR, wsemL, wsemR, hsem,
    *, c, bits, nf, nb, rows, fchunk, smax,
):
    """One launch per tree LEVEL: partition EVERY active leaf segment by
    its chosen split and emit both children's histograms per segment —
    the per-split kernel-launch + host-bookkeeping fixed cost (measured
    ~0.3 ms/split, 2/3 of a 255-leaf iteration) collapses to one launch
    for the whole level.  Segments are disjoint [start, start+cnt)
    ranges processed sequentially with the same two-ended in-place
    protocol (_run_segment); per-segment (16, F*B) histograms are
    DMA'd out double-buffered while the next segment streams.

    sref: (1 + smax, 12) int32 — row 0 holds [n_active, ...]; row 1+s
    holds segment s's [start, cnt, word, shift, zero_bin, dbz, thr,
    is_cat, off_lo, off_hi, bias, 0]."""
    n_active = sref[0, 0]
    _build_tri(tri_ref)

    def one_seg(s, _):
        slot = jax.lax.rem(s, 2)

        # wait for the DMA that used this hist slot two segments ago
        @pl.when(s >= 2)
        def _():
            pltpu.make_async_copy(hacc.at[slot], hacc.at[slot], hsem.at[slot]).wait()

        hacc[slot] = jnp.zeros_like(hacc[slot])
        scalars = tuple(sref[1 + s, k] for k in range(11))
        nl = _run_segment(
            p_any, hacc.at[slot], scalars, bufF, bufB, carL, carR,
            stageL, stageR, tri_ref, rsemF, rsemB, csemL, csemR,
            wsemL, wsemR,
            c=c, bits=bits, nf=nf, nb=nb, rows=rows, fchunk=fchunk,
        )
        nl_ref[s] = nl
        pltpu.make_async_copy(hacc.at[slot], hist_out.at[s], hsem.at[slot]).start()
        return 0

    jax.lax.fori_loop(0, n_active, one_seg, 0)

    @pl.when(n_active >= 1)
    def _():
        s = n_active - 1
        slot = jax.lax.rem(s, 2)
        pltpu.make_async_copy(hacc.at[slot], hacc.at[slot], hsem.at[slot]).wait()

    @pl.when(n_active >= 2)
    def _():
        s = n_active - 2
        slot = jax.lax.rem(s, 2)
        pltpu.make_async_copy(hacc.at[slot], hacc.at[slot], hsem.at[slot]).wait()


@functools.partial(jax.jit, static_argnames=("num_features", "num_bins", "bits", "rows", "smax", "interpret"),
                   donate_argnums=(0,))
def level_stream(p, seg_tab, n_active, *, num_features, num_bins, bits=8,
                 rows=None, smax, interpret=False):
    """Partition all ``n_active`` leaf segments described by ``seg_tab``
    in place in ONE kernel launch and return every segment's left count
    and both-children histograms.

    seg_tab: (smax, 12) int32 rows [start, cnt, word, shift, zero_bin,
    dbz, thr, is_cat, off_lo, off_hi, bias, 0] (same scalar contract as
    split_stream).  Returns (p', nl (smax,), hists (smax, 16, F*B)) —
    hist rows 0:7 = left child (3-plane g, 3-plane h, count), 7:14 =
    right child; rows for s >= n_active are undefined."""
    if rows is None:
        wpad = -(-num_words(num_features, bits) // 8) * 8
        rows = (wpad, wpad + 1, wpad + 2)
    c = p.shape[0]
    fb = num_features * num_bins
    # sliced VMEM refs (hacc.at[slot]) must be lane-tile (128) aligned
    fbp = -(-fb // _LANE) * _LANE
    # split/level kernels: VMEM is crowded by the partition stream
    # buffers, so cap the one-hot tile at the historical 1 MiB
    fchunk = tune_fchunk(num_features, num_bins,
                         max_tile_bytes=1024 * 1024)
    hdr = jnp.zeros((1, 12), jnp.int32).at[0, 0].set(jnp.int32(n_active))
    sv = jnp.concatenate([hdr, seg_tab.astype(jnp.int32)], axis=0)
    p, hist, nl = pl.pallas_call(
        functools.partial(_level_kernel, c=c, bits=bits, nf=num_features,
                          nb=num_bins, rows=rows, fchunk=fchunk, smax=smax),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],  # P (alias)
            out_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),  # hists (DMA'd per segment)
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            scratch_shapes=[
                pltpu.VMEM((_RING, c, BLK), jnp.int32),  # bufF
                pltpu.VMEM((_RING, c, BLK), jnp.int32),  # bufB
                pltpu.VMEM((c, BLK), jnp.int32),  # carL
                pltpu.VMEM((c, BLK), jnp.int32),  # carR
                pltpu.VMEM((2, c, BLK), jnp.int32),  # stageL
                pltpu.VMEM((2, c, BLK), jnp.int32),  # stageR
                pltpu.VMEM((BLK, BLK), jnp.bfloat16),  # tri
                pltpu.VMEM((2, 16, fbp), jnp.float32),  # hacc (double-buffered)
                pltpu.SemaphoreType.DMA((_RING,)),  # rsemF
                pltpu.SemaphoreType.DMA((_RING,)),  # rsemB
                pltpu.SemaphoreType.DMA(()),  # csemL
                pltpu.SemaphoreType.DMA(()),  # csemR
                pltpu.SemaphoreType.DMA((2,)),  # wsemL
                pltpu.SemaphoreType.DMA((2,)),  # wsemR
                pltpu.SemaphoreType.DMA((2,)),  # hsem
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct(p.shape, jnp.int32),
            jax.ShapeDtypeStruct((smax, 16, fbp), jnp.float32),
            jax.ShapeDtypeStruct((smax,), jnp.int32),
        ),
        input_output_aliases={1: 0},
        interpret=interpret,
    )(sv, p)
    return p, nl, hist[:, :, :fb]


@functools.partial(jax.jit, static_argnames=("num_features", "num_bins", "bits", "rows", "interpret"),
                   donate_argnums=(0,))
def split_stream(p, start, cnt, word, shift, zero_bin, dbz, thr, is_cat,
                 off_lo=0, off_hi=256, bias=0, *, num_features, num_bins,
                 bits=8, rows=None, interpret=False):
    """Partition the leaf segment [start, start+cnt) of ``p`` in place by
    the split predicate AND return both children's histograms from the
    same pass.

    Lefts land at [start, start+nl), rights at [start+nl, start+cnt)
    (order within each child unspecified).  Returns
    (p', nl, left_hist (F, B, 3), right_hist)."""
    if rows is None:
        wpad = -(-num_words(num_features, bits) // 8) * 8
        rows = (wpad, wpad + 1, wpad + 2)
    c = p.shape[0]
    fb = num_features * num_bins
    # split/level kernels: VMEM is crowded by the partition stream
    # buffers, so cap the one-hot tile at the historical 1 MiB
    fchunk = tune_fchunk(num_features, num_bins,
                         max_tile_bytes=1024 * 1024)
    sv = jnp.stack(
        [
            jnp.int32(start), jnp.int32(cnt), jnp.int32(word), jnp.int32(shift),
            jnp.int32(zero_bin), jnp.int32(dbz), jnp.int32(thr), jnp.int32(is_cat),
            jnp.int32(off_lo), jnp.int32(off_hi), jnp.int32(bias),
        ]
    )
    p, hist, nl = pl.pallas_call(
        functools.partial(_split_kernel, c=c, bits=bits, nf=num_features,
                          nb=num_bins, rows=rows, fchunk=fchunk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],  # P (alias)
            out_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            scratch_shapes=[
                pltpu.VMEM((_RING, c, BLK), jnp.int32),  # bufF
                pltpu.VMEM((_RING, c, BLK), jnp.int32),  # bufB
                pltpu.VMEM((c, BLK), jnp.int32),  # carL
                pltpu.VMEM((c, BLK), jnp.int32),  # carR
                pltpu.VMEM((2, c, BLK), jnp.int32),  # stageL
                pltpu.VMEM((2, c, BLK), jnp.int32),  # stageR
                pltpu.VMEM((BLK, BLK), jnp.bfloat16),  # tri
                pltpu.SemaphoreType.DMA((_RING,)),  # rsemF
                pltpu.SemaphoreType.DMA((_RING,)),  # rsemB
                pltpu.SemaphoreType.DMA(()),  # csemL
                pltpu.SemaphoreType.DMA(()),  # csemR
                pltpu.SemaphoreType.DMA((2,)),  # wsemL
                pltpu.SemaphoreType.DMA((2,)),  # wsemR
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct(p.shape, jnp.int32),
            jax.ShapeDtypeStruct((16, fb), jnp.float32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ),
        input_output_aliases={1: 0},
        interpret=interpret,
    )(sv, p)
    left = _hist_from_rows(hist, num_features, num_bins, row0=0)
    right = _hist_from_rows(hist, num_features, num_bins, row0=7)
    return p, nl[0], left, right


# ======================================================================
# update_channels: in-place gradient / bagging / score channel refresh
# ======================================================================
_URING = 8  # ring depth for the band streamer
_UAHEAD = 5  # reads primed ahead; write waits then trail by R-K=3 blocks
#             (an inline start-then-wait write measures ~100 us/block on
#             the tunneled runtime; >=2-deep deferral hides it entirely)


def _update_kernel(aux_any, p_in, p_any, buf, abuf, rsem, asem, wsem, *,
                   band0, bandn, naux, nblk, grad_fn, score_off, label_off,
                   weight_off, use_weight, use_sel, k_class):
    """Stream the mutable band: score += delta (aux row 0), then
    (g, h) = grad_fn(score, label, weight) written into rows 0..1 of the
    band, select = aux row 1 (bagging) when use_sel.

    The band layout within the streamed window is
      [0]=g [1]=h [2]=sel [3..3+K-1]=scores [3+K]=label [4+K]=rowid
      [5+K]=weight — i.e. rows [band0, band0+bandn) of P.

    One ring of _URING block buffers: block j reads into and writes back
    from slot j%R.  Reads run _UAHEAD blocks ahead; starting read j+K
    first waits write j+K-R (same slot), giving every write R-K blocks
    of slack before anything blocks on it."""
    R, K = _URING, _UAHEAD

    def rd(j):
        sl = jax.lax.rem(j, R)
        return pltpu.make_async_copy(
            p_any.at[band0 : band0 + bandn, pl.ds(j * BLK, BLK)], buf.at[sl], rsem.at[sl]
        )

    def rda(j):
        sl = jax.lax.rem(j, R)
        return pltpu.make_async_copy(
            aux_any.at[:, pl.ds(j * BLK, BLK)], abuf.at[sl], asem.at[sl]
        )

    def wr(j):
        sl = jax.lax.rem(j, R)
        return pltpu.make_async_copy(
            buf.at[sl], p_any.at[band0 : band0 + bandn, pl.ds(j * BLK, BLK)], wsem.at[sl]
        )

    for k in range(min(K, nblk)):
        rd(k).start()
        rda(k).start()

    def body(j, _):
        sl = jax.lax.rem(j, R)
        rd(j).wait()
        rda(j).wait()
        blk = buf[sl]
        aux = abuf[sl]
        delta = aux[0:1, :]
        score = pltpu.bitcast(blk[score_off + k_class : score_off + k_class + 1, :],
                              jnp.float32) + delta
        label = pltpu.bitcast(blk[label_off : label_off + 1, :], jnp.float32)
        if use_weight:
            weight = pltpu.bitcast(blk[weight_off : weight_off + 1, :], jnp.float32)
        else:
            weight = None
        g, h = grad_fn(score, label, weight)
        out = blk
        out = _setrow(out, 0, pltpu.bitcast(g.astype(jnp.float32), jnp.int32))
        out = _setrow(out, 1, pltpu.bitcast(h.astype(jnp.float32), jnp.int32))
        if use_sel:
            out = _setrow(out, 2, pltpu.bitcast(aux[1:2, :], jnp.int32))
        out = _setrow(out, score_off + k_class,
                      pltpu.bitcast(score, jnp.int32))
        buf[sl] = out
        wr(j).start()

        @pl.when(j + K < nblk)
        def _():
            @pl.when(j + K - R >= 0)
            def _():
                wr(j + K - R).wait()

            rd(j + K).start()
            rda(j + K).start()

        return 0

    jax.lax.fori_loop(0, nblk, body, 0)
    # drain: the in-loop wait fires only while reads remain (j+K < nblk),
    # so the last min(R, nblk) writes are still un-waited
    for k in range(min(R, nblk)):
        wr(nblk - 1 - k).wait()


def _setrow(mat, r, row):
    """Replace row ``r`` (static) of (R, BLK) with (1, BLK) ``row``.
    Builds without zero-size slices (Mosaic rejects (0, BLK) vectors)."""
    parts = []
    if r > 0:
        parts.append(mat[:r])
    parts.append(row)
    if r + 1 < mat.shape[0]:
        parts.append(mat[r + 1 :])
    return jnp.concatenate(parts, axis=0) if len(parts) > 1 else row


def update_channels(p, layout: PLayout, grad_fn, delta=None, sel=None,
                    k_class: int = 0, interpret: bool = False):
    """In-place refresh of the mutable band: ``score[k] += delta`` then
    ``g, h = grad_fn(score, label, weight)`` and optionally
    ``select = sel`` — the per-iteration channel maintenance of the fused
    trainer (GBDT::Boosting + Bagging, gbdt.cpp:692-700, 275-334) as ONE
    aliased Pallas pass.

    Exists because ANY XLA-level write to the big matrix (even a
    one-element update on a donated loop carry) costs a pathological
    whole-array copy on this backend; only Pallas input_output_aliases
    mutate in place — see the module docstring for the carry-layout
    contract that keeps the donated matrix XLA-write-free end to end.
    ``delta``/``sel`` are (N,)-or-longer f32 vectors (padded with zeros
    up to p.shape[1] here)."""
    ntot = p.shape[1]
    # floor, not ceil: P has n + BLK columns, so floor(ntot/BLK) blocks
    # always cover every real row without the last window overrunning
    nblk = ntot // BLK
    aux_rows = []
    zero = jnp.zeros((ntot,), jnp.float32)

    def fit(v):
        v = jnp.asarray(v, jnp.float32)
        pad = ntot - v.shape[0]
        return jnp.concatenate([v, jnp.zeros((pad,), jnp.float32)]) if pad else v

    aux_rows.append(fit(delta) if delta is not None else zero)
    use_sel = sel is not None
    aux_rows.append(fit(sel) if use_sel else zero)
    # 8 rows: DMA row-slices must be (8, 128)-tile aligned; rows 2..7 pad
    aux = jnp.concatenate(
        [jnp.stack(aux_rows), jnp.zeros((6, ntot), jnp.float32)], axis=0
    )  # (8, ntot) f32

    band0, bandn = layout.WPAD, layout.BAND
    kern = functools.partial(
        _update_kernel,
        band0=band0, bandn=bandn, naux=2, nblk=nblk, grad_fn=grad_fn,
        score_off=3 + 0, label_off=3 + layout.num_score,
        weight_off=3 + layout.num_score + 2,
        use_weight=layout.with_weight, use_sel=use_sel, k_class=k_class,
    )
    return pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(1,),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),  # aux
                pl.BlockSpec(memory_space=pl.ANY),  # P (alias)
            ],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[
                pltpu.VMEM((_URING, bandn, BLK), jnp.int32),
                pltpu.VMEM((_URING, 8, BLK), jnp.float32),
                pltpu.SemaphoreType.DMA((_URING,)),
                pltpu.SemaphoreType.DMA((_URING,)),
                pltpu.SemaphoreType.DMA((_URING,)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(p.shape, jnp.int32),
        input_output_aliases={1: 0},
        interpret=interpret,
    )(aux, p)


# ======================================================================
# pure-XLA / numpy reference implementations (CPU tests / documentation)
# ======================================================================
def unpack_bins(p, layout: PLayout, n: int) -> jnp.ndarray:
    """(N, F) uint8 bins recovered from the packed words (test helper)."""
    w = layout.W
    words = p[:w, :n]  # (W, N)
    mask = (1 << layout.bits) - 1
    cols = []
    for f in range(layout.F):
        wd, p4 = divmod(f, layout.per)
        cols.append((words[wd] >> (p4 * layout.bits)) & mask)
    return jnp.stack(cols, axis=1).astype(jnp.uint8)


def hist_ref(p, start: int, cnt: int, layout: PLayout, num_bins: int) -> jnp.ndarray:
    """Reference (XLA) histogram of a segment — same contract as hist_dyn."""
    from .histogram import build_histogram

    seg = p[:, start : start + cnt]
    bins = unpack_bins(seg, layout, cnt)
    g = jax.lax.bitcast_convert_type(seg[layout.G], jnp.float32)
    h = jax.lax.bitcast_convert_type(seg[layout.H], jnp.float32)
    sel = jax.lax.bitcast_convert_type(seg[layout.SEL], jnp.float32)
    return build_histogram(bins, g, h, sel, num_bins)


def partition_ref(p, start: int, cnt: int, feat: int, zero_bin: int, dbz: int, thr: int, is_cat: bool, layout: PLayout):
    """Reference (numpy) stable partition — the expected ROW SETS of
    split_stream (which is unordered within each side: compare sorted by
    the ROWID channel)."""
    pn = np.asarray(p)
    seg = pn[:, start : start + cnt]
    wd, p4 = divmod(feat, layout.per)
    binv = (seg[wd] >> (p4 * layout.bits)) & ((1 << layout.bits) - 1)
    fv = np.where(binv == zero_bin, dbz, binv)
    gl = (fv == thr) if is_cat else (fv <= thr)
    out = np.concatenate([seg[:, gl], seg[:, ~gl]], axis=1)
    pn = pn.copy()
    pn[:, start : start + cnt] = out
    return jnp.asarray(pn), int(gl.sum())
