"""Dynamic-segment Pallas kernels for the partitioned tree grower.

TPU-native counterpart of the reference's histogram kernels and data
partition (src/treelearner/ocl/histogram256.cl:345 per-workgroup
sub-histograms + reduction, host driver gpu_tree_learner.cpp:123-191;
src/treelearner/data_partition.hpp:94-150 ``Split``).

The training matrix ``P`` is one (C, N) int32 array whose rows are

    0..W-1 : packed bin words, 4 uint8 bins per int32 (W = ceil(F/4))
    W + 0  : grad   (f32 bitcast)
    W + 1  : hess   (f32 bitcast)
    W + 2  : select (f32 bitcast; 0/1 bagging mask)
    W + 3.. : driver-owned channels (scores, label, weight, row id) that
             the kernels never touch but that travel with every row.

Rows are kept PHYSICALLY PARTITIONED by leaf: each leaf owns a
contiguous column range [start, start+cnt).  That gives the reference's
DataPartition asymptotics (O(N_leaf) per histogram / split, not O(N))
without any gather — TPU gathers measure ~20 Mrow/s while streaming
DMA + MXU runs at GB/s.

All three kernels run as ONE grid step with an internal dynamic-length
``fori_loop`` over BLK-column chunks, double-buffered HBM->VMEM DMA, and
write in place via ``input_output_aliases`` (measured ~3 us/call inside
a jitted while_loop).  DMA windows must be 128-lane aligned, so every
stream runs on BLK-aligned windows with the segment's unaligned head
phase absorbed by a carry buffer (preloaded with the existing head
block) and the tail merged read-modify-write.

Why matmuls everywhere: Mosaic has no vector scatter/gather and no
cumsum, but the MXU is nearly free next to HBM bandwidth.  So
- cumsum(goes_left) = one dot with a triangular ones matrix,
- the in-block stable compaction is a one-hot permutation matmul applied
  to the block's four byte planes (integers 0..255 are exact in bf16, so
  the permutation is bit-exact on int32/f32 data),
exactly the trade SURVEY §7 prescribes (scatter -> one-hot matmul).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLK = 1024  # columns (data rows) per streamed chunk
_LANE = 128  # DMA lane-alignment quantum


def num_words(num_features: int, bits: int = 8) -> int:
    return -(-num_features // (32 // bits))


def num_channels(num_features: int, num_score: int = 1, with_weight: bool = True,
                 bits: int = 8) -> int:
    """Total padded channel count: W words + g,h,sel + num_score scores +
    label + rowid (+ weight), padded to a multiple of 8 (DMA sublane
    tiling)."""
    c = num_words(num_features, bits) + 3 + num_score + 2 + (1 if with_weight else 0)
    return -(-c // 8) * 8


class PLayout:
    """Channel-row indices inside the packed matrix.

    ``bits`` selects the bin word width: 8 (4 bins/int32) for max_bin up
    to 256, or 4 (8 bins/int32) when every column fits 16 bins — the TPU
    form of the reference's Dense4bitsBin (dense_nbits_bin.hpp:37),
    halving resident bin bytes and per-row stream traffic."""

    def __init__(self, num_features: int, num_score: int = 1, with_weight: bool = True,
                 bits: int = 8):
        self.F = num_features
        self.bits = bits
        self.per = 32 // bits
        self.W = num_words(num_features, bits)
        self.G = self.W
        self.H = self.W + 1
        self.SEL = self.W + 2
        self.SCORE = self.W + 3  # .. SCORE + num_score - 1
        self.num_score = num_score
        self.LABEL = self.SCORE + num_score
        self.ROWID = self.LABEL + 1
        self.WEIGHT = self.ROWID + 1 if with_weight else -1
        self.with_weight = with_weight
        self.C = num_channels(num_features, num_score, with_weight, bits)


def pack_matrix(bins: np.ndarray, layout: PLayout, label=None, weight=None) -> jnp.ndarray:
    """Build the (C, N + BLK) packed matrix from (N, F) uint8 bins.

    The BLK tail columns absorb block-granular DMA overruns.  grad/hess
    start at 0, select at 1, scores at 0; rowid is the original row
    index (prediction / eval unscrambling)."""
    n, f = bins.shape
    assert f == layout.F
    assert bins.dtype == np.uint8, "partitioned path requires max_bin <= 256"
    assert int(bins.max(initial=0)) < (1 << layout.bits), (
        f"bin values exceed the {layout.bits}-bit word field"
    )
    w, per, bits = layout.W, layout.per, layout.bits
    pad_f = w * per - f
    bb = np.pad(np.asarray(bins), ((0, 0), (0, pad_f))).astype(np.uint32)
    bb = bb.reshape(n, w, per)
    words = np.zeros((n, w), np.uint32)
    for k in range(per):
        words |= bb[:, :, k] << (bits * k)
    words = words.view(np.int32)
    P = np.zeros((layout.C, n + BLK), np.int32)
    P[:w, :n] = words.T
    one = np.float32(1.0).view(np.int32)
    P[layout.SEL, :n] = one
    if label is not None:
        P[layout.LABEL, :n] = np.asarray(label, np.float32).view(np.int32)
    P[layout.ROWID, :n] = np.arange(n, dtype=np.int32)
    if layout.with_weight:
        wv = np.ones(n, np.float32) if weight is None else np.asarray(weight, np.float32)
        P[layout.WEIGHT, :n] = wv.view(np.int32)
    return jnp.asarray(P)


def pack_matrix_device(bins_dev, layout: PLayout, label=None, weight=None) -> jnp.ndarray:
    """pack_matrix built ON DEVICE from an already-transferred (N, F)
    uint8 bins array.  Host->device bandwidth through the tunneled TPU is
    ~10 MB/s, so shipping the 28 B/row bins once and deriving the packed
    matrix with XLA shifts beats shipping the 64 B/row matrix."""
    n, f = bins_dev.shape
    w, per, bits = layout.W, layout.per, layout.bits
    pad_f = w * per - f
    bb = jnp.pad(bins_dev.astype(jnp.int32), ((0, 0), (0, pad_f)))
    # mask defensively: an oversized bin value would OR into the next
    # feature's field (callers guarantee the bound; this keeps corruption
    # local to the offending feature instead of silent cross-talk)
    bb = bb & ((1 << bits) - 1)
    bb = bb.reshape(n, w, per)
    shifts = (jnp.arange(per, dtype=jnp.int32) * bits)[None, None, :]
    words = jnp.sum(bb << shifts, axis=2, dtype=jnp.int32)  # (N, W)
    one = np.float32(1.0).view(np.int32)

    def frow(x):
        return jax.lax.bitcast_convert_type(jnp.asarray(x, jnp.float32), jnp.int32)

    rows = [words.T]
    rows.append(jnp.zeros((2, n), jnp.int32))  # g, h
    rows.append(jnp.full((1, n), one, jnp.int32))  # sel
    rows.append(jnp.zeros((layout.num_score, n), jnp.int32))  # scores
    rows.append(frow(label if label is not None else np.zeros(n, np.float32))[None, :])
    rows.append(jnp.arange(n, dtype=jnp.int32)[None, :])  # rowid
    if layout.with_weight:
        wv = jnp.ones((n,), jnp.float32) if weight is None else jnp.asarray(weight, jnp.float32)
        rows.append(jax.lax.bitcast_convert_type(wv, jnp.int32)[None, :])
    p = jnp.concatenate(rows, axis=0)
    cpad = layout.C - p.shape[0]
    return jnp.pad(p, ((0, cpad), (0, BLK)))


def _tri_np() -> np.ndarray:
    """(BLK, BLK) upper-triangular ones: dot(v, tri)[d] = cumsum_{s<=d} v[s]."""
    i = np.arange(BLK)
    return (i[:, None] <= i[None, :]).astype(np.float32)


_TRI_NP = None


def _get_tri():
    """bf16 triangular constant; numpy-backed so traced calls never cache
    a tracer."""
    global _TRI_NP
    if _TRI_NP is None:
        _TRI_NP = _tri_np()
    return jnp.asarray(_TRI_NP, jnp.bfloat16)


def _planes(blk_i32, c):
    """(C, BLK) int32 -> (4C, BLK) bf16 byte planes (exact in bf16)."""
    ps = [(blk_i32 >> (8 * k)) & 255 for k in range(4)]
    return jnp.concatenate(ps, axis=0).astype(jnp.bfloat16)


def _unplanes(dots_f32, c):
    """(4C, BLK) f32 byte planes -> (C, BLK) int32 (exact repack)."""
    p = dots_f32.astype(jnp.int32)
    return (
        p[0 * c : 1 * c]
        | (p[1 * c : 2 * c] << 8)
        | (p[2 * c : 3 * c] << 16)
        | (p[3 * c : 4 * c] << 24)
    )


# ======================================================================
# histogram kernel
# ======================================================================
def _hist_kernel(sref, p_any, o_ref, acc_ref, buf_ref, sem, *, nf, nb, w, c, fchunk, bits):
    start = sref[0]
    cnt = sref[1]
    base = pl.multiple_of((start // BLK) * BLK, _LANE)
    head = start - base
    nblk = (head + cnt + BLK - 1) // BLK
    acc_ref[:, :] = jnp.zeros_like(acc_ref)

    def get_dma(slot, j):
        return pltpu.make_async_copy(
            p_any.at[:, pl.ds(base + j * BLK, BLK)], buf_ref.at[slot], sem.at[slot]
        )

    get_dma(0, 0).start()

    iota_b = jax.lax.broadcasted_iota(jnp.int32, (nb, BLK), 0)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, BLK), 1)

    def body(j, _):
        slot = jax.lax.rem(j, 2)

        @pl.when(j + 1 < nblk)
        def _():
            get_dma(1 - slot, j + 1).start()

        get_dma(slot, j).wait()
        blk = buf_ref[slot]
        pos = lane + j * BLK
        valid = ((pos >= head) & (pos < head + cnt)).astype(jnp.float32)
        sel = pltpu.bitcast(blk[w + 2 : w + 3, :], jnp.float32) * valid
        g = pltpu.bitcast(blk[w : w + 1, :], jnp.float32) * sel
        h = pltpu.bitcast(blk[w + 1 : w + 2, :], jnp.float32) * sel

        # f32 fidelity at bf16 speed: x = hi + mid + lo (3 bf16 terms);
        # the dot's N dim pads to 128 lanes so extra value rows are free.
        def split3(x):
            hi = x.astype(jnp.bfloat16)
            r1 = x - hi.astype(jnp.float32)
            mid = r1.astype(jnp.bfloat16)
            lo = (r1 - mid.astype(jnp.float32)).astype(jnp.bfloat16)
            return hi, mid, lo

        g3 = split3(g)
        h3 = split3(h)
        vals = jnp.concatenate(list(g3) + list(h3) + [sel.astype(jnp.bfloat16)], axis=0)

        per = 32 // bits
        mask = (1 << bits) - 1
        for c0 in range(0, nf, fchunk):
            c1 = min(c0 + fchunk, nf)
            chunks = []
            for f in range(c0, c1):
                wd, p4 = divmod(f, per)
                byte = (blk[wd : wd + 1, :] >> (p4 * bits)) & mask
                chunks.append((byte == iota_b).astype(jnp.bfloat16))
            oh = jnp.concatenate(chunks, axis=0)
            # (7, BLK) x (F_c*B, BLK) -> (7, F_c*B): value rows on sublanes
            # so the accumulator/output is (8, F*B) — lane-major, which
            # copies out clean (an (F*B, 7) output pays a strided
            # VMEM->HBM copy measured at ~2 ms).
            acc_ref[0:7, c0 * nb : c1 * nb] += jax.lax.dot_general(
                vals, oh, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
        return 0

    jax.lax.fori_loop(0, nblk, body, 0, unroll=False)
    o_ref[:, :] = acc_ref[:, :]


@functools.partial(jax.jit, static_argnames=("num_features", "num_bins", "bits", "interpret"))
def hist_dyn(p, start, cnt, num_features, num_bins, bits=8, interpret=False):
    """(F, B, 3) histogram of the leaf segment [start, start+cnt) of the
    packed matrix ``p`` — DenseBin::ConstructHistogram (dense_bin.hpp:66)
    over the leaf's contiguous rows, streamed at HBM bandwidth.  bits=4
    streams the Dense4bitsBin-packed form (8 bins per word)."""
    w = num_words(num_features, bits)
    c = p.shape[0]
    fb = num_features * num_bins
    fchunk = max(1, min(num_features, 512 // num_bins))
    out = pl.pallas_call(
        functools.partial(_hist_kernel, nf=num_features, nb=num_bins, w=w, c=c,
                          fchunk=fchunk, bits=bits),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((8, fb), jnp.float32),
                pltpu.VMEM((2, c, BLK), jnp.int32),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((8, fb), jnp.float32),
        interpret=interpret,
    )(jnp.stack([jnp.int32(start), jnp.int32(cnt)]), p)
    hist = jnp.stack(
        [
            out[0] + (out[1] + out[2]),
            out[3] + (out[4] + out[5]),
            out[6],
        ],
        axis=1,
    )
    return hist.reshape(num_features, num_bins, 3)


# ======================================================================
# partition kernel
# ======================================================================
def _stream_flush(stage, wsem, dst_any, merged, nstart, dst_off):
    """Start one aligned BLK write via the double-buffered stage.  Caller
    guarantees wait-before-reuse via _stage_wait."""
    slot = jax.lax.rem(nstart, 2)

    @pl.when(nstart >= 2)
    def _():
        pltpu.make_async_copy(stage.at[slot], stage.at[slot], wsem.at[slot]).wait()

    stage[slot] = merged
    pltpu.make_async_copy(
        stage.at[slot], dst_any.at[:, pl.ds(dst_off, BLK)], wsem.at[slot]
    ).start()


def _stream_drain(stage, wsem, nstarts):
    @pl.when(nstarts >= 1)
    def _():
        pltpu.make_async_copy(stage.at[0], stage.at[0], wsem.at[0]).wait()

    @pl.when(nstarts >= 2)
    def _():
        pltpu.make_async_copy(stage.at[1], stage.at[1], wsem.at[1]).wait()


def _part_kernel(
    sref, tri_ref, p_in, s_in, p_any, s_any, nl_ref,
    buf, carL, carR, stageL, stageR, tmp, rsem, csem, wsemL, wsemR, *, c, bits,
):
    start = sref[0]
    cnt = sref[1]
    word = sref[2]
    shift = sref[3]
    zero_bin = sref[4]
    dbz = sref[5]
    thr = sref[6]
    is_cat = sref[7]
    # EFB bundle range remap (feature_group.h PushData layout): the
    # feature's bins occupy stored values [off_lo, off_hi) with ``bias``
    # correcting a dropped zero default bin; values outside the range
    # mean "this feature at its default".  Unbundled features pass
    # (0, 256, 0), making fb == raw value.
    off_lo = sref[8]
    off_hi = sref[9]
    bias = sref[10]
    base = pl.multiple_of((start // BLK) * BLK, _LANE)
    head = start - base
    nblk = (head + cnt + BLK - 1) // BLK

    def get_read(slot, j):
        return pltpu.make_async_copy(
            p_any.at[:, pl.ds(base + j * BLK, BLK)], buf.at[slot], rsem.at[slot]
        )

    get_read(0, 0).start()
    # preload the left carry with the existing head block: lanes < head are
    # preserved verbatim through the first flush (the in-place RMW head).
    pltpu.make_async_copy(p_any.at[:, pl.ds(base, BLK)], carL, csem).start()
    pltpu.make_async_copy(p_any.at[:, pl.ds(base, BLK)], carL, csem).wait()

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, BLK), 1)
    iota_c = jax.lax.broadcasted_iota(jnp.int32, (c, 1), 0)
    iota_d = jax.lax.broadcasted_iota(jnp.int32, (BLK, BLK), 0)
    tri = tri_ref[:, :]

    def body(j, st):
        cl, fl, cr, fr = st
        slot = jax.lax.rem(j, 2)

        @pl.when(j + 1 < nblk)
        def _():
            get_read(1 - slot, j + 1).start()

        get_read(slot, j).wait()
        blk = buf[slot]
        pos = lane + j * BLK
        valid = (pos >= head) & (pos < head + cnt)
        wordrow = jnp.sum(jnp.where(iota_c == word, blk, 0), axis=0, keepdims=True)
        binv = (wordrow >> shift) & ((1 << bits) - 1)
        in_range = (binv >= off_lo) & (binv < off_hi)
        fb = jnp.where(in_range, binv - off_lo + bias, zero_bin)
        fv = jnp.where(fb == zero_bin, dbz, fb)
        eqv = (fv == thr).astype(jnp.int32)
        lev = (fv <= thr).astype(jnp.int32)
        gl = (jnp.where(is_cat == 1, eqv, lev) == 1) & valid
        gr = valid & (~gl)

        glf = gl.astype(jnp.bfloat16)
        grf = gr.astype(jnp.bfloat16)
        cumL = jax.lax.dot_general(
            glf, tri, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        cumR = jax.lax.dot_general(
            grf, tri, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        cumLi = cumL.astype(jnp.int32)
        cumRi = cumR.astype(jnp.int32)
        cntl = jnp.max(cumLi)
        cntr = jnp.max(cumRi)

        planes = _planes(blk, c)

        def permute(sel_mask, cum_i, coff):
            tgt = coff + cum_i - 1
            tgt = tgt - jnp.where(tgt >= BLK, BLK, 0)
            oh = (sel_mask & (iota_d == tgt)).astype(jnp.bfloat16)  # (D, S) d x s
            dots = jax.lax.dot_general(
                planes, oh, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )  # (4C, D)
            return _unplanes(dots, c)

        permL = permute(gl, cumLi, cl)
        permR = permute(gr, cumRi, cr)

        tL = cl + cntl
        mergedL = jnp.where(lane < cl, carL[:, :], permL)
        flushL = tL >= BLK

        @pl.when(flushL)
        def _():
            _stream_flush(stageL, wsemL, p_any, mergedL, fl, base + fl * BLK)

        carL[:, :] = jnp.where(flushL, permL, mergedL)
        cl = jnp.where(flushL, tL - BLK, tL)
        fl = fl + flushL.astype(jnp.int32)

        tR = cr + cntr
        mergedR = jnp.where(lane < cr, carR[:, :], permR)
        flushR = tR >= BLK

        @pl.when(flushR)
        def _():
            _stream_flush(stageR, wsemR, s_any, mergedR, fr, fr * BLK)

        carR[:, :] = jnp.where(flushR, permR, mergedR)
        cr = jnp.where(flushR, tR - BLK, tR)
        fr = fr + flushR.astype(jnp.int32)
        return (cl, fl, cr, fr)

    cl, fl, cr, fr = jax.lax.fori_loop(
        0, nblk, body, (head, jnp.int32(0), jnp.int32(0), jnp.int32(0)), unroll=False
    )

    # final left flush: read-modify-write the tail block so columns past
    # the carry fill keep their current bytes (to be overwritten by the
    # rights copy-back, or beyond-segment data that must survive).
    pltpu.make_async_copy(p_any.at[:, pl.ds(base + fl * BLK, BLK)], tmp, csem).start()
    pltpu.make_async_copy(p_any.at[:, pl.ds(base + fl * BLK, BLK)], tmp, csem).wait()
    mergedL = jnp.where(lane < cl, carL[:, :], tmp[:, :])
    _stream_flush(stageL, wsemL, p_any, mergedL, fl, base + fl * BLK)
    # final right flush: whole carry block (garbage tail masked at copy-back)
    _stream_flush(stageR, wsemR, s_any, carR[:, :], fr, fr * BLK)

    _stream_drain(stageL, wsemL, fl + 1)
    _stream_drain(stageR, wsemR, fr + 1)
    nl_ref[0] = fl * BLK + cl - head


def _partition_call(p, scratch, tri, sv, bits=8, interpret=False):
    c = p.shape[0]
    nscr = scratch.shape[1]
    return pl.pallas_call(
        functools.partial(_part_kernel, c=c, bits=bits),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.VMEM),  # tri
                pl.BlockSpec(memory_space=pl.ANY),  # P (alias)
                pl.BlockSpec(memory_space=pl.ANY),  # scratch (alias)
            ],
            out_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            scratch_shapes=[
                pltpu.VMEM((2, c, BLK), jnp.int32),  # read buf
                pltpu.VMEM((c, BLK), jnp.int32),  # carL
                pltpu.VMEM((c, BLK), jnp.int32),  # carR
                pltpu.VMEM((2, c, BLK), jnp.int32),  # stageL
                pltpu.VMEM((2, c, BLK), jnp.int32),  # stageR
                pltpu.VMEM((c, BLK), jnp.int32),  # tmp (RMW)
                pltpu.SemaphoreType.DMA((2,)),  # rsem
                pltpu.SemaphoreType.DMA(()),  # csem
                pltpu.SemaphoreType.DMA((2,)),  # wsemL
                pltpu.SemaphoreType.DMA((2,)),  # wsemR
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct(p.shape, jnp.int32),
            jax.ShapeDtypeStruct(scratch.shape, jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ),
        input_output_aliases={2: 0, 3: 1},
        interpret=interpret,
    )(sv, tri, p, scratch)


# ======================================================================
# copy-back kernel (rights: scratch[0:cntR) -> P[dst: dst+cntR))
# ======================================================================
def _copyback_kernel(sref, s_in, p_in, p_any, buf, car, stage, tmp, rsem, csem, wsem, *, c):
    dst = sref[0]
    cntr = sref[1]
    base = pl.multiple_of((dst // BLK) * BLK, _LANE)
    head = dst - base
    nblk = (cntr + BLK - 1) // BLK
    s_any = s_in

    def get_read(slot, j):
        return pltpu.make_async_copy(
            s_any.at[:, pl.ds(j * BLK, BLK)], buf.at[slot], rsem.at[slot]
        )

    get_read(0, 0).start()
    pltpu.make_async_copy(p_any.at[:, pl.ds(base, BLK)], car, csem).start()
    pltpu.make_async_copy(p_any.at[:, pl.ds(base, BLK)], car, csem).wait()

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, BLK), 1)
    iota_d = jax.lax.broadcasted_iota(jnp.int32, (BLK, BLK), 0)
    # constant cyclic shift by `head`: src already compact, so rank = lane
    tgt = head + lane
    tgt = tgt - jnp.where(tgt >= BLK, BLK, 0)
    oh_shift = (iota_d == tgt).astype(jnp.bfloat16)

    def body(j, st):
        cl, fl = st
        slot = jax.lax.rem(j, 2)

        @pl.when(j + 1 < nblk)
        def _():
            get_read(1 - slot, j + 1).start()

        get_read(slot, j).wait()
        blk = buf[slot]
        n_in = jnp.minimum(cntr - j * BLK, BLK)
        planes = _planes(blk, c)
        valid = lane < n_in
        oh = jnp.where(valid, oh_shift, jnp.bfloat16(0.0))
        dots = jax.lax.dot_general(
            planes, oh, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        perm = _unplanes(dots, c)
        t = cl + n_in
        merged = jnp.where(lane < cl, car[:, :], perm)
        flush = t >= BLK

        @pl.when(flush)
        def _():
            _stream_flush(stage, wsem, p_any, merged, fl, base + fl * BLK)

        car[:, :] = jnp.where(flush, perm, merged)
        cl = jnp.where(flush, t - BLK, t)
        fl = fl + flush.astype(jnp.int32)
        return (cl, fl)

    cl, fl = jax.lax.fori_loop(0, nblk, body, (head, jnp.int32(0)), unroll=False)
    pltpu.make_async_copy(p_any.at[:, pl.ds(base + fl * BLK, BLK)], tmp, csem).start()
    pltpu.make_async_copy(p_any.at[:, pl.ds(base + fl * BLK, BLK)], tmp, csem).wait()
    merged = jnp.where(lane < cl, car[:, :], tmp[:, :])
    _stream_flush(stage, wsem, p_any, merged, fl, base + fl * BLK)
    _stream_drain(stage, wsem, fl + 1)


def _copyback_call(p, scratch, sv, interpret=False):
    c = p.shape[0]
    return pl.pallas_call(
        functools.partial(_copyback_kernel, c=c),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),  # scratch (read)
                pl.BlockSpec(memory_space=pl.ANY),  # P (alias)
            ],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[
                pltpu.VMEM((2, c, BLK), jnp.int32),
                pltpu.VMEM((c, BLK), jnp.int32),  # carry
                pltpu.VMEM((2, c, BLK), jnp.int32),  # stage
                pltpu.VMEM((c, BLK), jnp.int32),  # tmp
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA(()),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(p.shape, jnp.int32),
        input_output_aliases={2: 0},
        interpret=interpret,
    )(sv, scratch, p)


@functools.partial(jax.jit, static_argnames=("bits", "interpret"))
def partition_segment(p, scratch, start, cnt, word, shift, zero_bin, dbz, thr, is_cat,
                      off_lo=0, off_hi=256, bias=0, bits=8, interpret=False):
    """Stable-partition the leaf segment [start, start+cnt) of ``p`` by
    the split predicate (DataPartition::Split, data_partition.hpp:94-150,
    fused with the DefaultValueForZero bin remap of dense_bin.hpp:191-232).

    Lefts land at [start, start+nl), rights at [start+nl, start+cnt),
    in place.  Returns (p', scratch', nl)."""
    sv = jnp.stack(
        [
            jnp.int32(start), jnp.int32(cnt), jnp.int32(word), jnp.int32(shift),
            jnp.int32(zero_bin), jnp.int32(dbz), jnp.int32(thr), jnp.int32(is_cat),
            jnp.int32(off_lo), jnp.int32(off_hi), jnp.int32(bias),
        ]
    )
    tri = _get_tri()
    p, scratch, nl = _partition_call(p, scratch, tri, sv, bits=bits, interpret=interpret)
    nl = nl[0]
    cntr = cnt - nl
    sv2 = jnp.stack([jnp.int32(start) + nl, cntr])
    p = _copyback_call(p, scratch, sv2, interpret=interpret)
    return p, scratch, nl


# ======================================================================
# pure-XLA reference implementations (CPU tests / documentation)
# ======================================================================
def unpack_bins(p, layout: PLayout, n: int) -> jnp.ndarray:
    """(N, F) uint8 bins recovered from the packed words (test helper)."""
    w = layout.W
    words = p[:w, :n]  # (W, N)
    mask = (1 << layout.bits) - 1
    cols = []
    for f in range(layout.F):
        wd, p4 = divmod(f, layout.per)
        cols.append((words[wd] >> (p4 * layout.bits)) & mask)
    return jnp.stack(cols, axis=1).astype(jnp.uint8)


def hist_ref(p, start: int, cnt: int, layout: PLayout, num_bins: int) -> jnp.ndarray:
    """Reference (XLA) histogram of a segment — same contract as hist_dyn."""
    from .histogram import build_histogram

    seg = p[:, start : start + cnt]
    bins = unpack_bins(seg, layout, cnt)
    g = jax.lax.bitcast_convert_type(seg[layout.G], jnp.float32)
    h = jax.lax.bitcast_convert_type(seg[layout.H], jnp.float32)
    sel = jax.lax.bitcast_convert_type(seg[layout.SEL], jnp.float32)
    return build_histogram(bins, g, h, sel, num_bins)


def partition_ref(p, start: int, cnt: int, feat: int, zero_bin: int, dbz: int, thr: int, is_cat: bool, layout: PLayout):
    """Reference (numpy) stable partition — same contract as
    partition_segment."""
    pn = np.asarray(p)
    seg = pn[:, start : start + cnt]
    wd, p4 = divmod(feat, layout.per)
    binv = (seg[wd] >> (p4 * layout.bits)) & ((1 << layout.bits) - 1)
    fv = np.where(binv == zero_bin, dbz, binv)
    gl = (fv == thr) if is_cat else (fv <= thr)
    out = np.concatenate([seg[:, gl], seg[:, ~gl]], axis=1)
    pn = pn.copy()
    pn[:, start : start + cnt] = out
    return jnp.asarray(pn), int(gl.sum())
