"""Batched tree traversal — counterpart of Tree::Predict / GetLeaf
(include/LightGBM/tree.h:232-276) and Tree::AddPredictionToScore
(src/io/tree.cpp:107-260).

The reference walks one record at a time through pointer-chasing nodes;
here the whole batch walks in lockstep: a (N,) node-index vector advances
one level per ``while_loop`` step via gathers into the SoA node arrays.
Trees are stacked on a leading axis and vmapped, so a full model predicts
in one compiled program.

Two variants:
- ``predict_binned`` traverses with bin-space thresholds over the binned
  (N, F) matrix — used for train/valid score updates, where the data is
  already binned with the model's own mappers (exactly the semantics of
  the reference's score updater which predicts on the training Dataset).
- ``predict_raw`` traverses with real-valued thresholds over raw features,
  with the zero/missing remap DefaultValueForZero (tree.h:147-161).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..io.binning import MISSING_VALUE_RANGE


class TreeArrays:
    """Stacked SoA node arrays for T trees, padded to M = max nodes.

    Built host-side by model/gbdt_model.py. A tree with num_leaves == 1
    must have node 0 as (left=~0, right=~0) and leaf_value[0] = its
    constant output (0 for an empty tree).
    """

    FIELDS = (
        "split_feature",  # (T, M) int32 — inner (binned) feature for binned path
        "split_feature_real",  # (T, M) int32 — original feature for raw path
        "threshold_bin",  # (T, M) int32
        "threshold_real",  # (T, M) f32
        "zero_bin",  # (T, M) int32
        "default_bin_for_zero",  # (T, M) int32
        "default_value_real",  # (T, M) f32
        "is_categorical",  # (T, M) bool
        "left_child",  # (T, M) int32  (>=0 node, <0 → leaf ~idx)
        "right_child",  # (T, M) int32
        "leaf_value",  # (T, L) f32 (post-shrinkage)
    )

    def __init__(self, **kw):
        for f in self.FIELDS:
            setattr(self, f, kw[f])

    def tree_tuple(self):
        return tuple(getattr(self, f) for f in self.FIELDS)


def _traverse_one_tree_binned(bins, feat, thr_bin, zero_bin, dbz, is_cat, left, right):
    """(N,) leaf indices for one tree over binned data."""
    n = bins.shape[0]
    rows = jnp.arange(n)

    def cond(node):
        return jnp.any(node >= 0)

    def step(node):
        j = jnp.maximum(node, 0)
        col = bins[rows, feat[j]].astype(jnp.int32)
        fval = jnp.where(col == zero_bin[j], dbz[j], col)
        goes_left = jnp.where(is_cat[j], fval == thr_bin[j], fval <= thr_bin[j])
        nxt = jnp.where(goes_left, left[j], right[j])
        return jnp.where(node >= 0, nxt, node)

    node = jnp.zeros((n,), jnp.int32)
    node = jax.lax.while_loop(cond, step, node)
    return ~node  # leaf index


def _traverse_one_tree_raw(data, feat, thr, default_value, is_cat, left, right):
    n = data.shape[0]
    rows = jnp.arange(n)

    def cond(node):
        return jnp.any(node >= 0)

    def step(node):
        j = jnp.maximum(node, 0)
        v = data[rows, feat[j]]
        # DefaultValueForZero: |v| in (-range, range] → default_value
        is_zero = (v > -MISSING_VALUE_RANGE) & (v <= MISSING_VALUE_RANGE)
        is_zero = is_zero | jnp.isnan(v)  # NaN rides the zero bin (ValueToBin)
        fval = jnp.where(is_zero, default_value[j], v)
        goes_left = jnp.where(is_cat[j], fval.astype(jnp.int32) == thr[j].astype(jnp.int32), fval <= thr[j])
        nxt = jnp.where(goes_left, left[j], right[j])
        return jnp.where(node >= 0, nxt, node)

    node = jnp.zeros((n,), jnp.int32)
    node = jax.lax.while_loop(cond, step, node)
    return ~node


@jax.jit
def predict_binned(bins, split_feature, threshold_bin, zero_bin, default_bin_for_zero,
                   is_categorical, left_child, right_child, leaf_value):
    """Sum of leaf outputs over stacked trees, binned traversal.

    All tree arrays are (T, M)/(T, L); returns (N,) f32 scores.
    """
    leaves = jax.vmap(
        _traverse_one_tree_binned, in_axes=(None, 0, 0, 0, 0, 0, 0, 0)
    )(bins, split_feature, threshold_bin, zero_bin, default_bin_for_zero,
      is_categorical, left_child, right_child)  # (T, N)
    vals = jnp.take_along_axis(leaf_value, leaves, axis=1)  # (T, N)
    return jnp.sum(vals, axis=0)


@jax.jit
def predict_leaf_binned(bins, split_feature, threshold_bin, zero_bin,
                        default_bin_for_zero, is_categorical, left_child, right_child):
    """(T, N) leaf indices (PredictLeafIndex mode)."""
    return jax.vmap(
        _traverse_one_tree_binned, in_axes=(None, 0, 0, 0, 0, 0, 0, 0)
    )(bins, split_feature, threshold_bin, zero_bin, default_bin_for_zero,
      is_categorical, left_child, right_child)


@jax.jit
def predict_raw(data, split_feature_real, threshold_real, default_value_real,
                is_categorical, left_child, right_child, leaf_value):
    """(N,) raw scores over real-valued features."""
    leaves = jax.vmap(
        _traverse_one_tree_raw, in_axes=(None, 0, 0, 0, 0, 0, 0)
    )(data, split_feature_real, threshold_real, default_value_real,
      is_categorical, left_child, right_child)
    vals = jnp.take_along_axis(leaf_value, leaves, axis=1)
    return jnp.sum(vals, axis=0)


@functools.partial(jax.jit, static_argnames=())
def add_leaf_outputs(scores, leaf_id, leaf_outputs):
    """Train-score update: scores += leaf_outputs[leaf_id]
    (ScoreUpdater::AddScore via the learner's data partition,
    score_updater.hpp:68-88 — here a single gather since leaf_id[N] is the
    partition)."""
    return scores + leaf_outputs[leaf_id]
