"""Batched tree traversal — counterpart of Tree::Predict / GetLeaf
(include/LightGBM/tree.h:232-276) and Tree::AddPredictionToScore
(src/io/tree.cpp:107-260).

The reference walks one record at a time through pointer-chasing nodes;
here the whole batch walks in lockstep: a (N,) node-index vector advances
one level per ``while_loop`` step via gathers into the SoA node arrays.
Trees are stacked on a leading axis and vmapped, so a full model predicts
in one compiled program.

Two variants:
- ``predict_binned`` traverses with bin-space thresholds over the binned
  (N, F) matrix — used for train/valid score updates, where the data is
  already binned with the model's own mappers (exactly the semantics of
  the reference's score updater which predicts on the training Dataset).
- ``predict_raw`` traverses with real-valued thresholds over raw features,
  with the zero/missing remap DefaultValueForZero (tree.h:147-161).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..io.binning import MISSING_VALUE_RANGE


def _le3(ah, al, al2, bh, bl, bl2):
    """Lexicographic ``a <= b`` over triple-float planes — exact f64
    semantics (see model/ensemble.py split_hi_lo)."""
    return (
        (ah < bh)
        | ((ah == bh) & (al < bl))
        | ((ah == bh) & (al == bl) & (al2 <= bl2))
    )


# triple-float planes of kMissingValueRange so the zero/missing-range
# test itself is f64-exact (a double just above the range must NOT be
# remapped merely because its f32 rounding lands inside it)
import numpy as _np

_MR = float(MISSING_VALUE_RANGE)
_MR_HI = _np.float32(_MR)
_MR_LO = _np.float32(_MR - float(_MR_HI))
_MR_LO2 = _np.float32(_MR - float(_MR_HI) - float(_MR_LO))


class TreeArrays:
    """Stacked SoA node arrays for T trees, padded to M = max nodes.

    Built host-side from ``model/ensemble.stack_trees`` output (see
    serve/artifact.py).  A tree with num_leaves == 1 must have node 0 as
    (left=~0, right=~0) and leaf_value[0] = its constant output (0 for
    an empty tree).
    """

    FIELDS = (
        "split_feature",  # (T, M) int32 — inner (binned) feature for binned path
        "split_feature_real",  # (T, M) int32 — original feature for raw path
        "threshold_bin",  # (T, M) int32
        "threshold_real",  # (T, M) f32 hi plane
        "threshold_real_lo",  # (T, M) f32 lo plane (triple-float compare)
        "threshold_real_lo2",  # (T, M) f32 lo2 plane
        "zero_bin",  # (T, M) int32
        "default_bin_for_zero",  # (T, M) int32
        "default_value_real",  # (T, M) f32 hi plane
        "default_value_real_lo",  # (T, M) f32 lo plane
        "default_value_real_lo2",  # (T, M) f32 lo2 plane
        "is_categorical",  # (T, M) bool
        "left_child",  # (T, M) int32  (>=0 node, <0 → leaf ~idx)
        "right_child",  # (T, M) int32
        "leaf_value",  # (T, L) f32 (post-shrinkage)
    )

    def __init__(self, **kw):
        for f in self.FIELDS:
            setattr(self, f, kw[f])

    def tree_tuple(self):
        return tuple(getattr(self, f) for f in self.FIELDS)

    def validate(self) -> "TreeArrays":
        """Check every field is 2-D and the shapes agree: (T, M) for the
        node planes, (T, L) for ``leaf_value``.  Raises ValueError naming
        the first offending field (a shape mismatch here would otherwise
        surface as an opaque gather error inside the jitted traversal)."""
        t_m = None
        for f in self.FIELDS:
            a = getattr(self, f)
            shape = tuple(getattr(a, "shape", ()))
            if len(shape) != 2:
                raise ValueError(
                    f"TreeArrays.{f} must be 2-D, got shape {shape}")
            if f == "leaf_value":
                if t_m is not None and shape[0] != t_m[0]:
                    raise ValueError(
                        f"TreeArrays.leaf_value has {shape[0]} trees but the "
                        f"node arrays have {t_m[0]}")
            elif t_m is None:
                t_m = shape
            elif shape != t_m:
                raise ValueError(
                    f"TreeArrays.{f} has shape {shape}, expected {t_m} "
                    f"(T, M) like the other node arrays")
        return self


class LinearTreeArrays(TreeArrays):
    """TreeArrays + the linear-leaf coefficient planes of the v3 serving
    artifact (tree/linear.py plug-in, model/ensemble.py stacking).

    The raw serve path evaluates the per-leaf linear model over the hi
    f32 plane of the gathered path features (training fitted against
    f32 bin representatives, so f32 serve arithmetic is within the
    documented drift contract, docs/TREES.md); rows with a NaN path
    feature fall back to the leaf constant — LightGBM's linear-tree
    missing semantics."""

    LINEAR_FIELDS = (
        "leaf_feat_real",  # (T, L, K) int32 — raw-path gather index
        "leaf_feat_valid",  # (T, L, K) f32 0/1 — padded-slot mask
        "leaf_coeff",  # (T, L, K) f32 (post-shrinkage)
        "leaf_const",  # (T, L) f32 (post-shrinkage)
        "leaf_is_linear",  # (T, L) bool
    )
    FIELDS = TreeArrays.FIELDS + LINEAR_FIELDS

    def validate(self) -> "LinearTreeArrays":
        """The node/leaf planes validate as 2-D via the base class; the
        coefficient planes are (T, L, K) so they're checked here."""
        three_d = ("leaf_feat_real", "leaf_feat_valid", "leaf_coeff")
        tlk = None
        for f in three_d:
            a = getattr(self, f)
            shape = tuple(getattr(a, "shape", ()))
            if len(shape) != 3:
                raise ValueError(
                    f"LinearTreeArrays.{f} must be 3-D (T, L, K), "
                    f"got shape {shape}")
            if tlk is None:
                tlk = shape
            elif shape != tlk:
                raise ValueError(
                    f"LinearTreeArrays.{f} has shape {shape}, expected "
                    f"{tlk} like the other coefficient planes")
        base = TreeArrays(**{f: getattr(self, f)
                             for f in TreeArrays.FIELDS})
        base.validate()
        for f in ("leaf_const", "leaf_is_linear"):
            shape = tuple(getattr(getattr(self, f), "shape", ()))
            if len(shape) != 2:
                raise ValueError(
                    f"LinearTreeArrays.{f} must be 2-D (T, L), "
                    f"got shape {shape}")
        return self


def _traverse_one_tree_binned(bins, feat, thr_bin, zero_bin, dbz, is_cat, left, right):
    """(N,) leaf indices for one tree over binned data."""
    n = bins.shape[0]
    rows = jnp.arange(n)

    def cond(node):
        return jnp.any(node >= 0)

    def step(node):
        j = jnp.maximum(node, 0)
        col = bins[rows, feat[j]].astype(jnp.int32)
        fval = jnp.where(col == zero_bin[j], dbz[j], col)
        goes_left = jnp.where(is_cat[j], fval == thr_bin[j], fval <= thr_bin[j])
        nxt = jnp.where(goes_left, left[j], right[j])
        return jnp.where(node >= 0, nxt, node)

    node = jnp.zeros((n,), jnp.int32)
    node = jax.lax.while_loop(cond, step, node)
    return ~node  # leaf index


def _traverse_one_tree_raw(data_hi, data_lo, data_lo2, feat,
                           thr_hi, thr_lo, thr_lo2,
                           dv_hi, dv_lo, dv_lo2, is_cat, left, right):
    """Raw traversal with triple-float (hi, lo, lo2) planes.

    The reference decides in float64 (NumericalDecision<double>,
    tree.h:139-145); TPU f32 alone flips rows whose value is within f32
    rounding of a threshold.  A lexicographic compare over normalized
    (hi, lo, lo2) triples reproduces the double ``<=`` exactly
    (see model/ensemble.py split_hi_lo).  Categorical identity uses the
    hi plane only — category ids are small exact integers."""
    n = data_hi.shape[0]
    rows = jnp.arange(n)

    def cond(node):
        return jnp.any(node >= 0)

    def step(node):
        j = jnp.maximum(node, 0)
        v_hi = data_hi[rows, feat[j]]
        v_lo = data_lo[rows, feat[j]]
        v_lo2 = data_lo2[rows, feat[j]]
        # DefaultValueForZero: |v| in (-range, range] → default_value,
        # with the range test itself done in triple-float (f64-exact)
        gt_neg = ~_le3(v_hi, v_lo, v_lo2, -_MR_HI, -_MR_LO, -_MR_LO2)
        le_pos = _le3(v_hi, v_lo, v_lo2, _MR_HI, _MR_LO, _MR_LO2)
        is_zero = gt_neg & le_pos
        is_zero = is_zero | jnp.isnan(v_hi)  # NaN rides the zero bin (ValueToBin)
        f_hi = jnp.where(is_zero, dv_hi[j], v_hi)
        f_lo = jnp.where(is_zero, dv_lo[j], v_lo)
        f_lo2 = jnp.where(is_zero, dv_lo2[j], v_lo2)
        le = _le3(f_hi, f_lo, f_lo2, thr_hi[j], thr_lo[j], thr_lo2[j])
        t_hi = thr_hi[j]
        goes_left = jnp.where(
            is_cat[j], f_hi.astype(jnp.int32) == t_hi.astype(jnp.int32), le
        )
        nxt = jnp.where(goes_left, left[j], right[j])
        return jnp.where(node >= 0, nxt, node)

    node = jnp.zeros((n,), jnp.int32)
    node = jax.lax.while_loop(cond, step, node)
    return ~node


@jax.jit
def predict_binned(bins, split_feature, threshold_bin, zero_bin, default_bin_for_zero,
                   is_categorical, left_child, right_child, leaf_value):
    """Sum of leaf outputs over stacked trees, binned traversal.

    All tree arrays are (T, M)/(T, L); returns (N,) f32 scores.
    """
    leaves = jax.vmap(
        _traverse_one_tree_binned, in_axes=(None, 0, 0, 0, 0, 0, 0, 0)
    )(bins, split_feature, threshold_bin, zero_bin, default_bin_for_zero,
      is_categorical, left_child, right_child)  # (T, N)
    vals = jnp.take_along_axis(leaf_value, leaves, axis=1)  # (T, N)
    return jnp.sum(vals, axis=0)


@jax.jit
def predict_leaf_binned(bins, split_feature, threshold_bin, zero_bin,
                        default_bin_for_zero, is_categorical, left_child, right_child):
    """(T, N) leaf indices (PredictLeafIndex mode)."""
    return jax.vmap(
        _traverse_one_tree_binned, in_axes=(None, 0, 0, 0, 0, 0, 0, 0)
    )(bins, split_feature, threshold_bin, zero_bin, default_bin_for_zero,
      is_categorical, left_child, right_child)


@jax.jit
def predict_raw(data_hi, data_lo, data_lo2, split_feature_real, threshold_real,
                threshold_real_lo, threshold_real_lo2,
                default_value_real, default_value_real_lo, default_value_real_lo2,
                is_categorical, left_child, right_child, leaf_value):
    """(N,) raw scores over real-valued features (triple-float planes)."""
    leaves = jax.vmap(
        _traverse_one_tree_raw,
        in_axes=(None, None, None, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0),
    )(data_hi, data_lo, data_lo2, split_feature_real,
      threshold_real, threshold_real_lo, threshold_real_lo2,
      default_value_real, default_value_real_lo, default_value_real_lo2,
      is_categorical, left_child, right_child)
    vals = jnp.take_along_axis(leaf_value, leaves, axis=1)
    return jnp.sum(vals, axis=0)


@jax.jit
def predict_raw_linear(data_hi, data_lo, data_lo2, split_feature_real,
                       threshold_real, threshold_real_lo,
                       threshold_real_lo2, default_value_real,
                       default_value_real_lo, default_value_real_lo2,
                       is_categorical, left_child, right_child, leaf_value,
                       leaf_feat_real, leaf_feat_valid, leaf_coeff,
                       leaf_const, leaf_is_linear):
    """(N,) raw scores with per-leaf linear models (v3 artifacts).

    Traversal is identical to ``predict_raw`` (triple-float compares);
    the leaf output is ``const + coeff . x`` over the RAW hi-plane path
    features for linear leaves, the constant ``leaf_value`` otherwise.
    A row with a NaN (missing) path feature degrades to the constant —
    the linear fit never saw missing rows' imputed values, so the
    constant is the only output the training distribution covered."""
    leaves = jax.vmap(
        _traverse_one_tree_raw,
        in_axes=(None, None, None, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0),
    )(data_hi, data_lo, data_lo2, split_feature_real,
      threshold_real, threshold_real_lo, threshold_real_lo2,
      default_value_real, default_value_real_lo, default_value_real_lo2,
      is_categorical, left_child, right_child)  # (T, N)

    def one_tree(lv, lval_t, lfeat, lvalid, lcoef, lconst, lisl):
        fi = lfeat[lv]  # (N, K)
        valid = lvalid[lv]  # (N, K)
        x = jnp.take_along_axis(data_hi, fi, axis=1) * valid
        bad = jnp.any(jnp.isnan(x) & (valid > 0), axis=1)
        lin = lconst[lv] + jnp.sum(lcoef[lv] * jnp.where(
            jnp.isnan(x), 0.0, x), axis=1)
        use_lin = lisl[lv] & ~bad
        return jnp.where(use_lin, lin, lval_t[lv])

    vals = jax.vmap(one_tree)(leaves, leaf_value, leaf_feat_real,
                              leaf_feat_valid, leaf_coeff, leaf_const,
                              leaf_is_linear)  # (T, N)
    return jnp.sum(vals, axis=0)


@jax.jit
def add_leaf_outputs(scores, leaf_id, leaf_outputs):
    """Train-score update: scores += leaf_outputs[leaf_id]
    (ScoreUpdater::AddScore via the learner's data partition,
    score_updater.hpp:68-88 — here a single gather since leaf_id[N] is the
    partition)."""
    return scores + leaf_outputs[leaf_id]
