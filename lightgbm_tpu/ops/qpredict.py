"""Quantized batched tree traversal — the serving-only narrow-int path.

``ops/predict.predict_raw`` reproduces the reference's float64 decisions
with a triple-float (3 x f32 plane) lexicographic compare at every node:
three (N, F) data-plane gathers and nine comparisons per step.  But a
trained model only ever compares a feature against the *finite set* of
thresholds its own nodes hold, so the whole decision structure survives
rank quantization: map every value to its integer rank among the
feature's thresholds and one int16 compare per node decides routing
EXACTLY as the f64 reference does.

Encoding (per feature, host-side, float64 throughout):

  ``table`` = sorted distinct thresholds the model's nodes use on this
  feature (categorical features store ``trunc(threshold)``, matching the
  reference's integer-cast identity compare).  A value ``v`` encodes as

      code(v) = 2 * searchsorted(table, v, side="left") + (v in table)

  so a node threshold ``t = table[i]`` gets the odd code ``2i + 1`` and

      numeric:      code(v) <= 2i + 1  <=>  v <= t      (exactly)
      categorical:  code(v) == 2i + 1  <=>  v == t      (exactly)

  Zero/missing rows (the DefaultValueForZero remap, plus NaN) get the
  sentinel ``ZERO_CODE``; each node carries ``default_q``, its
  ``default_value`` pre-encoded in f64 on the host, so the remap is a
  single integer select.  There is no "bin boundary" caveat: route
  decisions agree with the exact path for every input.

The node SoA is narrowed to int16/int8 (codes are bounded by twice the
per-feature threshold count, far under 2**15 for any ``max_bin``-built
model) and **level-packed**: nodes are reordered breadth-first so each
depth level is a contiguous index range and the maximum depth is a
static ``levels`` bound, letting traversal run as a ``fori_loop`` with
no per-step cross-batch ``any()`` reduction (the ``while_loop`` exit
test the exact path pays every level).  Leaf values are stored f16 (or
bf16) and accumulated in f32 — the ONLY source of drift vs the exact
path, bounded by ``drift_bound``.
"""

from __future__ import annotations

import os
from functools import partial
from typing import List, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..io.binning import MISSING_VALUE_RANGE
from ..utils.log import Log

# data code for zero/missing rows (never a valid rank code, which are >= 0)
ZERO_CODE = np.int16(-1)

# widest representable rank code / node index / feature index
_I16_MAX = 32767

LEAF_DTYPES = ("float16", "bfloat16")


def quant_predict_enabled(default: bool = False) -> bool:
    """The ``LIGHTGBM_TPU_QUANT_PREDICT`` pin, read live per call:
    ``0`` forces the exact path everywhere (the documented opt-out),
    ``1`` opts ``Booster.predict`` / serving into the quantized path,
    unset defers to the caller's ``default``."""
    v = os.environ.get("LIGHTGBM_TPU_QUANT_PREDICT")
    if v is None:
        return bool(default)
    return v.strip().lower() not in ("0", "false", "off", "")


def _leaf_np_dtype(leaf_dtype: str):
    if leaf_dtype == "float16":
        return np.float16
    if leaf_dtype == "bfloat16":
        import ml_dtypes  # ships with jax

        return ml_dtypes.bfloat16
    Log.fatal("Unsupported quantized leaf dtype %r (supported: %s)",
              leaf_dtype, ", ".join(LEAF_DTYPES))


class QTreeArrays:
    """Stacked quantized SoA for T trees: narrow node planes plus the
    host-side per-feature threshold tables that encode request data.

    ``levels`` is the static traversal bound (1 + max node depth); the
    compile cache pads it up the same power-of-two ladder as M/L so
    same-shape-class models share every XLA program.
    """

    NODE_FIELDS = (
        "split_feature",  # (T, M) int16 — original feature index
        "threshold_q",  # (T, M) int16 — odd rank code of the threshold
        "default_q",  # (T, M) int16 — rank code of default_value
        "flags",  # (T, M) int8 — bit0: categorical
        "left_child",  # (T, M) int16 (>=0 node, <0 -> leaf ~idx)
        "right_child",  # (T, M) int16
        "leaf_value",  # (T, L) f16/bf16 (post-shrinkage)
    )
    TABLE_FIELDS = (
        "qbin_edges",  # (E,) f64 — per-feature tables, flattened
        "qbin_offsets",  # (F+1,) int32 — table j is edges[off[j]:off[j+1]]
        "feature_flags",  # (F,) int8 — bit0: categorical compare (trunc)
    )
    FIELDS = NODE_FIELDS + TABLE_FIELDS

    def __init__(self, levels: int, **kw):
        self.levels = int(levels)
        for f in self.FIELDS:
            setattr(self, f, kw[f])

    @property
    def leaf_dtype(self) -> str:
        return str(jnp.dtype(self.leaf_value.dtype).name)

    def validate(self) -> "QTreeArrays":
        t_m = None
        for f in self.NODE_FIELDS:
            a = getattr(self, f)
            shape = tuple(getattr(a, "shape", ()))
            if len(shape) != 2:
                raise ValueError(
                    f"QTreeArrays.{f} must be 2-D, got shape {shape}")
            if f == "leaf_value":
                if t_m is not None and shape[0] != t_m[0]:
                    raise ValueError(
                        f"QTreeArrays.leaf_value has {shape[0]} trees but "
                        f"the node arrays have {t_m[0]}")
                if self.leaf_dtype not in LEAF_DTYPES:
                    raise ValueError(
                        f"QTreeArrays.leaf_value dtype {self.leaf_dtype} "
                        f"is not one of {LEAF_DTYPES}")
            elif t_m is None:
                t_m = shape
            elif shape != t_m:
                raise ValueError(
                    f"QTreeArrays.{f} has shape {shape}, expected {t_m}")
        off = np.asarray(self.qbin_offsets)
        edges = np.asarray(self.qbin_edges)
        if off.ndim != 1 or off.size < 1 or off[0] != 0 \
                or off[-1] != edges.size or np.any(np.diff(off) < 0):
            raise ValueError(
                "QTreeArrays.qbin_offsets must be a monotone prefix-sum "
                "ending at len(qbin_edges)")
        if np.asarray(self.feature_flags).shape != (off.size - 1,):
            raise ValueError(
                "QTreeArrays.feature_flags must have one entry per feature")
        if self.levels < 1:
            raise ValueError("QTreeArrays.levels must be >= 1")
        return self

    @property
    def num_features(self) -> int:
        return int(np.asarray(self.qbin_offsets).size - 1)


def _encode(table: np.ndarray, v: np.ndarray) -> np.ndarray:
    """Rank codes (int64) of ``v`` against one sorted threshold table."""
    v = np.asarray(v, np.float64)
    i = np.searchsorted(table, v, side="left")
    exact = (i < table.size) & (table[np.minimum(i, table.size - 1)] == v) \
        if table.size else np.zeros(v.shape, bool)
    return 2 * i + exact


def _bfs_order(left: np.ndarray, right: np.ndarray) -> Tuple[np.ndarray, int]:
    """Breadth-first node order for one tree (root = node 0).

    Returns the visit order (depth-major, unreachable padded slots
    appended last so array shapes are preserved) and 1 + max depth."""
    m = left.shape[0]
    depth = np.full(m, -1, np.int64)
    order: List[int] = []
    frontier = [0]
    depth[0] = 0
    d = 0
    while frontier:
        order.extend(frontier)
        nxt = []
        for j in frontier:
            for c in (left[j], right[j]):
                if c >= 0 and depth[c] < 0:
                    depth[c] = d + 1
                    nxt.append(int(c))
        frontier = nxt
        d += 1
    levels = int(depth.max()) + 1
    order.extend(j for j in range(m) if depth[j] < 0)
    return np.asarray(order, np.int64), levels


def quantize_tree_arrays(arrays, leaf_dtype: str = "float16",
                         num_features: int = 0) -> QTreeArrays:
    """Quantize an exact host-side ``TreeArrays`` into a ``QTreeArrays``.

    The f64 thresholds/default values are recovered exactly from the
    triple-float planes (hi + lo + lo2 sums back to the original double
    with no rounding — the planes are non-overlapping by construction),
    so quantizing a loaded artifact is as lossless as quantizing the
    Booster itself.
    """
    feat = np.asarray(arrays.split_feature_real, np.int64)
    thr = (np.asarray(arrays.threshold_real, np.float64)
           + np.asarray(arrays.threshold_real_lo, np.float64)
           + np.asarray(arrays.threshold_real_lo2, np.float64))
    dv = (np.asarray(arrays.default_value_real, np.float64)
          + np.asarray(arrays.default_value_real_lo, np.float64)
          + np.asarray(arrays.default_value_real_lo2, np.float64))
    is_cat = np.asarray(arrays.is_categorical, bool)
    left = np.asarray(arrays.left_child, np.int64)
    right = np.asarray(arrays.right_child, np.int64)
    leaf = np.asarray(arrays.leaf_value, np.float32)

    t, m = feat.shape
    if m > _I16_MAX:
        Log.fatal(
            "Quantized serving supports at most %d nodes per tree, this "
            "model has %d — serve the exact artifact instead", _I16_MAX, m)
    num_features = max(int(feat.max()) + 1 if t else 1, int(num_features))
    if num_features > _I16_MAX:
        Log.fatal(
            "Quantized serving supports at most %d features, this model "
            "uses feature index %d — serve the exact artifact instead",
            _I16_MAX, num_features - 1)

    # reachable internal nodes + breadth-first level packing, per tree
    orders = np.empty((t, m), np.int64)
    reach = np.zeros((t, m), bool)
    levels = 1
    for i in range(t):
        order, lv = _bfs_order(left[i], right[i])
        orders[i] = order
        levels = max(levels, lv)
        # _bfs_order appends unreachable padding slots after the visited
        # prefix; the visited count = nodes with a BFS depth
        seen = np.zeros(m, bool)
        seen[0] = True
        stack = [0]
        while stack:
            j = stack.pop()
            for c in (left[i, j], right[i, j]):
                if c >= 0 and not seen[c]:
                    seen[c] = True
                    stack.append(int(c))
        reach[i] = seen

    # per-feature threshold tables from reachable nodes only, with the
    # categorical trunc transform folded in (identity compare on ints)
    feature_flags = np.zeros(num_features, np.int8)
    for j in np.unique(feat[reach & is_cat]):
        feature_flags[j] = 1
    tables: List[np.ndarray] = []
    offsets = np.zeros(num_features + 1, np.int32)
    for j in range(num_features):
        mask = reach & (feat == j)
        tj = thr[mask]
        if feature_flags[j]:
            tj = np.trunc(tj)
        table = np.unique(tj)
        if 2 * table.size + 1 > _I16_MAX:
            Log.fatal(
                "Quantized serving supports at most %d distinct "
                "thresholds per feature, feature %d has %d — serve the "
                "exact artifact instead", (_I16_MAX - 1) // 2, j, table.size)
        tables.append(table)
        offsets[j + 1] = offsets[j] + table.size
    edges = np.concatenate(tables) if tables else np.zeros(0, np.float64)

    # encode every node's threshold/default vectorized per feature, in
    # the ORIGINAL node order (the BFS gather below reorders them)
    thr_codes = np.zeros((t, m), np.int64)
    def_codes = np.zeros((t, m), np.int64)
    for j in range(num_features):
        mask = feat == j
        if not mask.any():
            continue
        tv, dvv = thr[mask], dv[mask]
        if feature_flags[j]:
            tv, dvv = np.trunc(tv), np.trunc(dvv)
        thr_codes[mask] = _encode(tables[j], tv)
        def_codes[mask] = _encode(tables[j], dvv)

    # gather per-node fields into BFS order; remap child node indices
    q_feat = np.zeros((t, m), np.int16)
    q_thr = np.zeros((t, m), np.int16)
    q_def = np.zeros((t, m), np.int16)
    q_flags = np.zeros((t, m), np.int8)
    q_left = np.zeros((t, m), np.int16)
    q_right = np.zeros((t, m), np.int16)
    for i in range(t):
        order = orders[i]
        newpos = np.empty(m, np.int64)
        newpos[order] = np.arange(m)
        q_feat[i] = feat[i, order].astype(np.int16)
        q_thr[i] = thr_codes[i, order].astype(np.int16)
        q_def[i] = def_codes[i, order].astype(np.int16)
        q_flags[i] = is_cat[i, order].astype(np.int8)
        lo_ = left[i, order]
        ro_ = right[i, order]
        q_left[i] = np.where(lo_ >= 0, newpos[np.maximum(lo_, 0)],
                             lo_).astype(np.int16)
        q_right[i] = np.where(ro_ >= 0, newpos[np.maximum(ro_, 0)],
                              ro_).astype(np.int16)

    return QTreeArrays(
        levels=levels,
        split_feature=q_feat,
        threshold_q=q_thr,
        default_q=q_def,
        flags=q_flags,
        left_child=q_left,
        right_child=q_right,
        leaf_value=leaf.astype(_leaf_np_dtype(leaf_dtype)),
        qbin_edges=edges,
        qbin_offsets=offsets,
        feature_flags=feature_flags,
    ).validate()


def quantize_data(data: np.ndarray, qbin_edges: np.ndarray,
                  qbin_offsets: np.ndarray,
                  feature_flags: np.ndarray) -> np.ndarray:
    """(N, F) int16 rank codes for raw (N, >=F) float64 features.

    The zero/missing remap happens HERE, in plain f64 (``|v|`` inside
    (-MISSING_VALUE_RANGE, MISSING_VALUE_RANGE] or NaN -> ``ZERO_CODE``)
    — host binning sees the original doubles, so the test needs no
    triple-float reconstruction like the exact device path does."""
    edges = np.asarray(qbin_edges, np.float64)
    offsets = np.asarray(qbin_offsets, np.int64)
    flags = np.asarray(feature_flags)
    nf = offsets.size - 1
    data = np.asarray(data, np.float64)
    if data.ndim == 1:
        data = data.reshape(1, -1)
    out = np.empty((data.shape[0], nf), np.int16)
    mr = float(MISSING_VALUE_RANGE)
    for j in range(nf):
        v = data[:, j]
        is_zero = ((v > -mr) & (v <= mr)) | np.isnan(v)
        vv = np.where(is_zero, 0.0, v)
        if flags[j]:
            vv = np.trunc(vv)
        code = _encode(edges[offsets[j]:offsets[j + 1]], vv)
        out[:, j] = np.where(is_zero, ZERO_CODE, code).astype(np.int16)
    return out


def drift_bound(leaf_value, leaf_dtype: str = "float16") -> float:
    """Documented bound on |quantized - exact| raw scores for one class
    of stacked trees: route decisions are exact, so the only drift is
    the leaf-value narrowing (half an ulp of each tree's largest |leaf|
    in the target dtype) plus f32 re-accumulation slack."""
    leaf = np.abs(np.asarray(leaf_value, np.float64))
    if leaf.size == 0:
        return 0.0
    maxabs = leaf.max(axis=-1)
    dt = _leaf_np_dtype(leaf_dtype)
    half_ulp = np.float64(np.spacing(maxabs.astype(dt))) / 2.0
    # f32 pairwise/sequential accumulation over T terms
    accum = leaf.max() * leaf.shape[0] * float(np.finfo(np.float32).eps)
    return float(np.sum(half_ulp) + accum)


def _traverse_one_tree_q(qbins, feat, thr_q, def_q, flags, left, right,
                         levels):
    """(N,) leaf indices for one level-packed quantized tree."""
    n = qbins.shape[0]
    rows = jnp.arange(n)

    def step(_, node):
        j = jnp.maximum(node, 0)
        q = qbins[rows, feat[j].astype(jnp.int32)]
        fq = jnp.where(q == ZERO_CODE, def_q[j], q)
        goes_left = jnp.where(
            flags[j] != 0, fq == thr_q[j], fq <= thr_q[j])
        nxt = jnp.where(goes_left, left[j], right[j]).astype(jnp.int32)
        return jnp.where(node >= 0, nxt, node)

    node = jnp.zeros((n,), jnp.int32)
    node = jax.lax.fori_loop(0, levels, step, node)
    return ~node


@partial(jax.jit, static_argnames=("levels",))
def qpredict_raw(qbins, split_feature, threshold_q, default_q, flags,
                 left_child, right_child, leaf_value, levels):
    """(N,) f32 raw scores over (N, F) int16 rank codes (one class)."""
    leaves = jax.vmap(
        _traverse_one_tree_q,
        in_axes=(None, 0, 0, 0, 0, 0, 0, None),
    )(qbins, split_feature, threshold_q, default_q, flags,
      left_child, right_child, levels)  # (T, N)
    vals = jnp.take_along_axis(leaf_value, leaves, axis=1)
    return jnp.sum(vals.astype(jnp.float32), axis=0)


@partial(jax.jit, static_argnames=("levels",))
def qpredict_leaf(qbins, split_feature, threshold_q, default_q, flags,
                  left_child, right_child, levels):
    """(T, N) leaf indices (PredictLeafIndex mode, quantized)."""
    return jax.vmap(
        _traverse_one_tree_q,
        in_axes=(None, 0, 0, 0, 0, 0, 0, None),
    )(qbins, split_feature, threshold_q, default_q, flags,
      left_child, right_child, levels)
