"""Host-driven leaf-wise grower with O(N_leaf) histogram work — the
performance-oriented counterpart of SerialTreeLearner + DataPartition
(serial_tree_learner.cpp:152-207, data_partition.hpp:94-150).

The jitted while-loop grower (ops/grow.py) is one compiled program but
pays O(N) masked histogram work per split — every row is scanned for every
split.  The reference scans only the smaller child's rows
(ordered index lists).  This grower restores that asymptotic:

- ``order`` is an (N,) row-index vector kept PARTITIONED by leaf (the
  reference's DataPartition ``indices_``); each leaf owns a contiguous
  [start, start+cnt) segment.  Splits re-partition one segment with a
  stable cumsum-rank scatter — O(segment), static shapes.
- Histograms gather only the split leaf's segment, padded up to a
  power-of-two bucket size.  XLA compiles one kernel per bucket
  (~log2(N/4096) variants), so work per split is O(bucket(N_leaf) · F · B)
  instead of O(N · F · B) — the factor that separates 5.7 s/iter from the
  reference GPU's per-row rate.
- Control flow (best-split table argmax, bucket choice) runs on host like
  the reference's Train loop; per split the device syncs twice (n_left,
  and the two children's packed best-split records).

Used by the serial path for large N; the shard_map distributed path keeps
the single-program grower (collectives must stay inside one program).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .grow import GrowResult
from .histogram import build_histogram
from .split import FeatureMeta, SplitHyper, best_split_all_features

MIN_BUCKET = 4096


def _bucket(cnt: int, n_pad: int) -> int:
    """Smallest power-of-two bucket >= cnt (floored at MIN_BUCKET)."""
    s = MIN_BUCKET
    while s < cnt:
        s *= 2
    return min(s, n_pad)


# ----------------------------------------------------------------------
# jitted kernels (static over bucket size S)
# ----------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("S", "num_bins"))
def _hist_segment(bins_p, grad_p, hess_p, select_p, order, start, cnt, S, num_bins):
    """(F, B, 3) histogram of the segment order[start:start+S], masked to
    the first ``cnt`` entries — DenseBin::ConstructHistogram over the
    leaf's data indices."""
    rows = jax.lax.dynamic_slice(order, (start,), (S,))
    valid = (jnp.arange(S) < cnt).astype(jnp.float32)
    seg_bins = bins_p[rows]
    seg_grad = grad_p[rows]
    seg_hess = hess_p[rows]
    seg_sel = select_p[rows] * valid
    return build_histogram(seg_bins, seg_grad, seg_hess, seg_sel, num_bins,
                           row_block=min(S, 4096))


@functools.partial(jax.jit, static_argnames=("S",))
def _partition_segment(bins_p, order, start, cnt, feat, thr, dbz, zero_bin, is_cat, S):
    """Stable in-segment partition (DataPartition::Split): left rows keep
    order before right rows.  Returns (new_order, n_left)."""
    seg = jax.lax.dynamic_slice(order, (start,), (S,))
    pos = jnp.arange(S)
    valid = pos < cnt
    col = bins_p[seg, feat].astype(jnp.int32)
    fval = jnp.where(col == zero_bin, dbz, col)
    gl = jnp.where(is_cat, fval == thr, fval <= thr) & valid
    gr = valid & ~gl
    n_left = jnp.sum(gl)
    lrank = jnp.cumsum(gl) - 1
    rrank = jnp.cumsum(gr) - 1
    tgt = jnp.where(gl, lrank, jnp.where(gr, n_left + rrank, pos))
    new_seg = jnp.zeros_like(seg).at[tgt].set(seg)
    order = jax.lax.dynamic_update_slice(order, new_seg, (start,))
    return order, n_left


def _pack(res):
    """SplitResult -> one f32 vector so the host pulls a single buffer.
    int fields are exact in f32 (< 2^24)."""
    return jnp.stack([
        res.gain,
        res.feature.astype(jnp.float32),
        res.threshold_bin.astype(jnp.float32),
        res.default_bin_for_zero.astype(jnp.float32),
        res.left_sum_g, res.left_sum_h, res.left_cnt,
    ])


@functools.partial(jax.jit, static_argnames=("use_missing",))
def _best_split_pair(lhist, rhist, lsums, rsums, meta, hyper, feature_mask,
                     use_missing):
    """Both children's best splits in one program -> (2, 7) packed."""
    lres = best_split_all_features(lhist, lsums[0], lsums[1], lsums[2], meta,
                                   hyper, feature_mask, use_missing)
    rres = best_split_all_features(rhist, rsums[0], rsums[1], rsums[2], meta,
                                   hyper, feature_mask, use_missing)
    return jnp.stack([_pack(lres), _pack(rres)])


@functools.partial(jax.jit, static_argnames=("use_missing",))
def _best_split_root(hist, sums, meta, hyper, feature_mask, use_missing):
    res = best_split_all_features(hist, sums[0], sums[1], sums[2], meta,
                                  hyper, feature_mask, use_missing)
    return _pack(res)


@jax.jit
def _root_stats(grad, hess, select):
    return jnp.stack([jnp.sum(grad * select), jnp.sum(hess * select),
                      jnp.sum(select)])


@jax.jit
def _leaf_id_from_segments(order_n, seg_starts, seg_leaves):
    """leaf_id[row] from contiguous segments: position -> leaf via
    searchsorted over sorted starts, scattered through the order
    permutation."""
    pos = jnp.arange(order_n.shape[0])
    leaf_at_pos = seg_leaves[jnp.searchsorted(seg_starts, pos, side="right") - 1]
    return jnp.zeros_like(order_n).at[order_n].set(leaf_at_pos)


class FastGrower:
    """Grows trees with host control flow; reusable across iterations
    (kernels cached per bucket size)."""

    def __init__(self, bins, meta: FeatureMeta, hyper: SplitHyper, params):
        n, f = bins.shape
        self.n = n
        self.params = params
        self.meta = meta
        self.hyper = hyper
        self.n_pad = 1
        while self.n_pad < max(n, MIN_BUCKET):
            self.n_pad *= 2
        self.bins = jnp.asarray(bins)
        # one dummy row (index n) absorbs bucket-padding gathers
        self.bins_p = jnp.concatenate(
            [self.bins, jnp.zeros((1, f), self.bins.dtype)], axis=0
        )
        # order padded by n_pad: a segment's bucket never overruns
        # (start + bucket(cnt) <= n + n_pad since bucket(cnt) <= n_pad)
        self._order_init = jnp.concatenate(
            [jnp.arange(n, dtype=jnp.int32),
             jnp.full((self.n_pad,), n, jnp.int32)]
        )
        self.db = np.asarray(meta.default_bin)
        self.cat = np.asarray(meta.is_categorical)

    def grow(self, grad, hess, select, feature_mask) -> GrowResult:
        p = self.params
        L, B = p.num_leaves, p.num_bins
        n = self.n
        um = bool(p.use_missing)
        grad_p = jnp.concatenate([grad, jnp.zeros((1,), grad.dtype)])
        hess_p = jnp.concatenate([hess, jnp.zeros((1,), hess.dtype)])
        select_p = jnp.concatenate([select, jnp.zeros((1,), select.dtype)])
        order = self._order_init

        # root: full-data histogram (no gather needed)
        root_hist = build_histogram(self.bins, grad, hess, select, B)
        stats = np.asarray(_root_stats(grad, hess, select), np.float64)
        tg, th, tc = stats
        pool = jnp.zeros((L,) + root_hist.shape, jnp.float32).at[0].set(root_hist)
        root_packed = np.asarray(
            _best_split_root(root_hist, jnp.asarray(stats, jnp.float32),
                             self.meta, self.hyper, feature_mask, um),
            np.float64,
        )

        # host-side bookkeeping (the reference's best_split_per_leaf_)
        starts = np.zeros(L, np.int64)
        cnts = np.zeros(L, np.int64)
        depths = np.zeros(L, np.int64)
        sums = np.zeros((L, 3))
        leaf_values = np.zeros(L)
        # cnts[] = SEGMENT sizes (all rows, selected or not — the partition
        # moves every row like the reference moves every index); the
        # statistical (selected) counts live in sums[:, 2] / bs["left"][2]
        cnts[0] = n
        sums[0] = [tg, th, tc]
        bs = {
            "gain": np.full(L, -np.inf),
            "feat": np.zeros(L, np.int64),
            "thr": np.zeros(L, np.int64),
            "dbz": np.zeros(L, np.int64),
            "left": np.zeros((L, 3)),
        }

        def store(leaf, packed):
            bs["gain"][leaf] = packed[0]
            bs["feat"][leaf] = int(packed[1])
            bs["thr"][leaf] = int(packed[2])
            bs["dbz"][leaf] = int(packed[3])
            bs["left"][leaf] = packed[4:7]

        store(0, root_packed)

        rec = {k: np.zeros(max(L - 1, 1), np.int64)
               for k in ("leaf", "feat", "thr", "dbz")}
        recf = {k: np.zeros(max(L - 1, 1)) for k in
                ("gain", "lval", "rval", "lcnt", "rcnt", "ival")}
        num_splits = 0
        l1 = float(self.hyper.lambda_l1)
        l2 = float(self.hyper.lambda_l2)

        def out(sg, sh):
            reg = max(abs(sg) - l1, 0.0)
            return -np.sign(sg) * reg / (sh + l2) if (sh + l2) != 0 else 0.0

        # segment bookkeeping note: cnts[] counts SELECTED+unselected rows
        # of the segment (the partition moves every row; histograms mask by
        # select), exactly like the reference partitions all indices.
        for s in range(L - 1):
            bl = int(np.argmax(bs["gain"]))
            if not (bs["gain"][bl] > 0.0):
                break
            feat = int(bs["feat"][bl])
            thr = int(bs["thr"][bl])
            dbz = int(bs["dbz"][bl])
            start, cnt = int(starts[bl]), int(cnts[bl])
            S = _bucket(cnt, self.n_pad)
            order, n_left_dev = _partition_segment(
                self.bins_p, order, jnp.int32(start), jnp.int32(cnt),
                jnp.int32(feat), jnp.int32(thr), jnp.int32(dbz),
                jnp.int32(self.db[feat]), jnp.bool_(self.cat[feat]), S,
            )
            n_left = int(n_left_dev)

            right_leaf = s + 1
            left = bs["left"][bl].copy()
            total = sums[bl]
            right = total - left
            lval, rval = out(left[0], left[1]), out(right[0], right[1])

            rec["leaf"][s], rec["feat"][s] = bl, feat
            rec["thr"][s], rec["dbz"][s] = thr, dbz
            recf["gain"][s] = bs["gain"][bl]
            recf["lval"][s], recf["rval"][s] = lval, rval
            recf["lcnt"][s], recf["rcnt"][s] = left[2], right[2]
            recf["ival"][s] = leaf_values[bl]

            # segment bookkeeping
            starts[right_leaf] = start + n_left
            cnts[right_leaf] = cnt - n_left
            cnts[bl] = n_left
            sums[bl], sums[right_leaf] = left, right
            leaf_values[bl], leaf_values[right_leaf] = lval, rval
            depths[bl] += 1
            depths[right_leaf] = depths[bl]

            # smaller child direct, larger by subtraction
            left_is_smaller = n_left < cnt - n_left
            sm = bl if left_is_smaller else right_leaf
            S_sm = _bucket(int(cnts[sm]), self.n_pad)
            sm_hist = _hist_segment(
                self.bins_p, grad_p, hess_p, select_p, order,
                jnp.int32(int(starts[sm])), jnp.int32(int(cnts[sm])), S_sm, B,
            )
            lg_hist = pool[bl] - sm_hist
            if left_is_smaller:
                lhist, rhist = sm_hist, lg_hist
            else:
                lhist, rhist = lg_hist, sm_hist
            pool = pool.at[bl].set(lhist).at[right_leaf].set(rhist)

            depth_ok = p.max_depth <= 0 or depths[bl] < p.max_depth
            if depth_ok:
                packed = np.asarray(
                    _best_split_pair(
                        lhist, rhist,
                        jnp.asarray(left, jnp.float32),
                        jnp.asarray(right, jnp.float32),
                        self.meta, self.hyper, feature_mask, um,
                    ),
                    np.float64,
                )
                store(bl, packed[0])
                store(right_leaf, packed[1])
            else:
                bs["gain"][bl] = -np.inf
                bs["gain"][right_leaf] = -np.inf
            num_splits += 1

        # leaf_id from the final segment layout
        nl = num_splits + 1
        seg_order = np.argsort(starts[:nl], kind="stable")
        leaf_id = _leaf_id_from_segments(
            order[:n],
            jnp.asarray(starts[:nl][seg_order].astype(np.int32)),
            jnp.asarray(seg_order.astype(np.int32)),
        )

        m = max(L - 1, 1)
        return GrowResult(
            num_splits=jnp.int32(num_splits),
            leaf_id=leaf_id,
            leaf_value=jnp.asarray(leaf_values.astype(np.float32)),
            leaf_cnt=jnp.asarray(sums[:L, 2].astype(np.float32)),
            rec_leaf=jnp.asarray(rec["leaf"][:m].astype(np.int32)),
            rec_feat=jnp.asarray(rec["feat"][:m].astype(np.int32)),
            rec_thr=jnp.asarray(rec["thr"][:m].astype(np.int32)),
            rec_dbz=jnp.asarray(rec["dbz"][:m].astype(np.int32)),
            rec_gain=jnp.asarray(recf["gain"][:m].astype(np.float32)),
            rec_lval=jnp.asarray(recf["lval"][:m].astype(np.float32)),
            rec_rval=jnp.asarray(recf["rval"][:m].astype(np.float32)),
            rec_lcnt=jnp.asarray(recf["lcnt"][:m].astype(np.float32)),
            rec_rcnt=jnp.asarray(recf["rcnt"][:m].astype(np.float32)),
            rec_internal_value=jnp.asarray(recf["ival"][:m].astype(np.float32)),
        )
