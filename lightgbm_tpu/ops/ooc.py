"""Chunk programs for out-of-core tree growth (boosting/ooc.py).

The mask grower (ops/grow.py) runs one XLA program over the full
``(N, F)`` bin matrix.  Out-of-core training keeps every *row vector*
(grad / hess / select / leaf_id / scores) device-resident — they are a
few N-floats — and streams only the matrix in row-chunks, so these
programs are the grower's per-split body re-cut at a chunk boundary:

  ``root_hist_chunk``   one chunk's contribution to the root histogram
  ``split_chunk``       one chunk's share of a split: partition-update
                        the chunk's ``leaf_id`` slice, count left rows,
                        and fold BOTH children's histogram partials
  ``find_best_split``   best split over an accumulated histogram
  ``child_leaf_values`` the two child leaf outputs at the classic
                        scalar shapes
  ``subtract_sibling``  the histogram-subtraction trick

Bit-identity contract (the reason these mirror ``grow_tree`` op for op):
with chunk boundaries on ``ROW_BLOCK`` multiples, the chunked histogram
folds perform the identical left-to-right block adds as the in-memory
scan (see ``accumulate_histogram``); every other per-row op (partition
predicate, mask multiply, gradient slice) is elementwise or integer, so
chunking cannot change it.  The only cross-row *float* reduction in tree
growth is the histogram — "Out-of-Core GPU Gradient Boosting"
(PAPERS.md) makes the same observation — which is what makes a
bit-identical streamed replay possible at all.

Donation: the running carries (leaf_id, the two child histograms, the
left-row count) are donated so per-chunk calls update them in place
instead of allocating per chunk; the chunk buffer itself is a regular
argument — the prefetch ring (data/prefetch.py) bounds those to two
in-flight buffers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .histogram import ROW_BLOCK, accumulate_histogram
from .split import NEG_INF, best_split_per_feature, finalize_split, leaf_output


@functools.partial(jax.jit, static_argnames=("num_bins", "row_block"),
                   donate_argnums=(0,))
def root_hist_chunk(hist, bins_chunk, grad, hess, select, start,
                    num_bins: int, row_block: int = ROW_BLOCK):
    """Fold one chunk into the root histogram.

    ``grad``/``hess``/``select`` are the FULL (N,) device vectors; the
    chunk's rows are sliced at ``start`` so the per-element products
    match the in-memory ``build_histogram(bins, grad, hess, select)``
    exactly."""
    c = bins_chunk.shape[0]
    g = jax.lax.dynamic_slice(grad, (start,), (c,))
    h = jax.lax.dynamic_slice(hess, (start,), (c,))
    s = jax.lax.dynamic_slice(select, (start,), (c,))
    return accumulate_histogram(hist, bins_chunk, g, h, s, num_bins, row_block)


@functools.partial(jax.jit, static_argnames=("num_bins", "row_block"),
                   donate_argnums=(0, 1, 2, 3))
def split_chunk(leaf_id, hist_l, hist_r, n_left, bins_chunk, grad, hess,
                select, start, feat, zero_bin, dbz, thr, is_cat, bl, rl,
                num_bins: int, row_block: int = ROW_BLOCK):
    """One chunk's share of one split — the streamed counterpart of
    ``grow_tree._split``'s partition + child-histogram body.

    Updates the chunk's ``leaf_id`` slice by the partition predicate
    (DataPartition::Split as a predicate on the split feature's bin
    column), accumulates the left-row count, and folds BOTH children's
    histogram partials.  Computing both (instead of the in-memory path's
    smaller-child-only pass) costs extra flops but keeps the streamed
    split to ONE pass over the matrix — transfers, not flops, bound the
    out-of-core path.  The caller keeps the direct accumulation for the
    smaller child and derives the larger via ``subtract_sibling``,
    exactly like the in-memory grower, so the pooled histograms are
    bit-identical."""
    c = bins_chunk.shape[0]
    lid = jax.lax.dynamic_slice(leaf_id, (start,), (c,))
    col = jnp.take(bins_chunk, feat, axis=1).astype(jnp.int32)
    fval = jnp.where(col == zero_bin, dbz, col)
    goes_left = jnp.where(is_cat, fval == thr, fval <= thr)
    in_leaf = lid == bl
    new_lid = jnp.where(in_leaf & ~goes_left, rl, lid)
    leaf_id = jax.lax.dynamic_update_slice(leaf_id, new_lid, (start,))
    n_left = n_left + jnp.sum((in_leaf & goes_left).astype(jnp.int32))

    g = jax.lax.dynamic_slice(grad, (start,), (c,))
    h = jax.lax.dynamic_slice(hess, (start,), (c,))
    s = jax.lax.dynamic_slice(select, (start,), (c,))
    sel_l = s * (new_lid == bl).astype(s.dtype)
    sel_r = s * (new_lid == rl).astype(s.dtype)
    hist_l = accumulate_histogram(hist_l, bins_chunk, g, h, sel_l,
                                  num_bins, row_block)
    hist_r = accumulate_histogram(hist_r, bins_chunk, g, h, sel_r,
                                  num_bins, row_block)
    return leaf_id, hist_l, hist_r, n_left


@jax.jit
def root_totals(grad, hess, select):
    """Root leaf sums — the same full-N reductions as ``grow_tree``'s
    ``LeafSplits::Init`` (the N-vectors stay device-resident out of
    core, so these are not chunked).

    Integer (quantized-training) gradients return exact (3,) int32
    totals; the trainer dequantizes them host-side."""
    if jnp.issubdtype(grad.dtype, jnp.integer):
        s16 = select.astype(jnp.int16)
        return jnp.stack([jnp.sum(grad * s16, dtype=jnp.int32),
                          jnp.sum(hess * s16, dtype=jnp.int32),
                          jnp.sum(s16, dtype=jnp.int32)])
    tg = jnp.sum(grad * select)
    th = jnp.sum(hess * select)
    tc = jnp.sum(select)
    return jnp.stack([tg, th, tc])


@functools.partial(jax.jit, static_argnames=("use_missing",))
def find_best_split(hist, sums, feature_mask, depth_ok, meta, hyper,
                    use_missing: bool = True, monotone=None,
                    leaf_lo=None, leaf_hi=None):
    """Best split over an accumulated (F, B, 3) histogram — the serial
    branch of ``grow_tree.find_best`` verbatim.  ``monotone`` /
    ``leaf_lo`` / ``leaf_hi`` thread the strategy seam's constraint
    surface (None = exact unconstrained graph); the streaming trainers
    carry the per-leaf bounds host-side."""
    sg, sh, sc = sums[0], sums[1], sums[2]
    gain_f, thr_f, dbz_f, left_f = best_split_per_feature(
        hist, sg, sh, sc, meta, hyper, feature_mask, use_missing,
        monotone=monotone, leaf_lo=leaf_lo, leaf_hi=leaf_hi,
    )
    res = finalize_split(gain_f, thr_f, dbz_f, left_f, sg, sh, sc, hyper,
                         leaf_lo=leaf_lo, leaf_hi=leaf_hi)
    return res._replace(gain=jnp.where(depth_ok, res.gain, NEG_INF))


@jax.jit
def child_leaf_values(left, right, l1, l2, leaf_lo=None, leaf_hi=None):
    """The two child outputs at the classic scalar shapes
    (CalculateSplittedLeafOutput on (sum_g, sum_h) scalars); monotone
    bounds clip both when given."""
    lval = leaf_output(left[0], left[1], l1, l2)
    rval = leaf_output(right[0], right[1], l1, l2)
    if leaf_lo is not None:
        lval = jnp.clip(lval, leaf_lo, leaf_hi)
        rval = jnp.clip(rval, leaf_lo, leaf_hi)
    return lval, rval


@jax.jit
def subtract_sibling(parent_hist, smaller_hist):
    """FeatureHistogram::Subtract — one tensor subtract."""
    return parent_hist - smaller_hist


@jax.jit
def scatter_add_slice(vec, delta, start):
    """``vec[start : start+len(delta)] += delta`` — used by the streamed
    ``predict_binned`` fallback (rollback/DART keep working when the
    matrix is not device-resident)."""
    c = delta.shape[0]
    cur = jax.lax.dynamic_slice(vec, (start,), (c,))
    return jax.lax.dynamic_update_slice(vec, cur + delta, (start,))
