"""lightgbm_tpu — a TPU-native gradient boosting framework.

A from-scratch reimplementation of the capabilities of LightGBM (reference:
sky-noodle/LightGBM, mirrored read-only at /root/reference) designed for TPU
hardware: binned feature matrices live in HBM as dense device arrays,
histograms are built by XLA/Pallas kernels (one-hot matmul onto the MXU),
split finding is a vectorized prefix-scan, tree growth is a single jitted
`lax.fori_loop`, and distributed training uses `jax.sharding.Mesh` +
`shard_map` with XLA collectives (psum / all_gather / reduce_scatter) over
ICI/DCN in place of the reference's socket/MPI Network layer.

Public API mirrors the reference python-package (python-package/lightgbm):
`Dataset`, `Booster`, `train`, `cv`, sklearn wrappers, callbacks, plotting.
"""

__version__ = "0.1.0"


_cache_enabled = False


def enable_compile_cache():
    """Persistent XLA compilation cache: the fused training programs take
    ~25 s to compile; caching drops repeat-run warmup to seconds.  Set
    LIGHTGBM_TPU_COMPILE_CACHE=0 to disable, or point it at a directory.

    Called LAZILY from the training drivers once the backend exists: the
    cache subdirectory is keyed on the REAL backend platform plus (for
    host backends) the node name, so artifacts never cross between a
    remote-compile device population and local CPU compiles, or between
    machines sharing a home directory (mismatched machine features in a
    loaded AOT result can SIGILL)."""
    global _cache_enabled
    if _cache_enabled:
        return
    import os

    flag = os.environ.get("LIGHTGBM_TPU_COMPILE_CACHE", "")
    if flag == "0":
        return
    _cache_enabled = True
    try:
        import jax

        backend = jax.default_backend()
        sub = backend
        if backend == "cpu":
            sub = f"cpu-{os.uname().nodename}"
        repo_root = os.path.dirname(os.path.dirname(__file__))
        if flag:
            path = os.path.join(flag, sub)
        elif os.path.isdir(os.path.join(repo_root, ".git")):
            path = os.path.join(repo_root, ".jax_cache", sub)  # source checkout
        else:
            path = os.path.join(os.path.expanduser("~"), ".cache", "lightgbm_tpu", "jax", sub)
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # pragma: no cover — cache is best-effort
        pass

def _honor_jax_platforms_env():
    """The axon TPU plugin ignores the JAX_PLATFORMS env var (only the
    config knob wins), so a caller exporting JAX_PLATFORMS=cpu — e.g. the
    CLI under a dead/absent tunnel — would still block on TPU backend
    init.  Mirror the env var into the config before first device use."""
    import os

    plat = os.environ.get("JAX_PLATFORMS", "")
    if plat:
        try:
            import jax

            jax.config.update("jax_platforms", plat)
        except Exception:  # pragma: no cover
            pass


_honor_jax_platforms_env()

from .basic import Booster, Dataset
from .engine import cv, train
from .callback import early_stopping, log_evaluation, record_evaluation, reset_parameter
from .ckpt import CheckpointManager
from .utils.log import LightGBMError

try:  # sklearn wrappers are optional (sklearn is present in CI images)
    from .sklearn import LGBMModel, LGBMRegressor, LGBMClassifier, LGBMRanker
except ImportError:  # pragma: no cover
    pass

try:  # plotting needs matplotlib (graphviz optional for plot_tree)
    from . import plotting
    from .plotting import plot_importance, plot_metric, plot_tree, create_tree_digraph
except ImportError:  # pragma: no cover
    pass

from . import config, metric, objective

__all__ = [
    "Dataset",
    "Booster",
    "LightGBMError",
    "train",
    "cv",
    "CheckpointManager",
    "LGBMModel",
    "LGBMRegressor",
    "LGBMClassifier",
    "LGBMRanker",
    "early_stopping",
    "log_evaluation",
    "record_evaluation",
    "reset_parameter",
    "plot_importance",
    "plot_metric",
    "plot_tree",
    "create_tree_digraph",
]

# Re-assert the caller's platform choice AFTER the package imports: pulling
# in the Pallas kernel modules triggers the axon plugin's registration,
# which overwrites jax_platforms with "axon,cpu" — under a dead/absent
# tunnel the next device access would then hang in the axon PJRT client
# instead of using the requested CPU backend.
_honor_jax_platforms_env()
