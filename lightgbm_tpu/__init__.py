"""lightgbm_tpu — a TPU-native gradient boosting framework.

A from-scratch reimplementation of the capabilities of LightGBM (reference:
sky-noodle/LightGBM, mirrored read-only at /root/reference) designed for TPU
hardware: binned feature matrices live in HBM as dense device arrays,
histograms are built by XLA/Pallas kernels (one-hot matmul onto the MXU),
split finding is a vectorized prefix-scan, tree growth is a single jitted
`lax.fori_loop`, and distributed training uses `jax.sharding.Mesh` +
`shard_map` with XLA collectives (psum / all_gather / reduce_scatter) over
ICI/DCN in place of the reference's socket/MPI Network layer.

Public API mirrors the reference python-package (python-package/lightgbm):
`Dataset`, `Booster`, `train`, `cv`, sklearn wrappers, callbacks, plotting.
"""

__version__ = "0.1.0"


def _enable_compile_cache():
    """Persistent XLA compilation cache: the fused training programs take
    ~25 s to compile; caching drops repeat-run warmup to seconds.  Set
    LIGHTGBM_TPU_COMPILE_CACHE=0 to disable, or point it at a directory."""
    import os

    flag = os.environ.get("LIGHTGBM_TPU_COMPILE_CACHE", "")
    if flag == "0":
        return
    # CPU compiles may be served by a remote compile helper with different
    # machine features; loading such AOT results risks SIGILL.  Cache only
    # the (expensive, feature-stable) TPU programs unless explicitly asked:
    # skip when the run is CPU-bound (env forces cpu, or no TPU plugin is
    # even importable — checked without touching the backend).
    if not flag:
        if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
            return
        import importlib.util

        if importlib.util.find_spec("libtpu") is None and importlib.util.find_spec(
            "jax_plugins"
        ) is None:
            return
    repo_root = os.path.dirname(os.path.dirname(__file__))
    if flag:
        path = flag
    elif os.path.isdir(os.path.join(repo_root, ".git")):
        path = os.path.join(repo_root, ".jax_cache")  # source checkout
    else:
        path = os.path.join(os.path.expanduser("~"), ".cache", "lightgbm_tpu", "jax")
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # pragma: no cover — cache is best-effort
        pass


_enable_compile_cache()

from .basic import Booster, Dataset
from .engine import cv, train
from .callback import early_stopping, log_evaluation, record_evaluation, reset_parameter

try:  # sklearn wrappers are optional (sklearn is present in CI images)
    from .sklearn import LGBMModel, LGBMRegressor, LGBMClassifier, LGBMRanker
except ImportError:  # pragma: no cover
    pass

try:  # plotting needs matplotlib (graphviz optional for plot_tree)
    from . import plotting
    from .plotting import plot_importance, plot_metric, plot_tree, create_tree_digraph
except ImportError:  # pragma: no cover
    pass

from . import config, metric, objective

__all__ = [
    "Dataset",
    "Booster",
    "train",
    "cv",
    "LGBMModel",
    "LGBMRegressor",
    "LGBMClassifier",
    "LGBMRanker",
    "early_stopping",
    "log_evaluation",
    "record_evaluation",
    "reset_parameter",
    "plot_importance",
    "plot_metric",
    "plot_tree",
    "create_tree_digraph",
]
