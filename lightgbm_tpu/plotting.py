"""Plotting — counterpart of python-package/lightgbm/plotting.py
(plot_importance, plot_metric, plot_tree, create_tree_digraph).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .basic import Booster
from .utils.log import Log


def _check_not_tuple_of_2_elements(obj, obj_name="obj"):
    if not isinstance(obj, tuple) or len(obj) != 2:
        raise TypeError(f"{obj_name} must be a tuple of 2 elements.")


def plot_importance(
    booster,
    ax=None,
    height: float = 0.2,
    xlim=None,
    ylim=None,
    title: str = "Feature importance",
    xlabel: str = "Feature importance",
    ylabel: str = "Features",
    importance_type: str = "split",
    max_num_features: Optional[int] = None,
    ignore_zero: bool = True,
    figsize=None,
    grid: bool = True,
    **kwargs,
):
    """Bar chart of feature importances (plotting.py plot_importance)."""
    import matplotlib.pyplot as plt

    if isinstance(booster, Booster):
        importance = booster.feature_importance(importance_type)
        feature_names = booster.feature_name()
    elif hasattr(booster, "booster_"):
        importance = booster.booster_.feature_importance(importance_type)
        feature_names = booster.booster_.feature_name()
    else:
        raise TypeError("booster must be Booster or LGBMModel")

    tuples = sorted(zip(feature_names, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    if not tuples:
        raise ValueError("Cannot plot trees with zero importance")
    labels, values = zip(*tuples)

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y, str(int(x) if importance_type == "split" else round(x, 2)),
                va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
        ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
        ax.set_ylim(ylim)
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(
    booster_or_evals_result,
    metric: Optional[str] = None,
    dataset_names=None,
    ax=None,
    xlim=None,
    ylim=None,
    title: str = "Metric during training",
    xlabel: str = "Iterations",
    ylabel: str = "auto",
    figsize=None,
    grid: bool = True,
):
    """Plot metric history recorded by record_evaluation
    (plotting.py plot_metric)."""
    import matplotlib.pyplot as plt

    if isinstance(booster_or_evals_result, dict):
        eval_results = booster_or_evals_result
    elif hasattr(booster_or_evals_result, "evals_result_"):
        eval_results = booster_or_evals_result.evals_result_
    else:
        raise TypeError(
            "booster_or_evals_result must be a dict from record_evaluation "
            "or a fitted LGBMModel"
        )
    if not eval_results:
        raise ValueError("eval results are empty")

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)

    names = list(dataset_names) if dataset_names else list(eval_results.keys())
    first = eval_results[names[0]]
    if metric is None:
        metric = next(iter(first.keys()))
    for name in names:
        if metric not in eval_results[name]:
            raise ValueError(f"Metric {metric} not found for dataset {name}")
        results = eval_results[name][metric]
        ax.plot(range(1, len(results) + 1), results, label=name)
    ax.legend(loc="best")
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
        ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
        ax.set_ylim(ylim)
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    ax.set_ylabel(metric if ylabel == "auto" else ylabel)
    ax.grid(grid)
    return ax


def _tree_of(booster, tree_index: int):
    if hasattr(booster, "booster_"):
        booster = booster.booster_
    if not isinstance(booster, Booster):
        raise TypeError("booster must be Booster or LGBMModel")
    models = booster.boosting.models
    if tree_index >= len(models):
        raise IndexError(f"tree_index {tree_index} out of range ({len(models)} trees)")
    return booster, models[tree_index]


def create_tree_digraph(
    booster,
    tree_index: int = 0,
    show_info=None,
    name=None,
    comment=None,
    **kwargs,
):
    """Graphviz Digraph of one tree (plotting.py create_tree_digraph)."""
    import graphviz

    booster, tree = _tree_of(booster, tree_index)
    feature_names = booster.feature_name()
    show_info = show_info or []
    graph = graphviz.Digraph(name=name, comment=comment, **kwargs)

    def add(idx, parent=None, decision=None):
        if idx >= 0:
            name_ = f"split{idx}"
            feat = tree.split_feature[idx]
            label = (
                f"{feature_names[feat] if feat < len(feature_names) else feat}"
                f" {'==' if tree.decision_type[idx] == 1 else '<='}"
                f" {tree.threshold[idx]:g}"
            )
            if "split_gain" in show_info:
                label += f"\\ngain: {tree.split_gain[idx]:g}"
            if "internal_value" in show_info:
                label += f"\\nvalue: {tree.internal_value[idx]:g}"
            if "internal_count" in show_info:
                label += f"\\ncount: {tree.internal_count[idx]}"
            graph.node(name_, label=label)
            add(tree.left_child[idx], name_, "yes")
            add(tree.right_child[idx], name_, "no")
        else:
            leaf = ~idx
            name_ = f"leaf{leaf}"
            label = f"leaf {leaf}: {tree.leaf_value[leaf]:g}"
            if "leaf_count" in show_info:
                label += f"\\ncount: {tree.leaf_count[leaf]}"
            graph.node(name_, label=label)
        if parent is not None:
            graph.edge(parent, name_, decision)

    add(0 if tree.num_leaves > 1 else -1)
    return graph


def plot_tree(booster, tree_index: int = 0, ax=None, figsize=None,
              show_info=None, **kwargs):
    """Render one tree with matplotlib via the graphviz digraph
    (plotting.py plot_tree)."""
    import matplotlib.image as mpimg
    import matplotlib.pyplot as plt

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)
    graph = create_tree_digraph(booster, tree_index, show_info=show_info, **kwargs)
    import io
    import tempfile

    try:
        s = graph.pipe(format="png")
        img = mpimg.imread(io.BytesIO(s))
        ax.imshow(img)
    except Exception as e:  # graphviz binary missing: text fallback
        Log.warning("graphviz rendering unavailable (%s); text fallback", e)
        booster_, tree = _tree_of(booster, tree_index)
        ax.text(0.5, 0.5, tree.to_string(), ha="center", va="center",
                family="monospace", fontsize=6)
    ax.axis("off")
    return ax
