// Native chunked text parser for lightgbm_tpu.
//
// Runtime counterpart of the reference's Parser/TextReader pipeline
// (src/io/parser.cpp, include/LightGBM/utils/text_reader.h): dense
// CSV/TSV and sparse LibSVM files are parsed into row-major double
// matrices with multithreaded chunking.
//
// Float parsing reproduces the reference's hand-rolled
// Common::Atof (include/LightGBM/utils/common.h:163-261) EXACTLY,
// including its non-correctly-rounded digit accumulation
// (value += digit/pow10): bin thresholds are midpoints of Atof-parsed
// values, so bit-identical parsing is a hard requirement for
// prediction parity at value==threshold knife edges — a correctly
// rounded strtod differs by 1 ulp on e.g. "1.413" and flips the
// <= decision against a reference-trained model.
//
// Exposed via ctypes (no pybind11 in the image); see native/__init__.py.

#include <algorithm>
#include <cctype>
#include <cstdint>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

inline char lower(char c) { return static_cast<char>(std::tolower(static_cast<unsigned char>(c))); }

// Reference-compatible float parse (common.h:163-261 semantics,
// independently written). Returns pointer past the parsed token.
const char* AtofRef(const char* p, const char* end, double* out) {
  *out = 0;
  while (p < end && *p == ' ') ++p;
  double sign = 1.0;
  if (p < end && *p == '-') { sign = -1.0; ++p; }
  else if (p < end && *p == '+') { ++p; }

  if (p < end && ((*p >= '0' && *p <= '9') || *p == '.' || *p == 'e' || *p == 'E')) {
    double value = 0.0;
    for (; p < end && *p >= '0' && *p <= '9'; ++p) {
      value = value * 10.0 + (*p - '0');
    }
    if (p < end && *p == '.') {
      double pow10 = 10.0;
      ++p;
      while (p < end && *p >= '0' && *p <= '9') {
        value += (*p - '0') / pow10;
        pow10 *= 10.0;
        ++p;
      }
    }
    int frac = 0;
    double scale = 1.0;
    if (p < end && (*p == 'e' || *p == 'E')) {
      ++p;
      if (p < end && *p == '-') { frac = 1; ++p; }
      else if (p < end && *p == '+') { ++p; }
      uint32_t expon = 0;
      for (; p < end && *p >= '0' && *p <= '9'; ++p) {
        expon = expon * 10 + (*p - '0');
      }
      if (expon > 308) expon = 308;
      while (expon >= 50) { scale *= 1E50; expon -= 50; }
      while (expon >= 8)  { scale *= 1E8;  expon -= 8; }
      while (expon > 0)   { scale *= 10.0; expon -= 1; }
    }
    *out = sign * (frac ? (value / scale) : (value * scale));
  } else {
    // word tokens: na/nan -> 0, inf/infinity -> sign*1e308; an EMPTY
    // token (e.g. "1,,3") is 0.0 — the reference's cnt>0 branch is
    // skipped and *out keeps its 0 init (common.h:225-243).  Unknown
    // non-empty tokens are Log::Fatal there; nullptr here.
    size_t cnt = 0;
    while (p + cnt < end && p[cnt] != '\0' && p[cnt] != ' ' && p[cnt] != '\t' &&
           p[cnt] != ',' && p[cnt] != '\n' && p[cnt] != '\r' && p[cnt] != ':') {
      ++cnt;
    }
    if (cnt > 0) {
      std::string tmp(p, cnt);
      std::transform(tmp.begin(), tmp.end(), tmp.begin(), lower);
      if (tmp == "na" || tmp == "nan") {
        *out = 0;
      } else if (tmp == "inf" || tmp == "infinity") {
        *out = sign * 1e308;
      } else {
        return nullptr;  // unparseable token (reference: Log::Fatal)
      }
      p += cnt;
    }
  }
  return p;
}

// Collect [start, end) offsets of non-empty lines (memchr-driven).
void SplitLines(const char* buf, int64_t len, std::vector<std::pair<int64_t, int64_t>>* lines) {
  int64_t i = 0;
  while (i < len) {
    int64_t start = i;
    const char* nl = static_cast<const char*>(std::memchr(buf + i, '\n', len - i));
    int64_t stop = nl ? (nl - buf) : len;
    i = stop + 1;
    if (stop > start && buf[stop - 1] == '\r') --stop;
    bool blank = true;
    for (int64_t k = start; k < stop; ++k) {
      if (buf[k] != ' ' && buf[k] != '\t') { blank = false; break; }
    }
    if (!blank) lines->emplace_back(start, stop);
  }
}

// Opaque scan handle so dims + parse share ONE pass over the buffer.
struct ScanHandle {
  std::vector<std::pair<int64_t, int64_t>> lines;
};

inline bool IsSep(char c, char sep) {
  if (sep == ' ') return c == ' ' || c == '\t';  // whitespace mode
  return c == sep;
}

}  // namespace

extern "C" {

// Scan line structure once; reuse across dims + parse. Free with
// ltpu_scan_free.
void* ltpu_scan(const char* buf, int64_t len) {
  auto* h = new ScanHandle();
  SplitLines(buf, len, &h->lines);
  return h;
}

void ltpu_scan_free(void* handle) {
  delete static_cast<ScanHandle*>(handle);
}

// Count rows and columns of a dense file. sep==' ' means "any run of
// whitespace". Returns 0 ok, -1 ragged/invalid.
int ltpu_dims_csv(void* handle, const char* buf, char sep, int skip_lines,
                  int64_t* nrows, int* ncols) {
  auto& lines = static_cast<ScanHandle*>(handle)->lines;
  if (static_cast<size_t>(skip_lines) >= lines.size()) { *nrows = 0; *ncols = 0; return 0; }
  int cols = -1;
  for (size_t li = skip_lines; li < lines.size(); ++li) {
    const char* p = buf + lines[li].first;
    const char* end = buf + lines[li].second;
    int c = 0;
    bool in_tok = false;
    for (; p < end; ++p) {
      if (IsSep(*p, sep)) {
        if (sep != ' ' ) ++c;           // empty fields count for hard seps
        else if (in_tok) { in_tok = false; }
      } else {
        if (sep == ' ' && !in_tok) { ++c; in_tok = true; }
      }
    }
    if (sep != ' ') ++c;
    if (cols < 0) cols = c;
    else if (c != cols) return -1;
  }
  *nrows = static_cast<int64_t>(lines.size()) - skip_lines;
  *ncols = cols < 0 ? 0 : cols;
  return 0;
}

// Parse dense rows into out[nrows*ncols] (row major). Returns 0 ok,
// -1 on parse error or shape mismatch.
int ltpu_parse_csv(void* handle, const char* buf, char sep, int skip_lines,
                   double* out, int64_t nrows, int ncols, int nthreads) {
  auto& lines = static_cast<ScanHandle*>(handle)->lines;
  if (static_cast<int64_t>(lines.size()) - skip_lines != nrows) return -1;

  std::vector<int> errs(std::max(nthreads, 1), 0);
  auto work = [&](int tid, int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      const char* p = buf + lines[r + skip_lines].first;
      const char* end = buf + lines[r + skip_lines].second;
      double* row = out + r * ncols;
      for (int c = 0; c < ncols; ++c) {
        if (sep == ' ') {
          while (p < end && (*p == ' ' || *p == '\t')) ++p;
        }
        if (p >= end && !(sep != ' ' && c == ncols - 1)) {
          // allow trailing empty field only for hard separators
          if (c != ncols - 1) { errs[tid] = 1; return; }
        }
        const char* q = AtofRef(p, end, &row[c]);
        if (q == nullptr) { errs[tid] = 1; return; }
        p = q;
        if (sep != ' ') {
          while (p < end && *p != sep) ++p;  // skip junk to separator
          if (p < end) ++p;                  // skip separator
        }
      }
    }
  };

  int nt = std::max(1, nthreads);
  if (nt == 1 || nrows < 4096) {
    work(0, 0, nrows);
  } else {
    std::vector<std::thread> threads;
    int64_t chunk = (nrows + nt - 1) / nt;
    for (int t = 0; t < nt; ++t) {
      int64_t lo = t * chunk, hi = std::min(nrows, lo + chunk);
      if (lo >= hi) break;
      threads.emplace_back(work, t, lo, hi);
    }
    for (auto& th : threads) th.join();
  }
  for (int e : errs) if (e) return -1;
  return 0;
}

// LibSVM pass 1: rows and max feature index (1 + max seen 0-based col).
int ltpu_dims_libsvm(void* handle, const char* buf, int64_t* nrows, int* ncols) {
  auto& lines = static_cast<ScanHandle*>(handle)->lines;
  int maxc = -1;
  for (auto& ln : lines) {
    const char* p = buf + ln.first;
    const char* end = buf + ln.second;
    // label token first — skip it
    while (p < end && *p != ' ' && *p != '\t') ++p;
    while (p < end) {
      while (p < end && (*p == ' ' || *p == '\t')) ++p;
      if (p >= end) break;
      int idx = 0;
      bool any = false;
      while (p < end && *p >= '0' && *p <= '9') { idx = idx * 10 + (*p - '0'); ++p; any = true; }
      if (!any || p >= end || *p != ':') return -1;
      ++p;
      while (p < end && *p != ' ' && *p != '\t') ++p;  // skip value
      maxc = std::max(maxc, idx);
    }
  }
  *nrows = static_cast<int64_t>(lines.size());
  *ncols = maxc + 1;
  return 0;
}

// LibSVM pass 2: fill dense out[nrows*ncols] (pre-zeroed by caller) and
// labels[nrows].
int ltpu_parse_libsvm(void* handle, const char* buf, double* out, double* labels,
                      int64_t nrows, int ncols, int nthreads) {
  auto& lines = static_cast<ScanHandle*>(handle)->lines;
  if (static_cast<int64_t>(lines.size()) != nrows) return -1;

  std::vector<int> errs(std::max(nthreads, 1), 0);
  auto work = [&](int tid, int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      const char* p = buf + lines[r].first;
      const char* end = buf + lines[r].second;
      const char* q = AtofRef(p, end, &labels[r]);
      if (q == nullptr) { errs[tid] = 1; return; }
      p = q;
      double* row = out + r * ncols;
      while (p < end) {
        while (p < end && (*p == ' ' || *p == '\t')) ++p;
        if (p >= end) break;
        int idx = 0;
        while (p < end && *p >= '0' && *p <= '9') { idx = idx * 10 + (*p - '0'); ++p; }
        if (p >= end || *p != ':' || idx >= ncols) { errs[tid] = 1; return; }
        ++p;
        q = AtofRef(p, end, &row[idx]);
        if (q == nullptr) { errs[tid] = 1; return; }
        p = q;
      }
    }
  };

  int nt = std::max(1, nthreads);
  if (nt == 1 || nrows < 4096) {
    work(0, 0, nrows);
  } else {
    std::vector<std::thread> threads;
    int64_t chunk = (nrows + nt - 1) / nt;
    for (int t = 0; t < nt; ++t) {
      int64_t lo = t * chunk, hi = std::min(nrows, lo + chunk);
      if (lo >= hi) break;
      threads.emplace_back(work, t, lo, hi);
    }
    for (auto& th : threads) th.join();
  }
  for (int e : errs) if (e) return -1;
  return 0;
}

// Single-value Atof for host-side parity needs (e.g. tests).
double ltpu_atof(const char* s) {
  double v = 0;
  AtofRef(s, s + std::strlen(s), &v);
  return v;
}

}  // extern "C"
