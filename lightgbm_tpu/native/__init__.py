"""Native (C++) runtime components, loaded via ctypes.

The image has g++ but no pybind11, so the extension is a plain C ABI
shared library compiled on first use and cached next to the source
(keyed by a hash of the .cpp, so editing the source recompiles).
``get_lib()`` returns the loaded library or None when no compiler is
available — callers must keep a pure-Python fallback.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "parser.cpp")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build(src: str, out: str) -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread", src, "-o", out]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("LIGHTGBM_TPU_NO_NATIVE"):
            return None
        try:
            with open(_SRC, "rb") as f:
                digest = hashlib.sha256(f.read()).hexdigest()[:16]
        except OSError:
            return None
        cache_dir = os.environ.get(
            "LIGHTGBM_TPU_NATIVE_CACHE", os.path.join(_HERE, "_build")
        )
        so = os.path.join(cache_dir, f"parser_{digest}.so")
        if not os.path.exists(so):
            try:
                os.makedirs(cache_dir, exist_ok=True)
            except OSError:
                return None
            tmp = so + f".tmp{os.getpid()}"
            if not _build(_SRC, tmp):
                return None
            os.replace(tmp, so)
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            return None
        c_char_p = ctypes.c_char_p
        i64, i32, dbl = ctypes.c_int64, ctypes.c_int, ctypes.c_double
        pd = ctypes.POINTER(ctypes.c_double)
        vp = ctypes.c_void_p
        lib.ltpu_scan.argtypes = [c_char_p, i64]
        lib.ltpu_scan.restype = vp
        lib.ltpu_scan_free.argtypes = [vp]
        lib.ltpu_scan_free.restype = None
        lib.ltpu_dims_csv.argtypes = [vp, c_char_p, ctypes.c_char, i32,
                                      ctypes.POINTER(i64), ctypes.POINTER(i32)]
        lib.ltpu_dims_csv.restype = i32
        lib.ltpu_parse_csv.argtypes = [vp, c_char_p, ctypes.c_char, i32,
                                       pd, i64, i32, i32]
        lib.ltpu_parse_csv.restype = i32
        lib.ltpu_dims_libsvm.argtypes = [vp, c_char_p, ctypes.POINTER(i64),
                                         ctypes.POINTER(i32)]
        lib.ltpu_dims_libsvm.restype = i32
        lib.ltpu_parse_libsvm.argtypes = [vp, c_char_p, pd, pd, i64, i32, i32]
        lib.ltpu_parse_libsvm.restype = i32
        lib.ltpu_atof.argtypes = [c_char_p]
        lib.ltpu_atof.restype = dbl
        _LIB = lib
        return _LIB


def atof(s: str) -> float:
    """Reference-compatible Atof (common.h:163-261) of one token."""
    lib = get_lib()
    if lib is None:
        return float(s)
    return lib.ltpu_atof(s.encode())
