"""``python -m lightgbm_tpu task=train config=train.conf`` — the
counterpart of the ``lightgbm`` binary (src/main.cpp)."""

import sys

from .cli import main

sys.exit(main())
