"""Tree model layer — counterpart of src/io/tree.cpp +
include/LightGBM/tree.h.
"""

from .tree import Tree
from .ensemble import stack_trees

__all__ = ["Tree", "stack_trees"]
