"""Host-side tree model — counterpart of Tree (include/LightGBM/tree.h:18-230,
src/io/tree.cpp).

Node indexing parity: the reference's Tree::Split creates node
``num_leaves-1`` at each split (tree.cpp:55-58), so the s-th split record of
a GrowResult becomes node ``s``; child entries are node indices when >= 0
and ``~leaf`` when negative — identical to the reference's convention, so
ToString output is cross-loadable.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..utils.log import Log

K_MAX_TREE_OUTPUT = 100.0  # tree.h:13 kMaxTreeOutput


def _avoid_inf(x: float) -> float:
    """Common::AvoidInf — clamp +-inf for serialization."""
    if np.isinf(x):
        return 1e300 if x > 0 else -1e300
    return float(x)


def _fmt(values, fmt="%g") -> str:
    return " ".join(fmt % v for v in values)


class Tree:
    """SoA flat-array tree.  Numerical decision: fval <= threshold goes
    left; categorical: fval == threshold goes left (tree.h decision funs)."""

    def __init__(self, max_leaves: int = 2):
        m = max(max_leaves - 1, 1)
        self.num_leaves = 1
        self.left_child = np.zeros(m, np.int32)
        self.right_child = np.zeros(m, np.int32)
        self.split_feature_inner = np.zeros(m, np.int32)
        self.split_feature = np.zeros(m, np.int32)
        self.threshold_in_bin = np.zeros(m, np.int32)
        self.threshold = np.zeros(m, np.float64)
        self.decision_type = np.zeros(m, np.int8)  # 0 numerical, 1 categorical
        self.default_value = np.zeros(m, np.float64)
        self.zero_bin = np.zeros(m, np.int32)
        self.default_bin_for_zero = np.zeros(m, np.int32)
        self.split_gain = np.zeros(m, np.float64)
        self.leaf_parent = np.full(max_leaves, -1, np.int32)
        self.leaf_value = np.zeros(max_leaves, np.float64)
        self.leaf_count = np.zeros(max_leaves, np.int64)
        self.internal_value = np.zeros(m, np.float64)
        self.internal_count = np.zeros(m, np.int64)
        self.shrinkage_rate = 1.0
        self.has_categorical = False
        # piecewise-linear leaves (tree/linear.py plug-in); constant
        # trees keep is_linear False and serialize byte-identically to
        # the pre-plug-in format
        self.is_linear = False
        self.leaf_features: List[tuple] = []  # real feature idx per leaf
        self.leaf_features_inner: List[tuple] = []
        self.leaf_coeff: List[tuple] = []
        self.leaf_const = np.zeros(max_leaves, np.float64)
        self.leaf_is_linear = np.zeros(max_leaves, bool)

    # ------------------------------------------------------------------
    def split(
        self,
        leaf: int,
        feature: int,
        bin_type_categorical: bool,
        threshold_bin: int,
        real_feature: int,
        threshold_double: float,
        left_value: float,
        right_value: float,
        left_cnt: int,
        right_cnt: int,
        gain: float,
        zero_bin: int,
        default_bin_for_zero: int,
        default_value: float,
    ) -> int:
        """Tree::Split (tree.cpp:55-105)."""
        new_node = self.num_leaves - 1
        parent = self.leaf_parent[leaf]
        if parent >= 0:
            if self.left_child[parent] == ~leaf:
                self.left_child[parent] = new_node
            else:
                self.right_child[parent] = new_node
        self.split_feature_inner[new_node] = feature
        self.split_feature[new_node] = real_feature
        self.zero_bin[new_node] = zero_bin
        self.default_bin_for_zero[new_node] = default_bin_for_zero
        self.default_value[new_node] = _avoid_inf(default_value)
        if bin_type_categorical:
            self.decision_type[new_node] = 1
            self.has_categorical = True
        else:
            self.decision_type[new_node] = 0
        self.threshold_in_bin[new_node] = threshold_bin
        self.threshold[new_node] = _avoid_inf(threshold_double)
        self.split_gain[new_node] = _avoid_inf(gain)
        self.left_child[new_node] = ~leaf
        self.right_child[new_node] = ~self.num_leaves
        self.leaf_parent[leaf] = new_node
        self.leaf_parent[self.num_leaves] = new_node
        self.internal_value[new_node] = self.leaf_value[leaf]
        self.internal_count[new_node] = left_cnt + right_cnt
        self.leaf_value[leaf] = 0.0 if np.isnan(left_value) else left_value
        self.leaf_count[leaf] = left_cnt
        self.leaf_value[self.num_leaves] = 0.0 if np.isnan(right_value) else right_value
        self.leaf_count[self.num_leaves] = right_cnt
        self.num_leaves += 1
        return self.num_leaves - 1

    # ------------------------------------------------------------------
    @classmethod
    def from_grow_result(cls, gr, dataset) -> "Tree":
        """Build from a device GrowResult (ops/grow.py) using the dataset's
        bin mappers for real thresholds (Dataset::RealThreshold)."""
        num_splits = int(gr.num_splits)
        rec_leaf = np.asarray(gr.rec_leaf)
        rec_feat = np.asarray(gr.rec_feat)
        rec_thr = np.asarray(gr.rec_thr)
        rec_dbz = np.asarray(gr.rec_dbz)
        rec_gain = np.asarray(gr.rec_gain)
        rec_lval = np.asarray(gr.rec_lval, np.float64)
        rec_rval = np.asarray(gr.rec_rval, np.float64)
        rec_lcnt = np.asarray(gr.rec_lcnt)
        rec_rcnt = np.asarray(gr.rec_rcnt)
        rec_ival = np.asarray(gr.rec_internal_value, np.float64)

        tree = cls(max(num_splits + 1, 2))
        for s in range(num_splits):
            inner = int(rec_feat[s])
            mapper = dataset.bin_mappers[inner]
            thr_bin = int(rec_thr[s])
            dbz = int(rec_dbz[s])
            tree.split(
                leaf=int(rec_leaf[s]),
                feature=inner,
                bin_type_categorical=mapper.bin_type == 1,
                threshold_bin=thr_bin,
                real_feature=dataset.inner_to_real_feature(inner),
                threshold_double=mapper.bin_to_value(thr_bin),
                left_value=float(rec_lval[s]),
                right_value=float(rec_rval[s]),
                left_cnt=int(rec_lcnt[s]),
                right_cnt=int(rec_rcnt[s]),
                gain=float(rec_gain[s]),
                zero_bin=mapper.default_bin,
                default_bin_for_zero=dbz,
                default_value=mapper.bin_to_value(dbz),
            )
            # the grower stores the PARENT's value in rec_internal_value
            tree.internal_value[s] = rec_ival[s]
        return tree

    @classmethod
    def constant(cls, value: float) -> "Tree":
        """The boost-from-average init tree: 2 leaves, both = value
        (gbdt.cpp:391-394)."""
        tree = cls(2)
        tree.split(0, 0, False, 0, 0, 0.0, value, value, 0, 0, -1.0, 0, 0, 0.0)
        return tree

    # ------------------------------------------------------------------
    def set_linear_models(self, paths_inner, coeff, const, ok, dataset) -> None:
        """Attach per-leaf linear models from the batched ridge solve
        (tree/linear.py): ``coeff`` (L, k) slopes, ``const`` (L,)
        intercepts, ``ok`` (L,) validity.  Leaves with ``ok`` False keep
        the grower's constant ``leaf_value`` (fallback contract).  Call
        BEFORE ``shrinkage`` so the learning rate scales both forms."""
        n = self.num_leaves
        coeff = np.asarray(coeff, np.float64)
        const = np.asarray(const, np.float64)
        ok = np.asarray(ok, bool)
        self.is_linear = True
        self.leaf_features_inner = []
        self.leaf_features = []
        self.leaf_coeff = []
        for i in range(n):
            path = tuple(paths_inner[i]) if ok[i] else ()
            self.leaf_features_inner.append(path)
            self.leaf_features.append(
                tuple(dataset.inner_to_real_feature(f) for f in path))
            self.leaf_coeff.append(tuple(coeff[i, : len(path)]))
            self.leaf_is_linear[i] = ok[i] and len(path) > 0
            self.leaf_const[i] = const[i] if self.leaf_is_linear[i] else 0.0

    def shrinkage(self, rate: float) -> None:
        """Tree::Shrinkage with the +-100 output clamp (tree.h:116-128)."""
        n = self.num_leaves
        self.leaf_value[:n] = np.clip(
            self.leaf_value[:n] * rate, -K_MAX_TREE_OUTPUT, K_MAX_TREE_OUTPUT
        )
        if self.is_linear:
            self.leaf_const[:n] *= rate
            self.leaf_coeff = [tuple(c * rate for c in cs)
                               for cs in self.leaf_coeff]
        self.shrinkage_rate *= rate

    # ------------------------------------------------------------------
    def predict(self, data: np.ndarray) -> np.ndarray:
        """Host (numpy) batch predict over raw features — the reference's
        Tree::Predict walk (tree.h:232-276); device path is ops/predict.py."""
        from ..io.binning import MISSING_VALUE_RANGE

        n = data.shape[0]
        out = np.zeros(n)
        if self.num_leaves <= 1:
            out[:] = self.leaf_value[0]
            return out
        leaf = self.predict_leaf_index(data)
        out = self.leaf_value[leaf]
        if self.is_linear:
            for i in np.nonzero(self.leaf_is_linear[: self.num_leaves])[0]:
                rows = np.nonzero(leaf == i)[0]
                if rows.size == 0:
                    continue
                x = data[np.ix_(rows, np.asarray(self.leaf_features[i]))]
                lin = self.leaf_const[i] + x @ np.asarray(self.leaf_coeff[i])
                # a NaN path feature degrades that row to the constant
                out[rows] = np.where(np.isfinite(lin), lin, out[rows])
        return out

    def predict_leaf_index(self, data: np.ndarray) -> np.ndarray:
        from ..io.binning import MISSING_VALUE_RANGE

        n = data.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, np.int32)
        node = np.zeros(n, np.int32)
        active = node >= 0
        while np.any(active):
            j = np.where(active, node, 0)
            fval = data[np.arange(n), self.split_feature[j]]
            is_zero = (
                ((fval > -MISSING_VALUE_RANGE) & (fval <= MISSING_VALUE_RANGE))
                | np.isnan(fval)
            )
            fval = np.where(is_zero, self.default_value[j], fval)
            goes_left = np.where(
                self.decision_type[j] == 1,
                fval.astype(np.int64) == self.threshold[j].astype(np.int64),
                fval <= self.threshold[j],
            )
            nxt = np.where(goes_left, self.left_child[j], self.right_child[j])
            node = np.where(active, nxt, node)
            active = node >= 0
        return (~node).astype(np.int32)

    # ------------------------------------------------------------------
    def to_string(self) -> str:
        """Tree::ToString (tree.cpp:312-343) — reference text format."""
        n = self.num_leaves
        m = n - 1
        lines = [
            f"num_leaves={n}",
            "split_feature=" + _fmt(self.split_feature[:m], "%d"),
            "split_gain=" + _fmt(self.split_gain[:m]),
            "threshold=" + _fmt(self.threshold[:m], "%.17g"),
            "decision_type=" + _fmt(self.decision_type[:m], "%d"),
            "default_value=" + _fmt(self.default_value[:m], "%.17g"),
            "left_child=" + _fmt(self.left_child[:m], "%d"),
            "right_child=" + _fmt(self.right_child[:m], "%d"),
            "leaf_parent=" + _fmt(self.leaf_parent[:n], "%d"),
            "leaf_value=" + _fmt(self.leaf_value[:n], "%.17g"),
            "leaf_count=" + _fmt(self.leaf_count[:n], "%d"),
            "internal_value=" + _fmt(self.internal_value[:m], "%.17g"),
            "internal_count=" + _fmt(self.internal_count[:m], "%d"),
            f"shrinkage={self.shrinkage_rate:g}",
            f"has_categorical={1 if self.has_categorical else 0}",
        ]
        if self.is_linear:
            # the reference's linear-tree block (tree.cpp ToString when
            # linear_tree): per-leaf intercepts, path-feature counts,
            # then flattened features/coefficients
            counts = [len(self.leaf_features[i]) for i in range(n)]
            flat_feat = [f for i in range(n) for f in self.leaf_features[i]]
            flat_coef = [c for i in range(n) for c in self.leaf_coeff[i]]
            lines += [
                "is_linear=1",
                "leaf_const=" + _fmt(self.leaf_const[:n], "%.17g"),
                "num_features=" + _fmt(counts, "%d"),
                "leaf_features=" + _fmt(flat_feat, "%d"),
                "leaf_coeff=" + _fmt(flat_coef, "%.17g"),
            ]
        lines.append("")
        return "\n".join(lines) + "\n"

    @classmethod
    def from_string(cls, s: str) -> "Tree":
        """Tree::Tree(const std::string&) (tree.cpp:443-552)."""
        kv = {}
        for line in s.splitlines():
            if "=" in line:
                k, _, v = line.partition("=")
                k, v = k.strip(), v.strip()
                if k and v:
                    kv[k] = v
        if "num_leaves" not in kv:
            Log.fatal("Tree model should contain num_leaves field.")
        n = int(kv["num_leaves"])
        tree = cls(max(n, 2))
        tree.num_leaves = n
        if n <= 1:
            return tree

        def arr(key, dtype, count, required=True):
            if key not in kv:
                if required:
                    Log.fatal("Tree model string format error, should contain %s field", key)
                return np.zeros(count, dtype)
            return np.array(kv[key].split(), dtype=np.float64).astype(dtype)[:count]

        m = n - 1
        tree.left_child[:m] = arr("left_child", np.int32, m)
        tree.right_child[:m] = arr("right_child", np.int32, m)
        tree.split_feature[:m] = arr("split_feature", np.int32, m)
        tree.split_feature_inner[:m] = tree.split_feature[:m]
        tree.threshold[:m] = arr("threshold", np.float64, m)
        tree.default_value[:m] = arr("default_value", np.float64, m)
        tree.leaf_value[:n] = arr("leaf_value", np.float64, n)
        tree.split_gain[:m] = arr("split_gain", np.float64, m, required=False)
        tree.internal_value[:m] = arr("internal_value", np.float64, m, required=False)
        tree.internal_count[:m] = arr("internal_count", np.int64, m, required=False)
        tree.leaf_count[:n] = arr("leaf_count", np.int64, n, required=False)
        tree.leaf_parent[:n] = arr("leaf_parent", np.int32, n, required=False)
        tree.decision_type[:m] = arr("decision_type", np.int8, m, required=False)
        tree.has_categorical = bool(np.any(tree.decision_type[:m] == 1))
        if "shrinkage" in kv:
            tree.shrinkage_rate = float(kv["shrinkage"])
        if int(kv.get("is_linear", "0")):
            tree.is_linear = True
            tree.leaf_const[:n] = arr("leaf_const", np.float64, n)
            counts = arr("num_features", np.int64, n)
            flat_feat = (np.array(kv["leaf_features"].split(), np.int64)
                         if kv.get("leaf_features") else np.zeros(0, np.int64))
            flat_coef = (np.array(kv["leaf_coeff"].split(), np.float64)
                         if kv.get("leaf_coeff") else np.zeros(0))
            off = 0
            for i in range(n):
                c = int(counts[i])
                feats = tuple(int(f) for f in flat_feat[off:off + c])
                tree.leaf_features.append(feats)
                tree.leaf_features_inner.append(feats)
                tree.leaf_coeff.append(tuple(flat_coef[off:off + c]))
                tree.leaf_is_linear[i] = c > 0
                off += c
        return tree

    # ------------------------------------------------------------------
    def _node_json(self, idx: int) -> dict:
        """Tree::NodeToJSON (tree.cpp:359-440)."""
        if idx >= 0:
            return {
                "split_index": int(idx),
                "split_feature": int(self.split_feature[idx]),
                "split_gain": float(self.split_gain[idx]),
                "threshold": float(self.threshold[idx]),
                "decision_type": "==" if self.decision_type[idx] == 1 else "<=",
                "default_value": float(self.default_value[idx]),
                "internal_value": float(self.internal_value[idx]),
                "internal_count": int(self.internal_count[idx]),
                "left_child": self._node_json(self.left_child[idx]),
                "right_child": self._node_json(self.right_child[idx]),
            }
        leaf = ~idx
        node = {
            "leaf_index": int(leaf),
            "leaf_parent": int(self.leaf_parent[leaf]),
            "leaf_value": float(self.leaf_value[leaf]),
            "leaf_count": int(self.leaf_count[leaf]),
        }
        if self.is_linear and self.leaf_is_linear[leaf]:
            node["leaf_const"] = float(self.leaf_const[leaf])
            node["leaf_features"] = [int(f) for f in self.leaf_features[leaf]]
            node["leaf_coeff"] = [float(c) for c in self.leaf_coeff[leaf]]
        return node

    def to_json(self) -> dict:
        out = {
            "num_leaves": int(self.num_leaves),
            "shrinkage": float(self.shrinkage_rate),
            "has_categorical": 1 if self.has_categorical else 0,
            "tree_structure": self._node_json(0 if self.num_leaves > 1 else -1),
        }
        if self.is_linear:
            out["is_linear"] = 1
        return out
