"""Stacking host Trees into device SoA arrays for batched prediction
(ops/predict.py).  Counterpart of the per-tree loops in
GBDT::PredictRaw/Predict (src/boosting/gbdt_prediction.cpp) — here all
trees traverse in one vmapped program.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np


def split_hi_lo(x: np.ndarray):
    """Triple-float (hi, lo, lo2) planes of a float64 array:
    hi = f32(x), lo = f32(x - hi), lo2 = f32(x - hi - lo).

    A lexicographic (hi, lo, lo2) comparison reproduces the float64
    ``<=`` EXACTLY for f64-sourced values: the TPU has no native f64,
    and a single-f32 comparison flips tree decisions whenever a feature
    value lands within f32 rounding of a threshold
    (Tree::NumericalDecision is a double compare, tree.h:139-145).
    Two planes (~2^-48 rel) still collapse 1-ulp f64 differences
    (2^-52); the third plane (~2^-72) discriminates every distinct f64
    pair, so equality of triples implies equality of doubles."""
    f32max = np.finfo(np.float32).max
    c = np.clip(x, -f32max, f32max)
    hi = c.astype(np.float32)
    r1 = c - hi.astype(np.float64)
    lo = np.clip(r1, -f32max, f32max).astype(np.float32)
    r2 = r1 - lo.astype(np.float64)
    lo2 = np.clip(r2, -f32max, f32max).astype(np.float32)
    return hi, lo, lo2


def stack_trees(trees: List) -> dict:
    """Pad T trees to (T, M)/(T, L) arrays.  Unused node slots point at
    leaf 0; a 1-leaf tree gets a sentinel node routing everything to its
    single leaf."""
    t = len(trees)
    m = max(max((tr.num_leaves - 1 for tr in trees), default=1), 1)
    L = max(max((tr.num_leaves for tr in trees), default=1), 1)

    def zf(shape, dtype):
        return np.zeros(shape, dtype)

    split_feature = zf((t, m), np.int32)
    split_feature_inner = zf((t, m), np.int32)
    threshold_bin = zf((t, m), np.int32)
    threshold_real = zf((t, m), np.float64)
    zero_bin = zf((t, m), np.int32)
    dbz = zf((t, m), np.int32)
    default_value = zf((t, m), np.float64)
    is_cat = zf((t, m), np.bool_)
    left = np.full((t, m), -1, np.int32)
    right = np.full((t, m), -1, np.int32)
    leaf_value = zf((t, L), np.float32)

    for i, tr in enumerate(trees):
        n = tr.num_leaves
        if n <= 1:
            # sentinel: node 0 sends every row to leaf 0
            threshold_real[i, 0] = np.inf
            threshold_bin[i, 0] = np.iinfo(np.int32).max
            left[i, 0] = -1  # ~0
            right[i, 0] = -1
            leaf_value[i, 0] = tr.leaf_value[0]
            continue
        k = n - 1
        split_feature[i, :k] = tr.split_feature[:k]
        split_feature_inner[i, :k] = tr.split_feature_inner[:k]
        threshold_bin[i, :k] = tr.threshold_in_bin[:k]
        threshold_real[i, :k] = tr.threshold[:k]
        zero_bin[i, :k] = tr.zero_bin[:k]
        dbz[i, :k] = tr.default_bin_for_zero[:k]
        default_value[i, :k] = tr.default_value[:k]
        is_cat[i, :k] = tr.decision_type[:k] == 1
        left[i, :k] = tr.left_child[:k]
        right[i, :k] = tr.right_child[:k]
        leaf_value[i, :n] = tr.leaf_value[:n]

    thr_hi, thr_lo, thr_lo2 = split_hi_lo(threshold_real)
    dv_hi, dv_lo, dv_lo2 = split_hi_lo(default_value)
    out = _linear_planes(trees, t, L)
    out.update({
        "split_feature": jnp.asarray(split_feature),
        "split_feature_inner": jnp.asarray(split_feature_inner),
        "threshold_bin": jnp.asarray(threshold_bin),
        "threshold_real": jnp.asarray(thr_hi),
        "threshold_real_lo": jnp.asarray(thr_lo),
        "threshold_real_lo2": jnp.asarray(thr_lo2),
        "zero_bin": jnp.asarray(zero_bin),
        "default_bin_for_zero": jnp.asarray(dbz),
        "default_value": jnp.asarray(dv_hi),
        "default_value_lo": jnp.asarray(dv_lo),
        "default_value_lo2": jnp.asarray(dv_lo2),
        "is_categorical": jnp.asarray(is_cat),
        "left_child": jnp.asarray(left),
        "right_child": jnp.asarray(right),
        "leaf_value": jnp.asarray(leaf_value),
    })
    return out


def _linear_planes(trees: List, t: int, L: int) -> dict:
    """Linear-leaf coefficient planes (tree/linear.py plug-in), emitted
    only when at least one tree carries linear leaf models so constant
    ensembles keep the exact 15-array layout.  ``leaf_feat_inner``
    drives binned traversal paths (training/valid scores, + the bin
    value LUT), ``leaf_feat_real`` the raw serving gather; padded
    coefficient slots are zero with ``leaf_feat_valid`` 0, so the
    padded dot product is exact."""
    if not any(getattr(tr, "is_linear", False) for tr in trees):
        return {}
    K = 1
    for tr in trees:
        if getattr(tr, "is_linear", False):
            for fs in tr.leaf_features:
                K = max(K, len(fs))
    feat_inner = np.zeros((t, L, K), np.int32)
    feat_real = np.zeros((t, L, K), np.int32)
    feat_valid = np.zeros((t, L, K), np.float32)
    coeff = np.zeros((t, L, K), np.float32)
    const = np.zeros((t, L), np.float32)
    is_lin = np.zeros((t, L), np.bool_)
    for i, tr in enumerate(trees):
        if not getattr(tr, "is_linear", False):
            continue
        n = max(tr.num_leaves, 1)
        const[i, :n] = tr.leaf_const[:n]
        is_lin[i, :n] = tr.leaf_is_linear[:n]
        for li in range(min(n, len(tr.leaf_features))):
            fs = tr.leaf_features[li]
            if not fs or not tr.leaf_is_linear[li]:
                continue
            k = len(fs)
            feat_real[i, li, :k] = fs
            feat_inner[i, li, :k] = tr.leaf_features_inner[li]
            feat_valid[i, li, :k] = 1.0
            coeff[i, li, :k] = tr.leaf_coeff[li]
    return {
        "leaf_feat_inner": jnp.asarray(feat_inner),
        "leaf_feat_real": jnp.asarray(feat_real),
        "leaf_feat_valid": jnp.asarray(feat_valid),
        "leaf_coeff": jnp.asarray(coeff),
        "leaf_const": jnp.asarray(const),
        "leaf_is_linear": jnp.asarray(is_lin),
    }
