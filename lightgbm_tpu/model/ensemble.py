"""Stacking host Trees into device SoA arrays for batched prediction
(ops/predict.py).  Counterpart of the per-tree loops in
GBDT::PredictRaw/Predict (src/boosting/gbdt_prediction.cpp) — here all
trees traverse in one vmapped program.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np


def stack_trees(trees: List) -> dict:
    """Pad T trees to (T, M)/(T, L) arrays.  Unused node slots point at
    leaf 0; a 1-leaf tree gets a sentinel node routing everything to its
    single leaf."""
    t = len(trees)
    m = max(max((tr.num_leaves - 1 for tr in trees), default=1), 1)
    L = max(max((tr.num_leaves for tr in trees), default=1), 1)

    def zf(shape, dtype):
        return np.zeros(shape, dtype)

    split_feature = zf((t, m), np.int32)
    split_feature_inner = zf((t, m), np.int32)
    threshold_bin = zf((t, m), np.int32)
    threshold_real = zf((t, m), np.float32)
    zero_bin = zf((t, m), np.int32)
    dbz = zf((t, m), np.int32)
    default_value = zf((t, m), np.float32)
    is_cat = zf((t, m), np.bool_)
    left = np.full((t, m), -1, np.int32)
    right = np.full((t, m), -1, np.int32)
    leaf_value = zf((t, L), np.float32)

    for i, tr in enumerate(trees):
        n = tr.num_leaves
        if n <= 1:
            # sentinel: node 0 sends every row to leaf 0
            threshold_real[i, 0] = np.inf
            threshold_bin[i, 0] = np.iinfo(np.int32).max
            left[i, 0] = -1  # ~0
            right[i, 0] = -1
            leaf_value[i, 0] = tr.leaf_value[0]
            continue
        k = n - 1
        f32max = np.finfo(np.float32).max
        split_feature[i, :k] = tr.split_feature[:k]
        split_feature_inner[i, :k] = tr.split_feature_inner[:k]
        threshold_bin[i, :k] = tr.threshold_in_bin[:k]
        threshold_real[i, :k] = np.clip(tr.threshold[:k], -f32max, f32max)
        zero_bin[i, :k] = tr.zero_bin[:k]
        dbz[i, :k] = tr.default_bin_for_zero[:k]
        default_value[i, :k] = np.clip(tr.default_value[:k], -f32max, f32max)
        is_cat[i, :k] = tr.decision_type[:k] == 1
        left[i, :k] = tr.left_child[:k]
        right[i, :k] = tr.right_child[:k]
        leaf_value[i, :n] = tr.leaf_value[:n]

    return {
        "split_feature": jnp.asarray(split_feature),
        "split_feature_inner": jnp.asarray(split_feature_inner),
        "threshold_bin": jnp.asarray(threshold_bin),
        "threshold_real": jnp.asarray(threshold_real),
        "zero_bin": jnp.asarray(zero_bin),
        "default_bin_for_zero": jnp.asarray(dbz),
        "default_value": jnp.asarray(default_value),
        "is_categorical": jnp.asarray(is_cat),
        "left_child": jnp.asarray(left),
        "right_child": jnp.asarray(right),
        "leaf_value": jnp.asarray(leaf_value),
    }
