"""Hardened multi-host transport: deadlines, retry/backoff, peer-failure
detection, cooperative abort, and collective fault injection.

The reference's socket linker (src/network/linkers_socket.cpp Construct)
retries connects against the machine list under a socket timeout and
fails loudly when a peer never answers.  The JAX replacement had no such
layer: the KV-store allgather blocked 120 s per key with no liveness
signal, the device allgather and ``jax.distributed.initialize`` had no
bound at all — one SIGKILLed rank (or a dead TPU tunnel, the BENCH_r05
hang class) stalled every surviving host indefinitely.  This module is
that missing layer:

- **Deadlines.**  Every hardened primitive is bounded by
  ``NetSettings.deadline_s`` (param ``network_timeout``, env
  ``LIGHTGBM_TPU_NET_TIMEOUT``).  Nothing blocks forever.
- **Retry/backoff.**  Transient RPC failures retry on a deterministic
  exponential backoff schedule (``network_retries`` /
  ``LIGHTGBM_TPU_NET_RETRIES``), capped by the deadline budget.
- **Peer liveness.**  Each rank's :class:`HeartbeatWriter` rotates a
  per-rank key under ``ltpu_hb/`` in the distributed KV store (the
  store is write-once, so beats write seq N then delete seq N-1); the
  :class:`PeerWatch` sweeper declares a rank dead when its key set has
  not *changed* for ``stale_after_s`` of **local** observation time —
  no cross-host clock comparison is ever made.
- **Typed failures.**  A dead peer surfaces as :class:`PeerFailureError`
  within ~2x the deadline (wait window + staleness window); a lost or
  wedged collective with live peers surfaces as
  :class:`CollectiveTimeoutError`.  Both carry ``elapsed_s``.
- **Cooperative abort.**  On a peer failure the survivors flush the
  latest checkpoint (``ckpt.manager``) and leave through
  :func:`hard_exit` — the JAX distributed-shutdown atexit barrier blocks
  ~100 s against a dead peer and then kills the process with a fatal
  log, so survivors must bypass interpreter exit.  ``task=train``
  auto-resume then restores bit-identically (docs/ROBUSTNESS.md).
- **Fault injection.**  ``LIGHTGBM_TPU_FAULT=die:N|drop_collective:N|
  delay:ms|delay:ms:after:N`` (optionally gated by
  ``LIGHTGBM_TPU_FAULT_RANK``) is checked at every hardened collective,
  so kill/hang/straggler scenarios are testable on a real subprocess
  matrix (tests/test_net_fault.py).  The ``after:N`` form arms the
  per-collective slowdown only from the N-th call on, so a rank can
  *become* a straggler mid-run; :func:`set_delay_scale` scales every
  injected delay multiplicatively (the GBDT driver ties it to the
  rank's current/initial row-count ratio, modeling a host whose
  per-row compute is slow — so shard rebalancing measurably shrinks
  the injected straggler's iteration time, docs/ROBUSTNESS.md).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import struct
import sys
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..obs import tracer
from ..utils.log import Log

_HB_DIR = "ltpu_hb/"
_COLLECT_DIR = "ltpu_collect/"
_CHUNK_DIR = "ltpu_chunk/"

# Epoch-scoped collective uid layout, shared by every issuer of
# kv_gather uids (membership.py namespaces, collect.py gathers): bits
# [EPOCH_SHIFT, EPOCH_SHIFT + EPOCH_BITS) carry the membership epoch,
# the low bits the per-epoch sequence/participant digest, bits above
# the purpose namespace.  Scoping uids by epoch means a collective
# retried after a live-membership resize can never read a stale
# pre-transition payload — the key subtrees are disjoint by
# construction, and the coordinator's commit-time GC can reap a whole
# superseded epoch by its uid field alone.
EPOCH_SHIFT = 40
EPOCH_BITS = 18


def epoch_uid(epoch: int, seq: int, ns: int = 0) -> int:
    """Compose ``ns | epoch-field | seq`` for an epoch-scoped collective."""
    epoch = int(epoch)
    if not 0 <= epoch < (1 << EPOCH_BITS):
        raise ValueError(f"epoch {epoch} outside the uid epoch field")
    return int(ns) | (epoch << EPOCH_SHIFT) | int(seq)


def uid_epoch(uid: int) -> int:
    """The epoch field of an epoch-scoped uid (0 for static-world uids)."""
    return (int(uid) >> EPOCH_SHIFT) & ((1 << EPOCH_BITS) - 1)


def _flight_dump(reason: str, error: Optional[BaseException] = None,
                 **attrs) -> None:
    """Flush the crash flight recorder (obs/flight.py) the moment a
    typed transport failure is about to be raised: the survivor's
    flush-and-exit path then always leaves a ``<trace>.crash.jsonl``
    with the final spans before the failure.  No-op when tracing (and
    therefore the ring) is off; never raises."""
    try:
        from ..obs import flight

        flight.dump(reason, error=error, **attrs)
    except Exception:  # pragma: no cover - dying path must not re-fail
        pass


# ----------------------------------------------------------------------
# error hierarchy
# ----------------------------------------------------------------------
class NetError(RuntimeError):
    """Base of the hardened-transport failures (all are bounded: they
    carry how long the operation waited before giving up)."""

    def __init__(self, msg: str, elapsed_s: float = 0.0):
        super().__init__(msg)
        self.elapsed_s = float(elapsed_s)


class CollectiveTimeoutError(NetError):
    """The deadline budget expired but every peer still looks alive —
    a lost, wedged, or badly skewed collective (or an unreachable
    coordinator during bootstrap)."""


class PeerFailureError(NetError):
    """One or more peer ranks stopped heartbeating (or the coordinator
    process died): the run cannot continue and survivors should flush
    the latest checkpoint and exit for auto-resume."""

    def __init__(self, msg: str, ranks: Sequence[int] = (),
                 elapsed_s: float = 0.0):
        super().__init__(msg, elapsed_s)
        self.ranks = tuple(int(r) for r in ranks)


# ----------------------------------------------------------------------
# settings: defaults < config params < env < explicit configure()
# ----------------------------------------------------------------------
@dataclasses.dataclass
class NetSettings:
    """Deadline/retry knobs for every hardened primitive."""

    deadline_s: float = 120.0      # per-collective wait window
    retries: int = 3               # transient-error retry attempts
    backoff_base_s: float = 0.1    # first backoff; doubles per attempt
    backoff_max_s: float = 5.0     # backoff cap
    heartbeat_interval_s: float = 0.0  # 0 = auto: deadline/4, capped 5 s
    stale_after_s: float = 0.0         # 0 = auto: deadline

    def hb_interval(self) -> float:
        if self.heartbeat_interval_s > 0:
            return self.heartbeat_interval_s
        return min(max(self.deadline_s / 4.0, 0.05), 5.0)

    def stale_after(self) -> float:
        return self.stale_after_s if self.stale_after_s > 0 else self.deadline_s

    def poll_s(self) -> float:
        """KV poll / watchdog tick slice: short enough that liveness
        checks interleave, long enough not to hammer the coordinator."""
        return min(max(self.deadline_s / 16.0, 0.05), 0.5)


_ENV_FIELDS: Dict[str, Tuple[str, type]] = {
    "deadline_s": ("LIGHTGBM_TPU_NET_TIMEOUT", float),
    "retries": ("LIGHTGBM_TPU_NET_RETRIES", int),
    "backoff_base_s": ("LIGHTGBM_TPU_NET_BACKOFF", float),
    "heartbeat_interval_s": ("LIGHTGBM_TPU_NET_HEARTBEAT", float),
    "stale_after_s": ("LIGHTGBM_TPU_NET_STALE_AFTER", float),
}

_CONFIG_FIELDS = {
    "deadline_s": "network_timeout",
    "retries": "network_retries",
    "heartbeat_interval_s": "network_heartbeat_interval",
}

_settings: Optional[NetSettings] = None
_settings_lock = threading.Lock()


def _apply_env(s: NetSettings) -> NetSettings:
    for field, (var, typ) in _ENV_FIELDS.items():
        raw = os.environ.get(var, "").strip()
        if raw:
            try:
                setattr(s, field, typ(float(raw)) if typ is int else typ(raw))
            except ValueError:
                Log.warning("Unparsable %s=%r ignored", var, raw)
    return s


def settings() -> NetSettings:
    """The process-wide net settings (env read once, lazily)."""
    global _settings
    with _settings_lock:
        if _settings is None:
            _settings = _apply_env(NetSettings())
        return _settings


def configure(**kw) -> NetSettings:
    """Explicitly override settings fields (tests / embedding runtimes).
    Wins over both config params and env."""
    s = settings()
    for k, v in kw.items():
        if not hasattr(s, k):
            raise TypeError(f"unknown net setting {k!r}")
        setattr(s, k, v)
    return s


def configure_from_config(config) -> NetSettings:
    """Pull ``network_timeout``/``network_retries``/
    ``network_heartbeat_interval`` from a Config.  Env vars win over
    config params (the deployment launcher owns the env)."""
    s = settings()
    for field, param in _CONFIG_FIELDS.items():
        if os.environ.get(_ENV_FIELDS[field][0], "").strip():
            continue  # env override outranks the param surface
        val = getattr(config, param, None)
        if val is not None and float(val) > 0:
            setattr(s, field, type(getattr(s, field))(val))
    return s


def _reset_for_tests() -> None:
    """Drop cached settings/fault state so env changes take effect."""
    global _settings, _fault_specs, _fault_calls, _delay_scale, _wait_clock_s
    with _settings_lock:
        _settings = None
    with _fault_lock:
        _fault_specs = None
        _fault_calls = 0
    _delay_scale = 1.0
    with _wait_clock_lock:
        _wait_clock_s = 0.0
    _chunks_written.clear()


# ----------------------------------------------------------------------
# retry / backoff
# ----------------------------------------------------------------------
def backoff_schedule(retries: int, base_s: float, max_s: float) -> List[float]:
    """Deterministic exponential backoff: base, 2*base, 4*base, ...
    capped at ``max_s`` — one delay per retry attempt."""
    return [min(base_s * (2.0 ** i), max_s) for i in range(max(retries, 0))]


def retry_call(fn: Callable, what: str, retries: Optional[int] = None,
               deadline_s: Optional[float] = None,
               retry_on=(Exception,)):
    """Call ``fn`` with bounded retries on a backoff schedule.  The
    cumulative elapsed time (attempts + sleeps) never exceeds
    ``deadline_s``; exhaustion raises :class:`CollectiveTimeoutError`
    chaining the last error."""
    s = settings()
    retries = s.retries if retries is None else int(retries)
    deadline = s.deadline_s if deadline_s is None else float(deadline_s)
    delays = backoff_schedule(retries, s.backoff_base_s, s.backoff_max_s)
    t0 = time.monotonic()
    last: Optional[BaseException] = None
    for attempt in range(retries + 1):
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 - retry loop
            last = e
            elapsed = time.monotonic() - t0
            tracer.counter("net.retry", what=what)
            if attempt >= retries or elapsed + delays[attempt] > deadline:
                break
            Log.warning("%s failed (attempt %d/%d): %s — retrying in %.2fs",
                        what, attempt + 1, retries + 1, e, delays[attempt])
            time.sleep(delays[attempt])
    elapsed = time.monotonic() - t0
    tracer.counter("net.timeout", what=what)
    _flight_dump("collective_timeout", error=last, what=what,
                 elapsed_s=round(elapsed, 3))
    raise CollectiveTimeoutError(
        f"{what} failed after {elapsed:.1f}s "
        f"(retries={retries}, deadline={deadline:.0f}s): {last}",
        elapsed_s=elapsed,
    ) from last


# ----------------------------------------------------------------------
# fault injection (tests / chaos drills)
# ----------------------------------------------------------------------
_fault_specs: Optional[List[Tuple]] = None
_fault_calls = 0
_fault_lock = threading.Lock()
# multiplicative scale on every injected delay sleep.  The GBDT driver
# sets it to (current local rows / initial local rows) under a
# row-sharded learner, so an injected per-collective slowdown models a
# host whose PER-ROW compute is slow: moving rows off the straggler
# shrinks its injected stall proportionally, making shard rebalancing
# measurable on CPU (bench.py elastic section).
_delay_scale = 1.0


def set_delay_scale(scale: float) -> None:
    """Scale injected ``delay`` fault sleeps (no-op without faults)."""
    global _delay_scale
    _delay_scale = max(float(scale), 0.0)


def delay_scale() -> float:
    return _delay_scale


# Cross-host wait time spent inside collective transports this interval.
# collect.allgather_bytes feeds it (transport call only, *after* the
# fault_point so injected straggler stalls land on the straggler's own
# compute side); the rebalance controller drains it once per iteration.
_wait_clock_s = 0.0
_wait_clock_lock = threading.Lock()


def wait_clock_add(seconds: float) -> None:
    """Accumulate collective-transport wait time (rebalance signal)."""
    global _wait_clock_s
    with _wait_clock_lock:
        _wait_clock_s += max(float(seconds), 0.0)


def wait_clock_drain() -> float:
    """Return accumulated transport wait seconds and reset to zero."""
    global _wait_clock_s
    with _wait_clock_lock:
        out = _wait_clock_s
        _wait_clock_s = 0.0
    return out


def parse_fault_spec(spec: str) -> List[Tuple]:
    """``die:N | drop_collective:N | delay:ms | delay:ms:after:N``
    (comma-separable).  ``N`` is the 1-based hardened-collective call
    index; a bare ``delay:ms`` applies to every call, while
    ``delay:ms:after:N`` arms the persistent slowdown only from call N
    on (a rank that becomes a straggler mid-run)."""
    out: List[Tuple] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        kind = fields[0].strip().lower()
        if kind not in ("die", "drop_collective", "delay"):
            raise ValueError(f"unknown fault kind {kind!r} in {spec!r}")
        if (kind == "delay" and len(fields) == 4
                and fields[2].strip().lower() == "after"):
            try:
                ms, after = float(fields[1]), float(fields[3])
            except ValueError:
                raise ValueError(f"bad fault argument in {part!r}")
            if after < 1:
                raise ValueError(
                    f"delay:ms:after:N needs a 1-based call index, "
                    f"got {part!r}")
            out.append(("delay_after", ms, after))
            continue
        if len(fields) > 2:
            raise ValueError(f"bad fault argument in {part!r}")
        arg = fields[1] if len(fields) > 1 else ""
        try:
            val = float(arg) if arg else 0.0
        except ValueError:
            raise ValueError(f"bad fault argument in {part!r}")
        if kind in ("die", "drop_collective") and val < 1:
            raise ValueError(f"{kind} needs a 1-based call index, got {part!r}")
        out.append((kind, val))
    return out


def _fault_applies_here() -> bool:
    target = os.environ.get("LIGHTGBM_TPU_FAULT_RANK", "").strip()
    if not target:
        return True
    try:
        import jax

        return int(target) == jax.process_index()
    except Exception:
        return True


def fault_point(kind: str = "collective") -> None:
    """Injection hook at the top of every hardened collective.  Parses
    ``LIGHTGBM_TPU_FAULT`` once; no-op (one dict lookup) when unset."""
    global _fault_specs, _fault_calls
    with _fault_lock:
        if _fault_specs is None:
            spec = os.environ.get("LIGHTGBM_TPU_FAULT", "")
            try:
                _fault_specs = parse_fault_spec(spec) if spec else []
            except ValueError as e:
                Log.warning("Ignoring LIGHTGBM_TPU_FAULT: %s", e)
                _fault_specs = []
        if not _fault_specs or not _fault_applies_here():
            return
        _fault_calls += 1
        calls = _fault_calls
    for spec_item in _fault_specs:
        fkind, arg = spec_item[0], spec_item[1]
        if fkind == "delay":
            time.sleep(arg / 1e3 * _delay_scale)
        elif fkind == "delay_after" and calls >= int(spec_item[2]):
            time.sleep(arg / 1e3 * _delay_scale)
        elif fkind == "die" and calls == int(arg):
            Log.warning("FAULT INJECTION: die at %s call %d", kind, calls)
            sys.stdout.flush()
            os.kill(os.getpid(), signal.SIGKILL)
        elif fkind == "drop_collective" and calls == int(arg):
            # simulate a lost collective from a live process: heartbeats
            # keep beating, this rank never contributes — peers must
            # surface CollectiveTimeoutError, not PeerFailureError
            Log.warning("FAULT INJECTION: dropping %s call %d (wedging)",
                        kind, calls)
            sys.stdout.flush()
            while True:
                time.sleep(3600)


# ----------------------------------------------------------------------
# KV-store plumbing
# ----------------------------------------------------------------------
def _client():
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:  # pragma: no cover - private-API drift tolerated
        return None


def require_client():
    client = _client()
    if client is None:
        raise NetError("distributed runtime not initialized (no KV client)")
    return client


def _is_deadline_error(e: BaseException) -> bool:
    return "DEADLINE_EXCEEDED" in str(e)


# frame prefix on every KV value: jaxlib 0.4.37's bytes API segfaults
# reading values shorter than 2 bytes, and barriers gather b"" payloads
_KV_FRAME = b"LT1\x00"

# ----------------------------------------------------------------------
# chunked KV payloads.  The coordination-service KV store is built for
# small config values; multi-MB blobs (elected-histogram allgathers on
# the XLA:CPU transport, wide-matrix find-bin states) are split across
# framed continuation keys with a per-chunk CRC.  (Quantized training,
# purpose "hist_q", shrinks the histogram blobs 3x — int16 (g,h) planes
# instead of f32 (g,h,cnt) — so wide exchanges often fit in a single
# head value and skip the continuation machinery.)  The head value either
# carries the whole payload (_KV_RAW) or a descriptor + the first chunk
# (_KV_CHUNKED); continuation chunks are written BEFORE the head, so a
# reader that sees the head never waits on a missing chunk — no extra
# synchronization round is needed and program-order GC still holds.
# ----------------------------------------------------------------------
_KV_RAW = b"R"
_KV_CHUNKED = b"C"
_KV_CHUNK_HDR = struct.Struct("<IQ")  # (num_chunks, total_len)
_KV_CHUNK_ENV = "LIGHTGBM_TPU_KV_CHUNK"
_KV_CHUNK_DEFAULT = 4 * 1024 * 1024
# (uid, rank) -> number of continuation keys written (for lazy GC; the
# rank in the key matters only for in-process multi-rank simulations,
# where all ranks share this module)
_chunks_written: Dict[Tuple[int, int], int] = {}


def kv_chunk_limit() -> int:
    """Max payload bytes carried by a single KV value (env-overridable;
    tests shrink it to force chunking on tiny blobs)."""
    raw = os.environ.get(_KV_CHUNK_ENV, "").strip()
    if raw:
        try:
            return max(int(raw), 1)
        except ValueError:
            Log.warning("Unparsable %s=%r ignored", _KV_CHUNK_ENV, raw)
    return _KV_CHUNK_DEFAULT


def _frame_chunk(chunk: bytes) -> bytes:
    return struct.pack("<I", zlib.crc32(chunk) & 0xFFFFFFFF) + chunk


def _unframe_chunk(raw: bytes, what: str, key: str) -> bytes:
    if len(raw) < 4:
        raise NetError(f"{what}: truncated KV chunk at {key}")
    want = struct.unpack("<I", raw[:4])[0]
    chunk = raw[4:]
    got = zlib.crc32(chunk) & 0xFFFFFFFF
    if got != want:
        raise NetError(
            f"{what}: KV chunk CRC mismatch at {key} "
            f"(stored {want:#010x}, computed {got:#010x}) — payload "
            f"corrupted in the coordination store")
    return chunk


def _kv_put_payload(client, uid: int, rank: int, key: str, blob: bytes,
                    deadline: float, what: str) -> None:
    """Write ``blob`` under ``key``, splitting payloads larger than the
    chunk limit across ``ltpu_chunk/`` continuation keys (written first,
    see the protocol note above)."""
    limit = kv_chunk_limit()
    if len(blob) <= limit:
        retry_call(lambda: _kv_put(client, key, _KV_RAW + blob),
                   what=f"{what}[set uid={uid}]", deadline_s=deadline)
        return
    chunks = [blob[i:i + limit] for i in range(0, len(blob), limit)]
    for i in range(1, len(chunks)):
        ckey = f"{_CHUNK_DIR}{uid}/{rank}/{i}"
        framed = _frame_chunk(chunks[i])
        retry_call(lambda k=ckey, v=framed: _kv_put(client, k, v),
                   what=f"{what}[set chunk uid={uid}/{i}]",
                   deadline_s=deadline)
    _chunks_written[(uid, rank)] = len(chunks) - 1
    tracer.counter("net.kv_chunk", float(len(chunks) - 1), what=what)
    head = (_KV_CHUNKED
            + _KV_CHUNK_HDR.pack(len(chunks), len(blob))
            + _frame_chunk(chunks[0]))
    retry_call(lambda: _kv_put(client, key, head),
               what=f"{what}[set uid={uid}]", deadline_s=deadline)


def _kv_read_payload(client, uid: int, r: int, head: bytes, poll_ms: int,
                     budget_left: Callable[[], float],
                     watch: Optional["PeerWatch"], what: str) -> bytes:
    """Decode one rank's head value, fetching continuation chunks if the
    payload was split.  Chunks exist before the head is visible, so the
    bounded gets here only absorb store latency, not peer skew."""
    if head[:1] == _KV_RAW:
        return head[1:]
    if head[:1] != _KV_CHUNKED:
        raise NetError(
            f"{what}: unrecognized KV payload framing {head[:1]!r} from "
            f"rank {r} (version skew between ranks?)")
    nchunks, total = _KV_CHUNK_HDR.unpack_from(head, 1)
    parts = [_unframe_chunk(head[1 + _KV_CHUNK_HDR.size:], what,
                            f"{_COLLECT_DIR}{uid}/{r}")]
    for i in range(1, nchunks):
        key = f"{_CHUNK_DIR}{uid}/{r}/{i}"
        while True:
            left = budget_left()
            if left <= 0:
                if watch is not None:
                    watch.check(what)
                tracer.counter("net.timeout", what=what)
                raise CollectiveTimeoutError(
                    f"{what} uid={uid}: chunk {i}/{nchunks} from rank {r} "
                    f"never appeared within the budget")
            try:
                raw = _kv_get(client, key, poll_ms)
                break
            except Exception as e:
                if not _is_deadline_error(e):
                    raise NetError(
                        f"{what} uid={uid}: KV store error reading chunk "
                        f"{key}: {e}") from e
                if watch is not None:
                    watch.check(what)
        parts.append(_unframe_chunk(raw, what, key))
    blob = b"".join(parts)
    if len(blob) != total:
        raise NetError(
            f"{what} uid={uid}: reassembled payload from rank {r} is "
            f"{len(blob)} bytes, descriptor said {total}")
    return blob


def _gc_chunks(client, uid: int, rank: int) -> None:
    cnt = _chunks_written.pop((uid, rank), 0)
    for i in range(1, cnt + 1):
        try:
            client.key_value_delete(f"{_CHUNK_DIR}{uid}/{rank}/{i}")
        except Exception:  # pragma: no cover - GC is best-effort
            pass


def _kv_put(client, key: str, blob: bytes) -> None:
    if hasattr(client, "key_value_set_bytes"):
        client.key_value_set_bytes(key, _KV_FRAME + blob)
    else:  # pragma: no cover - older jaxlib
        client.key_value_set(key, (_KV_FRAME + blob).hex())


def _kv_get(client, key: str, timeout_ms: int) -> bytes:
    if hasattr(client, "blocking_key_value_get_bytes"):
        raw = bytes(client.blocking_key_value_get_bytes(key, timeout_ms))
    else:  # pragma: no cover - older jaxlib
        raw = bytes.fromhex(client.blocking_key_value_get(key, timeout_ms))
    return raw[len(_KV_FRAME):]


# ----------------------------------------------------------------------
# heartbeats + peer liveness
# ----------------------------------------------------------------------
class HeartbeatWriter:
    """Daemon thread rotating this rank's liveness key.  The KV store is
    write-once, so each beat writes ``ltpu_hb/<rank>/<seq>`` then
    deletes seq-1 (write-then-delete keeps at least one key visible).
    A SIGKILL stops the rotation — that frozen key set IS the death
    signal :class:`PeerWatch` reads."""

    def __init__(self, client, rank: int, interval_s: float):
        self._client = client
        self._rank = int(rank)
        self._interval = float(interval_s)
        self._seq = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="ltpu-heartbeat", daemon=True
        )

    def start(self) -> None:
        self._beat()  # first beat lands before any collective waits on it
        self._thread.start()

    def _beat(self) -> None:
        self._seq += 1
        self._client.key_value_set(
            f"{_HB_DIR}{self._rank}/{self._seq}", str(self._seq)
        )
        if self._seq > 1:
            try:
                self._client.key_value_delete(
                    f"{_HB_DIR}{self._rank}/{self._seq - 1}"
                )
            except Exception:  # pragma: no cover - GC is best-effort
                pass

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                with tracer.span("net.heartbeat", rank=self._rank):
                    self._beat()
            except Exception as e:
                # coordinator unreachable: stop beating quietly; the
                # foreground collective will classify the failure
                Log.debug("heartbeat write failed (stopping): %s", e)
                return

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
        try:  # clean exit: remove our keys so peers don't sweep a ghost
            self._client.key_value_delete(f"{_HB_DIR}{self._rank}/")
        except Exception:
            pass


class PeerWatch:
    """Liveness sweeper over the per-rank heartbeat keys.

    Staleness is measured in **local observation time**: a rank is dead
    when its heartbeat key set has not changed for ``stale_after_s``
    since this watch last saw it change — no cross-host clock is read,
    so NTP skew cannot cause false positives."""

    def __init__(self, client, rank: int, nproc: int,
                 stale_after_s: Optional[float] = None,
                 time_fn: Callable[[], float] = time.monotonic):
        self._client = client
        self.rank = int(rank)
        self.nproc = int(nproc)
        self._stale_after = stale_after_s
        self._time = time_fn
        self._lock = threading.Lock()
        # rank -> (last observed key-set state, local time it changed)
        self._seen: Dict[int, Tuple[str, float]] = {}
        self._t_start = time_fn()

    def _states(self) -> Dict[int, str]:
        entries = self._client.key_value_dir_get(_HB_DIR)
        states: Dict[int, List[str]] = {}
        for key, val in entries:
            parts = key.split("/")
            if len(parts) < 2:
                continue
            try:
                r = int(parts[1])
            except ValueError:
                continue
            states.setdefault(r, []).append(f"{parts[-1]}={val}")
        return {r: ";".join(sorted(v)) for r, v in states.items()}

    def ages(self) -> Dict[int, float]:
        """Seconds since each peer's heartbeat state last changed (from
        this process's point of observation)."""
        now = self._time()
        states = self._states()
        out: Dict[int, float] = {}
        with self._lock:
            for r in range(self.nproc):
                if r == self.rank:
                    continue
                cur = states.get(r, "<absent>")
                prev = self._seen.get(r)
                if prev is None or prev[0] != cur:
                    # first sight / changed: alive as of now (a missing
                    # key on first sight baselines at watch start so a
                    # never-started peer still times out)
                    t_mark = self._t_start if (
                        prev is None and cur == "<absent>"
                    ) else now
                    self._seen[r] = (cur, t_mark)
                    out[r] = now - t_mark
                else:
                    out[r] = now - prev[1]
        return out

    def dead_ranks(self) -> List[int]:
        stale = (self._stale_after if self._stale_after is not None
                 else settings().stale_after())
        try:
            ages = self.ages()
        except Exception as e:
            # the KV store itself is gone: the coordinator (rank 0)
            # process died — everything routed through it is dead
            _flight_dump("coordinator_unreachable", error=e)
            raise PeerFailureError(
                f"distributed KV store unreachable (coordinator dead?): {e}",
                ranks=(0,),
            ) from e
        return [r for r, age in sorted(ages.items()) if age > stale]

    def check(self, what: str, elapsed_s: float = 0.0) -> None:
        """Raise :class:`PeerFailureError` if any peer went stale."""
        dead = self.dead_ranks()
        if dead:
            stale = (self._stale_after if self._stale_after is not None
                     else settings().stale_after())
            tracer.event("net.peer_failure", what=what, ranks=dead,
                         elapsed_s=round(elapsed_s, 3))
            _flight_dump("peer_failure", what=what, ranks=list(dead),
                         elapsed_s=round(elapsed_s, 3))
            raise PeerFailureError(
                f"rank(s) {dead} stopped heartbeating during {what} "
                f"(no change for > {stale:.1f}s)",
                ranks=dead, elapsed_s=elapsed_s,
            )


_hb_writer: Optional[HeartbeatWriter] = None
_peer_watch: Optional[PeerWatch] = None
_hb_lock = threading.Lock()


def ensure_heartbeat() -> Optional[PeerWatch]:
    """Start this process's heartbeat writer + peer watch once (no-op
    for single-process runs or before the runtime is initialized).
    Returns the shared :class:`PeerWatch`, if any."""
    global _hb_writer, _peer_watch
    with _hb_lock:
        if _peer_watch is not None:
            return _peer_watch
        client = _client()
        if client is None:
            return None
        import jax

        nproc = jax.process_count()
        if nproc <= 1:
            return None
        rank = jax.process_index()
        s = settings()
        writer = HeartbeatWriter(client, rank, s.hb_interval())
        try:
            writer.start()
        except Exception as e:  # pragma: no cover - store down at start
            Log.warning("Could not start heartbeat writer: %s", e)
            return None
        _hb_writer = writer
        _peer_watch = PeerWatch(client, rank, nproc)
        return _peer_watch


def peer_watch() -> Optional[PeerWatch]:
    return _peer_watch


def stop_heartbeat() -> None:
    """Stop the heartbeat and delete this rank's keys (clean shutdown)."""
    global _hb_writer, _peer_watch
    with _hb_lock:
        if _hb_writer is not None:
            _hb_writer.stop()
        _hb_writer = None
        _peer_watch = None


# ----------------------------------------------------------------------
# bounded primitives
# ----------------------------------------------------------------------
def kv_gather(uid: int, blob: bytes, *, client=None, rank: Optional[int] = None,
              nproc: Optional[int] = None, deadline_s: Optional[float] = None,
              watch: Optional[PeerWatch] = None,
              what: str = "kv_allgather") -> List[bytes]:
    """Deadline-bounded KV-store allgather with liveness classification
    and key GC.

    Budget is ``deadline + stale_after`` (~2x deadline): the wait window
    plus the staleness window a peer death needs to become visible.
    Inside the budget the per-rank blocking get polls in short slices,
    sweeping heartbeats between slices so a dead peer raises
    :class:`PeerFailureError` the moment it goes stale; budget expiry
    with live peers raises :class:`CollectiveTimeoutError`.

    GC: completing gather ``uid`` proves every rank finished gather
    ``uid-1`` (each rank writes its uid key before reading any, and
    collectives run in identical program order), so every rank has read
    this rank's ``uid-1`` key — it is deleted here.  Live KV usage is
    thereby bounded to O(ranks) keys instead of growing per gather."""
    s = settings()
    if client is None:
        client = require_client()
    if rank is None or nproc is None:
        import jax

        rank = jax.process_index() if rank is None else rank
        nproc = jax.process_count() if nproc is None else nproc
    deadline = s.deadline_s if deadline_s is None else float(deadline_s)
    budget = deadline + s.stale_after()
    if watch is None:
        watch = _peer_watch
    poll_ms = max(int(s.poll_s() * 1e3), 10)

    own_key = f"{_COLLECT_DIR}{uid}/{rank}"
    _kv_put_payload(client, uid, rank, own_key, blob, deadline, what)

    t0 = time.monotonic()
    out: List[bytes] = []
    for r in range(nproc):
        if r == rank:
            out.append(blob)
            continue
        key = f"{_COLLECT_DIR}{uid}/{r}"
        misses = 0
        while True:
            elapsed = time.monotonic() - t0
            if elapsed >= budget:
                if watch is not None:
                    watch.check(what, elapsed_s=elapsed)
                tracer.counter("net.timeout", what=what)
                _flight_dump("collective_timeout", what=what,
                             elapsed_s=round(elapsed, 3))
                raise CollectiveTimeoutError(
                    f"{what} uid={uid}: rank {r} never contributed within "
                    f"{budget:.1f}s (deadline={deadline:.1f}s) but peers "
                    f"look alive", elapsed_s=elapsed,
                )
            try:
                head = _kv_get(client, key, poll_ms)
                out.append(_kv_read_payload(
                    client, uid, r, head, poll_ms,
                    lambda: budget - (time.monotonic() - t0), watch, what))
                break
            except Exception as e:
                if not _is_deadline_error(e):
                    misses += 1
                    if misses > s.retries:
                        _flight_dump("coordinator_unreachable", error=e,
                                     what=what)
                        raise PeerFailureError(
                            f"{what} uid={uid}: KV store unreachable "
                            f"(coordinator dead?): {e}",
                            ranks=(0,), elapsed_s=elapsed,
                        ) from e
                    time.sleep(min(backoff_schedule(
                        s.retries, s.backoff_base_s, s.backoff_max_s
                    )[misses - 1], max(budget - elapsed, 0.0)))
                    continue
                if watch is not None:
                    watch.check(what, elapsed_s=time.monotonic() - t0)
    if uid > 0:
        try:
            client.key_value_delete(f"{_COLLECT_DIR}{uid - 1}/{rank}")
            _gc_chunks(client, uid - 1, rank)
            tracer.counter("net.kv_gc")
        except Exception:  # pragma: no cover - GC is best-effort
            pass
    return out


def watchdog_call(fn: Callable, what: str,
                  deadline_s: Optional[float] = None,
                  watch: Optional[PeerWatch] = None):
    """Run a blocking call (device allgather, backend init, distributed
    bootstrap) on a watchdog: the call executes on a daemon worker
    thread while this thread ticks, sweeping peer liveness each slice.
    A stale peer raises :class:`PeerFailureError`; budget expiry raises
    :class:`CollectiveTimeoutError`.  The worker thread cannot be
    cancelled — on timeout it is abandoned (daemon) and the caller is
    expected to abort the process via the cooperative-abort path."""
    s = settings()
    deadline = s.deadline_s if deadline_s is None else float(deadline_s)
    budget = deadline + s.stale_after()
    if watch is None:
        watch = _peer_watch
    box: Dict[str, object] = {}
    done = threading.Event()

    def _runner():
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 - ferried to caller
            box["error"] = e
        finally:
            done.set()

    threading.Thread(target=_runner, name=f"ltpu-net-{what}",
                     daemon=True).start()
    t0 = time.monotonic()
    while not done.wait(s.poll_s()):
        elapsed = time.monotonic() - t0
        if watch is not None:
            watch.check(what, elapsed_s=elapsed)
        if elapsed >= budget:
            tracer.counter("net.timeout", what=what)
            _flight_dump("collective_timeout", what=what,
                         elapsed_s=round(elapsed, 3))
            raise CollectiveTimeoutError(
                f"{what} did not complete within {budget:.1f}s "
                f"(deadline={deadline:.1f}s)", elapsed_s=elapsed,
            )
    if "error" in box:
        raise box["error"]  # type: ignore[misc]
    return box.get("value")


# ----------------------------------------------------------------------
# cooperative abort
# ----------------------------------------------------------------------
def hard_exit(code: int) -> None:
    """Exit WITHOUT running interpreter atexit hooks.

    After a peer death the JAX distributed-shutdown barrier (registered
    atexit) blocks until the coordination service's own ~100 s heartbeat
    timeout and then terminates the process with a fatal log — survivors
    that already flushed their checkpoint must not take that path.
    Flushes the tracer and stdio first, then ``os._exit``."""
    try:
        tracer.close()
    except Exception:
        pass
    try:
        sys.stdout.flush()
        sys.stderr.flush()
    except Exception:
        pass
    os._exit(code)
