"""Multi-host runtime initialization — the DCN half of the network stack.

The reference's machine-list bootstrap (src/network/linkers_socket.cpp
Construct + config.h:261-268 machines/machine_list_file/num_machines/
local_listen_port) establishes a TCP ring/bruck topology.  On TPU the
whole layer collapses into the JAX distributed runtime: one
``jax.distributed.initialize`` call per process and every collective in
ops/grow.py rides ICI/DCN through XLA, with ``jax.devices()`` becoming
the GLOBAL device list so ``make_mesh`` spans processes automatically.

Process bootstrap accepts, in priority order:
1. env vars (the JAX-native deployment path):
   LIGHTGBM_TPU_COORDINATOR=host:port, LIGHTGBM_TPU_NUM_PROCESSES,
   LIGHTGBM_TPU_PROCESS_ID
2. the reference's config surface: ``machine_list_file`` / ``machines``
   ("host:port,host:port,...") + ``num_machines``; the FIRST machine is
   the coordinator (rank 0), and this process's rank is its line index
   (which must be given by LIGHTGBM_TPU_PROCESS_ID or inferred from the
   local hostname matching a list entry — the reference does the same
   hostname match in linkers_socket.cpp:90-134).

Row data in distributed mode: each process holds ITS OWN row shard (the
reference's pre_partition=true contract, config.h:116) and
``global_rows_array`` assembles the global jax.Array across processes.
"""

from __future__ import annotations

import os
import socket
from typing import Optional

import jax
import numpy as np

from ..utils.log import Log
from . import net

_initialized = False


def _bounded_initialize(coord: str, nproc: int, pid: int) -> None:
    """``jax.distributed.initialize`` under a watchdog with bounded
    retry — the BENCH_r05 "dead tunnel" fix.  The RPC layer's own
    ``initialization_timeout`` bounds a *reachable-but-refusing*
    coordinator; the watchdog additionally bounds a blackholed
    connection that never errors.  Returned errors retry on the net
    backoff schedule; a watchdog trip raises immediately (a second
    concurrent initialize on the same runtime is not safe)."""
    s = net.settings()
    deadline = s.deadline_s

    def _attempt():
        jax.distributed.initialize(
            coordinator_address=coord, num_processes=nproc, process_id=pid,
            initialization_timeout=max(int(round(deadline)), 1),
        )

    import time as _time

    delays = net.backoff_schedule(s.retries, s.backoff_base_s, s.backoff_max_s)
    t0 = _time.monotonic()
    for attempt in range(s.retries + 1):
        try:
            # the watchdog only trips when initialize neither returns
            # nor errors (a blackholed tunnel); its trip is NOT retried
            # — a second concurrent initialize on the same runtime is
            # not safe while the first may still be in flight
            net.watchdog_call(_attempt, what="distributed.initialize",
                              deadline_s=deadline)
            return
        except net.NetError:
            raise
        except RuntimeError as e:
            msg = str(e)
            if "already" in msg or "only be called once" in msg:
                raise  # caller's already-initialized handling
            if attempt >= s.retries:
                elapsed = _time.monotonic() - t0
                raise net.CollectiveTimeoutError(
                    f"distributed bootstrap to {coord} failed after "
                    f"{attempt + 1} attempt(s) in {elapsed:.1f}s: {e}",
                    elapsed_s=elapsed,
                ) from e
            Log.warning(
                "distributed.initialize failed (attempt %d/%d): %s — "
                "retrying in %.2fs", attempt + 1, s.retries + 1, e,
                delays[attempt],
            )
            _time.sleep(delays[attempt])


def _machines_from_config(config) -> list:
    if getattr(config, "machine_list_file", ""):
        with open(config.machine_list_file) as f:
            return [ln.strip() for ln in f if ln.strip()]
    machines = getattr(config, "machines", "") or ""
    if machines:
        return [m.strip() for m in machines.split(",") if m.strip()]
    return []


def ensure_initialized(config=None, process_id: Optional[int] = None) -> bool:
    """Idempotently initialize the JAX distributed runtime when the run
    is multi-process.  Returns True when a multi-process runtime is (or
    already was) active."""
    global _initialized
    if config is not None:
        net.configure_from_config(config)
    if _initialized:
        return jax.process_count() > 1
    # NOTE: no jax.devices()/process_count() before initialize — any
    # backend query would lock in a single-process runtime.  Detect an
    # externally-initialized runtime via the distributed global state
    # (reading it does NOT initialize a backend).
    try:
        from jax._src import distributed as _dist

        if _dist.global_state.client is not None:
            _initialized = True
            if jax.process_count() > 1:
                net.ensure_heartbeat()
                from ..obs import tracer

                tracer.set_identity(rank=jax.process_index(),
                                    world_size=jax.process_count())
                return True
            return False
    except Exception:  # pragma: no cover — private-API drift tolerated
        pass

    coord = os.environ.get("LIGHTGBM_TPU_COORDINATOR", "")
    nproc = int(os.environ.get("LIGHTGBM_TPU_NUM_PROCESSES", "0") or 0)
    pid_env = os.environ.get("LIGHTGBM_TPU_PROCESS_ID", "")
    pid = process_id if process_id is not None else (int(pid_env) if pid_env else None)

    if not coord and config is not None and getattr(config, "num_machines", 1) > 1:
        machines = _machines_from_config(config)
        if machines:
            coord = machines[0]
            nproc = nproc or int(config.num_machines)
            if pid is None:
                # hostname match, like linkers_socket.cpp:90-134; when
                # several list entries share this host, local_listen_port
                # disambiguates (multiple ranks per machine)
                local = {socket.gethostname(), socket.getfqdn(), "127.0.0.1", "localhost"}
                try:
                    local.add(socket.gethostbyname(socket.gethostname()))
                except OSError:
                    pass
                lport = str(getattr(config, "local_listen_port", ""))
                matches = [i for i, m in enumerate(machines) if m.split(":")[0] in local]
                if len(matches) > 1:
                    by_port = [
                        i for i in matches
                        if len(machines[i].split(":")) > 1
                        and machines[i].split(":")[1] == lport
                    ]
                    if len(by_port) == 1:
                        matches = by_port
                    else:
                        Log.fatal(
                            "Cannot infer this process's rank: %d machine-list "
                            "entries match the local host and local_listen_port "
                            "does not disambiguate; set LIGHTGBM_TPU_PROCESS_ID",
                            len(matches),
                        )
                if matches:
                    pid = matches[0]
    if not coord or not nproc or pid is None:
        return False

    Log.info(
        "Initializing distributed runtime: coordinator=%s rank=%d/%d "
        "(deadline=%.0fs, retries=%d)",
        coord, pid, nproc, net.settings().deadline_s, net.settings().retries,
    )
    try:
        _bounded_initialize(coord, pid=pid, nproc=nproc)
    except net.NetError:
        # an explicitly-requested multi-process bootstrap that cannot be
        # established fails LOUDLY and bounded (linkers_socket.cpp does
        # the same after its connect retries) — silently continuing
        # single-process is the BENCH_r05 zeroed-benchmark bug class
        raise
    except RuntimeError as e:  # backend already up (too late) or re-init
        msg = str(e)
        if "already" in msg or "only be called once" in msg:
            _initialized = True
            if jax.process_count() > 1:
                net.ensure_heartbeat()
                return True
            return False
        Log.warning("Distributed init failed: %s", e)
        return False
    _initialized = True
    # backend-init probe: the first backend query after initialize can
    # itself hang on a dead tunnel — bound it like any other collective
    nproc_seen = net.watchdog_call(jax.process_count,
                                   what="backend_init_probe")
    if nproc_seen > 1:
        net.ensure_heartbeat()
        # stamp rank/world/run_id onto every trace record so `report
        # merge` can correlate the per-rank JSONLs of this run
        from ..obs import tracer

        tracer.set_identity(rank=jax.process_index(),
                            world_size=nproc_seen, run_id=coord)
    return nproc_seen > 1


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def global_rows_array(local_rows, mesh, row_axis: str = "data"):
    """Assemble a row-sharded global jax.Array from this process's local
    row block (the pre-partitioned data contract).  Single-process meshes
    pass through unchanged."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    if jax.process_count() == 1:
        return jnp.asarray(local_rows)
    spec = P(row_axis, *([None] * (np.ndim(local_rows) - 1)))
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_process_local_data(sharding, np.asarray(local_rows))


def replicated_array(value, mesh):
    """Replicate identical per-process data onto a multi-process mesh."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    if jax.process_count() == 1:
        return jnp.asarray(value)
    sharding = NamedSharding(mesh, P())
    return jax.make_array_from_process_local_data(sharding, np.asarray(value))


def current_epoch() -> int:
    """The live membership epoch of this process's fleet — the
    generation stamp elastic transitions bump (parallel/membership.py).
    Static jax.distributed worlds and unarmed runs report 0, so any
    caller can stamp epoch-sensitive state (collect.py uid scoping,
    checkpoint meta, observability rows) without caring whether the
    world is elastic."""
    from . import membership

    rt = membership.runtime()
    return max(rt.epoch, 0) if rt is not None else 0
