"""Sharded tree learner — wraps ops/grow.py's collective-aware grower in
``shard_map`` over a device mesh.

Mode mapping (TreeLearner::CreateTreeLearner, tree_learner.cpp:9-33):
  tree_learner=serial  -> plain jit (single shard)
  tree_learner=data    -> rows sharded, histogram psum
                          (DataParallelTreeLearner)
  tree_learner=feature -> rows replicated, feature search sharded
                          (FeatureParallelTreeLearner)
  tree_learner=voting  -> rows sharded, top-k voted histogram reduction
                          (VotingParallelTreeLearner)

The mesh is one axis named "data"; multi-host meshes come from
jax.distributed initialization upstream — the learner only sees the axis.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # JAX >= 0.4.35 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from ..ops.grow import GrowParams, GrowResult, grow_tree


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """One-axis ("data") mesh over the local devices."""
    devs = jax.devices()
    d = n_devices if n_devices is not None else len(devs)
    return Mesh(np.array(devs[:d]), ("data",))


def _shard_map_compat(fn, mesh, in_specs, out_specs):
    """shard_map with replication checking off (the grower's collective
    results are replicated by construction; the checker can't always
    prove it)."""
    try:
        return _shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                          check_vma=False)
    except TypeError:  # older kwarg name
        return _shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                          check_rep=False)


class ShardedLearner:
    """Builds and caches the shard_mapped grower for one configuration."""

    def __init__(self, mode: str, mesh: Mesh, params: GrowParams):
        assert mode in ("data", "feature", "voting")
        self.mode = mode
        self.mesh = mesh
        self.d = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
        self.params = params._replace(
            parallel=mode, axis_name="data", num_machines=self.d
        )

        row_sharded = mode in ("data", "voting")
        feature_sharded = mode == "feature"
        d = self.d

        def body(bins, grad, hess, select, fmask, meta, hyper):
            if feature_sharded:
                # contiguous per-shard feature ownership
                # (balanced assignment, feature_parallel_tree_learner.cpp:31-50)
                f = bins.shape[1]
                per = -(-f // d)
                own = (jnp.arange(f) // per) == jax.lax.axis_index("data")
                fmask = fmask * own.astype(fmask.dtype)
            return grow_tree(bins, grad, hess, select, fmask, meta, hyper, self.params)

        rowspec = P("data") if row_sharded else P()
        in_specs = (
            P("data", None) if row_sharded else P(),  # bins
            rowspec,  # grad
            rowspec,  # hess
            rowspec,  # select
            P(),  # feature_mask
            P(),  # meta
            P(),  # hyper
        )
        out_specs = GrowResult(
            num_splits=P(),
            leaf_id=P("data") if row_sharded else P(),
            leaf_value=P(),
            leaf_cnt=P(),
            rec_leaf=P(),
            rec_feat=P(),
            rec_thr=P(),
            rec_dbz=P(),
            rec_gain=P(),
            rec_lval=P(),
            rec_rval=P(),
            rec_lcnt=P(),
            rec_rcnt=P(),
            rec_internal_value=P(),
        )
        self._fn = jax.jit(
            _shard_map_compat(body, mesh, in_specs, out_specs)
        )
        self._row_sharded = row_sharded

    # ------------------------------------------------------------------
    def grow(self, bins, grad, hess, select, feature_mask, meta, hyper) -> GrowResult:
        n = bins.shape[0]
        pad = (-n) % self.d if self._row_sharded else 0
        if pad:
            bins = jnp.pad(bins, ((0, pad), (0, 0)))
            grad = jnp.pad(grad, (0, pad))
            hess = jnp.pad(hess, (0, pad))
            select = jnp.pad(select, (0, pad))  # padded rows: select=0
        gr = self._fn(bins, grad, hess, select, feature_mask, meta, hyper)
        if pad:
            gr = gr._replace(leaf_id=gr.leaf_id[:n])
        return gr
