"""Sharded tree learner — wraps ops/grow.py's collective-aware grower in
``shard_map`` over a device mesh.

Mode mapping (TreeLearner::CreateTreeLearner, tree_learner.cpp:9-33):
  tree_learner=serial  -> plain jit (single shard)
  tree_learner=data    -> rows sharded, histogram psum
                          (DataParallelTreeLearner)
  tree_learner=feature -> rows replicated, feature search sharded
                          (FeatureParallelTreeLearner)
  tree_learner=voting  -> rows sharded, top-k voted histogram reduction
                          (VotingParallelTreeLearner)

The mesh is one axis named "data"; multi-host meshes come from
jax.distributed initialization upstream — the learner only sees the axis.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # JAX >= 0.4.35 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from ..ops.grow import GrowParams, GrowResult, grow_tree


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """One-axis ("data") mesh over the local devices."""
    devs = jax.devices()
    d = n_devices if n_devices is not None else len(devs)
    return Mesh(np.array(devs[:d]), ("data",))


def _shard_map_compat(fn, mesh, in_specs, out_specs):
    """shard_map with replication checking off (the grower's collective
    results are replicated by construction; the checker can't always
    prove it)."""
    try:
        return _shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                          check_vma=False)
    except TypeError:  # older kwarg name
        return _shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                          check_rep=False)


class ShardedLearner:
    """Builds and caches the shard_mapped grower for one configuration."""

    def __init__(self, mode: str, mesh: Mesh, params: GrowParams):
        assert mode in ("data", "feature", "voting")
        self.mode = mode
        self.mesh = mesh
        self.d = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
        self.params = params._replace(
            parallel=mode, axis_name="data", num_machines=self.d
        )

        row_sharded = mode in ("data", "voting")
        feature_sharded = mode == "feature"
        d = self.d

        def body(bins, grad, hess, select, fmask, meta, hyper, qscale=None):
            if feature_sharded:
                # contiguous per-shard feature ownership
                # (balanced assignment, feature_parallel_tree_learner.cpp:31-50)
                f = bins.shape[1]
                per = -(-f // d)
                own = (jnp.arange(f) // per) == jax.lax.axis_index("data")
                fmask = fmask * own.astype(fmask.dtype)
            return grow_tree(bins, grad, hess, select, fmask, meta, hyper,
                             self.params, qscale)

        rowspec = P("data") if row_sharded else P()
        in_specs = (
            P("data", None) if row_sharded else P(),  # bins
            rowspec,  # grad
            rowspec,  # hess
            rowspec,  # select
            P(),  # feature_mask
            P(),  # meta
            P(),  # hyper
        )
        if self.params.quantized:
            # quantized training: the (2,) global dequantization scales
            # ride along replicated (computed once per iteration upstream)
            in_specs = in_specs + (P(),)
        out_specs = GrowResult(
            num_splits=P(),
            leaf_id=P("data") if row_sharded else P(),
            leaf_value=P(),
            leaf_cnt=P(),
            rec_leaf=P(),
            rec_feat=P(),
            rec_thr=P(),
            rec_dbz=P(),
            rec_gain=P(),
            rec_lval=P(),
            rec_rval=P(),
            rec_lcnt=P(),
            rec_rcnt=P(),
            rec_internal_value=P(),
        )
        self._fn = jax.jit(
            _shard_map_compat(body, mesh, in_specs, out_specs)
        )
        self._row_sharded = row_sharded
        self._rep_consts = None  # cached replicated meta/hyper (multi-process)
        self._global_bins = None  # cached assembled bins + gmax (multi-process)

    # ------------------------------------------------------------------
    def set_plan(self, plan) -> None:
        """Shard-plan seam (parallel/shardplan.py): row ownership moved,
        so the cached assembled global bins and the allgathered max row
        count are stale — drop them; the next grow reassembles from the
        new shards (shape-keyed jit recompiles automatically)."""
        del plan  # ownership is implicit in the arrays each rank passes
        self._global_bins = None
        self._gmax = None

    # ------------------------------------------------------------------
    def grow(self, bins, grad, hess, select, feature_mask, meta, hyper,
             qscale=None) -> GrowResult:
        """Grow one tree.  In a multi-process runtime each process passes
        its OWN row block (the reference's pre_partition=true contract,
        config.h:116) with equal per-process row counts; arrays are
        assembled into global row-sharded jax.Arrays and the collectives
        inside the grower ride ICI/DCN."""
        n = bins.shape[0]
        multi = jax.process_count() > 1
        shards = self.d if not multi else self.d // jax.process_count()
        pad = (-n) % max(shards, 1) if self._row_sharded else 0
        if multi and self._row_sharded:
            # processes may hold unequal row shards; pad every process to
            # the global max so the assembled global array is rectangular
            # (bins/row-count are immutable per learner — allgather once)
            if self._global_bins is None:
                from jax.experimental import multihost_utils

                counts = np.asarray(multihost_utils.process_allgather(np.asarray(n)))
                gmax = int(counts.max())
                gmax += (-gmax) % max(shards, 1)
                self._gmax = gmax
            pad = self._gmax - n
        if pad:
            bins = jnp.pad(bins, ((0, pad), (0, 0)))
            grad = jnp.pad(grad, (0, pad))
            hess = jnp.pad(hess, (0, pad))
            select = jnp.pad(select, (0, pad))  # padded rows: select=0
        if multi:
            from .distributed import global_rows_array, replicated_array

            if self._row_sharded:
                if self._global_bins is None:
                    self._global_bins = global_rows_array(bins, self.mesh)
                bins = self._global_bins
                grad = global_rows_array(grad, self.mesh)
                hess = global_rows_array(hess, self.mesh)
                select = global_rows_array(select, self.mesh)
            else:
                if self._global_bins is None:
                    self._global_bins = replicated_array(bins, self.mesh)
                bins = self._global_bins
                grad = replicated_array(grad, self.mesh)
                hess = replicated_array(hess, self.mesh)
                select = replicated_array(select, self.mesh)
            feature_mask = replicated_array(feature_mask, self.mesh)
            # meta/hyper are loop-invariant: replicate once, not per tree
            if self._rep_consts is None:
                self._rep_consts = (
                    jax.tree_util.tree_map(lambda x: replicated_array(x, self.mesh), meta),
                    jax.tree_util.tree_map(lambda x: replicated_array(x, self.mesh), hyper),
                )
            meta, hyper = self._rep_consts
            if self.params.quantized and qscale is not None:
                qscale = replicated_array(qscale, self.mesh)
        args = (bins, grad, hess, select, feature_mask, meta, hyper)
        if self.params.quantized:
            args = args + (qscale,)
        gr = self._fn(*args)
        if multi and self._row_sharded:
            # leaf_id comes back row-sharded globally; hand the caller its
            # process-local rows (matching the rows it passed in)
            shards = sorted(
                gr.leaf_id.addressable_shards, key=lambda s: s.index[0].start or 0
            )
            local = np.concatenate([np.asarray(s.data) for s in shards])
            gr = gr._replace(leaf_id=jnp.asarray(local[:n]))
        elif pad:
            gr = gr._replace(leaf_id=gr.leaf_id[:n])
        return gr
