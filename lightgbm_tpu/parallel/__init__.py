"""Distributed training over a jax.sharding.Mesh — the counterpart of the
reference's src/network/ + parallel tree learners, rebuilt on XLA
collectives over ICI/DCN (SURVEY §2.6: the Bruck/recursive-halving
topology code is deleted outright; psum/all_gather/reduce_scatter already
implement it in hardware).
"""

from .comm import LocalComm, LocalGroup, NetComm
from .hostlearner import HostParallelLearner
from .learner import ShardedLearner, make_mesh
from .net import CollectiveTimeoutError, NetError, PeerFailureError
from .shardplan import RebalanceController, ShardPlan, exchange_rows

__all__ = [
    "ShardedLearner",
    "HostParallelLearner",
    "NetComm",
    "LocalComm",
    "LocalGroup",
    "make_mesh",
    "NetError",
    "PeerFailureError",
    "CollectiveTimeoutError",
    "ShardPlan",
    "RebalanceController",
    "exchange_rows",
]
