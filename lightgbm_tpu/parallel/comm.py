"""Byte-blob communicators for the host-driven parallel tree learners.

The wide-data learners (``parallel/hostlearner.py``) express every
exchange as an allgather of opaque byte blobs — best-split records,
partition bitmaps, vote ballots, elected-column histograms.  Two
communicators implement that surface:

- :class:`NetComm` rides the hardened multi-process transports in
  ``collect.py`` / ``net.py`` (deadline-bounded, heartbeat liveness,
  chunked KV payloads), so peer-death and timeout semantics are
  identical to every other collective in the repo;
- :class:`LocalComm` simulates R ranks inside one process with a
  barrier-synchronized slot exchange.  It exists for fast determinism
  tests and the device-independent comms-volume bench: byte counts are
  exact and identical to what NetComm would send, without subprocesses.

Both keep an always-on ``ledger`` mapping purpose -> bytes sent by this
rank (``hist`` / ``best_split`` / ``vote`` / ``elect``, plus ``hist_q``
for the quantized-training int16 histogram wire and its scale/root-sum
side channels), independent of whether tracing is enabled — the bench
comms section and the per-iter ``net_bytes`` report field read it
directly.  Under ``quantized_training`` the per-node histogram payload
moves from f32x3 (``hist``, F*B*12 bytes) to int16x2 (``hist_q``,
F*B*4 bytes — the count plane is derived at the receiver), a fixed 3x
wire reduction; the report CLI surfaces the measured ratio per
iteration.
"""

from __future__ import annotations

import threading
from typing import Dict, List

from ..obs import tracer


class Comm:
    """Allgather-of-bytes surface with a purpose-tagged byte ledger."""

    #: membership epoch this communicator's collectives are scoped to.
    #: Static worlds never bump it; the elastic MembershipComm
    #: (parallel/membership.py) overrides it with the live runtime
    #: epoch, so learners can stamp epoch-sensitive state without
    #: knowing which transport they ride.
    epoch = 0

    def __init__(self, rank: int, nproc: int):
        self.rank = int(rank)
        self.nproc = int(nproc)
        self.ledger: Dict[str, int] = {}

    def _account(self, blob: bytes, purpose: str) -> None:
        self.ledger[purpose] = self.ledger.get(purpose, 0) + len(blob)

    def ledger_total(self) -> int:
        return sum(self.ledger.values())

    def allgather(self, blob: bytes, purpose: str = "misc") -> List[bytes]:
        raise NotImplementedError


class NetComm(Comm):
    """Multi-process communicator over the hardened collect/net stack."""

    def __init__(self):
        import jax

        super().__init__(jax.process_index(), jax.process_count())

    def allgather(self, blob: bytes, purpose: str = "misc") -> List[bytes]:
        from . import collect

        self._account(blob, purpose)
        # collect.allgather_bytes emits the net.bytes tracer counter
        return collect.allgather_bytes(blob, purpose=purpose)


class LocalGroup:
    """Shared state for an in-process group of :class:`LocalComm` ranks.

    Exchange protocol: write own slot -> barrier -> snapshot all slots
    -> barrier.  The trailing barrier keeps a fast rank from starting
    the next round (overwriting its slot) before a slow rank snapshots.
    """

    def __init__(self, nproc: int):
        self.nproc = int(nproc)
        self.slots: List[bytes] = [b""] * self.nproc
        self.barrier = threading.Barrier(self.nproc)

    def comms(self) -> List["LocalComm"]:
        return [LocalComm(r, self) for r in range(self.nproc)]


class LocalComm(Comm):
    """Single-process rank simulation; exact byte accounting, no net."""

    def __init__(self, rank: int, group: LocalGroup):
        super().__init__(rank, group.nproc)
        self.group = group

    def allgather(self, blob: bytes, purpose: str = "misc") -> List[bytes]:
        self._account(blob, purpose)
        tracer.counter("net.bytes", float(len(blob)), purpose=purpose,
                       transport="local")
        if self.nproc == 1:
            return [blob]
        self.group.slots[self.rank] = blob
        self.group.barrier.wait()
        out = list(self.group.slots)
        self.group.barrier.wait()
        return out
