"""Host-driven distributed tree learners for wide data.

``ops/grow.py`` folds the reference's three parallel modes into ONE
fused XLA program per shard — ideal when every rank participates in a
single multi-process computation (TPU meshes).  On backends where
multi-process programs don't exist (XLA:CPU) the only cross-rank
channel is the hardened byte-blob allgather (``parallel/net.py``), so
this module re-expresses the same leaf-wise loop with the *host*
driving control flow and tiny jitted kernels doing every piece of f32
arithmetic:

- ``mode="data"``     — rows sharded; each split allgathers the full
  local (F, B, 3) histogram and merges it in rank order
  (DataParallelTreeLearner; payload O(F*B) per node).
- ``mode="feature"``  — columns sharded; each rank builds histograms
  and finds best splits only for its own features, allreduces a 28-byte
  best-split record, and the split owner broadcasts the partition
  bitmap (FeatureParallelTreeLearner; payload O(1) per node).
- ``mode="voting"``   — PV-Tree: each rank votes its local top-k
  features by gain, a global election keeps the top-2k, and only the
  elected columns' histograms are exchanged (payload O(2k*B) per node;
  with 2k >= F the elected set covers every feature and the result is
  bit-identical to ``data``).

Bit-parity contract (pinned by tests/test_wide_learners.py):

- feature mode reproduces the serial ``grow_tree`` model BITWISE —
  per-feature split search is elementwise in F, and a histogram built
  over a column slice equals the slice of the full histogram, so
  sharding columns changes no arithmetic;
- voting with 2k >= F reproduces data mode BITWISE — the elected-column
  scatter covers every column, so the rank-order merge performs the
  identical sequence of IEEE f32 adds.

Every f32 value is produced by a jitted kernel mirroring grow.py's ops
or by IEEE numpy scalar arithmetic; the host only does control flow
(argmax = first-max, comparisons, integer bookkeeping), which is
exact.  All ranks take identical decisions from identical gathered
bytes, so collectives stay in lockstep program order (the KV GC
invariant).

Purpose tags on every exchange (``net.bytes{purpose=...}``):
``hist`` histogram payloads, ``best_split`` split records / partition
bitmaps / node counts, ``vote`` ballots, ``elect`` election results,
``hist_q`` quantized-training payloads (scale maxima, int root totals
and the int16-packed 2-plane histograms of ops/qhist.py).

Quantized training (``params.quantized``, data/voting modes): grad/hess
are stochastically rounded to int16 levels under a per-iteration global
scale (the scale maxima are the first ``hist_q`` exchange of each
tree), histograms accumulate in exact int32, and every histogram
payload ships as the 2-plane int16 ``hist_q`` wire — F*B*4 bytes
against the f32x3 wire's F*B*12.  The receiver derives the count plane
from the hessian plane and the node totals (the reference's cnt_factor
trick), merges ranks in exact integer arithmetic, and dequantizes once
before the split scan — so the merged histogram, and therefore the
tree, is IDENTICAL for any rank count and any row order.  Feature mode
ignores the flag: its rows are replicated and its exchanges are
28-byte records, so there is no histogram wire to compress.
"""

from __future__ import annotations

import functools
import struct
from typing import Dict, List

import jax
import numpy as np

from ..obs import tracer
from ..ops import qhist
from ..ops.grow import GrowParams, GrowResult
from ..ops.histogram import build_histogram
from ..ops.split import (
    NEG_INF,
    best_split_feature_block,
    best_split_per_feature,
    leaf_output,
    slice_features,
)
from .comm import Comm

# 28-byte best-split record: gain, feature, threshold_bin,
# default_bin_for_zero, left (sum_g, sum_h, cnt) — the SplitInfo wire
# format of FeatureParallelTreeLearner's Allreduce, minus the redundant
# right-side fields (right = leaf totals - left, recomputed exactly)
_REC = struct.Struct("<fiiifff")
_CNT = struct.Struct("<ii")
_SUMS = struct.Struct("<fff")
# quantized-training exchanges: per-rank (max|g|, max|h|) for the global
# scale, and exact int64 quantized root totals (sum_qg, sum_qh, count)
_QMAX = struct.Struct("<ff")
_QSUMS = struct.Struct("<qqq")


# ---------------------------------------------------------------------
# jitted kernels: every op mirrors the corresponding line of
# ops/grow.py so standalone execution reproduces the fused program's
# f32 arithmetic bit for bit
# ---------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("num_bins", "row_block"))
def _hist_leaf(bins, grad, hess, select, leaf_id, target, num_bins,
               row_block):
    sel = select * (leaf_id == target).astype(select.dtype)
    return build_histogram(bins, grad, hess, sel, num_bins, row_block)


@jax.jit
def _root_sums(grad, hess, select):
    import jax.numpy as jnp

    return (jnp.sum(grad * select), jnp.sum(hess * select),
            jnp.sum(select))


@jax.jit
def _root_sums_q(qgrad, qhess, select):
    """Exact int32 quantized node totals — associative, so any rank
    count / row order sums to the identical integers."""
    import jax.numpy as jnp

    s16 = select.astype(jnp.int16)
    return (jnp.sum(qgrad * s16, dtype=jnp.int32),
            jnp.sum(qhess * s16, dtype=jnp.int32),
            jnp.sum(s16, dtype=jnp.int32))


@functools.partial(jax.jit, static_argnames=("use_missing",))
def _best_split(hist, lo, sg, sh, sc, meta, hyper, fmask, use_missing,
                monotone=None, leaf_lo=None, leaf_hi=None):
    return best_split_feature_block(hist, lo, sg, sh, sc, meta, hyper,
                                    fmask, use_missing, monotone=monotone,
                                    leaf_lo=leaf_lo, leaf_hi=leaf_hi)


@functools.partial(jax.jit, static_argnames=("use_missing",))
def _local_gains(hist, sg, sh, sc, meta, hyper, fmask, use_missing,
                 monotone=None, leaf_lo=None, leaf_hi=None):
    gain_f, _, _, _ = best_split_per_feature(
        hist, sg, sh, sc, meta, hyper, fmask, use_missing,
        monotone=monotone, leaf_lo=leaf_lo, leaf_hi=leaf_hi
    )
    return gain_f


@jax.jit
def _local_leaf_tot(hist):
    import jax.numpy as jnp

    return jnp.sum(hist[0], axis=0)  # (3,): identical for every feature


@jax.jit
def _leaf_out(g, h, l1, l2):
    return leaf_output(g, h, l1, l2)


@jax.jit
def _goes_left(bins, feat, thr, dbz, zero_bin, is_cat):
    import jax.numpy as jnp

    col = jnp.take(bins, feat, axis=1).astype(jnp.int32)
    fval = jnp.where(col == zero_bin, dbz, col)
    return jnp.where(is_cat, fval == thr, fval <= thr)


@jax.jit
def _apply_partition(leaf_id, goes_left, bl, right_leaf):
    import jax.numpy as jnp

    in_leaf = leaf_id == bl
    new_id = jnp.where(in_leaf & ~goes_left, right_leaf, leaf_id)
    n_left = jnp.sum((in_leaf & goes_left).astype(jnp.int32))
    return new_id, n_left


class HostParallelLearner:
    """Leaf-wise grower driven from the host over a :class:`Comm`.

    Presents the same ``grow(...) -> GrowResult`` surface as
    ``ShardedLearner`` so ``boosting/gbdt.py`` treats it as a drop-in
    learner; inputs are this rank's shard (rows for data/voting, the
    full replicated matrix for feature mode)."""

    # gbdt.py hands us f32 gradients even under quantized_training: the
    # quantization scale must be a max over ALL ranks' rows, so the
    # allgather of local maxima happens inside _grow, not in the driver
    quantizes_internally = True

    def __init__(self, mode: str, comm: Comm, params: GrowParams):
        if mode not in ("data", "feature", "voting"):
            raise ValueError(f"unknown host learner mode {mode!r}")
        self.mode = mode
        self.comm = comm
        self.params = params
        # quantized training runs only in the histogram-exchanging modes
        self.quant = bool(params.quantized) and mode in ("data", "voting")
        self._qiter = -1  # per-grow stochastic-rounding key counter
        self._qscales = None  # (2,) np.float32 scales of the current tree

    def set_plan(self, plan) -> None:
        """Shard-plan seam (parallel/shardplan.py): the host-driven
        learner is stateless with respect to rows (bins/grad/hess arrive
        per grow call and jit caches are shape-keyed), so a row-ownership
        move needs no invalidation here — the seam exists so the driver
        can treat every parallel learner uniformly."""
        del plan

    # -- helpers ------------------------------------------------------

    def _feature_block(self, f: int):
        """Contiguous column block [lo, hi) owned by this rank (same
        blocking as ShardedLearner's per-shard feature mask)."""
        per = -(-f // self.comm.nproc)
        lo = min(f, self.comm.rank * per)
        return per, lo, min(f, lo + per)

    def _merge_f32(self, blobs: List[bytes], shape) -> np.ndarray:
        """Rank-order sequential IEEE f32 adds — the determinism anchor
        for the data <-> voting bit-parity contract."""
        parts = [np.frombuffer(b, np.float32).reshape(shape) for b in blobs]
        tot = parts[0].copy()
        for p in parts[1:]:
            tot = tot + p
        return tot

    def _merge_q(self, blobs: List[bytes], f: int, b: int):
        """Exact integer merge of ``hist_q`` payloads — int64 adds are
        associative, so the merged planes are independent of rank count
        and merge order (the quantized determinism anchor).

        Returns ``(planes, counts)``: the (F, B, 2) g/h sum and the
        summed (F, B) exact count plane of any 3-plane payloads (ranks
        whose hessian mass for the node quantized to zero), or None when
        every rank shipped the 2-plane format."""
        tot = np.zeros((f, b, 2), np.int64)
        counts = None
        for blob in blobs:
            arr = qhist.unpack_hist_q(blob, f, b)
            tot = tot + arr[..., :2]
            if arr.shape[-1] == 3:
                c = arr[..., 2].astype(np.int64)
                counts = c if counts is None else counts + c
        return tot, counts

    @staticmethod
    def _q_counts_if_degenerate(hist3: np.ndarray):
        """Sender side of the degenerate-node protocol: the exact int
        count plane iff this rank's quantized hessian mass for the node
        is zero while it still holds rows (hessians are non-negative, so
        the GLOBAL mass is zero iff every rank's is — each such rank
        ships counts and the receiver needs no second exchange)."""
        if (int(hist3[0, :, 1].sum()) == 0
                and int(hist3[0, :, 2].sum()) > 0):
            return hist3[..., 2]
        return None

    # -- per-node best split, one exchange pattern per mode -----------

    def _find_best(self, jnp, hist, sums, depth_ok, meta, hyper,
                   feature_mask, f, lo, monotone=None, leaf_lo=None,
                   leaf_hi=None):
        """Returns (gain, feat, thr, dbz, left(3,)) as numpy scalars,
        identical on every rank.  ``monotone`` covers this rank's hist
        columns (the block slice in feature mode); the leaf bounds are
        host scalars every rank replays identically."""
        p = self.params
        mono_kw = ({} if monotone is None else
                   dict(monotone=monotone, leaf_lo=jnp.float32(leaf_lo),
                        leaf_hi=jnp.float32(leaf_hi)))
        sg, sh, sc = (np.float32(sums[0]), np.float32(sums[1]),
                      np.float32(sums[2]))
        if self.mode == "feature":
            if hist is not None:
                res = _best_split(hist, np.int32(lo), jnp.float32(sg),
                                  jnp.float32(sh), jnp.float32(sc), meta,
                                  hyper, feature_mask, p.use_missing,
                                  **mono_kw)
                rec = _REC.pack(float(res.gain), int(res.feature),
                                int(res.threshold_bin),
                                int(res.default_bin_for_zero),
                                float(res.left_sum_g),
                                float(res.left_sum_h),
                                float(res.left_cnt))
            else:  # more ranks than column blocks: vacuous candidate
                rec = _REC.pack(NEG_INF, 0, 0, 0, 0.0, 0.0, 0.0)
            recs = [_REC.unpack(b)
                    for b in self.comm.allgather(rec, "best_split")]
            gains = np.array([r[0] for r in recs], np.float32)
            # first-max: ties resolve to the lowest rank = lowest global
            # feature index under contiguous column blocks, matching the
            # serial argmax tie-break
            w = recs[int(np.argmax(gains))]
            gain, feat, thr, dbz = w[0], w[1], w[2], w[3]
            left = np.array(w[4:7], np.float32)
        else:
            if self.mode == "voting":
                ghist, vmask = self._vote_and_merge(jnp, hist, meta, hyper,
                                                    feature_mask, f, sc,
                                                    mono_kw=mono_kw)
                fmask = feature_mask * jnp.asarray(vmask)
            elif self.quant:
                # 2-plane int16 wire (F*B*4 bytes vs the f32 wire's
                # F*B*12), exact integer merge, count plane derived from
                # the hessian plane + node totals (ops/qhist.py); a rank
                # with zero hessian mass here ships its counts exactly
                h3 = np.asarray(hist)
                blob = qhist.pack_hist_q(
                    h3[..., :2], self._q_counts_if_degenerate(h3))
                blobs = self.comm.allgather(blob, "hist_q")
                merged, exact_cnt = self._merge_q(blobs, f, p.num_bins)
                ghist = qhist.assemble_hist(merged, self._qscales,
                                            float(sc), counts=exact_cnt)
                fmask = feature_mask
            else:
                blobs = self.comm.allgather(
                    np.asarray(hist, np.float32).tobytes(), "hist")
                ghist = self._merge_f32(blobs, (f, p.num_bins, 3))
                fmask = feature_mask
            res = _best_split(jnp.asarray(ghist), np.int32(0),
                              jnp.float32(sg), jnp.float32(sh),
                              jnp.float32(sc), meta, hyper, fmask,
                              p.use_missing, **mono_kw)
            gain = float(res.gain)
            feat, thr = int(res.feature), int(res.threshold_bin)
            dbz = int(res.default_bin_for_zero)
            left = np.array([float(res.left_sum_g), float(res.left_sum_h),
                             float(res.left_cnt)], np.float32)
        if not depth_ok:
            gain = NEG_INF
        return np.float32(gain), feat, thr, dbz, left

    def _vote_and_merge(self, jnp, hist, meta, hyper, feature_mask, f,
                        node_cnt=None, mono_kw=None):
        """PV-Tree exchange: ballot -> election -> elected-column merge.
        Returns (global (F, B, 3) hist with non-elected columns zero,
        elected 0/1 mask).  ``mono_kw`` (monotone strategy) constrains
        the local ballot gains so ranks vote for splits the constrained
        global scan could actually take."""
        p = self.params
        nproc = self.comm.nproc
        k = max(min(p.top_k, f), 1)
        k2 = min(2 * k, f)
        if self.quant:
            # ballots are cast from the dequantized LOCAL hist (its
            # count plane is still an exact device integer); only the
            # elected columns ship, as 2-plane int16 hist_q payloads
            qhist_local = hist
            hist = qhist.dequantize_hist(hist, jnp.asarray(self._qscales))
        # local proposals under /nproc-relaxed constraints
        # (voting_parallel_tree_learner.cpp:54-56)
        lt = _local_leaf_tot(hist)
        local_hyper = hyper._replace(
            min_data_in_leaf=hyper.min_data_in_leaf / nproc,
            min_sum_hessian_in_leaf=hyper.min_sum_hessian_in_leaf / nproc,
        )
        lg_f = np.asarray(_local_gains(hist, lt[0], lt[1], lt[2], meta,
                                       local_hyper, feature_mask,
                                       p.use_missing, **(mono_kw or {})))
        ballot = np.argsort(-lg_f, kind="stable")[:k].astype(np.int32)
        blobs = self.comm.allgather(ballot.tobytes(), "vote")
        votes = np.zeros((f,), np.float32)
        for b in blobs:
            votes[np.frombuffer(b, np.int32)] += 1.0
        # stable sort: vote ties resolve toward the lower feature index
        elected = np.sort(np.argsort(-votes, kind="stable")[:k2])
        elected = elected.astype(np.int32)
        echo = self.comm.allgather(elected.tobytes(), "elect")
        if any(e != echo[0] for e in echo):  # pragma: no cover
            raise RuntimeError(
                "voting-parallel election disagreed across ranks — "
                "non-deterministic local gains?")
        if self.quant:
            sub3 = np.asarray(qhist_local)[elected]
            parts = self.comm.allgather(
                qhist.pack_hist_q(
                    sub3[..., :2], self._q_counts_if_degenerate(sub3)),
                "hist_q")
            merged_q, exact_cnt = self._merge_q(parts, k2, p.num_bins)
            # every row lands in one bin of ANY feature, so the first
            # elected column's hessian plane sums to the node total the
            # cnt_factor derivation needs
            merged_sub = qhist.assemble_hist(merged_q, self._qscales,
                                             float(node_cnt),
                                             counts=exact_cnt)
        else:
            sub = np.ascontiguousarray(np.asarray(hist, np.float32)[elected])
            parts = self.comm.allgather(sub.tobytes(), "hist")
            merged_sub = self._merge_f32(parts, (k2, p.num_bins, 3))
        ghist = np.zeros((f, p.num_bins, 3), np.float32)
        ghist[elected] = merged_sub
        vmask = np.zeros((f,), np.float32)
        vmask[elected] = 1.0
        return ghist, vmask

    # -- the leaf-wise loop -------------------------------------------

    def grow(self, bins, grad, hess, select, feature_mask, meta, hyper):
        with tracer.span("learner.grow", mode=self.mode,
                         nproc=self.comm.nproc):
            return self._grow(bins, grad, hess, select, feature_mask,
                              meta, hyper)

    def _grow(self, bins, grad, hess, select, feature_mask, meta, hyper):
        import jax.numpy as jnp

        p = self.params
        n, f = bins.shape
        L, B = p.num_leaves, p.num_bins
        rowed = self.mode in ("data", "voting")  # row-sharded modes

        if self.mode == "feature":
            per, lo, hi = self._feature_block(f)
            hbins = bins[:, lo:hi] if hi > lo else None
            hmeta = slice_features(meta, lo, hi)
            hmask = feature_mask[lo:hi]
        else:
            per, lo, hi = f, 0, f
            hbins, hmeta, hmask = bins, meta, feature_mask

        # monotone-constraint strategy seam (tree/strategy.py): bounds
        # replay host-side exactly as in the serial growers — every rank
        # derives identical np.float32 bounds from the lockstep replay;
        # unconstrained keeps the exact pre-strategy call graph (no
        # kwargs reach the jitted kernels)
        mono_t = p.strategy.split_gain.monotone
        use_mono = any(c != 0 for c in mono_t)
        if use_mono and len(mono_t) != f:
            raise ValueError(
                f"monotone constraint vector has {len(mono_t)} entries "
                f"but the dataset has {f} inner features")
        # each rank scans its own hist columns, so slice the direction
        # vector to the block in feature mode
        hmono = (jnp.asarray(mono_t[lo:hi], jnp.int32)
                 if use_mono and hi > lo else None)
        leaf_lo = np.full((L,), NEG_INF, np.float32)
        leaf_hi = np.full((L,), np.inf, np.float32)

        if self.quant:
            # ---- per-tree quantization: global scales from allgathered
            # local maxima (every rank derives the identical f32 scale),
            # then value-keyed stochastic rounding — a row quantizes the
            # same way whichever rank holds it, so the merged integer
            # histogram is invariant under rank count and row order.
            self._qiter += 1
            seed = (int(p.quant_seed) * 2654435761
                    + self._qiter * 97 + 1) & 0xFFFFFFFF
            mx = np.asarray(qhist.local_absmax(grad, hess, select),
                            np.float32)
            blobs = self.comm.allgather(
                _QMAX.pack(float(mx[0]), float(mx[1])), "hist_q")
            maxima = [_QMAX.unpack(b) for b in blobs]
            self._qscales = qhist.scales_from_max(
                max(m[0] for m in maxima), max(m[1] for m in maxima),
                p.quant_bits)
            grad, hess = qhist.quantize_rows(
                grad, hess, jnp.asarray(self._qscales), np.uint32(seed),
                p.quant_bits)

        def node_hist(leaf_id, target):
            if hbins is None:
                return None
            return _hist_leaf(hbins, grad, hess, select, leaf_id,
                              np.int32(target), B, p.row_block)

        # ---- root totals (LeafSplits::Init)
        if self.quant:
            # exact integer totals: int64-packed exchange, Python-int
            # rank sum, one dequantization on the host
            qg, qh, qc = _root_sums_q(grad, hess, select)
            blobs = self.comm.allgather(
                _QSUMS.pack(int(qg), int(qh), int(qc)), "hist_q")
            sums_i = [_QSUMS.unpack(b) for b in blobs]
            tot_g = sum(s[0] for s in sums_i)
            tot_h = sum(s[1] for s in sums_i)
            tot_c = sum(s[2] for s in sums_i)
            tg = np.float32(np.float32(tot_g) * self._qscales[0])
            th = np.float32(np.float32(tot_h) * self._qscales[1])
            tc = np.float32(tot_c)
        else:
            tg, th, tc = _root_sums(grad, hess, select)
            if rowed:
                blobs = self.comm.allgather(
                    _SUMS.pack(float(tg), float(th), float(tc)),
                    "best_split")
                vals = [np.array(_SUMS.unpack(b), np.float32) for b in blobs]
                tot = vals[0].copy()
                for v in vals[1:]:
                    tot = tot + v
                tg, th, tc = tot[0], tot[1], tot[2]
            else:
                tg, th, tc = np.float32(tg), np.float32(th), np.float32(tc)

        leaf_id = jnp.zeros((n,), jnp.int32)
        root_hist = node_hist(leaf_id, 0)

        # host-side _State mirror (numpy; device arrays only in pool)
        bs_gain = np.full((L,), NEG_INF, np.float32)
        bs_feat = np.zeros((L,), np.int32)
        bs_thr = np.zeros((L,), np.int32)
        bs_dbz = np.zeros((L,), np.int32)
        bs_left = np.zeros((L, 3), np.float32)
        leaf_sum = np.zeros((L, 3), np.float32)
        leaf_value = np.zeros((L,), np.float32)
        leaf_cnt = np.zeros((L,), np.float32)
        leaf_depth = np.zeros((L,), np.int32)
        leaf_rows = np.zeros((L,), np.int32)  # LOCAL rows
        zri = np.zeros((L - 1,), np.int32)
        zr = np.zeros((L - 1,), np.float32)
        rec_leaf, rec_feat = zri.copy(), zri.copy()
        rec_thr, rec_dbz = zri.copy(), zri.copy()
        rec_gain, rec_lval, rec_rval = zr.copy(), zr.copy(), zr.copy()
        rec_lcnt, rec_rcnt, rec_iv = zr.copy(), zr.copy(), zr.copy()

        leaf_sum[0] = (tg, th, tc)
        leaf_cnt[0] = tc
        leaf_rows[0] = n
        pool: Dict[int, object] = {0: root_hist}

        def store(leafi, res):
            bs_gain[leafi], bs_feat[leafi] = res[0], res[1]
            bs_thr[leafi], bs_dbz[leafi] = res[2], res[3]
            bs_left[leafi] = res[4]

        find = functools.partial(self._find_best, jnp, meta=hmeta,
                                 hyper=hyper, feature_mask=hmask, f=f,
                                 lo=lo)
        if use_mono:
            store(0, find(root_hist, leaf_sum[0], True, monotone=hmono,
                          leaf_lo=leaf_lo[0], leaf_hi=leaf_hi[0]))
        else:
            store(0, find(root_hist, leaf_sum[0], True))

        num_splits = 0
        l1, l2 = hyper.lambda_l1, hyper.lambda_l2
        while num_splits < L - 1:
            bl = int(np.argmax(bs_gain))  # first-max, like jnp.argmax
            if not (bs_gain[bl] > 0.0):
                break  # no further splits with positive gain
            s = num_splits
            right_leaf = s + 1
            feat, thr, dbz = (int(bs_feat[bl]), int(bs_thr[bl]),
                              int(bs_dbz[bl]))
            left = bs_left[bl].copy()
            right = leaf_sum[bl] - left  # IEEE f32, mirrors grow.py
            lval = np.float32(_leaf_out(jnp.float32(left[0]),
                                        jnp.float32(left[1]), l1, l2))
            rval = np.float32(_leaf_out(jnp.float32(right[0]),
                                        jnp.float32(right[1]), l1, l2))
            if use_mono:
                # clip to the leaf's inherited bounds (exact min/max on
                # f32 host scalars), then BasicLeafConstraints mid-point
                # tightening for the children
                plo, phi = leaf_lo[bl], leaf_hi[bl]
                lval = np.float32(min(max(lval, plo), phi))
                rval = np.float32(min(max(rval, plo), phi))
                cdir = int(mono_t[feat])
                mid = np.float32((lval + rval) * np.float32(0.5))
                leaf_lo[bl] = mid if cdir < 0 else plo
                leaf_hi[bl] = mid if cdir > 0 else phi
                leaf_lo[right_leaf] = mid if cdir > 0 else plo
                leaf_hi[right_leaf] = mid if cdir < 0 else phi

            # ---- partition (DataPartition::Split)
            if self.mode == "feature":
                owner = feat // per
                if owner == self.comm.rank:
                    mask = np.asarray(_goes_left(
                        bins, np.int32(feat), np.int32(thr), np.int32(dbz),
                        meta.default_bin[feat], meta.is_categorical[feat]))
                    blob = np.packbits(mask).tobytes()
                else:
                    blob = b""
                blobs = self.comm.allgather(blob, "best_split")
                mask = np.unpackbits(
                    np.frombuffer(blobs[owner], np.uint8), count=n
                ).astype(bool)
                leaf_id, n_left = _apply_partition(
                    leaf_id, jnp.asarray(mask), np.int32(bl),
                    np.int32(right_leaf))
            else:
                gl = _goes_left(bins, np.int32(feat), np.int32(thr),
                                np.int32(dbz), meta.default_bin[feat],
                                meta.is_categorical[feat])
                leaf_id, n_left = _apply_partition(
                    leaf_id, gl, np.int32(bl), np.int32(right_leaf))
            n_left = int(n_left)
            n_right = int(leaf_rows[bl]) - n_left

            # ---- smaller child by GLOBAL row count (grow.py:394-404)
            if rowed:
                blobs = self.comm.allgather(_CNT.pack(n_left, n_right),
                                            "best_split")
                cnts = [_CNT.unpack(b) for b in blobs]
                g_left = sum(c[0] for c in cnts)
                g_right = sum(c[1] for c in cnts)
            else:
                g_left, g_right = n_left, n_right
            is_left_smaller = g_left < g_right
            smaller_id = bl if is_left_smaller else right_leaf
            smaller = node_hist(leaf_id, smaller_id)
            if smaller is not None:
                larger = pool[bl] - smaller  # the subtraction trick
            else:
                larger = None
            left_hist = smaller if is_left_smaller else larger
            right_hist = larger if is_left_smaller else smaller
            pool[bl], pool[right_leaf] = left_hist, right_hist

            # ---- children best splits
            child_depth = int(leaf_depth[bl]) + 1
            depth_ok = p.max_depth <= 0 or child_depth < p.max_depth
            if use_mono:
                lres = find(left_hist, left, depth_ok, monotone=hmono,
                            leaf_lo=leaf_lo[bl], leaf_hi=leaf_hi[bl])
                rres = find(right_hist, right, depth_ok, monotone=hmono,
                            leaf_lo=leaf_lo[right_leaf],
                            leaf_hi=leaf_hi[right_leaf])
            else:
                lres = find(left_hist, left, depth_ok)
                rres = find(right_hist, right, depth_ok)

            rec_leaf[s], rec_feat[s] = bl, feat
            rec_thr[s], rec_dbz[s] = thr, dbz
            rec_gain[s] = bs_gain[bl]
            rec_lval[s], rec_rval[s] = lval, rval
            rec_lcnt[s], rec_rcnt[s] = left[2], right[2]
            rec_iv[s] = leaf_value[bl]
            leaf_sum[bl], leaf_sum[right_leaf] = left, right
            leaf_value[bl], leaf_value[right_leaf] = lval, rval
            leaf_cnt[bl], leaf_cnt[right_leaf] = left[2], right[2]
            leaf_depth[bl] = leaf_depth[right_leaf] = child_depth
            leaf_rows[bl], leaf_rows[right_leaf] = n_left, n_right
            store(bl, lres)
            store(right_leaf, rres)
            num_splits += 1

        return GrowResult(
            num_splits=np.int32(num_splits),
            leaf_id=leaf_id,
            leaf_value=leaf_value,
            leaf_cnt=leaf_cnt,
            rec_leaf=rec_leaf,
            rec_feat=rec_feat,
            rec_thr=rec_thr,
            rec_dbz=rec_dbz,
            rec_gain=rec_gain,
            rec_lval=rec_lval,
            rec_rval=rec_rval,
            rec_lcnt=rec_lcnt,
            rec_rcnt=rec_rcnt,
            rec_internal_value=rec_iv,
        )
