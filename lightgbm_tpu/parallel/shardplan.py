"""Shard-plan seam: runtime row-range ownership + straggler rebalancing.

A synchronous data-parallel fleet runs at the pace of its slowest host —
``report merge`` (obs/report.py) has measured the barrier-wait that
straggler causes since PR 7; this module is the actuator.  Three pieces:

- :class:`ShardPlan` — the contiguous global row partition, in rank
  order.  It preserves the pre-partition contract (global row order =
  concatenation of rank shards), so a checkpoint taken after any number
  of rebalances still merges into the same canonical global layout
  (ckpt/state.py) and the global dataset fingerprint is invariant.
- :class:`RebalanceController` — a pure, deterministic policy fed the
  allgathered per-rank compute/wait timings (and heartbeat ages, so no
  rows ever move toward a rank that may be dying).  Every rank runs the
  identical arithmetic on the identical table, so all ranks derive the
  same plan with no extra coordination round.
- :func:`exchange_rows` — applies a plan change by moving row blocks
  between ranks over the hardened byte collectives: "checkpoint reshape
  in RAM", the same slice semantics as the elastic restore path, one
  mechanism tested two ways.

Policy (config knobs, docs/ROBUSTNESS.md): a rank is a straggler when
its compute-time EWMA exceeds ``rebalance_threshold`` x the fleet
median for ``rebalance_patience`` consecutive iterations; the new plan
sizes shards inversely to per-row cost, moving at most
``rebalance_max_move_frac`` of the global rows per event.
"""

from __future__ import annotations

import dataclasses
import struct
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.log import Log

__all__ = ["ShardPlan", "RebalanceController", "exchange_rows",
           "snap_to_groups"]


@dataclasses.dataclass(frozen=True)
class ShardPlan:
    """Contiguous global row partition in rank order."""

    counts: Tuple[int, ...]

    def __post_init__(self):
        if not self.counts or any(int(c) < 0 for c in self.counts):
            raise ValueError(f"bad shard counts {self.counts}")
        object.__setattr__(self, "counts",
                           tuple(int(c) for c in self.counts))

    @property
    def world(self) -> int:
        return len(self.counts)

    @property
    def total(self) -> int:
        return sum(self.counts)

    @property
    def starts(self) -> Tuple[int, ...]:
        out, acc = [], 0
        for c in self.counts:
            out.append(acc)
            acc += c
        return tuple(out)

    def rank_range(self, rank: int) -> Tuple[int, int]:
        """[start, stop) of ``rank``'s rows in global row order."""
        s = self.starts[rank]
        return s, s + self.counts[rank]

    @classmethod
    def from_counts(cls, counts) -> "ShardPlan":
        return cls(tuple(int(c) for c in counts))


class RebalanceController:
    """Deterministic straggler detector + plan proposer.

    Feed :meth:`observe` once per iteration with the identical
    allgathered table on every rank; it returns a new :class:`ShardPlan`
    when the policy fires, else ``None``.  State resets after each
    emitted plan so the next move is based on fresh measurements of the
    new layout."""

    def __init__(self, threshold: float, patience: int,
                 max_move_frac: float, alpha: float = 0.3,
                 stale_s: float = 10.0, min_rows: int = 32,
                 group_bounds: Optional[np.ndarray] = None):
        self.threshold = float(threshold)
        self.patience = int(patience)
        self.max_move_frac = float(max_move_frac)
        self.alpha = float(alpha)
        self.stale_s = float(stale_s)
        self.min_rows = int(min_rows)
        # cumulative global query-group boundaries (0 ... total,
        # ascending).  When set, proposed shard cuts snap to the nearest
        # boundary so no query group is ever split across ranks — the
        # ranking objectives (lambdarank) need whole groups per rank.
        self.group_bounds = (None if group_bounds is None
                             else np.asarray(group_bounds, np.int64))
        self._ewma: Optional[List[float]] = None
        self._hot = 0

    def reset(self) -> None:
        self._ewma = None
        self._hot = 0

    def observe(self, plan: ShardPlan, compute_s: List[float],
                hb_ages: Optional[List[float]] = None
                ) -> Optional[ShardPlan]:
        """One iteration's per-rank compute seconds (+ max heartbeat age
        each rank observes).  Returns the next plan when a persistent
        straggler warrants a move."""
        xs = [max(float(c), 1e-9) for c in compute_s]
        if len(xs) != plan.world:
            raise ValueError(
                f"{len(xs)} timings for a world-{plan.world} plan")
        if self._ewma is None or len(self._ewma) != plan.world:
            self._ewma = list(xs)
        else:
            a = self.alpha
            self._ewma = [a * x + (1.0 - a) * e
                          for x, e in zip(xs, self._ewma)]
        if hb_ages and max(float(h) for h in hb_ages) > self.stale_s:
            # a peer's heartbeat is stale: it may be dying, not merely
            # slow — moving rows toward or away from it now would race
            # the failure detector; hold position
            self._hot = 0
            return None
        med = float(np.median(self._ewma))
        if med <= 0 or max(self._ewma) <= self.threshold * med:
            self._hot = 0
            return None
        self._hot += 1
        if self._hot < self.patience:
            return None
        new_plan = self._propose(plan)
        self.reset()
        if new_plan is None or new_plan.counts == plan.counts:
            return None
        return new_plan

    def _propose(self, plan: ShardPlan) -> Optional[ShardPlan]:
        """Size shards inversely to measured per-row cost, clamped by
        ``max_move_frac`` and a per-shard row floor.  Pure integer
        arithmetic after the float shares, largest-remainder rounding —
        identical on every rank."""
        total = plan.total
        ewma = self._ewma
        # per-row cost of rank r: ewma_r / rows_r; balanced counts are
        # proportional to the inverse cost
        speed = [plan.counts[r] / ewma[r] if plan.counts[r] > 0 else 0.0
                 for r in range(plan.world)]
        ssum = sum(speed)
        if ssum <= 0:
            return None
        shares = [s / ssum * total for s in speed]
        ideal = _largest_remainder(shares, total)
        # clamp the total displaced rows to max_move_frac * total
        move = sum(max(0, c - i) for c, i in zip(plan.counts, ideal))
        budget = int(self.max_move_frac * total)
        if move > budget and move > 0:
            scale = budget / move
            scaled = [c + (i - c) * scale
                      for c, i in zip(plan.counts, ideal)]
            ideal = _largest_remainder(scaled, total)
        if self.group_bounds is not None:
            # query-grouped data: the 32-row floor is replaced by
            # cut-point snapping — the cumulative group boundaries are
            # invariant under row moves, so every rank derives the same
            # snapped cuts from the same ideal counts
            cuts = snap_to_groups(np.cumsum(ideal)[:-1], self.group_bounds)
            if cuts is None:
                return None
            edges = [0] + list(cuts) + [total]
            ideal = [edges[i + 1] - edges[i] for i in range(plan.world)]
        else:
            floor = min(self.min_rows, max(total // (2 * plan.world), 1))
            ideal = _apply_floor(ideal, floor, total)
        return ShardPlan.from_counts(ideal)


def _largest_remainder(shares: List[float], total: int) -> List[int]:
    base = [int(np.floor(s)) for s in shares]
    rem = total - sum(base)
    order = sorted(range(len(shares)),
                   key=lambda r: (base[r] - shares[r], r))
    for k in range(rem):
        base[order[k % len(order)]] += 1
    return base


def snap_to_groups(cum_targets, group_bounds) -> Optional[Tuple[int, ...]]:
    """Snap ideal cumulative cut points to the nearest query-group
    boundary, keeping the cuts strictly increasing and strictly inside
    ``(0, total)``.  Ties break toward the lower boundary; collisions
    push the later cut to the next greater boundary.  Returns ``None``
    when there are fewer interior boundaries than cuts (a rank would
    own zero groups) — the caller holds position instead of moving."""
    gb = np.asarray(group_bounds, np.int64)
    total = int(gb[-1])
    interior = gb[(gb > 0) & (gb < total)]
    cuts: List[int] = []
    prev = 0
    for t in cum_targets:
        cand = interior[interior > prev]
        if cand.size == 0:
            return None
        i = int(np.searchsorted(cand, int(t)))
        if i == 0:
            pick = int(cand[0])
        elif i >= cand.size:
            pick = int(cand[-1])
        else:
            lo, hi = int(cand[i - 1]), int(cand[i])
            pick = lo if int(t) - lo <= hi - int(t) else hi
        cuts.append(pick)
        prev = pick
    return tuple(cuts)


def _apply_floor(counts: List[int], floor: int, total: int) -> List[int]:
    """Raise every shard to ``floor`` rows, taking from the largest."""
    out = list(counts)
    for r in range(len(out)):
        while out[r] < floor:
            donor = int(np.argmax(out))
            if donor == r or out[donor] <= floor:
                break
            give = min(floor - out[r], out[donor] - floor)
            if give <= 0:
                break
            out[donor] -= give
            out[r] += give
    assert sum(out) == total
    return out


# ----------------------------------------------------------------------
# row-block wire: framed raw-numpy bytes (no pickle on the wire)
# ----------------------------------------------------------------------
# Same framing idea as the quantized ``hist_q`` histogram wire: fixed
# struct headers + a CRC32 over each array payload, so a corrupted or
# truncated blob fails loudly instead of deserializing garbage.  The
# payload is the raw C-order buffer — byte-for-byte reproducible, which
# the round-trip test pins.
_RB_MAGIC = b"RB1\x00"
_RB_HDR = struct.Struct("<I")          # span count
_RB_SPAN = struct.Struct("<qqI")       # g0, g1, piece count
_RB_PIECE = struct.Struct("<HHBB")     # name len, dtype len, axis, ndim


def _pack_row_wire(outgoing: Dict[Tuple[int, int], Dict[str, np.ndarray]]
                   ) -> bytes:
    parts = [_RB_MAGIC, _RB_HDR.pack(len(outgoing))]
    for (g0, g1) in sorted(outgoing):
        blocks = outgoing[(g0, g1)]
        parts.append(_RB_SPAN.pack(g0, g1, len(blocks)))
        for name in sorted(blocks):
            arr = np.ascontiguousarray(blocks[name])
            nb = name.encode("utf-8")
            db = arr.dtype.str.encode("ascii")
            payload = arr.tobytes()
            parts.append(_RB_PIECE.pack(len(nb), len(db), 0, arr.ndim))
            parts.append(nb)
            parts.append(db)
            parts.append(struct.pack(f"<{arr.ndim}q", *arr.shape))
            parts.append(struct.pack("<QI", len(payload),
                                     zlib.crc32(payload)))
            parts.append(payload)
    return b"".join(parts)


def _unpack_row_wire(blob: bytes
                     ) -> Dict[Tuple[int, int], Dict[str, np.ndarray]]:
    if blob[:len(_RB_MAGIC)] != _RB_MAGIC:
        raise ValueError("rebalance wire: bad magic")
    off = len(_RB_MAGIC)
    (n_spans,) = _RB_HDR.unpack_from(blob, off)
    off += _RB_HDR.size
    out: Dict[Tuple[int, int], Dict[str, np.ndarray]] = {}
    for _ in range(n_spans):
        g0, g1, n_pieces = _RB_SPAN.unpack_from(blob, off)
        off += _RB_SPAN.size
        blocks: Dict[str, np.ndarray] = {}
        for _p in range(n_pieces):
            nlen, dlen, _axis, ndim = _RB_PIECE.unpack_from(blob, off)
            off += _RB_PIECE.size
            name = blob[off:off + nlen].decode("utf-8")
            off += nlen
            dtype = np.dtype(blob[off:off + dlen].decode("ascii"))
            off += dlen
            shape = struct.unpack_from(f"<{ndim}q", blob, off)
            off += 8 * ndim
            nbytes, crc = struct.unpack_from("<QI", blob, off)
            off += 12
            payload = blob[off:off + nbytes]
            off += nbytes
            if len(payload) != nbytes or zlib.crc32(payload) != crc:
                raise ValueError(
                    f"rebalance wire: CRC/length mismatch for {name!r} "
                    f"span [{g0},{g1})")
            blocks[name] = np.frombuffer(payload, dtype).reshape(shape)
        out[(g0, g1)] = blocks
    return out


# ----------------------------------------------------------------------
# applying a plan: row-block exchange over the hardened collectives
# ----------------------------------------------------------------------
def _subtract(a: Tuple[int, int], b: Tuple[int, int]
              ) -> List[Tuple[int, int]]:
    """Interval a minus interval b (half-open), as up to two pieces."""
    out = []
    if a[0] < min(a[1], b[0]):
        out.append((a[0], min(a[1], b[0])))
    if max(a[0], b[1]) < a[1]:
        out.append((max(a[0], b[1]), a[1]))
    return out


def exchange_rows(old_plan: ShardPlan, new_plan: ShardPlan, rank: int,
                  row_blocks: Dict[str, Tuple[np.ndarray, int]],
                  comm=None) -> Dict[str, np.ndarray]:
    """Move rows between ranks so every rank ends up owning its
    ``new_plan`` range.  ``row_blocks`` maps name -> (array, row_axis)
    holding the rank's CURRENT rows in global row order.  Returns the
    new local arrays, rows in global order.

    Each rank broadcasts only the row blocks LEAVING it (allgather over
    parallel/collect.py, or ``comm`` when the caller runs on a live
    membership fleet; tagged ``purpose="rebalance"`` in the comms
    ledger); receivers take the pieces intersecting their new range.
    Retained rows never leave the rank.  The wire is framed raw-numpy
    bytes (:func:`_pack_row_wire`), never pickle."""
    if old_plan.total != new_plan.total or old_plan.world != new_plan.world:
        raise ValueError(
            f"plan mismatch: {old_plan.counts} -> {new_plan.counts}")
    old_s, old_e = old_plan.rank_range(rank)
    new_s, new_e = new_plan.rank_range(rank)

    def _take(arr: np.ndarray, axis: int, lo: int, hi: int) -> np.ndarray:
        # lo/hi in LOCAL (old-range) coordinates
        sl = [slice(None)] * arr.ndim
        sl[axis] = slice(lo, hi)
        return np.ascontiguousarray(arr[tuple(sl)])

    outgoing = {}
    for (g0, g1) in _subtract((old_s, old_e), (new_s, new_e)):
        outgoing[(g0, g1)] = {
            name: _take(np.asarray(arr), axis, g0 - old_s, g1 - old_s)
            for name, (arr, axis) in row_blocks.items()
        }
    wire = _pack_row_wire(outgoing)
    if comm is not None:
        gathered = comm.allgather(wire, purpose="rebalance")
    else:
        from .collect import allgather_bytes

        gathered = allgather_bytes(wire, purpose="rebalance")

    n_new = new_e - new_s
    out: Dict[str, np.ndarray] = {}
    for name, (arr, axis) in row_blocks.items():
        arr = np.asarray(arr)
        shape = list(arr.shape)
        shape[axis] = n_new
        dst = np.empty(shape, arr.dtype)
        # retained intersection stays local
        lo, hi = max(old_s, new_s), min(old_e, new_e)
        if lo < hi:
            sl = [slice(None)] * dst.ndim
            sl[axis] = slice(lo - new_s, hi - new_s)
            dst[tuple(sl)] = _take(arr, axis, lo - old_s, hi - old_s)
        out[name] = dst
    filled = max(0, min(old_e, new_e) - max(old_s, new_s))
    for blob in gathered:
        for (g0, g1), blocks in _unpack_row_wire(blob).items():
            lo, hi = max(g0, new_s), min(g1, new_e)
            if lo >= hi:
                continue
            for name, piece in blocks.items():
                axis = row_blocks[name][1]
                sl = [slice(None)] * out[name].ndim
                sl[axis] = slice(lo - new_s, hi - new_s)
                psl = [slice(None)] * piece.ndim
                psl[axis] = slice(lo - g0, hi - g0)
                out[name][tuple(sl)] = piece[tuple(psl)]
            filled += hi - lo
    if filled != n_new:
        raise RuntimeError(
            f"rebalance exchange left rows unfilled on rank {rank}: "
            f"{filled}/{n_new}")
    Log.debug("Rebalance exchange on rank %d: [%d,%d) -> [%d,%d)",
              rank, old_s, old_e, new_s, new_e)
    return out
