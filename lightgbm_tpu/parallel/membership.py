"""Live elastic fleet membership over a shared-directory KV store.

PR 15 made topology a *restart-time* quantity: canonical checkpoints
merge every rank's training state into a rank-free form and reshard it
to any world size — but resizing still meant killing the whole fleet
and relaunching it.  This module makes membership a *runtime* event.

The design deliberately does NOT ride ``jax.distributed``: its C++
coordination service pins the fleet size at init and turns any peer
death into an uncatchable process-fatal ("a task has died").  Instead,
every worker runs single-process JAX and ALL coordination flows through
a :class:`FileKVClient` — a shared-directory store that duck-types the
jaxlib coordination-client surface ``net.py`` already hardens
(deadline-bounded gathers, chunked payloads, CRC framing, heartbeat
liveness).  Externalizing the liveness-critical KV state this way is
what makes the coordinator survivable: rank 0 owns no process-bound
state, so its death is just another eviction and the lowest surviving
member id is, by construction, the deterministically re-elected
coordinator.

Protocol (all keys live under the fleet's shared directory):

- ``members/<id>``       write-once id allocation (monotonic; joiners
                         scan upward with :meth:`FileKVClient.try_create`)
- ``ltpu_hb/<id>/<seq>`` net.py heartbeats, swept by :class:`MemberWatch`
- ``intent/join/<id>``   a joiner announcing itself
- ``dead/<E>/<id>``      staleness evidence, written by any survivor
- ``epoch/<E>``          the generation-stamped membership record
                         (members, shard counts, iteration, num_data),
                         write-once by epoch ``E``'s coordinator
- ``handoff/<E>``        canonical TrainState bytes for epoch ``E``,
                         written BEFORE ``epoch/<E>`` so an admitted
                         joiner never races an absent handoff

Per-iteration boundary, every member runs :meth:`MembershipRuntime.sync`
— a small KV allgather of frozen intent payloads.  The participant set
is folded into the collective uid, so members with divergent views of
who is alive gather in disjoint key spaces and time out instead of
corrupting each other; staleness evidence converges through ``dead/<E>``
and the retry succeeds once every survivor sees the same world.  The
transition itself (state merge + reshard) stays in ``boosting/gbdt.py``,
which owns the training state; this module only moves bytes and decides
rosters.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import threading
import time
import urllib.parse
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..obs import tracer
from . import net

# disjoint uid namespaces per purpose; python-int keys, so width is free
_NS_COMM = 1 << 59       # learner-comm allgathers   | (E<<40) | seq
_NS_SYNC = 1 << 60       # boundary membership syncs | (E<<40) | (idx<<16) | dig
_NS_TRANS = 1 << 58      # transition state gathers  | (E<<40) | (idx<<16) | dig

_SYNC_ATTEMPTS = 4       # bounded convergence: then PeerFailureError


class CleanLeave(Exception):
    """Raised through the training loop after a SIGTERM'd worker has
    handed its shard off at an epoch transition: the worker should
    flush outputs and exit 0, not 75."""

    def __init__(self, epoch: int):
        super().__init__(f"clean leave at membership epoch {epoch}")
        self.epoch = int(epoch)


# ----------------------------------------------------------------------
# FileKVClient: shared-directory store with the jaxlib client surface
# ----------------------------------------------------------------------
class _Deadline(Exception):
    """str() carries DEADLINE_EXCEEDED so net._is_deadline_error
    classifies a missing key exactly like the jaxlib client."""


def _enc(component: str) -> str:
    # "." / ".." are valid quote() outputs but walk the directory tree;
    # encode the leading dot so every component stays a plain basename
    q = urllib.parse.quote(component, safe="")
    return "%2E" + q[1:] if q.startswith(".") else q


def _dec(component: str) -> str:
    return urllib.parse.unquote(component)


class FileKVClient:
    """Duck-types the jaxlib coordination-client KV surface on a shared
    directory.  Keys map to nested paths (one percent-encoded path
    component per ``/``-separated key component); every write lands via
    an atomic rename so readers never observe partial values, and
    :meth:`try_create` adds the write-once primitive (hardlink publish)
    the membership protocol builds its epoch records on."""

    def __init__(self, root: str, poll_s: float = 0.02):
        self._root = os.path.abspath(root)
        self._poll = float(poll_s)
        self._tmp_seq = 0
        self._lock = threading.Lock()
        os.makedirs(self._root, exist_ok=True)

    # -- paths ---------------------------------------------------------
    def _path(self, key: str) -> str:
        parts = [p for p in key.split("/") if p]
        if not parts:
            raise ValueError(f"empty KV key: {key!r}")
        return os.path.join(self._root, *[_enc(p) for p in parts])

    def _tmp_path(self, final: str) -> str:
        with self._lock:
            self._tmp_seq += 1
            seq = self._tmp_seq
        # pid alone is not unique: several clients can share one process
        # (in-process fleet tests, the spot supervisor's own client)
        return os.path.join(os.path.dirname(final),
                            f".tmp.{os.getpid()}.{id(self):x}.{seq}")

    def _write(self, key: str, value: bytes, *, exclusive: bool) -> bool:
        final = self._path(key)
        os.makedirs(os.path.dirname(final), exist_ok=True)
        tmp = self._tmp_path(final)
        with open(tmp, "wb") as f:
            f.write(value)
            f.flush()
            os.fsync(f.fileno())
        try:
            if exclusive:
                try:
                    os.link(tmp, final)  # atomic create-or-fail, full value
                except FileExistsError:
                    return False
            else:
                os.replace(tmp, final)
                tmp = None
            return True
        finally:
            if tmp is not None:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass

    # -- jaxlib-compatible surface -------------------------------------
    def key_value_set_bytes(self, key: str, value: bytes) -> None:
        self._write(key, bytes(value), exclusive=False)

    def key_value_set(self, key: str, value: str) -> None:
        self._write(key, value.encode("utf-8"), exclusive=False)

    def blocking_key_value_get_bytes(self, key: str, timeout_ms: int) -> bytes:
        path = self._path(key)
        deadline = time.monotonic() + max(0, int(timeout_ms)) / 1000.0
        while True:
            try:
                with open(path, "rb") as f:
                    return f.read()
            except (FileNotFoundError, IsADirectoryError):
                pass
            if time.monotonic() >= deadline:
                raise _Deadline(f"DEADLINE_EXCEEDED: kv key {key!r} "
                                f"absent after {timeout_ms}ms")
            time.sleep(self._poll)

    def blocking_key_value_get(self, key: str, timeout_ms: int) -> str:
        return self.blocking_key_value_get_bytes(key, timeout_ms).decode(
            "utf-8", errors="replace")

    def key_value_dir_get(self, prefix: str) -> List[Tuple[str, str]]:
        parts = [p for p in prefix.split("/") if p]
        base = os.path.join(self._root, *[_enc(p) for p in parts])
        if not os.path.isdir(base):
            return []
        out: List[Tuple[str, str]] = []
        for dirpath, _dirnames, filenames in os.walk(base):
            for name in filenames:
                if name.startswith(".tmp"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, name), base)
                comps = parts + [_dec(c) for c in rel.split(os.sep)]
                try:
                    with open(os.path.join(dirpath, name), "rb") as f:
                        val = f.read().decode("utf-8", errors="replace")
                except OSError:
                    continue  # racing a delete / mid-publish
                out.append(("/".join(comps), val))
        return out

    def key_value_delete(self, key: str) -> None:
        if key.endswith("/"):
            shutil.rmtree(self._path(key), ignore_errors=True)
            return
        try:
            os.unlink(self._path(key))
        except OSError:
            pass

    # -- membership extension ------------------------------------------
    def try_create(self, key: str, value: bytes) -> bool:
        """Atomic write-once: True iff this call published ``key``.
        Readers that win the race still see the COMPLETE value — the
        content is fully written to a tmp file before the hardlink
        makes it visible under the final name."""
        return self._write(key, bytes(value), exclusive=True)


# ----------------------------------------------------------------------
# MemberWatch: PeerWatch over an explicit, mutable member-id set
# ----------------------------------------------------------------------
class MemberWatch(net.PeerWatch):
    """``net.PeerWatch`` sweeps ranks ``0..nproc-1``; after churn the
    live member ids are sparse (ids are monotonic, never reused), so
    this subclass sweeps an explicit set instead.  ``set_members`` is
    called at every epoch transition; staleness bookkeeping for ids
    that stay members carries over untouched."""

    def __init__(self, client, member_id: int, members: Sequence[int],
                 stale_after_s: Optional[float] = None,
                 time_fn: Callable[[], float] = time.monotonic):
        super().__init__(client, rank=member_id, nproc=0,
                         stale_after_s=stale_after_s, time_fn=time_fn)
        self._members = frozenset(int(m) for m in members)

    def set_members(self, members: Sequence[int]) -> None:
        with self._lock:
            self._members = frozenset(int(m) for m in members)
            # evicted / departed ids must not linger as "stale peers"
            for r in list(self._seen):
                if r not in self._members:
                    del self._seen[r]

    def ages(self) -> Dict[int, float]:
        now = self._time()
        states = self._states()
        out: Dict[int, float] = {}
        with self._lock:
            for r in sorted(self._members):
                if r == self.rank:
                    continue
                cur = states.get(r, "<absent>")
                prev = self._seen.get(r)
                if prev is None or prev[0] != cur:
                    # same baseline rule as PeerWatch.ages: a key absent
                    # on first sight counts from watch start so a
                    # never-started member still times out
                    t_mark = self._t_start if (
                        prev is None and cur == "<absent>"
                    ) else now
                    self._seen[r] = (cur, t_mark)
                    out[r] = now - t_mark
                else:
                    out[r] = now - prev[1]
        return out


# ----------------------------------------------------------------------
# churn decisions
# ----------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class ChurnDecision:
    """Deterministic outcome of one membership sync: every participant
    derives the identical decision from the identical gathered payloads,
    so no separate agreement round is needed."""

    leavers: Tuple[int, ...]       # clean SIGTERM departures (still alive)
    dead: Tuple[int, ...]          # evicted by staleness evidence
    joiners: Tuple[int, ...]       # admitted intent/join ids
    participants: Tuple[int, ...]  # old members still alive (incl leavers)
    new_members: Tuple[int, ...]   # the next epoch's sorted roster

    @property
    def survivors(self) -> Tuple[int, ...]:
        return tuple(m for m in self.participants if m not in self.leavers)


def _digest(parts: Sequence[int]) -> int:
    raw = ",".join(str(p) for p in parts).encode("ascii")
    return zlib.crc32(raw) & 0xFFFF


# ----------------------------------------------------------------------
# MembershipRuntime
# ----------------------------------------------------------------------
class MembershipRuntime:
    """One worker's handle on the fleet: identity, roster, heartbeat,
    liveness watch, and the epoch-stamped sync/transition protocol.

    Lifecycle: construct -> :meth:`bootstrap` (launch-time member) or
    :meth:`join` (mid-run arrival) -> the booster routes collectives
    through :meth:`comm_allgather` and calls :meth:`sync` at every
    iteration boundary -> on churn, :meth:`gather_states` +
    :meth:`commit_epoch` move the fleet to the next epoch."""

    def __init__(self, root: str, member_id: Optional[int] = None):
        self.root = os.path.abspath(root)
        self.client = FileKVClient(os.path.join(self.root, "kv"))
        self.id = None if member_id is None else int(member_id)
        self.epoch: int = -1
        self.members: Tuple[int, ...] = ()
        self.counts: Optional[Tuple[int, ...]] = None
        self.start_iter: int = 0
        self.num_data: Optional[int] = None
        self.joined_mid_run = False
        # seam: fn(lo, hi) -> (X_raw, y) regenerating ABSOLUTE global
        # rows [lo, hi); required to synthesize an evicted member's
        # shard and to grow a survivor's shard without a disk round-trip
        self.row_provider = None
        self._leave = threading.Event()
        self._hb: Optional[net.HeartbeatWriter] = None
        self.watch: Optional[MemberWatch] = None
        self._comm_seq = 0
        self._sync_index = 0
        self._trans_index = 0
        self._last_sync_uid: Optional[int] = None

    # -- identity / roster ---------------------------------------------
    @property
    def rank(self) -> int:
        return self.members.index(self.id)

    @property
    def nproc(self) -> int:
        return len(self.members)

    @property
    def is_coordinator(self) -> bool:
        return bool(self.members) and self.id == self.members[0]

    def request_leave(self) -> None:
        """Signal-handler safe: marks the intent; the leave itself is
        negotiated at the next iteration-boundary sync."""
        self._leave.set()

    @property
    def leave_requested(self) -> bool:
        return self._leave.is_set()

    # -- lifecycle -----------------------------------------------------
    def _start_liveness(self) -> None:
        s = net.settings()
        self._hb = net.HeartbeatWriter(self.client, self.id,
                                       interval_s=s.hb_interval())
        self._hb.start()
        self.watch = MemberWatch(self.client, self.id, self.members)

    def _adopt_epoch(self, epoch: int, record: Dict) -> None:
        self.epoch = int(epoch)
        self.members = tuple(int(m) for m in record["members"])
        self.counts = tuple(int(c) for c in record["counts"])
        self.start_iter = int(record.get("iteration", 0))
        self.num_data = int(record["num_data"])
        self._comm_seq = 0
        self._sync_index = 0
        self._trans_index = 0
        if self.watch is not None:
            self.watch.set_members(self.members)

    def bootstrap(self, nproc: int, counts: Sequence[int]) -> None:
        """Launch-time member ``id in [0, nproc)``: register the id,
        have the lowest id publish epoch 0, and adopt it."""
        if self.id is None or not (0 <= self.id < nproc):
            raise ValueError(f"bootstrap needs member_id in [0,{nproc}), "
                             f"got {self.id}")
        self.client.try_create(f"members/{self.id}", b"1")
        record = {"members": list(range(nproc)),
                  "counts": [int(c) for c in counts],
                  "iteration": 0, "num_data": int(sum(counts))}
        if self.id == 0:
            self.client.try_create("epoch/0",
                                   json.dumps(record).encode("utf-8"))
        blob = self.client.blocking_key_value_get_bytes(
            "epoch/0", int(net.settings().deadline_s * 1000))
        self._adopt_epoch(0, json.loads(blob))
        self._start_liveness()
        tracer.event("member.join", member=self.id, epoch=0, mid_run=False)

    def _epoch_records(self) -> Dict[int, Dict]:
        out = {}
        for key, _val in self.client.key_value_dir_get("epoch/"):
            try:
                e = int(key.split("/")[-1])
            except ValueError:
                continue
            blob = self.client.blocking_key_value_get_bytes(f"epoch/{e}",
                                                            1000)
            out[e] = json.loads(blob)
        return out

    def join(self, timeout_s: Optional[float] = None) -> None:
        """Mid-run arrival: allocate the next monotonic id, announce
        intent, and block until an epoch record admits us."""
        budget = (timeout_s if timeout_s is not None
                  else 8 * net.settings().deadline_s)
        deadline = time.monotonic() + budget
        if self.id is None:
            # the fleet is born before anyone can join it: wait for its
            # first epoch record, then allocate strictly ABOVE every id
            # any record has ever listed — a joiner racing the launch
            # members' registration must never steal a launch-time id
            self.client.blocking_key_value_get_bytes(
                "epoch/0", int(max(1.0, budget) * 1000))
            floor = 1 + max(m for rec in self._epoch_records().values()
                            for m in rec["members"])
            i = floor
            while not self.client.try_create(f"members/{i}", b"1"):
                i += 1
            self.id = i
        else:
            self.client.try_create(f"members/{self.id}", b"1")
        self.members = (self.id,)  # provisional, until admitted
        self._start_liveness()
        self.client.key_value_set_bytes(f"intent/join/{self.id}", b"1")
        poll = min(0.05, max(0.01, net.settings().poll_s()))
        while True:
            best = None
            for key, _val in self.client.key_value_dir_get("epoch/"):
                try:
                    e = int(key.split("/")[-1])
                except ValueError:
                    continue
                if best is None or e > best:
                    best = e
            if best is not None:
                blob = self.client.blocking_key_value_get_bytes(
                    f"epoch/{best}", 1000)
                record = json.loads(blob)
                if self.id in record["members"]:
                    self._adopt_epoch(best, record)
                    break
            if time.monotonic() >= deadline:
                raise net.CollectiveTimeoutError(
                    f"join: no epoch admitted member {self.id} within "
                    f"{budget:.1f}s", elapsed_s=budget)
            time.sleep(poll)
        self.joined_mid_run = True
        self.client.key_value_delete(f"intent/join/{self.id}")
        tracer.event("member.join", member=self.id, epoch=self.epoch,
                     mid_run=True)

    def stop(self) -> None:
        if self._hb is not None:
            self._hb.stop()
            self._hb = None

    # -- collectives ---------------------------------------------------
    def comm_allgather(self, blob: bytes, what: str = "collective"
                       ) -> List[bytes]:
        """Learner-plane allgather among the current epoch's members.
        uid is epoch-prefixed so a retried iteration after an epoch bump
        can never collide with a stale pre-transition key."""
        uid = net.epoch_uid(self.epoch, self._comm_seq, ns=_NS_COMM)
        self._comm_seq += 1
        return net.kv_gather(uid, blob, client=self.client, rank=self.rank,
                             nproc=self.nproc, watch=self.watch, what=what)

    # -- boundary sync -------------------------------------------------
    def _mark_dead(self, member: int) -> None:
        if member != self.id and member in self.members:
            self.client.try_create(f"dead/{self.epoch}/{int(member)}", b"1")

    def _read_dead(self) -> frozenset:
        out = set()
        for key, _val in self.client.key_value_dir_get(f"dead/{self.epoch}/"):
            try:
                out.add(int(key.split("/")[-1]))
            except ValueError:
                continue
        return frozenset(out & set(self.members) - {self.id})

    def _poll_joins(self) -> List[int]:
        out = set()
        for key, _val in self.client.key_value_dir_get("intent/join/"):
            try:
                out.add(int(key.split("/")[-1]))
            except ValueError:
                continue
        return sorted(out - set(self.members))

    def sync(self, known_dead: Sequence[int] = ()) -> Optional[ChurnDecision]:
        """One boundary sync.  Returns None when the world is unchanged,
        a :class:`ChurnDecision` otherwise.  Lockstep program order
        guarantees every member runs sync ``i`` at the same training
        point, so the (epoch, index, participant-digest) uid triple is
        identical exactly when the members agree on who is alive —
        divergent views gather in disjoint uid spaces, time out, refresh
        the ``dead/<E>`` evidence, and retry until they converge."""
        for d in known_dead:
            self._mark_dead(d)
        payload = json.dumps({
            "id": self.id,
            "leave": self._leave.is_set(),
            "joins": self._poll_joins(),
        }).encode("utf-8")  # frozen: every retry re-posts identical bytes
        idx = self._sync_index
        self._sync_index += 1
        deadline_s = net.settings().deadline_s
        last_err: Optional[BaseException] = None
        for _attempt in range(_SYNC_ATTEMPTS):
            dead = self._read_dead()
            parts = tuple(m for m in self.members if m not in dead)
            uid = net.epoch_uid(self.epoch, (idx << 16) | _digest(parts),
                                ns=_NS_SYNC)
            try:
                blobs = net.kv_gather(
                    uid, payload, client=self.client,
                    rank=parts.index(self.id), nproc=len(parts),
                    deadline_s=deadline_s, watch=None, what="member_sync")
            except Exception as e:
                last_err = e
                if self.watch is not None:
                    for d in self.watch.dead_ranks():
                        self._mark_dead(d)
                continue
            records = [json.loads(b) for b in blobs]
            if tuple(sorted(r["id"] for r in records)) != parts:
                last_err = net.CollectiveTimeoutError(
                    "member_sync uid collision", elapsed_s=0.0)
                continue  # 16-bit digest collision between divergent views
            if self._last_sync_uid is not None:
                # GC our slot from the previous sync's uid space
                self.client.key_value_delete(
                    f"{net._COLLECT_DIR}{self._last_sync_uid}/"
                    f"{self._last_sync_rank}")
            self._last_sync_uid = uid
            self._last_sync_rank = parts.index(self.id)
            leavers = tuple(sorted(r["id"] for r in records if r["leave"]))
            joins = set()
            for r in records:
                joins.update(int(j) for j in r.get("joins", ()))
            joiners = tuple(sorted(joins - set(self.members)))
            dead = tuple(sorted(set(self.members) - set(parts)))
            if not leavers and not joiners and not dead:
                return None
            new_members = tuple(sorted(
                (set(parts) - set(leavers)) | set(joiners)))
            if not new_members:
                raise net.PeerFailureError(
                    "membership sync left an empty fleet", ranks=dead)
            return ChurnDecision(leavers=leavers, dead=dead,
                                 joiners=joiners, participants=parts,
                                 new_members=new_members)
        raise net.PeerFailureError(
            f"membership sync {idx} failed to converge after "
            f"{_SYNC_ATTEMPTS} attempts: {last_err}",
            ranks=tuple(sorted(self._read_dead())))

    # -- transition ----------------------------------------------------
    def gather_states(self, state_bytes: bytes,
                      participants: Sequence[int]) -> List[bytes]:
        """Allgather TrainState bytes among ``participants`` (the old
        roster minus the dead — leavers included, they hand their shard
        off before exiting).  Chunking/CRC framing comes from
        ``net.kv_gather``; a death mid-transition raises
        PeerFailureError and the caller re-syncs."""
        parts = tuple(participants)
        idx = self._trans_index
        self._trans_index += 1
        uid = net.epoch_uid(self.epoch, (idx << 16) | _digest(parts),
                            ns=_NS_TRANS)
        return net.kv_gather(uid, state_bytes, client=self.client,
                             rank=parts.index(self.id), nproc=len(parts),
                             watch=self.watch, what="member_handoff")

    def commit_epoch(self, decision: ChurnDecision, counts: Sequence[int],
                     iteration: int, num_data: int,
                     handoff_bytes: Optional[bytes] = None) -> None:
        """Advance to epoch E+1.  The NEW coordinator (lowest id of the
        new roster — deterministic re-election) publishes the handoff
        before the epoch record, so an admitted joiner can always read
        both; every survivor adopts the new roster locally without
        reading the record back (they derived it)."""
        new_epoch = self.epoch + 1
        record = {"members": list(decision.new_members),
                  "counts": [int(c) for c in counts],
                  "iteration": int(iteration), "num_data": int(num_data)}
        for d in decision.dead:
            tracer.event("member.evict", member=d, epoch=new_epoch)
        for l in decision.leavers:
            tracer.event("member.leave", member=l, epoch=new_epoch)
        for j in decision.joiners:
            tracer.event("member.join", member=j, epoch=new_epoch,
                         mid_run=True)
        if self.id == min(decision.new_members):
            if handoff_bytes is not None:
                self.client.try_create(f"handoff/{new_epoch}", handoff_bytes)
            self.client.try_create(f"epoch/{new_epoch}",
                                   json.dumps(record).encode("utf-8"))
            # GC: superseded handoff + staleness evidence + join intents
            # + collective keys from epochs every member has left behind
            # (epoch E keys may still be mid-read by a slow survivor;
            # E-1 and older are provably drained — lockstep program
            # order puts every member past the E-1 -> E transition)
            self.client.key_value_delete(f"handoff/{new_epoch - 1}")
            self.client.key_value_delete(f"dead/{self.epoch}/")
            for j in decision.joiners:
                self.client.key_value_delete(f"intent/join/{j}")
            for gone in tuple(decision.dead) + tuple(decision.leavers):
                self.client.key_value_delete(f"{net._HB_DIR}{gone}/")
            self._gc_collect_epochs(before=self.epoch)
        self._adopt_epoch(new_epoch, record)
        tracer.event("member.epoch", epoch=new_epoch,
                     members=list(self.members),
                     coordinator=self.members[0], iteration=int(iteration))

    def _gc_collect_epochs(self, before: int) -> None:
        """Delete membership-namespaced collective keys whose epoch field
        is strictly below ``before`` (they can no longer be read)."""
        seen = set()
        for key, _val in self.client.key_value_dir_get(net._COLLECT_DIR):
            parts = key.split("/")
            if len(parts) < 2:
                continue
            try:
                uid = int(parts[1])
            except ValueError:
                continue
            if uid < _NS_TRANS or uid in seen:
                continue  # static-world collect.py uids: no namespace
            seen.add(uid)
            if net.uid_epoch(uid) < before:
                self.client.key_value_delete(
                    f"{net._COLLECT_DIR}{uid}/")

    def read_handoff(self, epoch: Optional[int] = None) -> bytes:
        e = self.epoch if epoch is None else int(epoch)
        return self.client.blocking_key_value_get_bytes(
            f"handoff/{e}", int(net.settings().deadline_s * 1000))


# ----------------------------------------------------------------------
# learner communicator
# ----------------------------------------------------------------------
class MembershipComm:
    """``parallel/comm.py`` Comm surface whose rank/world follow the
    live epoch: the HostParallelLearner reads ``comm.rank`` /
    ``comm.nproc`` on every collective, so an epoch transition resizes
    the learner with no learner-side code.  Not a ``Comm`` subclass
    constructor-wise: rank/nproc are live properties here, while the
    base class pins them as attributes at construction."""

    def __init__(self, runtime: MembershipRuntime):
        self._rt = runtime
        self.ledger: Dict[str, int] = {}

    @property
    def rank(self) -> int:
        return self._rt.rank

    @property
    def nproc(self) -> int:
        return self._rt.nproc

    @property
    def epoch(self) -> int:
        return self._rt.epoch

    def _account(self, blob: bytes, purpose: str) -> None:
        self.ledger[purpose] = self.ledger.get(purpose, 0) + len(blob)

    def ledger_total(self) -> int:
        return sum(self.ledger.values())

    def allgather(self, blob: bytes, purpose: str = "misc") -> List[bytes]:
        self._account(blob, purpose)
        tracer.counter("net.bytes", float(len(blob)), purpose=purpose,
                       transport="member_kv")
        net.fault_point("collective")
        return self._rt.comm_allgather(blob, what=purpose)


# ----------------------------------------------------------------------
# process-wide registry (worker scripts arm it before Booster init)
# ----------------------------------------------------------------------
_runtime: Optional[MembershipRuntime] = None


def set_runtime(rt: Optional[MembershipRuntime]) -> None:
    global _runtime
    _runtime = rt


def runtime() -> Optional[MembershipRuntime]:
    return _runtime


def runtime_from_env() -> Optional[MembershipRuntime]:
    """Fallback arming for processes that did not construct a runtime
    explicitly: LIGHTGBM_TPU_MEMBER_DIR names the fleet directory and
    LIGHTGBM_TPU_MEMBER_ID this worker's id (bootstrap/join is still
    the worker's job — this only builds the unadopted handle)."""
    root = os.environ.get("LIGHTGBM_TPU_MEMBER_DIR")
    if not root:
        return None
    mid = os.environ.get("LIGHTGBM_TPU_MEMBER_ID")
    return MembershipRuntime(root, None if mid is None else int(mid))
