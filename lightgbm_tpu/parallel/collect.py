"""Host-level collective helpers for variable-length payloads.

The reference's distributed find-bin allgathers serialized BinMappers
with fixed-width copy buffers sized by an Allreduce'd max
(dataset_loader.cpp:733-835).  Here every host-side merge (bin mappers,
ingest statistics sketches) rides one code path with two transports:

- device arrays via ``multihost_utils.process_allgather`` (length-
  prefixed blobs padded to a gathered max) when the backend supports
  multi-process computations;
- the distributed-runtime key-value store (the same store
  ``jax.distributed.initialize`` bootstraps from) on backends that do
  not — XLA:CPU rejects multi-process programs outright, which is
  exactly the multi-host ingest test environment.

The transport is chosen deterministically from the backend name so
every process takes the same branch (a mixed choice would deadlock).
Single-process runs short-circuit without touching the backend.
"""

from __future__ import annotations

import itertools
import pickle
from typing import List, Optional

import numpy as np

# per-process call counter: processes make collective calls in the same
# program order, so the counter yields matching keys across ranks
_kv_uid = itertools.count()


def _kv_allgather(blob: bytes) -> List[bytes]:
    import jax
    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:
        raise RuntimeError("distributed runtime not initialized")
    rank = jax.process_index()
    nproc = jax.process_count()
    uid = next(_kv_uid)
    client.key_value_set(f"ltpu_collect/{uid}/{rank}", blob.hex())
    out = []
    for r in range(nproc):
        v = client.blocking_key_value_get(f"ltpu_collect/{uid}/{r}", 120_000)
        out.append(bytes.fromhex(v))
    return out


def _array_allgather(blob: bytes) -> List[bytes]:
    import jax
    from jax.experimental import multihost_utils

    gmax = int(np.max(multihost_utils.process_allgather(
        np.asarray(len(blob), np.int64)
    )))
    buf = np.zeros(gmax + 8, np.uint8)
    buf[:8] = np.frombuffer(len(blob).to_bytes(8, "little"), np.uint8)
    buf[8 : 8 + len(blob)] = np.frombuffer(blob, np.uint8)
    gathered = np.asarray(multihost_utils.process_allgather(buf))
    out = []
    for r in range(gathered.shape[0]):
        ln = int.from_bytes(gathered[r, :8].tobytes(), "little")
        out.append(gathered[r, 8 : 8 + ln].tobytes())
    return out


def allgather_bytes(blob: bytes) -> List[bytes]:
    """One blob per process -> every process's blob, in process order."""
    import jax

    if jax.process_count() == 1:
        return [blob]
    if jax.default_backend() == "cpu":
        # XLA:CPU has no multi-process computations; use the KV store
        return _kv_allgather(blob)
    return _array_allgather(blob)


def allgather_blob_lists(
    blobs: List[bytes], list_len: Optional[int] = None
) -> List[List[bytes]]:
    """Gather each process's list of byte blobs; returns one list per
    process, in process order.  ``list_len`` pads every process's list
    to a common length (callers that index a fixed feature-block shape
    — e.g. the last find-bin block being short); padded slots come back
    as empty blobs."""
    pad = list_len if list_len is not None else len(blobs)
    payload = pickle.dumps(list(blobs) + [b""] * (pad - len(blobs)),
                           protocol=pickle.HIGHEST_PROTOCOL)
    return [pickle.loads(p) for p in allgather_bytes(payload)]
