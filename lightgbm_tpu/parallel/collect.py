"""Host-level collective helpers for variable-length payloads.

The reference's distributed find-bin allgathers serialized BinMappers
with fixed-width copy buffers sized by an Allreduce'd max
(dataset_loader.cpp:733-835).  Here every host-side merge (bin mappers,
ingest statistics sketches, checkpoint barriers) rides one code path
with two transports:

- device arrays via ``multihost_utils.process_allgather`` (length-
  prefixed blobs padded to a gathered max) when the backend supports
  multi-process computations;
- the distributed-runtime key-value store (the same store
  ``jax.distributed.initialize`` bootstraps from) on backends that do
  not — XLA:CPU rejects multi-process programs outright, which is
  exactly the multi-host ingest test environment.

Both transports are **hardened** through ``parallel/net.py``
(docs/ROBUSTNESS.md): deadline-bounded waits, heartbeat-based peer
liveness so a SIGKILLed rank surfaces as ``PeerFailureError`` within
~2x the deadline instead of hanging every host, ``LIGHTGBM_TPU_FAULT``
injection points, and KV key GC so a long multihost run's live KV
footprint stays O(ranks) instead of growing per gather.

The transport is chosen deterministically from the backend name so
every process takes the same branch (a mixed choice would deadlock).
Single-process runs short-circuit without touching the backend.
"""

from __future__ import annotations

import itertools
import pickle
import time
from typing import List, Optional

import numpy as np

from ..obs import tracer
from . import net

# per-process call counter: processes make collective calls in the same
# program order, so the counter yields matching keys across ranks (and
# net.kv_gather's lazy GC relies on exactly that ordering)
_kv_uid = itertools.count()
# membership-epoch scope for the uids (net.epoch_uid layout): a static
# world stays at 0 — bare sequence numbers, unchanged wire keys.  An
# elastic transition calls set_epoch so post-resize gathers land in a
# fresh uid subtree and can never read a stale pre-transition payload.
_kv_epoch = 0


def set_epoch(epoch: int) -> None:
    """Scope subsequent KV-gather uids to a membership epoch.  The
    per-epoch sequence restarts only on a real bump — re-announcing the
    current epoch must NOT reuse uids."""
    global _kv_epoch, _kv_uid
    epoch = int(epoch)
    if epoch != _kv_epoch:
        _kv_epoch = epoch
        _kv_uid = itertools.count()


def _kv_allgather(blob: bytes) -> List[bytes]:
    import jax

    return net.kv_gather(
        net.epoch_uid(_kv_epoch, next(_kv_uid)), blob,
        client=net.require_client(),
        rank=jax.process_index(), nproc=jax.process_count(),
    )


def _array_allgather(blob: bytes) -> List[bytes]:
    import jax
    from jax.experimental import multihost_utils

    gmax = int(np.max(net.watchdog_call(
        lambda: multihost_utils.process_allgather(
            np.asarray(len(blob), np.int64)
        ),
        what="allgather[sizes]",
    )))
    buf = np.zeros(gmax + 8, np.uint8)
    buf[:8] = np.frombuffer(len(blob).to_bytes(8, "little"), np.uint8)
    buf[8 : 8 + len(blob)] = np.frombuffer(blob, np.uint8)
    gathered = np.asarray(net.watchdog_call(
        lambda: multihost_utils.process_allgather(buf),
        what="allgather[payload]",
    ))
    out = []
    for r in range(gathered.shape[0]):
        ln = int.from_bytes(gathered[r, :8].tobytes(), "little")
        out.append(gathered[r, 8 : 8 + ln].tobytes())
    return out


def allgather_bytes(blob: bytes, purpose: str = "misc") -> List[bytes]:
    """One blob per process -> every process's blob, in process order.
    Bounded: raises ``net.PeerFailureError`` / ``CollectiveTimeoutError``
    instead of hanging on a dead or wedged peer.  ``purpose`` tags the
    sent bytes in the comms-volume ledger (``net.bytes{purpose=...}``)
    so per-learner payload profiles (hist vs best_split vs vote/elect)
    fall out of the trace stream."""
    import jax

    if jax.process_count() == 1:
        return [blob]
    net.fault_point("collective")
    net.ensure_heartbeat()
    transport = "kv" if jax.default_backend() == "cpu" else "array"
    tracer.counter("net.bytes", float(len(blob)), purpose=purpose,
                   transport=transport)
    with tracer.span("net.allgather", transport=transport, bytes=len(blob),
                     purpose=purpose):
        # time the transport only (after fault_point, so an injected
        # straggler stall counts as the straggler's own compute while
        # its peers book the stall here as wait — the signal the
        # rebalance controller feeds on)
        t0 = time.perf_counter()
        try:
            if transport == "kv":
                # XLA:CPU has no multi-process computations; use the KV
                # store
                return _kv_allgather(blob)
            return _array_allgather(blob)
        finally:
            net.wait_clock_add(time.perf_counter() - t0)


def barrier(tag: str = "barrier") -> None:
    """All processes reach this point, bounded by the net deadline —
    an empty allgather, so it rides the same hardened transports and
    fault-injection points as every other collective."""
    import jax

    if jax.process_count() == 1:
        return
    with tracer.span("net.barrier", tag=tag):
        allgather_bytes(b"")


def allgather_blob_lists(
    blobs: List[bytes], list_len: Optional[int] = None
) -> List[List[bytes]]:
    """Gather each process's list of byte blobs; returns one list per
    process, in process order.  ``list_len`` pads every process's list
    to a common length (callers that index a fixed feature-block shape
    — e.g. the last find-bin block being short); padded slots come back
    as empty blobs."""
    pad = list_len if list_len is not None else len(blobs)
    payload = pickle.dumps(list(blobs) + [b""] * (pad - len(blobs)),
                           protocol=pickle.HIGHEST_PROTOCOL)
    return [pickle.loads(p) for p in allgather_bytes(payload)]
