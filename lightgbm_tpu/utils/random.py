"""Deterministic light-weight PRNG matching the reference's ``Random``
(include/LightGBM/utils/random.h): an LCG with NextShort/NextInt/NextFloat
and the same three-branch ``Sample`` (full / selection / step sampling).
Host-side sampling (bin-construction row sampling, feature_fraction,
bagging) uses this so seeded runs are reproducible and structurally
comparable with the reference.

Device-side randomness (DART drops inside jit, Pallas PRNG) uses
``jax.random`` instead — cross-implementation bit-parity of sampled indices
is not required there, only determinism under a fixed seed.
"""

from __future__ import annotations

import numpy as np


class Random:
    def __init__(self, seed: int = 123456789):
        self.x = int(seed) & 0xFFFFFFFF

    # -- checkpoint support --------------------------------------------
    # The whole generator is the 32-bit LCG word, so state export is one
    # int; model text cannot carry it, which is exactly why checkpoint
    # resume needs it (ckpt/state.py).
    def get_state(self) -> int:
        return int(self.x)

    def set_state(self, state: int) -> "Random":
        self.x = int(state) & 0xFFFFFFFF
        return self

    def next_short(self, lower_bound: int, upper_bound: int) -> int:
        """Random int in [lower_bound, upper_bound), 15-bit source."""
        return self._rand_int16() % (upper_bound - lower_bound) + lower_bound

    def next_int(self, lower_bound: int, upper_bound: int) -> int:
        """Random int in [lower_bound, upper_bound), 31-bit source."""
        return self._rand_int31() % (upper_bound - lower_bound) + lower_bound

    def next_float(self) -> float:
        """Random float in [0, 1)."""
        return self._rand_int16() / 32768.0

    def _rand_int16(self) -> int:
        self.x = (214013 * self.x + 2531011) & 0xFFFFFFFF
        return (self.x >> 16) & 0x7FFF

    def _rand_int31(self) -> int:
        self.x = (214013 * self.x + 2531011) & 0xFFFFFFFF
        return self.x & 0x7FFFFFFF

    def sample(self, n: int, k: int) -> np.ndarray:
        """Sample ``k`` ordered values from range(n) (random.h Sample)."""
        ret: list[int] = []
        if k > n or k < 0:
            pass
        elif k == n:
            ret = list(range(n))
        elif k > n // 2:
            # selection sampling
            for i in range(n):
                prob = (k - len(ret)) / (n - i)
                if self.next_float() < prob:
                    ret.append(i)
        else:
            # step sampling: cheap for sparse picks
            min_step = 1
            avg_step = n // k
            max_step = 2 * avg_step - min_step
            start = -1
            for _ in range(k):
                start += self.next_short(min_step, max_step + 1)
                if start >= n:
                    break
                ret.append(start)
        return np.asarray(ret, dtype=np.int64)
