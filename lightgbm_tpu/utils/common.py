"""Small shared helpers (counterpart of include/LightGBM/utils/common.h).

Most of the reference's Common:: helpers (string split/atof, ParallelSort,
Softmax) are subsumed by numpy/jax; what remains here are the pieces other
modules genuinely share.
"""

from __future__ import annotations

import numpy as np


def array_to_string(arr, sep: str = " ") -> str:
    """Format a 1-D array the way the reference's Common::ArrayToString does
    (repr chosen per dtype; used by the model text format)."""
    out = []
    for v in arr:
        if isinstance(v, (int, np.integer)):
            out.append(str(int(v)))
        else:
            out.append(format_double(float(v)))
    return sep.join(out)


def format_double(v: float) -> str:
    """Shortest round-trip decimal for a double, matching how the model text
    format prints real numbers (C++ operator<< with default precision for
    display fields; full precision via repr for values that must round-trip)."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def softmax(x: np.ndarray, axis: int = -1) -> np.ndarray:
    x = x - np.max(x, axis=axis, keepdims=True)
    e = np.exp(x)
    return e / np.sum(e, axis=axis, keepdims=True)


def check(condition: bool, msg: str = "check failed") -> None:
    if not condition:
        from .log import Log

        Log.fatal(msg)
