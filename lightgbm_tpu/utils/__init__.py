from .log import Log
from .random import Random
