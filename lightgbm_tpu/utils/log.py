"""Leveled logger, the counterpart of the reference's static ``Log`` class
(include/LightGBM/utils/log.h). ``Log.fatal`` raises (the reference throws a
``std::runtime_error`` that the CLI main() catches)."""

from __future__ import annotations

import sys


class LightGBMError(RuntimeError):
    """Raised by Log.fatal — the counterpart of the reference's fatal throw."""


class Log:
    # Levels: fatal=-1, warning=0, info=1, debug=2 (reference log.h LogLevel)
    _level = 1

    @classmethod
    def reset_level(cls, level: int) -> None:
        cls._level = level

    @classmethod
    def get_level(cls) -> int:
        return cls._level

    @classmethod
    def debug(cls, msg: str, *args) -> None:
        if cls._level >= 2:
            cls._write("Debug", msg, args)

    @classmethod
    def info(cls, msg: str, *args) -> None:
        if cls._level >= 1:
            cls._write("Info", msg, args)

    @classmethod
    def warning(cls, msg: str, *args) -> None:
        if cls._level >= 0:
            cls._write("Warning", msg, args)

    @classmethod
    def fatal(cls, msg: str, *args) -> None:
        text = (msg % args) if args else msg
        raise LightGBMError(text)

    @staticmethod
    def _write(level_str: str, msg: str, args) -> None:
        text = (msg % args) if args else msg
        sys.stdout.write(f"[LightGBM-TPU] [{level_str}] {text}\n")
        sys.stdout.flush()
