"""Tracing/profiling — counterpart of the reference's compile-time TIMETAG
phase timers (serial_tree_learner.cpp:10-37, gbdt.cpp:22-63) plus the
per-iteration wall-clock log (application.cpp:233-236).

TPU-first: phases are ``jax.named_scope`` annotations (visible in XLA/
jax.profiler traces) wrapped in host-side accumulating timers.  Enable
with LIGHTGBM_TPU_TIMETAG=1 or ``timetag.enable()``; dumped at exit like
the reference's destructor prints.
"""

from __future__ import annotations

import atexit
import contextlib
import os
import time
from collections import defaultdict
from typing import Dict, Iterator

import jax

from .log import Log


class PhaseTimers:
    """Accumulating named phase timers (the TIMETAG duration maps)."""

    def __init__(self):
        self.enabled = bool(int(os.environ.get("LIGHTGBM_TPU_TIMETAG", "0")))
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self._dump_registered = False

    def enable(self) -> None:
        self.enabled = True
        if not self._dump_registered:
            atexit.register(self.dump)
            self._dump_registered = True

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        """Time a phase; also emits a jax.named_scope so device traces
        (jax.profiler.trace) carry the same phase names."""
        if not self.enabled:
            with jax.named_scope(name):
                yield
            return
        start = time.perf_counter()
        with jax.named_scope(name):
            yield
        self.totals[name] += time.perf_counter() - start
        self.counts[name] += 1

    def dump(self) -> None:
        """TIMETAG destructor-style dump (serial_tree_learner.cpp:12-24)."""
        if not self.totals:
            return
        for name in sorted(self.totals):
            Log.info(
                "%s costs: %f (n=%d)", name, self.totals[name], self.counts[name]
            )

    def reset(self) -> None:
        self.totals.clear()
        self.counts.clear()


timetag = PhaseTimers()
if timetag.enabled:
    atexit.register(timetag.dump)
    timetag._dump_registered = True


@contextlib.contextmanager
def profile_trace(log_dir: str) -> Iterator[None]:
    """Device-level profiler trace (the deep-dive tool the reference never
    had): view with TensorBoard / xprof."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
