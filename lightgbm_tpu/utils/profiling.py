"""Tracing/profiling — counterpart of the reference's compile-time TIMETAG
phase timers (serial_tree_learner.cpp:10-37, gbdt.cpp:22-63) plus the
per-iteration wall-clock log (application.cpp:233-236).

``PhaseTimers`` is now a thin adapter over the structured tracer
(obs/trace.py): every phase still emits a ``jax.named_scope`` (so
xprof/jax.profiler device traces carry the same span names), accumulates
into the TIMETAG-style totals dumped at exit, AND — when
``LIGHTGBM_TPU_TRACE`` is set — lands as a structured span in the JSONL
trace (feeding the per-iteration ``phases`` breakdown).  Enable the
legacy aggregate dump with LIGHTGBM_TPU_TIMETAG=1 or ``timetag.enable()``.
"""

from __future__ import annotations

import atexit
import contextlib
import os
import time
from collections import defaultdict
from typing import Dict, Iterator

import jax

from ..obs.trace import tracer
from .log import Log


class PhaseTimers:
    """Accumulating named phase timers (the TIMETAG duration maps),
    bridged onto the structured tracer."""

    def __init__(self):
        self.enabled = bool(int(os.environ.get("LIGHTGBM_TPU_TIMETAG", "0")))
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self._dump_registered = False

    def enable(self) -> None:
        self.enabled = True
        if not self._dump_registered:
            atexit.register(self.dump)
            self._dump_registered = True

    @contextlib.contextmanager
    def phase(self, name: str, **attrs) -> Iterator[None]:
        """Time a phase; also emits a jax.named_scope so device traces
        (jax.profiler.trace) carry the same phase names, and a structured
        tracer span when the JSONL trace is enabled."""
        if not self.enabled and not tracer.enabled:
            with jax.named_scope(name):
                yield
            return
        start = time.perf_counter()
        with tracer.span(name, **attrs):
            with jax.named_scope(name):
                yield
        if self.enabled:
            self.totals[name] += time.perf_counter() - start
            self.counts[name] += 1

    def dump(self) -> None:
        """TIMETAG destructor-style dump (serial_tree_learner.cpp:12-24)."""
        if not self.totals:
            return
        for name in sorted(self.totals):
            Log.info(
                "%s costs: %f (n=%d)", name, self.totals[name], self.counts[name]
            )

    def reset(self) -> None:
        self.totals.clear()
        self.counts.clear()


timetag = PhaseTimers()
if timetag.enabled:
    atexit.register(timetag.dump)
    timetag._dump_registered = True


@contextlib.contextmanager
def profile_trace(log_dir: str) -> Iterator[None]:
    """Device-level profiler trace (the deep-dive tool the reference never
    had): view with TensorBoard / xprof."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class XprofCapture:
    """Bounded-iteration device-profiler capture — the prewired harness
    behind ``LIGHTGBM_TPU_XPROF=<dir>``.

    Skips the first ``LIGHTGBM_TPU_XPROF_SKIP`` iterations (default 1:
    compiles and warmup would drown the steady-state timeline), then
    runs :func:`profile_trace` across the next
    ``LIGHTGBM_TPU_XPROF_ITERS`` iterations (default 4) and stops — one
    bounded xplane capture per run.  The ``jax.named_scope`` phase
    names PhaseTimers already emits land in the device trace, so the
    capture needs no further instrumentation at the call sites: drive
    ``on_iter_start()`` / ``on_iter_end()`` around each training
    iteration and call :meth:`close` on the way out (stops a capture
    the run abandoned mid-window)."""

    def __init__(self, log_dir: str, skip: int = None, iters: int = None):
        self.log_dir = log_dir
        self.skip = int(os.environ.get("LIGHTGBM_TPU_XPROF_SKIP", "1")) \
            if skip is None else int(skip)
        self.iters = max(1, int(
            os.environ.get("LIGHTGBM_TPU_XPROF_ITERS", "4"))
            if iters is None else int(iters))
        self._seen = 0
        self._active = False
        self._done = False
        self._t0 = 0.0

    def on_iter_start(self) -> None:
        if self._done or self._active or self._seen < self.skip:
            return
        jax.profiler.start_trace(self.log_dir)
        self._active = True
        self._t0 = time.perf_counter()
        Log.info("xprof capture started -> %s (iters %d..%d)",
                 self.log_dir, self._seen, self._seen + self.iters - 1)

    def on_iter_end(self) -> None:
        self._seen += 1
        if self._active and self._seen >= self.skip + self.iters:
            self._stop()

    def close(self) -> None:
        """Stop an in-flight capture (early exit / exception path)."""
        if self._active:
            self._stop()

    def _stop(self) -> None:
        wall = time.perf_counter() - self._t0
        try:
            jax.profiler.stop_trace()
        finally:
            self._active = False
            self._done = True
        tracer.event("xprof.capture", dir=self.log_dir,
                     iters=self.iters, skip=self.skip,
                     wall_s=round(wall, 6))
        Log.info("xprof capture done: %d iteration(s) in %.3f s -> %s",
                 self.iters, wall, self.log_dir)


def maybe_xprof_capture() -> "XprofCapture | None":
    """The env-gated constructor training entry points call:
    ``LIGHTGBM_TPU_XPROF=<dir>`` arms a capture, unset returns None."""
    log_dir = os.environ.get("LIGHTGBM_TPU_XPROF", "").strip()
    return XprofCapture(log_dir) if log_dir else None
