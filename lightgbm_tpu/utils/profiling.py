"""Tracing/profiling — counterpart of the reference's compile-time TIMETAG
phase timers (serial_tree_learner.cpp:10-37, gbdt.cpp:22-63) plus the
per-iteration wall-clock log (application.cpp:233-236).

``PhaseTimers`` is now a thin adapter over the structured tracer
(obs/trace.py): every phase still emits a ``jax.named_scope`` (so
xprof/jax.profiler device traces carry the same span names), accumulates
into the TIMETAG-style totals dumped at exit, AND — when
``LIGHTGBM_TPU_TRACE`` is set — lands as a structured span in the JSONL
trace (feeding the per-iteration ``phases`` breakdown).  Enable the
legacy aggregate dump with LIGHTGBM_TPU_TIMETAG=1 or ``timetag.enable()``.
"""

from __future__ import annotations

import atexit
import contextlib
import os
import time
from collections import defaultdict
from typing import Dict, Iterator

import jax

from ..obs.trace import tracer
from .log import Log


class PhaseTimers:
    """Accumulating named phase timers (the TIMETAG duration maps),
    bridged onto the structured tracer."""

    def __init__(self):
        self.enabled = bool(int(os.environ.get("LIGHTGBM_TPU_TIMETAG", "0")))
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self._dump_registered = False

    def enable(self) -> None:
        self.enabled = True
        if not self._dump_registered:
            atexit.register(self.dump)
            self._dump_registered = True

    @contextlib.contextmanager
    def phase(self, name: str, **attrs) -> Iterator[None]:
        """Time a phase; also emits a jax.named_scope so device traces
        (jax.profiler.trace) carry the same phase names, and a structured
        tracer span when the JSONL trace is enabled."""
        if not self.enabled and not tracer.enabled:
            with jax.named_scope(name):
                yield
            return
        start = time.perf_counter()
        with tracer.span(name, **attrs):
            with jax.named_scope(name):
                yield
        if self.enabled:
            self.totals[name] += time.perf_counter() - start
            self.counts[name] += 1

    def dump(self) -> None:
        """TIMETAG destructor-style dump (serial_tree_learner.cpp:12-24)."""
        if not self.totals:
            return
        for name in sorted(self.totals):
            Log.info(
                "%s costs: %f (n=%d)", name, self.totals[name], self.counts[name]
            )

    def reset(self) -> None:
        self.totals.clear()
        self.counts.clear()


timetag = PhaseTimers()
if timetag.enabled:
    atexit.register(timetag.dump)
    timetag._dump_registered = True


@contextlib.contextmanager
def profile_trace(log_dir: str) -> Iterator[None]:
    """Device-level profiler trace (the deep-dive tool the reference never
    had): view with TensorBoard / xprof."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
