"""Continuous-training supervisor — ``python -m lightgbm_tpu factory``.

The loop (docs/FACTORY.md has the diagram):

  watch data dir ──▶ warm-start retrain ──▶ publish (inactive)
        ▲                (checkpointed)         │ dedupe_key=run_id
        │                                        ▼
   record verdict ◀── promote / rollback ◀── eval gate + canary
   (state+history)     activate/quarantine     (SLO window)

Crash safety is stage idempotence, not transactions: the run record is
made durable BEFORE any work starts, and a kill at any point restarts
into the same run where every stage converges instead of repeating —
the retrain resumes from its checkpoint (ckpt/), the staging file and
model text are write-once (tmp+rename), the publish dedupes on the run
id (registry), and promote/quarantine are idempotent manifest writes.
So a SIGKILL anywhere never double-publishes and never loses a
recorded verdict.

Canary: the candidate is published INACTIVE, a one-off serve replica is
spawned pinned to it (``pin_version``), and the FleetProxy diverts
``canary_fraction`` of live /predict traffic to that replica
(``POST /fleet/canary``).  The verdict reads the replica's per-version
metrics (requests/errors/latency split by ``X-Model-Version``
attribution) over a bounded ``observe_s`` window; promotion is one
``registry.activate`` (the whole fleet hot-swaps), rollback is a
``registry.quarantine`` with the reason recorded in the verdict
history.  A canary failure never costs a client a response — the proxy
falls back into the main pool (serve/fleet.py).
"""

from __future__ import annotations

import http.client
import json
import os
import shutil
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import engine
from ..basic import Booster, Dataset
from ..ckpt.store import _atomic_write
from ..config import Config
from ..obs import tracer
from ..serve.artifact import PredictorArtifact
from ..serve.fleet import _free_ports, _wait_ready
from ..serve.registry import ModelRegistry
from ..utils.log import Log
from . import watch
from .state import FactoryState

DEFAULTS = {
    "poll_ms": 1000.0,       # data-dir scan interval
    "debounce_ms": 500.0,    # a changed file must be this quiet first
    "period_s": 0.0,         # 0 = retrain only on data change
    "num_boost_round": 20,   # NEW rounds per retrain (on top of init)
    "checkpoint_freq": 1,    # retrain checkpoint cadence (iterations)
    "canary_fraction": 0.2,  # slice of fleet /predict traffic diverted
    "observe_s": 5.0,        # bounded canary observation window
    "min_requests": 20,      # canary must see this many requests...
    "max_error_rate": 0.02,  # ...with at most this error rate...
    "p99_slo_ms": 5000.0,    # ...and at most this p99 latency
    "metric_rel_tol": 0.02,  # eval-gate relative regression tolerance
    "metric_abs_tol": 0.005,  # plus this absolute slack (near-zero rates)
    "eval_max_rows": 100000,  # eval-gate row cap (freshest rows win)
    "max_cycles": 0,         # stop after N completed runs (0 = forever)
    "canary_warmup_rows": 256,     # canary replica warmup ladder cap
    "ready_timeout_ms": 120000.0,  # canary replica readiness deadline
    "max_registry_stale_s": 30.0,  # refuse to promote against a fleet
                                   # replica whose registry swaps have
                                   # been failing longer (0 disables)
}

EXIT_OK = 0
EXIT_BAD_ARGS = 2


def _http_json(host: str, port: int, method: str, path: str,
               body=None, timeout_s: float = 5.0):
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout_s)
    try:
        payload = json.dumps(body).encode() if body is not None else None
        conn.request(method, path, body=payload,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
        if resp.status != 200:
            raise OSError(f"{method} {path} on {host}:{port} "
                          f"-> HTTP {resp.status}")
        return json.loads(data.decode("utf-8") or "null")
    finally:
        conn.close()


class FactorySupervisor:
    """One factory instance owns one (data_dir, workdir, registry)
    triple.  ``run_cycle`` drives at most one complete run; a run that
    was interrupted by a kill is re-entered and finished first."""

    def __init__(self, data_dir: str, workdir: str, registry_dir: str,
                 params: Optional[Dict] = None, proxy: Optional[str] = None,
                 host: str = "127.0.0.1", **knobs):
        unknown = set(knobs) - set(DEFAULTS)
        if unknown:
            Log.fatal("factory: unknown knob(s) %s (have: %s)",
                      sorted(unknown), sorted(DEFAULTS))
        self.opts = dict(DEFAULTS)
        self.opts.update(knobs)
        self.data_dir = data_dir
        self.workdir = workdir
        self.registry_dir = registry_dir
        os.makedirs(workdir, exist_ok=True)
        os.makedirs(os.path.join(workdir, "models"), exist_ok=True)
        self.registry = ModelRegistry(registry_dir)
        self.params = dict(params or {})
        self.proxy = proxy  # "host:port" front end, or None (no canary)
        self.host = host
        self.state = FactoryState.load(workdir)
        self._stop = threading.Event()
        self._eval_cache: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}

    def stop(self) -> None:
        self._stop.set()

    # -- trigger -------------------------------------------------------
    def _period_due(self) -> bool:
        p = float(self.opts["period_s"])
        return p > 0 and (time.time() - self.state.last_run_ts) >= p

    def run_cycle(self, force: bool = False) -> Optional[Dict]:
        """Drive one run to its verdict.  Returns the verdict record,
        or None when there is nothing to do (no data, no change, or a
        change still inside the debounce window)."""
        run = self.state.run
        if run is None:
            cur = watch.scan(self.data_dir)
            if not cur:
                return None
            delta = watch.changed(self.state.ingested, cur)
            if not delta and not self._period_due() and not force:
                return None
            if not watch.stable(cur, float(self.opts["debounce_ms"]) / 1e3):
                return None  # writer still appending; next poll retries
            self.state.retrain_seq += 1
            fp = watch.combined_fingerprint(cur)
            run = {
                "run_id": f"r{self.state.retrain_seq:06d}-{fp}",
                "fingerprint": fp,
                "files": cur,
                "changed": delta,
                "candidate_version": None,
                "warm_start": False,
                "t_start": round(time.time(), 3),
            }
            # durable BEFORE any work: a kill from here on restarts
            # into this same run instead of minting a new one
            self.state.run = run
            self.state.save()
            tracer.counter("factory.runs")
            Log.info("factory: run %s begins (%d file(s), %d changed)",
                     run["run_id"], len(run["files"]), len(delta))
        return self._drive(run)

    # -- the run pipeline ----------------------------------------------
    def _drive(self, run: Dict) -> Dict:
        run_dir = os.path.join(self.workdir, run["run_id"])
        os.makedirs(run_dir, exist_ok=True)
        with tracer.span("factory.retrain", run_id=run["run_id"]):
            model_path = self._retrain(run, run_dir)
        with tracer.span("factory.publish", run_id=run["run_id"]):
            version = self._publish(run, model_path)
        ok, detail = self._eval_gate(run, run_dir, model_path)
        if ok and self.proxy \
                and float(self.opts["max_registry_stale_s"]) > 0:
            ok, stale_detail = self._fleet_fresh()
            detail.update(stale_detail)
        if ok and self.proxy and float(self.opts["canary_fraction"]) > 0 \
                and float(self.opts["observe_s"]) > 0:
            with tracer.span("factory.canary", version=version):
                ok, canary_detail = self._canary(version)
            detail.update(canary_detail)
        return self._finish(run, run_dir, model_path, version, ok, detail)

    # -- fleet freshness gate ------------------------------------------
    def _fleet_fresh(self) -> Tuple[bool, Dict]:
        """A fleet replica whose registry swaps keep failing serves
        last-good no matter what we activate — promoting against it
        only *pretends* to ship the candidate.  Walk the proxy's
        healthy backends and refuse to promote while any reports
        ``registry.stale_seconds`` beyond the knob."""
        limit = float(self.opts["max_registry_stale_s"])
        proxy_host, _, proxy_port_s = self.proxy.rpartition(":")
        proxy_host, proxy_port = (proxy_host or "127.0.0.1",
                                  int(proxy_port_s))
        detail: Dict = {"fleet": {"max_registry_stale_s": limit,
                                  "stale_backends": {}}}
        det = detail["fleet"]
        try:
            st = _http_json(proxy_host, proxy_port, "GET", "/fleet/stats")
        except (OSError, ValueError) as e:
            det["reason"] = f"cannot read fleet stats: {e}"
            return False, detail
        worst = 0.0
        for b in (st or {}).get("backends", []):
            if not b.get("healthy"):
                continue  # reachability is the prober's problem
            host, _, port_s = str(b.get("addr", "")).rpartition(":")
            try:
                bs = _http_json(host or "127.0.0.1", int(port_s),
                                "GET", "/stats")
            except (OSError, ValueError):
                continue  # transiently unreachable: the prober will eject
            stale = float((bs or {}).get("registry", {})
                          .get("stale_seconds") or 0.0)
            if stale > 0:
                det["stale_backends"][b["addr"]] = round(stale, 1)
            worst = max(worst, stale)
        det["max_stale_s"] = round(worst, 1)
        if worst > limit:
            det["reason"] = (
                f"fleet registry staleness {worst:.1f}s > "
                f"{limit:.1f}s on {sorted(det['stale_backends'])} — an "
                f"activation would not reach those replicas; fix the "
                f"registry before promoting")
            tracer.event("factory.fleet_stale", max_stale_s=worst,
                         backends=sorted(det["stale_backends"]))
            return False, detail
        return True, detail

    def _stage_data(self, run: Dict, run_dir: str) -> str:
        """Concatenate the watched chunks (lexical order) into one
        write-once staging file — the frozen input of this run, immune
        to appends landing mid-retrain."""
        staging = os.path.join(run_dir, "train.data")
        if os.path.exists(staging):
            return staging
        tmp = f"{staging}.tmp.{os.getpid()}"
        with open(tmp, "wb") as out:
            for name in sorted(run["files"]):
                last = b"\n"
                with open(os.path.join(self.data_dir, name), "rb") as f:
                    while True:
                        chunk = f.read(1 << 20)
                        if not chunk:
                            break
                        out.write(chunk)
                        last = chunk[-1:]
                if last != b"\n":
                    out.write(b"\n")
            out.flush()
            os.fsync(out.fileno())
        os.replace(tmp, staging)
        return staging

    def _retrain(self, run: Dict, run_dir: str) -> str:
        """Warm-started incremental retrain, checkpointed so a SIGKILL
        resumes mid-run instead of restarting.  The finished model text
        is write-once: a completed-then-killed retrain is skipped
        entirely on replay."""
        model_path = os.path.join(run_dir, "model.txt")
        if os.path.exists(model_path):
            return model_path
        staging = self._stage_data(run, run_dir)
        params = dict(self.params)
        params.setdefault("out_of_core", "auto")
        init = None
        cur = self.state.current
        if cur and os.path.exists(cur.get("model_path", "")):
            init = cur["model_path"]
        if init is not None:
            # continued training seeds scores from the raw matrix, which
            # the out-of-core streaming path never materializes — when
            # the accumulation outgrows memory, degrade to a cold (but
            # still out-of-core-capable) retrain rather than OOM
            from ..data.ingest import should_stream

            cfg = Config.from_params(
                {k: str(v) for k, v in params.items()})
            if should_stream(staging, cfg):
                Log.warning(
                    "factory: accumulated data now routes out-of-core; "
                    "warm start needs the raw matrix, so run %s retrains "
                    "cold", run["run_id"])
                init = None
        run["warm_start"] = init is not None
        train_set = Dataset(staging, params=dict(params))
        booster = engine.train(
            params, train_set,
            num_boost_round=int(self.opts["num_boost_round"]),
            init_model=init,
            checkpoint_dir=os.path.join(run_dir, "ckpt"),
            checkpoint_freq=int(self.opts["checkpoint_freq"]),
            verbose_eval=False,
        )
        _atomic_write(model_path, booster.model_to_string().encode())
        return model_path

    def _publish(self, run: Dict, model_path: str) -> int:
        """Publish the candidate INACTIVE; ``dedupe_key=run_id`` makes a
        kill between publish and the state write idempotent — the replay
        gets the already-claimed version back."""
        artifact = PredictorArtifact.from_booster(
            Booster(model_file=model_path))
        version = self.registry.publish(artifact, activate=False,
                                        dedupe_key=run["run_id"])
        run["candidate_version"] = int(version)
        self.state.save()
        return int(version)

    # -- eval gate -----------------------------------------------------
    def _load_eval(self, data_path: str) -> Tuple[np.ndarray, np.ndarray]:
        cached = self._eval_cache.get(data_path)
        if cached is not None:
            return cached
        from ..io.parser import load_text_file

        cfg = Config.from_params(
            {k: str(v) for k, v in self.params.items()})
        X, y = load_text_file(data_path, cfg)[:2]
        cap = int(self.opts["eval_max_rows"])
        if cap > 0 and len(X) > cap:
            X, y = X[-cap:], y[-cap:]  # freshest rows carry the signal
        out = (np.asarray(X, np.float64), np.asarray(y, np.float64))
        self._eval_cache = {data_path: out}  # one staging file at a time
        return out

    def _eval_metric(self, model_path: str, data_path: str) -> Dict:
        X, y = self._load_eval(data_path)
        pred = np.asarray(Booster(model_file=model_path).predict(X))
        if str(self.params.get("objective", "")).startswith("binary"):
            err = float(np.mean((pred > 0.5) != (y > 0.5)))
            return {"name": "binary_error", "value": err}
        first = pred.reshape(len(y), -1)[:, 0].astype(np.float64)
        return {"name": "l2", "value": float(np.mean((first - y) ** 2))}

    def _eval_gate(self, run: Dict, run_dir: str,
                   model_path: str) -> Tuple[bool, Dict]:
        """Candidate-vs-promoted metric on this run's frozen data: a
        regression beyond tolerance rolls back WITHOUT spending fleet
        traffic on a canary."""
        staging = os.path.join(run_dir, "train.data")
        cand = self._eval_metric(model_path, staging)
        detail: Dict = {"eval": {"metric": cand["name"],
                                 "candidate": round(cand["value"], 6),
                                 "baseline": None}}
        cur = self.state.current
        if not cur or not os.path.exists(cur.get("model_path", "")):
            return True, detail  # nothing to regress against
        base = self._eval_metric(cur["model_path"], staging)
        detail["eval"]["baseline"] = round(base["value"], 6)
        limit = base["value"] * (1.0 + float(self.opts["metric_rel_tol"])) \
            + float(self.opts["metric_abs_tol"])
        if cand["value"] > limit:
            detail["eval"]["reason"] = (
                f"{cand['name']} regressed: {cand['value']:.6g} vs "
                f"baseline {base['value']:.6g} (limit {limit:.6g})")
            return False, detail
        return True, detail

    # -- canary --------------------------------------------------------
    def _canary(self, version: int) -> Tuple[bool, Dict]:
        """Pin a one-off replica to the candidate, divert a slice of
        proxy traffic to it, and judge the per-version metrics over a
        bounded window.  Everything installed here is torn back down on
        every exit path — a crashed canary leaves no routing residue."""
        proxy_host, _, proxy_port_s = self.proxy.rpartition(":")
        proxy_host, proxy_port = proxy_host or "127.0.0.1", int(proxy_port_s)
        fraction = min(1.0, float(self.opts["canary_fraction"]))
        detail: Dict = {"canary": {"fraction": fraction,
                                   "window_s": float(self.opts["observe_s"])}}
        det = detail["canary"]
        port = _free_ports(1, self.host)[0]
        # retention-protect the candidate for the whole window
        self.registry.set_canary(int(version))
        proc = subprocess.Popen([
            sys.executable, "-m", "lightgbm_tpu", "serve",
            f"host={self.host}", f"port={port}",
            f"registry={self.registry_dir}", f"pin_version={int(version)}",
            f"warmup_max_rows={int(self.opts['canary_warmup_rows'])}",
            "max_delay_ms=1", "registry_poll_ms=1000",
        ])
        installed = False
        try:
            if not _wait_ready(self.host, port,
                               float(self.opts["ready_timeout_ms"]) / 1e3):
                det["reason"] = "canary replica never became ready"
                return False, detail
            _http_json(proxy_host, proxy_port, "POST", "/fleet/canary",
                       {"addr": f"{self.host}:{port}", "fraction": fraction})
            installed = True
            deadline = time.monotonic() + float(self.opts["observe_s"])
            while time.monotonic() < deadline and not self._stop.is_set():
                time.sleep(min(0.2, max(deadline - time.monotonic(), 0.01)))
            stats = _http_json(self.host, port, "GET", "/stats")
            obs = (stats or {}).get("per_version", {}).get(str(version), {})
            requests = int(obs.get("requests", 0))
            errors = int(obs.get("errors", 0))
            total = requests + errors
            err_rate = errors / max(total, 1)
            p99 = float(obs.get("latency_p99_ms", 0.0))
            det.update({"requests": requests, "errors": errors,
                        "error_rate": round(err_rate, 5), "p99_ms": p99})
            if total < int(self.opts["min_requests"]):
                det["reason"] = (
                    f"only {total} canary request(s) in the {det['window_s']}"
                    f"s window (min_requests={int(self.opts['min_requests'])})"
                    " — cannot verify the SLO, refusing to promote blind")
                return False, detail
            if err_rate > float(self.opts["max_error_rate"]):
                det["reason"] = (
                    f"canary error rate {err_rate:.4f} > "
                    f"{float(self.opts['max_error_rate'])} "
                    f"({errors}/{total})")
                return False, detail
            if p99 > float(self.opts["p99_slo_ms"]):
                det["reason"] = (f"canary p99 {p99:.1f} ms > SLO "
                                 f"{float(self.opts['p99_slo_ms'])} ms")
                return False, detail
            return True, detail
        except OSError as e:
            det["reason"] = f"canary plumbing failed: {e}"
            return False, detail
        finally:
            if installed:
                try:
                    _http_json(proxy_host, proxy_port, "POST",
                               "/fleet/canary",
                               {"addr": None, "fraction": 0.0})
                except OSError:
                    Log.warning("factory: could not clear the proxy "
                                "canary route on %s", self.proxy)
            try:
                if self.registry.canary_version() == int(version):
                    self.registry.clear_canary()
            except Exception:
                pass
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    # -- verdict -------------------------------------------------------
    def _finish(self, run: Dict, run_dir: str, model_path: str,
                version: int, promoted: bool, detail: Dict) -> Dict:
        verdict = {
            "run_id": run["run_id"],
            "version": int(version),
            "verdict": "promoted" if promoted else "rolled_back",
            "warm_start": bool(run.get("warm_start")),
            "detail": detail,
            "t_start": run["t_start"],
            "t_end": round(time.time(), 3),
        }
        if promoted:
            kept = os.path.join(self.workdir, "models",
                                f"v{int(version):08d}.txt")
            if not os.path.exists(kept):
                with open(model_path, "rb") as f:
                    _atomic_write(kept, f.read())
            self.registry.activate(int(version))  # whole-fleet swap
            self.state.current = {
                "version": int(version), "model_path": kept,
                "metric": detail.get("eval", {}).get("candidate"),
            }
            tracer.counter("factory.promotions")
        else:
            reason = "unspecified regression"
            for block in ("canary", "fleet", "eval"):
                d = detail.get(block)
                if isinstance(d, dict) and d.get("reason"):
                    reason = d["reason"]
                    break
            verdict["reason"] = reason
            self.registry.quarantine(int(version), reason)
            if self.registry.active_version() == int(version):
                # a previous life of this run promoted before a kill and
                # this replay's verdict flipped: activate(older) is the
                # whole-fleet rollback
                older = [m["version"] for m in self.registry.list_models()
                         if int(m["version"]) != int(version)
                         and not m.get("quarantined")]
                if older:
                    self.registry.activate(max(older))
            tracer.counter("factory.rollbacks")
        tracer.event("factory.verdict", run_id=run["run_id"],
                     version=int(version), verdict=verdict["verdict"],
                     reason=verdict.get("reason"))
        # ONE durable write retires the run: ingest baseline, verdict
        # history, and run=None move together, so a kill here either
        # replays the whole (idempotent) verdict or sees it recorded
        self.state.ingested = dict(run["files"])
        self.state.last_run_ts = time.time()
        self.state.record_verdict(verdict)
        self.state.run = None
        self.state.save()
        shutil.rmtree(run_dir, ignore_errors=True)
        Log.info("factory: run %s -> %s (v%d)%s", run["run_id"],
                 verdict["verdict"], int(version),
                 f" — {verdict.get('reason')}" if not promoted else "")
        return verdict

    # -- loop ----------------------------------------------------------
    def run_forever(self) -> int:
        poll_s = max(float(self.opts["poll_ms"]), 10.0) / 1e3
        max_cycles = int(self.opts["max_cycles"])
        cycles = 0
        while not self._stop.is_set():
            verdict = self.run_cycle()
            if verdict is not None:
                cycles += 1
                if max_cycles and cycles >= max_cycles:
                    break
            self._stop.wait(poll_s)
        return cycles


def main(argv: List[str]) -> int:
    """``python -m lightgbm_tpu factory data=DIR workdir=DIR
    registry=DIR [proxy=host:port] [knob=value ...] [training params]``.

    Knobs are the DEFAULTS keys; every other key=value is passed to
    training (objective=binary num_leaves=31 ...).  Exit codes:
    0 = clean stop (SIGTERM or max_cycles), 2 = bad arguments; a crash
    exits non-zero and a restart resumes the interrupted run."""
    from ..cli import parse_argv

    if argv and argv[0] == "spot":
        # preemptible-capacity economics loop (factory/spot.py)
        from .spot import main as spot_main

        return spot_main(argv[1:])
    tracer.refresh_from_env()
    params = parse_argv(argv)
    data_dir = params.pop("data", None)
    workdir = params.pop("workdir", None)
    registry_dir = params.pop("registry", None)
    proxy = params.pop("proxy", None)
    host = params.pop("host", "127.0.0.1")
    if not (data_dir and workdir and registry_dir):
        Log.warning("factory: need data=DIR workdir=DIR registry=DIR "
                    "[proxy=host:port] [knob=value ...] [training params]")
        return EXIT_BAD_ARGS
    knobs = {}
    for k in list(params):
        if k in DEFAULTS:
            knobs[k] = type(DEFAULTS[k])(float(params.pop(k)))
    supervisor = FactorySupervisor(data_dir, workdir, registry_dir,
                                   params=params, proxy=proxy, host=host,
                                   **knobs)

    def _on_sigterm(signum, frame):
        Log.warning("factory: SIGTERM — stopping at the next boundary")
        supervisor.stop()

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:  # pragma: no cover - embedded in a non-main thread
        pass
    cycles = supervisor.run_forever()
    Log.info("factory: stopped after %d completed run(s)", cycles)
    return EXIT_OK
