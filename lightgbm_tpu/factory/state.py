"""Crash-safe factory supervisor state.

One JSON file in the factory workdir, written through the checkpoint
store's atomic dance (tmp + fsync + rename + dir fsync) with a CRC32
over the canonical payload bytes, so a reader never sees a torn or
bit-rotten state and a kill at ANY instruction boundary leaves either
the previous complete state or the new complete state.

What must survive a kill (docs/FACTORY.md):

- ``ingested``: the fingerprint manifest of data files already folded
  into the promoted model — the watcher's "what changed?" baseline.
- ``run``: the in-flight run record (run id, data fingerprint, stage,
  candidate version).  A restart re-enters the SAME run; every stage is
  idempotent (the retrain resumes from its checkpoint, the publish
  dedupes on the run id, promote/quarantine are idempotent registry
  writes), so re-driving the run after a kill converges instead of
  duplicating work.
- ``history``: bounded list of recorded verdicts — the audit trail a
  rollback investigation starts from.
- ``current``: the promoted model (version + model text path + eval
  metric) that seeds the next warm-started retrain.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Dict, List, Optional

from ..ckpt.store import _atomic_write
from ..utils.log import Log

STATE_FILE = "factory_state.json"
HISTORY_KEEP = 50


def _payload_crc(payload: Dict) -> int:
    blob = json.dumps(payload, sort_keys=True).encode()
    return zlib.crc32(blob) & 0xFFFFFFFF


class FactoryState:
    """In-memory view of the supervisor state + atomic save/load."""

    def __init__(self, workdir: str):
        self.workdir = workdir
        self.path = os.path.join(workdir, STATE_FILE)
        self.ingested: Dict[str, Dict] = {}
        self.run: Optional[Dict] = None
        self.history: List[Dict] = []
        self.current: Optional[Dict] = None
        self.retrain_seq = 0
        self.last_run_ts = 0.0

    # -- (de)serialization ---------------------------------------------
    def _payload(self) -> Dict:
        return {
            "ingested": self.ingested,
            "run": self.run,
            "history": self.history,
            "current": self.current,
            "retrain_seq": int(self.retrain_seq),
            "last_run_ts": float(self.last_run_ts),
        }

    def save(self) -> None:
        payload = self._payload()
        doc = {"crc32": _payload_crc(payload), "payload": payload}
        _atomic_write(self.path, json.dumps(doc, indent=1).encode())

    @classmethod
    def load(cls, workdir: str) -> "FactoryState":
        """Load the saved state, or a fresh one when absent.  A CRC
        mismatch (disk corruption — atomic writes rule out torn files)
        is refused loudly rather than silently starting over: the
        operator decides whether to delete the file, and the registry's
        publish dedupe means even a fresh start cannot double-publish."""
        st = cls(workdir)
        try:
            with open(st.path) as f:
                doc = json.load(f)
        except FileNotFoundError:
            return st
        except (OSError, ValueError) as e:
            Log.fatal("factory: unreadable state file %s (%s) — delete it "
                      "to start fresh (publishes are deduped, so no "
                      "double-publish can result)", st.path, e)
        payload = doc.get("payload")
        if not isinstance(payload, dict) or (
                _payload_crc(payload) != int(doc.get("crc32", -1))):
            Log.fatal("factory: state file %s fails its CRC — the file is "
                      "corrupt; delete it to start fresh (publishes are "
                      "deduped, so no double-publish can result)", st.path)
        st.ingested = dict(payload.get("ingested") or {})
        st.run = payload.get("run") or None
        st.history = list(payload.get("history") or [])
        st.current = payload.get("current") or None
        st.retrain_seq = int(payload.get("retrain_seq") or 0)
        st.last_run_ts = float(payload.get("last_run_ts") or 0.0)
        return st

    # -- verdict history -----------------------------------------------
    def record_verdict(self, verdict: Dict,
                       keep: int = HISTORY_KEEP) -> None:
        self.history.append(verdict)
        if len(self.history) > keep:
            self.history = self.history[-keep:]
