"""Preemptible-capacity economics loop (docs/FACTORY.md, ``spot``).

The elastic membership runtime (parallel/membership.py) makes worker
death a RESIZE instead of a job restart — which turns preemptible
(spot) capacity from a reliability hazard into a price discount.  This
module closes that loop and measures it:

``SpotSchedule``
    A deterministic price + preemption trace: either scripted
    (``from_script``, exact timings for tests and the bench) or sampled
    (``sample``, seeded Poisson arrivals) — never wall-clock random at
    run time, so a trace can be replayed.

``CostLedger``
    An atomic (tmp+rename, single JSON document) ledger of fleet spend:
    per-member member-seconds priced by the trace, every preemption /
    spawn event, and fleet-wide iteration completions harvested from
    the membership KV store.  ``zero_lost_iterations`` proves the
    economic premise — survivors resized in RAM, no iteration was lost,
    and (via per-attempt epoch-keyed records) none was redone.

``SpotFleet``
    Drives REAL worker subprocesses (tests/membership_worker.py by
    default) over one shared fleet directory: a ``preempt`` event
    SIGKILLs a live member mid-iteration, a ``spawn`` event launches a
    mid-run joiner that auto-resumes from the coordinator's handoff,
    and the fleet's survivors keep training throughout.

``python -m lightgbm_tpu factory spot fleet=DIR ...`` runs one fleet
against a schedule and prints the ledger; ``baseline=1`` runs the
static on-demand reference instead so the two ledgers can be compared
(``factory.cost_per_model`` vs ``factory.cost_baseline``).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..obs import tracer
from ..utils.log import Log

#: on-demand price of one member for one second — the unit every spot
#: price in a trace is a fraction of
ON_DEMAND_PRICE = 1.0


# ----------------------------------------------------------------------
# schedule
# ----------------------------------------------------------------------
@dataclass(frozen=True)
class SpotEvent:
    """One point on the capacity/price trace.

    kind ``price``   — the spot price becomes ``value`` at ``t_s``
    kind ``preempt`` — SIGKILL a live member at ``t_s`` (``target`` is a
                       bootstrap member id, or None for the youngest)
    kind ``spawn``   — launch a mid-run joiner at ``t_s``
    """

    t_s: float
    kind: str
    value: float = 0.0
    target: Optional[int] = None


class SpotSchedule:
    """Deterministic price + preemption trace (sorted :class:`SpotEvent`
    list over a base price).  Replayable by construction: randomness is
    only ever drawn in :meth:`sample` from an explicit seed."""

    KINDS = ("price", "preempt", "spawn")

    def __init__(self, events: List[SpotEvent], base_price: float = 0.3):
        for ev in events:
            if ev.kind not in self.KINDS:
                raise ValueError(f"unknown spot event kind {ev.kind!r}")
        self.events = sorted(events, key=lambda e: (e.t_s, e.kind))
        self.base_price = float(base_price)

    @classmethod
    def from_script(cls, script: str, base_price: float = 0.3):
        """``"preempt@2.5;spawn@4;price@6=0.5;preempt@8=1"`` — kind at
        time, ``=N`` is a price for ``price`` and a target member id for
        ``preempt``."""
        events = []
        for tok in script.split(";"):
            tok = tok.strip()
            if not tok:
                continue
            kind, _, rest = tok.partition("@")
            when, _, arg = rest.partition("=")
            kind = kind.strip()
            if kind not in cls.KINDS or not when:
                raise ValueError(f"bad spot script token {tok!r}")
            if kind == "price" and not arg:
                raise ValueError(
                    f"price event needs a value (price@T=P): {tok!r}")
            value, target = 0.0, None
            if arg:
                if kind == "price":
                    value = float(arg)
                elif kind == "preempt":
                    target = int(arg)
                else:
                    raise ValueError(f"bad spot script token {tok!r}")
            events.append(SpotEvent(float(when), kind, value, target))
        return cls(events, base_price)

    @classmethod
    def sample(cls, seed: int, horizon_s: float, preempt_hz: float = 0.1,
               spawn_hz: float = 0.1, base_price: float = 0.3,
               volatility: float = 0.25, price_step_s: float = 5.0):
        """Seeded Poisson preempt/spawn arrivals over a clipped
        random-walk price — the same seed always yields the same trace."""
        import numpy as np

        rng = np.random.default_rng(seed)
        events: List[SpotEvent] = []
        for kind, hz in (("preempt", preempt_hz), ("spawn", spawn_hz)):
            t = 0.0
            while True:
                t += float(rng.exponential(1.0 / hz)) if hz > 0 else horizon_s
                if t >= horizon_s:
                    break
                events.append(SpotEvent(round(t, 3), kind))
        price, t = base_price, price_step_s
        while t < horizon_s:
            price = float(np.clip(
                price * (1.0 + volatility * rng.standard_normal()),
                0.05 * base_price, ON_DEMAND_PRICE))
            events.append(SpotEvent(round(t, 3), "price", round(price, 4)))
            t += price_step_s
        return cls(events, base_price)

    def price_at(self, t_s: float) -> float:
        price = self.base_price
        for ev in self.events:
            if ev.kind == "price" and ev.t_s <= t_s:
                price = ev.value
        return price

    def due(self, t_prev: float, t_now: float) -> List[SpotEvent]:
        """Capacity events (preempt/spawn) with ``t_prev < t_s <= t_now``."""
        return [ev for ev in self.events
                if ev.kind != "price" and t_prev < ev.t_s <= t_now]


# ----------------------------------------------------------------------
# ledger
# ----------------------------------------------------------------------
class CostLedger:
    """Atomic single-document JSON ledger (tmp + fsync + rename, the
    checkpoint-store publish idiom): a SIGKILL of the fleet driver at
    any instant leaves either the previous or the next complete ledger
    on disk, never a torn one.  Format documented in docs/FACTORY.md."""

    VERSION = 1

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        self._doc = {
            "version": self.VERSION,
            "member_seconds": {},   # member key -> seconds alive
            "cost": {},             # member key -> priced spend
            "events": [],           # preempt/spawn/price changes, timed
            "iterations": {},       # iter -> {"epoch": E, "t_s": ...}
            "attempts": {},         # "iter.mM" -> [epochs it completed in]
            "total_cost": 0.0,
            "completed": False,
            "trees": None,
        }

    # -- mutation ------------------------------------------------------
    def charge(self, member, dt_s: float, price: float) -> None:
        key = str(member)
        self._doc["member_seconds"][key] = (
            self._doc["member_seconds"].get(key, 0.0) + dt_s)
        self._doc["cost"][key] = (
            self._doc["cost"].get(key, 0.0) + dt_s * price)
        self._doc["total_cost"] = sum(self._doc["cost"].values())

    def event(self, t_s: float, kind: str, **attrs) -> None:
        self._doc["events"].append(dict(t_s=round(t_s, 3), kind=kind,
                                        **attrs))

    def iteration(self, it: int, epoch: int, t_s: float) -> None:
        self._doc["iterations"].setdefault(
            str(it), {"epoch": epoch, "t_s": round(t_s, 3)})

    def attempt(self, it: int, member, epoch: int) -> None:
        """One member completed iteration ``it`` under ``epoch`` (from a
        write-once ``attempts/<it>.m<member>.e<epoch>`` KV record —
        idempotent, the harvest loop re-reads the store every poll)."""
        epochs = self._doc.setdefault("attempts", {}).setdefault(
            f"{int(it)}.m{member}", [])
        if int(epoch) not in epochs:
            epochs.append(int(epoch))
            epochs.sort()

    def finish(self, trees: int) -> None:
        self._doc["completed"] = True
        self._doc["trees"] = int(trees)

    # -- queries -------------------------------------------------------
    @property
    def total_cost(self) -> float:
        return float(self._doc["total_cost"])

    def zero_lost_iterations(self) -> bool:
        """No training iteration was lost OR redone across the churn:
        the write-once ``progress/<it>`` slots must cover exactly
        ``0..trees-1`` (nothing lost), and — when per-attempt records
        were harvested — no member may have completed the same iteration
        under two different epochs (nothing redone; a redo necessarily
        lands in a later epoch, so it leaves a second attempt key even
        though it cannot re-claim the write-once progress slot)."""
        trees = self._doc["trees"]
        if not self._doc["completed"] or trees is None:
            return False
        got = sorted(int(k) for k in self._doc["iterations"])
        if got != list(range(int(trees))):
            return False
        attempts = self._doc.get("attempts") or {}
        return all(len(epochs) == 1 for epochs in attempts.values())

    def cost_per_model(self) -> Optional[float]:
        return self.total_cost if self._doc["completed"] else None

    # -- persistence ---------------------------------------------------
    def flush(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self._doc, fh, indent=1, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.path)

    @classmethod
    def load(cls, path: str) -> "CostLedger":
        ledger = cls(path)
        with open(path) as fh:
            doc = json.load(fh)
        if doc.get("version") != cls.VERSION:
            raise ValueError(
                f"cost ledger {path}: version {doc.get('version')!r} "
                f"(supported: {cls.VERSION})")
        ledger._doc = doc
        return ledger


# ----------------------------------------------------------------------
# fleet driver
# ----------------------------------------------------------------------
def _default_worker() -> str:
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(repo, "tests", "membership_worker.py")


class SpotFleet:
    """Run one elastic training fleet of REAL subprocesses against a
    :class:`SpotSchedule`, pricing every member-second into a
    :class:`CostLedger`.

    The driver only ever sends signals and reads the shared KV store —
    all recovery (eviction, resize, join restore) is the workers' own
    membership runtime, exactly as it would be under a cloud scheduler.
    """

    def __init__(self, fleet_dir: str, schedule: SpotSchedule, nproc: int,
                 ledger_path: str, trees: int = 12, rows: int = 600,
                 worker: Optional[str] = None,
                 extra_env: Optional[Dict[str, str]] = None,
                 poll_s: float = 0.2):
        self.fleet_dir = os.path.abspath(fleet_dir)
        self.schedule = schedule
        self.nproc = int(nproc)
        self.trees = int(trees)
        self.rows = int(rows)
        self.worker = worker or _default_worker()
        self.extra_env = dict(extra_env or {})
        self.poll_s = float(poll_s)
        self.ledger = CostLedger(ledger_path)
        self.out = os.path.join(self.fleet_dir, "out")
        self._procs: List[dict] = []  # {proc, key, kind, alive}
        self._spawned_joiners = 0

    # -- workers -------------------------------------------------------
    def _env(self) -> Dict[str, str]:
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("LIGHTGBM_TPU_", "MEMBER_", "XLA_"))}
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = (
            os.path.dirname(os.path.dirname(os.path.abspath(self.worker)))
            + os.pathsep + env.get("PYTHONPATH", ""))
        env["LIGHTGBM_TPU_NET_TIMEOUT"] = env.get(
            "LIGHTGBM_TPU_NET_TIMEOUT", "8")
        env.update({
            "MEMBER_NPROC": str(self.nproc),
            "MEMBER_ROWS": str(self.rows),
            "MEMBER_TREES": str(self.trees),
            "MEMBER_PROGRESS": "1",
            # pace iterations so scripted event times land mid-run even
            # on a fast box; the ledger prices member-seconds, so pacing
            # inflates spot and baseline identically
            "MEMBER_ITER_SLEEP": env.get("MEMBER_ITER_SLEEP", "0.3"),
        })
        env.update(self.extra_env)
        return env

    def _spawn(self, member_arg) -> dict:
        key = str(member_arg)
        if member_arg == "join":
            # ledger keys must be unique per worker, not per argv form
            key = f"join{sum(1 for r in self._procs if r['kind'] == 'join') + 1}"
        # per-member log file, NOT a pipe: nothing drains a pipe until the
        # run ends, so a chatty worker (verbose>=1 over many iterations)
        # would block on the full OS pipe buffer and stall the fleet into
        # a spurious timeout — and the files survive for post-mortems
        os.makedirs(self.fleet_dir, exist_ok=True)
        log_path = os.path.join(self.fleet_dir, f"worker.{key}.log")
        with open(log_path, "w") as log_fh:
            proc = subprocess.Popen(
                [sys.executable, self.worker, str(member_arg),
                 self.fleet_dir, self.out],
                env=self._env(), stdout=log_fh, stderr=subprocess.STDOUT,
                text=True)
        rec = dict(proc=proc, key=key, log=log_path, kind=(
            "join" if member_arg == "join" else "bootstrap"))
        self._procs.append(rec)
        return rec

    def _live(self) -> List[dict]:
        return [r for r in self._procs if r["proc"].poll() is None]

    def _preempt(self, ev: SpotEvent, t: float) -> None:
        live = self._live()
        victim = None
        if ev.target is not None:
            victim = next((r for r in live if r["key"] == str(ev.target)),
                          None)
        elif live:
            victim = live[-1]  # youngest capacity goes first
        if victim is None:
            Log.warning("spot: preempt@%.1fs found no live member", ev.t_s)
            return
        victim["proc"].send_signal(signal.SIGKILL)
        victim["proc"].wait()
        tracer.event("spot.preempt", member=victim["key"], t_s=round(t, 3))
        self.ledger.event(t, "preempt", member=victim["key"])

    def _spawn_joiner(self, t: float) -> None:
        self._spawned_joiners += 1
        self._spawn("join")
        tracer.event("spot.spawn", ordinal=self._spawned_joiners,
                     t_s=round(t, 3))
        self.ledger.event(t, "spawn", ordinal=self._spawned_joiners)

    # -- progress ------------------------------------------------------
    def _harvest_progress(self, t: float) -> None:
        from ..parallel.membership import FileKVClient

        client = FileKVClient(os.path.join(self.fleet_dir, "kv"))
        for key, value in client.key_value_dir_get("progress/"):
            it = int(key.rsplit("/", 1)[-1])
            try:
                epoch = int(json.loads(value)["epoch"])
            except (ValueError, KeyError, TypeError):
                epoch = -1
            self.ledger.iteration(it, epoch, t)
        for key, _value in client.key_value_dir_get("attempts/"):
            # "attempts/<it>.m<member>.e<epoch>" — one write-once key per
            # completion attempt, feeding the nothing-redone proof
            name = key.rsplit("/", 1)[-1]
            try:
                it_s, m_s, e_s = name.split(".")
                self.ledger.attempt(int(it_s), m_s[1:], int(e_s[1:]))
            except (ValueError, IndexError):
                Log.warning("spot: unparsable attempt key %r", key)

    # -- run -----------------------------------------------------------
    def run(self, timeout_s: float = 300.0) -> dict:
        os.makedirs(self.fleet_dir, exist_ok=True)
        for m in range(self.nproc):
            self._spawn(m)
        t0 = time.monotonic()
        last = 0.0
        self.ledger.event(0.0, "price", price=self.schedule.base_price)
        while True:
            time.sleep(self.poll_s)
            t = time.monotonic() - t0
            price = self.schedule.price_at(t)
            for rec in self._live():
                self.ledger.charge(rec["key"], t - last, price)
            for ev in self.schedule.due(last, t):
                if ev.kind == "preempt":
                    self._preempt(ev, t)
                elif ev.kind == "spawn":
                    self._spawn_joiner(t)
            self._harvest_progress(t)
            self.ledger.flush()
            last = t
            if not self._live():
                break
            if t > timeout_s:
                Log.warning("spot: fleet timeout after %.0fs — killing", t)
                for rec in self._live():
                    rec["proc"].kill()
                break
        wall = time.monotonic() - t0
        results = self._collect()
        if results["models"]:
            self.ledger.finish(self.trees)
        self.ledger.event(wall, "done", completed=bool(results["models"]))
        self.ledger.flush()
        cost = self.ledger.cost_per_model()
        if cost is not None:
            tracer.gauge("factory.cost_per_model", cost,
                         fleet=os.path.basename(self.fleet_dir))
        return dict(wall_s=round(wall, 3), cost=cost,
                    zero_lost_iterations=self.ledger.zero_lost_iterations(),
                    ledger=self.ledger.path, **results)

    def _collect(self) -> dict:
        exits, models, metas = {}, {}, {}
        for rec in self._procs:
            rec["proc"].wait()
            exits[rec["key"]] = rec["proc"].returncode
        for name in sorted(os.listdir(self.fleet_dir)):
            if name.startswith("out.m") and name.endswith(".txt"):
                mid = name[len("out.m"):-len(".txt")]
                with open(os.path.join(self.fleet_dir, name)) as fh:
                    models[mid] = fh.read()
            elif name.startswith("out.m") and name.endswith(".json"):
                mid = name[len("out.m"):-len(".json")]
                with open(os.path.join(self.fleet_dir, name)) as fh:
                    metas[mid] = json.load(fh)
        return dict(exits=exits, models=models, metas=metas)


def run_static_baseline(fleet_dir: str, nproc: int, ledger_path: str,
                        trees: int = 12, rows: int = 600,
                        worker: Optional[str] = None,
                        extra_env: Optional[Dict[str, str]] = None,
                        timeout_s: float = 300.0) -> dict:
    """The on-demand reference: the same fleet with no churn, every
    member-second priced at :data:`ON_DEMAND_PRICE`."""
    fleet = SpotFleet(fleet_dir, SpotSchedule([], base_price=ON_DEMAND_PRICE),
                      nproc, ledger_path, trees=trees, rows=rows,
                      worker=worker, extra_env=extra_env)
    summary = fleet.run(timeout_s=timeout_s)
    if summary["cost"] is not None:
        tracer.gauge("factory.cost_baseline", summary["cost"],
                     fleet=os.path.basename(os.path.abspath(fleet_dir)))
    return summary


# ----------------------------------------------------------------------
# ``factory spot`` subcommand
# ----------------------------------------------------------------------
def main(argv: List[str]) -> int:
    """``python -m lightgbm_tpu factory spot fleet=DIR [nproc=3]
    [trees=12] [rows=600] [script=preempt@3;spawn@6] [seed=N]
    [horizon=30] [price=0.3] [baseline=1] [ledger=PATH]``."""
    from ..cli import parse_argv
    from .supervisor import EXIT_BAD_ARGS, EXIT_OK

    tracer.refresh_from_env()
    params = parse_argv(argv)
    fleet_dir = params.get("fleet")
    if not fleet_dir:
        Log.warning("factory spot: need fleet=DIR [nproc=3] [trees=12] "
                    "[script=...|seed=N] [price=0.3] [baseline=1]")
        return EXIT_BAD_ARGS
    nproc = int(params.get("nproc", "3"))
    trees = int(params.get("trees", "12"))
    rows = int(params.get("rows", "600"))
    price = float(params.get("price", "0.3"))
    ledger = params.get("ledger",
                        os.path.join(fleet_dir, "cost_ledger.json"))
    if params.get("baseline", "0") == "1":
        summary = run_static_baseline(fleet_dir, nproc, ledger, trees=trees,
                                      rows=rows)
    else:
        if "script" in params:
            schedule = SpotSchedule.from_script(params["script"], price)
        else:
            schedule = SpotSchedule.sample(
                int(params.get("seed", "0")),
                float(params.get("horizon", "30")), base_price=price)
        fleet = SpotFleet(fleet_dir, schedule, nproc, ledger, trees=trees,
                          rows=rows)
        summary = fleet.run()
    print(json.dumps({k: v for k, v in summary.items() if k != "models"},
                     indent=1, sort_keys=True))
    return EXIT_OK if summary["cost"] is not None else 1
