"""Continuous-training model factory (docs/FACTORY.md).

``python -m lightgbm_tpu factory`` closes the loop the other
subsystems left open: watch a data directory (factory/watch.py),
warm-start an incremental retrain through the checkpointed engine,
publish to the serving fleet's model registry, canary the candidate on
a slice of live traffic, and auto-promote or auto-roll-back on the
observed eval metric + serving SLO.  Supervisor state is an atomic
CRC'd file (factory/state.py) so a kill anywhere restarts into the
same run without double-publishing or losing a verdict.
"""

from .spot import CostLedger, SpotFleet, SpotSchedule
from .state import FactoryState
from .supervisor import DEFAULTS, FactorySupervisor, main

__all__ = ["CostLedger", "FactoryState", "FactorySupervisor", "DEFAULTS",
           "SpotFleet", "SpotSchedule", "main"]
