"""Data-directory watcher: mtime + content-fingerprint manifest.

Poll-based (no inotify dependency, works on network mounts — the same
reasoning as registry.watch_token).  A file's fingerprint is its size,
mtime, and a CRC32 over its first and last 64 KiB: cheap enough to
rescan every poll even for multi-GB chunks, and an APPEND to an
existing file changes both size and tail CRC, so appended chunks
retrain just like new files (the ISSUE-11 contract).

Debounce: a change only counts once every watched file's mtime is at
least ``debounce_s`` old — a writer mid-append never triggers a
retrain on a half-written chunk.
"""

from __future__ import annotations

import os
import time
import zlib
from typing import Dict, List, Tuple

_FP_CHUNK = 65536
DATA_SUFFIXES: Tuple[str, ...] = (".csv", ".tsv", ".txt", ".data")


def fingerprint(path: str) -> Dict:
    st = os.stat(path)
    crc = 0
    with open(path, "rb") as f:
        crc = zlib.crc32(f.read(_FP_CHUNK))
        if st.st_size > 2 * _FP_CHUNK:
            f.seek(-_FP_CHUNK, os.SEEK_END)
            crc = zlib.crc32(f.read(_FP_CHUNK), crc)
    return {
        "size": int(st.st_size),
        "mtime_ns": int(st.st_mtime_ns),
        "crc32": crc & 0xFFFFFFFF,
    }


def scan(data_dir: str,
         suffixes: Tuple[str, ...] = DATA_SUFFIXES) -> Dict[str, Dict]:
    """{filename: fingerprint} for every data chunk in ``data_dir``,
    sorted by name (chunk order = lexical order, the ingest convention).
    Hidden files and non-data suffixes are ignored."""
    out: Dict[str, Dict] = {}
    try:
        names = sorted(os.listdir(data_dir))
    except OSError:
        return out
    for name in names:
        if name.startswith("."):
            continue
        if suffixes and not name.endswith(suffixes):
            continue
        path = os.path.join(data_dir, name)
        try:
            if not os.path.isfile(path):
                continue
            out[name] = fingerprint(path)
        except OSError:
            continue  # vanished mid-scan; next poll sees the truth
    return out


def changed(prev: Dict[str, Dict], cur: Dict[str, Dict]) -> List[str]:
    """Names that are new or whose content fingerprint moved (size or
    CRC — mtime alone is NOT a change: a touch must not retrain)."""
    out = []
    for name, fp in cur.items():
        old = prev.get(name)
        if old is None or old["size"] != fp["size"] \
                or old["crc32"] != fp["crc32"]:
            out.append(name)
    return out


def stable(cur: Dict[str, Dict], debounce_s: float) -> bool:
    """True once every watched file's mtime is at least ``debounce_s``
    old — the writer finished appending."""
    now = time.time()
    return all(now - fp["mtime_ns"] / 1e9 >= debounce_s
               for fp in cur.values())


def combined_fingerprint(cur: Dict[str, Dict]) -> str:
    """Order-stable fingerprint of the whole data set — the run id's
    content half, so re-scanning unchanged data maps to the same run."""
    crc = 0
    for name in sorted(cur):
        fp = cur[name]
        crc = zlib.crc32(
            f"{name}:{fp['size']}:{fp['crc32']}".encode(), crc)
    return f"{crc & 0xFFFFFFFF:08x}"
