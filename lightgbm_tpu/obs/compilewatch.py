"""JAX compile / retrace accountant.

Unexpected retraces are the classic silent TPU perf killer: a jitted
program whose closure bakes in a trace-time value (an env var, a python
float) silently recompiles — or worse, silently does NOT pick up a
changed value — and nothing in the training log shows it.  This module
provides two layers:

1. A process-global compile counter fed by ``jax.monitoring`` duration
   events (``/jax/core/compile/backend_compile_duration`` fires once per
   XLA backend compilation, on every jax version we target).  Each
   compile also lands in the trace as a ``jax_compile`` event.

2. ``JitWatch`` — a wrapper for jitted entry points that tracks the
   jit cache size per *array signature* (shapes + dtypes of array
   arguments).  When the cache grows on a signature that has already
   been traced, the call is flagged as an **unexpected retrace**
   (``jax_retrace`` trace event + Log.warning): the cache key changed
   through something invisible in the arguments — exactly the
   env-var-read-at-trace-time class of bug.

Both layers are cheap enough to stay on unconditionally: the monitoring
listener fires only on compiles, and a ``JitWatch`` call adds two cache
-size reads per invocation (the fused trainer invokes its chunk program
once per 64 iterations).
"""

from __future__ import annotations

from typing import Any, Dict

from ..utils.log import Log

_counts = {"backend_compiles": 0, "backend_compile_secs": 0.0}
_installed = False
_watches = []


def install() -> None:
    """Register the jax.monitoring listener (idempotent)."""
    global _installed
    if _installed:
        return
    import jax.monitoring

    jax.monitoring.register_event_duration_secs_listener(_on_duration)
    _installed = True


def _on_duration(name: str, secs: float, **kwargs) -> None:
    if name != "/jax/core/compile/backend_compile_duration":
        return
    _counts["backend_compiles"] += 1
    _counts["backend_compile_secs"] += secs
    from .trace import tracer

    if tracer.enabled:
        tracer.event("jax_compile", secs=round(secs, 4))


def total_compiles() -> int:
    return _counts["backend_compiles"]


def snapshot() -> Dict[str, Any]:
    """Aggregate compile accounting for bench output / reports."""
    return {
        "backend_compiles": _counts["backend_compiles"],
        "backend_compile_secs": round(_counts["backend_compile_secs"], 3),
        "watched": {
            w.name: {
                "calls": w.calls,
                "compiles": w.compiles,
                "retraces": w.retraces,
                "signatures": len(w._sigs),
            }
            for w in _watches
        },
    }


def _sig_of(args, kwargs):
    """Array signature: (shape, dtype, sharding) per array leaf;
    non-array leaves are deliberately EXCLUDED so a cache key that
    shifts without any visible argument change is caught as a retrace.
    Sharding IS part of jax's cache key (a device_put onto a mesh
    legitimately recompiles at the same shape), so it belongs in the
    signature — without it the serving layer's row-sharded predict reads
    as a false retrace of the single-device program."""
    import jax

    leaves = jax.tree_util.tree_leaves((args, kwargs))
    return tuple(
        (tuple(l.shape), str(l.dtype), str(getattr(l, "sharding", "")))
        for l in leaves
        if hasattr(l, "shape") and hasattr(l, "dtype")
    )


class JitWatch:
    """Wrap a jitted callable; count compilations per array signature and
    flag cache growth on an already-seen signature as a retrace.

    ``phase`` tags the program with the measured phase-span name it
    accounts under (``histogram``, ``chunk_program``, ``serve_batch``,
    ...) so the cost model (obs/costmodel.py) can join its HLO roofline
    against the wall-clock the trace measured for that phase."""

    def __init__(self, fn, name: str, phase: str = None):
        import threading

        self._fn = fn
        self.name = name
        self.phase = phase
        self.calls = 0
        self.compiles = 0
        self.retraces = 0
        self._sigs = set()
        self._last_cache_size = 0
        # serialize calls so a concurrent caller's compile can't land
        # inside another caller's before/after window and read as that
        # caller's (false) retrace — the serving batchers share one watch
        self._lock = threading.Lock()
        install()
        _watches.append(self)

    def _cache_size(self):
        cs = getattr(self._fn, "_cache_size", None)
        if cs is None:
            return None
        try:
            return cs()
        except Exception:  # pragma: no cover - jax internals moved
            return None

    def __call__(self, *args, **kwargs):
        from jax.core import trace_state_clean

        # called while an OUTER jit is tracing: this program is inlined
        # into the caller's jaxpr — no backend compile happens here, and
        # the cache bookkeeping below would misread the outer trace's
        # state.  Call straight through (the module-level kernel watches
        # in ops/pgrow.py and ops/histogram.py hit this constantly).
        if not trace_state_clean():
            return self._fn(*args, **kwargs)
        with self._lock:
            return self._call_locked(args, kwargs)

    def _call_locked(self, args, kwargs):
        self.calls += 1
        before = self._cache_size()
        # a shrunken cache means jax.clear_caches() (or a backend
        # teardown) emptied the jit cache out from under us: every seen
        # signature will legitimately compile again, so the seen set is
        # from a dead cache lifetime — forget it instead of flagging the
        # whole re-warm as retraces
        if before is not None and before < self._last_cache_size:
            self._sigs.clear()
        csecs0 = _counts["backend_compile_secs"]
        out = self._fn(*args, **kwargs)
        if before is None:
            return out
        after = self._cache_size()
        if after is not None:
            self._last_cache_size = after
        if after is not None and after > before:
            self.compiles += 1
            sig = _sig_of(args, kwargs)
            from .trace import tracer

            if sig in self._sigs:
                self.retraces += 1
                Log.warning(
                    "unexpected retrace of %s (jit cache grew %d -> %d on an "
                    "already-traced argument signature) — a trace-time "
                    "constant changed outside the cache key (env var read "
                    "inside the traced function?)",
                    self.name, before, after,
                )
                tracer.event("jax_retrace", fn=self.name,
                             cache_size=after)
            else:
                self._sigs.add(sig)
                tracer.event("jax_trace", fn=self.name, cache_size=after)
                self._record_cost(
                    args, kwargs,
                    _counts["backend_compile_secs"] - csecs0, sig)
        return out

    def _record_cost(self, args, kwargs, compile_secs, sig=None):
        """First compile per signature: scrape HLO cost/memory analysis
        into the program inventory + a ``jax_cost`` trace record
        (obs/costmodel.py).  Only when tracing is enabled (the capture
        re-lowers the program once — not free), and never allowed to
        break the training step."""
        from .trace import tracer

        if not tracer.enabled:
            return
        try:
            from . import costmodel

            costmodel.capture(self, args, kwargs, compile_secs, sig=sig)
        except Exception as e:
            Log.warning("cost capture failed for %s: %s", self.name, e)
