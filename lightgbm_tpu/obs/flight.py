"""Crash flight recorder — the last N trace records, flushed on death.

A multi-host failure usually kills the interesting evidence: the JSONL
trace is line-buffered so *completed* records survive, but the operator
still has to find the right file on the right rank and scroll to the
end.  The flight recorder keeps a bounded in-memory ring of the most
recent records the tracer emitted and, at the moment a typed transport
failure is raised (``PeerFailureError`` / ``CollectiveTimeoutError``,
parallel/net.py), on fatal CLI paths, or on ``SIGUSR1``, writes the
whole ring — plus a meta record naming the reason — to
``<trace>.crash.jsonl`` next to the trace.  The survivor
flush-and-exit path (docs/ROBUSTNESS.md) therefore always leaves a
self-contained "what were the final spans before the failure" dump.

Lifecycle: the ring is allocated ONLY when the tracer is configured
(``tracer.configure`` calls :func:`FlightRecorder.activate`); with
tracing off no ring exists and no record is ever copied — the
disabled-overhead guard test pins that.  Knobs:

  LIGHTGBM_TPU_FLIGHT_RING=n   ring capacity in records (default 512)
  LIGHTGBM_TPU_FLIGHT=path     override the dump path (default derives
                               from the trace path)

``dump()`` is idempotent per reason and crash-safe: records are written
through a private file handle with an fsync, because the caller is
usually about to ``os._exit``.
"""

from __future__ import annotations

import collections
import json
import os
import signal
import threading
import time
from typing import Any, Dict, Optional

DEFAULT_RING = 512


def _crash_path_for(trace_path: str) -> str:
    """<dir>/run.jsonl -> <dir>/run.crash.jsonl (a non-.jsonl trace
    path just gains the suffix)."""
    if trace_path.endswith(".jsonl"):
        return trace_path[: -len(".jsonl")] + ".crash.jsonl"
    return trace_path + ".crash.jsonl"


class FlightRecorder:
    """Bounded ring of recent trace records + the crash dump writer."""

    def __init__(self):
        self.ring: Optional[collections.deque] = None
        self.path: Optional[str] = None
        self._lock = threading.Lock()
        self.dumps = 0  # how many crash dumps this process wrote

    # -- lifecycle -----------------------------------------------------
    def activate(self, trace_path: str) -> None:
        override = os.environ.get("LIGHTGBM_TPU_FLIGHT", "").strip()
        cap_raw = os.environ.get("LIGHTGBM_TPU_FLIGHT_RING", "").strip()
        try:
            cap = int(cap_raw) if cap_raw else DEFAULT_RING
        except ValueError:
            cap = DEFAULT_RING
        with self._lock:
            self.path = override or _crash_path_for(trace_path)
            if cap <= 0:  # explicit opt-out
                self.ring = None
            else:
                self.ring = collections.deque(maxlen=cap)

    def deactivate(self) -> None:
        with self._lock:
            self.ring = None
            self.path = None

    # -- hot path (called by Tracer._emit on every enabled record) -----
    def record(self, rec: Dict[str, Any]) -> None:
        ring = self.ring
        if ring is not None:
            ring.append(rec)  # deque.append is atomic under the GIL

    # -- the crash dump ------------------------------------------------
    def dump(self, reason: str, error: Optional[BaseException] = None,
             **attrs) -> Optional[str]:
        """Flush the ring to the crash file.  Returns the path written,
        or None when the recorder is inactive.  Never raises: this runs
        on paths that are already dying."""
        with self._lock:
            ring = self.ring
            path = self.path
            if ring is None or path is None:
                return None
            records = list(ring)
        meta: Dict[str, Any] = {
            "ev": "meta", "kind": "flight", "reason": reason,
            "pid": os.getpid(), "ts": round(time.time(), 6),
            "ring_len": len(records),
        }
        if error is not None:
            meta["error"] = f"{type(error).__name__}: {error}"
        meta.update(attrs)
        try:
            from .trace import tracer

            meta.update(tracer._ident)
        except Exception:
            pass
        try:
            with open(path, "w") as f:
                f.write(json.dumps(meta, default=str) + "\n")
                for rec in records:
                    f.write(json.dumps(rec, default=str) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except Exception:  # pragma: no cover - disk full on a dying host
            return None
        self.dumps += 1
        return path


recorder = FlightRecorder()


def dump(reason: str, error: Optional[BaseException] = None,
         **attrs) -> Optional[str]:
    """Module-level convenience used by parallel/net.py and the CLI."""
    return recorder.dump(reason, error=error, **attrs)


def install_signal_handler(signum: int = signal.SIGUSR1) -> bool:
    """SIGUSR1 -> flush the ring (live-run forensics: ask a wedged
    training process what it was doing without killing it).  Main
    thread only; returns False when the handler cannot be installed."""

    def _on_signal(_signum, _frame):
        p = dump("sigusr1")
        if p:
            from ..utils.log import Log

            Log.warning("flight recorder dumped to %s (SIGUSR1)", p)

    try:
        signal.signal(signum, _on_signal)
        return True
    except (ValueError, OSError):  # non-main thread / unsupported
        return False
