"""Split-decision audit trail — every accepted split, as JSONL.

The reference's model-text dump records the *final* tree; when two runs
disagree (the open LEVELGROW=1 vs =0 divergence, ROADMAP item 1) the
model diff says "trees differ" without saying WHICH decision diverged
first.  This stream records every accepted split in acceptance order —
(iteration, class, split ordinal, leaf, feature, bin threshold, real
threshold, gain, default-left, left/right counts) plus each finished
tree's leaf values — so ``python -m lightgbm_tpu report diff a b``
pins the first divergent decision to a single line.

Enable with ``LIGHTGBM_TPU_AUDIT=path`` (re-read at every
``engine.train`` / ``GBDT.init``, like the tracer).  Disabled mode is
one attribute check per tree.

Determinism contract: records carry NO timestamps, floats are emitted
through Python repr (shortest round-trip — byte-identical iff the
doubles are bit-identical), keys are written in fixed order, and the
record order is the trainer's split-acceptance order.  Two runs that
build bit-identical trees therefore produce byte-identical audit files;
the parity leg of tests/test_audit.py pins exactly that, and the
divergence leg pins that ``report diff`` localizes the first
divergent (iteration, leaf, feature, threshold, gain) at the
known-divergent LEVELGROW config.

The fields come from the grower's raw split records via
``ops/pgrow.split_audit_rows`` — the same records every trainer path
(mask grower, fused classic, fused level-batched, traced) feeds into
``Tree.from_grow_result``, which is what makes the trail comparable
across LEVELGROW modes in the first place.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional


class AuditWriter:
    """Process-global JSONL audit sink (LIGHTGBM_TPU_AUDIT=path)."""

    def __init__(self):
        self.enabled = False
        self.path: Optional[str] = None
        self._f = None

    def refresh_from_env(self) -> None:
        path = os.environ.get("LIGHTGBM_TPU_AUDIT", "")
        if path and path != self.path:
            self.configure(path)

    def configure(self, path: str) -> None:
        self.close()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "w", buffering=1)  # line buffered
        self.path = path
        self.enabled = True

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.flush()
                self._f.close()
            except Exception:  # pragma: no cover - interpreter teardown
                pass
        self._f = None
        self.enabled = False

    def _write(self, rec: Dict[str, Any]) -> None:
        if self._f is not None:
            self._f.write(json.dumps(rec) + "\n")

    def record_tree(self, it: int, k: int, view, tree) -> None:
        """Emit the accepted splits of one finished tree plus its leaf
        values.  ``view`` is the GrowResult-like raw-record view
        (``ops/grow.GrowResult`` or ``ptrainer.grow_result_view``);
        ``tree`` is the built ``model.tree.Tree`` AFTER shrinkage, so
        the recorded thresholds/values are exactly the model's."""
        if not self.enabled:
            return
        from ..ops.pgrow import split_audit_rows

        for row in split_audit_rows(view):
            s = row["s"]
            rec = {
                "ev": "split", "it": int(it), "k": int(k), "s": s,
                "leaf": row["leaf"], "feat": int(tree.split_feature[s]),
                "bin": row["bin"],
                "thr": float(tree.threshold[s]),
                "gain": row["gain"],
                # default-left: where the zero/missing bin routes under
                # this node's decision type (tree.h decision funs)
                "dl": int(row["dbz"] == row["bin"]
                          if tree.decision_type[s] == 1
                          else row["dbz"] <= row["bin"]),
                "dbz": row["dbz"],
                "lcnt": row["lcnt"], "rcnt": row["rcnt"],
            }
            self._write(rec)
        rec = {
            "ev": "tree", "it": int(it), "k": int(k),
            "leaves": int(tree.num_leaves),
            "values": [float(v) for v in
                       tree.leaf_value[: tree.num_leaves]],
        }
        if getattr(tree, "is_linear", False):
            # leaf-model kind + coefficients (tree/linear.py plug-in):
            # json floats serialize via repr, so the trail is byte-stable
            # across runs; constant trees keep the exact legacy record
            n = tree.num_leaves
            rec["leaf_model"] = "linear"
            rec["linear_leaves"] = [int(v) for v in
                                    tree.leaf_is_linear[:n]]
            rec["const"] = [float(v) for v in tree.leaf_const[:n]]
            rec["coeff"] = [[float(c) for c in tree.leaf_coeff[i]]
                            if i < len(tree.leaf_coeff) else []
                            for i in range(n)]
            rec["feat"] = [list(tree.leaf_features[i])
                           if i < len(tree.leaf_features) else []
                           for i in range(n)]
        self._write(rec)


audit = AuditWriter()
