"""Host + device memory gauges (best-effort, dependency-free).

Host RSS comes from /proc/self/status (Linux) with a resource.getrusage
fallback; device memory from ``Device.memory_stats()`` where the backend
exposes it (the tunneled axon plugin may not — absent keys are simply
omitted from the gauges).  Peak watermarks are tracked process-wide so a
trace's last iteration record carries the high-water mark even when
individual snapshots move around.
"""

from __future__ import annotations

from typing import Any, Dict

_peaks = {"host_rss_mb": 0.0, "dev_mb": 0.0}


def host_rss_mb() -> float:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) / 1024.0
    except OSError:
        pass
    try:  # pragma: no cover - non-Linux fallback
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
    except Exception:  # pragma: no cover
        return 0.0
    return 0.0


def device_memory_mb() -> Dict[str, float]:
    """{'dev_mb': in-use, 'dev_peak_mb': backend peak} when exposed.
    Only queried once jax is already imported — never triggers backend
    initialization on its own."""
    import sys

    if "jax" not in sys.modules:
        return {}
    jax = sys.modules["jax"]
    try:
        ms = jax.local_devices()[0].memory_stats()
    except Exception:
        return {}
    if not ms or "bytes_in_use" not in ms:
        return {}
    out = {"dev_mb": round(ms["bytes_in_use"] / 1e6, 1)}
    if "peak_bytes_in_use" in ms:
        out["dev_peak_mb"] = round(ms["peak_bytes_in_use"] / 1e6, 1)
    return out


def memory_gauges() -> Dict[str, Any]:
    """Combined host+device snapshot used on every iteration record."""
    out: Dict[str, Any] = {"host_rss_mb": round(host_rss_mb(), 1)}
    out.update(device_memory_mb())
    for k in ("host_rss_mb", "dev_mb"):
        if k in out and out[k] > _peaks[k]:
            _peaks[k] = out[k]
    return out


def peaks() -> Dict[str, float]:
    return dict(_peaks)
