"""Trace-file summarizer — ``python -m lightgbm_tpu report trace.jsonl``.

Renders a TIMETAG-style table (the reference's destructor dump,
serial_tree_learner.cpp:12-24, but from structured records instead of
printf): per-phase totals over the run, per-iteration statistics,
compile/retrace accounting and memory watermarks.  ``summarize`` is
also importable — bench.py uses it to fold a (possibly partial) trace of
a dead run into its failure report.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List


def load_trace(path: str) -> List[Dict[str, Any]]:
    """Read a JSONL trace, tolerating a torn final line (the run died
    mid-write) — partial traces are the point."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                continue  # torn tail record from a killed process
    return records


def summarize(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    spans: Dict[str, List[float]] = {}
    iters: List[Dict[str, Any]] = []
    compiles = 0
    compile_secs = 0.0
    retraces = 0
    peak_host = 0.0
    peak_dev = 0.0
    ingest_done: Dict[str, Any] = {}
    for r in records:
        ev = r.get("ev")
        if ev == "span":
            agg = spans.setdefault(r.get("name", "?"), [0.0, 0])
            agg[0] += float(r.get("dur_s", 0.0))
            agg[1] += 1
        elif ev == "iter":
            iters.append(r)
            peak_host = max(peak_host, float(r.get("host_rss_mb", 0.0)))
            peak_dev = max(peak_dev, float(r.get("dev_mb", 0.0)))
        elif ev == "event":
            name = r.get("name")
            if name == "jax_compile":
                compiles += 1
                compile_secs += float(r.get("secs", 0.0))
            elif name == "jax_retrace":
                retraces += 1
            elif name == "ingest.done":
                ingest_done = {k: v for k, v in r.items()
                               if k not in ("ev", "name", "ts")}
    phase_totals: Dict[str, Dict[str, float]] = {}
    for it in iters:
        for k, v in (it.get("phases") or {}).items():
            agg = phase_totals.setdefault(k, {"total_s": 0.0, "count": 0})
            agg["total_s"] += float(v)
            agg["count"] += 1
    walls = [float(it.get("wall_s", 0.0)) for it in iters]
    out = {
        "iterations": len(iters),
        "total_iter_wall_s": round(sum(walls), 6),
        "mean_s_per_iter": round(sum(walls) / len(walls), 6) if walls else None,
        "phases": {
            k: {"total_s": round(v["total_s"], 6), "count": v["count"],
                "mean_ms": round(1e3 * v["total_s"] / max(v["count"], 1), 3)}
            for k, v in sorted(phase_totals.items(),
                               key=lambda kv: -kv[1]["total_s"])
        },
        "spans": {
            k: {"total_s": round(t, 6), "count": c,
                "mean_ms": round(1e3 * t / max(c, 1), 3)}
            for k, (t, c) in sorted(spans.items(), key=lambda kv: -kv[1][0])
        },
        "compiles": compiles,
        "compile_secs": round(compile_secs, 3),
        "retraces_flagged": retraces,
        "peak_host_rss_mb": round(peak_host, 1),
        "peak_dev_mb": round(peak_dev, 1),
    }
    if ingest_done:
        out["ingest"] = ingest_done
    if iters:
        last = iters[-1]
        out["last_iter"] = int(last.get("iter", -1))
        if "leaves" in last:
            out["leaves_last_iter"] = last["leaves"]
    return out


def top_phases_line(summary: Dict[str, Any], k: int = 3) -> str:
    """One-line per-phase percentage attribution — the top-``k`` phases
    by share of total phase time, e.g.
    ``top phases: partition 61.2% | histogram 22.4% | split 9.8%``.
    Shares are of the summed PHASE time (not iteration wall) so the line
    is meaningful for partial traces too.  Empty string when the trace
    has no phase records."""
    phases = summary.get("phases") or {}
    total = sum(v["total_s"] for v in phases.values())
    if not phases or total <= 0:
        return ""
    ranked = sorted(phases.items(), key=lambda kv: -kv[1]["total_s"])[:k]
    parts = [f"{name} {100.0 * v['total_s'] / total:.1f}%" for name, v in ranked]
    return "top phases: " + " | ".join(parts)


def render(summary: Dict[str, Any], path: str = "") -> str:
    """TIMETAG-style text table."""
    lines = []
    lines.append(f"=== lightgbm_tpu run-trace report{': ' + path if path else ''} ===")
    n = summary["iterations"]
    if n:
        lines.append(
            f"iterations: {n}   iter wall total: {summary['total_iter_wall_s']:.3f} s"
            f"   mean: {1e3 * summary['mean_s_per_iter']:.2f} ms/iter"
        )
    else:
        lines.append("iterations: 0 (no iter records — run died before training?)")
    total_wall = summary["total_iter_wall_s"] or 0.0
    if summary["phases"]:
        # one-line attribution: top-3 phases by share of iteration wall,
        # so "where does the time go" doesn't require reading the table
        # (or the raw JSONL)
        top = top_phases_line(summary)
        if top:
            lines.append(top)
        lines.append("")
        lines.append(f"{'phase (per-iteration)':<28}{'total_s':>10}{'count':>8}"
                     f"{'mean_ms':>10}{'% iter':>8}")
        for name, s in summary["phases"].items():
            pct = 100.0 * s["total_s"] / total_wall if total_wall else 0.0
            lines.append(f"{name:<28}{s['total_s']:>10.3f}{s['count']:>8}"
                         f"{s['mean_ms']:>10.2f}{pct:>8.1f}")
    if summary["spans"]:
        lines.append("")
        lines.append(f"{'span':<28}{'total_s':>10}{'count':>8}{'mean_ms':>10}")
        for name, s in list(summary["spans"].items())[:20]:
            lines.append(f"{name:<28}{s['total_s']:>10.3f}{s['count']:>8}"
                         f"{s['mean_ms']:>10.2f}")
    lines.append("")
    lines.append(
        f"compiles: {summary['compiles']} ({summary['compile_secs']:.1f} s)"
        f"   unexpected retraces flagged: {summary['retraces_flagged']}"
    )
    lines.append(
        f"memory watermarks: host RSS {summary['peak_host_rss_mb']:.0f} MB"
        + (f", device {summary['peak_dev_mb']:.0f} MB"
           if summary["peak_dev_mb"] else "")
    )
    ing = summary.get("ingest")
    if ing:
        lines.append(
            "streaming ingest: "
            f"{ing.get('rows', '?')} rows in {ing.get('wall_s', '?')} s "
            f"({ing.get('rows_per_s', '?')} rows/s), "
            f"{ing.get('chunks_pass2', '?')} chunks x {ing.get('chunk_rows', '?')} rows, "
            f"packed {ing.get('packed_mb', '?')} MB, "
            f"peak RSS {ing.get('rss_peak_mb', '?')} MB"
        )
    return "\n".join(lines) + "\n"


def main(argv: List[str]) -> int:
    """CLI entry: ``python -m lightgbm_tpu report <trace.jsonl> [--json]``."""
    import sys

    args = [a for a in argv if not a.startswith("--")]
    as_json = "--json" in argv
    if not args:
        sys.stderr.write(
            "usage: python -m lightgbm_tpu report <trace.jsonl> [--json]\n"
        )
        return 2
    path = args[0]
    try:
        records = load_trace(path)
    except OSError as e:
        sys.stderr.write(f"cannot read trace {path}: {e}\n")
        return 1
    summary = summarize(records)
    if as_json:
        sys.stdout.write(json.dumps(summary) + "\n")
    else:
        sys.stdout.write(render(summary, path))
    return 0
